"""Scale-simulation plane (dtload): the capacity-manifest gate.

``dynamo-tpu lint --load`` runs the macro-simulation sweep
(``dynamo_tpu/load``) — the REAL KvIndexer/KvScheduler, admission
controller and planner policy on a seeded DetLoop, workers simulated
from dtperf's committed predicted-latency manifest — and diffs the
resulting capacity surface against the committed
``analysis/load_manifest.json``:

    LD001  capacity regression: a (cell, level)'s p99 TTFT grew past
           1.3x the committed value, its shed rate rose by more than
           5 points, or its completions fell below 80% of committed
    LD002  SLA knee drift: the lowest load level that breaches the
           cell's TTFT SLA (or sheds > 1%) moved DOWN — the system
           saturates earlier than the committed surface says
    LD003  nondeterminism: two runs of the same cell with the same
           seed produced different canonical bytes (never acceptable
           by justification — fix the leak)
    LD004  scenario census drift: the cell grid, load levels, or a
           cell's event census changed shape vs the manifest
    LD005  shard scaling violated: a sharded-router cell (wNrK, K>1)
           fails to knee strictly later than its singleton twin (wNr1),
           or fails to sustain >= 2x the singleton's offered load
           before its knee — the structural claim of the sharded
           control plane (never acceptable by justification)

Same contract as the other seven planes: accepted findings carry a
one-line justification and match as a (scenario, rule, key) multiset;
``--update-baseline`` re-snapshots facts and carries justifications;
drift rules (LD001/LD002/LD004) only judge the pinned default sweep —
DTLOAD_BUDGET/DTLOAD_SEED_BASE/DTLOAD_TARGET/DTLOAD_SCALE overrides
explore more seeds or other operating points without drift noise
(LD003 still applies: determinism must hold at every seed; LD005, like
LD003, can never be baked into the baseline).

Every LD001/LD002 finding carries a ``dtl1.`` replay token; ``lint
--load --replay TOKEN`` re-runs exactly that cell and prints its
metrics, so a nightly regression reproduces locally in one command.
"""

from __future__ import annotations

import base64
import json
import os
import zlib
from dataclasses import dataclass
from pathlib import Path
from typing import Optional

__all__ = [
    "LOAD_RULES",
    "LoadFinding",
    "LoadManifest",
    "encode_token",
    "decode_token",
    "check_load",
    "run_load",
    "DEFAULT_LOAD_MANIFEST_PATH",
]

DEFAULT_LOAD_MANIFEST_PATH = Path(__file__).parent / "load_manifest.json"

_MANIFEST_NOTE = (
    "Committed capacity surface (dynamo-tpu lint --load): per-cell "
    "latency/shed/routing metrics at each offered-load level from the "
    "pinned-seed macro-simulation of the real control plane at virtual "
    "time.  Regenerate with --load --update-baseline; every accepted "
    "entry needs a real justification."
)

LOAD_RULES = {
    "LD001": "capacity regression vs the committed surface (p99 TTFT, "
             "shed rate, or completions)",
    "LD002": "SLA knee moved to a lower offered-load level",
    "LD003": "same-seed twin runs diverged (nondeterminism)",
    "LD004": "cell grid / level / census drifted from the manifest",
    "LD005": "sharded-router cell fails its scaling claim vs the "
             "singleton twin (knee not later, or < 2x sustained load)",
}

# drift rules are resolved by re-snapshotting, not by justification
_DRIFT_RULES = ("LD001", "LD002", "LD004")

_TOKEN_PREFIX = "dtl1."

# LD001 thresholds: generous enough that scheduler-seed jitter inside
# one pinned run never trips them, tight enough that doubling a stage's
# latency or halving capacity always does
_P99_RATIO = 1.3
_P99_FLOOR_MS = 5.0
_SHED_DELTA = 0.05
_COMPLETED_RATIO = 0.8


# ---------------------------------------------------------------- findings


@dataclass(frozen=True, order=True)
class LoadFinding:
    """One load-plane finding.  ``(scenario, rule, key)`` is the stable
    acceptance key — scenario is the cell name ("family/topology");
    replay tokens live in ``detail`` only."""

    scenario: str
    rule: str
    key: str
    detail: str

    @property
    def accept_key(self) -> tuple[str, str, str]:
        return (self.scenario, self.rule, self.key)

    def render(self) -> str:
        return f"{self.scenario}: {self.rule}[{self.key}] {self.detail}"

    def to_json(self) -> dict:
        return {
            "scenario": self.scenario,
            "rule": self.rule,
            "key": self.key,
            "detail": self.detail,
        }


# ---------------------------------------------------------------- manifest


class LoadManifest:
    """Committed capacity surface + accepted (justified) findings."""

    def __init__(self, cells: Optional[dict] = None,
                 accepted: Optional[list[dict]] = None,
                 header: Optional[dict] = None,
                 params: Optional[dict] = None):
        self.cells: dict = cells or {}
        self.accepted: list[dict] = accepted or []
        self.header: dict = header or {}
        self.params: dict = params or {}

    @classmethod
    def load(cls, path: Path) -> "LoadManifest":
        if not Path(path).is_file():
            return cls()
        data = json.loads(Path(path).read_text())
        return cls(dict(data.get("cells", {})),
                   list(data.get("accepted", [])),
                   dict(data.get("header", {})),
                   dict(data.get("params", {})))

    def save(self, path: Path) -> None:
        doc = {
            "version": 1,
            "header": self.header or {"note": _MANIFEST_NOTE},
            "params": self.params,
            "cells": self.cells,
            "accepted": sorted(
                self.accepted,
                key=lambda e: (e["scenario"], e["rule"], e["key"]),
            ),
        }
        Path(path).write_text(
            json.dumps(doc, indent=2, sort_keys=True) + "\n"
        )

    def _counts(self) -> dict[tuple[str, str, str], int]:
        counts: dict[tuple[str, str, str], int] = {}
        for e in self.accepted:
            key = (e["scenario"], e["rule"], e["key"])
            counts[key] = counts.get(key, 0) + 1
        return counts

    def filter(self, findings: list[LoadFinding]) -> list[LoadFinding]:
        """Findings NOT covered by an accepted entry (stable-sorted)."""
        budget = self._counts()
        fresh: list[LoadFinding] = []
        for f in sorted(findings):
            if budget.get(f.accept_key, 0) > 0:
                budget[f.accept_key] -= 1
            else:
                fresh.append(f)
        return fresh

    @classmethod
    def from_facts(cls, facts: dict, findings: list[LoadFinding],
                   previous: "LoadManifest") -> "LoadManifest":
        just: dict[tuple[str, str, str], list[str]] = {}
        for e in previous.accepted:
            key = (e["scenario"], e["rule"], e["key"])
            just.setdefault(key, []).append(e.get("justification", ""))
        accepted = []
        for f in sorted(findings):
            carried = just.get(f.accept_key)
            accepted.append({
                "scenario": f.scenario,
                "rule": f.rule,
                "key": f.key,
                "detail": f.detail,
                "justification": (
                    carried.pop(0) if carried else "TODO: justify"
                ),
            })
        return cls(facts["cells"], accepted, previous.header or None,
                   facts.get("params", {}))


# ------------------------------------------------------------ replay token


def encode_token(payload: dict) -> str:
    raw = json.dumps(payload, sort_keys=True,
                     separators=(",", ":")).encode()
    return _TOKEN_PREFIX + base64.urlsafe_b64encode(
        zlib.compress(raw, 9)).decode().rstrip("=")


def decode_token(token: str) -> dict:
    if not token.startswith(_TOKEN_PREFIX):
        raise ValueError(f"not a dtload replay token: {token[:16]!r}")
    body = token[len(_TOKEN_PREFIX):]
    body += "=" * (-len(body) % 4)
    return json.loads(zlib.decompress(base64.urlsafe_b64decode(body)))


def _cell_token(cell: str, level: float, seed: int, target: int) -> str:
    family, topology = cell.split("/", 1)
    return encode_token({"family": family, "topology": topology,
                         "level": level, "seed": seed, "target": target})


# ------------------------------------------------------------------ checks


def _knee_rank(knee) -> float:
    return float("inf") if knee is None else float(knee)


def _sustained_rps(cell_obs: dict) -> float:
    """Highest offered load the cell held BEFORE its SLA knee (or over
    the whole grid when it never kneed)."""
    knee = _knee_rank(cell_obs.get("knee_level"))
    held = [m.get("offered_rps", 0.0)
            for lvl, m in cell_obs.get("levels", {}).items()
            if float(lvl) < knee]
    return max(held, default=0.0)


def _shard_scaling(facts: dict) -> list[LoadFinding]:
    """LD005: every sharded-router cell must beat its singleton twin —
    the load manifest is the committed proof of ROADMAP item 1."""
    findings: list[LoadFinding] = []
    cells = facts["cells"]
    for cell in sorted(cells):
        family, topo = cell.split("/", 1)
        base, _, k = topo.rpartition("r")
        if not base or not k.isdigit() or int(k) <= 1:
            continue
        singleton = f"{family}/{base}r1"
        if singleton not in cells:
            continue
        obs, ref = cells[cell], cells[singleton]
        if _knee_rank(obs.get("knee_level")) <= \
                _knee_rank(ref.get("knee_level")):
            findings.append(LoadFinding(
                cell, "LD005", "knee",
                f"knee at level {obs.get('knee_level')} is not strictly "
                f"later than the singleton twin's "
                f"({ref.get('knee_level')})"))
        held, ref_held = _sustained_rps(obs), _sustained_rps(ref)
        if held < 2.0 * ref_held:
            findings.append(LoadFinding(
                cell, "LD005", "sustained",
                f"sustains {held:.2f} rps before the knee vs singleton "
                f"{ref_held:.2f} rps — below the 2x scaling claim"))
    return findings


def check_load(facts: dict, manifest: LoadManifest, *,
               drift: bool = True, seed_base: int = 0) -> list[LoadFinding]:
    """Diff an observed sweep against the committed surface."""
    findings: list[LoadFinding] = []
    target = int(facts.get("params", {}).get("target_requests", 0))
    for cell, obs in sorted(facts["cells"].items()):
        if not obs.get("twin_match", True):
            findings.append(LoadFinding(
                cell, "LD003", "determinism",
                "two runs of this cell with the same seed produced "
                "different canonical bytes"))
    if not drift:
        return findings
    # the scaling claim is a property of the pinned surface itself, not
    # a diff against the manifest — judged whenever drift rules are
    findings.extend(_shard_scaling(facts))
    com_cells = manifest.cells
    for cell in sorted(set(facts["cells"]) - set(com_cells)):
        findings.append(LoadFinding(
            cell, "LD004", "+cell",
            "cell absent from the committed load manifest "
            "(run --load --update-baseline)"))
    for cell in sorted(set(com_cells) - set(facts["cells"])):
        findings.append(LoadFinding(
            cell, "LD004", "-cell",
            "committed cell no longer swept"))
    for cell, obs in sorted(facts["cells"].items()):
        com = com_cells.get(cell)
        if com is None:
            continue
        obs_levels, com_levels = obs["levels"], com.get("levels", {})
        for lvl in sorted(set(obs_levels) - set(com_levels), key=float):
            findings.append(LoadFinding(
                cell, "LD004", f"+level:{lvl}",
                f"level {lvl} not in the committed surface"))
        for lvl in sorted(set(com_levels) - set(obs_levels), key=float):
            findings.append(LoadFinding(
                cell, "LD004", f"-level:{lvl}",
                f"committed level {lvl} no longer swept"))
        obs_census = set(obs.get("census", {}))
        com_census = set(com.get("census", {}))
        for k in sorted(obs_census - com_census):
            findings.append(LoadFinding(
                cell, "LD004", f"+census:{k}",
                f"new event kind {k!r} in the cell's census"))
        for k in sorted(com_census - obs_census):
            findings.append(LoadFinding(
                cell, "LD004", f"-census:{k}",
                f"committed event kind {k!r} no longer occurs"))
        for lvl in sorted(set(obs_levels) & set(com_levels), key=float):
            o, c = obs_levels[lvl], com_levels[lvl]
            token = _cell_token(cell, float(lvl), seed_base, target)
            old_p99 = c.get("ttft_p99_ms", 0.0)
            new_p99 = o.get("ttft_p99_ms", 0.0)
            if (new_p99 > _P99_RATIO * max(old_p99, _P99_FLOOR_MS)):
                findings.append(LoadFinding(
                    cell, "LD001", f"p99:{lvl}",
                    f"p99 TTFT {new_p99:.1f}ms vs committed "
                    f"{old_p99:.1f}ms at level {lvl} "
                    f"[replay {token}]"))
            old_shed = c.get("shed_rate", 0.0)
            new_shed = o.get("shed_rate", 0.0)
            if new_shed - old_shed > _SHED_DELTA:
                findings.append(LoadFinding(
                    cell, "LD001", f"shed:{lvl}",
                    f"shed rate {new_shed:.3f} vs committed "
                    f"{old_shed:.3f} at level {lvl} "
                    f"[replay {token}]"))
            old_done = c.get("completed", 0)
            if old_done and o.get("completed", 0) < \
                    _COMPLETED_RATIO * old_done:
                findings.append(LoadFinding(
                    cell, "LD001", f"completed:{lvl}",
                    f"completed {o.get('completed', 0)} vs committed "
                    f"{old_done} at level {lvl} [replay {token}]"))
        obs_knee = _knee_rank(obs.get("knee_level"))
        com_knee = _knee_rank(com.get("knee_level"))
        if obs_knee < com_knee:
            token = _cell_token(cell, obs.get("knee_level"), seed_base,
                                target)
            findings.append(LoadFinding(
                cell, "LD002", "knee",
                f"SLA knee moved down: level {obs.get('knee_level')} "
                f"now breaches (committed: {com.get('knee_level')}) "
                f"[replay {token}]"))
    return findings


# --------------------------------------------------------------- CLI entry


def _budget_env() -> tuple[int, int, bool]:
    budget = max(1, int(os.environ.get("DTLOAD_BUDGET", "1") or 1))
    seed_base = int(os.environ.get("DTLOAD_SEED_BASE", "0") or 0)
    pinned = (budget == 1 and seed_base == 0
              and not os.environ.get("DTLOAD_TARGET")
              and not os.environ.get("DTLOAD_SCALE"))
    return budget, seed_base, pinned


_TOUCHES = (
    "dynamo_tpu/load/", "analysis/loadcheck", "analysis/detloop",
    "analysis/perf_manifest.json", "llm/kv_router/", "llm/kv/",
    "planner/", "obs/costs", "obs/topology", "dynamo_tpu/tokens",
)


def _load_affected(root: Path) -> bool:
    """The sweep exercises the whole control plane; ``--changed`` only
    decides whether to run it at all (cells aren't file-subsettable)."""
    from dynamo_tpu.analysis.cli import _git_changed_paths

    dirty = [str(p) for p in _git_changed_paths(root)]
    return any(frag in d for d in dirty for frag in _TOUCHES)


def _replay(token: str, fmt: str, out) -> int:
    from dynamo_tpu.load.sim import canonical_bytes, run_cell

    p = decode_token(token)
    res = run_cell(p["family"], p["topology"], seed=int(p["seed"]),
                   level=float(p["level"]),
                   target_requests=int(p["target"]))
    if fmt == "json":
        doc = {"cell": f"{p['family']}/{p['topology']}",
               "level": p["level"], "seed": p["seed"],
               "metrics": res["metrics"], "census": res["census"]}
        print(json.dumps(doc, indent=2, sort_keys=True), file=out)
    else:
        m = res["metrics"]
        print(f"{p['family']}/{p['topology']} level={p['level']} "
              f"seed={p['seed']}: {m['requests']} requests, "
              f"{m['completed']} completed, shed={m['shed_rate']}, "
              f"p99 TTFT {m['ttft_p99_ms']}ms "
              f"(sla {m['sla_ttft_ms']}ms)", file=out)
        print(f"  canonical: {len(canonical_bytes(res))} bytes", file=out)
    return 0


def run_load(args, out) -> int:
    """``dynamo-tpu lint --load``: sweep the capacity grid, diff it
    against the committed surface, exit 1 on any non-accepted finding.
    ``--update-baseline`` re-snapshots the manifest (carrying
    justifications by key); ``--replay TOKEN`` re-runs one cell."""
    token = getattr(args, "replay", None)
    if token:
        if not token.startswith(_TOKEN_PREFIX):
            print(f"not a dtload replay token: {token[:16]!r} "
                  f"(expected {_TOKEN_PREFIX}...)", file=out)
            return 2
        return _replay(token, getattr(args, "fmt", "text"), out)

    from dynamo_tpu.load.sim import CELLS, sweep

    manifest_path = Path(
        getattr(args, "manifest", None) or DEFAULT_LOAD_MANIFEST_PATH)
    manifest = LoadManifest.load(manifest_path)
    budget, seed_base, pinned = _budget_env()
    root = Path(getattr(args, "root", None)
                or Path(__file__).resolve().parents[2])
    if getattr(args, "changed", False) and not _load_affected(root):
        print("load plane unaffected by changed files", file=out)
        return 0
    facts = sweep(budget=budget, seed_base=seed_base)
    # drift rules only judge the pinned default operating point: extra
    # seeds or a different target/scale legitimately move the surface
    findings = check_load(facts, manifest, drift=pinned,
                          seed_base=seed_base)
    # per-cell level grids may differ (sharded-router cells sweep a
    # wider ladder), so count from the observed facts
    n_runs = sum(len(c.get("levels", {})) + 2 * budget - 1
                 for c in facts["cells"].values())

    if getattr(args, "update_baseline", False):
        if not pinned:
            print("refusing to update the load manifest from a "
                  "non-default-budget/seed/target run", file=out)
            return 2
        # LD003/LD005 are never baked into the baseline: neither a
        # nondeterministic surface nor one that fails the sharding
        # claim can be a reference point
        keep = [f for f in findings
                if f.rule not in _DRIFT_RULES
                and f.rule not in ("LD003", "LD005")]
        ld3 = [f for f in findings if f.rule in ("LD003", "LD005")]
        LoadManifest.from_facts(facts, keep, manifest).save(manifest_path)
        print(
            f"load manifest updated: {len(facts['cells'])} cell"
            f"{'' if len(facts['cells']) == 1 else 's'}, {len(keep)} "
            f"accepted finding{'' if len(keep) == 1 else 's'} -> "
            f"{manifest_path}",
            file=out,
        )
        if ld3:
            for f in ld3:
                print(f.render(), file=out)
            print(f"{len(ld3)} determinism/scaling finding"
                  f"{'' if len(ld3) == 1 else 's'} NOT accepted — fix "
                  "the regression", file=out)
            return 1
        return 0

    fresh = manifest.filter(findings)
    n_accepted = len(findings) - len(fresh)
    if getattr(args, "fmt", "text") == "json":
        doc = {
            "findings": [f.to_json() for f in fresh],
            "accepted": n_accepted,
            "total": len(findings),
            "cells": sorted(f"{fam}/{topo}" for fam, topo in CELLS),
            "runs": n_runs,
        }
        print(json.dumps(doc, indent=2, sort_keys=True), file=out)
    else:
        for f in fresh:
            print(f.render(), file=out)
        print(
            f"{len(fresh)} load finding"
            f"{'s' if len(fresh) != 1 else ''} ({n_accepted} accepted) "
            f"over {len(facts['cells'])} cells, {n_runs} deterministic "
            "runs",
            file=out,
        )
    return 1 if fresh else 0
