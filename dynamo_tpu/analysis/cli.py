"""``dynamo-tpu lint`` — CLI front end for the static-analysis suite.

Text output for humans, ``--format json`` (stable-sorted) for CI diffing,
exit code 1 on any non-baselined finding.  ``--update-baseline`` rewrites
the committed baseline from the current findings, carrying existing
justifications over where the (path, rule, content) key still matches.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import Optional

from dynamo_tpu.analysis.core import (
    DEFAULT_BASELINE_PATH,
    Baseline,
    all_rules,
    lint_paths,
)

__all__ = ["configure_parser", "run_lint", "main"]


def configure_parser(p: argparse.ArgumentParser) -> argparse.ArgumentParser:
    p.add_argument("paths", nargs="*", default=None,
                   help="files/dirs to lint (default: the dynamo_tpu "
                        "package)")
    p.add_argument("--format", choices=["text", "json"], default="text",
                   dest="fmt")
    p.add_argument("--project", action="store_true",
                   help="also run the interprocedural pass (DT005-DT008: "
                        "cross-module call-graph rules) on top of the "
                        "per-file rules")
    p.add_argument("--trace", action="store_true",
                   help="run the compile-plane pass instead (TR001-TR007: "
                        "jaxpr/HLO trace census, donation audit, dtype "
                        "propagation, static HBM footprint) against the "
                        "committed trace manifest")
    p.add_argument("--manifest", default=None, metavar="PATH",
                   help="trace manifest file (default: the committed "
                        "analysis/trace_manifest.json; --trace only)")
    p.add_argument("--select", default=None, metavar="DT001,DT102",
                   help="comma-separated rule codes to run (default: all)")
    p.add_argument("--baseline", default=None, metavar="PATH",
                   help="baseline file (default: the committed "
                        "analysis/baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding, ignoring the baseline")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline from current findings "
                        "(carries justifications over by key)")
    p.add_argument("--root", default=None,
                   help="paths in output are relative to this directory "
                        "(default: cwd)")
    return p


def run_lint(args: argparse.Namespace, out=None) -> int:
    out = out if out is not None else sys.stdout
    if getattr(args, "trace", False):
        # compile-plane pass: its unit is jitted entrypoints, not source
        # files — it runs on its own manifest contract
        from dynamo_tpu.analysis.tracecheck import run_trace

        return run_trace(args, out)
    paths = [Path(p) for p in (args.paths or [])]
    if args.root:
        root = Path(args.root)
    elif not paths:
        # bare `dynamo-tpu lint` from any cwd: paths repo-root-relative
        # so they match the committed baseline
        root = Path(__file__).resolve().parents[2]
    else:
        root = Path.cwd()
    if not paths:
        paths = [Path(__file__).resolve().parents[1]]  # the package
    select = args.select.split(",") if args.select else None
    use_project = getattr(args, "project", False)
    file_select = select
    project_only = False
    if select and use_project:
        # project codes live in their own registry; route the split
        from dynamo_tpu.analysis.project import _PROJECT_REGISTRY

        file_select = [
            c for c in select if c.strip().upper() not in _PROJECT_REGISTRY
        ]
        project_only = not file_select
    try:
        rules = [] if project_only else all_rules(file_select or None)
    except ValueError as e:
        print(f"dynamo-tpu lint: {e}", file=sys.stderr)
        return 2

    findings = lint_paths(paths, rules, root=root)
    if use_project:
        from dynamo_tpu.analysis.project import lint_project, project_rules

        prules = project_rules(select)
        if prules:
            findings = sorted(
                findings + lint_project(paths, prules, root=root)
            )

    baseline_path = Path(args.baseline) if args.baseline else (
        DEFAULT_BASELINE_PATH
    )
    baseline = (
        Baseline() if args.no_baseline else Baseline.load(baseline_path)
    )

    if args.update_baseline:
        Baseline.from_findings(findings, baseline).save(baseline_path)
        print(
            f"baseline updated: {len(findings)} entr"
            f"{'y' if len(findings) == 1 else 'ies'} -> {baseline_path}",
            file=out,
        )
        return 0

    fresh = baseline.filter(findings)
    n_baselined = len(findings) - len(fresh)

    if args.fmt == "json":
        doc = {
            "findings": [f.to_json() for f in fresh],  # already sorted
            "baselined": n_baselined,
            "total": len(findings),
        }
        print(json.dumps(doc, indent=2, sort_keys=True), file=out)
    else:
        for f in fresh:
            print(f.render(), file=out)
        summary = (
            f"{len(fresh)} finding{'s' if len(fresh) != 1 else ''}"
            f" ({n_baselined} baselined)"
        )
        print(summary, file=out)
    return 1 if fresh else 0


def main(argv: Optional[list[str]] = None) -> int:
    p = configure_parser(argparse.ArgumentParser(prog="dynamo-tpu lint"))
    return run_lint(p.parse_args(argv))


if __name__ == "__main__":
    raise SystemExit(main())
