"""``dynamo-tpu lint`` — CLI front end for the static-analysis suite.

Text output for humans, ``--format json`` (stable-sorted) for CI diffing,
exit code 1 on any non-baselined finding.  ``--update-baseline`` rewrites
the committed baseline from the current findings, carrying existing
justifications over where the (path, rule, content) key still matches.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import Optional

from dynamo_tpu.analysis.core import (
    DEFAULT_BASELINE_PATH,
    Baseline,
    all_rules,
    lint_paths,
)

__all__ = ["configure_parser", "run_lint", "run_all", "main"]


def configure_parser(p: argparse.ArgumentParser) -> argparse.ArgumentParser:
    p.add_argument("paths", nargs="*", default=None,
                   help="files/dirs to lint (default: the dynamo_tpu "
                        "package)")
    p.add_argument("--format", choices=["text", "json"], default="text",
                   dest="fmt")
    p.add_argument("--project", action="store_true",
                   help="also run the interprocedural pass (DT005-DT008: "
                        "cross-module call-graph rules) on top of the "
                        "per-file rules")
    p.add_argument("--trace", action="store_true",
                   help="run the compile-plane pass instead (TR001-TR007: "
                        "jaxpr/HLO trace census, donation audit, dtype "
                        "propagation, static HBM footprint) against the "
                        "committed trace manifest")
    p.add_argument("--wire", action="store_true",
                   help="run the wire-plane pass instead (WR001-WR007: "
                        "extracted cross-process message contracts, "
                        "producer/consumer drift) against the committed "
                        "wire manifest")
    p.add_argument("--perf", action="store_true",
                   help="run the perf-plane pass instead (PF001-PF004: "
                        "jaxpr-walked roofline FLOPs/bytes, collective "
                        "census, predicted step latency) against the "
                        "committed perf manifest")
    p.add_argument("--shard", action="store_true",
                   help="run the sharding-plane pass instead (SH001-SH005: "
                        "SPMD placement census, per-chip memory model, "
                        "implicit-reshard and donation-sharding probes) "
                        "against the committed shard manifest")
    p.add_argument("--proto", action="store_true",
                   help="run the protocol-plane pass instead (PR001-PR005: "
                        "deterministic-schedule model checking + crash-point "
                        "exploration of the coordinator/queue/drain/persist "
                        "protocols) against the committed proto manifest")
    p.add_argument("--load", action="store_true",
                   help="run the scale-simulation pass instead (LD001-LD004: "
                        "macro-simulated capacity sweep of the real control "
                        "plane at virtual time — p99 TTFT / shed / knee per "
                        "topology x load level) against the committed load "
                        "manifest")
    p.add_argument("--kern", action="store_true",
                   help="run the kernel-plane pass instead (KN001-KN006: "
                        "static Pallas audit — VMEM budgets, index-map "
                        "bounds/race proofs, NaN-canary padding oracles, "
                        "kernel pricing + census) against the committed "
                        "kern manifest")
    p.add_argument("--metrics", action="store_true",
                   help="run the metrics-plane pass instead (MT001-MT005: "
                        "static producer->renderer->scraper audit of the "
                        "/metrics surface — dead telemetry, stale scrape "
                        "keys, label cardinality, type misuse, census "
                        "drift) against the committed metrics manifest")
    p.add_argument("--replay", default=None, metavar="TOKEN",
                   help="with --proto, --load or --kern: re-execute one "
                        "recorded run from a dtp1. interleaving token, "
                        "dtl1. cell token or dtk1. fuzz-geometry token (as "
                        "printed by a failing run or the nightly sweep) "
                        "instead of sweeping; exit 1 if it still violates")
    p.add_argument("--all", action="store_true",
                   help="run all ten passes (per-file + project, trace, "
                        "wire, perf, shard, proto, load, kern, metrics) "
                        "in one process sharing the parse cache; exit 1 "
                        "if any pass fails")
    p.add_argument("--changed", action="store_true",
                   help="restrict the per-file pass to git-dirty files "
                        "(project/trace/wire passes stay whole-program); "
                        "fast pre-commit mode")
    p.add_argument("--manifest", default=None, metavar="PATH",
                   help="manifest file (default: the committed "
                        "analysis/trace_manifest.json, wire_manifest.json "
                        "or shard_manifest.json; single-plane modes only)")
    p.add_argument("--select", default=None, metavar="DT001,DT102",
                   help="comma-separated rule codes to run (default: all)")
    p.add_argument("--baseline", default=None, metavar="PATH",
                   help="baseline file (default: the committed "
                        "analysis/baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding, ignoring the baseline")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline from current findings "
                        "(carries justifications over by key)")
    p.add_argument("--root", default=None,
                   help="paths in output are relative to this directory "
                        "(default: cwd)")
    return p


def _git_changed_paths(root: Path) -> list[Path]:
    """Python files git reports dirty (staged, unstaged, untracked)."""
    import subprocess

    try:
        res = subprocess.run(
            ["git", "status", "--porcelain"],
            cwd=root, capture_output=True, text=True, timeout=15,
        )
    except (OSError, subprocess.TimeoutExpired):
        return []
    if res.returncode != 0:
        return []
    paths = []
    for line in res.stdout.splitlines():
        frag = line[3:].split(" -> ")[-1].strip().strip('"')
        if frag.endswith(".py"):
            p = root / frag
            if p.is_file():
                paths.append(p)
    return paths


def run_lint(args: argparse.Namespace, out=None) -> int:
    out = out if out is not None else sys.stdout
    if getattr(args, "all", False):
        return run_all(args, out)
    if getattr(args, "trace", False):
        # compile-plane pass: its unit is jitted entrypoints, not source
        # files — it runs on its own manifest contract
        from dynamo_tpu.analysis.tracecheck import run_trace

        return run_trace(args, out)
    if getattr(args, "wire", False):
        # wire-plane pass: its unit is cross-process message channels —
        # it runs on its own manifest contract too
        from dynamo_tpu.analysis.wirecheck import run_wire

        return run_wire(args, out)
    if getattr(args, "perf", False):
        # perf-plane pass: its unit is roofline-priced entrypoint
        # jaxprs — same manifest contract, its own committed file
        from dynamo_tpu.analysis.perfcheck import run_perf

        return run_perf(args, out)
    if getattr(args, "shard", False):
        # sharding-plane pass: its unit is array placements under the
        # canonical audit mesh — same manifest contract again
        from dynamo_tpu.analysis.shardcheck import run_shard

        return run_shard(args, out)
    if getattr(args, "proto", False):
        # protocol-plane pass: its unit is deterministic protocol
        # scenarios (real coordinator/transport code under a seeded
        # scheduler) — same manifest contract, its own committed file
        from dynamo_tpu.analysis.protocheck import run_proto

        return run_proto(args, out)
    if getattr(args, "load", False):
        # scale-simulation pass: its unit is capacity cells (the real
        # control plane macro-simulated at virtual time against the
        # dtperf latency model) — same manifest contract, its own file
        from dynamo_tpu.analysis.loadcheck import run_load

        return run_load(args, out)
    if getattr(args, "kern", False):
        # kernel-plane pass: its unit is pallas_call sites under the
        # audit geometry matrix (interpret-mode runs + spec-only
        # traces) — same manifest contract, its own committed file
        from dynamo_tpu.analysis.kerncheck import run_kern

        return run_kern(args, out)
    if getattr(args, "metrics", False):
        # metrics-plane pass: its unit is metric names (static census
        # of the /metrics surface across producers, renderers and
        # scrapers) — same manifest contract, its own committed file
        from dynamo_tpu.analysis.metcheck import run_metrics

        return run_metrics(args, out)
    paths = [Path(p) for p in (args.paths or [])]
    if args.root:
        root = Path(args.root)
    elif not paths:
        # bare `dynamo-tpu lint` from any cwd: paths repo-root-relative
        # so they match the committed baseline
        root = Path(__file__).resolve().parents[2]
    else:
        root = Path.cwd()
    if not paths:
        paths = [Path(__file__).resolve().parents[1]]  # the package
    select = args.select.split(",") if args.select else None
    use_project = getattr(args, "project", False)
    file_select = select
    project_only = False
    if select and use_project:
        # project codes live in their own registry; route the split
        from dynamo_tpu.analysis.project import _PROJECT_REGISTRY

        file_select = [
            c for c in select if c.strip().upper() not in _PROJECT_REGISTRY
        ]
        project_only = not file_select
    try:
        rules = [] if project_only else all_rules(file_select or None)
    except ValueError as e:
        print(f"dynamo-tpu lint: {e}", file=sys.stderr)
        return 2

    file_paths = paths
    if getattr(args, "changed", False):
        # pre-commit mode: per-file rules only touch git-dirty files;
        # the project pass below stays whole-program (its rules are
        # cross-module, a partial view would miss real drift)
        file_paths = _git_changed_paths(root)
    findings = lint_paths(file_paths, rules, root=root)
    if use_project:
        from dynamo_tpu.analysis.project import lint_project, project_rules

        prules = project_rules(select)
        if prules:
            findings = sorted(
                findings + lint_project(paths, prules, root=root)
            )

    baseline_path = Path(args.baseline) if args.baseline else (
        DEFAULT_BASELINE_PATH
    )
    baseline = (
        Baseline() if args.no_baseline else Baseline.load(baseline_path)
    )

    if args.update_baseline:
        Baseline.from_findings(findings, baseline).save(baseline_path)
        print(
            f"baseline updated: {len(findings)} entr"
            f"{'y' if len(findings) == 1 else 'ies'} -> {baseline_path}",
            file=out,
        )
        return 0

    fresh = baseline.filter(findings)
    n_baselined = len(findings) - len(fresh)

    if args.fmt == "json":
        doc = {
            "findings": [f.to_json() for f in fresh],  # already sorted
            "baselined": n_baselined,
            "total": len(findings),
        }
        print(json.dumps(doc, indent=2, sort_keys=True), file=out)
    else:
        for f in fresh:
            print(f.render(), file=out)
        summary = (
            f"{len(fresh)} finding{'s' if len(fresh) != 1 else ''}"
            f" ({n_baselined} baselined)"
        )
        print(summary, file=out)
    return 1 if fresh else 0


def run_all(args: argparse.Namespace, out=None) -> int:
    """All ten passes in one process: per-file + project rules (one
    ``ast.parse`` per file via ``core.parse_module``'s cache, which the
    wire pass shares), then the compile-plane trace audit, then the
    wire-plane contract check, then the perf-plane roofline check
    (which shares tracecheck's entrypoint registry), then the
    sharding-plane placement audit, then the protocol-plane
    deterministic exploration, then the scale-simulation capacity
    sweep, then the kernel-plane Pallas audit, then the metrics-plane
    /metrics-surface census.  Exit 1 if any pass has fresh findings;
    ``--update-baseline`` rewrites all the committed baselines."""
    out = out if out is not None else sys.stdout
    # the shard probes need >= 4 devices, and the device count can only
    # be forced BEFORE any pass initializes the jax backend
    from dynamo_tpu.analysis.shardcheck import ensure_audit_devices

    ensure_audit_devices()
    from dynamo_tpu.analysis.kerncheck import run_kern
    from dynamo_tpu.analysis.loadcheck import run_load
    from dynamo_tpu.analysis.metcheck import run_metrics
    from dynamo_tpu.analysis.perfcheck import run_perf
    from dynamo_tpu.analysis.protocheck import run_proto
    from dynamo_tpu.analysis.shardcheck import run_shard
    from dynamo_tpu.analysis.tracecheck import run_trace
    from dynamo_tpu.analysis.wirecheck import run_wire

    sub = argparse.Namespace(**vars(args))
    sub.all = False
    sub.project = True
    sub.manifest = None        # per-plane defaults; --manifest is ambiguous here
    rc_file = run_lint(sub, out)
    rc_trace = run_trace(sub, out)
    rc_wire = run_wire(sub, out)
    rc_perf = run_perf(sub, out)
    rc_shard = run_shard(sub, out)
    rc_proto = run_proto(sub, out)
    rc_load = run_load(sub, out)
    rc_kern = run_kern(sub, out)
    rc_metrics = run_metrics(sub, out)
    return max(rc_file, rc_trace, rc_wire, rc_perf, rc_shard, rc_proto,
               rc_load, rc_kern, rc_metrics)


def main(argv: Optional[list[str]] = None) -> int:
    p = configure_parser(argparse.ArgumentParser(prog="dynamo-tpu lint"))
    return run_lint(p.parse_args(argv))


if __name__ == "__main__":
    raise SystemExit(main())
