"""Wire-plane static analysis (dtwire): extracted message contracts.

The per-file rules see one module, the project pass sees the call
graph, tracecheck sees what XLA compiles — none of them see the *wire*:
coordinator KV/blob commands, TCP endpoint frames, router KV events,
KV-block transfer ops, ``DTKVP1`` persist headers and planner prewarm
hints are all stringly-typed dicts whose producer and consumer live in
different functions (often different modules) and drift silently until
a runtime ``KeyError``.  This pass extracts every cross-process message
contract from the code itself, over the same ``ProjectIndex`` the
interprocedural pass builds (one ``ast.parse`` per file, shared through
``core.parse_module``):

- **producers** — dict literals flowing into a framing/JSON sink
  (``write_frame``/``encode_frame`` header positions, ``json.dumps``,
  ``publish(subject, payload)``, durable WAL/``kv_put`` writes), found
  through a fixpoint over function parameters that reach a sink, plus
  conditional ``d["k"] = v`` augmentations (always vs maybe keys) and
  literal discriminator domains resolved through module/class string
  constants (``CoordOp.KV_PUT`` -> ``"kv_put"``);
- **consumers** — dict roots born from ``read_frame`` unpacks, RPC
  round-trip returns, ``subscribe`` callback payloads and
  ``json.loads``, profiled for reads (``h["k"]`` required,
  ``h.get("k")`` optional, ``"k" in h`` guards), discriminator dispatch
  (``if op == ...: / elif``) tagging reads per variant, and opaque
  ``Cls(**d)`` destructuring.

Producer and consumer sites meet on a *channel* — ``module:<mod>``,
``subject:<normalized subject>`` or ``kv:<key>``, split by
discriminator key — and the rules run per channel:

  WR001  field written by a producer but read by no consumer
  WR002  field read without a default but not written by every producer
  WR003  discriminator drift: emitted value no dispatch handles (or a
         dispatch arm for a value no producer emits)
  WR004  persisted / cross-replica payload missing a version tag
  WR005  non-JSON-safe value (bytes, numpy/jax scalar, struct.pack)
         flowing into ``json.dumps`` via local dataflow
  WR006  framing write reachable after a close/abort of the same
         writer (static twin of dtsan's FramingGuard)
  WR007  schema drift against the committed wire manifest

Channel facts snapshot into ``analysis/wire_manifest.json`` with the
same accepted/justification/``--update-baseline`` contract as the
trace manifest: ``dynamo-tpu lint --wire`` exits 1 on any non-accepted
finding, and any schema change is an explicit, reviewed manifest diff.

Extraction is deliberately heuristic (see docs/static_analysis.md for
the caveats): it resolves local dataflow one or two hops, not arbitrary
aliasing, and channels with no extracted consumer *and* no durability
are dropped rather than guessed at.
"""

from __future__ import annotations

import ast
import hashlib
import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Optional, Sequence

from dynamo_tpu.analysis.core import dotted_name, iter_python_files
from dynamo_tpu.analysis.project import (
    FunctionInfo,
    ProjectIndex,
    _classify_call,
)

__all__ = [
    "DEFAULT_WIRE_MANIFEST_PATH",
    "WIRE_RULES",
    "WireFinding",
    "WireManifest",
    "collect_wire_facts",
    "check_wire",
    "run_wire",
]

DEFAULT_WIRE_MANIFEST_PATH = Path(__file__).parent / "wire_manifest.json"

WIRE_RULES = {
    "WR001": ("dead-wire-field",
              "field written by a producer but read by no consumer"),
    "WR002": ("latent-keyerror",
              "field read with no default but not written by every "
              "producer of the message"),
    "WR003": ("discriminator-drift",
              "discriminator value emitted that no consumer dispatch "
              "handles, or handled but never emitted"),
    "WR004": ("unversioned-payload",
              "persisted or cross-replica payload missing a "
              "version/generation tag"),
    "WR005": ("json-unsafe-value",
              "non-JSON-safe value (bytes / numpy / jax scalar) "
              "flowing into json.dumps"),
    "WR006": ("write-after-close",
              "framing write reachable after a close/abort of the "
              "same writer"),
    "WR007": ("schema-drift",
              "extracted message schema changed vs the committed "
              "wire manifest"),
}

# package-relative directories the wire plane lives in (the default
# scan scope; explicit paths override, e.g. for fixtures)
WIRE_SCOPE_DIRS = (
    "runtime", "llm/kv", "llm/kv_router", "fault", "planner",
    "components",
)

# channel discriminator keys, in priority order
DISC_KEYS = ("op", "type", "kind", "t")
# keys whose literal value domains are recorded (discriminators plus
# the router event tier tag)
DOMAIN_KEYS = DISC_KEYS + ("tier",)
# any of these keys on a durable payload counts as a version tag
VERSION_KEYS = frozenset({
    "version", "format_version", "generation", "epoch", "v", "schema",
})

_MANIFEST_NOTE = (
    "AST-extracted wire contracts (analysis/wirecheck.py): channel = "
    "producer/consumer meeting point keyed by module, pub/sub subject "
    "or kv key, split by discriminator. Schema hashes cover key census "
    "+ discriminator domains + version tagging; producer/consumer "
    "counts are informational only. Extraction is heuristic — see "
    "docs/static_analysis.md (Wire plane) for caveats."
)


# ---------------------------------------------------------------- findings ----


@dataclass(frozen=True, order=True)
class WireFinding:
    """One wire-plane finding.  ``(message, rule, key)`` is the stable
    acceptance key, the way (entrypoint, rule, key) works for trace
    findings — line numbers are deliberately absent so accepted entries
    survive unrelated edits."""

    message: str   # channel name, e.g. "module:dynamo_tpu.runtime...../op"
    rule: str
    key: str
    detail: str

    @property
    def accept_key(self) -> tuple[str, str, str]:
        return (self.message, self.rule, self.key)

    def render(self) -> str:
        return f"{self.message}: {self.rule}[{self.key}] {self.detail}"

    def to_json(self) -> dict:
        return {
            "message": self.message,
            "rule": self.rule,
            "key": self.key,
            "detail": self.detail,
        }


# ---------------------------------------------------------------- manifest ----


class WireManifest:
    """Committed wire-plane snapshot + accepted (justified) findings.

    Same contract as tracecheck.Manifest: ``accepted`` entries carry a
    one-line justification and are matched as a (message, rule, key)
    multiset; ``--update-baseline`` (with ``--wire``) re-snapshots the
    message facts and carries justifications over where the key still
    matches."""

    def __init__(self, messages: Optional[dict] = None,
                 accepted: Optional[list[dict]] = None,
                 header: Optional[dict] = None):
        self.messages: dict = messages or {}
        self.accepted: list[dict] = accepted or []
        self.header: dict = header or {}

    @classmethod
    def load(cls, path: Path) -> "WireManifest":
        if not Path(path).is_file():
            return cls()
        data = json.loads(Path(path).read_text())
        return cls(dict(data.get("messages", {})),
                   list(data.get("accepted", [])),
                   dict(data.get("header", {})))

    def save(self, path: Path) -> None:
        doc = {
            "version": 1,
            "header": self.header or {"note": _MANIFEST_NOTE},
            "messages": self.messages,
            "accepted": sorted(
                self.accepted,
                key=lambda e: (e["message"], e["rule"], e["key"]),
            ),
        }
        Path(path).write_text(
            json.dumps(doc, indent=2, sort_keys=True) + "\n"
        )

    def _counts(self) -> dict[tuple[str, str, str], int]:
        counts: dict[tuple[str, str, str], int] = {}
        for e in self.accepted:
            key = (e["message"], e["rule"], e["key"])
            counts[key] = counts.get(key, 0) + 1
        return counts

    def filter(self, findings: list[WireFinding]) -> list[WireFinding]:
        """Findings NOT covered by an accepted entry (stable-sorted)."""
        budget = self._counts()
        fresh: list[WireFinding] = []
        for f in sorted(findings):
            if budget.get(f.accept_key, 0) > 0:
                budget[f.accept_key] -= 1
            else:
                fresh.append(f)
        return fresh

    @classmethod
    def from_facts(cls, facts: dict, findings: list[WireFinding],
                   previous: "WireManifest") -> "WireManifest":
        """Re-snapshot: current channel facts become the committed
        messages; intrinsic findings become accepted entries, carrying
        the previous justification where the key still matches."""
        just: dict[tuple[str, str, str], list[str]] = {}
        for e in previous.accepted:
            key = (e["message"], e["rule"], e["key"])
            just.setdefault(key, []).append(e.get("justification", ""))
        accepted = []
        for f in sorted(findings):
            carried = just.get(f.accept_key)
            accepted.append({
                "message": f.message,
                "rule": f.rule,
                "key": f.key,
                "detail": f.detail,
                "justification": (
                    carried.pop(0) if carried else "TODO: justify"
                ),
            })
        return cls(facts, accepted, previous.header or None)


# ---------------------------------------------------- literal resolution ----


def _const_table(index: ProjectIndex) -> dict[str, str]:
    """Dotted name -> string literal, over every module's top-level and
    class-level ``NAME = "lit"`` assignments.  Cross-module references
    resolve through each module's import table (ctx.canonical), so
    ``CoordOp.KV_PUT`` bottoms out at its literal wherever it is used."""
    consts: dict[str, str] = {}
    for modname, ctx in index.modules.items():
        for node in ctx.tree.body:
            if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Constant) and isinstance(
                    node.value.value, str):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        consts[f"{modname}.{t.id}"] = node.value.value
            elif isinstance(node, ast.ClassDef):
                for s in node.body:
                    if isinstance(s, ast.Assign) and isinstance(
                            s.value, ast.Constant) and isinstance(
                            s.value.value, str):
                        for t in s.targets:
                            if isinstance(t, ast.Name):
                                consts[
                                    f"{modname}.{node.name}.{t.id}"
                                ] = s.value.value
    return consts


def _lit_values(expr: ast.AST, ctx, modname: str,
                consts: dict[str, str]) -> list[str]:
    """Possible string values of ``expr``; "?" marks unresolvable."""
    if isinstance(expr, ast.Constant):
        return [expr.value] if isinstance(expr.value, str) else ["?"]
    if isinstance(expr, ast.IfExp):
        return (_lit_values(expr.body, ctx, modname, consts)
                + _lit_values(expr.orelse, ctx, modname, consts))
    raw = dotted_name(expr)
    if raw:
        for cand in (ctx.canonical(raw), f"{modname}.{raw}"):
            if cand in consts:
                return [consts[cand]]
    return ["?"]


def _param_names(fn_node) -> list[str]:
    a = fn_node.args
    return [p.arg for p in (list(a.posonlyargs) + list(a.args))]


def _root_name(expr: ast.AST) -> Optional[str]:
    """Name a dict-valued expression is rooted in: a bare ``Name`` or
    the first arg of a ``dict(name, ...)`` rebuild."""
    if isinstance(expr, ast.Name):
        return expr.id
    if (isinstance(expr, ast.Call) and isinstance(expr.func, ast.Name)
            and expr.func.id == "dict" and expr.args
            and isinstance(expr.args[0], ast.Name)):
        return expr.args[0].id
    return None


def _unwrap_async(expr: ast.AST) -> ast.AST:
    """Strip Await and asyncio.wait_for wrappers."""
    while True:
        if isinstance(expr, ast.Await):
            expr = expr.value
            continue
        if isinstance(expr, ast.Call):
            raw = dotted_name(expr.func)
            if raw and raw.rsplit(".", 1)[-1] == "wait_for" and expr.args:
                expr = expr.args[0]
                continue
        return expr


def _normalize_subject(expr, fn_node, ctx, index=None, cls=None,
                       depth=0) -> str:
    """Stable label for a pub/sub subject (or kv key) expression: the
    helper-function leaf name (``events_subject(...)`` ->
    "events_subject"), an f-string with holes as "*", a literal, or a
    one/two-hop local / self-attribute resolution of either."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return expr.value
    if isinstance(expr, ast.JoinedStr):
        parts = []
        for v in expr.values:
            if isinstance(v, ast.Constant):
                parts.append(str(v.value))
            else:
                parts.append("*")
        return "".join(parts)
    if isinstance(expr, ast.Call):
        raw = dotted_name(expr.func)
        return raw.rsplit(".", 1)[-1] if raw else "?"
    if isinstance(expr, ast.Name) and fn_node is not None and depth < 3:
        for st in ast.walk(fn_node):
            if (isinstance(st, ast.Assign) and len(st.targets) == 1
                    and isinstance(st.targets[0], ast.Name)
                    and st.targets[0].id == expr.id):
                return _normalize_subject(st.value, fn_node, ctx, index,
                                          cls, depth + 1)
        return f"?{expr.id}"
    if isinstance(expr, ast.Attribute) and depth < 3:
        raw = dotted_name(expr)
        if raw.startswith("self.") and cls is not None and index is not None:
            attr = raw.split(".", 1)[1]
            ci = index.classes.get(cls)
            if ci:
                for m in ci.methods.values():
                    if m.node is None:
                        continue
                    for st in ast.walk(m.node):
                        if (isinstance(st, ast.Assign)
                                and len(st.targets) == 1
                                and isinstance(st.targets[0], ast.Attribute)
                                and isinstance(st.targets[0].value, ast.Name)
                                and st.targets[0].value.id == "self"
                                and st.targets[0].attr == attr):
                            return _normalize_subject(
                                st.value, m.node, ctx, index, cls,
                                depth + 1)
        return f"?{raw or 'attr'}"
    return "?"


# ------------------------------------------------------- dict key census ----


def _dict_keys(d: ast.Dict, ctx, modname, consts):
    """(keys {k: "always"}, domains {k: set of values}, opaque) for one
    dict literal.  ``**expansion`` or a non-literal key -> opaque."""
    keys: dict[str, str] = {}
    domains: dict[str, set] = {}
    opaque = False
    for k, v in zip(d.keys, d.values):
        if k is None:
            opaque = True
            continue
        names = [x for x in _lit_values(k, ctx, modname, consts)
                 if x != "?"]
        if not names:
            opaque = True
            continue
        for name in names:
            keys[name] = "always"
            if name in DOMAIN_KEYS:
                domains.setdefault(name, set()).update(
                    _lit_values(v, ctx, modname, consts))
    return keys, domains, opaque


def _dict_augments(body, varname, ctx, modname, consts, keys, domains,
                   cond=False):
    """Fold ``varname["k"] = v`` assignments under ``body`` into the
    key census: unconditional -> always, under a branch -> maybe, and
    if-with-else assigning the same key in both arms -> always."""
    for st in body:
        if isinstance(st, ast.Assign):
            for t in st.targets:
                if (isinstance(t, ast.Subscript)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == varname
                        and isinstance(t.slice, ast.Constant)
                        and isinstance(t.slice.value, str)):
                    k = t.slice.value
                    mode = "maybe" if cond else "always"
                    if keys.get(k) != "always":
                        keys[k] = mode
                    if k in DOMAIN_KEYS:
                        domains.setdefault(k, set()).update(
                            _lit_values(st.value, ctx, modname, consts))
        elif isinstance(st, ast.If):
            bk: dict[str, str] = {}
            ok: dict[str, str] = {}
            _dict_augments(st.body, varname, ctx, modname, consts,
                           bk, domains, cond=False)
            _dict_augments(st.orelse, varname, ctx, modname, consts,
                           ok, domains, cond=False)
            for k in set(bk) | set(ok):
                both = bk.get(k) == "always" and ok.get(k) == "always"
                mode = "always" if (both and not cond) else "maybe"
                if keys.get(k) != "always":
                    keys[k] = mode
        elif isinstance(st, (ast.For, ast.AsyncFor, ast.While, ast.Try,
                             ast.With, ast.AsyncWith)):
            for attr in ("body", "orelse", "finalbody"):
                _dict_augments(getattr(st, attr, []) or [], varname,
                               ctx, modname, consts, keys, domains,
                               cond=True)
            for h in getattr(st, "handlers", []) or []:
                _dict_augments(h.body, varname, ctx, modname, consts,
                               keys, domains, cond=True)


# ------------------------------------------------------------- site model ----


@dataclass
class _Producer:
    module: str
    base: str                       # channel base ("module:...", "subject:...")
    keys: dict                      # key -> "always" | "maybe"
    domains: dict                   # DOMAIN key -> set of values ("?" possible)
    opaque: bool = False
    durable: bool = False


@dataclass
class _Profile:
    """Read profile of one dict root (a function param or local)."""

    reads: set = field(default_factory=set)   # (key, required, tags)
    domain: set = field(default_factory=set)  # consumed discriminator values
    discs: set = field(default_factory=set)   # discriminator keys seen
    opaque: bool = False                      # Cls(**root) somewhere
    open_dispatch: bool = False               # dispatch has a terminal else

    @property
    def disc(self) -> Optional[str]:
        for k in DISC_KEYS:
            if k in self.discs:
                return k
        return None

    @property
    def empty(self) -> bool:
        return not (self.reads or self.domain or self.opaque)

    def merge(self, other: "_Profile", outer_tags: frozenset) -> None:
        for key, req, tags in other.reads:
            self.reads.add((key, req, tags if tags else outer_tags))
        self.domain |= other.domain
        self.discs |= other.discs
        self.opaque = self.opaque or other.opaque
        self.open_dispatch = self.open_dispatch or other.open_dispatch


@dataclass
class _Consumer:
    module: str
    base: str
    profile: _Profile


# ------------------------------------------------------------ read walker ----


class _ReadWalker:
    """Collect the read profile of dict ``root`` in one function body:
    required/optional key reads, membership guards, discriminator
    aliasing and if/elif dispatch tagging, ``Cls(**root)`` opacity, and
    one-level propagation into callees taking the root positionally."""

    def __init__(self, ext: "_Extractor", fn: FunctionInfo, ctx,
                 root: str, profile: _Profile, depth: int = 0):
        self.ext = ext
        self.fn = fn
        self.ctx = ctx
        self.root = root
        self.p = profile
        self.depth = depth
        self.aliases: dict[str, str] = {}    # local name -> disc key
        # locals that ARE the root's payload (d = json.loads(root)):
        # the decode-helper idiom, where the raw bytes arrive as a
        # param and every key read happens on the parsed local
        self.loads_roots: set[str] = set()

    # ------------------------------------------------------------ plumbing
    def run(self, body, tags=frozenset(), guarded=frozenset()):
        for st in body:
            self.stmt(st, tags, guarded)

    def stmt(self, st, tags, guarded):
        if isinstance(st, ast.If):
            newtags, guards, is_disc = self.analyze_test(st.test)
            self.expr_scan(st.test, tags, guarded)
            self.run(st.body, newtags if is_disc else tags,
                     guarded | guards)
            if st.orelse:
                if (is_disc and len(st.orelse) == 1
                        and isinstance(st.orelse[0], ast.If)):
                    self.stmt(st.orelse[0], tags, guarded)  # elif chain
                elif is_disc:
                    self.p.open_dispatch = True
                    self.run(st.orelse, frozenset({"~else"}), guarded)
                else:
                    self.run(st.orelse, tags, guarded)
            return
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # default-arg expressions evaluate at def time, under the
            # enclosing dispatch arm (the `async def _pull(q=h["queue"])`
            # idiom) — scan them with the CURRENT tags
            for d in list(st.args.defaults) + [
                    x for x in st.args.kw_defaults if x is not None]:
                self.expr_scan(d, tags, guarded)
            if self.root not in _param_names(st):   # not shadowed
                self.run(st.body, frozenset(), guarded)
            return
        if isinstance(st, (ast.For, ast.AsyncFor, ast.While, ast.Try,
                           ast.With, ast.AsyncWith)):
            for attr in ("iter", "test"):
                sub = getattr(st, attr, None)
                if sub is not None:
                    self.expr_scan(sub, tags, guarded)
            for item in getattr(st, "items", []) or []:
                self.expr_scan(item.context_expr, tags, guarded)
            for attr in ("body", "orelse", "finalbody"):
                self.run(getattr(st, attr, []) or [], tags, guarded)
            for h in getattr(st, "handlers", []) or []:
                self.run(h.body, tags, guarded)
            return
        if isinstance(st, ast.Assign):
            self.handle_assign(st, tags, guarded)
            return
        self.expr_scan(st, tags, guarded)

    # ------------------------------------------------------------- pieces
    def is_root(self, expr) -> bool:
        return isinstance(expr, ast.Name) and (
            expr.id == self.root or expr.id in self.loads_roots)

    def read_key_of(self, expr):
        """("key", required) if expr reads one key off the root."""
        if (isinstance(expr, ast.Subscript) and self.is_root(expr.value)
                and isinstance(expr.slice, ast.Constant)
                and isinstance(expr.slice.value, str)
                and isinstance(expr.ctx, ast.Load)):
            return expr.slice.value, True
        if (isinstance(expr, ast.Call)
                and isinstance(expr.func, ast.Attribute)
                and expr.func.attr in ("get", "pop")
                and self.is_root(expr.func.value)
                and expr.args
                and isinstance(expr.args[0], ast.Constant)
                and isinstance(expr.args[0].value, str)):
            return expr.args[0].value, False
        return None

    def disc_of(self, expr) -> Optional[str]:
        """Discriminator key this expression denotes, if any."""
        if isinstance(expr, ast.Name) and expr.id in self.aliases:
            return self.aliases[expr.id]
        got = self.read_key_of(expr)
        if got and got[0] in DISC_KEYS:
            return got[0]
        return None

    def record(self, key, required, tags, guarded):
        if key in guarded:
            required = False
        self.p.reads.add((key, required, tags))
        if key in DISC_KEYS:
            self.p.discs.add(key)

    def handle_assign(self, st, tags, guarded):
        target = st.targets[0] if len(st.targets) == 1 else None
        pairs = []
        if (isinstance(target, ast.Tuple) and isinstance(st.value, ast.Tuple)
                and len(target.elts) == len(st.value.elts)):
            pairs = list(zip(target.elts, st.value.elts))
        elif target is not None:
            pairs = [(target, st.value)]
        for t, v in pairs:
            got = self.read_key_of(v)
            if got and isinstance(t, ast.Name):
                key, _req = got
                if key in DISC_KEYS:
                    self.aliases[t.id] = key
                    self.p.discs.add(key)
            if (isinstance(t, ast.Name) and isinstance(v, ast.Call)
                    and self.ext.canon(v, self.ctx) == "json.loads"
                    and v.args and self.is_root(v.args[0])):
                self.loads_roots.add(t.id)
        self.expr_scan(st, tags, guarded)

    def analyze_test(self, test):
        """(variant tags, membership-guarded keys, is_disc_dispatch)"""
        tags: set = set()
        guards: set = set()

        def visit(t):
            if isinstance(t, ast.BoolOp):
                for v in t.values:
                    visit(v)
                return
            if not isinstance(t, ast.Compare) or len(t.ops) != 1:
                return
            op, left, right = t.ops[0], t.left, t.comparators[0]
            if isinstance(op, ast.Eq):
                disc = self.disc_of(left) or self.disc_of(right)
                lit = right if self.disc_of(left) else left
                if disc:
                    vals = _lit_values(lit, self.ctx, self.fn.module,
                                       self.ext.consts)
                    tags.update(vals)
                    self.p.domain.update(v for v in vals if v != "?")
                return
            if isinstance(op, ast.In):
                if (isinstance(left, ast.Constant)
                        and isinstance(left.value, str)
                        and self.is_root(right)):
                    guards.add(left.value)
                    return
                disc = self.disc_of(left)
                if disc and isinstance(right, (ast.Tuple, ast.List,
                                               ast.Set)):
                    for e in right.elts:
                        vals = _lit_values(e, self.ctx, self.fn.module,
                                           self.ext.consts)
                        tags.update(vals)
                        self.p.domain.update(
                            v for v in vals if v != "?")

        visit(test)
        return frozenset(tags), frozenset(guards), bool(tags)

    def expr_scan(self, node, tags, guarded):
        for n in ast.walk(node):
            got = self.read_key_of(n)
            if got:
                self.record(got[0], got[1], tags, guarded)
                continue
            if not isinstance(n, ast.Call):
                continue
            # dtspan envelope: tracing.extract(root) is an optional
            # read of the trace-context field off the header
            raw = dotted_name(n.func)
            if (raw.rsplit(".", 1)[-1] == "extract"
                    and "tracing" in raw
                    and n.args and self.is_root(n.args[0])):
                self.record("trace", False, tags, guarded)
            # consumed-domain contributions outside If tests handled by
            # analyze_test on the enclosing If; Compare nodes inside
            # expressions (return x == ...) are rare enough to skip.
            for kw in n.keywords:
                if kw.arg is None and self.is_root(kw.value):
                    self.p.opaque = True
            if self.depth < 2:
                for i, a in enumerate(n.args):
                    if self.is_root(a):
                        self.propagate(n, i, tags)

    def propagate(self, call, argidx, tags):
        """Merge the read profile of the callee param the root lands in."""
        site = _classify_call(call, self.ctx)
        if site is None:
            return
        for t in self.ext.index.resolve(site, self.fn):
            if t.node is None:
                continue
            params = _param_names(t.node)
            offset = 1 if (params and params[0] in ("self", "cls")
                           and site.kind in ("self", "attr")) else 0
            pi = argidx + offset
            if pi >= len(params):
                continue
            sub = self.ext.param_profile(t, params[pi],
                                         depth=self.depth + 1)
            self.p.merge(sub, tags)
            return


# -------------------------------------------------------------- extractor ----


class _Extractor:
    """One pass over the ProjectIndex: producer sites, consumer roots,
    and the site-level WR005/WR006 findings."""

    def __init__(self, index: ProjectIndex):
        self.index = index
        self.consts = _const_table(index)
        self.producers: list[_Producer] = []
        self.consumers: list[_Consumer] = []
        self.site_findings: list[WireFinding] = []
        self._profiles: dict[tuple[str, str], _Profile] = {}
        self.sink_params: set[tuple[str, str]] = set()
        # (qualname, param) pairs a tracing.inject() call stamps the
        # dtspan trace field onto before the frame is written
        self.inject_params: set[tuple[str, str]] = set()
        self.callback_channels: dict[tuple[str, str], str] = {}
        self.frame_returners: set[str] = set()
        self.dict_returners: set[str] = set()

    # ------------------------------------------------------------ helpers
    def canon(self, call: ast.Call, ctx) -> str:
        raw = dotted_name(call.func)
        return ctx.canonical(raw) if raw else ""

    def _local_assign(self, fn_node, name: str) -> Optional[ast.AST]:
        for st in ast.walk(fn_node):
            if (isinstance(st, ast.Assign) and len(st.targets) == 1
                    and isinstance(st.targets[0], ast.Name)
                    and st.targets[0].id == name):
                return st.value
        return None

    def _as_dict_source(self, expr, fn: FunctionInfo, ctx, depth=0):
        """Resolve an expression to a producible dict: returns
        (dict_node, varname, owner_fn_node, owner_ctx, owner_mod),
        the string "opaque", or None."""
        if depth > 3:
            return None
        if isinstance(expr, ast.Dict):
            return (expr, None, fn.node, ctx, fn.module)
        if isinstance(expr, ast.BinOp):     # json.dumps(...) + "\n"
            return (self._as_dict_source(expr.left, fn, ctx, depth + 1)
                    or self._as_dict_source(expr.right, fn, ctx,
                                            depth + 1))
        if isinstance(expr, ast.Call):
            canon = self.canon(expr, ctx)
            if (isinstance(expr.func, ast.Attribute)
                    and expr.func.attr == "encode"):
                return self._as_dict_source(expr.func.value, fn, ctx,
                                            depth + 1)
            if canon == "json.dumps" and expr.args:
                return self._as_dict_source(expr.args[0], fn, ctx,
                                            depth + 1)
            if canon.endswith("tracing.inject") and expr.args:
                # dtspan envelope: inject(h) returns the same header
                # with an optional trace-context field stamped on it —
                # unwrap so the underlying dict literal still resolves
                # (the caller tags the producer with the trace key)
                return self._as_dict_source(expr.args[0], fn, ctx,
                                            depth + 1)
            if canon.endswith("asdict") or any(
                    kw.arg is None for kw in expr.keywords):
                return "opaque"
            site = _classify_call(expr, ctx)
            if site is not None:
                for t in self.index.resolve(site, fn):
                    if t.node is None:
                        continue
                    tctx = self.index.modules.get(t.module)
                    if tctx is None:
                        continue
                    for st in ast.walk(t.node):
                        if (isinstance(st, ast.Return)
                                and st.value is not None):
                            v = st.value
                            if isinstance(v, ast.Dict):
                                return (v, None, t.node, tctx, t.module)
                            if isinstance(v, ast.Name):
                                d = self._local_assign(t.node, v.id)
                                if isinstance(d, ast.Dict):
                                    return (d, v.id, t.node, tctx,
                                            t.module)
                            if isinstance(v, (ast.Call, ast.BinOp)):
                                # encode-helper idiom: the target
                                # returns json.dumps({...}).encode()
                                sub = self._as_dict_source(
                                    v, t, tctx, depth + 1)
                                if isinstance(sub, tuple):
                                    return sub
            return "opaque"
        if isinstance(expr, ast.Name):
            a = self._local_assign(fn.node, expr.id)
            if isinstance(a, ast.Dict):
                return (a, expr.id, fn.node, ctx, fn.module)
            if a is not None and not isinstance(a, ast.Name):
                src = self._as_dict_source(a, fn, ctx, depth + 1)
                if isinstance(src, tuple):
                    # the local was built by a call returning a dict —
                    # caller-side augments (line["v"] = ... after
                    # line = make_header(...)) still apply; append an
                    # extra augment scope for add_producer to fold
                    return src + ((fn.node, expr.id, ctx, fn.module),)
                return src
        return None

    def add_producer(self, src, base: str, durable: bool,
                     fallback_module: str, injected: bool = False):
        if src == "opaque":
            self.producers.append(_Producer(
                fallback_module, base, {}, {}, opaque=True,
                durable=durable))
            return True
        if src is None:
            return False
        d, varname, owner_node, owner_ctx, owner_mod = src[:5]
        keys, domains, opaque = _dict_keys(d, owner_ctx, owner_mod,
                                           self.consts)
        if varname:
            _dict_augments(owner_node.body, varname, owner_ctx,
                           owner_mod, self.consts, keys, domains)
        for aug_node, aug_var, aug_ctx, aug_mod in src[5:]:
            _dict_augments(aug_node.body, aug_var, aug_ctx, aug_mod,
                           self.consts, keys, domains)
        if injected:
            # dtspan envelope: inject() stamps the trace context only
            # when tracing is enabled AND a span is active — maybe
            keys.setdefault("trace", "maybe")
        self.producers.append(_Producer(
            owner_mod, base, keys, domains, opaque=opaque,
            durable=durable))
        return True

    def param_profile(self, fn: FunctionInfo, param: str,
                      depth: int = 0) -> _Profile:
        key = (fn.qualname, param)
        hit = self._profiles.get(key)
        if hit is not None:
            return hit
        profile = _Profile()
        self._profiles[key] = profile        # recursion guard
        ctx = self.index.modules.get(fn.module)
        if ctx is not None and fn.node is not None:
            _ReadWalker(self, fn, ctx, param, profile,
                        depth=depth).run(fn.node.body)
        return profile

    # --------------------------------------------------------- sink fixpoint
    def _sink_arg_exprs(self, call: ast.Call, fn: FunctionInfo, ctx):
        """(expr, injected) pairs at header-sink positions of this
        call; ``injected`` marks headers a ``tracing.inject`` stamps
        the optional dtspan trace field onto en route to the wire."""
        out = []

        def is_inject(e) -> bool:
            return (isinstance(e, ast.Call)
                    and dotted_name(e.func).rsplit(".", 1)[-1]
                    == "inject")

        canon = self.canon(call, ctx)
        leaf = canon.rsplit(".", 1)[-1] if canon else ""
        if leaf == "write_frame" and len(call.args) >= 2:
            out.append((call.args[1], is_inject(call.args[1])))
        elif leaf == "encode_frame" and call.args:
            out.append((call.args[0], is_inject(call.args[0])))
        elif canon == "json.dumps" and call.args:
            out.append((call.args[0], False))
        for kw in call.keywords:
            if kw.arg == "header" and leaf in ("write_frame",
                                               "encode_frame"):
                out.append((kw.value, is_inject(kw.value)))
        site = _classify_call(call, ctx)
        if site is not None and self.sink_params:
            for t in self.index.resolve(site, fn):
                if t.node is None:
                    continue
                params = _param_names(t.node)
                offset = 1 if (params and params[0] in ("self", "cls")
                               and site.kind in ("self", "attr")) else 0
                for i, a in enumerate(call.args):
                    pi = i + offset
                    if (pi < len(params)
                            and (t.qualname, params[pi])
                            in self.sink_params):
                        out.append((a, is_inject(a) or
                                    (t.qualname, params[pi])
                                    in self.inject_params))
                for kw in call.keywords:
                    if kw.arg and (t.qualname, kw.arg) in self.sink_params:
                        out.append((kw.value, is_inject(kw.value) or
                                    (t.qualname, kw.arg)
                                    in self.inject_params))
        return out

    def _build_inject_params(self):
        """Function params a ``tracing.inject(param)`` call stamps the
        dtspan trace field onto (the RPC-helper idiom: the header dict
        arrives as a param, inject mutates it, write_frame sends it)."""
        for fn in self.index.functions.values():
            if fn.node is None:
                continue
            pnames = set(_param_names(fn.node))
            for call in (n for n in ast.walk(fn.node)
                         if isinstance(n, ast.Call)):
                raw = dotted_name(call.func)
                if (raw.rsplit(".", 1)[-1] == "inject"
                        and "tracing" in raw
                        and call.args
                        and isinstance(call.args[0], ast.Name)
                        and call.args[0].id in pnames):
                    self.inject_params.add((fn.qualname,
                                            call.args[0].id))

    def _build_sinks(self):
        changed = True
        while changed:
            changed = False
            for fn in self.index.functions.values():
                ctx = self.index.modules.get(fn.module)
                if ctx is None or fn.node is None:
                    continue
                pnames = set(_param_names(fn.node))
                for call in (n for n in ast.walk(fn.node)
                             if isinstance(n, ast.Call)):
                    for expr, _inj in self._sink_arg_exprs(call, fn, ctx):
                        name = _root_name(expr)
                        if (name and name in pnames
                                and (fn.qualname, name)
                                not in self.sink_params):
                            self.sink_params.add((fn.qualname, name))
                            changed = True

    def _build_frame_returners(self):
        for q, fn in self.index.functions.items():
            if fn.node is None:
                continue
            ctx = self.index.modules.get(fn.module)
            if fn.name in ("_call", "_lease_call", "_roundtrip"):
                self.frame_returners.add(q)
                continue
            frame_locals = set()
            json_locals = set()
            # pass 1: locals (ast.walk is breadth-first, so a return at
            # body level is visited before an assign nested in a try)
            for st in ast.walk(fn.node):
                if (isinstance(st, ast.Assign) and len(st.targets) == 1
                        and isinstance(st.targets[0], ast.Name)):
                    inner = _unwrap_async(st.value)
                    if isinstance(inner, ast.Call):
                        leaf = dotted_name(inner.func).rsplit(".", 1)[-1]
                        if leaf == "read_frame":
                            frame_locals.add(st.targets[0].id)
                        elif (ctx is not None
                              and self.canon(inner, ctx) == "json.loads"):
                            json_locals.add(st.targets[0].id)
            # pass 2: returns
            for st in ast.walk(fn.node):
                if isinstance(st, ast.Return) and st.value is not None:
                    inner = _unwrap_async(st.value)
                    if (isinstance(inner, ast.Call)
                            and dotted_name(inner.func).rsplit(
                                ".", 1)[-1] == "read_frame"):
                        self.frame_returners.add(q)
                    elif (isinstance(inner, ast.Name)
                          and inner.id in frame_locals):
                        self.frame_returners.add(q)
                    elif (isinstance(inner, ast.Name)
                          and inner.id in json_locals):
                        self.dict_returners.add(q)
                    elif (isinstance(inner, ast.Call) and ctx is not None
                          and self.canon(inner, ctx) == "json.loads"):
                        self.dict_returners.add(q)
                    elif (isinstance(inner, ast.Tuple)
                          and len(inner.elts) == 2
                          and isinstance(inner.elts[0], ast.Name)
                          and inner.elts[0].id in json_locals):
                        # `return header, payload` where header came from
                        # json.loads (the DTKVP1 _parse idiom)
                        self.frame_returners.add(q)

    def _build_callbacks(self):
        for fn in self.index.functions.values():
            ctx = self.index.modules.get(fn.module)
            if ctx is None or fn.node is None:
                continue
            for call in (n for n in ast.walk(fn.node)
                         if isinstance(n, ast.Call)):
                if not (isinstance(call.func, ast.Attribute)
                        and call.func.attr == "subscribe"
                        and len(call.args) >= 2):
                    continue
                subject = _normalize_subject(
                    call.args[0], fn.node, ctx, self.index, fn.cls)
                cb = call.args[1]
                target = None
                raw = dotted_name(cb)
                if raw.startswith("self.") and fn.cls:
                    ci = self.index.classes.get(fn.cls)
                    if ci:
                        target = ci.methods.get(raw.split(".", 1)[1])
                elif isinstance(cb, ast.Name):
                    target = self.index.functions.get(
                        f"{fn.module}.{cb.id}")
                if target is None or target.node is None:
                    continue
                params = _param_names(target.node)
                if params and params[0] in ("self", "cls"):
                    params = params[1:]
                if params:
                    self.callback_channels[
                        (target.qualname, params[-1])
                    ] = f"subject:{subject}"

    # ------------------------------------------------------------ the pass
    def run(self):
        self._build_inject_params()
        self._build_sinks()
        self._build_frame_returners()
        self._build_callbacks()
        for fn in self.index.functions.values():
            ctx = self.index.modules.get(fn.module)
            if ctx is None or fn.node is None:
                continue
            if isinstance(fn.node, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                self._scan_function(fn, ctx)
                self._wr006(fn, ctx)

    def _scan_function(self, fn: FunctionInfo, ctx):
        roots: list[tuple[str, str]] = []     # (local name, channel base)
        frame_vars: dict[str, str] = {}       # frame tuple var -> base
        handled_dicts: set[int] = set()       # id() of claimed Dict args
        mod_base = f"module:{fn.module}"

        # pass 1: roots produced directly by calls (ast.walk is
        # breadth-first, so a tuple-unpack at body level can be visited
        # before the nested assign that binds its frame var — collect
        # all call-bound locals before resolving unpacks in pass 2)
        for st in ast.walk(fn.node):
            if isinstance(st, ast.Assign) and len(st.targets) == 1:
                t, v = st.targets[0], st.value
                inner = _unwrap_async(v)
                if isinstance(inner, ast.Call):
                    leaf = dotted_name(inner.func).rsplit(".", 1)[-1]
                    canon = self.canon(inner, ctx)
                    base = None
                    dict_base = None
                    if leaf == "read_frame":
                        base = mod_base
                    else:
                        site = _classify_call(inner, ctx)
                        if site is not None:
                            for tgt in self.index.resolve(site, fn):
                                if tgt.qualname in self.frame_returners:
                                    base = f"module:{tgt.module}"
                                    break
                                if tgt.qualname in self.dict_returners:
                                    dict_base = f"module:{tgt.module}"
                                    break
                        if (base is None and dict_base is None and leaf in
                                ("_call", "_lease_call", "_roundtrip")):
                            base = mod_base
                    if dict_base is not None and isinstance(t, ast.Name):
                        roots.append((t.id, dict_base))
                        continue
                    if base is not None:
                        if isinstance(t, ast.Name):
                            frame_vars[t.id] = base
                        elif (isinstance(t, ast.Tuple) and t.elts
                              and isinstance(t.elts[0], ast.Name)):
                            roots.append((t.elts[0].id, base))
                        continue
                    if canon == "json.loads" and inner.args:
                        src = inner.args[0]
                        base = mod_base
                        if isinstance(src, ast.Name):
                            base = self.callback_channels.get(
                                (fn.qualname, src.id), mod_base)
                        if isinstance(t, ast.Name):
                            roots.append((t.id, base))
                        continue

        # pass 2: unpacks of pass-1 locals and awaited reply futures
        for st in ast.walk(fn.node):
            if isinstance(st, ast.Assign) and len(st.targets) == 1:
                t, v = st.targets[0], st.value
                if (isinstance(v, ast.Name) and v.id in frame_vars
                        and isinstance(t, ast.Tuple) and t.elts
                        and isinstance(t.elts[0], ast.Name)):
                    roots.append((t.elts[0].id, frame_vars[v.id]))
                    continue
                # inside an RPC round-trip helper, the awaited reply
                # future unpacks to (header, payload) — the read loop
                # resolves it with a frame on a different task
                if (fn.qualname in self.frame_returners
                        and isinstance(v, ast.Await)
                        and isinstance(_unwrap_async(v),
                                       (ast.Name, ast.Attribute))
                        and isinstance(t, ast.Tuple) and t.elts
                        and isinstance(t.elts[0], ast.Name)):
                    roots.append((t.elts[0].id, mod_base))

        # producer sites + json.loads-as-argument consumers
        for call in (n for n in ast.walk(fn.node)
                     if isinstance(n, ast.Call)):
            self._scan_call(call, fn, ctx, handled_dicts)

        for name, base in roots:
            profile = _Profile()
            _ReadWalker(self, fn, ctx, name, profile).run(fn.node.body)
            if not profile.empty:
                self.consumers.append(_Consumer(fn.module, base,
                                                profile))

        # consumer: a subscribe-callback whose payload is parsed by a
        # decode helper (the json.loads lives in the callee) — profile
        # the param itself; callbacks that json.loads inline are
        # already rooted above, so skip them to avoid double counting
        for p in _param_names(fn.node):
            base = self.callback_channels.get((fn.qualname, p))
            if base is None:
                continue
            if any(isinstance(n, ast.Call)
                   and self.canon(n, ctx) == "json.loads"
                   and n.args and isinstance(n.args[0], ast.Name)
                   and n.args[0].id == p
                   for n in ast.walk(fn.node)):
                continue
            profile = self.param_profile(fn, p)
            if not profile.empty:
                self.consumers.append(_Consumer(fn.module, base,
                                                profile))

    def _scan_call(self, call: ast.Call, fn: FunctionInfo, ctx,
                   handled: set):
        canon = self.canon(call, ctx)
        leaf = (canon.rsplit(".", 1)[-1] if canon
                else (call.func.attr
                      if isinstance(call.func, ast.Attribute) else ""))
        mod_base = f"module:{fn.module}"

        # consumer: json.loads(...) passed straight into a callee
        for i, a in enumerate(call.args):
            if not (isinstance(a, ast.Call)
                    and self.canon(a, ctx) == "json.loads" and a.args):
                continue
            base = mod_base
            if isinstance(a.args[0], ast.Name):
                base = self.callback_channels.get(
                    (fn.qualname, a.args[0].id), mod_base)
            site = _classify_call(call, ctx)
            if site is None:
                continue
            for t in self.index.resolve(site, fn):
                if t.node is None:
                    continue
                params = _param_names(t.node)
                offset = 1 if (params and params[0] in ("self", "cls")
                               and site.kind in ("self", "attr")) else 0
                pi = i + offset
                if pi < len(params):
                    sub = self.param_profile(t, params[pi], depth=1)
                    if not sub.empty:
                        self.consumers.append(
                            _Consumer(fn.module, base, sub))
                break
        # consumer: Cls(**json.loads(payload)) — opaque destructuring
        for kw in call.keywords:
            if kw.arg is None and isinstance(kw.value, ast.Call) \
                    and self.canon(kw.value, ctx) == "json.loads" \
                    and kw.value.args:
                base = mod_base
                if isinstance(kw.value.args[0], ast.Name):
                    base = self.callback_channels.get(
                        (fn.qualname, kw.value.args[0].id), mod_base)
                p = _Profile(opaque=True)
                self.consumers.append(_Consumer(fn.module, base, p))

        # producers via header sinks
        sunk = self._sink_arg_exprs(call, fn, ctx)
        for expr, injected in sunk:
            src = self._as_dict_source(expr, fn, ctx)
            if src not in (None, "opaque"):
                handled.add(id(src[0]))
            if canon == "json.dumps":
                self._wr005(expr, src, fn, ctx)
            self.add_producer(src, mod_base, False, fn.module,
                              injected=injected)

        # producers via pub/sub publish
        if (isinstance(call.func, ast.Attribute)
                and call.func.attr == "publish" and len(call.args) >= 2):
            subject = _normalize_subject(call.args[0], fn.node, ctx,
                                         self.index, fn.cls)
            src = self._as_dict_source(call.args[1], fn, ctx)
            if src not in (None, "opaque"):
                handled.add(id(src[0]))
            self.add_producer(src, f"subject:{subject}", False,
                              fn.module)

        # durable producers: WAL/file writes and coordinator kv puts
        if leaf in ("write", "write_text") and call.args:
            inner = self._find_json_dumps(call.args[0], ctx)
            if inner is not None:
                src = self._as_dict_source(inner, fn, ctx)
                if src not in (None, "opaque"):
                    handled.add(id(src[0]))
                self.add_producer(src, mod_base, True, fn.module)
        if (isinstance(call.func, ast.Attribute)
                and call.func.attr in ("kv_put", "kv_create",
                                       "kv_create_or_validate")
                and len(call.args) >= 2):
            inner = self._find_json_dumps(call.args[1], ctx)
            if inner is not None:
                keyfrag = _normalize_subject(call.args[0], fn.node, ctx,
                                             self.index, fn.cls)
                src = self._as_dict_source(inner, fn, ctx)
                if src not in (None, "opaque"):
                    handled.add(id(src[0]))
                self.add_producer(src, f"kv:{keyfrag}", True, fn.module)

        # fallback: a dict literal with a discriminator key passed to
        # any call we could not resolve (e.g. a nested send() closure)
        if not sunk and leaf not in ("publish",):
            for a in call.args:
                if (isinstance(a, ast.Dict) and id(a) not in handled
                        and any(isinstance(k, ast.Constant)
                                and k.value in DISC_KEYS
                                for k in a.keys if k is not None)):
                    handled.add(id(a))
                    self.add_producer(
                        (a, None, fn.node, ctx, fn.module),
                        mod_base, False, fn.module)

    def _find_json_dumps(self, expr, ctx) -> Optional[ast.Call]:
        for n in ast.walk(expr):
            if (isinstance(n, ast.Call)
                    and self.canon(n, ctx) == "json.dumps" and n.args):
                return n
        return None

    # ----------------------------------------------------------- WR005
    def _wr005(self, expr, src, fn: FunctionInfo, ctx):
        if src in (None, "opaque"):
            return
        d, varname, owner_node, owner_ctx, owner_mod = src[:5]
        checks: list[tuple[str, ast.AST]] = []
        for k, v in zip(d.keys, d.values):
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                checks.append((k.value, v))
        if varname:
            for st in ast.walk(owner_node):
                if isinstance(st, ast.Assign):
                    for t in st.targets:
                        if (isinstance(t, ast.Subscript)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == varname
                                and isinstance(t.slice, ast.Constant)
                                and isinstance(t.slice.value, str)):
                            checks.append((t.slice.value, st.value))
        for aug_node, aug_var, _aug_ctx, _aug_mod in src[5:]:
            for st in ast.walk(aug_node):
                if isinstance(st, ast.Assign):
                    for t in st.targets:
                        if (isinstance(t, ast.Subscript)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == aug_var
                                and isinstance(t.slice, ast.Constant)
                                and isinstance(t.slice.value, str)):
                            checks.append((t.slice.value, st.value))
        for key, value in checks:
            why = self._json_unsafe(value, owner_node, owner_ctx)
            if why:
                self.site_findings.append(WireFinding(
                    f"module:{owner_mod}", "WR005",
                    f"{fn.name}:{key}",
                    f"value for key '{key}' is {why} — json.dumps "
                    f"will raise or mangle it"))

    def _json_unsafe(self, expr, fn_node, ctx, hop=0) -> Optional[str]:
        if isinstance(expr, ast.Constant) and isinstance(
                expr.value, (bytes, bytearray)):
            return "a bytes literal"
        if isinstance(expr, ast.Call):
            raw = dotted_name(expr.func)
            canon = ctx.canonical(raw) if raw else ""
            head = canon.split(".", 1)[0]
            if head in ("numpy", "jax") or canon.startswith("jnp."):
                return f"a {canon}() value (numpy/jax scalar or array)"
            if canon == "struct.pack":
                return "struct.pack() bytes"
            if isinstance(expr.func, ast.Attribute) and \
                    expr.func.attr in ("tobytes", "encode"):
                return f"a .{expr.func.attr}() bytes value"
        if isinstance(expr, ast.Name) and hop == 0:
            a = self._local_assign(fn_node, expr.id)
            if a is not None:
                return self._json_unsafe(a, fn_node, ctx, hop=1)
        return None

    # ----------------------------------------------------------- WR006
    def _wr006(self, fn: FunctionInfo, ctx):
        found: set[str] = set()

        def closed_target(call) -> Optional[str]:
            canon = self.canon(call, ctx)
            leaf = canon.rsplit(".", 1)[-1] if canon else ""
            if leaf == "close_writer" and call.args:
                t = dotted_name(call.args[0])
                return t or None
            if isinstance(call.func, ast.Attribute) and \
                    call.func.attr in ("close", "abort"):
                t = dotted_name(call.func.value)
                if t.endswith(".transport"):
                    t = t[: -len(".transport")]
                return t or None
            return None

        def scan(body, closed: set) -> Optional[set]:
            for st in body:
                if isinstance(st, (ast.Return, ast.Raise, ast.Break,
                                   ast.Continue)):
                    return None
                if isinstance(st, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                    continue        # closure body runs later
                if isinstance(st, ast.If):
                    b = scan(list(st.body), set(closed))
                    o = scan(list(st.orelse), set(closed))
                    if b is None and o is None:
                        return None
                    closed = (b if o is None else
                              o if b is None else (b & o))
                    continue
                if isinstance(st, ast.Try):
                    outs = [scan(list(st.body), set(closed))]
                    for h in st.handlers:
                        outs.append(scan(list(h.body), set(closed)))
                    live = [x for x in outs if x is not None]
                    closed = (set.intersection(*live) if live
                              else set(closed))
                    f = scan(list(st.finalbody), closed)
                    if f is None:
                        return None
                    closed = f
                    continue
                if isinstance(st, (ast.For, ast.AsyncFor, ast.While)):
                    scan(list(st.body), set(closed))
                    continue
                if isinstance(st, (ast.With, ast.AsyncWith)):
                    r = scan(list(st.body), closed)
                    if r is None:
                        return None
                    closed = r
                    continue
                if isinstance(st, ast.Assign):
                    for t in st.targets:
                        name = dotted_name(t)
                        if name:
                            closed = {c for c in closed
                                      if c != name
                                      and not c.startswith(name + ".")}
                for node in ast.walk(st):
                    if not isinstance(node, ast.Call):
                        continue
                    canon = self.canon(node, ctx)
                    leaf = canon.rsplit(".", 1)[-1] if canon else ""
                    if leaf == "write_frame" and node.args:
                        target = dotted_name(node.args[0])
                        if target and target in closed:
                            found.add(target)
                    t = closed_target(node)
                    if t:
                        closed.add(t)
            return closed

        scan(list(fn.node.body), set())
        for writer in sorted(found):
            self.site_findings.append(WireFinding(
                f"module:{fn.module}", "WR006",
                f"{fn.name}:{writer}",
                f"write_frame({writer}, ...) reachable after "
                f"{writer} was closed/aborted on the same path"))


# --------------------------------------------------------------- channels ----


@dataclass
class _Channel:
    name: str
    disc: Optional[str]
    durable: bool = False
    # variant -> {"keys": merged {k: mode}, "opaque": bool, "sites": n}
    variants: dict = field(default_factory=dict)
    # variant -> {key: "required" | "optional"}
    reads: dict = field(default_factory=dict)
    produced_domain: set = field(default_factory=set)
    consumed_domain: set = field(default_factory=set)
    n_producers: int = 0
    n_consumers: int = 0
    opaque_consumers: bool = False
    open_dispatch: bool = False
    unknown_disc: bool = False     # some producer's disc value unresolved


def _producer_disc(p: _Producer) -> Optional[str]:
    for k in DISC_KEYS:
        if k in p.keys:
            return k
    return None


def _assemble(ext: _Extractor) -> dict[str, _Channel]:
    channels: dict[str, _Channel] = {}
    sites: dict[str, list[tuple[str, _Producer]]] = {}

    def chan(base: str, disc: Optional[str]) -> _Channel:
        name = f"{base}/{disc or '-'}"
        ch = channels.get(name)
        if ch is None:
            ch = channels[name] = _Channel(name, disc)
        return ch

    for p in ext.producers:
        disc = _producer_disc(p)
        ch = chan(p.base, disc)
        ch.n_producers += 1
        ch.durable = ch.durable or p.durable
        values = ["-"]
        if disc:
            values = sorted(p.domains.get(disc, {"?"}))
            if "?" in values:
                ch.unknown_disc = True
        ch.produced_domain.update(v for v in values
                                  if v not in ("-", "?"))
        for v in values:
            sites.setdefault(ch.name, []).append((v, p))

    for name, vlist in sites.items():
        ch = channels[name]
        by_variant: dict[str, list[_Producer]] = {}
        for v, p in vlist:
            by_variant.setdefault(v, []).append(p)
        for v, plist in by_variant.items():
            all_keys: set[str] = set()
            for p in plist:
                all_keys |= set(p.keys)
            merged = {}
            for k in all_keys:
                merged[k] = ("always" if all(
                    p.keys.get(k) == "always" for p in plist)
                    else "maybe")
            ch.variants[v] = {
                "keys": merged,
                "opaque": any(p.opaque for p in plist),
                "sites": len(plist),
            }

    for c in ext.consumers:
        pr = c.profile
        ch = chan(c.base, pr.disc)
        ch.n_consumers += 1
        ch.consumed_domain |= pr.domain
        ch.opaque_consumers = ch.opaque_consumers or pr.opaque
        ch.open_dispatch = ch.open_dispatch or pr.open_dispatch
        spill = None
        if pr.disc and any("~else" in tags for _, _, tags in pr.reads):
            # the dispatch's terminal else handles messages that carry
            # no discriminator (a reply routed past the push arms) —
            # those reads also consume the base's disc-less channel
            spill = chan(c.base, None)
            spill.n_consumers += 1
            spill.opaque_consumers = (spill.opaque_consumers
                                      or pr.opaque)
        for key, required, tags in pr.reads:
            variants = sorted(tags) if tags else ["*"]
            for v in variants:
                if v == "?":
                    continue
                rmap = ch.reads.setdefault(v, {})
                sev = "required" if required else "optional"
                if rmap.get(key) != "required":
                    rmap[key] = sev
                if v == "~else" and spill is not None:
                    smap = spill.reads.setdefault("*", {})
                    if smap.get(key) != "required":
                        smap[key] = sev

    # keep a channel only when both halves were extracted, or when the
    # payload is durable (a file/KV write has an implicit future reader)
    return {
        name: ch for name, ch in channels.items()
        if (ch.n_producers and ch.n_consumers)
        or (ch.durable and ch.n_producers)
    }


# ------------------------------------------------------------ channel rules ----


def _check_channels(channels: dict[str, _Channel]) -> list[WireFinding]:
    findings: list[WireFinding] = []
    for name in sorted(channels):
        ch = channels[name]
        star_reads = dict(ch.reads.get("*", {}))
        star_reads.update(ch.reads.get("~else", {}))

        # WR001 — dead wire field
        if ch.n_consumers and not ch.opaque_consumers:
            for v in sorted(ch.variants):
                if v == "?":
                    continue
                if (ch.disc and ch.consumed_domain
                        and v != "-" and v not in ch.consumed_domain):
                    continue    # whole variant unhandled -> WR003's job
                readable = set(star_reads) | set(ch.reads.get(v, {}))
                for k in sorted(ch.variants[v]["keys"]):
                    if k == ch.disc or k in readable:
                        continue
                    if k in VERSION_KEYS:
                        # version tags exist for readers that don't
                        # exist yet — unread-by-design (WR004's point)
                        continue
                    findings.append(WireFinding(
                        name, "WR001", f"{v}:{k}",
                        f"field '{k}' (variant '{v}') is written by "
                        f"producers but read by no extracted consumer"))

        # WR002 — latent KeyError
        if ch.n_producers:
            for v in sorted(ch.reads):
                if v == "~else":
                    continue
                if v == "*":
                    targets = [t for t in ch.variants if t != "?"]
                else:
                    targets = [v] if v in ch.variants else []
                for k, sev in sorted(ch.reads[v].items()):
                    if sev != "required" or k == ch.disc:
                        continue
                    for tv in targets:
                        var = ch.variants[tv]
                        if var["opaque"]:
                            continue
                        if var["keys"].get(k) != "always":
                            findings.append(WireFinding(
                                name, "WR002", f"{v}:{k}",
                                f"consumer reads '{k}' with no default "
                                f"but producer variant '{tv}' does not "
                                f"always write it"))
                            break

        # WR003 — discriminator drift
        if ch.disc and ch.produced_domain and ch.consumed_domain:
            if not ch.open_dispatch:
                for val in sorted(ch.produced_domain
                                  - ch.consumed_domain):
                    findings.append(WireFinding(
                        name, "WR003", f"produced-unhandled:{val}",
                        f"producers emit {ch.disc}='{val}' but no "
                        f"consumer dispatch handles it"))
            if not ch.unknown_disc:
                for val in sorted(ch.consumed_domain
                                  - ch.produced_domain):
                    findings.append(WireFinding(
                        name, "WR003", f"consumed-unproduced:{val}",
                        f"a consumer dispatches on {ch.disc}='{val}' "
                        f"but no producer emits it"))

        # WR004 — unversioned durable payload
        if ch.durable and ch.n_producers:
            opaque_only = all(v["opaque"] and not v["keys"]
                              for v in ch.variants.values())
            tagged = any(set(v["keys"]) & VERSION_KEYS
                         for v in ch.variants.values())
            if not tagged and not opaque_only:
                findings.append(WireFinding(
                    name, "WR004", "unversioned",
                    "persisted payload carries no version/generation "
                    "tag (DTKVP1-style) — old readers cannot detect a "
                    "format change"))
    return findings


# ------------------------------------------------------------------- facts ----


def _channel_facts(ch: _Channel) -> dict:
    variants = {}
    for v in sorted(ch.variants):
        var = ch.variants[v]
        reads = dict(ch.reads.get("*", {}))
        reads.update(ch.reads.get("~else", {}))
        reads.update(ch.reads.get(v, {}))
        variants[v] = {
            "produced": {k: var["keys"][k] for k in sorted(var["keys"])},
            "required": sorted(k for k, s in reads.items()
                               if s == "required"),
            "optional": sorted(k for k, s in reads.items()
                               if s == "optional"),
        }
    schema_src = {
        "discriminator": ch.disc,
        "durable": ch.durable,
        "version_tagged": any(set(v["keys"]) & VERSION_KEYS
                              for v in ch.variants.values()),
        "produced_domain": sorted(ch.produced_domain),
        "consumed_domain": sorted(ch.consumed_domain),
        "variants": variants,
    }
    schema = hashlib.sha256(
        json.dumps(schema_src, sort_keys=True).encode()
    ).hexdigest()[:16]
    facts = dict(schema_src)
    facts.update({
        "schema": schema,
        "producers": ch.n_producers,
        "consumers": ch.n_consumers,
    })
    return facts


def collect_wire_facts(paths: Optional[Sequence] = None,
                       root: Optional[Path] = None):
    """(channel facts dict, intrinsic WR001–WR006 findings) over the
    wire-plane scope (or explicit ``paths``, e.g. fixtures)."""
    if root is None:
        root = Path(__file__).resolve().parents[2]
    if paths:
        files = list(iter_python_files([Path(p) for p in paths]))
    else:
        pkg = Path(__file__).resolve().parents[1]
        scope = [pkg / d for d in WIRE_SCOPE_DIRS]
        files = list(iter_python_files([d for d in scope
                                        if d.exists()]))
    index = ProjectIndex.build(files, root=root)
    ext = _Extractor(index)
    ext.run()
    channels = _assemble(ext)
    facts = {name: _channel_facts(ch)
             for name, ch in sorted(channels.items())}
    intrinsic = sorted(_check_channels(channels) + ext.site_findings)
    return facts, intrinsic


def check_wire(facts: dict, manifest: WireManifest,
               intrinsic: Sequence[WireFinding] = ()) -> list[WireFinding]:
    """Intrinsic findings + WR007 drift vs the committed manifest."""
    findings = list(intrinsic)
    if manifest.messages:
        cur, prev = set(facts), set(manifest.messages)
        for name in sorted(cur - prev):
            findings.append(WireFinding(
                name, "WR007", "added",
                "new wire message type not in the committed manifest "
                "(run --wire --update-baseline to review the diff)"))
        for name in sorted(prev - cur):
            findings.append(WireFinding(
                name, "WR007", "removed",
                "wire message type in the manifest is no longer "
                "extracted from the code"))
        for name in sorted(cur & prev):
            old = manifest.messages[name].get("schema")
            new = facts[name].get("schema")
            if old != new:
                findings.append(WireFinding(
                    name, "WR007", "schema-drift",
                    f"extracted schema {new} != committed {old} — "
                    f"wire contract changed"))
    return sorted(findings)


# --------------------------------------------------------------------- CLI ----


def run_wire(args, out) -> int:
    """`dynamo-tpu lint --wire`: text or stable JSON, exit 1 on any
    non-accepted finding, `--update-baseline` re-snapshots the wire
    manifest (carrying justifications by key)."""
    manifest_path = Path(
        getattr(args, "manifest", None) or DEFAULT_WIRE_MANIFEST_PATH
    )
    manifest = WireManifest.load(manifest_path)
    paths = getattr(args, "paths", None) or None
    root = getattr(args, "root", None)
    facts, intrinsic = collect_wire_facts(
        paths, root=Path(root) if root else None)
    findings = check_wire(facts, manifest, intrinsic)

    if getattr(args, "update_baseline", False):
        # WR007 drift is resolved by the snapshot itself; intrinsic
        # findings become accepted entries
        keep = [f for f in findings if f.rule != "WR007"]
        WireManifest.from_facts(facts, keep, manifest).save(
            manifest_path)
        print(
            f"wire manifest updated: {len(facts)} message type"
            f"{'' if len(facts) == 1 else 's'}, {len(keep)} accepted "
            f"finding{'' if len(keep) == 1 else 's'} -> "
            f"{manifest_path}",
            file=out,
        )
        return 0

    fresh = manifest.filter(findings)
    n_accepted = len(findings) - len(fresh)
    if getattr(args, "fmt", "text") == "json":
        doc = {
            "findings": [f.to_json() for f in fresh],
            "accepted": n_accepted,
            "total": len(findings),
            "messages": sorted(facts),
        }
        print(json.dumps(doc, indent=2, sort_keys=True), file=out)
    else:
        for f in fresh:
            print(f.render(), file=out)
        print(
            f"{len(fresh)} wire finding{'s' if len(fresh) != 1 else ''} "
            f"({n_accepted} accepted) over {len(facts)} message types",
            file=out,
        )
    return 1 if fresh else 0
