"""Async-safety rules (DT001–DT004) for the distributed runtime.

These target the control-plane failure modes that dominate production
incidents in disaggregated serving stacks (PAPERS.md FlowKV; PR 2's
hand-found workers.py swallowed-cancellation bug): leaked fire-and-forget
tasks, silently eaten errors, event-loop stalls, and FIRST_COMPLETED
waiter leaks.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from dynamo_tpu.analysis.core import (
    Finding,
    ModuleContext,
    Rule,
    dotted_name,
    register,
)

# canonical dotted names that spawn a task
_SPAWN_NAMES = {"asyncio.ensure_future", "asyncio.create_task"}

_BROAD_EXC = {"Exception", "BaseException"}

# logging-ish attribute names: a handler calling one of these is not
# silently eating the error
_LOG_METHODS = {
    "debug", "info", "warning", "error", "exception", "critical", "log",
    "print_exc",
}

# canonical dotted names of calls that block the event loop
_BLOCKING_CALLS = {
    "time.sleep",
    "subprocess.run",
    "subprocess.call",
    "subprocess.check_call",
    "subprocess.check_output",
    "subprocess.Popen",
    "os.system",
    "os.popen",
    "os.waitpid",
    "socket.create_connection",
    "urllib.request.urlopen",
    "requests.get",
    "requests.post",
    "requests.put",
    "requests.delete",
    "requests.head",
    "requests.request",
    "open",
}


def _stmt_of(node: ast.AST) -> Optional[ast.AST]:
    """Innermost statement containing ``node`` (via walker parent links)."""
    while node is not None and not isinstance(node, ast.stmt):
        node = getattr(node, "_dt_parent", None)
    return node


@register
class FireAndForgetTask(Rule):
    """DT001 — ``asyncio.ensure_future``/``create_task`` whose handle is
    discarded.  An unreferenced task can be garbage-collected mid-flight,
    and its exception is silently dropped at loop shutdown; the runtime
    has been bitten by exactly this (coordinator watcher notifies).  Store
    the handle (retain + done-callback, drain on close) or await it."""

    code = "DT001"
    name = "fire-and-forget-task"
    summary = (
        "task handle from ensure_future/create_task is never stored, "
        "awaited, or cancelled"
    )
    interests = (ast.Call,)

    def visit(self, node: ast.Call, ctx: ModuleContext) -> Iterable[Finding]:
        fn = ctx.call_name(node)
        if fn not in _SPAWN_NAMES and not fn.endswith(".create_task"):
            return
        parent = getattr(node, "_dt_parent", None)
        # a bare expression statement discards the handle; anything else
        # (assignment, await, return, argument, attribute access) keeps
        # or consumes it
        if isinstance(parent, ast.Expr):
            yield ctx.finding(
                self, node,
                f"fire-and-forget task from {fn.rsplit('.', 1)[-1]}(): "
                "handle is never stored, awaited, or cancelled — retain it "
                "(set + done-callback that logs exceptions) and drain it "
                "on shutdown",
            )


@register
class SilentBroadExcept(Rule):
    """DT002 — broad ``except Exception``/bare ``except`` inside ``async
    def`` that neither logs nor re-raises.  In an async loop this eats
    transport faults invisibly: the stream just stops and nobody can
    diagnose why.  Log with ``exc_info=True`` (debug level is fine) or
    narrow the exception type."""

    code = "DT002"
    name = "silent-broad-except"
    summary = (
        "broad except in async code swallows the error without logging"
    )
    interests = (ast.ExceptHandler,)

    def _is_broad(self, handler: ast.ExceptHandler, ctx: ModuleContext) -> bool:
        t = handler.type
        if t is None:
            return True
        types = t.elts if isinstance(t, ast.Tuple) else [t]
        return any(
            ctx.canonical(dotted_name(el)) in _BROAD_EXC for el in types
        )

    def _handles_error(self, handler: ast.ExceptHandler) -> bool:
        for node in ast.walk(handler):
            if isinstance(node, ast.Raise):
                return True
            if isinstance(node, ast.Call):
                fn = node.func
                if isinstance(fn, ast.Attribute) and fn.attr in _LOG_METHODS:
                    return True
                if isinstance(fn, ast.Name) and fn.id in ("print",):
                    return True
                name = dotted_name(fn)
                if name.startswith("warnings.warn"):
                    return True
        return False

    def visit(
        self, node: ast.ExceptHandler, ctx: ModuleContext
    ) -> Iterable[Finding]:
        if not ctx.in_async:
            return
        if not self._is_broad(node, ctx):
            return
        if self._handles_error(node):
            return
        yield ctx.finding(
            self, node,
            "broad except inside async def silently eats the error — "
            "add log.debug(..., exc_info=True) or narrow the exception "
            "type",
        )


@register
class BlockingCallInAsync(Rule):
    """DT003 — blocking calls (``time.sleep``, sync subprocess/socket/
    file IO) directly on the event loop.  One blocked loop stalls every
    connection sharing it — keepalives miss TTLs, leases expire, watchers
    false-delete live workers.  Use the asyncio equivalent or push the
    call through ``run_in_executor`` (the coordinator's fsync/blob IO
    shows the pattern)."""

    code = "DT003"
    name = "blocking-call-in-async"
    summary = "blocking call inside async def stalls the event loop"
    interests = (ast.Call,)

    def visit(self, node: ast.Call, ctx: ModuleContext) -> Iterable[Finding]:
        if not ctx.in_async:
            return
        fn = ctx.call_name(node)
        if fn not in _BLOCKING_CALLS:
            return
        yield ctx.finding(
            self, node,
            f"blocking call {fn}() inside async def stalls the event "
            "loop — use the asyncio equivalent or run_in_executor",
        )


@register
class FirstCompletedLoserLeak(Rule):
    """DT004 — ``asyncio.wait(..., FIRST_COMPLETED)`` whose losing
    waiters are never cancelled.  The loser keeps running (and holding
    its queue/stream slot) after the winner returns; over a long stream
    that's a task-per-token leak.  tcp.py's generate loop and
    async_engine's cancel race show the correct shape: cancel the loser
    in every exit path."""

    code = "DT004"
    name = "first-completed-loser-leak"
    summary = (
        "asyncio.wait(FIRST_COMPLETED) without cancelling the losing "
        "waiters"
    )
    interests = (ast.Call,)

    def _is_first_completed(self, node: ast.Call, ctx: ModuleContext) -> bool:
        if ctx.call_name(node) != "asyncio.wait":
            return False
        for kw in node.keywords:
            if kw.arg == "return_when":
                name = ctx.canonical(dotted_name(kw.value))
                if name.endswith("FIRST_COMPLETED"):
                    return True
                if (
                    isinstance(kw.value, ast.Constant)
                    and kw.value.value == "FIRST_COMPLETED"
                ):
                    return True
        return False

    def _candidates(self, node: ast.Call) -> set[str]:
        """Names whose cancellation discharges the finding: the waited
        task names, the pending-set unpack target, and loop vars
        iterating either."""
        names: set[str] = set()
        if node.args:
            arg0 = node.args[0]
            if isinstance(arg0, (ast.List, ast.Set, ast.Tuple)):
                for el in arg0.elts:
                    if isinstance(el, ast.Name):
                        names.add(el.id)
            elif isinstance(arg0, ast.Name):
                names.add(arg0.id)
        # done, pending = await asyncio.wait(...)
        stmt = _stmt_of(node)
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            tgt = stmt.targets[0]
            if isinstance(tgt, ast.Tuple) and len(tgt.elts) == 2:
                pend = tgt.elts[1]
                if isinstance(pend, ast.Name):
                    names.add(pend.id)
        return names

    def visit(self, node: ast.Call, ctx: ModuleContext) -> Iterable[Finding]:
        if not self._is_first_completed(node, ctx):
            return
        func = ctx.current_func
        if func is None:
            return
        candidates = self._candidates(node)
        # extend candidates with loop vars over any candidate
        # (for t in pending: t.cancel()), then look for a discharge:
        # .cancel() on a candidate, or gather/wait over it (awaiting the
        # losers is also a non-leak)
        for sub in ast.walk(func):
            if isinstance(sub, (ast.For, ast.AsyncFor)):
                if (
                    isinstance(sub.iter, ast.Name)
                    and sub.iter.id in candidates
                    and isinstance(sub.target, ast.Name)
                ):
                    candidates.add(sub.target.id)
        for sub in ast.walk(func):
            if not isinstance(sub, ast.Call):
                continue
            fn = sub.func
            if (
                isinstance(fn, ast.Attribute)
                and fn.attr == "cancel"
                and isinstance(fn.value, ast.Name)
                and fn.value.id in candidates
            ):
                return
            if ctx.call_name(sub) in ("asyncio.gather", "asyncio.wait"):
                if sub is node:
                    continue
                for a in sub.args:
                    target = a.value if isinstance(a, ast.Starred) else a
                    if (
                        isinstance(target, ast.Name)
                        and target.id in candidates
                    ):
                        return
        yield ctx.finding(
            self, node,
            "asyncio.wait(FIRST_COMPLETED): the losing waiter tasks are "
            "never cancelled — cancel (or await) the pending set on every "
            "exit path",
        )
