"""Deterministic asyncio machinery for the protocol plane (dtproto).

Three pieces, all schedule-owned by a seeded scheduler:

``DetLoop``
    A minimal event loop (``asyncio.AbstractEventLoop`` surface, not a
    ``BaseEventLoop`` subclass — no selector, no real clock).  It keeps
    its own ready list and timer heap; each ``_run_once`` the scheduler
    picks exactly ONE ready callback, so the interleaving of every task
    in the system is a sequence of explicit, replayable choices.  Time
    is virtual: ``loop.time()`` only advances when nothing is runnable,
    jumping straight to the next timer — a 10-second lease TTL costs
    zero wall-clock.  ``run_in_executor`` runs the function inline
    (deterministic, and it is how ``asyncio.to_thread`` fsyncs land
    inside the model rather than on a real thread pool).

``RandomScheduler`` / ``PctScheduler``
    Seeded strategies over the ready list.  Random is uniform; PCT
    assigns seeded priorities per callback label and demotes the
    current leader at seeded change points — long stretches of one
    task, with injected priority inversions (the schedules that shake
    out ordering bugs uniform sampling rarely hits).

``MemNet``
    An in-memory implementation of the ``runtime/transports/net.py``
    seam: paired ``StreamReader``s speaking the real ``framing.py``
    bytes, with per-connection sever triggers ("cut this peer at its
    k-th server→client frame") and whole-server kill (crash modeling).
    Every byte crossing a channel is recorded, so the checker can
    reconstruct per-channel op-transition state machines afterwards.

Determinism contract: given the same scenario code, seed, and crash
plan, two runs produce byte-identical schedule traces.  Every choice
the loop makes is appended to ``loop.choices``; a replay token embeds
that list and ``forced_choices`` re-executes it exactly.

No scenario code lives here — see ``analysis/protocheck.py``.
"""

from __future__ import annotations

import asyncio
import contextvars
import heapq
import itertools
import logging
import random
import sys
import time as _time
import weakref
from typing import Any, Callable, Optional

log = logging.getLogger("dynamo_tpu.analysis.detloop")

__all__ = [
    "DetLoop",
    "RandomScheduler",
    "PctScheduler",
    "MemNet",
    "SimulatedCrash",
    "DeadlockError",
    "HorizonExceeded",
    "ReplayMismatch",
    "run_deterministic",
]

# virtual wall-clock epoch: time.time() inside a deterministic run reads
# epoch + loop.time(), so WAL id epochs and persist timestamps are stable
VIRTUAL_EPOCH = 1_700_000_000.0


class SimulatedCrash(BaseException):
    """Raised by a crash hook to model instant process death.

    BaseException on purpose: the coordinator's per-op ``except
    Exception`` error-reply path must NOT catch it — a dead process
    sends no error reply."""


class DeadlockError(RuntimeError):
    """Nothing runnable, nothing scheduled, main not done."""


class HorizonExceeded(RuntimeError):
    """Virtual time or step budget ran out before quiescence."""


class ReplayMismatch(RuntimeError):
    """A forced choice didn't fit the observed ready list."""


# --------------------------------------------------------------- schedulers


class RandomScheduler:
    """Uniform seeded pick over the ready list."""

    name = "random"

    def __init__(self, seed: int):
        self.seed = seed
        self.rng = random.Random(seed)

    def choose(self, ready: list) -> int:
        return self.rng.randrange(len(ready))


class PctScheduler:
    """PCT-style priority scheduler (Burckhardt et al.): each callback
    label gets a seeded priority; the highest-priority ready handle
    runs.  At ``depth`` seeded change points the current leader is
    demoted below everyone, forcing a priority inversion — the class of
    schedule that exposes ordering bugs with probabilistic guarantees
    uniform random rarely reaches."""

    name = "pct"

    def __init__(self, seed: int, depth: int = 3, span: int = 4000):
        self.seed = seed
        self.rng = random.Random(seed)
        self.depth = depth
        self._prio: dict[str, float] = {}
        self._steps = 0
        self._change = sorted(self.rng.randrange(1, span)
                              for _ in range(depth))

    def choose(self, ready: list) -> int:
        self._steps += 1
        labels = [h.label for h in ready]
        for lbl in labels:
            if lbl not in self._prio:
                self._prio[lbl] = 1.0 + self.rng.random()
        if self._change and self._steps >= self._change[0]:
            self._change.pop(0)
            top = max(labels, key=lambda l: self._prio[l])
            self._prio[top] = self.rng.random() * 0.5
        # ties (same label twice) resolve FIFO: earliest index wins
        return max(range(len(ready)),
                   key=lambda i: (self._prio[labels[i]], -i))


def make_scheduler(seed: int):
    """Seed parity alternates strategy so one seed range sweeps both."""
    return PctScheduler(seed) if seed % 2 else RandomScheduler(seed)


# ------------------------------------------------------------------- handles


class _Handle:
    """Loop-owned callback record (asyncio.Handle has __slots__ and
    cannot carry the label/seq bookkeeping the scheduler needs)."""

    __slots__ = ("callback", "args", "context", "label", "seq", "when",
                 "_cancelled")

    def __init__(self, callback, args, context, label, seq, when=None):
        self.callback = callback
        self.args = args
        self.context = context
        self.label = label
        self.seq = seq
        self.when = when
        self._cancelled = False

    def cancel(self) -> None:
        self._cancelled = True

    def cancelled(self) -> bool:
        return self._cancelled

    def __lt__(self, other) -> bool:  # heap tiebreak
        return (self.when, self.seq) < (other.when, other.seq)


def _label_of(callback) -> str:
    """Stable, address-free label for a callback: task steps get their
    coroutine's qualname, plain callbacks their own."""
    owner = getattr(callback, "__self__", None)
    if isinstance(owner, asyncio.Task):
        coro = owner.get_coro()
        return getattr(coro, "__qualname__", None) or type(coro).__name__
    if isinstance(owner, asyncio.Future):
        return "Future._schedule_callbacks"
    return (getattr(callback, "__qualname__", None)
            or type(callback).__name__)


# ---------------------------------------------------------------------- loop


class DetLoop(asyncio.AbstractEventLoop):
    def __init__(self, scheduler=None, *,
                 forced_choices: Optional[list[int]] = None,
                 horizon_s: float = 1800.0, max_steps: int = 250_000):
        self.scheduler = scheduler or RandomScheduler(0)
        self._ready: list[_Handle] = []
        self._timers: list[_Handle] = []
        self._vtime = 0.0
        self._seq = itertools.count()
        self._stopping = False
        self._running = False
        self._closed = False
        self._horizon = horizon_s
        self._max_steps = max_steps
        self._steps = 0
        self._label_counts: dict[str, int] = {}
        # the two replay artifacts: every scheduling decision, and the
        # resulting execution order as "label#occurrence" strings
        self.choices: list[int] = []
        self.trace: list[str] = []
        self._forced = list(forced_choices) if forced_choices else None
        self._exceptions: list[dict] = []
        self._asyncgens: "weakref.WeakSet" = weakref.WeakSet()
        self._ag_closers: set = set()
        self._all_tasks: "weakref.WeakSet" = weakref.WeakSet()

    # ------------------------------------------------------------ scheduling
    def call_soon(self, callback, *args, context=None):
        if self._closed:  # teardown GC stragglers: nothing left to run
            return _Handle(callback, args, None, "closed", -1)
        h = _Handle(callback, args,
                    context if context is not None
                    else contextvars.copy_context(),
                    _label_of(callback), next(self._seq))
        self._ready.append(h)
        return h

    call_soon_threadsafe = call_soon

    def call_later(self, delay, callback, *args, context=None):
        return self.call_at(self._vtime + max(0.0, delay), callback, *args,
                            context=context)

    def call_at(self, when, callback, *args, context=None):
        if self._closed:
            return _Handle(callback, args, None, "closed", -1, when)
        h = _Handle(callback, args,
                    context if context is not None
                    else contextvars.copy_context(),
                    _label_of(callback), next(self._seq), when)
        heapq.heappush(self._timers, h)
        return h

    def time(self) -> float:
        return self._vtime

    # --------------------------------------------------------------- futures
    def create_future(self) -> asyncio.Future:
        return asyncio.Future(loop=self)

    def create_task(self, coro, *, name=None, context=None):
        # context kwarg is 3.11+: drop it on 3.10 (callers here never pass it)
        t = asyncio.Task(coro, loop=self, name=name)
        self._all_tasks.add(t)
        return t

    def run_in_executor(self, executor, func, *args):
        """Inline execution: deterministic, and the only way crash hooks
        firing inside ``asyncio.to_thread`` fsyncs stay on the model's
        schedule.  The future resolves immediately; the awaiter still
        passes through the ready queue (a scheduling point)."""
        fut = self.create_future()
        try:
            fut.set_result(func(*args))
        except SimulatedCrash:
            raise  # process death: unwind the caller, no result to deliver
        except BaseException as e:
            fut.set_exception(e)
        return fut

    # ------------------------------------------------------------- lifecycle
    def is_running(self) -> bool:
        return self._running

    def is_closed(self) -> bool:
        return self._closed

    def close(self) -> None:
        self._closed = True
        for t in list(self._ag_closers):
            t.cancel()
        self._ag_closers.clear()
        # a modeled death strands tasks mid-flight (or before their first
        # step); reap their coroutines now rather than leaving them for a
        # heap-proportional gc pass — closing here keeps the "never
        # awaited" / "destroyed but pending" warnings from firing at
        # interpreter exit no matter when the strays are collected
        for task in list(self._all_tasks):
            if task.done():
                continue
            task._log_destroy_pending = False
            try:
                task.get_coro().close()
            except BaseException:
                pass  # a finally block died against the closed loop
        self._all_tasks.clear()
        self._ready.clear()
        self._timers.clear()

    def stop(self) -> None:
        self._stopping = True

    def get_debug(self) -> bool:
        return False

    def set_debug(self, enabled: bool) -> None:
        pass

    async def shutdown_asyncgens(self) -> None:
        closing = [ag.aclose() for ag in list(self._asyncgens)]
        for c in closing:
            try:
                await c
            except BaseException as e:
                # teardown of a crashed run: generators die with the
                # model's own SimulatedCrash/CancelledError
                log.debug("asyncgen close failed during loop shutdown: %r",
                          e, exc_info=True)

    async def shutdown_default_executor(self, timeout=None) -> None:
        return

    # ----------------------------------------------------------- error sink
    def call_exception_handler(self, context: dict) -> None:
        # collected, not printed: abandoned post-crash tasks routinely die
        # with SimulatedCrash/ConnectionResetError and that's the model
        # working, not noise for stderr
        self._exceptions.append(context)

    def default_exception_handler(self, context: dict) -> None:
        self._exceptions.append(context)

    def set_exception_handler(self, handler) -> None:
        pass

    def get_exception_handler(self):
        return None

    # ------------------------------------------------------------ run loops
    def _ag_firstiter(self, agen) -> None:
        self._asyncgens.add(agen)

    def _ag_finalizer(self, agen) -> None:
        if not self._closed:
            t = self.create_task(agen.aclose())
            self._ag_closers.add(t)
            t.add_done_callback(self._ag_closers.discard)

    def run_forever(self) -> None:
        if self._running:
            raise RuntimeError("loop already running")
        old_hooks = sys.get_asyncgen_hooks()
        sys.set_asyncgen_hooks(firstiter=self._ag_firstiter,
                               finalizer=self._ag_finalizer)
        asyncio.events._set_running_loop(self)
        self._running = True
        try:
            while not self._stopping:
                self._run_once()
        finally:
            self._stopping = False
            self._running = False
            asyncio.events._set_running_loop(None)
            sys.set_asyncgen_hooks(*old_hooks)

    def run_until_complete(self, future):
        fut = asyncio.ensure_future(future, loop=self)
        fut.add_done_callback(lambda f: self.stop())
        self.run_forever()
        if not fut.done():
            raise RuntimeError("loop stopped before future completed")
        return fut.result()

    def _run_once(self) -> None:
        # expire due timers into the ready list (seq order: deterministic)
        while self._timers and self._timers[0].when <= self._vtime:
            h = heapq.heappop(self._timers)
            if not h._cancelled:
                self._ready.append(h)
        if any(h._cancelled for h in self._ready):
            self._ready = [h for h in self._ready if not h._cancelled]
        # canonicalize: stable-sort by label so the ready list is identical
        # across interpreter runs even where set-iteration order (str hash)
        # permuted same-label callbacks at creation — schedules become
        # label-isomorphic, which is what traces and replay tokens key on
        self._ready.sort(key=lambda h: h.label)
        if not self._ready:
            while self._timers and self._timers[0]._cancelled:
                heapq.heappop(self._timers)
            if not self._timers:
                raise DeadlockError(
                    f"deadlock at vt={self._vtime:.3f}: nothing runnable, "
                    "nothing scheduled")
            nxt = self._timers[0].when
            if nxt > self._horizon:
                raise HorizonExceeded(
                    f"virtual-time horizon {self._horizon}s exceeded "
                    f"(next timer at {nxt:.1f}s)")
            self.trace.append(f"<advance:{nxt:.6f}>")
            self._vtime = nxt
            return
        self._steps += 1
        if self._steps > self._max_steps:
            raise HorizonExceeded(f"step budget {self._max_steps} exceeded")
        if self._forced:
            idx = self._forced.pop(0)
            if idx >= len(self._ready):
                raise ReplayMismatch(
                    f"forced choice {idx} outside ready list of "
                    f"{len(self._ready)} at step {self._steps}")
        else:
            idx = self.scheduler.choose(self._ready)
        h = self._ready.pop(idx)
        self.choices.append(idx)
        occ = self._label_counts.get(h.label, 0)
        self._label_counts[h.label] = occ + 1
        self.trace.append(f"{h.label}#{occ}")
        h.context.run(h.callback, *h.args)


def run_deterministic(loop: DetLoop, main, epoch: float = VIRTUAL_EPOCH):
    """``loop.run_until_complete(main)`` under the virtual clock.

    ``time.time`` / ``time.monotonic`` / ``time.perf_counter`` read the
    loop's virtual time for the duration — coordinator id epochs, lease
    expiry arithmetic and persist timestamps all become functions of the
    schedule alone.  References bound before the patch (pytest's timer,
    the logging module's cached formatter time) keep the real clock.
    """
    saved = (_time.time, _time.monotonic, _time.perf_counter)
    _time.time = lambda: epoch + loop.time()
    _time.monotonic = lambda: loop.time()
    _time.perf_counter = lambda: loop.time()
    try:
        return loop.run_until_complete(main)
    finally:
        _time.time, _time.monotonic, _time.perf_counter = saved


# ----------------------------------------------------------------- MemNet


class _FrameCounter:
    """Incremental complete-frame count over an append-only byte buffer
    (framing layout: [u32 hlen][u32 plen][header][payload])."""

    __slots__ = ("buf", "off", "count")

    def __init__(self):
        self.buf = bytearray()
        self.off = 0
        self.count = 0

    def feed(self, data: bytes) -> int:
        import struct

        self.buf += data
        while self.off + 8 <= len(self.buf):
            hlen, plen = struct.unpack_from(">II", self.buf, self.off)
            end = self.off + 8 + hlen + plen
            if end > len(self.buf):
                break
            self.off = end
            self.count += 1
        return self.count


class MemStreamWriter:
    """StreamWriter surface over one direction of a MemConn."""

    def __init__(self, conn: "_MemConn", direction: str):
        self._conn = conn
        self._dir = direction

    def write(self, data: bytes) -> None:
        self._conn.send(self._dir, data)

    async def drain(self) -> None:
        if self._conn.closed[self._dir]:
            raise ConnectionResetError("write to severed mem-connection")
        await asyncio.sleep(0)  # a real drain is a scheduling point

    def close(self) -> None:
        self._conn.close()

    def is_closing(self) -> bool:
        return self._conn.closed[self._dir]

    async def wait_closed(self) -> None:
        return

    def get_extra_info(self, name: str, default=None):
        return default

    @property
    def transport(self) -> "MemStreamWriter":
        return self  # .abort() lives here

    def abort(self) -> None:
        self._conn.close()


class _MemConn:
    """One full-duplex connection: two StreamReaders fed by the opposite
    writer.  ``c2s`` is client→server, ``s2c`` server→client."""

    def __init__(self, net: "MemNet", port: int, conn_no: int):
        self.net = net
        self.port = port
        self.conn_no = conn_no
        self.readers = {"c2s": asyncio.StreamReader(),
                        "s2c": asyncio.StreamReader()}
        self.closed = {"c2s": False, "s2c": False}

    def send(self, direction: str, data: bytes) -> None:
        if self.closed[direction]:
            return  # writes into a severed transport vanish, like TCP
        n = self.net._record(self, direction, data)
        plan = self.net.sever_plan
        if (plan is not None and plan["conn"] == self.conn_no
                and plan["direction"] == direction
                and n >= plan["after_frames"]):
            self.net.sever_plan = None
            self.close()
            return  # the triggering frame is lost with the connection
        self.readers[direction].feed_data(data)

    def close(self) -> None:
        for d, reader in self.readers.items():
            if not self.closed[d]:
                self.closed[d] = True
                reader.feed_eof()


class MemServer:
    """Handle returned by MemNet.start_server — the asyncio.Server
    surface the transports' stop() paths use."""

    def __init__(self, net: "MemNet", port: int, cb):
        self.net = net
        self.port = port
        self.cb = cb
        self.conns: list[_MemConn] = []
        self.tasks: "set[asyncio.Task]" = set()
        self.closed = False

    def close(self) -> None:
        self.closed = True
        self.net._servers.pop(self.port, None)

    async def wait_closed(self) -> None:
        return


class MemNet:
    """In-memory Net (transports/net.py seam) for the DetLoop.

    ``sever_plan`` cuts one connection at its k-th complete frame in one
    direction (the crash-op vocabulary's "sever" against an exact frame
    ordinal); ``kill_server`` models whole-process death.  All channel
    bytes are retained per (port, conn, direction) for the checker's
    transition extraction.
    """

    def __init__(self, loop: DetLoop):
        self.loop = loop
        self._servers: dict[int, MemServer] = {}
        self._ports = itertools.count(10001)
        self.conns: list[_MemConn] = []
        self.port_names: dict[int, str] = {}
        self.sever_plan: Optional[dict] = None
        self._counters: dict[tuple, _FrameCounter] = {}

    # ------------------------------------------------------------- Net API
    async def start_server(self, cb, host: str, port: int):
        if port == 0:
            port = next(self._ports)
        if port in self._servers:
            raise OSError(98, f"mem port {port} already bound")
        srv = MemServer(self, port, cb)
        self._servers[port] = srv
        return srv, port

    async def open_connection(self, host: str, port: int):
        srv = self._servers.get(port)
        if srv is None or srv.closed:
            raise ConnectionRefusedError(111, f"mem connect refused :{port}")
        await asyncio.sleep(0)  # dialing is a scheduling point
        conn = _MemConn(self, port, len(self.conns) + 1)
        self.conns.append(conn)
        srv.conns.append(conn)
        server_writer = MemStreamWriter(conn, "s2c")
        t = self.loop.create_task(
            self._serve(srv, conn, server_writer))
        srv.tasks.add(t)
        t.add_done_callback(srv.tasks.discard)
        return conn.readers["s2c"], MemStreamWriter(conn, "c2s")

    @staticmethod
    async def _serve(srv: MemServer, conn: _MemConn, writer) -> None:
        try:
            await srv.cb(conn.readers["c2s"], writer)
        except asyncio.CancelledError:
            raise
        except SimulatedCrash:
            pass  # the crash already tore the server down
        except (ConnectionError, RuntimeError):
            pass  # handler died against a severed peer: modeled noise

    # ------------------------------------------------------------- recorder
    def _record(self, conn: _MemConn, direction: str, data: bytes) -> int:
        key = (conn.port, conn.conn_no, direction)
        ctr = self._counters.get(key)
        if ctr is None:
            ctr = self._counters[key] = _FrameCounter()
        return ctr.feed(data)

    def name_port(self, port: int, name: str) -> None:
        """Label a bound port with its service name for fact extraction."""
        self.port_names[port] = name

    def channel_frames(self) -> dict[tuple[str, str], list[dict]]:
        """Decoded frame headers per (service, direction), connection
        transcripts concatenated in connection order."""
        from dynamo_tpu.runtime.transports.framing import decode_frames

        out: dict[tuple[str, str], list[dict]] = {}
        for (port, conn_no, direction), ctr in sorted(self._counters.items()):
            name = self.port_names.get(port, f"port{port}")
            headers = [h for h, _ in decode_frames(bytes(ctr.buf))]
            out.setdefault((name, direction), []).extend(headers)
        return out

    # ------------------------------------------------------------ crash ops
    def sever_conn_after(self, conn_no: int, after_frames: int,
                         direction: str = "s2c") -> None:
        self.sever_plan = {"conn": conn_no, "direction": direction,
                           "after_frames": after_frames}

    def kill_server(self, port: int) -> Optional[MemServer]:
        """Instant process death: unbind the port, sever every live
        connection, cancel the handler tasks.  Sync on purpose — crash
        hooks call it from inside the dying server's own stack."""
        srv = self._servers.pop(port, None)
        if srv is None:
            return None
        srv.closed = True
        for conn in srv.conns:
            conn.close()
        for t in list(srv.tasks):
            t.cancel()
        return srv
