"""AST checker framework for the dynamo-tpu static-analysis suite.

The Dynamo reference leans on Rust's type system + clippy to keep its
async control plane and engine hot path honest; rebuilding both in
Python/JAX gave that up.  This package wins some of it back mechanically:
a rule registry (rules_async.py, rules_jax.py), per-line suppression
(``# dt: noqa[DTxxx]``), and a committed baseline
(analysis/baseline.json) for grandfathered findings so the tier-1 gate
(tests/test_lint.py) starts green and stays zero-findings.

Performance contract: each file is parsed ONCE and all rules run off the
same tree — one cheap pre-scan walk (imports + jit registry, shared by
every rule) and one main visitor pass that dispatches nodes to the rules
interested in them.  The whole package lints well inside the 20s
per-test tier-1 budget.

Baseline entries match on (path, rule, line content) — not line number —
so unrelated edits above a grandfathered finding don't break the gate.
Matching is a multiset: N identical findings need N entries.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable, Iterator, Optional, Sequence

__all__ = [
    "Finding",
    "Rule",
    "ModuleContext",
    "Baseline",
    "DEFAULT_BASELINE_PATH",
    "all_rules",
    "lint_file",
    "lint_paths",
    "iter_python_files",
    "parse_module",
]

DEFAULT_BASELINE_PATH = Path(__file__).parent / "baseline.json"

_NOQA_RE = re.compile(r"#\s*dt:\s*noqa(?:\[([A-Za-z0-9_,\s]+)\])?")

# canonical dotted names that construct a jitted callable
JIT_NAMES = {"jax.jit", "jax.pjit", "jax.experimental.pjit.pjit"}
PARTIAL_NAMES = {"functools.partial", "partial"}


@dataclass(frozen=True, order=True)
class Finding:
    path: str  # posix path relative to the lint root
    line: int
    col: int
    rule: str
    message: str
    snippet: str = ""  # stripped source line — the baseline content key

    @property
    def baseline_key(self) -> tuple[str, str, str]:
        return (self.path, self.rule, self.snippet)

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    def to_json(self) -> dict:
        return {
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "rule": self.rule,
            "message": self.message,
            "snippet": self.snippet,
        }


# ------------------------------------------------------------------ rules ----


class Rule:
    """One checker.  ``interests`` lists the AST node types the main pass
    dispatches to ``visit``; ``begin_module`` sees the shared pre-scan."""

    code: str = "DT000"
    name: str = ""
    summary: str = ""
    interests: tuple = ()

    def begin_module(self, ctx: "ModuleContext") -> None:
        pass

    def visit(self, node: ast.AST, ctx: "ModuleContext") -> Iterable[Finding]:
        return ()


_REGISTRY: dict[str, type[Rule]] = {}


def register(cls: type[Rule]) -> type[Rule]:
    _REGISTRY[cls.code] = cls
    return cls


def all_rules(select: Optional[Sequence[str]] = None) -> list[Rule]:
    # importing the rule modules populates the registry
    from dynamo_tpu.analysis import rules_async, rules_jax  # noqa: F401

    codes = sorted(_REGISTRY)
    if select:
        wanted = {c.strip().upper() for c in select}
        unknown = wanted - set(codes)
        if unknown:
            raise ValueError(f"unknown rule code(s): {sorted(unknown)}")
        codes = [c for c in codes if c in wanted]
    return [_REGISTRY[c]() for c in codes]


# -------------------------------------------------------------- module ctx ----


@dataclass
class JitRegistry:
    """Shared jit facts both JAX rule families key off (one pre-scan)."""

    # function def names considered jitted (decorated with jax.jit /
    # partial(jax.jit, ...) or wrapped by name: jax.jit(self._impl))
    jitted_fns: set[str] = field(default_factory=set)
    # callable dotted name ("fn", "self._step_fn") -> donated positions
    donated: dict[str, tuple[int, ...]] = field(default_factory=dict)


class ModuleContext:
    """Per-file state handed to every rule: the parsed tree, source
    lines, import table, jit registry, and the walker's scope stacks."""

    def __init__(self, path: str, source: str, tree: ast.Module):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.imports: dict[str, str] = {}
        self.jit = JitRegistry()
        # walker-maintained scope state
        self.func_stack: list[ast.AST] = []  # FunctionDef/AsyncFunctionDef
        self.loop_depth = 0  # loops in the INNERMOST function (or module)
        self._noqa: Optional[dict[int, Optional[set[str]]]] = None

    # ------------------------------------------------------------- scopes
    @property
    def in_async(self) -> bool:
        """True when the innermost enclosing function is ``async def``
        (a nested sync ``def`` inside an async one is NOT async)."""
        return bool(self.func_stack) and isinstance(
            self.func_stack[-1], ast.AsyncFunctionDef
        )

    @property
    def current_func(self) -> Optional[ast.AST]:
        return self.func_stack[-1] if self.func_stack else None

    # ------------------------------------------------------------- names
    def canonical(self, dotted: str) -> str:
        """Resolve the leading segment through the import table:
        ``jnp.dot`` -> ``jax.numpy.dot`` under ``import jax.numpy as
        jnp``; ``jit`` -> ``jax.jit`` under ``from jax import jit``."""
        head, sep, rest = dotted.partition(".")
        base = self.imports.get(head, head)
        return base + (("." + rest) if rest else "")

    def call_name(self, node: ast.Call) -> str:
        """Canonical dotted name of a call's target ('' if dynamic)."""
        return self.canonical(dotted_name(node.func))

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    # -------------------------------------------------------------- noqa
    def is_suppressed(self, finding: Finding) -> bool:
        if self._noqa is None:
            self._noqa = {}
            for i, text in enumerate(self.lines, start=1):
                m = _NOQA_RE.search(text)
                if m:
                    codes = m.group(1)
                    self._noqa[i] = (
                        {c.strip().upper() for c in codes.split(",")}
                        if codes
                        else None  # blanket noqa
                    )
        codes = self._noqa.get(finding.line, "missing")
        if codes == "missing":
            return False
        return codes is None or finding.rule in codes

    def finding(self, rule: Rule, node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Finding(
            path=self.path,
            line=line,
            col=col,
            rule=rule.code,
            message=message,
            snippet=self.line_text(line),
        )


def dotted_name(node: ast.AST) -> str:
    """'a.b.c' for Name/Attribute chains, '' for anything dynamic."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def is_jit_call(node: ast.AST, ctx: ModuleContext) -> bool:
    return (
        isinstance(node, ast.Call)
        and ctx.canonical(dotted_name(node.func)) in JIT_NAMES
    )


def jit_decorator_keywords(
    dec: ast.AST, ctx: ModuleContext
) -> Optional[list[ast.keyword]]:
    """If ``dec`` makes the decorated function jitted, return the jit
    keywords (possibly []); else None.  Handles ``@jax.jit``,
    ``@jax.jit(...)`` and ``@partial(jax.jit, ...)``."""
    if ctx.canonical(dotted_name(dec)) in JIT_NAMES:
        return []
    if isinstance(dec, ast.Call):
        fn = ctx.canonical(dotted_name(dec.func))
        if fn in JIT_NAMES:
            return list(dec.keywords)
        if fn in PARTIAL_NAMES and dec.args and (
            ctx.canonical(dotted_name(dec.args[0])) in JIT_NAMES
        ):
            return list(dec.keywords)
    return None


def donate_positions(keywords: Iterable[ast.keyword]) -> tuple[int, ...]:
    for kw in keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                out = []
                for el in v.elts:
                    if isinstance(el, ast.Constant) and isinstance(el.value, int):
                        out.append(el.value)
                return tuple(out)
    return ()


# ---------------------------------------------------------------- pre-scan ----


def _prescan(ctx: ModuleContext) -> None:
    """One walk collecting imports and the jit registry (shared by all
    rules) before the main pass."""
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                ctx.imports[alias.asname or alias.name.split(".")[0]] = (
                    alias.name
                )
        elif isinstance(node, ast.ImportFrom):
            if node.module and node.level == 0:
                for alias in node.names:
                    ctx.imports[alias.asname or alias.name] = (
                        f"{node.module}.{alias.name}"
                    )
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                kws = jit_decorator_keywords(dec, ctx)
                if kws is not None:
                    ctx.jit.jitted_fns.add(node.name)
                    pos = donate_positions(kws)
                    if pos:
                        ctx.jit.donated[node.name] = pos
        elif isinstance(node, ast.Assign):
            if is_jit_call(node.value, ctx):
                call = node.value
                # the wrapped callable is jitted by name: jax.jit(f),
                # jax.jit(self._impl)
                if call.args:
                    wrapped = dotted_name(call.args[0])
                    if wrapped:
                        ctx.jit.jitted_fns.add(wrapped.rsplit(".", 1)[-1])
                pos = donate_positions(call.keywords)
                if pos:
                    for tgt in node.targets:
                        name = dotted_name(tgt)
                        if name:
                            ctx.jit.donated[name] = pos
        elif isinstance(node, ast.Call) and is_jit_call(node, ctx):
            if node.args:
                wrapped = dotted_name(node.args[0])
                if wrapped:
                    ctx.jit.jitted_fns.add(wrapped.rsplit(".", 1)[-1])


# ------------------------------------------------------------- main walker ----


class _Walker:
    """Single visitor pass: maintains scope stacks on the ctx, links
    parents (``node._dt_parent``), and dispatches each node to the rules
    interested in its type."""

    def __init__(self, ctx: ModuleContext, rules: Sequence[Rule]):
        self.ctx = ctx
        self.findings: list[Finding] = []
        self._dispatch: dict[type, list[Rule]] = {}
        for rule in rules:
            for t in rule.interests:
                self._dispatch.setdefault(t, []).append(rule)

    def walk(self) -> list[Finding]:
        self._visit(self.ctx.tree, None)
        return self.findings

    def _visit(self, node: ast.AST, parent: Optional[ast.AST]) -> None:
        node._dt_parent = parent  # type: ignore[attr-defined]
        ctx = self.ctx
        for rule in self._dispatch.get(type(node), ()):
            self.findings.extend(rule.visit(node, ctx))

        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            ctx.func_stack.append(node)
            outer_loops, ctx.loop_depth = ctx.loop_depth, 0
            for child in ast.iter_child_nodes(node):
                self._visit(child, node)
            ctx.loop_depth = outer_loops
            ctx.func_stack.pop()
        elif isinstance(node, (ast.For, ast.While, ast.AsyncFor)):
            ctx.loop_depth += 1
            for child in ast.iter_child_nodes(node):
                self._visit(child, node)
            ctx.loop_depth -= 1
        else:
            for child in ast.iter_child_nodes(node):
                self._visit(child, node)


# ---------------------------------------------------------------- baseline ----


class Baseline:
    """Committed grandfathered findings.  Entries carry a one-line
    ``justification``; matching is a (path, rule, content) multiset."""

    def __init__(self, entries: Optional[list[dict]] = None):
        self.entries: list[dict] = entries or []

    @classmethod
    def load(cls, path: Path) -> "Baseline":
        if not Path(path).is_file():
            return cls()
        data = json.loads(Path(path).read_text())
        return cls(list(data.get("entries", [])))

    def save(self, path: Path) -> None:
        entries = sorted(
            self.entries,
            key=lambda e: (e["path"], e["rule"], e.get("line", 0)),
        )
        Path(path).write_text(
            json.dumps({"version": 1, "entries": entries}, indent=2)
            + "\n"
        )

    def _counts(self) -> dict[tuple[str, str, str], int]:
        counts: dict[tuple[str, str, str], int] = {}
        for e in self.entries:
            key = (e["path"], e["rule"], e.get("content", ""))
            counts[key] = counts.get(key, 0) + 1
        return counts

    def filter(self, findings: Iterable[Finding]) -> list[Finding]:
        """Findings NOT covered by the baseline (stable-sorted)."""
        budget = self._counts()
        fresh: list[Finding] = []
        for f in sorted(findings):
            key = f.baseline_key
            if budget.get(key, 0) > 0:
                budget[key] -= 1
            else:
                fresh.append(f)
        return fresh

    @classmethod
    def from_findings(
        cls, findings: Iterable[Finding], previous: "Baseline"
    ) -> "Baseline":
        """Rebuild from current findings, carrying justifications over
        from the previous baseline where the key still matches."""
        just: dict[tuple[str, str, str], list[str]] = {}
        for e in previous.entries:
            key = (e["path"], e["rule"], e.get("content", ""))
            just.setdefault(key, []).append(e.get("justification", ""))
        entries = []
        for f in sorted(findings):
            carried = just.get(f.baseline_key)
            entries.append({
                "path": f.path,
                "rule": f.rule,
                "line": f.line,
                "content": f.snippet,
                "justification": (
                    carried.pop(0) if carried else "TODO: justify"
                ),
            })
        return cls(entries)


# ----------------------------------------------------------------- drivers ----

# Parse-once cache shared by the per-file pass (lint_file) and the
# interprocedural pass (project.ProjectIndex): running both over the same
# tree — as `dynamo-tpu lint --project` and the tier-1 gate do — pays the
# ast.parse cost once per file.  Keyed on (mtime_ns, size) so edited
# files (fixtures, tmp paths in tests) re-parse.
_PARSE_CACHE: dict[str, tuple[tuple[int, int], str, ast.Module]] = {}


def parse_module(path: Path) -> tuple[str, ast.Module]:
    """Return (source, tree) for ``path``, cached on content identity.
    Raises SyntaxError for unparsable files (callers decide whether that
    is a DT000 finding or a skip)."""
    p = str(Path(path).resolve())
    try:
        st = os.stat(p)
        key = (st.st_mtime_ns, st.st_size)
    except OSError:
        key = None
    hit = _PARSE_CACHE.get(p)
    if hit is not None and key is not None and hit[0] == key:
        return hit[1], hit[2]
    source = Path(p).read_text(encoding="utf-8", errors="replace")
    tree = ast.parse(source)
    if key is not None:
        _PARSE_CACHE[p] = (key, source, tree)
    return source, tree


def iter_python_files(paths: Sequence[Path]) -> Iterator[Path]:
    for p in paths:
        p = Path(p)
        if p.is_dir():
            yield from sorted(p.rglob("*.py"))
        elif p.suffix == ".py":
            yield p


def lint_file(
    path: Path,
    rules: Sequence[Rule],
    root: Optional[Path] = None,
) -> list[Finding]:
    """Parse ``path`` once, run every rule in one pass, apply noqa."""
    path = Path(path)
    rel = path
    if root is not None:
        try:
            rel = path.resolve().relative_to(Path(root).resolve())
        except ValueError:
            rel = path
    try:
        source, tree = parse_module(path)
    except SyntaxError as e:
        return [Finding(
            path=rel.as_posix(), line=e.lineno or 1, col=e.offset or 0,
            rule="DT000", message=f"syntax error: {e.msg}",
            snippet=(e.text or "").strip(),
        )]
    ctx = ModuleContext(rel.as_posix(), source, tree)
    _prescan(ctx)
    for rule in rules:
        rule.begin_module(ctx)
    findings = _Walker(ctx, rules).walk()
    return sorted(f for f in findings if not ctx.is_suppressed(f))


def lint_paths(
    paths: Sequence[Path],
    rules: Optional[Sequence[Rule]] = None,
    root: Optional[Path] = None,
) -> list[Finding]:
    rules = list(rules) if rules is not None else all_rules()
    out: list[Finding] = []
    for f in iter_python_files([Path(p) for p in paths]):
        out.extend(lint_file(f, rules, root=root))
    return sorted(out)
