"""Batched, jit-friendly token sampling.

One vectorised sampler covers greedy / temperature / top-k / top-p with
per-slot parameters, so heterogeneous requests share a single decode step.
Candidates are restricted to the top ``K_MAX`` logits (lax.top_k) — exact
for top_k <= K_MAX and a standard, tight approximation for pure top-p on a
peaked LLM distribution; avoids a full vocab sort every step on TPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

K_MAX = 64

__all__ = ["sample_tokens", "K_MAX"]


def sample_tokens(
    logits: jax.Array,        # [B, V] f32
    rng: jax.Array,           # PRNGKey
    temperature: jax.Array,   # [B] f32; <=0 → greedy
    top_k: jax.Array,         # [B] int32; 0 → disabled
    top_p: jax.Array,         # [B] f32; 1.0 → disabled
) -> jax.Array:
    """Returns sampled token ids [B]."""
    b, v = logits.shape
    k_max = min(K_MAX, v)
    # approx_max_k: per-tile reduction then exact top-k of the reduced set.
    # The true max always survives (it wins its tile), so greedy stays
    # exact; only deep-tail candidates can be missed.  Much faster than a
    # full lax.top_k over a 128k vocab on TPU.
    vals, idx = jax.lax.approx_max_k(logits, k_max, recall_target=0.95)

    greedy = temperature <= 0.0
    temp = jnp.where(greedy, 1.0, jnp.maximum(temperature, 1e-6))[:, None]
    scaled = vals / temp

    rank = jnp.arange(k_max, dtype=jnp.int32)[None, :]
    k = jnp.where(top_k <= 0, k_max, jnp.minimum(top_k, k_max))[:, None]
    keep = rank < k

    # top-p over the kept candidates: keep the smallest prefix whose
    # cumulative probability reaches top_p (first token always kept)
    probs = jax.nn.softmax(jnp.where(keep, scaled, -jnp.inf), axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = keep & ((cum - probs) < top_p[:, None])

    masked = jnp.where(keep, scaled, -jnp.inf)
    gumbel = jax.random.gumbel(rng, (b, k_max), dtype=jnp.float32)
    choice_sampled = jnp.argmax(masked + gumbel, axis=-1)
    choice = jnp.where(greedy, 0, choice_sampled)  # top_k output is sorted
    return jnp.take_along_axis(idx, choice[:, None], axis=-1)[:, 0]
