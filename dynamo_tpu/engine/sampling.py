"""Batched, jit-friendly token sampling with logprobs and penalties.

One vectorised sampler covers greedy / temperature / top-k / top-p with
per-slot parameters, so heterogeneous requests share a single decode step.
Candidates are restricted to the top ``k_cand`` logits — exact for
top_k <= k_cand and a standard, tight approximation for pure top-p on a
peaked LLM distribution; avoids a full vocab sort every step on TPU.  The
engine raises ``k_cand`` (power-of-two bucketed) and switches to exact
``lax.top_k`` whenever a request asks for top_k > K_MAX, so large top_k
never silently truncates (VERDICT r1 weak #3).

Frequency/presence penalties (OpenAI semantics over *generated* tokens,
vLLM-compatible) are applied by scatter-add into the logits buffer at the
generated token positions — no [B, V] side buffer is materialised.  The
host passes every generated occurrence (``pen_tokens``) plus a
first-occurrence mask (``pen_first``) so presence penalties apply once.

Logprobs are log-softmax over the *penalised* logits (temperature- and
top-k/p-independent, matching vLLM): the chosen token's logprob plus the
candidate set's ids/logprobs for top_logprobs slicing on host.

Reference parity: the reference delegates sampling to vLLM; the protocol
surface is lib/llm/src/protocols/openai/common.rs (penalties, logprobs).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

K_MAX = 64

__all__ = ["sample_tokens", "sample_full", "K_MAX"]


def _apply_penalties(
    logits: jax.Array,      # [B, V] f32
    pen_tokens: jax.Array,  # [B, T] int32, -1 padded — generated tokens (all occurrences)
    pen_first: jax.Array,   # [B, T] bool — True at each token's first occurrence
    freq_pen: jax.Array,    # [B] f32
    pres_pen: jax.Array,    # [B] f32
) -> jax.Array:
    b, t = pen_tokens.shape
    rows = jnp.broadcast_to(jnp.arange(b, dtype=jnp.int32)[:, None], (b, t))
    valid = pen_tokens >= 0
    # every occurrence subtracts freq_pen (count * penalty == per-occurrence add);
    # the first occurrence additionally subtracts pres_pen
    upd = -(freq_pen[:, None] * valid + pres_pen[:, None] * (valid & pen_first))
    tok = jnp.where(valid, pen_tokens, 0)
    return logits.at[rows.reshape(-1), tok.reshape(-1)].add(
        upd.reshape(-1), mode="drop"
    )


def _exact_top_k(logits: jax.Array, k: int) -> tuple[jax.Array, jax.Array]:
    """Exact top-k, backend-routed: the tile reduce only pays off where
    ``lax.top_k`` lowers to a full bitonic sort over V (TPU) — CPU's
    top_k is already selection-based and the tiling measures ~5x SLOWER
    there (benchmarks/probe_kernels.py topk)."""
    if jax.default_backend() != "tpu":
        return jax.lax.top_k(logits, k)
    return _exact_top_k_tiled(logits, k)


def _exact_top_k_tiled(logits: jax.Array, k: int) -> tuple[jax.Array, jax.Array]:
    """Exact top-k via per-tile reduce: top-k of each vocab tile, then
    top-k of the [B, nt*k] survivors.  Any global top-k element ranks
    <= k inside its own tile, so the result is exact — but the big sort
    over V (how XLA lowers ``lax.top_k`` on TPU) shrinks to nt parallel
    sorts of V/nt plus one sort of nt*k.  Tie-breaking matches
    ``lax.top_k`` (lowest index first): survivors are ordered by
    (tile, in-tile rank), which for equal values is index order.

    This is the exact-sampling path a single seeded / top_k>K_MAX
    request switches the whole batch onto (VERDICT r3 weak #7) — the
    tile reduce bounds that batch-wide cost."""
    b, v = logits.shape
    nt = 1
    while nt < 32 and v % (nt * 2) == 0 and v // (nt * 2) >= 4 * k:
        nt *= 2
    if nt == 1:
        return jax.lax.top_k(logits, k)
    tv = v // nt
    tvals, tidx = jax.lax.top_k(logits.reshape(b, nt, tv), k)  # [B, nt, k]
    tidx = tidx + (jnp.arange(nt, dtype=tidx.dtype) * tv)[None, :, None]
    vals, sel = jax.lax.top_k(tvals.reshape(b, nt * k), k)
    idx = jnp.take_along_axis(tidx.reshape(b, nt * k), sel, axis=-1)
    return vals, idx


def sample_full(
    logits: jax.Array,        # [B, V] f32
    rng: jax.Array,           # PRNGKey
    temperature: jax.Array,   # [B] f32; <=0 → greedy
    top_k: jax.Array,         # [B] int32; 0 → disabled
    top_p: jax.Array,         # [B] f32; 1.0 → disabled
    pen_tokens: jax.Array | None = None,  # [B, T] int32 (-1 pad)
    pen_first: jax.Array | None = None,   # [B, T] bool
    freq_pen: jax.Array | None = None,    # [B] f32
    pres_pen: jax.Array | None = None,    # [B] f32
    bias_tokens: jax.Array | None = None,  # [B, Nb] int32 (-1 pad)
    bias_vals: jax.Array | None = None,    # [B, Nb] f32
    min_p: jax.Array | None = None,        # [B] f32; 0 → disabled
    seeds: jax.Array | None = None,        # [B] int32 per-request seeds
    seed_rows: jax.Array | None = None,    # [B] bool — row uses its seed
    seed_steps: jax.Array | None = None,   # [B] int32 fold index (position)
    *,
    k_cand: int = K_MAX,
    exact: bool = False,
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Returns (sampled [B], chosen_logprob [B], cand_ids [B, k_cand],
    cand_logprobs [B, k_cand]).  Candidates are sorted descending, so the
    host slices the first ``top_logprobs`` entries per request."""
    b, v = logits.shape
    k_cand = min(k_cand, v)

    if bias_tokens is not None:
        # OpenAI logit_bias: sparse per-request additive bias, scatter-added
        # BEFORE candidate selection so a +100 bias can promote any token
        rows = jnp.broadcast_to(
            jnp.arange(b, dtype=jnp.int32)[:, None], bias_tokens.shape
        )
        valid = bias_tokens >= 0
        tok = jnp.where(valid, bias_tokens, 0)
        logits = logits.at[rows.reshape(-1), tok.reshape(-1)].add(
            jnp.where(valid, bias_vals, 0.0).reshape(-1), mode="drop"
        )
    if pen_tokens is not None:
        logits = _apply_penalties(logits, pen_tokens, pen_first, freq_pen, pres_pen)

    if exact:
        vals, idx = _exact_top_k(logits, k_cand)
    else:
        # approx_max_k: per-tile reduction then exact top-k of the reduced
        # set.  The true max always survives (it wins its tile), so greedy
        # stays exact; only deep-tail candidates can be missed.
        vals, idx = jax.lax.approx_max_k(logits, k_cand, recall_target=0.95)

    # logprobs over the full (penalised) vocab distribution
    log_z = jax.scipy.special.logsumexp(logits, axis=-1)  # [B]
    cand_lps = vals - log_z[:, None]

    greedy = temperature <= 0.0
    temp = jnp.where(greedy, 1.0, jnp.maximum(temperature, 1e-6))[:, None]
    scaled = vals / temp

    rank = jnp.arange(k_cand, dtype=jnp.int32)[None, :]
    k = jnp.where(top_k <= 0, k_cand, jnp.minimum(top_k, k_cand))[:, None]
    keep_base = rank < k  # the top-k mask, before top-p/min-p filtering

    # top-p over the kept candidates: keep the smallest prefix whose
    # cumulative probability reaches top_p (first token always kept)
    probs = jax.nn.softmax(jnp.where(keep_base, scaled, -jnp.inf), axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = keep_base & ((cum - probs) < top_p[:, None])
    if min_p is not None:
        # min-p (vLLM extension, ref protocols/common.rs:293): drop
        # candidates whose probability is below min_p * max_prob.  The
        # first (max) candidate always survives.
        keep = keep & (probs >= min_p[:, None] * probs[:, :1])

    if seeds is not None:
        # seeded rows need a fully batch-independent candidate policy:
        # the engine forces exact top-k whenever seeds are present, and a
        # seeded row's ENTIRE pipeline (softmax normalization, top-p
        # cutoff, min-p floor) runs over the true top-K_MAX — so a
        # companion widening k_cand cannot shift the kept set.  Effective
        # top_k for a seeded request therefore caps at K_MAX (documented
        # in docs/guides/serve.md).
        kb = keep_base & (rank < min(K_MAX, k_cand))
        probs_s = jax.nn.softmax(jnp.where(kb, scaled, -jnp.inf), axis=-1)
        cum_s = jnp.cumsum(probs_s, axis=-1)
        keep_s = kb & ((cum_s - probs_s) < top_p[:, None])
        if min_p is not None:
            keep_s = keep_s & (probs_s >= min_p[:, None] * probs_s[:, :1])
        keep = jnp.where(seed_rows[:, None], keep_s, keep)

    masked = jnp.where(keep, scaled, -jnp.inf)
    gumbel = jax.random.gumbel(rng, (b, k_cand), dtype=jnp.float32)
    if seeds is not None:
        # per-request determinism (OpenAI `seed`): a seeded row's noise is
        # a pure function of (seed, absolute position, TOKEN ID) — keying
        # by token id (not candidate rank) keeps the stream identical
        # across runs, burst boundaries, and batch compositions even when
        # a companion request widens k_cand or flips exact top-k (the
        # overlapping candidates keep identical scores either way)
        def row_noise(seed, step, token_ids):
            key = jax.random.fold_in(jax.random.PRNGKey(seed), step)

            def one(tid):
                return jax.random.gumbel(jax.random.fold_in(key, tid), (),
                                         dtype=jnp.float32)

            return jax.vmap(one)(token_ids)

        g_row = jax.vmap(row_noise)(seeds, seed_steps, idx)
        gumbel = jnp.where(seed_rows[:, None], g_row, gumbel)
    choice_sampled = jnp.argmax(masked + gumbel, axis=-1)
    choice = jnp.where(greedy, 0, choice_sampled)  # top_k output is sorted
    sampled = jnp.take_along_axis(idx, choice[:, None], axis=-1)[:, 0]
    chosen_lp = jnp.take_along_axis(cand_lps, choice[:, None], axis=-1)[:, 0]
    return sampled, chosen_lp, idx, cand_lps


def sample_tokens(
    logits: jax.Array,
    rng: jax.Array,
    temperature: jax.Array,
    top_k: jax.Array,
    top_p: jax.Array,
) -> jax.Array:
    """Sampled token ids [B] — the lean entry point (no logprobs/penalties)."""
    return sample_full(logits, rng, temperature, top_k, top_p)[0]
