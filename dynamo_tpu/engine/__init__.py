"""The in-process JAX engine: continuous batching over paged KV.

The reference orchestrates external engines (vLLM/SGLang, SURVEY.md §2.4);
here the engine is ours: a single jitted unified step (prefill & decode
share one forward), static shapes (fixed decode batch, bucketed prefill
lengths), a block manager with prefix reuse, and an asyncio front door that
plugs into the runtime's AsyncEngine pipeline.
"""

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.core import EngineCore
from dynamo_tpu.engine.async_engine import AsyncLLMEngine

__all__ = ["EngineConfig", "EngineCore", "AsyncLLMEngine"]
