"""Draft-model speculative decoding — the proposer half.

A small draft model (same tokenizer/vocab as the target) keeps its own
paged KV cache and proposes ``k`` greedy continuations per sequence in
ONE jitted dispatch; the target engine verifies them with its existing
rejection-sampled verify pass (engine/core.py:_spec_impl).  Greedy
point-mass proposals keep the verify rule exact at any temperature, and
seeded streams remain bit-identical with speculation on or off — the
draft only changes WHICH tokens get proposed, never how emitted tokens
are sampled.

TPU shape: the proposer dispatch ingests each row's not-yet-seen tokens
(one S=U forward over the paged draft cache, pow2-bucketed U) and then
runs k-1 single-token steps under ``lax.scan`` — all on device, one
dispatch per engine spec step.  The draft lags the target by exactly the
tokens emitted since its last dispatch, so in steady spec-mode operation
U stays ≤ k+1; a freshly admitted row's first dispatch ingests its whole
prompt (chunked through the same buckets).

Reference parity: the reference inherits draft/eagle speculative modes
from its engines (vLLM); SURVEY §2.4.  The n-gram prompt-lookup proposer
(engine/spec.py) remains the zero-cost default; the draft engages when
the engine is built with one.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["DraftProposer"]

_MAX_INGEST_BUCKET = 512  # longest single ingest dispatch (prompt chunks)


class DraftProposer:
    """Owns the draft model's paged cache + per-slot sync state."""

    def __init__(self, model, params, config, num_blocks: Optional[int] = None):
        self.model = model
        self.params = params
        self.config = config
        self.block_size = config.block_size
        nb = num_blocks or config.num_blocks
        # the draft cache follows the engine's cache_dtype: on HBM-tight
        # deployments (8B target + draft on one 16GiB chip) the int8
        # draft cache is part of what makes the pair fit — quantization
        # error only shifts PROPOSALS; the target's verification stays
        # exact either way
        self.cache = model.init_kv_cache(
            nb, config.block_size, config.cache_dtype)
        self._free = list(range(nb))
        self._blocks: dict[int, list[int]] = {}   # slot -> draft block ids
        self._synced: dict[int, int] = {}         # slot -> tokens ingested
        self._fn = jax.jit(self._impl, donate_argnums=(1,),
                           static_argnames=("k",))
        self.dispatches = 0

    # ------------------------------------------------------------- lifecycle
    def release(self, slot: int) -> None:
        """Return a finished/aborted slot's draft blocks to the pool."""
        self._free.extend(self._blocks.pop(slot, ()))
        self._synced.pop(slot, None)

    # ------------------------------------------------------------- device fn
    def _impl(self, params, cache, tokens, positions, block_tables,
              seq_lens, slot_idx, last_idx, active, *, k):
        """Ingest U tokens per row, then draft k greedy tokens.

        tokens/positions/slot_idx: [B, U] (-1-padded slots drop writes);
        seq_lens: [B] context length AFTER ingest; last_idx: [B] index of
        each row's last real ingest token; active: [B] bool.
        Returns (proposals [B, k] int32, cache).
        """
        model, bs = self.model, self.block_size
        b = tokens.shape[0]
        hidden, cache = model.forward(
            params, tokens, positions, cache, block_tables, seq_lens,
            slot_idx,
        )
        h_last = hidden[jnp.arange(b), last_idx]
        tok = jnp.argmax(
            model.compute_logits(params, h_last), axis=-1
        ).astype(jnp.int32)
        # position of the first drafted token = the row's context length
        pos = seq_lens
        m = block_tables.shape[1]

        def step(carry, _):
            cache, tok, pos, lens = carry
            blk = jnp.minimum(pos // bs, m - 1)
            base = jnp.take_along_axis(block_tables, blk[:, None], axis=1)[:, 0]
            slot = jnp.where(active, base * bs + pos % bs, -1)
            hidden, cache = model.forward(
                params, tok[:, None], pos[:, None], cache, block_tables,
                lens + 1, slot[:, None],
            )
            nxt = jnp.argmax(
                model.compute_logits(params, hidden[:, 0]), axis=-1
            ).astype(jnp.int32)
            return (cache, nxt, pos + 1, lens + 1), tok

        (cache, tok, _, _), drafted = jax.lax.scan(
            step, (cache, tok, pos, seq_lens), None, length=k - 1
        ) if k > 1 else ((cache, tok, pos, seq_lens), jnp.zeros((0, b), jnp.int32))
        props = jnp.concatenate([drafted, tok[None]], axis=0)  # [k, B]
        return props.T, cache

    # ---------------------------------------------------------------- propose
    def _grow(self, slot: int, want_tokens: int) -> bool:
        """Ensure the slot's draft block table covers ``want_tokens``.
        All-or-nothing: a row that cannot fully grow takes NOTHING —
        partial grabs would strand pool blocks on rows that can never
        draft, starving every other row until the hoarders finish."""
        ids = self._blocks.setdefault(slot, [])
        need = (max(want_tokens, 1) - 1) // self.block_size + 1
        if need - len(ids) > len(self._free):
            return False
        while len(ids) < need:
            ids.append(self._free.pop())
        return True

    def _dispatch(self, entries, k: int, draft_active: bool) -> np.ndarray:
        """One jitted draft dispatch over ``entries`` = [(req, start, n)]
        rows placed AT THEIR SLOT in a batch padded to max_batch_size —
        fixed shapes, so the executable count is O(log) in the ingest
        bucket, never per-live-batch-size (the churn the target engine
        pads against).  The block table is sliced to the live context
        (pow2 of the widest row) like the verify path.  Returns the
        [B, k] proposals (pad rows garbage — caller indexes by slot)."""
        b = self.config.max_batch_size
        u = 1 << max(0, (max(n for _, _, n in entries) - 1).bit_length())
        m = 1 << max(0, (max(len(self._blocks[req.slot])
                             for req, _, _ in entries) - 1).bit_length())
        tokens = np.zeros((b, u), np.int32)
        positions = np.zeros((b, u), np.int32)
        slot_idx = np.full((b, u), -1, np.int32)
        bt = np.zeros((b, m), np.int32)
        seq_lens = np.zeros(b, np.int32)
        last_idx = np.zeros(b, np.int32)
        active = np.zeros(b, bool)
        for req, start, n in entries:
            i = req.slot
            toks = req.seq.tokens[start:start + n]
            ids = np.asarray(self._blocks[i], np.int32)
            tokens[i, :n] = toks
            positions[i, :n] = np.arange(start, start + n, dtype=np.int32)
            blk = positions[i, :n] // self.block_size
            slot_idx[i, :n] = (ids[blk] * self.block_size
                               + positions[i, :n] % self.block_size)
            bt[i, :len(ids)] = ids
            seq_lens[i] = start + n
            last_idx[i] = n - 1
            active[i] = draft_active
            self._synced[i] = start + n
        # ONE batched host->device upload (engine/core.py:_upload_dispatch
        # convention): per-array jnp.asarray would issue seven transfer
        # round trips, and per-transfer latency is the cost that matters
        # on a remote-attached chip
        up = jax.device_put(
            (tokens, positions, bt, seq_lens, slot_idx, last_idx, active)
        )
        props, self.cache = self._fn(self.params, self.cache, *up, k=k)
        self.dispatches += 1
        return np.asarray(props)

    def propose(self, reqs, k: int, max_blocks_per_seq: int) -> dict[int, list[int]]:
        """Draft up to ``k`` tokens for each RUNNING request.  Returns
        {slot: proposal tokens}; a row the draft cannot serve this round
        (no free blocks / table overflow) is simply absent — the caller
        falls back to the n-gram proposer for it.

        Rows far behind (fresh long prompts) catch up via at most ONE
        batched ingest-only dispatch per call (k=1, proposals discarded,
        all behind rows in one padded batch) and are skipped for
        proposals until caught up — a 32k prompt costs one extra
        dispatch per engine step for a few steps instead of stalling its
        batch-mates behind ~64 serial dispatches in one step.
        """
        rows = []
        behind = []
        for req in reqs:
            slot = req.slot
            total = req.seq.total_tokens
            if total + k > max_blocks_per_seq * self.block_size:
                continue
            if not self._grow(slot, total + k):
                continue
            if total - self._synced.get(slot, 0) > _MAX_INGEST_BUCKET:
                behind.append(req)
            else:
                rows.append(req)
        if behind:
            self._dispatch(
                [(req, self._synced.get(req.slot, 0), _MAX_INGEST_BUCKET)
                 for req in behind],
                k=1, draft_active=False,
            )
            # a row fully caught up by that chunk may draft this round
            rows.extend(
                req for req in behind
                if req.seq.total_tokens - self._synced[req.slot]
                <= _MAX_INGEST_BUCKET
            )
        if not rows:
            return {}
        entries = [
            (req, self._synced.get(req.slot, 0),
             req.seq.total_tokens - self._synced.get(req.slot, 0))
            for req in rows
        ]
        props = self._dispatch(entries, k=k, draft_active=True)
        # the drafted tokens' KV was written at positions seq_lens..+k-1;
        # the NEXT dispatch re-ingests the really-accepted tokens over
        # those slots, so sync state advances only by ingested tokens
        return {req.slot: [int(t) for t in props[req.slot, :k]]
                for req in rows}
