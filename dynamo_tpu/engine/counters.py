"""Process-global prefill-batching counters.

Same dependency-free idiom as ``dynamo_tpu/fault/counters.py``: the
engine layer records, the llm layer (http/metrics.py render) and the
benchmarks read — no import cycles.  The HTTP metrics endpoint exposes:

    dynamo_tpu_engine_prefill_dispatches_total     counter
    dynamo_tpu_engine_prefill_tokens_total         counter
    dynamo_tpu_engine_prefill_batch_occupancy      gauge (rows/dispatch)
    dynamo_tpu_engine_prefill_budget_utilization   gauge (used/offered)
    dynamo_tpu_engine_unified_dispatches_total     counter
    dynamo_tpu_engine_unified_decode_rows_total    counter
    dynamo_tpu_engine_unified_prefill_tokens_total counter
    dynamo_tpu_engine_unified_budget_utilization   gauge (used/offered)
    dynamo_tpu_engine_lookahead_bursts_total       counter
    dynamo_tpu_engine_lookahead_hits_total         counter
    dynamo_tpu_engine_lookahead_mispredicts_total  counter
    dynamo_tpu_engine_lookahead_commits_total      counter
    dynamo_tpu_engine_lookahead_flushes_total      counter
    dynamo_tpu_engine_lookahead_dispatch_depth     gauge (turns/device_get)

The ``unified_*`` family counts the mixed prefill+decode dispatches of
the unified token-budget scheduler (engine/core.py ``_run_unified``):
how many turns collapsed the legacy two-dispatch interleave into one,
how many decode rows and prefill tokens shared each flat axis, and how
full the offered axis budget ran.
"""

from __future__ import annotations

__all__ = ["PrefillCounters", "counters", "PersistCounters", "persist_counters",
           "KvStreamCounters", "kv_stream_counters",
           "KvShardCounters", "kv_shard_counters",
           "LookaheadCounters", "lookahead_counters"]


class PrefillCounters:
    def __init__(self) -> None:
        self.reset()

    def record(self, rows: int, tokens: int, budget: int = 0) -> None:
        """One prefill dispatch: ``rows`` sequences packed, ``tokens``
        prompt tokens computed.  ``budget`` is the token budget offered
        (0 for legacy one-request / seq-parallel dispatches — those don't
        count toward budget utilization)."""
        self.dispatches_total += 1
        self.rows_total += rows
        self.tokens_total += tokens
        if budget > 0:
            self.budget_offered_total += budget
            self.budget_used_total += tokens

    def record_unified(self, decode_rows: int, prefill_tokens: int,
                       budget: int) -> None:
        """One unified mixed dispatch: ``decode_rows`` 1-token decode
        rows plus ``prefill_tokens`` prompt tokens packed on one flat
        axis, under an offered budget of ``budget`` tokens."""
        self.unified_dispatches_total += 1
        self.unified_decode_rows_total += decode_rows
        self.unified_prefill_tokens_total += prefill_tokens
        self.unified_budget_offered_total += budget
        self.unified_budget_used_total += decode_rows + prefill_tokens

    @property
    def unified_budget_utilization(self) -> float:
        """(decode rows + prefill tokens) / budget offered over unified
        dispatches."""
        if not self.unified_budget_offered_total:
            return 0.0
        return (self.unified_budget_used_total
                / self.unified_budget_offered_total)

    @property
    def batch_occupancy(self) -> float:
        """Mean sequences per prefill dispatch (lifetime)."""
        if not self.dispatches_total:
            return 0.0
        return self.rows_total / self.dispatches_total

    @property
    def budget_utilization(self) -> float:
        """Tokens packed / budget offered over batched dispatches."""
        if not self.budget_offered_total:
            return 0.0
        return self.budget_used_total / self.budget_offered_total

    def reset(self) -> None:
        """Test isolation hook — the counters are process-global."""
        self.dispatches_total = 0
        self.rows_total = 0
        self.tokens_total = 0
        self.budget_offered_total = 0
        self.budget_used_total = 0
        self.unified_dispatches_total = 0
        self.unified_decode_rows_total = 0
        self.unified_prefill_tokens_total = 0
        self.unified_budget_offered_total = 0
        self.unified_budget_used_total = 0


counters = PrefillCounters()


class PersistCounters:
    """Persistent prefix-cache tier (llm/kv/persist.py) counters.

        dynamo_tpu_engine_persist_hits_total            counter (blocks)
        dynamo_tpu_engine_persist_misses_total          counter (lookups
                                                        that restored
                                                        nothing)
        dynamo_tpu_engine_persist_restored_tokens_total counter
        dynamo_tpu_engine_persist_spill_bytes_total     counter
        dynamo_tpu_engine_persist_resident_bytes        gauge

    The store records spill volume and residency; the engine's restore
    path records hits/misses/restored tokens at commit time, so a match
    that failed to land on device never counts as a hit.
    """

    def __init__(self) -> None:
        self.reset()

    def record_restore(self, blocks: int, tokens: int) -> None:
        self.hits_total += blocks
        self.restored_tokens_total += tokens

    def record_miss(self) -> None:
        self.misses_total += 1

    def record_spill(self, nbytes: int) -> None:
        self.spill_bytes_total += nbytes

    def set_resident(self, nbytes: int) -> None:
        self.resident_bytes = nbytes

    def reset(self) -> None:
        """Test isolation hook — the counters are process-global."""
        self.hits_total = 0
        self.misses_total = 0
        self.restored_tokens_total = 0
        self.spill_bytes_total = 0
        self.resident_bytes = 0


persist_counters = PersistCounters()


class KvStreamCounters:
    """Streamed KV handoff (llm/kv/stream.py) counters.

        dynamo_tpu_kv_stream_sessions_total     counter (STREAM_BEGINs sent)
        dynamo_tpu_kv_stream_layers_sent_total  counter (WRITE_LAYER frames)
        dynamo_tpu_kv_stream_bytes_total        counter (layer payload bytes)
        dynamo_tpu_kv_stream_fallbacks_total    counter (sessions that fell
                                                back to the whole-cache push)
        dynamo_tpu_kv_stream_overlap_ratio      gauge

    ``overlap_ratio`` is transfer seconds HIDDEN under prefill compute
    (frames sent while later chunks were still computing) over total
    streamed transfer seconds — 1.0 means the wire was entirely paid
    for by compute, 0.0 means the stream degenerated to the blocking
    schedule (e.g. single-chunk prefills).
    """

    def __init__(self) -> None:
        self.reset()

    def record_session(self) -> None:
        self.sessions_total += 1

    def record_layer(self, nbytes: int, seconds: float,
                     hidden: bool) -> None:
        """One WRITE_LAYER frame acked: ``hidden`` marks frames sent
        while the producer's prefill was still computing."""
        self.layers_sent_total += 1
        self.bytes_total += nbytes
        self.transfer_seconds_total += seconds
        if hidden:
            self.hidden_seconds_total += seconds

    def record_fallback(self) -> None:
        self.fallbacks_total += 1

    @property
    def overlap_ratio(self) -> float:
        if self.transfer_seconds_total <= 0:
            return 0.0
        return self.hidden_seconds_total / self.transfer_seconds_total

    def reset(self) -> None:
        """Test isolation hook — the counters are process-global."""
        self.sessions_total = 0
        self.layers_sent_total = 0
        self.bytes_total = 0
        self.fallbacks_total = 0
        self.transfer_seconds_total = 0.0
        self.hidden_seconds_total = 0.0


kv_stream_counters = KvStreamCounters()


class KvShardCounters:
    """Sharded control plane (llm/kv_router/shards/) counters.

        dynamo_tpu_kv_shard_scatters_total        counter (gather rounds)
        dynamo_tpu_kv_shard_gather_partial_total  counter (rounds where a
                                                  shard missed its deadline
                                                  or answered stale)
        dynamo_tpu_kv_shard_fanout_latency_ms     histogram (scatter issue
                                                  → last reply/deadline)
        dynamo_tpu_kv_shard_generation            gauge (current fence)
        dynamo_tpu_kv_shard_last_fan_out          gauge (shards in the
                                                  last scatter round)
        dynamo_tpu_kv_shard_index_blocks{shard=}  gauge (device blocks)
        dynamo_tpu_kv_shard_resident_keys{shard=} gauge (distinct keys,
                                                  both tiers)

    The fan-out histogram lives here (cumulative bucket counts over the
    fixed ladder below) rather than in http/metrics.py's Histogram so
    the router layer stays free of the HTTP module; the render side
    turns the buckets into Prometheus histogram lines.
    """

    FANOUT_BUCKETS_MS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                         25.0, 50.0, 100.0)

    def __init__(self) -> None:
        self.reset()

    def record_scatter(self, fanout_ms: float, fan_out: int = 0) -> None:
        """One scatter round completed (all replies in, or deadline)."""
        self.scatters_total += 1
        self.fanout_ms_sum += fanout_ms
        self.last_fan_out = fan_out
        for i, edge in enumerate(self.FANOUT_BUCKETS_MS):
            if fanout_ms <= edge:
                self.fanout_bucket_counts[i] += 1

    def record_partial_gather(self) -> None:
        self.gather_partial_total += 1

    def set_generation(self, generation: int) -> None:
        self.generation = generation

    def set_shard_size(self, shard_id: int, index_blocks: int,
                       resident_keys: int) -> None:
        self.index_blocks[shard_id] = index_blocks
        self.resident_keys[shard_id] = resident_keys

    @property
    def gather_partial_frac(self) -> float:
        if not self.scatters_total:
            return 0.0
        return self.gather_partial_total / self.scatters_total

    def reset(self) -> None:
        """Test isolation hook — the counters are process-global."""
        self.scatters_total = 0
        self.gather_partial_total = 0
        self.fanout_ms_sum = 0.0
        self.fanout_bucket_counts = [0] * len(self.FANOUT_BUCKETS_MS)
        self.last_fan_out = 0
        self.generation = 0
        self.index_blocks: dict[int, int] = {}
        self.resident_keys: dict[int, int] = {}


kv_shard_counters = KvShardCounters()


class LookaheadCounters:
    """Double-buffered dispatch (engine/core.py lookahead scheduler)
    counters.

        dynamo_tpu_engine_lookahead_bursts_total       counter (fused
                                                       multi-turn dispatches)
        dynamo_tpu_engine_lookahead_hits_total         counter (burst rows
                                                       whose predicted token
                                                       count held to the end)
        dynamo_tpu_engine_lookahead_mispredicts_total  counter (rows where a
                                                       stop fired mid-burst
                                                       and the tail was
                                                       discarded)
        dynamo_tpu_engine_lookahead_commits_total      counter (speculative
                                                       next-turn builds
                                                       committed as-is)
        dynamo_tpu_engine_lookahead_flushes_total      counter (speculative
                                                       builds discarded —
                                                       admission/finish
                                                       changed the plan)
        dynamo_tpu_engine_lookahead_dispatch_depth     gauge (device turns
                                                       folded per device_get,
                                                       last burst)

    A *burst* is one fused dispatch that runs ``depth`` unified turns
    on-device with a single trailing ``jax.device_get`` — the
    prediction being that every active decode row yields exactly one
    token per turn unless a stop fires.  ``hits``/``mispredicts``
    count rows, ``commits``/``flushes`` count speculative host-side
    prebuilds of the *next* turn's dispatch operands.
    """

    def __init__(self) -> None:
        self.reset()

    def record_burst(self, depth: int, hits: int, mispredicts: int) -> None:
        """One fused burst landed: ``depth`` device turns folded into
        one device_get; ``hits`` rows consumed every predicted token,
        ``mispredicts`` rows stopped mid-burst (tail discarded)."""
        self.bursts_total += 1
        self.hits_total += hits
        self.mispredicts_total += mispredicts
        self.dispatch_depth = depth

    def record_commit(self) -> None:
        self.commits_total += 1

    def record_flush(self) -> None:
        self.flushes_total += 1

    def reset(self) -> None:
        """Test isolation hook — the counters are process-global."""
        self.bursts_total = 0
        self.hits_total = 0
        self.mispredicts_total = 0
        self.commits_total = 0
        self.flushes_total = 0
        self.dispatch_depth = 0


lookahead_counters = LookaheadCounters()
