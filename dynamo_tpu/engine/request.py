"""Per-request engine state machine."""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Callable, Optional

from dynamo_tpu.engine.grammar import INIT_STATE
from dynamo_tpu.llm.protocols import (
    FinishReason,
    LLMEngineOutput,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.tokens import TokenBlockSequence


class RequestState(enum.Enum):
    WAITING = "waiting"    # queued, no slot yet
    PREFILL = "prefill"    # slot assigned, prompt not fully computed
    REMOTE_PREFILL = "remote_prefill"  # slot+blocks assigned; KV arrives from a prefill worker
    RUNNING = "running"    # decoding
    FINISHED = "finished"


@dataclass
class EngineRequest:
    request_id: str
    prompt: list[int]
    sampling: SamplingOptions = field(default_factory=SamplingOptions)
    stops: StopConditions = field(default_factory=StopConditions)
    # called from the engine thread with each LLMEngineOutput delta
    emit: Callable[[LLMEngineOutput], None] = lambda out: None

    # --- disaggregation flags (ref vllm patch remote_prefill.py:
    # RemotePrefillParams.is_remote_prefill / is_remote_decode) ---
    # decode side: blocks are allocated up front and the request stalls in
    # REMOTE_PREFILL until a prefill worker writes KV and notifies
    remote_prefill: bool = False
    # prefill side: stop after the prefill step + first sampled token, keep
    # blocks held (not released) until the worker has transferred them out
    remote_decode: bool = False
    # called on the engine thread right after blocks are allocated (decode
    # side uses this to learn the block ids to hand to the prefill worker)
    on_allocated: Optional[Callable[["EngineRequest"], None]] = None

    state: RequestState = RequestState.WAITING
    seq: Optional[TokenBlockSequence] = None  # prompt + generated tokens
    block_ids: list[int] = field(default_factory=list)
    cached_tokens: int = 0     # prefix-cache hit (KV already resident)
    computed_tokens: int = 0   # prompt tokens whose KV is computed
    # prompt tokens whose blocks were already offered to block_manager
    # .commit — the chunked-prefill watermark (each chunk commits only the
    # blocks it completed; re-offering every earlier block per chunk made
    # an L-block prompt pay O(L^2) commit calls)
    committed_upto: int = 0
    # prompt tokens [computed_tokens, wait_upto) live in blocks another
    # request is prefilling right now (joined via the reserved-block
    # registry): this request absorbs them as the owner commits instead of
    # recomputing, and takes over if the owner aborts
    wait_upto: int = 0
    # (seq_hash, block_id) reservations THIS request owns; unresolved ones
    # are dropped on finish so joiners can take over
    reserved_pairs: list = field(default_factory=list)
    generated: int = 0
    # JSON-mode grammar automaton state: (dfa_state, depth, bit-stack) —
    # advanced host-side per appended token, mirrored on device in-scan
    gstate: tuple = (INIT_STATE, 0, 0)
    slot: int = -1
    finish_reason: Optional[FinishReason] = None
    abort_requested: bool = False
    # dtspan trace context (trace_id, span_id) — the engine thread has
    # no ambient contextvar, so spans it records for this request pass
    # this pair as parent= explicitly (obs/tracing.py)
    trace: Optional[tuple] = None
    # queue-wait measurement: submit() stamps submitted_at
    # (perf_counter); _admit computes queue_wait_s at slot assignment
    # and the async engine surfaces it to the HTTP histogram
    submitted_at: float = 0.0
    queue_wait_s: Optional[float] = None

    @property
    def prompt_len(self) -> int:
        return len(self.prompt)

    @property
    def total_tokens(self) -> int:
        return self.seq.total_tokens if self.seq else self.prompt_len
