"""Prompt-lookup (n-gram) speculative decoding — the draft-model-free kind.

Decode emits one token per model pass; speculation verifies K proposed
tokens in ONE pass and keeps the longest correct prefix, so repetitive
continuations (code, extraction, quoting — exactly the long-output
serving workloads) emit several tokens per dispatch.  Proposals come from
the sequence itself: if the last N tokens already occurred earlier, the
tokens that followed that occurrence are likely to follow again
(vLLM's "prompt lookup decoding"; the reference gets this from its
engines' speculative modes).

TPU shape: the verify pass is the engine's existing unified S>1 forward
against the paged cache — proposed tokens scatter their KV and attend
causally, a SAMPLE at every position comes back (each with its own
noise), and the host accepts the matching prefix.  Rejected positions'
KV is simply overwritten when the real tokens reach those slots (slots
are position-derived).  Exactness: for a point-mass proposal,
sample-and-match IS the canonical rejection-sampling rule (accept w.p.
p(x); a mismatching sample is already the renormalised residual), so
every emitted token is distributed exactly as plain decoding at any
temperature; greedy rows reduce to argmax (bit-identical streams), and
seeded rows are bit-identical with speculation on or off because their
noise is a pure function of (seed, position, token id).

Engine wiring lives in engine/core.py (`spec_tokens`/`spec_ngram`
config); this module is the pure host-side proposer.
"""

from __future__ import annotations

__all__ = ["propose_ngram"]


def propose_ngram(tokens, ngram: int, k: int, min_ngram: int = 1) -> list[int]:
    """Propose up to ``k`` continuation tokens for ``tokens`` by n-gram
    lookup: find the most recent earlier occurrence of the longest suffix
    (length ``ngram`` down to ``min_ngram``) and return the tokens that
    followed it.  Returns [] when nothing matches.
    """
    import numpy as np

    n_total = len(tokens)
    if n_total < min_ngram + 1 or k <= 0:
        return []
    arr = np.asarray(tokens, dtype=np.int64)
    for n in range(min(ngram, n_total - 1), min_ngram - 1, -1):
        suffix = arr[n_total - n:]
        # vectorised match over all candidate starts (n is tiny, so this
        # is n boolean passes over the array — the hot decode loop calls
        # this per row per dispatch, a Python scan would be O(ctx) slices)
        n_cand = n_total - n  # exclude the suffix's own position
        ok = np.ones(n_cand, dtype=bool)
        for j in range(n):
            ok &= arr[j: n_cand + j] == suffix[j]
        hits = np.flatnonzero(ok)
        if hits.size == 0:
            continue
        # the most recent occurrence whose continuation fills all k slots
        # wins (overlapping repeats leave short tails on the nearest match
        # — an earlier one proposes more)
        full = hits[hits + n + k <= n_total]
        start = int(full[-1]) if full.size else int(hits[-1])
        cont = arr[start + n: start + n + k]
        if cont.size:
            return cont.tolist()
    return []
