"""EngineCore — the continuous-batching scheduler + executor.

One jitted *unified step* runs both phases (the model's forward handles any
[B, S] of new tokens against the paged cache):

  prefill:  B=1, S=bucketed prompt remainder (prefix-cache hits skipped)
  decode:   B=max_batch_size slots, S=1

All shapes are static: the decode batch is a fixed array of slots (inactive
rows masked via seq_len=0 / slot_idx=-1) and prefill lengths are padded to
power-of-two buckets — so XLA compiles a handful of executables total and
the hot loop never retraces.  The KV cache array is donated through the
step so XLA updates it in place.

Scheduling policy (reference analogue is inside vLLM; ours is explicit):
admit waiting requests into free slots, run at most one prefill step per
iteration (keeps decode ITL bounded), otherwise run one decode step for all
running slots.  Prefix-cache hits shorten prefill via the block manager
(lib/llm/src/kv/manager.rs:31 prepare_prefill_sequence analogue).

With ``unified_token_dispatch`` the prefill/decode alternation collapses:
a turn with work in both phases runs ONE token-budget ragged dispatch
(``_run_unified`` / ``_unified_fn``) — decode rows lead the flat axis as
1-token chunks, prefill spans pack the remainder — so the per-switch
device round-trip disappears (docs/engine_scheduling.md).

Thread-safety: everything here runs on the engine thread; submit()/abort()
are the only cross-thread entry points and only touch thread-safe queues.
"""

from __future__ import annotations

import collections
import concurrent.futures
import dataclasses
import logging
import os
import queue
import threading
import time
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.counters import counters as prefill_counters
from dynamo_tpu.engine.counters import lookahead_counters
from dynamo_tpu.engine.grammar import (
    INIT_STATE, JsonGrammar, compile_choice_vocab, compile_regex_vocab,
    compose_tables, device_tables, grammar_advance, grammar_mask,
)
from dynamo_tpu.engine.request import EngineRequest, RequestState
from dynamo_tpu.engine.sampling import K_MAX, sample_full
from dynamo_tpu.ops.block_copy import gather_blocks_padded, scatter_blocks_inplace
from dynamo_tpu.llm.kv.block_manager import KvBlockManager, NoFreeBlocks
from dynamo_tpu.llm.protocols import FinishReason, LLMEngineOutput
from dynamo_tpu.models.llama import LlamaModel
from dynamo_tpu.obs.perfmodel import perf_model
from dynamo_tpu.utils.mesh import AXIS_DATA
from dynamo_tpu.obs.timeline import step_timeline
from dynamo_tpu.tokens import TokenBlockSequence

log = logging.getLogger("dynamo_tpu.engine")

__all__ = ["EngineCore", "unified_step", "multi_decode_step",
           "ragged_prefill_step", "unified_token_step",
           "unified_burst_step"]


def unified_step(
    model, params, cache, tokens, positions, block_tables, seq_lens,
    slot_idx, last_idx, rng, temp, top_k, top_p, prefix_blocks=None,
    k_cand=K_MAX, exact=False, grammar=None, jrows=None, jstate=None,
    jdepth=None, jstack=None, min_p=None, bias_tokens=None, bias_vals=None,
    seeds=None, seed_rows=None,
):
    """THE jitted serving step: forward over the paged cache, gather each
    row's last hidden state, project to logits, sample.  Shared by the
    engine hot loop and the driver's compile checks (__graft_entry__.py).

    Returns ((sampled [B], logprob [B], cand_ids [B,C], cand_lps [B,C]),
    cache) — candidate arrays feed OpenAI top_logprobs."""
    hidden, cache = model.forward(
        params, tokens, positions, cache, block_tables, seq_lens, slot_idx,
        prefix_blocks=prefix_blocks,
    )
    b = tokens.shape[0]
    last_h = hidden[jnp.arange(b), last_idx]  # [B, Dm]
    logits = model.compute_logits(params, last_h)  # [B, V] f32
    if grammar is not None:
        # JSON mode: mask invalid-next-token logits (engine/grammar.py)
        logits = grammar_mask(logits, grammar, jrows, jstate, jdepth, jstack)
    out = sample_full(logits, rng, temp, top_k, top_p,
                      bias_tokens=bias_tokens, bias_vals=bias_vals,
                      min_p=min_p, seeds=seeds, seed_rows=seed_rows,
                      # fold on the sampled token's absolute position
                      seed_steps=(seq_lens if seeds is not None else None),
                      k_cand=k_cand, exact=exact)
    return out, cache


def multi_decode_step(
    model, params, cache, last_tokens, positions, block_tables, seq_lens,
    limits, rng, temp, top_k, top_p,
    pen_tokens=None, pen_first=None, pen_cursor=None, freq_pen=None,
    pres_pen=None, grammar=None, jrows=None, jstate=None, jdepth=None,
    jstack=None, min_p=None, bias_tokens=None, bias_vals=None,
    seeds=None, seed_rows=None,
    *, num_steps: int, block_size: int,
    k_cand: int = K_MAX, exact: bool = False, use_penalties: bool = False,
):
    """K decode iterations fully on device in one dispatch (multi-step
    scheduling): forward → sample → feed the token back, K times under one
    ``lax.scan``.  Amortises per-dispatch host/RPC overhead over K tokens —
    on remote-attached TPU the dispatch round-trip, not compute, dominates
    single-step ITL.

    ``limits[i]`` is the max total tokens sequence i has block space for
    (and may not exceed max_model_len): a position at/past its limit
    writes no KV (slot -1 → dropped) and the host discards its samples.
    Inactive rows have limits=0.

    With ``use_penalties`` (static) the generated-token buffer
    (``pen_tokens`` [B,T] -1-padded, ``pen_first`` first-occurrence mask,
    ``pen_cursor`` [B] next write index) rides the scan carry: each newly
    sampled token is appended on device so mid-burst repeats are penalised
    without a host round-trip.

    Returns ((sampled [K,B], logprob [K,B], cand_ids [K,B,C],
    cand_lps [K,B,C]), cache).
    """
    m = block_tables.shape[1]
    use_grammar = grammar is not None

    def one(carry, rng_k):
        gs = gd = gk = None
        if use_penalties and use_grammar:
            cache, toks, pos, lens, ptoks, pfirst, cur, gs, gd, gk = carry
        elif use_penalties:
            cache, toks, pos, lens, ptoks, pfirst, cur = carry
        elif use_grammar:
            cache, toks, pos, lens, gs, gd, gk = carry
        else:
            cache, toks, pos, lens = carry
        blk = jnp.minimum(pos // block_size, m - 1)
        base = jnp.take_along_axis(block_tables, blk[:, None], axis=1)[:, 0]
        slot = base * block_size + pos % block_size
        slot = jnp.where(pos < limits, slot, -1)
        hidden, cache = model.forward(
            params, toks[:, None], pos[:, None], cache, block_tables, lens,
            slot[:, None],
        )
        logits = model.compute_logits(params, hidden[:, 0])
        if use_grammar:
            logits = grammar_mask(logits, grammar, jrows, gs, gd, gk)
        sampled, lp, cids, clps = sample_full(
            logits, rng_k, temp, top_k, top_p,
            ptoks if use_penalties else None,
            pfirst if use_penalties else None,
            freq_pen if use_penalties else None,
            pres_pen if use_penalties else None,
            # bias/min_p/seeds are constant across the burst: closure
            # capture; the seed fold index is the in-scan position
            bias_tokens=bias_tokens, bias_vals=bias_vals, min_p=min_p,
            seeds=seeds, seed_rows=seed_rows,
            seed_steps=(pos + 1 if seeds is not None else None),
            k_cand=k_cand, exact=exact,
        )
        # clamp the context length at the limit: past it no KV was written,
        # and an unclamped length would walk the block table out of bounds
        new_lens = jnp.minimum(lens + 1, limits)
        ys = (sampled, lp, cids, clps)
        if use_grammar:
            gs, gd, gk = grammar_advance(grammar, jrows, gs, gd, gk, sampled)
        if use_penalties:
            b = sampled.shape[0]
            rows = jnp.arange(b, dtype=jnp.int32)
            seen = jnp.any(ptoks == sampled[:, None], axis=-1)
            t_cap = ptoks.shape[1]
            at = jnp.minimum(cur, t_cap - 1)
            ptoks = ptoks.at[rows, at].set(sampled)
            pfirst = pfirst.at[rows, at].set(~seen)
            cur = jnp.minimum(cur + 1, t_cap - 1)
        nxt = (cache, sampled, pos + 1, new_lens)
        if use_penalties:
            nxt = nxt + (ptoks, pfirst, cur)
        if use_grammar:
            nxt = nxt + (gs, gd, gk)
        return nxt, ys

    init = (cache, last_tokens, positions, seq_lens)
    if use_penalties:
        init = init + (pen_tokens, pen_first, pen_cursor)
    if use_grammar:
        init = init + (jstate, jdepth, jstack)
    carry, out = jax.lax.scan(one, init, jax.random.split(rng, num_steps))
    return out, carry[0]


def ragged_prefill_step(
    model, params, cache, tokens, positions, block_tables, seq_lens,
    slot_idx, seq_ids, seq_starts, row_offsets, last_idx, rng, temp, top_k,
    top_p, prefix_blocks=0, k_cand=K_MAX, exact=False, grammar=None,
    jrows=None, jstate=None, jdepth=None, jstack=None, min_p=None,
    bias_tokens=None, bias_vals=None, seeds=None, seed_rows=None,
):
    """Token-budget ragged prefill step: ONE forward over a flat packed
    token axis ([1, T]) holding several sequences' prefill chunks, then a
    per-SEQUENCE sample — ``last_idx`` [R] gathers each row's last fresh
    hidden state off the flat axis.  The host keeps only final-chunk rows'
    samples (mixed batches: some rows sample with grammar/logprobs/seeded
    RNG, mid-chunk rows discard).

    ``seed_steps`` is each row's absolute end position (``seq_lens``), so
    a seeded row's sampled token is bit-identical to the one the legacy
    single-request dispatch would draw.
    """
    hidden, cache = model.forward(
        params, tokens, positions, cache, block_tables, seq_lens, slot_idx,
        prefix_blocks=prefix_blocks,
        ragged=(seq_ids, seq_starts, row_offsets),
    )
    last_h = hidden[0, last_idx]  # [R, Dm] — flat-axis gather per sequence
    logits = model.compute_logits(params, last_h)  # [R, V] f32
    if grammar is not None:
        logits = grammar_mask(logits, grammar, jrows, jstate, jdepth, jstack)
    out = sample_full(logits, rng, temp, top_k, top_p,
                      bias_tokens=bias_tokens, bias_vals=bias_vals,
                      min_p=min_p, seeds=seeds, seed_rows=seed_rows,
                      seed_steps=(seq_lens if seeds is not None else None),
                      k_cand=k_cand, exact=exact)
    return out, cache


def unified_token_step(
    model, params, cache, tokens, positions, block_tables, seq_lens,
    slot_idx, seq_ids, seq_starts, row_offsets, last_idx, rng, temp, top_k,
    top_p, pen_tokens=None, pen_first=None, freq_pen=None, pres_pen=None,
    *, row_tokens=0, prefix_blocks=0, k_cand=K_MAX, exact=False,
    grammar=None, jrows=None, jstate=None, jdepth=None, jstack=None,
    min_p=None, bias_tokens=None, bias_vals=None, seeds=None,
    seed_rows=None,
):
    """Unified mixed prefill+decode step: ONE forward over a flat packed
    token axis whose first ``row_tokens`` slots hold DECODE rows (one
    fresh token each, written to the cache per row — their in-block
    offsets are arbitrary) and whose remainder holds block-aligned
    prefill chunk spans.  Decode rows are just 1-token chunks to the
    ragged attention: their ``start`` is the full cached context, the
    per-row prefix gather/DMA covers it, and the positionally-exact
    prefix mask handles the partially-filled tail block.

    Per-row sampling preserves the legacy paths' semantics: decode rows
    and final-chunk prefill rows sample (grammar masks, per-request
    seeds folded on the absolute position ``seq_lens``, penalties over
    the host-built generated-token buffers, logit bias, min_p,
    top_logprobs candidates); mid-chunk rows sample garbage the host
    discards.  Seeded/greedy rows are therefore bit-identical to the
    decode-burst and ragged-prefill dispatches they replace
    (tests/test_unified_dispatch.py pins this).
    """
    hidden, cache = model.forward(
        params, tokens, positions, cache, block_tables, seq_lens, slot_idx,
        prefix_blocks=prefix_blocks,
        ragged=(seq_ids, seq_starts, row_offsets),
        ragged_row_tokens=row_tokens,
    )
    last_h = hidden[0, last_idx]  # [R, Dm] — flat-axis gather per row
    logits = model.compute_logits(params, last_h)  # [R, V] f32
    if grammar is not None:
        logits = grammar_mask(logits, grammar, jrows, jstate, jdepth, jstack)
    out = sample_full(logits, rng, temp, top_k, top_p,
                      pen_tokens, pen_first, freq_pen, pres_pen,
                      bias_tokens=bias_tokens, bias_vals=bias_vals,
                      min_p=min_p, seeds=seeds, seed_rows=seed_rows,
                      seed_steps=(seq_lens if seeds is not None else None),
                      k_cand=k_cand, exact=exact)
    return out, cache


def unified_burst_step(
    model, params, cache, tokens, positions, block_tables, seq_lens,
    slot_idx, seq_ids, seq_starts, row_offsets, last_idx, limits, rng,
    temp, top_k, top_p, pen_tokens=None, pen_first=None, pen_cursor=None,
    freq_pen=None, pres_pen=None,
    *, num_steps: int, block_size: int, row_tokens: int = 0,
    prefix_blocks: int = 0, k_cand: int = K_MAX, exact: bool = False,
    use_penalties: bool = False, grammar=None, jrows=None, jstate=None,
    jdepth=None, jstack=None, min_p=None, bias_tokens=None, bias_vals=None,
    seeds=None, seed_rows=None,
):
    """Fused multi-turn unified dispatch (double-buffered dispatch): turn
    0 is exactly :func:`unified_token_step` (decode rows + prefill spans
    on one flat axis), then ``num_steps - 1`` further decode turns run
    on device under one ``lax.scan`` — :func:`multi_decode_step`'s body
    over the unified ROW axis, with turn 0's sampled tokens fed back.
    A burst of ``num_steps`` device turns therefore needs ONE
    ``jax.device_get`` at the end, generalising the pure-decode
    multi-step burst to mixed prefill+decode turns.

    Stop-condition handling stays host-side but is *deferred*: the scan
    keeps generating past a stop (the prediction is that no row stops
    mid-burst); the host discards the tail samples of a row whose stop
    fired (a lookahead mispredict).  KV written past a stop lands only
    in blocks the request still owns and never commits — released on
    finish, the same discard semantics ``multi_decode_step`` already
    has.  Prefill and padding rows are inert in the scan: ``limits`` is
    0 for them, so they write no KV, attend over zero context, and
    sample garbage the host discards.

    Sampled-token append runs on device too: grammar states advance and
    the penalty buffers (``pen_cursor`` is each row's next write index)
    absorb each turn's sample inside the dispatch, so grammar masks and
    repetition penalties see mid-burst tokens without a host round
    trip.  Seeded rows fold on the absolute position (turn 0:
    ``seq_lens``; scan: ``pos + 1``), so their streams are bit-identical
    to the single-turn dispatches the burst replaces
    (tests/test_lookahead_dispatch.py pins this).

    Returns ``((out0, outs), cache)`` — ``out0`` is turn 0's
    (sampled [R], logprob [R], cand_ids [R,C], cand_lps [R,C]) and
    ``outs`` stacks the scan turns' ([K-1,R], ...).
    """
    use_grammar = grammar is not None
    m = block_tables.shape[1]
    rng0, rng_scan = jax.random.split(rng)

    # ---- turn 0: the unified mixed step
    hidden, cache = model.forward(
        params, tokens, positions, cache, block_tables, seq_lens, slot_idx,
        prefix_blocks=prefix_blocks,
        ragged=(seq_ids, seq_starts, row_offsets),
        ragged_row_tokens=row_tokens,
    )
    last_h = hidden[0, last_idx]  # [R, Dm] — flat-axis gather per row
    logits = model.compute_logits(params, last_h)  # [R, V] f32
    if use_grammar:
        logits = grammar_mask(logits, grammar, jrows, jstate, jdepth, jstack)
    out0 = sample_full(
        logits, rng0, temp, top_k, top_p,
        pen_tokens if use_penalties else None,
        pen_first if use_penalties else None,
        freq_pen if use_penalties else None,
        pres_pen if use_penalties else None,
        bias_tokens=bias_tokens, bias_vals=bias_vals, min_p=min_p,
        seeds=seeds, seed_rows=seed_rows,
        seed_steps=(seq_lens if seeds is not None else None),
        k_cand=k_cand, exact=exact)
    sampled0 = out0[0]

    # ---- on-device append of turn 0's samples into the carried state
    gs = gd = gk = None
    if use_grammar:
        gs, gd, gk = grammar_advance(
            grammar, jrows, jstate, jdepth, jstack, sampled0)
    ptoks, pfirst, cur = pen_tokens, pen_first, pen_cursor
    if use_penalties:
        rows = jnp.arange(sampled0.shape[0], dtype=jnp.int32)
        seen = jnp.any(ptoks == sampled0[:, None], axis=-1)
        t_cap = ptoks.shape[1]
        at = jnp.minimum(cur, t_cap - 1)
        ptoks = ptoks.at[rows, at].set(sampled0)
        pfirst = pfirst.at[rows, at].set(~seen)
        cur = jnp.minimum(cur + 1, t_cap - 1)

    # ---- turns 1..num_steps-1: multi_decode_step's scan body over the
    # unified row axis (decode rows live, prefill/pad rows inert)
    def one(carry, rng_k):
        gs = gd = gk = None
        if use_penalties and use_grammar:
            cache, toks, pos, lens, ptoks, pfirst, cur, gs, gd, gk = carry
        elif use_penalties:
            cache, toks, pos, lens, ptoks, pfirst, cur = carry
        elif use_grammar:
            cache, toks, pos, lens, gs, gd, gk = carry
        else:
            cache, toks, pos, lens = carry
        blk = jnp.minimum(pos // block_size, m - 1)
        base = jnp.take_along_axis(block_tables, blk[:, None], axis=1)[:, 0]
        slot = base * block_size + pos % block_size
        slot = jnp.where(pos < limits, slot, -1)
        hidden, cache = model.forward(
            params, toks[:, None], pos[:, None], cache, block_tables, lens,
            slot[:, None],
        )
        logits = model.compute_logits(params, hidden[:, 0])
        if use_grammar:
            logits = grammar_mask(logits, grammar, jrows, gs, gd, gk)
        sampled, lp, cids, clps = sample_full(
            logits, rng_k, temp, top_k, top_p,
            ptoks if use_penalties else None,
            pfirst if use_penalties else None,
            freq_pen if use_penalties else None,
            pres_pen if use_penalties else None,
            bias_tokens=bias_tokens, bias_vals=bias_vals, min_p=min_p,
            seeds=seeds, seed_rows=seed_rows,
            seed_steps=(pos + 1 if seeds is not None else None),
            k_cand=k_cand, exact=exact,
        )
        new_lens = jnp.minimum(lens + 1, limits)
        ys = (sampled, lp, cids, clps)
        if use_grammar:
            gs, gd, gk = grammar_advance(grammar, jrows, gs, gd, gk, sampled)
        if use_penalties:
            rows = jnp.arange(sampled.shape[0], dtype=jnp.int32)
            seen = jnp.any(ptoks == sampled[:, None], axis=-1)
            t_cap = ptoks.shape[1]
            at = jnp.minimum(cur, t_cap - 1)
            ptoks = ptoks.at[rows, at].set(sampled)
            pfirst = pfirst.at[rows, at].set(~seen)
            cur = jnp.minimum(cur + 1, t_cap - 1)
        nxt = (cache, sampled, pos + 1, new_lens)
        if use_penalties:
            nxt = nxt + (ptoks, pfirst, cur)
        if use_grammar:
            nxt = nxt + (gs, gd, gk)
        return nxt, ys

    # init mirrors the follow-up decode turn the scan replaces: turn 0's
    # token sits at position seq_lens, the context now includes it
    # (clamped at the block limit — past it no KV was written)
    init = (cache, sampled0, seq_lens, jnp.minimum(seq_lens + 1, limits))
    if use_penalties:
        init = init + (ptoks, pfirst, cur)
    if use_grammar:
        init = init + (gs, gd, gk)
    carry, outs = jax.lax.scan(
        one, init, jax.random.split(rng_scan, num_steps - 1))
    return (out0, outs), carry[0]


class EngineCore:
    def __init__(
        self,
        model: LlamaModel,
        params,
        config: EngineConfig,
        mesh: Optional[jax.sharding.Mesh] = None,
        eos_token_ids: Optional[list[int]] = None,
        grammar: Optional[JsonGrammar] = None,
        draft: Optional[tuple] = None,
    ):
        self.model = model
        self.config = config
        self.mesh = mesh
        # draft-model speculation: (draft_model, draft_params) with the
        # same tokenizer/vocab as the target — proposals come from the
        # draft (engine/draft.py) instead of n-gram lookup; the verify
        # pass is unchanged (greedy point-mass proposals keep it exact)
        self.draft = None
        if draft is not None:
            if config.spec_tokens <= 0:
                # a silently-inactive draft would be a lie to the operator
                raise ValueError(
                    "a draft model requires spec_tokens > 0 "
                    "(--spec-tokens) to ever propose"
                )
            from dynamo_tpu.engine.draft import DraftProposer

            dmodel, dparams = draft
            if dmodel.config.vocab_size != model.config.vocab_size:
                raise ValueError(
                    "draft model must share the target's vocab "
                    f"({dmodel.config.vocab_size} != {model.config.vocab_size})"
                )
            self.draft = DraftProposer(
                dmodel, dparams, config,
                num_blocks=config.draft_num_blocks or None,
            )
        self.eos_token_ids = set(eos_token_ids or [])
        # JSON-mode grammar: compiled tables (host) + lazy device upload.
        # attach_grammar_tokenizer defers the ~1s vocab compile to the
        # first json_mode request instead of every engine start.
        self._grammar = grammar
        self._grammar_tok = None
        self._choice_tables: dict[tuple, object] = {}
        self._gdev_cache: dict[tuple, tuple] = {}
        self.block_manager = KvBlockManager(
            config.num_blocks,
            config.block_size,
            enable_prefix_reuse=config.enable_prefix_reuse,
        )
        cache_dtype = config.cache_dtype or model.config.dtype
        self.cache_quant = str(cache_dtype) == "int8"
        # host-RAM offload tier: device-evicted blocks stay restorable
        # (ref kv/reuse.rs + layer.rs copy streams; SURVEY §5 checkpoint row)
        self.host_pool = None
        self._pending_offload: list[tuple[int, int]] = []  # (device bid, seq_hash)
        if config.num_host_blocks > 0:
            if not config.enable_prefix_reuse:
                log.warning(
                    "num_host_blocks=%d ignored: host offload needs "
                    "enable_prefix_reuse=True (blocks are keyed by prefix hash)",
                    config.num_host_blocks,
                )
            else:
                from dynamo_tpu.llm.kv.host_pool import HostKvPool

                self.host_pool = HostKvPool(config.num_host_blocks)
                self.block_manager.offload_sink = (
                    lambda bid, seq_hash, parent: self._pending_offload.append((bid, seq_hash))
                )
                # async store: the engine thread only dispatches the
                # on-device gather (ordered before any overwrite of the
                # evicted ids); the device→host readback + memcpy runs on
                # this thread — the CUDA-copy-stream analogue, so a
                # request never pays another conversation's offload in
                # its own TTFT.  Bounded queue = HBM backpressure: a full
                # queue falls back to a synchronous store.
                self._offload_lock = threading.Lock()
                self._offload_closed = False
                # each queued entry pins an on-device gather snapshot in
                # HBM until the worker's device_get, so backpressure is
                # bounded by total queued BLOCKS (config budget), not
                # entry count — a large eviction burst falls back to the
                # synchronous store instead of pinning hundreds of MB
                self._offload_inflight_blocks = 0
                self._offload_q: queue.Queue = queue.Queue(maxsize=4)
                self._offload_thread = threading.Thread(
                    target=self._offload_worker, name="kv-offload", daemon=True
                )
                self._offload_thread.start()

        # persistent prefix-cache tier (llm/kv/persist.py): host-published
        # blocks spill to a content-addressed disk store; host-pool misses
        # on admission fall through to it, so warm prefixes survive worker
        # restarts and replicate across workers via the coordinator index
        self.persist_store = None
        self._persist_events: "collections.deque" = collections.deque()
        if config.kv_persist_dir:
            if self.host_pool is None:
                log.warning(
                    "kv_persist_dir=%s ignored: the persistent tier stages "
                    "through the host pool (set num_host_blocks > 0 and "
                    "keep enable_prefix_reuse on)", config.kv_persist_dir,
                )
            else:
                from dynamo_tpu.llm.kv.persist import PersistentKvStore

                self.persist_store = PersistentKvStore(
                    config.kv_persist_dir,
                    generation=self._persist_generation(model, cache_dtype),
                    max_bytes=config.kv_persist_max_bytes,
                    ttl_s=config.kv_persist_ttl_s,
                )
                resident = self.persist_store.resident_hashes()
                if resident:
                    # announce what a restart found on disk, so the router
                    # index learns this worker's persist tier once a
                    # publisher attaches (events drain on the engine
                    # thread each step)
                    from dynamo_tpu.llm.kv.events import TIER_PERSIST, KvStoredEvent

                    self._persist_events.append(
                        KvStoredEvent(block_hashes=resident, tier=TIER_PERSIST))

        cache = model.init_kv_cache(config.num_blocks, config.block_size, cache_dtype)
        self._cache_specs = None
        if mesh is not None:
            from jax.sharding import NamedSharding

            from dynamo_tpu.models.quant import align_specs, prune_specs

            params = jax.device_put(
                params,
                jax.tree.map(
                    lambda s: NamedSharding(mesh, s),
                    align_specs(params, prune_specs(
                        params, model.partition_specs(), mesh)),
                    is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec),
                ),
            )
            # cache sharding pruned the same way (a kv-head axis the mesh
            # doesn't divide replicates rather than failing device_put)
            self._cache_specs = prune_specs(
                cache, model.cache_spec(quant=self.cache_quant), mesh
            )
            cache = jax.device_put(cache, self._cache_sharding())
        self.params = params
        self.cache = cache

        self._rng = jax.random.PRNGKey(config.seed)
        self._step_fn = jax.jit(
            self._step_impl, donate_argnums=(1,),
            static_argnames=("prefix_blocks", "k_cand", "exact"),
        )
        self._multi_fn = jax.jit(
            self._multi_impl, donate_argnums=(1,),
            static_argnames=("num_steps", "k_cand", "exact", "use_penalties"),
        )
        self._spec_fn = jax.jit(
            self._spec_impl, donate_argnums=(1,),
            static_argnames=("k_cand", "exact"),
        )
        self._ragged_fn = jax.jit(
            self._ragged_impl, donate_argnums=(1,),
            static_argnames=("prefix_blocks", "k_cand", "exact"),
        )
        # the fifth donated serving impl: unified mixed prefill+decode
        # dispatch (decode rows + prefill spans on one flat token axis)
        self._unified_fn = jax.jit(
            self._unified_impl, donate_argnums=(1,),
            static_argnames=("row_tokens", "prefix_blocks", "k_cand",
                             "exact"),
        )
        # double-buffered dispatch: the fused multi-turn unified burst
        # (turn 0 = unified mixed step, then a multi-step decode scan
        # over the unified row axis — ONE device_get per burst)
        self._burst_fn = jax.jit(
            self._burst_impl, donate_argnums=(1,),
            static_argnames=("num_steps", "row_tokens", "prefix_blocks",
                             "k_cand", "exact", "use_penalties"),
        )
        # sequence-parallel long-prefill (ring attention over the "data"
        # axis): one dispatch computes the whole prompt with the sequence
        # sharded across the mesh — SURVEY §5 long-context path
        self._sp_size = 0
        if (
            mesh is not None
            and config.sp_prefill_threshold > 0
            and AXIS_DATA in mesh.axis_names
            and mesh.shape[AXIS_DATA] > 1
        ):
            if not hasattr(model, "forward_seq_parallel") or not getattr(
                    model, "supports_seq_parallel", True):
                # fail at construction, not mid-serving on the first long
                # prompt (Llama-family and absorbed-MLA DeepSeek have the
                # ring path; expanded-MLA and future families without one
                # land here — supports_seq_parallel lets a model veto SP
                # for specific configs even though the method exists)
                raise ValueError(
                    f"{type(model).__name__} does not support seq-parallel "
                    "prefill (this config); disable sp_prefill_threshold"
                )
            self._sp_size = mesh.shape[AXIS_DATA]
            self._sp_fn = jax.jit(
                self._sp_impl, static_argnames=("nb", "k_cand", "exact")
            )

        self.slots: list[Optional[EngineRequest]] = [None] * config.max_batch_size
        self.waiting: "queue.SimpleQueue[EngineRequest]" = queue.SimpleQueue()
        self._admitted: list[EngineRequest] = []  # waiting for a slot/blocks
        self._by_id: dict[str, EngineRequest] = {}
        self._abort_q: "queue.SimpleQueue[str]" = queue.SimpleQueue()
        # aborts that arrived before their request was even admitted
        self._pending_aborts: set[str] = set()
        self._lock = threading.Lock()
        # ops enqueued by other threads, run on the engine thread at the next
        # step boundary (KV scatter/gather, remote-prefill completion, ...)
        self._ops: "queue.SimpleQueue[tuple[Callable, concurrent.futures.Future]]" = (
            queue.SimpleQueue()
        )
        # prefill-side held blocks: finished remote-decode prefills whose
        # blocks must survive until the transfer out completes
        self._held: dict[str, list[int]] = {}
        # streamed-handoff commit hooks (llm/kv/stream.py): per request,
        # fn(committed_block_ids, done) fired on the engine thread at each
        # chunk boundary (jitted scan bodies preclude per-layer callbacks —
        # chunk granularity is the documented fallback, docs/kv_streaming.md)
        # and once more with done=True when the prefill completes
        self._commit_hooks: dict[str, Callable[[list[int], bool], None]] = {}
        # perf counters
        self.steps = 0
        self.prefill_steps = 0
        # prefill batching: dispatches (any path), sequences packed over
        # them, and the token budget offered/used by batched dispatches
        self.prefill_dispatches = 0
        self.prefill_rows_dispatched = 0
        self.prefill_budget_offered = 0
        self.prefill_budget_used = 0
        self.decode_steps = 0
        self.tokens_generated = 0
        self.prompt_tokens_computed = 0  # actual prefill work (dedupe-aware)
        self.sp_prefills = 0             # seq-parallel long-prefill dispatches
        self.spec_steps = 0              # speculative verify dispatches
        self.spec_proposed = 0           # tokens proposed by n-gram lookup
        self.spec_accepted = 0           # proposals the model agreed with
        # unified mixed prefill+decode dispatch (unified_token_dispatch)
        self.unified_dispatches = 0      # mixed dispatches issued
        self.unified_decode_rows = 0     # decode rows packed over them
        self.unified_prefill_tokens = 0  # prefill tokens packed over them
        self.unified_budget_offered = 0  # flat-axis budget offered
        self.unified_budget_used = 0     # decode rows + prefill tokens
        # double-buffered dispatch (lookahead_dispatch): fused bursts,
        # per-row prediction outcomes, and the speculative next-turn
        # prebuild commit/flush protocol
        self.lookahead_bursts = 0        # fused multi-turn dispatches
        self.lookahead_hits = 0          # rows that consumed every sample
        self.lookahead_mispredicts = 0   # rows whose stop fired mid-burst
        self.lookahead_commits = 0       # speculative prebuilds committed
        self.lookahead_flushes = 0       # speculative prebuilds discarded
        self.lookahead_depth = 0         # device turns per device_get (last)
        self.device_gets = 0             # step-loop jax.device_get calls
        # speculative next-turn dispatch operands, built during the
        # overlap window while the device computes (committed next turn
        # if the predicted plan held, flushed otherwise)
        self._spec_next: Optional[dict] = None
        # cached _unified_penalties host buffers (invalidated on
        # admission/finish; incremental append between turns)
        self._pen_cache: Optional[dict] = None
        self._last_was_prefill = False
        # --profile-dir hook: one jax.profiler capture over the first
        # config.profile_steps device steps, keyed by starting step id
        self._profile_active = False
        self._profile_done = False
        self._profile_from_step = 0

    # ----------------------------------------------------------- step kernel
    def _step_impl(self, params, cache, *args, prefix_blocks=None,
                   k_cand=K_MAX, exact=False, grammar=None, jrows=None,
                   jstate=None, jdepth=None, jstack=None, min_p=None,
                   bias_tokens=None, bias_vals=None, seeds=None,
                   seed_rows=None):
        return unified_step(self.model, params, cache, *args,
                            prefix_blocks=prefix_blocks, k_cand=k_cand,
                            exact=exact, grammar=grammar, jrows=jrows,
                            jstate=jstate, jdepth=jdepth, jstack=jstack,
                            min_p=min_p, bias_tokens=bias_tokens,
                            bias_vals=bias_vals, seeds=seeds,
                            seed_rows=seed_rows)

    def _ragged_impl(self, params, cache, tokens, positions, block_tables,
                     seq_lens, slot_idx, seq_ids, seq_starts, row_offsets,
                     last_idx, rng, temp, top_k, top_p, *, prefix_blocks=0,
                     k_cand=K_MAX, exact=False, grammar=None, jrows=None,
                     jstate=None, jdepth=None, jstack=None, min_p=None,
                     bias_tokens=None, bias_vals=None, seeds=None,
                     seed_rows=None):
        return ragged_prefill_step(
            self.model, params, cache, tokens, positions, block_tables,
            seq_lens, slot_idx, seq_ids, seq_starts, row_offsets, last_idx,
            rng, temp, top_k, top_p, prefix_blocks=prefix_blocks,
            k_cand=k_cand, exact=exact, grammar=grammar, jrows=jrows,
            jstate=jstate, jdepth=jdepth, jstack=jstack, min_p=min_p,
            bias_tokens=bias_tokens, bias_vals=bias_vals, seeds=seeds,
            seed_rows=seed_rows)

    def _unified_impl(self, params, cache, tokens, positions, block_tables,
                      seq_lens, slot_idx, seq_ids, seq_starts, row_offsets,
                      last_idx, rng, temp, top_k, top_p, *, row_tokens=0,
                      prefix_blocks=0, k_cand=K_MAX, exact=False,
                      grammar=None, jrows=None, jstate=None, jdepth=None,
                      jstack=None, min_p=None, bias_tokens=None,
                      bias_vals=None, seeds=None, seed_rows=None,
                      pen_tokens=None, pen_first=None, freq_pen=None,
                      pres_pen=None):
        return unified_token_step(
            self.model, params, cache, tokens, positions, block_tables,
            seq_lens, slot_idx, seq_ids, seq_starts, row_offsets, last_idx,
            rng, temp, top_k, top_p, pen_tokens, pen_first, freq_pen,
            pres_pen, row_tokens=row_tokens, prefix_blocks=prefix_blocks,
            k_cand=k_cand, exact=exact, grammar=grammar, jrows=jrows,
            jstate=jstate, jdepth=jdepth, jstack=jstack, min_p=min_p,
            bias_tokens=bias_tokens, bias_vals=bias_vals, seeds=seeds,
            seed_rows=seed_rows)

    def _burst_impl(self, params, cache, tokens, positions, block_tables,
                    seq_lens, slot_idx, seq_ids, seq_starts, row_offsets,
                    last_idx, limits, rng, temp, top_k, top_p, *,
                    num_steps=2, row_tokens=0, prefix_blocks=0,
                    k_cand=K_MAX, exact=False, use_penalties=False,
                    grammar=None, jrows=None, jstate=None, jdepth=None,
                    jstack=None, min_p=None, bias_tokens=None,
                    bias_vals=None, seeds=None, seed_rows=None,
                    pen_tokens=None, pen_first=None, pen_cursor=None,
                    freq_pen=None, pres_pen=None):
        return unified_burst_step(
            self.model, params, cache, tokens, positions, block_tables,
            seq_lens, slot_idx, seq_ids, seq_starts, row_offsets, last_idx,
            limits, rng, temp, top_k, top_p, pen_tokens, pen_first,
            pen_cursor, freq_pen, pres_pen, num_steps=num_steps,
            block_size=self.config.block_size, row_tokens=row_tokens,
            prefix_blocks=prefix_blocks, k_cand=k_cand, exact=exact,
            use_penalties=use_penalties, grammar=grammar, jrows=jrows,
            jstate=jstate, jdepth=jdepth, jstack=jstack, min_p=min_p,
            bias_tokens=bias_tokens, bias_vals=bias_vals, seeds=seeds,
            seed_rows=seed_rows)

    def _sp_impl(self, params, tokens, positions, last_idx, rng, temp,
                 top_k, top_p, *, nb, k_cand=K_MAX, exact=False):
        """Sequence-parallel prefill: ring attention over mesh["data"],
        then sample the first token and lay the fresh KV out as cache
        blocks [L, nb, 2, Bs, HkD] (sharded like the pool, so the
        follow-up scatter is a resident-layout write).  With the int8
        cache the blocks are quantized here, in the same dispatch."""
        hidden, kv = self.model.forward_seq_parallel(
            params, tokens, positions, self.mesh, sp_axis=AXIS_DATA
        )
        last_h = hidden[jnp.arange(1), last_idx]
        logits = self.model.compute_logits(params, last_h)
        out = sample_full(logits, rng, temp, top_k, top_p,
                          k_cand=k_cand, exact=exact)
        l, _, b, s, hkd = kv.shape
        bs = self.config.block_size
        blocks = kv[:, :, 0].reshape(l, 2, nb, bs, hkd).transpose(0, 2, 1, 3, 4)
        if self.cache_quant:
            from dynamo_tpu.ops.kv_quant import (
                QuantKvCache, pad_scales, quantize_kv_rows,
            )

            hk = self.model.config.num_kv_heads
            q8, sc = quantize_kv_rows(
                blocks.reshape(l, nb, 2, bs, hk, hkd // hk)
            )  # int8 [..., Bs, Hk, D], scale f32 [..., Bs, Hk]
            blocks = QuantKvCache(
                q8.reshape(l, nb, 2, bs, hkd),
                # token-minor [L, nb, 2, Hk, Bs] -> tile-padded [.., Hp, Sp]
                pad_scales(jnp.swapaxes(sc, -1, -2)),
            )
        blocks = jax.lax.with_sharding_constraint(
            blocks, self._cache_sharding()
        )
        return out, blocks

    def _spec_impl(self, params, cache, tokens, positions, block_tables,
                   seq_lens, slot_idx, rng, temperature, top_k, top_p,
                   min_p, seeds, seed_rows, *, k_cand=K_MAX, exact=False):
        """Speculative verify: forward S tokens per row against the paged
        cache (KV scattered like prefill) and SAMPLE at every position
        with that position's own noise — the host accepts the proposal
        prefix the samples agree with.

        This is exact rejection sampling for the n-gram proposer: the
        proposal is a point mass, so "sample from the target and accept
        iff it matches" accepts with probability p(x) — the canonical
        min(1, p/q) rule — and on mismatch the drawn sample is already
        distributed as the renormalised residual (p restricted to ≠ x).
        Every emitted token is therefore distributed exactly as plain
        decoding, at any temperature.  Greedy rows (temp 0) reduce to
        argmax.  Seeded rows reuse the (seed, position, token-id) noise
        of engine/sampling.py, so their streams are bit-identical with
        speculation on or off (tests/test_spec_decode.py)."""
        hidden, cache = self.model.forward(
            params, tokens, positions, cache, block_tables, seq_lens, slot_idx
        )
        logits = self.model.compute_logits(params, hidden)  # [B, S, V]
        b, s, v = logits.shape
        rep = lambda a: jnp.repeat(a, s)
        sampled, _, _, _ = sample_full(
            logits.reshape(b * s, v), rng,
            rep(temperature), rep(top_k), rep(top_p),
            min_p=rep(min_p), seeds=rep(seeds), seed_rows=rep(seed_rows),
            # fold index = the sampled token's absolute sequence position,
            # matching unified_step/multi_decode_step exactly
            seed_steps=positions.reshape(b * s) + 1,
            # the caller threads _sampling_mode's (k_cand, exact) through,
            # so the verify candidate policy matches what the plain decode
            # path would use for the same batch (seeds force exact there)
            k_cand=k_cand, exact=exact,
        )
        return sampled.reshape(b, s).astype(jnp.int32), cache

    def _multi_impl(self, params, cache, *args, num_steps=1, k_cand=K_MAX,
                    exact=False, use_penalties=False, grammar=None,
                    jrows=None, jstate=None, jdepth=None, jstack=None,
                    min_p=None, bias_tokens=None, bias_vals=None,
                    seeds=None, seed_rows=None):
        return multi_decode_step(
            self.model, params, cache, *args,
            grammar=grammar, jrows=jrows, jstate=jstate, jdepth=jdepth,
            jstack=jstack, min_p=min_p, bias_tokens=bias_tokens,
            bias_vals=bias_vals, seeds=seeds, seed_rows=seed_rows,
            num_steps=num_steps,
            block_size=self.config.block_size,
            k_cand=k_cand, exact=exact, use_penalties=use_penalties,
        )

    def _cache_sharding(self):
        """NamedSharding tree matching the cache pytree (bf16 array or
        QuantKvCache data+scale pair), mesh-pruned at init."""
        from jax.sharding import NamedSharding, PartitionSpec

        return jax.tree.map(
            lambda s: NamedSharding(self.mesh, s),
            self._cache_specs,
            is_leaf=lambda x: isinstance(x, PartitionSpec),
        )

    # ------------------------------------------------------- JSON grammar
    def attach_grammar_tokenizer(self, tokenizer, eos_ids=None) -> None:
        """Provide the tokenizer JSON-mode tables are compiled from; the
        compile itself runs lazily on the first json_mode request."""
        if self._grammar is None:
            self._grammar_tok = (tokenizer, tuple(eos_ids or self.eos_token_ids))

    def _ensure_grammar(self) -> Optional[JsonGrammar]:
        if self._grammar is None and self._grammar_tok is not None:
            tok, eos = self._grammar_tok
            self._grammar_tok = None
            self._grammar = JsonGrammar.from_tokenizer(tok, eos_ids=eos)
            log.info("compiled JSON grammar tables (%d states x %d tokens)",
                     self._grammar.tables.n_states,
                     self._grammar.tables.vocab_size)
        return self._grammar

    def _grammar_usable(self) -> bool:
        g = self._ensure_grammar()
        return g is not None and any(
            0 <= e < self.model.config.vocab_size for e in g.tables.eos_ids
        )

    @staticmethod
    def _grammar_key(req: EngineRequest):
        """None | "json" | ("choice", ...) | ("regex", ...) — which
        grammar (if any) constrains this request.  guided_regex wins over
        json_mode: schema requests carry both, regex enforcing the shape
        and json_mode serving as the uncompilable-regex fallback."""
        # regex before json: schema requests carry BOTH (the regex enforces
        # the schema's shape; json_mode is the documented fallback if that
        # regex turns out uncompilable)
        if req.sampling.guided_regex:
            return ("regex", req.sampling.guided_regex)
        if req.sampling.json_mode:
            return "json"
        if req.sampling.guided_choice:
            return ("choice",) + tuple(req.sampling.guided_choice)
        return None

    # composite state budget: a dispatch's composed tables must stay well
    # inside int16 ids; requests that would exceed it wait for slots to
    # free (same backpressure shape as NoFreeBlocks)
    GRAMMAR_STATE_BUDGET = 16384

    def _grammar_states_bound(self, key) -> int:
        """Upper bound on a grammar's state count.  Regex grammars compile
        (and cache) their tables here — the DFA size is not knowable from
        the pattern text, and admission must reject/stall BEFORE a
        dispatch composes an overflowing table."""
        if key == "json":
            return 128  # the JSON pushdown automaton is ~90 states
        if key[0] == "regex":
            return self._tables_for(key).n_states
        return sum(len(c.encode("utf-8")) for c in key[1:]) + 2

    def _active_grammar_budget_ok(self, new_key) -> bool:
        keys = {self._grammar_key(r) for r in self.slots if r is not None}
        keys.discard(None)
        keys.add(new_key)
        return (sum(self._grammar_states_bound(k) for k in keys)
                <= self.GRAMMAR_STATE_BUDGET)

    def _tables_for(self, key):
        """Host VocabTables for one grammar key (request-relative state
        space).  Choice tables compile on first use and cache by choices."""
        if key == "json":
            return self._grammar.tables
        if key in self._choice_tables:
            cached = self._choice_tables[key]
            if isinstance(cached, Exception):
                raise cached  # known-bad pattern: re-raise, don't recompile
            return cached
        try:
            if key[0] == "regex":
                tables = compile_regex_vocab(
                    self._grammar.token_bytes, key[1],
                    eos_ids=self._grammar.tables.eos_ids,
                )
            else:
                tables = compile_choice_vocab(
                    self._grammar.token_bytes, list(key[1:]),
                    eos_ids=self._grammar.tables.eos_ids,
                )
        except Exception as e:
            # cache the failure (bounded): a resubmitted bad pattern must
            # not pay the compile cost again, and varied bad patterns must
            # not grow the cache without limit or starve live tables
            failures = [k for k, v in self._choice_tables.items()
                        if isinstance(v, Exception)]
            if len(failures) >= 32:
                self._choice_tables.pop(failures[0])
            self._choice_tables[key] = e
            raise
        cap = max(16, self.config.max_batch_size)
        if len(self._choice_tables) >= cap:
            # evict a set no active request is using — in-flight grammars
            # must stay resident or every dispatch would recompile them
            active = {self._grammar_key(r) for r in self.slots
                      if r is not None}
            victim = next(
                (k for k, v in self._choice_tables.items()
                 if k not in active and not isinstance(v, Exception)),
                None,
            )
            if victim is not None:
                self._choice_tables.pop(victim)
                self._gdev_cache.clear()  # composites may reference it
        self._choice_tables[key] = tables
        return tables

    def _composite_for(self, keys: tuple):
        """(device tables, {key: state offset}) for a dispatch whose
        constrained rows use exactly ``keys`` (json first — the pushdown
        sentinel resolves against offset-0 ids)."""
        if keys not in self._gdev_cache:
            comp, offs = compose_tables([self._tables_for(k) for k in keys])
            # pad the state axis to a power of two: the table rides the
            # jitted step as a pytree, so each distinct shape is a fresh
            # executable — bucketing keeps the count O(log) over keysets
            n = comp.n_states
            pad = (1 << max(0, (n - 1).bit_length())) - n
            if pad:
                comp = dataclasses.replace(
                    comp,
                    next_state=np.pad(comp.next_state, ((0, pad), (0, 0))),
                    npops=np.pad(comp.npops, ((0, pad), (0, 0))),
                    popbits=np.pad(comp.popbits, ((0, pad), (0, 0))),
                    npush=np.pad(comp.npush, ((0, pad), (0, 0))),
                    pushbits=np.pad(comp.pushbits, ((0, pad), (0, 0))),
                    eos_ok=np.pad(comp.eos_ok, (0, pad)),
                    terminal_only=np.pad(comp.terminal_only, (0, pad)),
                )
            if len(self._gdev_cache) >= 8:
                self._gdev_cache.clear()
            self._gdev_cache[keys] = (
                device_tables(comp, self.model.config.vocab_size),
                dict(zip(keys, offs)),
            )
        return self._gdev_cache[keys]

    def _sampling_extras(self, reqs, rows=None, b=None) -> dict:
        """min_p / logit_bias device kwargs for one dispatch, or {} when no
        request uses them (the common case compiles no extra executables).

        ``rows``: slot index per request for batch-shaped dispatches
        (decode); None = requests are the dispatch rows in order (prefill).
        ``b`` overrides the dispatch row count (ragged prefill: the padded
        sequence-row axis, not max_batch_size).
        """
        kw = {}
        if b is None:
            b = self.config.max_batch_size if rows is not None else len(reqs)
        at = (lambda i: rows[i]) if rows is not None else (lambda i: i)
        if any(r.sampling.min_p > 0 for r in reqs):
            mp = np.zeros(b, np.float32)
            for i, r in enumerate(reqs):
                mp[at(i)] = r.sampling.min_p
            kw["min_p"] = mp
        if any(r.sampling.seed is not None and not r.sampling.greedy
               for r in reqs):
            sd = np.zeros(b, np.int32)
            sr = np.zeros(b, bool)
            for i, r in enumerate(reqs):
                if r.sampling.seed is not None and not r.sampling.greedy:
                    sd[at(i)] = int(r.sampling.seed) & 0x7FFFFFFF
                    sr[at(i)] = True
            kw["seeds"] = sd
            kw["seed_rows"] = sr
        if any(r.sampling.logit_bias for r in reqs):
            longest = max(len(r.sampling.logit_bias or {}) for r in reqs)
            nb = max(8, 1 << (longest - 1).bit_length())  # pow2 buckets
            toks = np.full((b, nb), -1, np.int32)
            vals = np.zeros((b, nb), np.float32)
            for i, r in enumerate(reqs):
                for j, (t, v) in enumerate(
                    list((r.sampling.logit_bias or {}).items())[:nb]
                ):
                    toks[at(i), j] = int(t)
                    vals[at(i), j] = float(v)
            kw["bias_tokens"] = toks
            kw["bias_vals"] = vals
        return kw  # host arrays: the dispatch sites batch-upload them

    def _dispatch_keys(self, reqs) -> tuple:
        """Ordered grammar keys for one dispatch: json first (pushdown
        sentinel constraint), then choice sets in first-seen order."""
        keys = {self._grammar_key(r) for r in reqs}
        keys.discard(None)
        # canonical order: identical grammar sets must hit the same cached
        # composite regardless of request arrival order
        return tuple(sorted(keys, key=lambda k: (k != "json", k)))

    def _gram_kwargs(self, gram) -> dict:
        """Device kwargs for one dispatch's grammar state, or {}."""
        if gram is None:
            return {}
        keys, jrows, jstate, jdepth, jstack = gram
        gdev, _ = self._composite_for(keys)
        # row-state arrays stay host-side here; the dispatch sites fold
        # them into their single batched device_put
        return dict(
            grammar=gdev,
            jrows=np.asarray(jrows), jstate=np.asarray(jstate),
            jdepth=np.asarray(jdepth), jstack=np.asarray(jstack),
        )

    def _sampling_mode(self, reqs) -> tuple[int, bool]:
        """(k_cand, exact) for this dispatch: exact full top-k whenever a
        request asks for top_k beyond the approx candidate set, so large
        top_k never silently truncates.  k_cand is power-of-two bucketed
        (executable count stays O(log)) and capped at 1024 — the deep tail
        beyond that carries negligible probability mass."""
        want = max((r.sampling.top_k for r in reqs), default=0)
        exact = bool(self.config.exact_sampling)
        if any(r.sampling.seed is not None and not r.sampling.greedy
               for r in reqs):
            # seeded determinism requires the exact sorted candidate set:
            # the true top-K_MAX is then batch-composition-independent
            exact = True
        k_cand = K_MAX
        if want > K_MAX:
            k_cand = min(1 << (want - 1).bit_length(), 1024)
            exact = True
        return k_cand, exact

    @staticmethod
    def _upload_dispatch(host_args, gkw=None):
        """ONE batched host->device upload for a dispatch's small
        operands — positional AND grammar/extras rows (per-array
        jnp.asarray would issue a transfer round trip each; per-transfer
        latency is the cost that matters on a remote-attached chip).
        Returns (device_args tuple, gkw with its host arrays replaced)."""
        gkw = dict(gkw or {})
        host_kw = {k: v for k, v in gkw.items() if isinstance(v, np.ndarray)}
        up, up_kw = jax.device_put(
            (tuple(np.asarray(a) for a in host_args), host_kw))
        gkw.update(up_kw)
        return up, gkw

    def _run_step(self, tokens, positions, block_tables, seq_lens, slot_idx,
                  last_idx, temp, top_k, top_p, prefix_blocks=None,
                  k_cand=K_MAX, exact=False, gram=None, extras=None):
        """Returns (sampled [B], logprob [B], cand_ids [B,C], cand_lps [B,C])."""
        self._rng, rng = jax.random.split(self._rng)
        gkw = self._gram_kwargs(gram)
        gkw.update(extras or {})
        step_timeline.mark("host_build")
        up, gkw = self._upload_dispatch(
            (tokens, positions, block_tables, seq_lens, slot_idx, last_idx,
             temp, top_k, top_p), gkw)
        step_timeline.mark("upload")
        if perf_model.wants("step"):
            perf_model.offer(
                "step", self._step_fn,
                (self.params, self.cache, *up[:6], rng, *up[6:]), kw=gkw,
                statics=dict(prefix_blocks=prefix_blocks, k_cand=k_cand,
                             exact=exact))
        out, self.cache = self._step_fn(
            self.params, self.cache,
            *up[:6], rng, *up[6:],
            prefix_blocks=prefix_blocks, k_cand=k_cand, exact=exact, **gkw,
        )
        step_timeline.mark("dispatch", kind="step")
        self.steps += 1
        out = tuple(jax.device_get(out))
        self.device_gets += 1
        step_timeline.mark("readback")
        return out

    def _run_multi_decode_step(self, tokens, positions, block_tables, seq_lens,
                               limits, temp, top_k, top_p, pen=None, gram=None,
                               extras=None, num_steps=1, k_cand=K_MAX,
                               exact=False):
        """Dispatch one multi-step decode; returns (sampled [K,B],
        logprob [K,B], cand_ids [K,B,C], cand_lps [K,B,C])."""
        self._rng, rng = jax.random.split(self._rng)
        use_pen = pen is not None
        host = [tokens, positions, block_tables, seq_lens, limits,
                temp, top_k, top_p] + (list(pen) if use_pen else [])
        gkw = self._gram_kwargs(gram)
        gkw.update(extras or {})
        step_timeline.mark("host_build")
        up, gkw = self._upload_dispatch(host, gkw)
        step_timeline.mark("upload")
        up = list(up)
        args = up[:5] + [rng] + up[5:]
        if perf_model.wants("decode_multi"):
            perf_model.offer(
                "decode_multi", self._multi_fn,
                (self.params, self.cache, *args), kw=gkw,
                statics=dict(num_steps=num_steps, k_cand=k_cand,
                             exact=exact, use_penalties=use_pen))
        out, self.cache = self._multi_fn(
            self.params, self.cache, *args,
            num_steps=num_steps, k_cand=k_cand, exact=exact,
            use_penalties=use_pen, **gkw,
        )
        step_timeline.mark("dispatch", kind="decode_multi")
        self.steps += 1
        if self._lookahead_enabled():
            # overlap window: absorb arrivals while the device runs the
            # decode burst (admission next turn starts from a warm list)
            self._drain_waiting()
            step_timeline.mark("overlap")
        # ONE batched transfer: per-array np.asarray would issue a
        # device->host round trip per output (per-array latency is the
        # cost that matters on a remote-attached chip)
        out = tuple(jax.device_get(out))
        self.device_gets += 1
        step_timeline.mark("readback")
        return out

    # ------------------------------------------------------- cross-thread API
    def submit(self, request: EngineRequest) -> None:
        request.submitted_at = time.perf_counter()
        self.waiting.put(request)

    def abort(self, request_id: str) -> None:
        self._abort_q.put(request_id)

    def run_on_step(self, fn: Callable) -> "concurrent.futures.Future":
        """Enqueue ``fn`` to run on the engine thread at the next step
        boundary; the returned future resolves with its result.  This is the
        only safe way for other threads to touch the cache / block manager
        (single-writer discipline, SURVEY.md §5 race detection)."""
        fut: concurrent.futures.Future = concurrent.futures.Future()
        self._ops.put((fn, fut))
        return fut

    def has_work(self) -> bool:
        return (
            not self.waiting.empty()
            or bool(self._admitted)
            or not self._ops.empty()
            or any(s is not None for s in self.slots)
        )

    def fail_all(self) -> None:
        """Fail every in-flight and queued request (engine step blew up) so
        callers get an error finish instead of a hung stream."""
        for req in [r for r in self.slots if r is not None]:
            self._finish_slot(req, FinishReason.ERROR)
        for req in self._admitted:
            self._finish(req, FinishReason.ERROR)
        self._admitted.clear()
        while True:
            try:
                self._finish(self.waiting.get_nowait(), FinishReason.ERROR)
            except queue.Empty:
                break

    def metrics(self) -> dict:
        """ForwardPassMetrics equivalent (ref kv_router/protocols.rs:30-47)."""
        active = sum(1 for s in self.slots if s is not None)
        out = {
            "request_active_slots": active,
            "request_total_slots": self.config.max_batch_size,
            "kv_active_blocks": self.block_manager.active_blocks,
            "kv_total_blocks": self.block_manager.num_blocks,
            "num_requests_waiting": self.waiting.qsize() + len(self._admitted),
            "kv_usage_perc": self.block_manager.usage,
            "tokens_generated": self.tokens_generated,
            "spec_steps": self.spec_steps,
            "spec_proposed": self.spec_proposed,
            "spec_accepted": self.spec_accepted,
            # prefill batching (token-budget ragged prefill)
            "prefill_dispatches_total": self.prefill_dispatches,
            "prefill_batch_occupancy": (
                self.prefill_rows_dispatched / self.prefill_dispatches
                if self.prefill_dispatches else 0.0
            ),
            "prefill_budget_utilization": (
                self.prefill_budget_used / self.prefill_budget_offered
                if self.prefill_budget_offered else 0.0
            ),
            # unified mixed prefill+decode dispatch
            "unified_dispatches_total": self.unified_dispatches,
            "unified_decode_rows": self.unified_decode_rows,
            "unified_prefill_tokens": self.unified_prefill_tokens,
            "unified_budget_utilization": (
                self.unified_budget_used / self.unified_budget_offered
                if self.unified_budget_offered else 0.0
            ),
            # double-buffered dispatch (lookahead scheduler)
            "lookahead_bursts_total": self.lookahead_bursts,
            "lookahead_hits_total": self.lookahead_hits,
            "lookahead_mispredicts_total": self.lookahead_mispredicts,
            "lookahead_commits_total": self.lookahead_commits,
            "lookahead_flushes_total": self.lookahead_flushes,
            "lookahead_dispatch_depth": self.lookahead_depth,
            "device_gets_total": self.device_gets,
        }
        if self.host_pool is not None:
            out.update(self.host_pool.stats())
        if self.persist_store is not None:
            out.update(self.persist_store.stats())
        # step-timeline headline (process-global; obs/timeline.py)
        out["host_gap_ms_per_turn"] = step_timeline.host_gap_ms_per_turn
        return out

    # -------------------------------------------------------------- main loop
    def step(self) -> bool:
        """Run one scheduling iteration.  Returns False when idle.

        The body is wrapped in the dtspan step timeline (obs/timeline.py):
        ``begin()`` opens the step, the scheduler and every dispatch
        helper ``mark()`` their phase boundaries, ``end()`` attributes
        the residue — so per-phase wall time sums to step wall time by
        construction (the host-bubble before-number ROADMAP item 3
        needs)."""
        self._maybe_profile_start()
        step_timeline.begin()
        try:
            return self._step_inner()
        finally:
            step_timeline.end(trace=self._active_trace())
            self._maybe_profile_stop()

    def _active_trace(self):
        """(trace_id, span_id) of any traced request currently in a
        slot — parents the per-step ``engine.step`` span (and its
        dtperf counter track) under a live request trace.  None when
        tracing is off or no slotted request carries a trace."""
        from dynamo_tpu.obs import tracing

        if not tracing.enabled():
            return None
        for req in self.slots:
            trace = getattr(req, "trace", None)
            if trace:
                return trace
        return None

    def _maybe_profile_start(self) -> None:
        cfg = self.config
        if not cfg.profile_dir or self._profile_done or self._profile_active:
            return
        path = os.path.join(cfg.profile_dir, f"steps-{self.steps:06d}")
        os.makedirs(path, exist_ok=True)
        jax.profiler.start_trace(path)
        self._profile_active = True
        self._profile_from_step = self.steps

    def _maybe_profile_stop(self) -> None:
        if not self._profile_active:
            return
        if (self.steps - self._profile_from_step
                >= max(1, self.config.profile_steps)):
            jax.profiler.stop_trace()
            self._profile_active = False
            self._profile_done = True

    def _step_inner(self) -> bool:
        self._drain_offload()  # evictions from the previous step's tail
        step_timeline.mark("kv_spill_restore")
        self._process_ops()
        self._process_aborts()
        step_timeline.mark("host_ops")
        self._admit()
        step_timeline.mark("admission")
        # slots not yet decoding (waiting on external KV, or mid-chunked-
        # prefill): honour aborts here — _append_token never runs for them,
        # so without this a cancelled long prompt would keep prefilling
        for req in self.slots:
            if (
                req is not None
                and req.state in (RequestState.REMOTE_PREFILL, RequestState.PREFILL)
                and req.abort_requested
            ):
                self._finish_slot(req, FinishReason.CANCELLED)
        ready = [
            r
            for r in self.slots
            if r is not None
            and r.state is RequestState.PREFILL
            and self._prefill_ready(r)
        ]
        decoding = any(
            r is not None and r.state is RequestState.RUNNING for r in self.slots
        )
        if self._unified_enabled():
            # unified token-budget scheduler: a mixed turn is ONE ragged
            # dispatch (decode rows + prefill spans on one flat axis) —
            # no alternation state machine, no per-switch round-trip
            return self._step_unified(ready, decoding)
        # chunked-prefill interleave: when both phases have work, alternate
        # one prefill turn (one chunk, or one ragged token-budget batch)
        # with one decode burst so admissions never stall the decoders for
        # a whole long prompt (VERDICT r1 weak #2)
        if ready and decoding and self.config.prefill_chunk_tokens:
            if self._last_was_prefill:
                self._last_was_prefill = False
                self._run_decode()
            else:
                self._last_was_prefill = True
                self._dispatch_prefill(ready)
            return True
        if ready:
            self._last_was_prefill = True
            self._dispatch_prefill(ready)
            return True
        if decoding:
            self._last_was_prefill = False
            self._run_decode()
            return True
        return False

    def _unified_enabled(self) -> bool:
        return (
            self.config.unified_token_dispatch
            and self.config.prefill_token_budget > 0
            and getattr(self.model, "supports_unified_dispatch", False)
        )

    def _lookahead_enabled(self) -> bool:
        """Double-buffered dispatch: a layer over unified dispatch (the
        fused burst generalizes the unified mixed step), so it engages
        only where unified dispatch would."""
        return self.config.lookahead_dispatch and self._unified_enabled()

    def _step_unified(self, ready: list[EngineRequest], decoding: bool
                      ) -> bool:
        """One turn of the unified token-budget scheduler: mixed work
        runs as ONE dispatch via :meth:`_run_unified`; pure-prefill turns
        keep the ragged token-budget batch and pure-decode turns keep the
        multi-step burst (its scan amortisation and the speculative path
        only make sense with no prefill sharing the axis)."""
        if ready and self._sp_eligible(ready[0]):
            # seq-parallel long prompts keep their dedicated dispatch
            self._run_sp_prefill(ready[0])
            return True
        ready = [r for r in ready if not self._sp_eligible(r)]
        if ready and decoding and self._run_unified(ready):
            return True
        if ready:
            self._dispatch_prefill(ready)
            return True
        if decoding:
            self._run_decode()
            return True
        return False

    def _process_ops(self) -> None:
        while True:
            try:
                fn, fut = self._ops.get_nowait()
            except queue.Empty:
                break
            if not fut.set_running_or_notify_cancel():
                continue
            try:
                fut.set_result(fn())
            except Exception as e:
                fut.set_exception(e)

    def _process_aborts(self) -> None:
        while True:
            try:
                rid = self._abort_q.get_nowait()
            except queue.Empty:
                break
            req = self._by_id.get(rid)
            if req is not None:
                req.abort_requested = True
                continue
            admitted = next(
                (r for r in self._admitted if r.request_id == rid), None
            )
            if admitted is not None:
                admitted.abort_requested = True
                continue
            # not seen yet: the request may still be in the cross-thread
            # waiting queue — remember the abort so admission applies it
            # (without this, cancelling a QUEUED request was silently lost
            # and it ran to completion)
            self._pending_aborts.add(rid)

    def _drain_waiting(self) -> None:
        """Pull the cross-thread waiting queue into ``_admitted``,
        applying pending aborts.  Factored from :meth:`_admit` so the
        lookahead overlap window can absorb arrivals while the device
        computes (the next turn's admission then starts from a warm
        list instead of paying the queue drain in the host gap)."""
        while True:
            try:
                req = self.waiting.get_nowait()
            except queue.Empty:
                break
            if req.request_id in self._pending_aborts:
                self._pending_aborts.discard(req.request_id)
                req.abort_requested = True
            self._admitted.append(req)

    def _admit(self) -> None:
        self._drain_waiting()
        # pending aborts unmatched after a full queue drain can never match:
        # a caller that submitted before aborting had its request visible in
        # this drain (_process_aborts runs before _admit each step), so the
        # leftovers are finished/unknown ids — drop them or the set grows
        # forever on abort-vs-finish races
        self._pending_aborts.clear()
        for req in list(self._admitted):
            if req.abort_requested:
                self._admitted.remove(req)
                self._finish(req, FinishReason.CANCELLED)
                continue
            slot = next((i for i, s in enumerate(self.slots) if s is None), None)
            if slot is None:
                break
            if req.prompt_len == 0:
                self._admitted.remove(req)
                self._finish(req, FinishReason.ERROR)
                continue
            if req.prompt_len >= self.config.max_model_len:
                self._admitted.remove(req)
                self._finish(req, FinishReason.LENGTH)
                continue
            gkey = self._grammar_key(req)
            if gkey is not None and not (
                self._grammar_usable()
                and (gkey == "json" or self._grammar.token_bytes is not None)
            ):
                # constrained decoding needs tokenizer-compiled tables AND
                # a model-vocab EOS id (terminal states are eos-only;
                # without one the mask would go all -inf on completion and
                # sampling degrades to uniform noise)
                self._admitted.remove(req)
                self._finish(req, FinishReason.ERROR)
                continue
            if gkey is not None:
                try:
                    budget_ok = self._active_grammar_budget_ok(gkey)
                except Exception:
                    if gkey[0] == "regex" and req.sampling.json_mode:
                        # schema-derived regex overflowed the DFA cap:
                        # fall back to the generic JSON grammar (prompt
                        # injection still steers the shape)
                        log.warning(
                            "schema regex uncompilable for %s; falling "
                            "back to generic JSON mode", req.request_id,
                        )
                        req.sampling.guided_regex = None
                        gkey = "json"
                        budget_ok = self._active_grammar_budget_ok(gkey)
                    else:
                        # bad pattern / oversized DFA with no fallback:
                        # fail the request, don't crash the engine step
                        log.exception("grammar compile failed for %s",
                                      req.request_id)
                        self._admitted.remove(req)
                        self._finish(req, FinishReason.ERROR)
                        continue
                if not budget_ok:
                    # composed dispatch tables must stay inside int16 state
                    # ids: wait for constrained slots to free
                    # (NoFreeBlocks-style backpressure, not an error)
                    break
            req.seq = TokenBlockSequence(req.prompt, self.config.block_size)
            try:
                alloc = self.block_manager.allocate(
                    req.seq.sequence_hashes(), req.prompt_len
                )
            except NoFreeBlocks:
                break  # retry next step once blocks free up
            req.block_ids = alloc.block_ids
            req.cached_tokens = alloc.cached_tokens
            if self.host_pool is not None and alloc.joined_tokens == 0:
                # allocation may have evicted registered blocks — capture
                # their content BEFORE restore writes into the same ids.
                # (With joined in-flight blocks, restore would scatter host
                # content into blocks the owner is writing — skip; the
                # owner's compute is arriving anyway.)
                self._drain_offload()
                self._restore_from_host(req)
            req.computed_tokens = req.cached_tokens
            req.wait_upto = req.cached_tokens + alloc.joined_tokens
            self._reserve_own(req)
            req.slot = slot
            if req.submitted_at:
                req.queue_wait_s = time.perf_counter() - req.submitted_at
            req.state = (
                RequestState.REMOTE_PREFILL if req.remote_prefill else RequestState.PREFILL
            )
            self.slots[slot] = req
            self._by_id[req.request_id] = req
            self._admitted.remove(req)
            self._pen_cache = None  # live request set changed
            if req.on_allocated is not None:
                try:
                    req.on_allocated(req)
                except Exception:
                    # a dying caller (closed event loop) must not take down
                    # every other request via step() -> fail_all()
                    log.exception("on_allocated callback failed for %s", req.request_id)
                    req.abort_requested = True

    def _dispatch_prefill(self, ready: list[EngineRequest]) -> None:
        """One prefill turn over the READY requests (slot order): the
        head request keeps its historical routing (seq-parallel long
        prompts dispatch alone), otherwise the token-budget ragged batch
        packs every non-SP ready request — or, with batching disabled
        (prefill_token_budget=0) or a model without the ragged attention
        path, the legacy one-request dispatch."""
        head = ready[0]
        if self._sp_eligible(head):
            self._run_sp_prefill(head)
            return
        if self.config.prefill_token_budget > 0 and getattr(
            self.model, "supports_ragged_prefill", False
        ):
            self._run_prefill_batch(
                [r for r in ready if not self._sp_eligible(r)]
            )
        else:
            self._run_prefill(head)

    # ---------------------------------------------------------------- prefill
    def _reserve_own(self, req: EngineRequest) -> None:
        """Register this request as the computer of its not-yet-covered
        full prompt blocks, so concurrent identical prompts join these
        blocks instead of prefilling duplicates."""
        bs = self.config.block_size
        for i in range(req.wait_upto // bs, req.prompt_len // bs):
            blk = req.seq.blocks[i]
            if self.block_manager.reserve(blk.sequence_hash, req.block_ids[i]):
                req.reserved_pairs.append((blk.sequence_hash, req.block_ids[i]))

    def _prefill_ready(self, req: EngineRequest) -> bool:
        """Absorb joined in-flight blocks their owner has committed; return
        True when this request can dispatch a prefill chunk now (nothing
        ahead of ``computed_tokens`` is still being written by someone
        else).  If the owner aborted before committing, take over the
        remaining prompt ourselves."""
        bs = self.config.block_size
        bm = self.block_manager
        while req.computed_tokens < req.wait_upto:
            i = req.computed_tokens // bs
            if bm.block_committed(req.block_ids[i]):
                req.computed_tokens += bs
                req.cached_tokens += bs  # someone else's compute — a hit
                continue
            blk = req.seq.blocks[i]
            if bm.is_reserved(blk.sequence_hash):
                return False  # owner still prefilling — wait, don't recompute
            # owner vanished without committing: take over from here
            req.wait_upto = req.computed_tokens
            self._reserve_own(req)
        return True

    def _run_prefill(self, req: EngineRequest) -> None:
        cfg = self.config
        remaining = req.prompt_len - req.computed_tokens
        # chunked prefill: bound the tokens computed this dispatch so decode
        # bursts interleave (step() alternates); non-final chunks end on a
        # block boundary so the next chunk stays block-aligned
        chunk = cfg.prefill_chunk_tokens or remaining
        take = min(remaining, chunk)
        final = take == remaining
        s = cfg.bucket_for(take)
        m = cfg.max_blocks_per_seq
        end = req.computed_tokens + take

        tokens = np.zeros((1, s), np.int32)
        positions = np.zeros((1, s), np.int32)
        slot_idx = np.full((1, s), -1, np.int32)
        tokens[0, :take] = req.prompt[req.computed_tokens : end]
        pos = np.arange(req.computed_tokens, end, dtype=np.int32)
        positions[0, :take] = pos
        bt = np.zeros((1, m), np.int32)
        bt[0, : len(req.block_ids)] = req.block_ids
        slot_idx[0, :take] = (
            bt[0, pos // cfg.block_size] * cfg.block_size + pos % cfg.block_size
        )
        seq_lens = np.asarray([end], np.int32)
        last_idx = np.asarray([take - 1], np.int32)

        # prefill fast path: cached-prefix blocks, bucketed to powers of two
        # so the executable count stays O(log) (prefill_attention gathers
        # only these instead of the whole padded table)
        pb = req.computed_tokens // cfg.block_size
        pb = 0 if pb == 0 else 1 << (pb - 1).bit_length()
        pb = min(pb, m)

        k_cand, exact = self._sampling_mode([req])
        gram = None
        # only the final chunk's sample is kept — masking earlier chunks
        # would just burn an extra executable per prefill bucket
        gkey = self._grammar_key(req)
        if final and gkey is not None and self._ensure_grammar() is not None:
            keys = self._dispatch_keys([req])
            off = self._composite_for(keys)[1][gkey]
            gs, gd, gk = req.gstate
            gram = (keys, np.asarray([True]),
                    np.asarray([gs + off if gs > 0 else gs], np.int32),
                    np.asarray([gd], np.int32), np.asarray([gk], np.int32))
        sampled, lps, cids, clps = self._run_step(
            tokens, positions, bt, seq_lens, slot_idx, last_idx,
            np.asarray([req.sampling.temperature], np.float32),
            np.asarray([req.sampling.top_k], np.int32),
            np.asarray([req.sampling.top_p], np.float32),
            prefix_blocks=pb, k_cand=k_cand, exact=exact, gram=gram,
            extras=self._sampling_extras([req]) if final else None,
        )
        self.prefill_steps += 1
        self.prefill_dispatches += 1
        self.prefill_rows_dispatched += 1
        prefill_counters.record(rows=1, tokens=take)
        self.prompt_tokens_computed += take
        req.computed_tokens = end
        self._commit_prefill_blocks(req)
        if not final:
            return  # more chunks to go; sample discarded (no logits needed)
        self._complete_prefill(req, sampled, lps, cids, clps)

    def _commit_prefill_blocks(self, req: EngineRequest) -> None:
        """Offer newly completed prompt blocks to the block manager.  The
        ``committed_upto`` watermark makes chunked prefill linear: each
        chunk commits only the blocks it completed — re-offering every
        earlier block per chunk (commit is idempotent but not free) made
        an L-block prompt pay O(L^2) commit calls across its chunks."""
        bs = self.config.block_size
        done = req.computed_tokens // bs
        for blk in req.seq.blocks[req.committed_upto // bs : done]:
            self.block_manager.commit(
                req.block_ids[blk.position], blk.sequence_hash,
                blk.parent_sequence_hash, list(blk.tokens),
            )
        req.committed_upto = done * bs
        self._fire_commit_hook(req, done=False)

    def _run_prefill_batch(self, reqs: list[EngineRequest]) -> None:
        """Token-budget ragged prefill: pack up to ``prefill_token_budget``
        tokens of pending prefill work (several requests' chunks) onto one
        flat token axis and run ONE ragged dispatch.

        Each selected chunk occupies a contiguous block-aligned span of
        the flat axis (padding slots are -1 / seq_id -1), so the
        block-granular cache write and the ragged attention masks hold by
        construction.  The axis is bucketed via ``config.bucket_for`` and
        the sequence-row axis is power-of-two padded — executables stay
        O(log^2).  Only final-chunk rows' samples are kept: those rows
        carry their request's grammar state, sampling extras and seeds;
        mid-chunk rows sample garbage that the host discards."""
        cfg = self.config
        bs = cfg.block_size
        budget = cfg.prefill_token_budget
        sel: list[tuple[EngineRequest, int, bool]] = []  # (req, take, final)
        used = 0
        for req in reqs:
            avail = budget - used
            if avail < bs:
                break
            remaining = req.prompt_len - req.computed_tokens
            chunk = cfg.prefill_chunk_tokens or remaining
            take = min(remaining, chunk, avail)
            if take < remaining:
                # non-final chunks end block-aligned so the resumed chunk
                # starts block-aligned (fast-path + packing requirement)
                take = take // bs * bs
                if take == 0:
                    break
            sel.append((req, take, take == remaining))
            used += -(-take // bs) * bs  # span = block-rounded take

        r_real = len(sel)
        r_pad = 1 << max(0, (r_real - 1).bit_length())
        t_pad = cfg.bucket_for(used)
        m = cfg.max_blocks_per_seq
        tokens = np.zeros((1, t_pad), np.int32)
        positions = np.zeros((1, t_pad), np.int32)
        slot_idx = np.full((1, t_pad), -1, np.int32)
        seq_ids = np.full((1, t_pad), -1, np.int32)
        bt = np.zeros((r_pad, m), np.int32)
        seq_lens = np.zeros(r_pad, np.int32)
        starts = np.zeros(r_pad, np.int32)
        roff = np.zeros(r_pad, np.int32)
        last_idx = np.zeros(r_pad, np.int32)
        temp = np.zeros(r_pad, np.float32)
        top_k = np.zeros(r_pad, np.int32)
        top_p = np.ones(r_pad, np.float32)
        off = 0
        max_pb = 0
        for r, (req, take, final) in enumerate(sel):
            begin = req.computed_tokens
            end = begin + take
            tokens[0, off:off + take] = req.prompt[begin:end]
            pos = np.arange(begin, end, dtype=np.int32)
            positions[0, off:off + take] = pos
            bt[r, : len(req.block_ids)] = req.block_ids
            slot_idx[0, off:off + take] = (
                bt[r, pos // bs] * bs + pos % bs
            )
            seq_ids[0, off:off + take] = r
            seq_lens[r] = end
            starts[r] = begin
            roff[r] = off
            last_idx[r] = off + take - 1
            temp[r] = req.sampling.temperature
            top_k[r] = req.sampling.top_k
            top_p[r] = req.sampling.top_p
            max_pb = max(max_pb, begin // bs)
            off += -(-take // bs) * bs
        # cached-prefix gather bound: max over rows, pow2-bucketed like the
        # single-request path (rows with shorter prefixes mask by start)
        pb = 0 if max_pb == 0 else 1 << (max_pb - 1).bit_length()
        pb = min(pb, m)

        finals = [(r, req) for r, (req, _, fin) in enumerate(sel) if fin]
        final_reqs = [req for _, req in finals]
        k_cand, exact = self._sampling_mode(final_reqs)
        gram = None
        if final_reqs and any(
            self._grammar_key(rq) for rq in final_reqs
        ) and self._ensure_grammar() is not None:
            keys = self._dispatch_keys(final_reqs)
            offs = self._composite_for(keys)[1]
            jrows = np.zeros(r_pad, bool)
            jstate = np.full(r_pad, INIT_STATE, np.int32)
            jdepth = np.zeros(r_pad, np.int32)
            jstack = np.zeros(r_pad, np.int32)
            for r, rq in finals:
                key = self._grammar_key(rq)
                if key is None:
                    continue
                jrows[r] = True
                gs, gd, gk = rq.gstate
                jstate[r] = gs + offs[key] if gs > 0 else gs
                jdepth[r], jstack[r] = gd, gk
            gram = (keys, jrows, jstate, jdepth, jstack)
        extras = None
        if final_reqs:
            extras = self._sampling_extras(
                final_reqs, rows=[r for r, _ in finals], b=r_pad
            )

        self._rng, rng = jax.random.split(self._rng)
        gkw = self._gram_kwargs(gram)
        gkw.update(extras or {})
        step_timeline.mark("host_build")
        up, gkw = self._upload_dispatch(
            (tokens, positions, bt, seq_lens, slot_idx, seq_ids, starts,
             roff, last_idx, temp, top_k, top_p), gkw)
        step_timeline.mark("upload")
        if perf_model.wants("prefill_ragged"):
            perf_model.offer(
                "prefill_ragged", self._ragged_fn,
                (self.params, self.cache, *up[:9], rng, *up[9:]), kw=gkw,
                statics=dict(prefix_blocks=pb, k_cand=k_cand,
                             exact=exact))
        out, self.cache = self._ragged_fn(
            self.params, self.cache, *up[:9], rng, *up[9:],
            prefix_blocks=pb, k_cand=k_cand, exact=exact, **gkw,
        )
        step_timeline.mark("dispatch", kind="prefill_ragged")
        if self._lookahead_enabled():
            self._drain_waiting()  # overlap: absorb arrivals under compute
            step_timeline.mark("overlap")
        sampled, lps, cids, clps = jax.device_get(out)  # one batched pull
        self.device_gets += 1
        step_timeline.mark("readback")
        self.steps += 1
        self.prefill_steps += 1
        take_sum = sum(take for _, take, _ in sel)
        self.prompt_tokens_computed += take_sum
        self.prefill_dispatches += 1
        self.prefill_rows_dispatched += r_real
        self.prefill_budget_offered += budget
        self.prefill_budget_used += take_sum
        prefill_counters.record(rows=r_real, tokens=take_sum, budget=budget)
        for r, (req, take, final) in enumerate(sel):
            req.computed_tokens += take
            self._commit_prefill_blocks(req)
            if final:
                self._complete_prefill(
                    req, sampled[r:r + 1], lps[r:r + 1],
                    cids[r:r + 1], clps[r:r + 1],
                )

    def _complete_prefill(self, req, sampled, lps, cids, clps) -> None:
        """Shared tail of chunked and sequence-parallel prefill: state
        transition, remote-decode holdout, first-token emission."""
        # a COMPLETED prefill must not count against the next arrival: reset
        # the interleave so a fresh prompt's first chunk runs immediately
        # instead of behind a decode burst.  Only when no OTHER prefill is
        # mid-flight — a queue of short prompts must still alternate with
        # decode bursts, or running decoders starve through the whole queue.
        if not any(
            r is not None and r is not req and r.state is RequestState.PREFILL
            for r in self.slots
        ):
            self._last_was_prefill = False
        req.state = RequestState.RUNNING
        if req.remote_decode:
            # prefill-only request: emit the first sampled token, hold the
            # blocks for transfer-out, free the slot (ref prefill_worker.py:148
            # runs generate(max_tokens=1, is_remote_decode=True))
            self._held[req.request_id] = list(req.block_ids)
            # done=True covers ALL blocks, including the partial tail
            # block _commit_prefill_blocks never reaches (it commits only
            # FULL blocks) — the streamed handoff's final chunk rides here
            self._fire_commit_hook(req, done=True)
            self.slots[req.slot] = None
            self._by_id.pop(req.request_id, None)
            req.state = RequestState.FINISHED
            req.finish_reason = FinishReason.STOP
            self.tokens_generated += 1
            req.emit(
                LLMEngineOutput(
                    token_ids=[int(sampled[0])],
                    finish_reason=FinishReason.STOP,
                    cached_tokens=req.cached_tokens,
                )
            )
            return
        self._append_token(req, int(sampled[0]), first=True,
                           logprob=float(lps[0]), cand=(cids[0], clps[0]))

    # ------------------------------------------- unified mixed dispatch
    def _run_unified(self, ready: list[EngineRequest]) -> bool:
        """ONE mixed dispatch for this turn: every RUNNING slot
        contributes a decode row (1 fresh token) on the leading
        row-scatter region of the flat axis, then the READY prefill
        chunks pack block-aligned spans into the remaining token budget.
        The legacy interleave's two dispatches per mixed turn (decode
        burst + prefill turn, with a device round-trip between) collapse
        to one — chunked-prefill-under-decode co-scheduling falls out of
        the layout.  Returns False when no decode row is dispatchable
        or no prefill chunk fits (the caller falls back to a pure
        prefill/decode turn)."""
        cfg = self.config
        bs = cfg.block_size
        m = cfg.max_blocks_per_seq
        # decode region: a STATIC block-multiple of the flat axis (one
        # slot per batch slot), so the prefill spans after it stay
        # block-aligned for the block-granular write and the executable
        # count gains no new axis
        d_region = -(-cfg.max_batch_size // bs) * bs
        budget = max(bs, cfg.prefill_token_budget - d_region)
        budget = min(budget, cfg.max_model_len - d_region)
        if budget < bs:
            return False  # flat axis cannot fit a span past the region

        lookahead = self._lookahead_enabled()
        # fused burst depth: mixed turns always have prefill pending, so
        # the interactive burst length applies (cf. _run_decode); 1 when
        # lookahead is off keeps the single-turn dispatch bit-for-bit
        k_steps = max(1, cfg.interactive_decode_steps) if lookahead else 1

        dec: list[EngineRequest] = []
        dec_limits: list[int] = []
        for req in self.slots:
            if req is None or req.state is not RequestState.RUNNING:
                continue
            limit = self._grow_blocks(req, k_steps)
            if limit is None:
                continue  # no slot for even the current token: LENGTH
            dec.append(req)
            dec_limits.append(limit)
        if not dec:
            return False

        # prefill packing under the remaining budget (same selection as
        # _run_prefill_batch)
        sel: list[tuple[EngineRequest, int, bool]] = []
        used = 0
        for req in ready:
            avail = budget - used
            if avail < bs:
                break
            remaining = req.prompt_len - req.computed_tokens
            chunk = cfg.prefill_chunk_tokens or remaining
            take = min(remaining, chunk, avail)
            if take < remaining:
                take = take // bs * bs  # resumed chunks stay block-aligned
                if take == 0:
                    break
            sel.append((req, take, take == remaining))
            used += -(-take // bs) * bs  # span = block-rounded take
        if not sel:
            return False

        n_dec = len(dec)
        r_real = n_dec + len(sel)
        r_pad = 1 << max(0, (r_real - 1).bit_length())
        t_pad = cfg.bucket_for(d_region + used)

        # speculative-dispatch commit protocol: if last turn's overlap
        # window prebuilt exactly this plan, reuse its prefill-span
        # arrays (the O(t_pad) host work) — decode-row scalars advance
        # every turn and are always refilled below.  Any divergence
        # (a stop fired, an admission/finish changed the slot map, a
        # prefill chunk resized) mismatches the key: flush and rebuild.
        arrays = None
        pf_max_pb = 0
        if lookahead:
            spec, self._spec_next = self._spec_next, None
            if spec is not None:
                key = (tuple(r.request_id for r in dec),
                       tuple((rq.request_id, rq.computed_tokens, take, fin)
                             for rq, take, fin in sel),
                       d_region, r_pad, t_pad)
                if spec["key"] == key:
                    arrays = spec["arrays"]
                    pf_max_pb = spec["max_pb"]
                    self.lookahead_commits += 1
                    lookahead_counters.record_commit()
                else:
                    self.lookahead_flushes += 1
                    lookahead_counters.record_flush()
        if arrays is None:
            arrays = self._alloc_unified_arrays(r_pad, t_pad)
            off = d_region
            for j, (req, take, _final) in enumerate(sel):
                off = self._fill_prefill_span(
                    arrays, n_dec + j, off, req, req.computed_tokens, take)
                pf_max_pb = max(pf_max_pb, req.computed_tokens // bs)
        (tokens, positions, slot_idx, seq_ids, bt, seq_lens, starts, roff,
         last_idx, temp, top_k, top_p, limits) = arrays
        max_pb = pf_max_pb
        for r, req in enumerate(dec):
            p = req.seq.total_tokens - 1  # uncomputed tail position
            tokens[0, r] = req.seq.tokens[-1]
            positions[0, r] = p
            slot_idx[0, r] = req.block_ids[p // bs] * bs + p % bs
            seq_ids[0, r] = r
            bt[r, : len(req.block_ids)] = req.block_ids
            seq_lens[r] = p + 1
            starts[r] = p  # full cached prefix; need NOT be block-aligned
            roff[r] = r
            last_idx[r] = r
            temp[r] = req.sampling.temperature
            top_k[r] = req.sampling.top_k
            top_p[r] = req.sampling.top_p
            limits[r] = dec_limits[r]
            max_pb = max(max_pb, -(-p // bs))
        pb = 0 if max_pb == 0 else 1 << (max_pb - 1).bit_length()
        pb = min(pb, m)

        # sampling rows: every decode row plus final-chunk prefill rows
        # (mid-chunk rows' samples are discarded below)
        samp = list(enumerate(dec)) + [
            (n_dec + j, rq) for j, (rq, _, fin) in enumerate(sel) if fin
        ]
        samp_reqs = [rq for _, rq in samp]
        k_cand, exact = self._sampling_mode(samp_reqs)
        gram = None
        if any(self._grammar_key(rq) for rq in samp_reqs) \
                and self._ensure_grammar() is not None:
            keys = self._dispatch_keys(samp_reqs)
            offs = self._composite_for(keys)[1]
            jrows = np.zeros(r_pad, bool)
            jstate = np.full(r_pad, INIT_STATE, np.int32)
            jdepth = np.zeros(r_pad, np.int32)
            jstack = np.zeros(r_pad, np.int32)
            for r, rq in samp:
                key = self._grammar_key(rq)
                if key is None:
                    continue
                jrows[r] = True
                gs, gd, gk = rq.gstate
                jstate[r] = gs + offs[key] if gs > 0 else gs
                jdepth[r], jstack[r] = gd, gk
            gram = (keys, jrows, jstate, jdepth, jstack)
        extras = self._sampling_extras(
            samp_reqs, rows=[r for r, _ in samp], b=r_pad)
        burst = lookahead and k_steps >= 2
        extras.update(self._unified_penalties(
            samp, r_pad, horizon=k_steps if burst else 1))
        use_pen = "pen_tokens" in extras

        # growth allocations above may have evicted registered blocks
        # that this very dispatch writes into — offload them first
        step_timeline.mark("host_build")
        self._drain_offload()
        step_timeline.mark("kv_spill_restore")
        self._rng, rng = jax.random.split(self._rng)
        gkw = self._gram_kwargs(gram)
        gkw.update(extras)
        if burst:
            up, gkw = self._upload_dispatch(
                (tokens, positions, bt, seq_lens, slot_idx, seq_ids,
                 starts, roff, last_idx, limits, temp, top_k, top_p), gkw)
            step_timeline.mark("upload")
            if perf_model.wants("unified_burst"):
                perf_model.offer(
                    "unified_burst", self._burst_fn,
                    (self.params, self.cache, *up[:10], rng, *up[10:]),
                    kw=gkw,
                    statics=dict(num_steps=k_steps, row_tokens=d_region,
                                 prefix_blocks=pb, k_cand=k_cand,
                                 exact=exact, use_penalties=use_pen))
            out, self.cache = self._burst_fn(
                self.params, self.cache, *up[:10], rng, *up[10:],
                num_steps=k_steps, row_tokens=d_region, prefix_blocks=pb,
                k_cand=k_cand, exact=exact, use_penalties=use_pen, **gkw,
            )
            step_timeline.mark("dispatch", kind="unified_burst")
        else:
            up, gkw = self._upload_dispatch(
                (tokens, positions, bt, seq_lens, slot_idx, seq_ids,
                 starts, roff, last_idx, temp, top_k, top_p), gkw)
            step_timeline.mark("upload")
            if perf_model.wants("unified"):
                perf_model.offer(
                    "unified", self._unified_fn,
                    (self.params, self.cache, *up[:9], rng, *up[9:]),
                    kw=gkw,
                    statics=dict(row_tokens=d_region, prefix_blocks=pb,
                                 k_cand=k_cand, exact=exact))
            out, self.cache = self._unified_fn(
                self.params, self.cache, *up[:9], rng, *up[9:],
                row_tokens=d_region, prefix_blocks=pb, k_cand=k_cand,
                exact=exact, **gkw,
            )
            step_timeline.mark("dispatch", kind="unified")
        if lookahead:
            # overlap window: the dispatch above is in flight — drain
            # arrivals and speculatively prebuild the NEXT turn's
            # prefill-span operands while the device computes.  The
            # device_get below is the synchronization point, so this
            # host work is hidden under device time (attributed to the
            # "overlap" phase, excluded from the host gap).
            self._drain_waiting()
            self._spec_next = self._prebuild_next(
                ready, sel, dec, d_region, budget)
            step_timeline.mark("overlap")
        if burst:
            # ONE pull for the whole burst: turn-0 samples (named as in
            # the single-turn path — the sel completion below is shared)
            # plus the on-device-appended scan turns
            (sampled, lps, cids, clps), (ss, ls, css, cls) = \
                jax.device_get(out)
        else:
            sampled, lps, cids, clps = jax.device_get(out)
        self.device_gets += 1
        step_timeline.mark("readback")
        self.steps += 1
        self.prefill_steps += 1
        self.decode_steps += k_steps
        take_sum = sum(take for _, take, _ in sel)
        self.prompt_tokens_computed += take_sum
        self.prefill_dispatches += 1
        self.prefill_rows_dispatched += len(sel)
        self.prefill_budget_offered += budget
        self.prefill_budget_used += take_sum
        self.unified_dispatches += 1
        self.unified_decode_rows += n_dec
        self.unified_prefill_tokens += take_sum
        self.unified_budget_offered += cfg.prefill_token_budget
        self.unified_budget_used += n_dec + take_sum
        prefill_counters.record(rows=len(sel), tokens=take_sum,
                                budget=budget)
        prefill_counters.record_unified(
            decode_rows=n_dec, prefill_tokens=take_sum,
            budget=cfg.prefill_token_budget)

        hits = mis = 0
        for r, req in enumerate(dec):
            want_lp = req.sampling.logprobs or req.sampling.top_logprobs > 0
            row_len = int(seq_lens[r])  # pre-dispatch total (p + 1)
            self._append_token(
                req, int(sampled[r]),
                logprob=float(lps[r]) if want_lp else None,
                cand=(cids[r], clps[r]) if want_lp else None,
            )
            if not burst:
                continue
            # scan turns: positions at/past the row's block limit wrote
            # no KV on device, so only `allowed` samples are real
            allowed = max(0, min(k_steps - 1, dec_limits[r] - row_len))
            consumed = 0
            for j in range(allowed):
                if req.state is not RequestState.RUNNING:
                    break  # stop fired mid-burst: discard the tail
                self._append_token(
                    req, int(ss[j, r]),
                    logprob=float(ls[j, r]) if want_lp else None,
                    cand=(css[j, r], cls[j, r]) if want_lp else None,
                )
                consumed += 1
            if req.state is RequestState.RUNNING and allowed < k_steps - 1:
                # ran out of block-table room mid-burst — same LENGTH
                # semantics as the pure-decode burst
                self._finish_slot(req, FinishReason.LENGTH)
            if consumed < allowed:
                mis += 1  # a stop fired: predicted tail discarded
            else:
                hits += 1
        if burst:
            self.lookahead_bursts += 1
            self.lookahead_hits += hits
            self.lookahead_mispredicts += mis
            self.lookahead_depth = k_steps
            lookahead_counters.record_burst(k_steps, hits, mis)
        for j, (req, take, final) in enumerate(sel):
            r = n_dec + j
            req.computed_tokens += take
            self._commit_prefill_blocks(req)
            if final:
                self._complete_prefill(
                    req, sampled[r:r + 1], lps[r:r + 1],
                    cids[r:r + 1], clps[r:r + 1],
                )
        return True

    def _alloc_unified_arrays(self, r_pad: int, t_pad: int):
        """Zero/pad-initialised dispatch operands for one unified turn —
        shared by the live build and :meth:`_prebuild_next` so a
        committed speculative build is bit-identical to a fresh one."""
        m = self.config.max_blocks_per_seq
        tokens = np.zeros((1, t_pad), np.int32)
        positions = np.zeros((1, t_pad), np.int32)
        slot_idx = np.full((1, t_pad), -1, np.int32)
        seq_ids = np.full((1, t_pad), -1, np.int32)
        bt = np.zeros((r_pad, m), np.int32)
        seq_lens = np.zeros(r_pad, np.int32)
        starts = np.zeros(r_pad, np.int32)
        roff = np.zeros(r_pad, np.int32)
        last_idx = np.zeros(r_pad, np.int32)
        temp = np.zeros(r_pad, np.float32)
        top_k = np.zeros(r_pad, np.int32)
        top_p = np.ones(r_pad, np.float32)
        limits = np.zeros(r_pad, np.int32)
        return (tokens, positions, slot_idx, seq_ids, bt, seq_lens,
                starts, roff, last_idx, temp, top_k, top_p, limits)

    def _fill_prefill_span(self, arrays, r: int, off: int,
                           rq: EngineRequest, begin: int, take: int) -> int:
        """Fill dispatch row ``r`` with ``rq``'s prefill chunk
        ``[begin, begin+take)`` starting at flat-axis offset ``off``;
        returns the next (block-rounded) span offset.  Safe to run
        speculatively: it reads only ``rq.prompt`` and ``rq.block_ids``,
        which are immutable while the request sits in PREFILL."""
        bs = self.config.block_size
        (tokens, positions, slot_idx, seq_ids, bt, seq_lens, starts,
         roff, last_idx, temp, top_k, top_p, _limits) = arrays
        end = begin + take
        tokens[0, off:off + take] = rq.prompt[begin:end]
        pos = np.arange(begin, end, dtype=np.int32)
        positions[0, off:off + take] = pos
        bt[r, : len(rq.block_ids)] = rq.block_ids
        slot_idx[0, off:off + take] = bt[r, pos // bs] * bs + pos % bs
        seq_ids[0, off:off + take] = r
        seq_lens[r] = end
        starts[r] = begin
        roff[r] = off
        last_idx[r] = off + take - 1
        temp[r] = rq.sampling.temperature
        top_k[r] = rq.sampling.top_k
        top_p[r] = rq.sampling.top_p
        return off + -(-take // bs) * bs

    def _prebuild_next(self, ready, sel, dec, d_region: int,
                       budget: int) -> Optional[dict]:
        """Speculatively build the NEXT unified turn's prefill-span
        operands while the device computes the current one (the overlap
        window between the dispatch call and its device_get).

        Prediction model: this turn's selected chunks land (their
        effects are deterministic — ``computed_tokens`` advances by
        ``take``), every decode row survives the turn (exactly one
        token, no stop fires), finals join the decode set, and no
        admission or finish changes the slot map.  The returned dict's
        ``key`` pins that prediction; the next :meth:`_run_unified`
        commits the arrays when its actual plan matches and flushes
        them otherwise.  Only the O(t_pad) prefill-span work is
        prebuilt — decode-row scalars advance every turn and are always
        refilled at commit time, so a committed build needs no
        patching."""
        cfg = self.config
        bs = cfg.block_size
        sel_map = {rq.request_id: (take, fin) for rq, take, fin in sel}
        nxt = []  # (req, predicted next begin) — ready order preserved
        for rq in ready:
            take, fin = sel_map.get(rq.request_id, (0, False))
            if fin:
                continue  # completes this turn: joins the decode set
            nxt.append((rq, rq.computed_tokens + take))
        if not nxt:
            return None  # no prefill survives: next turn isn't mixed
        # predicted packing — same selection loop as the live build,
        # over the predicted begins
        plan = []
        used = 0
        for rq, begin in nxt:
            avail = budget - used
            if avail < bs:
                break
            remaining = rq.prompt_len - begin
            chunk = cfg.prefill_chunk_tokens or remaining
            take = min(remaining, chunk, avail)
            if take < remaining:
                take = take // bs * bs
                if take == 0:
                    break
            plan.append((rq, begin, take, take == remaining))
            used += -(-take // bs) * bs
        if not plan:
            return None
        dec_ids = {r.request_id for r in dec}
        fin_ids = {rq.request_id for rq, _, fin in sel if fin}
        pred_dec = [
            r.request_id for r in self.slots
            if r is not None
            and (r.request_id in dec_ids or r.request_id in fin_ids)
        ]
        n_dec = len(pred_dec)
        r_real = n_dec + len(plan)
        r_pad = 1 << max(0, (r_real - 1).bit_length())
        t_pad = cfg.bucket_for(d_region + used)
        arrays = self._alloc_unified_arrays(r_pad, t_pad)
        off = d_region
        max_pb = 0
        for j, (rq, begin, take, _fin) in enumerate(plan):
            off = self._fill_prefill_span(arrays, n_dec + j, off, rq,
                                          begin, take)
            max_pb = max(max_pb, begin // bs)
        key = (tuple(pred_dec),
               tuple((rq.request_id, begin, take, fin)
                     for rq, begin, take, fin in plan),
               d_region, r_pad, t_pad)
        return dict(key=key, arrays=arrays, max_pb=max_pb)

    def _unified_penalties(self, samp, r_pad: int, horizon: int = 1) -> dict:
        """Penalty buffers for one unified dispatch, keyed by DISPATCH
        row (cf. :meth:`_penalty_buffers`, which keys by slot): a
        [R_pad, T] generated-token buffer + first-occurrence mask +
        per-row strengths.  {} when no sampling row uses penalties, so
        the common case compiles no extra executables.

        ``horizon`` > 1 sizes the buffer for a fused burst (the scan
        appends up to ``horizon`` tokens per row on device) and adds
        the per-row ``pen_cursor`` write index; ``horizon`` == 1 keeps
        the single-turn buffer shape (and its trace keys) unchanged.

        The host build is cached on (rows, shapes, live request set +
        penalty strengths): while the plan is stable, only the tokens
        generated since the previous turn are appended into the cached
        buffers instead of rebuilding the whole [R, T] arrays.  The
        cache is invalidated on admission and finish (slot placement
        changes rows) and misses on any shape change."""
        users = [(r, rq) for r, rq in samp
                 if rq.sampling.frequency_penalty
                 or rq.sampling.presence_penalty]
        if not users:
            return {}
        longest = max(rq.seq.total_tokens - rq.prompt_len
                      for _, rq in users)
        need = longest if horizon <= 1 else longest + horizon
        t_cap = max(16, 1 << max(0, need - 1).bit_length())
        t_cap = min(t_cap, max(
            16, 1 << (self.config.max_model_len - 1).bit_length()))
        key = (r_pad, t_cap, tuple(
            (rq.request_id, r, rq.sampling.frequency_penalty,
             rq.sampling.presence_penalty) for r, rq in users))
        pc = self._pen_cache
        if pc is not None and pc["key"] == key:
            ptoks, pfirst = pc["ptoks"], pc["pfirst"]
            for r, rq in users:
                gen = rq.seq.tokens[rq.prompt_len:]
                seen = pc["seen"][rq.request_id]
                n = min(len(gen), t_cap)
                for j in range(pc["count"][rq.request_id], n):
                    t = gen[j]
                    ptoks[r, j] = t
                    if t not in seen:
                        pfirst[r, j] = True
                        seen.add(t)
                pc["count"][rq.request_id] = n
            out = dict(pc["out"])
        else:
            ptoks = np.full((r_pad, t_cap), -1, np.int32)
            pfirst = np.zeros((r_pad, t_cap), bool)
            freq = np.zeros(r_pad, np.float32)
            pres = np.zeros(r_pad, np.float32)
            seen_map: dict[str, set] = {}
            count_map: dict[str, int] = {}
            for r, rq in users:
                gen = rq.seq.tokens[rq.prompt_len:]
                n = min(len(gen), t_cap)
                seen: set[int] = set()
                for j, t in enumerate(gen[:n]):
                    ptoks[r, j] = t
                    if t not in seen:
                        pfirst[r, j] = True
                        seen.add(t)
                freq[r] = rq.sampling.frequency_penalty
                pres[r] = rq.sampling.presence_penalty
                seen_map[rq.request_id] = seen
                count_map[rq.request_id] = n
            out = dict(pen_tokens=ptoks, pen_first=pfirst,
                       freq_pen=freq, pres_pen=pres)
            self._pen_cache = dict(key=key, out=dict(out), ptoks=ptoks,
                                   pfirst=pfirst, seen=seen_map,
                                   count=count_map)
        if horizon > 1:
            # fused burst: the device appends past this cursor per turn
            cur = np.zeros(r_pad, np.int32)
            for r, rq in users:
                cur[r] = min(rq.seq.total_tokens - rq.prompt_len, t_cap)
            out["pen_cursor"] = cur
        return out

    # ------------------------------------------------ seq-parallel prefill
    def _sp_eligible(self, req: EngineRequest) -> bool:
        return (
            self._sp_size > 0
            and req.computed_tokens == 0
            and req.prompt_len >= self.config.sp_prefill_threshold
            # the SP first-token sample path has no grammar/bias/min_p
            # hooks — those requests take the chunked prefill path, which
            # threads _sampling_extras into the final chunk's sampler
            and not req.sampling.json_mode
            and not req.sampling.guided_choice
            and not req.sampling.guided_regex
            and not req.sampling.logit_bias
            and not req.sampling.min_p
            # the SP first-token sampler has no per-request seed hook
            and not (req.sampling.seed is not None
                     and not req.sampling.greedy)
        )

    def _run_sp_prefill(self, req: EngineRequest) -> None:
        """Whole-prompt prefill in ONE dispatch with the sequence sharded
        over mesh["data"] (ring attention — ops/ring_attention.py): the
        long-context path where even a single prompt's activations/KV
        exceed one chip's comfort.  KV comes back already block-shaped and
        pool-sharded; a donated scatter drops it into the paged cache."""
        cfg = self.config
        bs = cfg.block_size
        unit = bs * self._sp_size
        # pow2 bucketing in units of (block_size × sp) keeps the executable
        # count O(log) while satisfying both divisibility constraints
        units = -(-req.prompt_len // unit)
        units = 1 << (units - 1).bit_length()
        s_pad = units * unit
        nb_pad = s_pad // bs

        tokens = np.zeros((1, s_pad), np.int32)
        tokens[0, : req.prompt_len] = req.prompt
        # padding keys get positions beyond every real query → causally
        # invisible; padding queries produce discarded (finite) rows
        positions = np.arange(s_pad, dtype=np.int32)[None, :]
        last_idx = np.asarray([req.prompt_len - 1], np.int32)
        self._rng, rng = jax.random.split(self._rng)
        k_cand, exact = self._sampling_mode([req])
        step_timeline.mark("host_build")
        up, _ = self._upload_dispatch((
            tokens, positions, last_idx,
            np.asarray([req.sampling.temperature], np.float32),
            np.asarray([req.sampling.top_k], np.int32),
            np.asarray([req.sampling.top_p], np.float32),
        ))
        step_timeline.mark("upload")
        if perf_model.wants("sp_prefill"):
            perf_model.offer(
                "sp_prefill", self._sp_fn,
                (self.params, up[0], up[1], up[2], rng, up[3], up[4],
                 up[5]),
                statics=dict(nb=nb_pad, k_cand=k_cand, exact=exact))
        (sampled, lps, cids, clps), blocks = self._sp_fn(
            self.params, up[0], up[1], up[2], rng, up[3], up[4], up[5],
            nb=nb_pad, k_cand=k_cand, exact=exact,
        )
        step_timeline.mark("dispatch", kind="sp_prefill")
        sampled, lps, cids, clps = jax.device_get(
            (sampled, lps, cids, clps))  # one batched transfer
        self.device_gets += 1
        step_timeline.mark("readback")
        nb = -(-req.prompt_len // bs)
        self.cache = scatter_blocks_inplace(
            self.cache, req.block_ids[:nb],
            jax.tree.map(lambda a: a[:, :nb], blocks),
        )
        self.steps += 1
        self.prefill_steps += 1
        self.sp_prefills += 1
        self.prefill_dispatches += 1
        self.prefill_rows_dispatched += 1
        prefill_counters.record(rows=1, tokens=req.prompt_len)
        self.prompt_tokens_computed += req.prompt_len
        req.computed_tokens = req.prompt_len
        self._commit_prefill_blocks(req)
        self._complete_prefill(req, sampled, lps, cids, clps)

    # ----------------------------------------------------------------- decode
    # ----------------------------------------------------- speculative decode
    def _spec_eligible(self, reqs) -> bool:
        """Speculation composes with plain sampling (greedy, temperature,
        top_k <= K_MAX, top_p, min_p, per-request seeds — the verify pass
        samples each position with its own noise, see ``_spec_impl``).
        Still excluded: penalties (the verify forward doesn't thread the
        generated-token buffers through accepted positions), logprobs
        (not returned per verified position), logit_bias, and grammar
        modes (mask state advances once per emitted token on the decode
        path).  top_k > K_MAX needs the widened exact-candidate dispatch
        the verify executable doesn't compile."""
        return all(
            (r.sampling.greedy or r.sampling.top_k <= K_MAX)
            and not r.sampling.frequency_penalty
            and not r.sampling.presence_penalty
            and not r.sampling.logprobs
            and not r.sampling.top_logprobs
            and not r.sampling.logit_bias
            and not r.sampling.json_mode
            and not r.sampling.guided_choice
            and not r.sampling.guided_regex
            for r in reqs
        )

    def _grow_blocks(self, req: EngineRequest, extra_tokens: int
                     ) -> Optional[int]:
        """Extend ``req``'s block table to cover ``extra_tokens`` more
        positions beyond its uncomputed tail; returns the row's token
        limit, or None when not even the current token has a slot (the
        request was finished at LENGTH).  Shared by the burst and
        speculative dispatch builders."""
        cfg = self.config
        p = req.seq.total_tokens - 1
        want_tokens = min(p + extra_tokens, cfg.max_model_len)
        needed = (want_tokens - 1) // cfg.block_size + 1
        if len(req.block_ids) < needed:
            try:
                req.block_ids.extend(
                    self.block_manager.allocate_raw(needed - len(req.block_ids))
                )
            except NoFreeBlocks:
                if len(req.block_ids) * cfg.block_size <= p:
                    self._finish_slot(req, FinishReason.LENGTH)
                    return None
        return min(len(req.block_ids) * cfg.block_size, cfg.max_model_len)

    def _try_spec_decode(self) -> bool:
        """Prompt-lookup speculative dispatch (engine/spec.py): verify up
        to spec_tokens proposed continuations per row in ONE forward and
        emit the matching prefix + one bonus token.  Returns False when no
        row has a proposal (caller falls back to the burst path).

        On TPU the verify forward takes the multi-query flash-decode
        kernel (ops/pallas/decode_attention.py) — only owned blocks
        stream from HBM.  The block table is additionally SLICED to the
        batch's live context (power-of-two bucketed, so executables stay
        O(log)), which is what bounds the pure-JAX fallback's gather."""
        from dynamo_tpu.engine.spec import propose_ngram

        cfg = self.config
        k = cfg.spec_tokens
        b, m = cfg.max_batch_size, cfg.max_blocks_per_seq
        s = k + 1
        active = [
            r for r in self.slots
            if r is not None and r.state is RequestState.RUNNING
        ]
        if not active or not self._spec_eligible(active):
            return False

        tokens = np.zeros((b, s), np.int32)
        positions = np.zeros((b, s), np.int32)
        slot_idx = np.full((b, s), -1, np.int32)
        bt = np.zeros((b, m), np.int32)
        seq_lens = np.zeros(b, np.int32)
        limits = np.zeros(b, np.int32)
        temp = np.zeros(b, np.float32)  # inactive rows: greedy, ignored
        top_k = np.zeros(b, np.int32)
        top_p = np.ones(b, np.float32)
        min_p = np.zeros(b, np.float32)
        seeds = np.zeros(b, np.int32)
        seed_rows = np.zeros(b, bool)
        props: dict[int, list[int]] = {}
        rows: list[EngineRequest] = []
        any_prop = False
        # draft-model proposals for the whole batch in one dispatch;
        # rows the draft can't serve fall back to n-gram lookup below
        draft_props: dict[int, list[int]] = {}
        if self.draft is not None:
            draft_props = self.draft.propose(active, k, m)
        for req in active:
            i = req.slot
            temp[i] = req.sampling.temperature
            top_k[i] = req.sampling.top_k
            top_p[i] = req.sampling.top_p
            min_p[i] = req.sampling.min_p
            if req.sampling.seed is not None and not req.sampling.greedy:
                seeds[i] = int(req.sampling.seed) & 0x7FFFFFFF
                seed_rows[i] = True
            p = req.seq.total_tokens - 1  # position of the uncomputed tail
            limit = self._grow_blocks(req, s)
            if limit is None:
                continue
            prop = draft_props.get(i) or propose_ngram(
                req.seq.tokens, cfg.spec_ngram, k
            )
            prop = prop[: max(0, limit - (p + 1))]  # KV positions stay in range
            props[i] = prop
            any_prop = any_prop or bool(prop)
            rows.append(req)
            row_tokens = [req.seq.tokens[-1]] + prop
            n = len(row_tokens)
            tokens[i, :n] = row_tokens
            positions[i, :n] = np.arange(p, p + n, dtype=np.int32)
            blk = positions[i, :n] // cfg.block_size
            slot_idx[i, :n] = (
                np.asarray(req.block_ids, np.int32)[blk] * cfg.block_size
                + positions[i, :n] % cfg.block_size
            )
            bt[i, : len(req.block_ids)] = req.block_ids
            seq_lens[i] = p + n
            limits[i] = limit
        if not any_prop or not rows:
            return False
        # a speculative dispatch emits 1 token for every non-proposing row
        # (vs up to decode_steps in a burst): one repetitive request must
        # not collapse the whole batch's throughput, so speculate only when
        # proposals cover at least half the rows (single-row batches always
        # qualify — speculation is the latency lever there)
        proposing = sum(1 for r in rows if props.get(r.slot))
        if self.config.decode_steps > 1 and proposing * 2 < len(rows):
            return False

        # slice the block table to the batch's live context, pow2-bucketed:
        # the verify gather then reads O(max context) KV, not O(model_len)
        blocks_used = max(1, -(-int(seq_lens.max()) // cfg.block_size))
        m_used = min(m, 1 << (blocks_used - 1).bit_length())

        step_timeline.mark("host_build")
        self._drain_offload()
        step_timeline.mark("kv_spill_restore")
        self._rng, rng = jax.random.split(self._rng)
        k_cand, exact = self._sampling_mode(rows)
        up, _ = self._upload_dispatch(
            (tokens, positions, bt[:, :m_used], seq_lens, slot_idx,
             temp, top_k, top_p, min_p, seeds, seed_rows))
        step_timeline.mark("upload")
        if perf_model.wants("spec_verify"):
            perf_model.offer(
                "spec_verify", self._spec_fn,
                (self.params, self.cache, *up[:5], rng, *up[5:]),
                statics=dict(k_cand=k_cand, exact=exact))
        verified, self.cache = self._spec_fn(
            self.params, self.cache,
            *up[:5], rng, *up[5:],
            k_cand=k_cand, exact=exact,
        )
        step_timeline.mark("dispatch", kind="spec_verify")
        verified = jax.device_get(verified)
        self.device_gets += 1
        step_timeline.mark("readback")
        self.steps += 1
        self.decode_steps += 1
        self.spec_steps += 1
        for req in rows:
            i = req.slot
            prop = props.get(i, [])
            # accept the proposal prefix the verify samples agree with,
            # then the bonus token from the first disagreeing (or final)
            # position — each emitted token is that position's own sample
            a = 0
            while a < len(prop) and prop[a] == int(verified[i, a]):
                a += 1
            emit = [int(verified[i, j]) for j in range(a + 1)]
            self.spec_proposed += len(prop)
            self.spec_accepted += a
            allowed = min(len(emit), int(limits[i] - (req.seq.total_tokens - 1)))
            for t in emit[:allowed]:
                if req.state is not RequestState.RUNNING:
                    break  # EOS/stop/max_tokens mid-acceptance
                self._append_token(req, t)
            if req.state is RequestState.RUNNING and allowed < len(emit):
                self._finish_slot(req, FinishReason.LENGTH)
        return True

    def _run_decode(self) -> None:
        """One decode dispatch = up to ``config.decode_steps`` tokens per
        active sequence, generated entirely on device (multi-step
        scheduling).  Blocks for the whole burst are pre-allocated; a
        sequence that runs out of block space stops writing KV at its
        ``limit`` and is finished at LENGTH once its allowed samples are
        consumed.

        Burst length is adaptive: while prefill work is pending (a
        mid-prefill slot, or requests waiting for admission) the burst
        shrinks to ``interactive_decode_steps`` so a fresh prompt waits
        ~8 ITLs, not a whole 64-step burst, before its first prefill chunk
        — the dominant term in chunked-prefill TTFT (VERDICT r2 weak #3)."""
        cfg = self.config
        if cfg.spec_tokens > 0 and self._try_spec_decode():
            return
        b, m = cfg.max_batch_size, cfg.max_blocks_per_seq
        # REMOTE_PREFILL counts too: the disagg first token arrives via the
        # ops queue, processed only between dispatches.  Queued requests
        # only count when a slot is (or is about to be) free — under full
        # saturation no burst length can start a prefill, so don't pay the
        # 8x dispatch count for nothing.
        can_admit = (
            any(s is None for s in self.slots)
            and self.block_manager.free_blocks > 0
        ) or any(r is not None and r.abort_requested for r in self.slots)
        prefill_pending = (
            ((bool(self._admitted) or not self.waiting.empty()) and can_admit)
            or any(
                r is not None
                and r.state in (RequestState.PREFILL, RequestState.REMOTE_PREFILL)
                for r in self.slots
            )
        )
        k_steps = max(
            1,
            cfg.interactive_decode_steps if prefill_pending else cfg.decode_steps,
        )
        tokens = np.zeros(b, np.int32)
        positions = np.zeros(b, np.int32)
        bt = np.zeros((b, m), np.int32)
        seq_lens = np.zeros(b, np.int32)
        limits = np.zeros(b, np.int32)
        temp = np.ones(b, np.float32)
        top_k = np.zeros(b, np.int32)
        top_p = np.ones(b, np.float32)

        active: list[EngineRequest] = []
        for i, req in enumerate(self.slots):
            if req is None or req.state is not RequestState.RUNNING:
                continue
            p = req.seq.total_tokens - 1  # position of the not-yet-computed last token
            # cover the whole burst: positions p .. p+k-1, clamped to model len
            limit = self._grow_blocks(req, k_steps)
            if limit is None:
                continue  # not even the current token has a slot
            active.append(req)
            tokens[i] = req.seq.tokens[-1]
            positions[i] = p
            bt[i, : len(req.block_ids)] = req.block_ids
            seq_lens[i] = req.seq.total_tokens
            limits[i] = limit
            temp[i] = req.sampling.temperature
            top_k[i] = req.sampling.top_k
            top_p[i] = req.sampling.top_p

        if not active:
            return
        # growth allocations above may have evicted registered blocks that
        # this very dispatch writes into — offload them first
        step_timeline.mark("host_build")
        self._drain_offload()
        step_timeline.mark("kv_spill_restore")
        k_cand, exact = self._sampling_mode(active)
        pen = self._penalty_buffers(active, k_steps)
        gram = None
        if any(self._grammar_key(r) for r in active) \
                and self._ensure_grammar() is not None:
            keys = self._dispatch_keys(active)
            offs = self._composite_for(keys)[1]
            jrows = np.zeros(b, bool)
            jstate = np.full(b, INIT_STATE, np.int32)
            jdepth = np.zeros(b, np.int32)
            jstack = np.zeros(b, np.int32)
            for r in active:
                k = self._grammar_key(r)
                if k is not None:
                    jrows[r.slot] = True
                    gs, gd, gk = r.gstate
                    # request-relative state id -> composite id
                    jstate[r.slot] = gs + offs[k] if gs > 0 else gs
                    jdepth[r.slot], jstack[r.slot] = gd, gk
            gram = (keys, jrows, jstate, jdepth, jstack)
        sampled, lps, cids, clps = self._run_multi_decode_step(
            tokens, positions, bt, seq_lens, limits, temp, top_k, top_p,
            pen=pen, gram=gram,
            extras=self._sampling_extras(active, rows=[r.slot for r in active]),
            num_steps=k_steps, k_cand=k_cand, exact=exact,
        )  # [K, B], [K, B], [K, B, C], [K, B, C]
        self.decode_steps += sampled.shape[0]
        for req in active:
            slot = req.slot
            want_lp = req.sampling.logprobs or req.sampling.top_logprobs > 0
            # samples at/past the limit wrote no KV — not appendable
            allowed = min(sampled.shape[0], int(limits[slot] - positions[slot]))
            for k in range(allowed):
                if req.state is not RequestState.RUNNING:
                    break  # EOS/stop/max_tokens hit mid-burst
                self._append_token(
                    req, int(sampled[k, slot]),
                    logprob=float(lps[k, slot]) if want_lp else None,
                    cand=(cids[k, slot], clps[k, slot]) if want_lp else None,
                )
            if req.state is RequestState.RUNNING and allowed < sampled.shape[0]:
                # block space exhausted before the burst ended
                self._finish_slot(req, FinishReason.LENGTH)

    def _penalty_buffers(self, active, k_steps: int):
        """Build the generated-token penalty buffers for this dispatch, or
        None when no active request uses penalties (the common case pays
        nothing — ``use_penalties`` is a static jit arg).

        [B, T] token buffer (-1 pad) + first-occurrence mask + per-row
        cursor; T is power-of-two bucketed over (max generated + burst) so
        the executable count stays O(log max_model_len)."""
        if not any(
            r.sampling.frequency_penalty or r.sampling.presence_penalty
            for r in active
        ):
            return None
        b = self.config.max_batch_size
        longest = max(r.seq.total_tokens - r.prompt_len for r in active)
        t_cap = max(16, 1 << (longest + k_steps - 1).bit_length())
        t_cap = min(t_cap, max(16, 1 << (self.config.max_model_len - 1).bit_length()))
        ptoks = np.full((b, t_cap), -1, np.int32)
        pfirst = np.zeros((b, t_cap), bool)
        cursor = np.zeros(b, np.int32)
        freq = np.zeros(b, np.float32)
        pres = np.zeros(b, np.float32)
        for r in active:
            i = r.slot
            gen = r.seq.tokens[r.prompt_len:]
            n = min(len(gen), t_cap)
            seen: set[int] = set()
            for j, t in enumerate(gen[:n]):
                ptoks[i, j] = t
                if t not in seen:
                    pfirst[i, j] = True
                    seen.add(t)
            cursor[i] = n
            freq[i] = r.sampling.frequency_penalty
            pres[i] = r.sampling.presence_penalty
        return ptoks, pfirst, cursor, freq, pres

    # ------------------------------------------------------------- lifecycle
    def _append_token(self, req: EngineRequest, token: int, first: bool = False,
                      logprob: Optional[float] = None, cand=None) -> None:
        """Record a sampled token, emit the delta, apply stop conditions.

        The token's KV is *not* yet in the cache — it is computed by the next
        decode step (standard one-step lag).  A block completed by the
        previous token is committed here once its KV landed.
        """
        if req.abort_requested:
            self._finish_slot(req, FinishReason.CANCELLED)
            return
        # the previous tail token's KV just landed (one-step lag); if that
        # filled a block, the block is now fully resident — commit it
        kv_resident = req.seq.total_tokens  # tokens with KV in cache, pre-append
        if not first and kv_resident > 0 and kv_resident % self.config.block_size == 0:
            blk = req.seq.blocks[kv_resident // self.config.block_size - 1]
            if blk.position < len(req.block_ids):
                self.block_manager.commit(
                    req.block_ids[blk.position],
                    blk.sequence_hash,
                    blk.parent_sequence_hash,
                    list(blk.tokens),
                )
        req.seq.append(token)
        req.generated += 1
        self.tokens_generated += 1
        gkey = self._grammar_key(req)
        if gkey is not None and self._grammar is not None:
            # host mirror of the in-scan grammar advance (deterministic:
            # same tables, same sampled token; request-relative state ids)
            req.gstate = self._tables_for(gkey).advance(*req.gstate, token)

        finish: Optional[FinishReason] = None
        st = req.stops
        if token in self.eos_token_ids and not st.ignore_eos and req.generated >= st.min_tokens:
            finish = FinishReason.EOS
        elif token in st.stop_token_ids and req.generated >= st.min_tokens:
            finish = FinishReason.STOP
        elif st.max_tokens is not None and req.generated >= st.max_tokens:
            finish = FinishReason.LENGTH
        elif req.seq.total_tokens >= self.config.max_model_len:
            finish = FinishReason.LENGTH

        out = LLMEngineOutput(
            token_ids=[token], finish_reason=finish, cached_tokens=req.cached_tokens
        )
        if logprob is not None and (req.sampling.logprobs or req.sampling.top_logprobs):
            out.logprobs = [logprob]
            n = req.sampling.top_logprobs
            if n > 0 and cand is not None:
                ids, lps = cand
                out.top_logprobs = [
                    [(int(i), float(l)) for i, l in zip(ids[:n], lps[:n])]
                ]
        req.emit(out)
        if finish is not None:
            self._finish_slot(req, finish, emitted=True)

    def _finish_slot(self, req: EngineRequest, reason: FinishReason, emitted: bool = False) -> None:
        if req.slot >= 0 and self.slots[req.slot] is req:
            self.slots[req.slot] = None
            if self.draft is not None:
                self.draft.release(req.slot)
        self._pen_cache = None  # live request set changed
        # drop unresolved reservations (commit resolved the rest) so any
        # joiners waiting on us take over instead of hanging
        for h, bid in req.reserved_pairs:
            self.block_manager.unreserve(h, bid)
        req.reserved_pairs = []
        self.block_manager.release(req.block_ids)
        req.block_ids = []
        self._by_id.pop(req.request_id, None)
        req.state = RequestState.FINISHED
        req.finish_reason = reason
        if not emitted:
            req.emit(LLMEngineOutput(token_ids=[], finish_reason=reason,
                                     cached_tokens=req.cached_tokens))

    def _finish(self, req: EngineRequest, reason: FinishReason) -> None:
        """Finish a request that never got a slot."""
        req.state = RequestState.FINISHED
        req.finish_reason = reason
        req.emit(LLMEngineOutput(token_ids=[], finish_reason=reason))

    # ------------------------------------------------- disaggregation support
    # All of these run on the engine thread (call via run_on_step from
    # elsewhere).  They are the TPU-native replacement for the reference's
    # NIXL block read/write (vllm patch nixl.py) — device-side gather/scatter
    # with host staging for the DCN hop.

    def held_blocks(self, request_id: str) -> list[int]:
        """Block ids of a finished remote-decode prefill, still resident."""
        return list(self._held.get(request_id, ()))

    def release_held(self, request_id: str) -> None:
        """Transfer-out done: drop the prefill-side block references."""
        ids = self._held.pop(request_id, None)
        if ids:
            self.block_manager.release(ids)

    # --------------------------------------- streamed-handoff commit hooks
    def register_commit_hook(
        self, request_id: str, fn: Callable[[list[int], bool], None]
    ) -> None:
        """Streamed handoff (llm/kv/stream.py): call ``fn(block_ids,
        done)`` on the engine thread after each prefill chunk commits —
        ``block_ids`` is the CUMULATIVE list of this request's committed
        local block ids, ``done=True`` on the final call (which includes
        the partial tail block).  Per-layer callbacks are impossible
        under the jitted scan body, so chunk-boundary granularity is the
        documented fallback (docs/kv_streaming.md).  The hook is
        auto-unregistered after the ``done`` call."""
        self._commit_hooks[request_id] = fn

    def unregister_commit_hook(self, request_id: str) -> None:
        self._commit_hooks.pop(request_id, None)

    def _fire_commit_hook(self, req: EngineRequest, done: bool) -> None:
        fn = self._commit_hooks.get(req.request_id)
        if fn is None:
            return
        bs = self.config.block_size
        n = len(req.block_ids) if done else req.committed_upto // bs
        try:
            fn([int(b) for b in req.block_ids[:n]], done)
        except Exception:
            log.exception("commit hook failed for %s", req.request_id)
        if done:
            self._commit_hooks.pop(req.request_id, None)

    # ------------------------------------------------------ host offload tier
    @staticmethod
    def _persist_generation(model, cache_dtype) -> str:
        """Generation tag for the persistent KV tier: a stable hash of
        everything that determines block-file layout and validity —
        model architecture/dtype, cache dtype, block size.  Any change
        opens a fresh store generation and invalidates the old one."""
        import hashlib
        import json as _json

        mc = getattr(model, "config", None)
        if mc is not None and hasattr(mc, "__dict__"):
            ident = {k: repr(v) for k, v in sorted(vars(mc).items())}
        else:
            ident = {"model": repr(mc)}
        ident["__cache_dtype"] = str(cache_dtype)
        ident["__model_cls"] = type(model).__name__
        blob = _json.dumps(ident, sort_keys=True).encode()
        return hashlib.sha256(blob).hexdigest()[:16]

    def _flush_persist_events(self) -> None:
        """Forward queued persist-tier router events (engine thread only;
        the kv-offload thread enqueues, this drains into the publisher's
        sink which is not thread-safe)."""
        if self.persist_store is None:
            return
        sink = self.block_manager.event_sink
        while self._persist_events:
            ev = self._persist_events.popleft()
            if sink is not None:
                sink(ev)

    def _spill_to_persist(self, hashes: list[int], blocks) -> None:
        """Mirror a host-pool store batch into the persistent tier (runs
        on the kv-offload thread — fsync never blocks the engine loop)."""
        from dynamo_tpu.llm.kv.events import (
            TIER_PERSIST,
            KvRemovedEvent,
            KvStoredEvent,
        )

        try:
            wrote = self.persist_store.spill(hashes, blocks)
        except Exception:  # pragma: no cover - disk full etc; tier degrades
            log.exception("persist spill failed; tier continues without it")
            return
        if wrote:
            self._persist_events.append(
                KvStoredEvent(block_hashes=list(hashes), tier=TIER_PERSIST))
        removed = self.persist_store.drain_removed()
        if removed:
            self._persist_events.append(
                KvRemovedEvent(block_hashes=removed, tier=TIER_PERSIST))

    def _promote_from_persist(self, hashes: list[int]) -> int:
        """Load a persist-tier prefix host-side so the ordinary host-pool
        restore picks it up; returns how many blocks were promoted."""
        try:
            phit = self.persist_store.match_prefix(hashes)
            if not phit:
                return 0
            blocks = self.persist_store.load(phit)
        except KeyError:
            return 0  # raced an eviction / corrupt file — plain miss
        except Exception:  # pragma: no cover - keep admission alive
            log.exception("persist restore failed; treating as miss")
            return 0
        with self._offload_lock:
            self.host_pool.store(phit, blocks)
        return len(phit)

    def _drain_offload(self) -> None:
        """Offload just-evicted device blocks to the host pool.

        The on-device gather MUST dispatch before anything overwrites the
        evicted block ids (single device stream: dispatch order is
        execution order, so the snapshot wins the race by construction).
        The expensive half — device→host readback + host memcpy — runs on
        the kv-offload thread (the CopyStream analogue, kv/layer.rs:619),
        so a request's TTFT never includes another conversation's store.
        """
        if self.host_pool is None:
            return
        self._flush_persist_events()
        if not self._pending_offload:
            return
        pending, self._pending_offload = self._pending_offload, []
        with self._offload_lock:
            # re-evictions of host-resident content only need an LRU
            # refresh — skip the HBM gather for them
            self.host_pool.touch(
                [h for _, h in pending if h in self.host_pool])
            fresh = [(b, h) for b, h in pending if h not in self.host_pool]
        if not fresh:
            return
        bids = [b for b, _ in fresh]
        hashes = [h for _, h in fresh]
        arr = self.gather_blocks_device(bids)    # on-device snapshot
        queued = False
        with self._offload_lock:
            # flag check + enqueue are atomic with close()'s flag set, so
            # a batch can never land behind the shutdown sentinel (where
            # it would be silently dropped and hang a later flush)
            budget = self.config.offload_inflight_blocks
            if not self._offload_closed and (
                self._offload_inflight_blocks + len(bids) <= budget
                # never starve: an oversized single batch may queue alone
                or self._offload_inflight_blocks == 0
            ):
                try:
                    self._offload_q.put_nowait((hashes, arr))
                    self._offload_inflight_blocks += len(bids)
                    queued = True
                except queue.Full:
                    pass  # backpressure: the staging arrays pin HBM
        if not queued:
            # closed, full, or over the block budget — store synchronously
            # so no batch is lost and no further HBM is pinned
            self._store_offload_batch(hashes, arr)

    def _store_offload_batch(self, hashes: list[int], arr) -> None:
        """Readback a gathered [L,n,2,Bs,HkD] snapshot and store it
        host-side (runs on the kv-offload thread, or inline under
        backpressure / flush).

        Three-phase store: reserve (lock), write (NO lock — the bulk
        memcpy must not stall the engine thread's drain/restore behind
        this thread), publish (lock).  ``reserve`` skips hashes another
        in-flight batch already landed (LRU-refresh only), and
        ``publish`` frees rows that lost a store race."""
        np_arr = jax.device_get(arr)  # one batched transfer, numpy leaves
        blocks = jax.tree.map(lambda a: np.moveaxis(a, 1, 0), np_arr)
        with self._offload_lock:
            hids, rows = self.host_pool.reserve(hashes, blocks)
        if not hids:
            return
        try:
            self.host_pool.write_rows(hids, blocks, rows)
        except BaseException:
            with self._offload_lock:
                self.host_pool.abort(hids)  # don't leak reserved capacity
            raise
        with self._offload_lock:
            self.host_pool.publish(hids, [hashes[r] for r in rows])
        if self.persist_store is not None:
            # write-through: published content spills to disk here on the
            # offload thread, so a restart (or a replica pulling the
            # coordinator index) can restore it
            self._spill_to_persist(hashes, blocks)

    def _offload_worker(self) -> None:
        while True:
            item = self._offload_q.get()
            try:
                if item is None:
                    return
                self._store_offload_batch(*item)
            except Exception:  # pragma: no cover - keep the tier alive
                log.exception("async KV offload store failed")
            finally:
                if item is not None:
                    # the snapshot's HBM is released whether or not the
                    # store succeeded — retire its blocks from the
                    # backpressure budget even on failure, else the
                    # budget leaks and degrades every later store to sync
                    with self._offload_lock:
                        self._offload_inflight_blocks -= len(item[0])
                self._offload_q.task_done()

    def flush_host_offload(self) -> None:
        """Block until every queued offload store has landed (tests and
        benches that assert on host-pool contents)."""
        if self.host_pool is None:
            return
        self._drain_offload()
        self._offload_q.join()

    def close(self) -> None:
        """Stop the kv-offload thread (idempotent).  Without this an
        abandoned engine's daemon thread would pin the whole instance —
        params, cache, host pool — for process lifetime."""
        t = getattr(self, "_offload_thread", None)
        if t is not None and t.is_alive():
            # flag first (under the lock _drain_offload enqueues under):
            # after this, drains store inline — nothing can land behind
            # the sentinel.  The sentinel put happens OUTSIDE the lock:
            # it may block on a full queue until the worker drains, and
            # the worker needs the lock for its store phases.
            with self._offload_lock:
                self._offload_closed = True
            self._offload_q.put(None)
            t.join(timeout=30.0)
        self._offload_thread = None
        if getattr(self, "persist_store", None) is not None:
            self.persist_store.close()

    def _restore_from_host(self, req: EngineRequest) -> None:
        """Upload host-resident prefix blocks into the request's fresh
        device blocks, register them, and extend the cached prefix —
        turning a device cache miss into a host hit (TTFT win, ref
        docs/architecture.md:87-93).  Host-pool misses fall through to
        the persistent tier (llm/kv/persist.py): matched blocks are
        promoted host-side first, then ride the same gather/scatter/
        commit path, so a restored prefix is indistinguishable from a
        warm host hit downstream."""
        from dynamo_tpu.engine.counters import persist_counters

        bs = self.config.block_size
        dev = req.cached_tokens // bs
        max_blocks = (req.prompt_len - 1) // bs  # >=1 token must remain
        want = [b.sequence_hash for b in req.seq.blocks[dev:max_blocks]]
        if not want:
            return
        with self._offload_lock:
            host_hit = len(self.host_pool.match_prefix(want))
        promoted = 0
        if self.persist_store is not None and host_hit < len(want):
            promoted = self._promote_from_persist(want[host_hit:])
            if not promoted:
                persist_counters.record_miss()
        with self._offload_lock:
            # the kv-offload thread stores/evicts concurrently; a block
            # still in flight to the pool just misses here (re-prefilled
            # — correct, merely slower).  match+gather under ONE lock
            # hold: a matched block must not be evicted before gather.
            hit = self.host_pool.match_prefix(want)
            if not hit:
                return
            blocks = self.host_pool.gather(hit)  # [n, L, 2, Bs, HkD] (pytree)
        if promoted:
            restored = max(0, len(hit) - host_hit)
            if restored:
                persist_counters.record_restore(restored, restored * bs)
        target = req.block_ids[dev : dev + len(hit)]
        self.scatter_external(
            target, jax.tree.map(lambda a: np.moveaxis(a, 0, 1), blocks)
        )
        for i in range(len(hit)):
            blk = req.seq.blocks[dev + i]
            self.block_manager.commit(
                target[i], blk.sequence_hash, blk.parent_sequence_hash, list(blk.tokens)
            )
        req.cached_tokens += len(hit) * bs

    def gather_blocks_device(self, block_ids: list[int]) -> jax.Array:
        """Gather blocks WITHOUT leaving the device: returns a jax.Array
        [L, n, 2, Bs, HkD].  The colocated transfer fast path hands this
        straight to the target engine's scatter — the copy rides ICI (or
        stays on-chip), never touching host RAM (ref: NIXL device WRITE,
        vllm patch nixl.py +394; VERDICT r2 ask #8)."""
        return gather_blocks_padded(self.cache, block_ids)

    def gather_blocks_np(self, block_ids: list[int]):
        """Stage blocks to host RAM: [L, n, 2, Bs, HkD] ndarray (a
        (data, scale) pair of ndarrays for the int8 cache).  Under a
        sharded mesh this all-gathers KV heads — which is exactly the
        TP-resharding the reference needs a Triton kernel for
        (kv_rearrange.py); here the host staging buffer is layout-neutral."""
        out = gather_blocks_padded(self.cache, block_ids)
        return jax.device_get(out)  # one batched transfer, numpy leaves

    def scatter_external(
        self,
        block_ids: list[int],
        blocks: np.ndarray,
        request_id: Optional[str] = None,
    ) -> None:
        """Write transferred blocks into this engine's cache (in place).

        When ``request_id`` is given (remote-prefill ingest), the write is
        validated against that request's live block ownership: if the
        request was aborted meanwhile its blocks may already belong to
        someone else, and a late write must be dropped, not applied.
        """
        if request_id is not None:
            req = self._by_id.get(request_id)
            if (
                req is None
                or req.state is not RequestState.REMOTE_PREFILL
                or not set(block_ids) <= set(req.block_ids)
            ):
                log.warning(
                    "dropping stale KV write for %s (request gone or blocks reassigned)",
                    request_id,
                )
                return
        # `blocks` mirrors the cache pytree (ndarray, or data+scale pair
        # from a quantized peer); structure mismatch = config error
        from dynamo_tpu.ops.kv_quant import QuantKvCache

        if self.cache_quant and type(blocks) is tuple and len(blocks) == 2:
            blocks = QuantKvCache(*blocks)  # wire tuples -> cache pytree
        if self.mesh is not None:
            # shard the staged blocks like the pool so the donated scatter
            # preserves the cache sharding (no step-fn recompiles) — this IS
            # the TP-reshard on ingest (each shard keeps only its heads);
            # ONE device_put straight from host (uploading to the default
            # device first would transfer twice)
            arr = jax.device_put(blocks, self._cache_sharding())
        else:
            arr = jax.device_put(blocks)  # one batched upload, all leaves
        self.cache = scatter_blocks_inplace(self.cache, block_ids, arr)

    def complete_remote_prefill(
        self, request_id: str, first_token: int, error: Optional[str] = None
    ) -> None:
        """Prefill-done notification: the request's KV is now resident in
        this engine's cache; append the prefill-sampled first token and
        enter decode.  (Ref: scheduler stall-until-notified, vllm patch
        scheduler.py hunks + worker.py:212.)"""
        req = self._by_id.get(request_id)
        if req is None or req.state is not RequestState.REMOTE_PREFILL:
            return  # cancelled/finished while prefill ran elsewhere
        if error is not None:
            self._finish_slot(req, FinishReason.ERROR)
            return
        req.computed_tokens = req.prompt_len
        req.state = RequestState.RUNNING
        for blk in req.seq.blocks:
            bid = req.block_ids[blk.position]
            self.block_manager.commit(
                bid, blk.sequence_hash, blk.parent_sequence_hash, list(blk.tokens)
            )
        self._append_token(req, int(first_token), first=True)

    def prefix_hit_tokens(self, seq_hashes: list[int], prompt_len: int) -> int:
        """How many prompt tokens would hit the local prefix cache — the
        disagg router's prefix_hit_length input.

        Read-only dict probes (GIL-atomic), safe to call from any thread; a
        concurrently-mutating engine can make the answer slightly stale,
        which only perturbs the routing heuristic, never correctness."""
        return len(
            self.block_manager.match_prefix(seq_hashes, prompt_len)
        ) * self.config.block_size

    def persist_hit_blocks(self, seq_hashes: list[int]) -> int:
        """How many prompt blocks the persist tier could restore locally —
        the transfer-aware router's stream-vs-restore cost input.  0 when
        no persist tier is configured.  Same staleness caveat as
        :meth:`prefix_hit_tokens`: a heuristic input, not a guarantee."""
        if self.persist_store is None or not seq_hashes:
            return 0
        try:
            return len(self.persist_store.match_prefix(list(seq_hashes)))
        except Exception:  # pragma: no cover - probe must never raise
            return 0

    def kv_bytes_per_block(self) -> int:
        """Host-staged wire bytes one KV block occupies (all layers, both
        K and V, all parts of a quantized pair) — the router's
        transfer-cost size input.  Derived from the live cache pytree so
        quantization/dtype changes are automatically reflected."""
        leaves = jax.tree.leaves(self.cache)
        # cache leaves are [L, n_blocks, ...]: bytes per block = leaf
        # bytes / n_blocks, summed over parts
        return sum(int(l.nbytes) // max(1, int(l.shape[1])) for l in leaves)
