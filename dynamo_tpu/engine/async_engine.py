"""AsyncLLMEngine — asyncio front door over the engine thread.

Implements the runtime's AsyncEngine contract (generate(Context[BackendInput])
→ stream of LLMEngineOutput) so the engine slots directly into pipelines,
the HTTP service, and distributed endpoints.  The engine core runs on its
own thread (JAX dispatch blocks); tokens cross back via
loop.call_soon_threadsafe into per-request asyncio queues.

Cancellation: a stopped/killed Context aborts the request in the core at
the next step boundary (reference: AsyncEngineContext::stop_generating
carried as ControlMessage::{Stop,Kill}, lib/runtime/src/engine.rs:76-84).
"""

from __future__ import annotations

import asyncio
import logging
import threading
from typing import AsyncIterator

from dynamo_tpu.engine.core import EngineCore
from dynamo_tpu.engine.request import EngineRequest
from dynamo_tpu.llm.protocols import BackendInput, LLMEngineOutput
from dynamo_tpu.obs import tracing
from dynamo_tpu.runtime.engine import AsyncEngine, Context

log = logging.getLogger("dynamo_tpu.engine")

__all__ = ["AsyncLLMEngine"]


class AsyncLLMEngine(AsyncEngine):
    def __init__(self, core: EngineCore):
        self.core = core
        self._wake = threading.Event()
        self._shutdown = False
        self._thread: threading.Thread | None = None

    # --------------------------------------------------------------- lifecycle
    def start(self) -> "AsyncLLMEngine":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="engine-core", daemon=True
            )
            self._thread.start()
        return self

    def shutdown(self) -> None:
        self._shutdown = True
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        if hasattr(self.core, "close"):
            self.core.close()  # stop the kv-offload thread, if any

    def _run(self) -> None:
        while not self._shutdown:
            try:
                did_work = self.core.step()
            except Exception:
                log.exception("engine step failed; failing in-flight requests")
                self.core.fail_all()
                did_work = False
            if not did_work:
                self._wake.wait(timeout=0.05)
                self._wake.clear()

    async def run_on_engine(self, fn):
        """Run ``fn`` on the engine thread at a step boundary (cache/block
        bookkeeping must stay single-writer); await its result."""
        fut = self.core.run_on_step(fn)
        self._wake.set()
        return await asyncio.wrap_future(fut)

    # ---------------------------------------------------------------- generate
    def generate(self, request: Context[BackendInput]) -> AsyncIterator[LLMEngineOutput]:
        return self._generate(request)

    def generate_ex(
        self,
        request: Context[BackendInput],
        *,
        remote_prefill: bool = False,
        remote_decode: bool = False,
        on_allocated=None,
    ) -> AsyncIterator[LLMEngineOutput]:
        """generate() with disaggregation knobs (ref RemotePrefillParams,
        vllm patch remote_prefill.py): ``remote_prefill`` stalls the request
        until a prefill worker delivers KV; ``remote_decode`` runs prefill
        only and holds the blocks for transfer-out."""
        return self._generate(
            request,
            remote_prefill=remote_prefill,
            remote_decode=remote_decode,
            on_allocated=on_allocated,
        )

    async def _generate(
        self,
        request: Context[BackendInput],
        *,
        remote_prefill: bool = False,
        remote_decode: bool = False,
        on_allocated=None,
    ) -> AsyncIterator[LLMEngineOutput]:
        inp = request.data
        loop = asyncio.get_running_loop()
        out_q: asyncio.Queue[LLMEngineOutput] = asyncio.Queue()

        def emit(out: LLMEngineOutput) -> None:
            loop.call_soon_threadsafe(out_q.put_nowait, out)

        # dtspan: one span per engine-side generation, parented on the
        # caller's context (HTTP root span or a TCP server hop) so the
        # frontend's trace id continues through the engine.  The engine
        # thread has no ambient contextvar — req.trace carries the pair.
        span = tracing.start_span(
            "engine.generate", attrs={"request_id": request.id})
        req = EngineRequest(
            request_id=request.id,
            prompt=list(inp.token_ids),
            sampling=inp.sampling,
            stops=inp.stops,
            emit=emit,
            remote_prefill=remote_prefill,
            remote_decode=remote_decode,
            on_allocated=on_allocated,
            trace=span.context(),
        )
        if tracing.enabled():
            tracing.collector.bind_request(request.id, span.trace_id)
        self.core.submit(req)
        self._wake.set()

        cancel_task = asyncio.ensure_future(request.stopped())
        get_task: asyncio.Future | None = None
        try:
            while True:
                get_task = asyncio.ensure_future(out_q.get())
                done, _ = await asyncio.wait(
                    [get_task, cancel_task], return_when=asyncio.FIRST_COMPLETED
                )
                if get_task in done:
                    out = get_task.result()
                    if (req.queue_wait_s is not None
                            and "queue_wait_s" not in request.annotations):
                        # surface admission wait for the HTTP histogram
                        request.annotations["queue_wait_s"] = req.queue_wait_s
                    yield out
                    if out.finished:
                        return
                else:
                    get_task.cancel()
                    self.core.abort(req.request_id)
                    self._wake.set()
                    # drain until the core confirms cancellation
                    while True:
                        out = await out_q.get()
                        yield out
                        if out.finished:
                            return
        finally:
            # a consumer abandoning the stream lands here from the
            # `await asyncio.wait` — without the cancel, get_task stays
            # pending on out_q.get() forever (dtsan task leak)
            if get_task is not None and not get_task.done():
                get_task.cancel()
            cancel_task.cancel()
            if not request.is_stopped and req.finish_reason is None:
                # consumer dropped the stream mid-generation
                self.core.abort(req.request_id)
                self._wake.set()
            span.set(
                finish=str(req.finish_reason) if req.finish_reason else "",
                queue_wait_s=req.queue_wait_s or 0.0,
            ).end()
