"""Engine configuration: batching, cache sizing, bucketing, sharding."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional


def default_buckets(max_len: int) -> list[int]:
    """Powers of two up to max_len (prefill padding buckets)."""
    out = []
    b = 16
    while b < max_len:
        out.append(b)
        b *= 2
    out.append(max_len)
    return out


@dataclass
class EngineConfig:
    # batching
    max_batch_size: int = 8           # decode slots (static shape)
    max_model_len: int = 2048
    # decode tokens generated per device dispatch (multi-step scheduling);
    # >1 amortises dispatch overhead at the cost of stop-condition
    # granularity (up to decode_steps-1 discarded samples per request)
    decode_steps: int = 1
    # chunked prefill: max prompt tokens computed per prefill dispatch
    # (0 = whole remainder in one step).  Bounding the chunk keeps decode
    # ITL flat while long prompts prefill — the scheduler alternates one
    # prefill chunk with one decode burst when both have work (the
    # reference gets this from vLLM's chunked-prefill scheduler; ours is
    # native).  Rounded down to a block multiple so resumed chunks stay
    # block-aligned for the prefill fast path.
    prefill_chunk_tokens: int = 0
    # token-budget ragged prefill: pack the prefill chunks of SEVERAL
    # pending requests into one flat-token-axis dispatch of at most this
    # many tokens (each request's chunk occupies a block-aligned span; the
    # flat axis is bucketed via bucket_for so executables stay O(log)).
    # Converts a backlog of N short prompts from N device round-trips to
    # ~ceil(total_tokens / budget) dispatches.  0 = legacy one-request-
    # per-dispatch prefill.  Rounded down to a block multiple; capped at
    # max_model_len (the largest prefill bucket).
    prefill_token_budget: int = 0
    # unified mixed prefill+decode dispatch: when BOTH phases have work,
    # run ONE token-budget ragged step per turn — decode rows (1 token
    # each) lead the flat axis, waiting prefill chunks pack into the
    # remaining prefill_token_budget.  Replaces the chunked-prefill
    # alternation (one device round-trip per phase switch) with a single
    # dispatch per turn; decode-only turns keep the multi-step burst and
    # prefill-only turns the ragged batch.  Requires a model with the
    # ragged forward path; prefill_token_budget defaults on when unset.
    # Default off until parity-gated (tests/test_unified_dispatch.py
    # pins seeded-stream parity vs the legacy paths).
    unified_token_dispatch: bool = False
    # double-buffered dispatch (lookahead scheduler): overlap next-turn
    # host scheduling with device compute.  Mixed prefill+decode turns
    # fuse interactive_decode_steps unified turns into ONE dispatch with
    # on-device stop/append (a burst needs a single trailing device_get),
    # and while the device computes, the host speculatively prebuilds
    # the NEXT turn's dispatch operands from predicted token counts
    # (every active decode row yields exactly 1 token/turn unless a stop
    # fires) — committed if the prediction held, flushed on mismatch.
    # Implies unified_token_dispatch.  Default off until parity-gated
    # (tests/test_lookahead_dispatch.py pins seeded-stream parity).
    lookahead_dispatch: bool = False
    # decode burst length while prefill work is pending (admitted/waiting
    # requests or a mid-prefill slot).  Long bursts amortise dispatch
    # overhead but make a freshly-arrived prompt wait a whole burst
    # (decode_steps * ITL ≈ 760ms at 64 steps) before its first chunk —
    # the dominant term in VERDICT r2's TTFT miss.  0 = min(8, decode_steps).
    interactive_decode_steps: int = 0
    # prompt-lookup speculative decoding (engine/spec.py): propose up to
    # spec_tokens continuation tokens by n-gram match against the sequence
    # itself and verify them in ONE dispatch.  Greedy-exact; engages only
    # for dispatches where every active request is plain greedy (no
    # penalties/logprobs/bias/min_p/JSON mode).  0 = off.
    spec_tokens: int = 0
    spec_ngram: int = 3
    # draft-model speculation (engine/draft.py): block count of the
    # draft's own paged cache.  0 = same count as the target's — shrink
    # it on HBM-tight deployments (the draft cache costs
    # L_draft/L_target of the target cache at equal counts).
    draft_num_blocks: int = 0
    # sequence-parallel (ring attention) prefill: prompts at least this
    # long (with no cached prefix) prefill in ONE dispatch with the
    # sequence sharded over the mesh's "data" axis — context parallelism
    # for prompts beyond a single chip's comfort.  0 = disabled; requires
    # an engine mesh whose "data" axis is > 1.
    sp_prefill_threshold: int = 0
    # paged cache
    block_size: int = 16
    num_blocks: int = 512             # cache blocks in HBM
    num_host_blocks: int = 0          # host-RAM offload tier (0 = disabled)
    # async-offload HBM backpressure: total device blocks that may sit in
    # queued gather snapshots awaiting the device→host readback.  A batch
    # that would push the outstanding count past this budget stores
    # synchronously instead (each queued snapshot pins its blocks' HBM —
    # a burst of large evictions must not pin hundreds of MB)
    offload_inflight_blocks: int = 256
    # persistent prefix-cache tier (llm/kv/persist.py): directory for the
    # content-addressed block store.  None/"" = disabled (the default).
    # Requires num_host_blocks > 0 — spill and restore both stage through
    # the host pool.  Blocks published to the host pool spill here
    # asynchronously; host-pool misses on admission fall through to this
    # tier, so a restart (same dir) or a replicated index re-enters warm
    # prefixes as cached_tokens.
    kv_persist_dir: Optional[str] = None
    # size cap for the persistent store (LRU by last-touch at block-group
    # file granularity); 0 = unbounded
    kv_persist_max_bytes: int = 0
    # TTL for persisted block groups since last touch; 0 = no expiry
    kv_persist_ttl_s: float = 0.0
    # KV cache dtype: None = model dtype; "int8" = quantized cache with
    # per-token-per-head scales (ops/kv_quant.py) — half the KV HBM
    # footprint and decode-step KV traffic
    cache_dtype: Optional[str] = None
    enable_prefix_reuse: bool = True
    # force exact lax.top_k candidate selection in the sampler (the default
    # approx_max_k path is exact for greedy and ~0.95-recall for the deep
    # tail; requests with top_k > 64 switch to exact automatically)
    exact_sampling: bool = False
    # prefill
    prefill_buckets: list[int] = field(default_factory=list)
    # sharding: data/model axis sizes; 1,1 = single chip
    mesh_shape: tuple[int, int] = (1, 1)
    # dtspan profile hook: when profile_dir is set, the engine wraps the
    # first profile_steps device steps in ONE jax.profiler capture
    # written under profile_dir/steps-<first step id>/ (CLI:
    # --profile-dir / --profile-steps on serve/http)
    profile_dir: Optional[str] = None
    profile_steps: int = 8
    # rng
    seed: int = 0

    def __post_init__(self):
        if not self.prefill_buckets:
            self.prefill_buckets = default_buckets(self.max_model_len)
        self.prefill_buckets = sorted(self.prefill_buckets)
        if self.interactive_decode_steps <= 0:
            self.interactive_decode_steps = min(8, max(1, self.decode_steps))
        self.interactive_decode_steps = min(
            self.interactive_decode_steps, max(1, self.decode_steps)
        )
        if self.prefill_chunk_tokens:
            # block-align the chunk so every resumed chunk starts on a block
            # boundary (required by the prefill fast path)
            self.prefill_chunk_tokens = max(
                self.block_size,
                self.prefill_chunk_tokens // self.block_size * self.block_size,
            )
        if self.lookahead_dispatch and not self.unified_token_dispatch:
            # the lookahead scheduler is a layer over unified dispatch:
            # the fused burst generalizes the unified mixed step, so the
            # flag implies it (and inherits its budget defaulting below)
            self.unified_token_dispatch = True
        if self.unified_token_dispatch and not self.prefill_token_budget:
            # the unified scheduler packs under prefill_token_budget; a
            # bare --unified-token-dispatch gets a sensible default
            # rather than silently staying on the legacy paths
            self.prefill_token_budget = min(1024, self.max_model_len)
        if self.prefill_token_budget:
            # block-align (spans in the packed axis are block multiples)
            # and cap at the largest prefill bucket — bucket_for pads the
            # flat axis, so a budget past max_model_len could never fill
            self.prefill_token_budget = max(
                self.block_size,
                self.prefill_token_budget // self.block_size * self.block_size,
            )
            self.prefill_token_budget = min(
                self.prefill_token_budget, self.max_model_len
            )

    @property
    def max_blocks_per_seq(self) -> int:
        return -(-self.max_model_len // self.block_size)

    def bucket_for(self, n: int) -> int:
        for b in self.prefill_buckets:
            if n <= b:
                return b
        raise ValueError(f"sequence length {n} exceeds max_model_len {self.max_model_len}")
