"""Grammar-constrained decoding: JSON mode that runs INSIDE the decode scan.

OpenAI ``response_format={"type": "json_object"}`` guarantees the model
emits syntactically valid JSON.  The reference delegates this to its
engines' guided-decoding (vLLM/outlines run a host-side FSM between
steps); that design needs a host round-trip per token, which would defeat
this engine's multi-step decode scan (K tokens per device dispatch).

TPU-native design — the automaton itself is device-computable:

* A byte-level DFA for the JSON lexical grammar whose states carry the
  *current container context* (top-level / object / array), plus a
  bounded pushdown for bracket matching: depth counter + an int32
  bit-stack (1 bit per nesting level: OBJ or ARR, max depth 24).
* Per tokenizer, every (state, token) transition is precomputed by
  composing the token's bytes symbolically (pops/pushes normalise to
  "pop a prefix, then push a suffix").  The result is dense ``[S, V]``
  tables — next state (int16: composed grammars exceed 127 states), pop
  count/bits, push count/bits (int8) — ~60MB HBM for a 128k vocab,
  uploaded once on first use.
* At each decode step the valid-token mask for a row is pure vectorised
  arithmetic: a table-row gather + bit compares against the row's
  (state, depth, stack) — no host interaction, so JSON mode rides the
  ``lax.scan`` decode burst at full speed.  After sampling, the row's
  automaton state advances via scalar gathers in the same scan.
* Tokens whose byte behaviour would depend on stack content *below* the
  levels they pop (e.g. ``},`` — the comma's meaning depends on the
  container we pop into) are conservatively masked; every JSON
  construct remains expressible through shorter tokens (all single-byte
  JSON punctuation exists in any BPE vocab).

Reference parity: response_format in lib/llm/src/protocols/openai
(chat_completions request surface); enforcement is engine-side here
because this repo owns the engine.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np

__all__ = [
    "JsonGrammar", "VocabTables", "token_bytes_map", "MAX_DEPTH",
    "INIT_STATE", "DEAD", "compile_choice_vocab", "compile_regex_vocab",
    "compose_tables", "json_schema_to_regex",
]

MAX_DEPTH = 24          # nesting levels the int32 bit-stack holds
MAX_TOKEN_OPS = 7       # per-token pop/push bound (3 bits each in tables)
# next_state value meaning "landed in a popped-into container whose type the
# runtime resolves against the stack".  Negative so it can never collide
# with a composed grammar's (positive, offset-shifted) state ids.
SENTINEL = -1

# --------------------------------------------------------------------------
# state space
#
# Contexts: T (top level), O (inside object), A (inside array).  U is the
# transient "popped into unknown container" context — it only appears
# mid-token or as a sentinel end-state that the runtime resolves against
# the real stack.
DEAD = 0

_CONTEXTS = ("T", "O", "A")
_NAMES: list[str] = ["DEAD"]


def _st(name: str) -> int:
    _NAMES.append(name)
    return len(_NAMES) - 1


# value-position states, per context
EXPECT_VALUE = {c: _st(f"EXPECT_VALUE_{c}") for c in _CONTEXTS}
AFTER_VALUE = {c: _st(f"AFTER_VALUE_{c}") for c in _CONTEXTS}
AFTER_VALUE_U = _st("AFTER_VALUE_U")  # sentinel: context resolved at runtime
# strings (value position), per context
IN_STR = {c: _st(f"IN_STR_{c}") for c in _CONTEXTS}
STR_ESC = {c: _st(f"STR_ESC_{c}") for c in _CONTEXTS}
STR_U = {c: [_st(f"STR_U{i}_{c}") for i in range(1, 5)] for c in _CONTEXTS}
# numbers, per context
NUM_MINUS = {c: _st(f"NUM_MINUS_{c}") for c in _CONTEXTS}
NUM_ZERO = {c: _st(f"NUM_ZERO_{c}") for c in _CONTEXTS}
NUM_INT = {c: _st(f"NUM_INT_{c}") for c in _CONTEXTS}
NUM_DOT = {c: _st(f"NUM_DOT_{c}") for c in _CONTEXTS}
NUM_FRAC = {c: _st(f"NUM_FRAC_{c}") for c in _CONTEXTS}
NUM_E = {c: _st(f"NUM_E_{c}") for c in _CONTEXTS}
NUM_ESIGN = {c: _st(f"NUM_ESIGN_{c}") for c in _CONTEXTS}
NUM_EXP = {c: _st(f"NUM_EXP_{c}") for c in _CONTEXTS}
# literals true/false/null: one state per remaining-suffix position
_LITS = {"true": "rue", "false": "alse", "null": "ull"}
LIT = {
    c: {w: [_st(f"LIT_{w}{i}_{c}") for i in range(len(suf))]
        for w, suf in _LITS.items()}
    for c in _CONTEXTS
}
# object structure (context is implicitly O)
OBJ_OPEN = _st("OBJ_OPEN")          # after '{': key or '}'
OBJ_EXPECT_KEY = _st("OBJ_EXPECT_KEY")  # after ',': key only
IN_KEY = _st("IN_KEY")
KEY_ESC = _st("KEY_ESC")
KEY_U = [_st(f"KEY_U{i}") for i in range(1, 5)]
AFTER_KEY = _st("AFTER_KEY")        # expect ':'
# array structure (context is implicitly A)
ARR_OPEN = _st("ARR_OPEN")          # after '[': value or ']'

N_STATES = len(_NAMES)
INIT_STATE = EXPECT_VALUE["T"]

# stack symbols (1 bit per level)
SYM_OBJ, SYM_ARR = 1, 0

# byte-transition ops
OP_NONE, OP_PUSH_OBJ, OP_PUSH_ARR, OP_POP = 0, 1, 2, 3

_WS = b" \t\n\r"
_DIGITS = b"0123456789"
_HEX = b"0123456789abcdefABCDEF"


def _build_delta() -> tuple[np.ndarray, np.ndarray]:
    """(delta_state [S,256] int16, delta_op [S,256] int8); DEAD = invalid."""
    ds = np.zeros((N_STATES, 256), np.int16)  # DEAD
    op = np.zeros((N_STATES, 256), np.int8)

    def t(s: int, byte: int, ns: int, o: int = OP_NONE) -> None:
        ds[s, byte], op[s, byte] = ns, o

    def ws_loop(s: int) -> None:
        for b in _WS:
            t(s, b, s)

    def value_start(s: int, c: str) -> None:
        """Transitions for a value-start position whose *new* values live
        in context c (i.e. pushes land the state in the opened container,
        scalars land in c's string/number states)."""
        t(s, ord("{"), OBJ_OPEN, OP_PUSH_OBJ)
        t(s, ord("["), ARR_OPEN, OP_PUSH_ARR)
        t(s, ord('"'), IN_STR[c])
        t(s, ord("-"), NUM_MINUS[c])
        t(s, ord("0"), NUM_ZERO[c])
        for b in _DIGITS[1:]:
            t(s, b, NUM_INT[c])
        for w, suf in _LITS.items():
            t(s, ord(w[0]), LIT[c][w][0])

    def value_end(s: int, c: str) -> None:
        """Transitions available where a value has just ended in context
        c: ',' continues the container, '}'/']' pop it."""
        if c == "O":
            t(s, ord(","), OBJ_EXPECT_KEY)
            t(s, ord("}"), AFTER_VALUE_U, OP_POP)
        elif c == "A":
            t(s, ord(","), EXPECT_VALUE["A"])
            t(s, ord("]"), AFTER_VALUE_U, OP_POP)
        # c == "T": nothing to continue; EOS only (runtime eos_ok)

    for c in _CONTEXTS:
        ev, av = EXPECT_VALUE[c], AFTER_VALUE[c]
        ws_loop(ev)
        value_start(ev, c)
        ws_loop(av)
        value_end(av, c)
        # strings: any byte >= 0x20 except '"' and '\' stays (UTF-8
        # continuation bytes included; JSON forbids raw control chars)
        for s_in, s_esc, s_u, done in (
            (IN_STR[c], STR_ESC[c], STR_U[c], av),
        ):
            for b in range(0x20, 256):
                t(s_in, b, s_in)
            t(s_in, ord("\\"), s_esc)
            t(s_in, ord('"'), done)
            for b in b'"\\/bfnrt':
                t(s_esc, b, s_in)
            t(s_esc, ord("u"), s_u[0])
            for i in range(4):
                nxt = s_in if i == 3 else s_u[i + 1]
                for b in _HEX:
                    t(s_u[i], b, nxt)
        # numbers
        for b in _DIGITS[1:]:
            t(NUM_MINUS[c], b, NUM_INT[c])
        t(NUM_MINUS[c], ord("0"), NUM_ZERO[c])
        for s_num in (NUM_ZERO[c], NUM_INT[c], NUM_FRAC[c], NUM_EXP[c]):
            # implicit number end: whitespace or container punctuation
            for b in _WS:
                t(s_num, b, av)
            value_end(s_num, c)
        for b in _DIGITS:
            t(NUM_INT[c], b, NUM_INT[c])
            t(NUM_DOT[c], b, NUM_FRAC[c])
            t(NUM_FRAC[c], b, NUM_FRAC[c])
            t(NUM_ESIGN[c], b, NUM_EXP[c])
            t(NUM_E[c], b, NUM_EXP[c])
            t(NUM_EXP[c], b, NUM_EXP[c])
        for s_num in (NUM_ZERO[c], NUM_INT[c]):
            t(s_num, ord("."), NUM_DOT[c])
        for s_num in (NUM_ZERO[c], NUM_INT[c], NUM_FRAC[c]):
            t(s_num, ord("e"), NUM_E[c])
            t(s_num, ord("E"), NUM_E[c])
        for b in b"+-":
            t(NUM_E[c], b, NUM_ESIGN[c])
        # literals
        for w, suf in _LITS.items():
            chain = LIT[c][w]
            for i, ch in enumerate(suf):
                nxt = av if i == len(suf) - 1 else chain[i + 1]
                t(chain[i], ord(ch), nxt)

    # object keys
    ws_loop(OBJ_OPEN)
    t(OBJ_OPEN, ord('"'), IN_KEY)
    t(OBJ_OPEN, ord("}"), AFTER_VALUE_U, OP_POP)
    ws_loop(OBJ_EXPECT_KEY)
    t(OBJ_EXPECT_KEY, ord('"'), IN_KEY)
    for b in range(0x20, 256):
        t(IN_KEY, b, IN_KEY)
    t(IN_KEY, ord("\\"), KEY_ESC)
    t(IN_KEY, ord('"'), AFTER_KEY)
    for b in b'"\\/bfnrt':
        t(KEY_ESC, b, IN_KEY)
    t(KEY_ESC, ord("u"), KEY_U[0])
    for i in range(4):
        nxt = IN_KEY if i == 3 else KEY_U[i + 1]
        for b in _HEX:
            t(KEY_U[i], b, nxt)
    ws_loop(AFTER_KEY)
    t(AFTER_KEY, ord(":"), EXPECT_VALUE["O"])

    # arrays
    ws_loop(ARR_OPEN)
    value_start(ARR_OPEN, "A")
    t(ARR_OPEN, ord("]"), AFTER_VALUE_U, OP_POP)

    # sentinel context: only whitespace and further pops are
    # context-independent; anything else mid-token is conservatively dead
    ws_loop(AFTER_VALUE_U)
    t(AFTER_VALUE_U, ord("}"), AFTER_VALUE_U, OP_POP)
    t(AFTER_VALUE_U, ord("]"), AFTER_VALUE_U, OP_POP)

    return ds, op


_DELTA_STATE, _DELTA_OP = _build_delta()

# states where a complete top-level JSON value has been produced: EOS is
# the only allowed continuation (no whitespace padding after completion)
_EOS_OK = np.zeros(N_STATES, bool)
_EOS_OK[AFTER_VALUE["T"]] = True
for _s in (NUM_ZERO["T"], NUM_INT["T"], NUM_FRAC["T"], NUM_EXP["T"]):
    _EOS_OK[_s] = True
# completed-value states: once reached at top level, every byte mask goes
# dead (enforced at runtime via eos-only override rather than in delta,
# because mid-token trailing whitespace like '0\n' must still compose)
_TERMINAL_ONLY = np.zeros(N_STATES, bool)
_TERMINAL_ONLY[AFTER_VALUE["T"]] = True


@dataclass
class VocabTables:
    """Per-tokenizer compiled transition tables (host numpy; the engine
    uploads them to device on first use)."""

    next_state: np.ndarray   # [S, V] int16; DEAD = token invalid from state
    npops: np.ndarray        # [S, V] int8
    popbits: np.ndarray      # [S, V] int8  (bit npops-1-i = i-th pop, top first)
    npush: np.ndarray        # [S, V] int8
    pushbits: np.ndarray     # [S, V] int8  (bit j = j-th push, bottom first)
    eos_ok: np.ndarray       # [S] bool
    terminal_only: np.ndarray  # [S] bool
    eos_ids: tuple[int, ...]

    @property
    def n_states(self) -> int:
        return self.next_state.shape[0]

    @property
    def vocab_size(self) -> int:
        return self.next_state.shape[1]

    # ------------------------------------------------------------- host side
    def valid_mask(self, state: int, depth: int, stack: int) -> np.ndarray:
        """[V] bool valid-token mask for one row (host mirror of the
        device computation; used by tests and the host fallback)."""
        ns = self.next_state[state]
        np_ = self.npops[state].astype(np.int32)
        nq = self.npush[state].astype(np.int32)
        pb = self.popbits[state].astype(np.int32)
        ok = ns != DEAD
        ok &= np_ <= depth
        rem = np.maximum(depth - np_, 0)
        ok &= ((stack >> rem) & ((1 << np_) - 1)) == pb
        ok &= rem + nq <= MAX_DEPTH
        if self.terminal_only[state]:
            ok &= False
        for e in self.eos_ids:
            ok[e] = bool(self.eos_ok[state])
        return ok

    def advance(self, state: int, depth: int, stack: int, token: int
                ) -> tuple[int, int, int]:
        """Apply one sampled token to (state, depth, stack) — host mirror
        of the in-scan update."""
        if token in self.eos_ids:
            return state, depth, stack
        ns = int(self.next_state[state, token])
        np_ = int(self.npops[state, token])
        nq = int(self.npush[state, token])
        qb = int(self.pushbits[state, token])
        d1 = max(depth - np_, 0)
        stack = (stack & ((1 << d1) - 1)) | (qb << d1)
        depth = d1 + nq
        if ns == SENTINEL:
            # pushdown grammars sit at composite offset 0, so the resolved
            # AFTER_VALUE ids need no shift (compose_tables enforces this)
            if depth == 0:
                ns = AFTER_VALUE["T"]
            elif (stack >> (depth - 1)) & 1 == SYM_OBJ:
                ns = AFTER_VALUE["O"]
            else:
                ns = AFTER_VALUE["A"]
        return ns, depth, stack


def compile_vocab(
    token_bytes: Sequence[Optional[bytes]],
    eos_ids: Sequence[int] = (),
) -> VocabTables:
    """Compose every token's bytes from every start state (vectorised over
    the [S, V] grid, one pass per byte position).  ~1s for a 128k vocab."""
    v = len(token_bytes)
    max_len = max((len(t) for t in token_bytes if t), default=1)
    # pad byte matrix with sentinel 256 = "past end of token"
    bmat = np.full((v, max_len), 256, np.int16)
    for i, tb in enumerate(token_bytes):
        if tb:
            bmat[i, : len(tb)] = np.frombuffer(tb, np.uint8)

    state = np.broadcast_to(
        np.arange(N_STATES, dtype=np.int16)[:, None], (N_STATES, v)
    ).copy()
    alive = np.ones((N_STATES, v), bool)
    # specials / empty tokens are never valid in constrained mode
    for i, tb in enumerate(token_bytes):
        if not tb:
            alive[:, i] = False
    npops = np.zeros((N_STATES, v), np.int8)
    popbits = np.zeros((N_STATES, v), np.int8)
    npush = np.zeros((N_STATES, v), np.int8)
    pushbits = np.zeros((N_STATES, v), np.int8)

    for l in range(max_len):
        byte = bmat[:, l]                     # [V] int16
        has = byte != 256
        act = alive & has[None, :]
        if not act.any():
            break
        b_idx = np.where(has, byte, 0).astype(np.int64)
        ns = _DELTA_STATE[state, b_idx[None, :]]   # [S, V]
        op = _DELTA_OP[state, b_idx[None, :]]
        alive &= ~(act & (ns == DEAD))
        act = alive & has[None, :]

        # pushes
        for o, sym in ((OP_PUSH_OBJ, SYM_OBJ), (OP_PUSH_ARR, SYM_ARR)):
            m = act & (op == o)
            over = m & (npush >= MAX_TOKEN_OPS)
            alive &= ~over
            m &= ~over
            pushbits[m] |= (sym << npush[m]).astype(np.int8)
            npush[m] += 1
        # pops
        m = act & (op == OP_POP)
        if m.any():
            sym = np.where(byte == ord("}"), SYM_OBJ, SYM_ARR)  # [V]
            symg = np.broadcast_to(sym[None, :], m.shape)
            # pop an in-token push when one exists
            mi = m & (npush > 0)
            top = (pushbits[mi] >> (npush[mi] - 1)) & 1
            bad = top != symg[mi]
            # mismatched close of an in-token container -> dead
            if bad.any():
                idx = np.where(mi)
                alive[idx[0][bad], idx[1][bad]] = False
                mi_ok = mi.copy()
                mi_ok[idx[0][bad], idx[1][bad]] = False
                mi = mi_ok
            npush[mi] -= 1
            pushbits[mi] &= ~(1 << npush[mi]).astype(np.int8)
            # context after the pop: remaining in-token push, or unknown
            has_rem = mi & (npush > 0)
            if has_rem.any():
                topsym = (pushbits[has_rem] >> (npush[has_rem] - 1)) & 1
                ns[has_rem] = np.where(
                    topsym == SYM_OBJ, AFTER_VALUE["O"], AFTER_VALUE["A"]
                )
            # pop from the outer (runtime) stack
            mo = m & alive & ~mi
            over = mo & (npops >= MAX_TOKEN_OPS)
            alive &= ~over
            mo &= ~over
            popbits[mo] = ((popbits[mo].astype(np.int16) << 1)
                           | symg[mo]).astype(np.int8)
            npops[mo] += 1
        state = np.where(alive & has[None, :], ns, state)

    next_state = np.where(alive, state, DEAD).astype(np.int16)
    # the AFTER_VALUE_U end-state becomes the runtime SENTINEL value (-1):
    # composed grammars shift positive state ids, and a shifted id must
    # never be mistaken for the resolve-against-the-stack marker
    next_state = np.where(next_state == AFTER_VALUE_U, SENTINEL, next_state)
    # a token ending exactly at DEAD id 0 can't be conflated: state ids
    # start at 1, DEAD==0 only means invalid.  int16: composed tables
    # (JSON + choice grammars, compose_tables) exceed 127 states.
    return VocabTables(
        next_state=next_state,
        npops=np.where(alive, npops, 0).astype(np.int8),
        popbits=np.where(alive, popbits, 0).astype(np.int8),
        npush=np.where(alive, npush, 0).astype(np.int8),
        pushbits=np.where(alive, pushbits, 0).astype(np.int8),
        eos_ok=_EOS_OK.copy(),
        terminal_only=_TERMINAL_ONLY.copy(),
        eos_ids=tuple(int(e) for e in eos_ids),
    )


# --------------------------------------------------------------------------
# tokenizer byte mapping

# GPT-2 byte-level BPE printable-unicode <-> byte table (the tokenizers
# crate's ByteLevel pretokenizer; Llama-3 and GPT vocabs use it)
def _gpt2_unicode_to_bytes() -> dict[str, int]:
    bs = (list(range(ord("!"), ord("~") + 1))
          + list(range(0xA1, 0xAD)) + list(range(0xAE, 0x100)))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return {chr(c): b for b, c in zip(bs, cs)}


def token_bytes_map(tokenizer) -> list[Optional[bytes]]:
    """token id -> raw bytes (None for special/unmappable tokens).

    Handles the two HF conventions: GPT-2 byte-level BPE (Ġ/Ċ unicode
    remap) and sentencepiece (▁ space marker + <0xNN> byte tokens).
    Accepts a ``TokenizerWrapper`` or a raw ``tokenizers.Tokenizer``.
    """
    tk = getattr(tokenizer, "_tk", tokenizer)
    vocab: dict[str, int] = tk.get_vocab()
    size = max(vocab.values()) + 1 if vocab else 0
    out: list[Optional[bytes]] = [None] * size
    byte_level = any(t.startswith(("Ġ", "Ċ")) for t in vocab)
    u2b = _gpt2_unicode_to_bytes() if byte_level else None
    special = set()
    try:
        special = {t.content for t in tk.get_added_tokens_decoder().values()
                   if getattr(t, "special", False)}
    except Exception:
        pass
    for tok, i in vocab.items():
        if i >= size or tok in special:
            continue
        if tok.startswith("<") and tok.endswith(">") and len(tok) > 2:
            if tok.startswith("<0x") and len(tok) == 6:
                try:
                    out[i] = bytes([int(tok[3:5], 16)])
                except ValueError:
                    pass
            continue  # other <...> tokens treated as special
        if byte_level:
            try:
                out[i] = bytes(u2b[ch] for ch in tok)
            except KeyError:
                out[i] = tok.encode("utf-8")
        else:
            out[i] = tok.replace("▁", " ").encode("utf-8")
    return out


# --------------------------------------------------------------------------
# choice grammars + composition (guided_choice)


def compile_choice_vocab(
    token_bytes: Sequence[Optional[bytes]],
    choices: Sequence[str],
    eos_ids: Sequence[int] = (),
) -> VocabTables:
    """Tables for "the output is exactly one of ``choices``": a byte trie
    over the candidate strings, composed against the vocab.  No pushdown —
    pops/pushes stay zero, so these tables compose with the JSON grammar's
    via :func:`compose_tables`.  EOS is allowed exactly at complete
    choices; a complete choice that is no other choice's prefix becomes
    terminal (EOS only)."""
    if not choices:
        raise ValueError("guided_choice needs at least one choice")
    enc = [c.encode("utf-8") for c in choices]
    # trie over byte prefixes; state 0 = DEAD, 1 = root
    nodes: dict[bytes, int] = {b"": 1}
    for c in enc:
        for i in range(1, len(c) + 1):
            nodes.setdefault(c[:i], len(nodes) + 1)
    n_states = len(nodes) + 1  # + DEAD
    delta = np.zeros((n_states, 256), np.int16)  # DEAD
    for prefix, sid in nodes.items():
        for c in enc:
            if c[: len(prefix)] == prefix and len(c) > len(prefix):
                delta[sid, c[len(prefix)]] = nodes[c[: len(prefix) + 1]]
    eos_ok = np.zeros(n_states, bool)
    terminal_only = np.zeros(n_states, bool)
    for c in enc:
        sid = nodes[c]
        eos_ok[sid] = True
        terminal_only[sid] = not delta[sid].any()
    return _compose_dfa_vocab(delta, token_bytes, eos_ok, terminal_only,
                              eos_ids)


def _regex_escape(text: str) -> str:
    out = []
    for ch in text:
        if ch in r"\.()[]|*+?{}^$/-'" + '"':
            out.append("\\" + ch)
        else:
            out.append(ch)
    return "".join(out)


# regex fragments for JSON primitives (match the JSON grammar's lexing)
# strings forbid RAW control bytes and restrict escapes to the legal set
# (matching the JSON pushdown grammar's lexing — the lax `\\.` / [^"\\]
# form let schema mode emit invalid JSON)
_RX_STRING = (r'"([^"\\\x00-\x1f]|\\(["\\/bfnrt]|u'
              + "[0-9a-fA-F]" * 4 + r'))*"')
_RX_INT = r"-?(0|[1-9][0-9]*)"
_RX_NUMBER = _RX_INT + r"(\.[0-9]+)?([eE][-+]?[0-9]+)?"
_RX_BOOL = r"(true|false)"
_RX_WS = r"[ \n\t]*"


def _digits_range_rx(lo: str, hi: str) -> str:
    """Regex for decimal integers with the SAME digit count in [lo, hi]
    (recursive digit-prefix construction; no {n} quantifier — the bounded
    engine supports only * + ?, so fixed repeats are spelled out)."""
    if lo == hi:
        return lo
    if len(lo) == 1:
        return f"[{lo}-{hi}]"
    if lo[0] == hi[0]:
        return lo[0] + _digits_range_rx(lo[1:], hi[1:])
    n = len(lo) - 1
    rest_min, rest_max = "0" * n, "9" * n
    parts = []
    start = lo[0]
    if lo[1:] != rest_min:
        parts.append(lo[0] + _digits_range_rx(lo[1:], rest_max))
        start = chr(ord(lo[0]) + 1)
    end = hi[0]
    if hi[1:] != rest_max:
        parts.append(hi[0] + _digits_range_rx(rest_min, hi[1:]))
        end = chr(ord(hi[0]) - 1)
    if start <= end:
        first = f"[{start}-{end}]" if start != end else start
        parts.append(first + "[0-9]" * n)
    return "(" + "|".join(parts) + ")"


def _uint_range_rx(a: int, b: Optional[int]) -> str:
    """Regex for non-negative integers in [a, b] (b=None → unbounded),
    canonical JSON form (no leading zeros, no sign)."""
    alts = []
    if a == 0:
        alts.append("0")
        a = 1
        if b == 0:
            return "0"
    if b is None:
        la = len(str(a))
        alts.append(_digits_range_rx(str(a), "9" * la))
        # any number with MORE digits than a is > a
        alts.append("[1-9]" + "[0-9]" * (la - 1) + "[0-9]+")
        return "(" + "|".join(alts) + ")"
    for length in range(len(str(a)), len(str(b)) + 1):
        lo = max(a, 10 ** (length - 1))
        hi = min(b, 10 ** length - 1)
        if lo <= hi:
            alts.append(_digits_range_rx(str(lo), str(hi)))
    return "(" + "|".join(alts) + ")"


def _int_range_rx(lo: Optional[int], hi: Optional[int]) -> Optional[str]:
    """Regex for integers in [lo, hi]; either side may be None
    (unbounded).  Returns None for an empty range."""
    if lo is not None and hi is not None and lo > hi:
        return None
    parts = []
    if lo is None or lo < 0:  # negative side: -(magnitude)
        mag_lo = 1 if hi is None or hi >= 0 else -hi
        mag_hi = None if lo is None else -lo
        parts.append("-" + _uint_range_rx(mag_lo, mag_hi))
    if hi is None or hi >= 0:  # non-negative side
        parts.append(_uint_range_rx(max(lo or 0, 0), hi))
    return "(" + "|".join(parts) + ")"


_BOUND_KEYS = ("minimum", "maximum", "exclusiveMinimum", "exclusiveMaximum")
_MAX_BOUND = 10 ** 18  # beyond ~18 digits any range regex blows the 4096 cap


def _schema_int_bounds(schema: dict):
    """(ok, lo, hi): inclusive integer bounds from minimum/maximum/
    exclusiveMinimum/exclusiveMaximum (numeric draft-2020 form; the
    draft-4 boolean form adjusts minimum/maximum).  Schemas are UNTRUSTED
    request bodies: non-numeric, non-finite, or astronomically large
    bounds return ok=False (caller falls back to the generic grammar)
    instead of raising — and the magnitude cap also stops a tiny request
    from provoking a megabyte-sized range regex."""
    import math

    def num(v):
        # bool is an int subclass but "minimum: true" is not a bound
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            return None
        if isinstance(v, float) and not math.isfinite(v):
            return None
        if abs(v) > _MAX_BOUND:
            return None
        return v

    lo = schema.get("minimum")
    hi = schema.get("maximum")
    xlo = schema.get("exclusiveMinimum")
    xhi = schema.get("exclusiveMaximum")
    if isinstance(xlo, bool):  # draft-4: exclusiveMinimum: true + minimum
        xlo = lo if xlo else None
        lo = None if xlo is not None else lo
    if isinstance(xhi, bool):
        xhi = hi if xhi else None
        hi = None if xhi is not None else hi
    for v in (lo, hi, xlo, xhi):
        if v is not None and num(v) is None:
            return False, None, None
    if xlo is not None:
        v = math.floor(xlo) + 1
        lo = v if lo is None else max(lo, v)
    if xhi is not None:
        v = math.ceil(xhi) - 1
        hi = v if hi is None else min(hi, v)
    lo = None if lo is None else math.ceil(lo)
    hi = None if hi is None else math.floor(hi)
    return True, lo, hi


def json_schema_to_regex(schema: dict, _depth: int = 0) -> Optional[str]:
    """Translate a JSON-Schema SUBSET into a pattern for the bounded regex
    engine, so ``response_format: json_schema`` enforces the schema's
    SHAPE at decode time (not just syntactic JSON + prompt steering).

    Supported: type string/integer/number/boolean/null (and a list of
    those), integer minimum/maximum/exclusive* bounds (exact digit-range
    regex), enum/const of scalars, anyOf/oneOf of supported branches
    (oneOf is treated as anyOf — branches are assumed disjoint), object
    with ``properties`` in declared order — required ones mandatory,
    up to 5 optional ones may be independently omitted (``required``
    absent keeps the historical all-required emission), array of a
    supported item type.  Returns None when the schema uses anything
    else — notably bounds on non-integer numbers, which a regex cannot
    enforce exactly — and the caller falls back to the generic JSON
    grammar + prompt steering.
    """
    if _depth > 6 or not isinstance(schema, dict):
        return None
    if "enum" in schema:
        vals = schema["enum"]
        if not isinstance(vals, list) or not vals:
            return None
        if any(k in schema for k in _BOUND_KEYS):
            return None  # enum ∩ numeric bounds: conjoin semantics, bail
        t = schema.get("type")
        if t is not None:
            # keywords CONJOIN: a sibling type narrows the enum.  Only a
            # plain scalar type name is narrowed here; a type LIST (or
            # any other shape — schemas are untrusted) falls back.
            if not isinstance(t, str):
                return None
            chk = {"string": str, "boolean": bool, "null": type(None),
                   "integer": int, "number": (int, float)}.get(t)
            if chk is None:
                return None  # enum under object/array types: bail
            vals = [v for v in vals
                    if isinstance(v, chk)
                    and not (chk is not bool and isinstance(v, bool))]
            if not vals:
                return None
        alts = []
        for v in vals:
            if isinstance(v, str):
                # json.dumps first: quotes/backslashes/control chars must
                # appear ESCAPED in the emitted JSON, not raw
                alts.append(_regex_escape(json.dumps(v)))
            elif isinstance(v, bool):
                alts.append("true" if v else "false")
            elif isinstance(v, (int, float)):
                alts.append(_regex_escape(json.dumps(v)))
            elif v is None:
                alts.append("null")
            else:
                return None
        return "(" + "|".join(alts) + ")"
    if "const" in schema:
        return json_schema_to_regex(
            {k: v for k, v in schema.items() if k != "const"}
            | {"enum": [schema["const"]]}, _depth)
    for key in ("anyOf", "oneOf"):
        branches = schema.get(key)
        if branches is not None:
            # JSON Schema keywords conjoin: a sibling type/enum/bound next
            # to anyOf would be DROPPED by a plain union — fall back to the
            # generic grammar rather than emit a false guarantee.
            # (Annotation-only siblings are harmless.)
            sib = set(schema) - {key, "title", "description", "default",
                                 "examples", "$schema", "$id", "$comment"}
            if sib:
                return None
            if not isinstance(branches, list) or not branches:
                return None
            subs = [json_schema_to_regex(b, _depth + 1) for b in branches]
            if any(s is None for s in subs):
                return None
            return "(" + "|".join(subs) + ")"
    t = schema.get("type")
    if isinstance(t, list):  # type union == anyOf of the member types
        if not t:
            return None
        subs = [
            json_schema_to_regex(dict(schema, type=x), _depth + 1) for x in t
        ]
        if any(s is None for s in subs):
            return None
        return "(" + "|".join(subs) + ")"
    if t == "string":
        return _RX_STRING
    if t == "integer":
        ok, lo, hi = _schema_int_bounds(schema)
        if not ok:
            return None
        if lo is None and hi is None:
            return _RX_INT
        return _int_range_rx(lo, hi)
    if t == "number":
        if any(k in schema for k in _BOUND_KEYS):
            return None  # real-valued bounds can't be regex-enforced
        return _RX_NUMBER
    if t == "boolean":
        return _RX_BOOL
    if t == "null":
        return "null"
    if t == "array":
        item = json_schema_to_regex(schema.get("items", {}), _depth + 1)
        if item is None:
            return None
        w = _RX_WS
        return (r"\[" + w + "(" + item + "(" + w + "," + w + item + ")*"
                + w + r")?\]")
    if t == "object":
        props = schema.get("properties")
        if not isinstance(props, dict) or not props:
            return None
        keys = list(props.keys())
        required = schema.get("required")
        # historical behaviour: no ``required`` -> emit every property
        # (always schema-valid, and keeps pre-r4 outputs stable).
        # ``required`` must be a list of strings — anything else in an
        # untrusted schema falls back rather than raising (or treating a
        # string as its characters).
        if required is not None and (
            not isinstance(required, list)
            or not all(isinstance(k, str) for k in required)
        ):
            return None
        req_set = set(keys) if required is None else set(required)
        if not req_set <= set(keys):
            return None  # a required key with no declared schema
        if len(keys) - len(req_set) > 5:
            # the ordered-subsequence expansion below doubles per optional
            # key; past ~5 the generic JSON grammar is the better tool
            return None
        w = _RX_WS
        pats = []
        for k in keys:
            sub = json_schema_to_regex(props[k], _depth + 1)
            if sub is None:
                return None
            pats.append(_regex_escape(json.dumps(k)) + w + ":" + w + sub + w)

        # ordered-subsequence emission: properties appear in declared
        # order, required ones always, optional ones independently
        # omittable, commas only between present ones.  suffix(i, emitted)
        # = pattern for items i.. given whether anything was emitted yet
        # ("" = epsilon); memoised so shared suffixes are computed once.
        from functools import lru_cache

        @lru_cache(maxsize=None)
        def suffix(i: int, emitted: bool) -> str:
            if i == len(pats):
                return ""
            head = ("," + w if emitted else "") + pats[i]
            with_i = head + suffix(i + 1, True)
            if keys[i] in req_set:
                return with_i
            without = suffix(i + 1, emitted)
            if without == "":
                return "(" + with_i + ")?"
            return "((" + with_i + ")|(" + without + "))"

        return r"\{" + w + suffix(0, False) + r"\}"
    return None


MAX_REGEX_STATES = 2048


class RegexError(ValueError):
    pass


def _parse_regex(pattern: str):
    """Parse a bounded regex subset into an NFA (Thompson construction
    over BYTES).  Supported: literals (UTF-8, escapes), '.', character
    classes [a-z0-9_] (ASCII ranges, negation), groups (), alternation |,
    quantifiers * + ?.  Fullmatch semantics (implicit anchors), matching
    vLLM's guided_regex.  Unsupported syntax raises RegexError.

    NFA representation: list of nodes; node = (eps: list[int],
    edges: list[(bool[256], int)]).
    """
    # fullmatch semantics: a leading ^ / trailing $ are redundant no-ops
    # (the common anchored form); anywhere else they are rejected below
    if pattern.startswith("^"):
        pattern = pattern[1:]
    if pattern.endswith("$"):
        bs_run = len(pattern) - 1 - len(pattern[:-1].rstrip("\\"))
        if bs_run % 2 == 0:  # even backslashes -> the $ is a real anchor
            pattern = pattern[:-1]

    eps: list[list[int]] = []
    edges: list[list] = []

    def new_node() -> int:
        eps.append([])
        edges.append([])
        return len(eps) - 1

    i = 0
    n = len(pattern)

    def class_endpoint():
        r"""One class member: returns an ASCII byte, or a mask for \d-style
        escapes (which cannot anchor a range)."""
        nonlocal i
        c = pattern[i]
        if c == "\\":
            if i + 1 >= n:
                raise RegexError("trailing backslash in class")
            i += 1
            if pattern[i] == "x":  # \xNN byte escape (class endpoints)
                if i + 2 >= n:
                    raise RegexError("truncated \\x escape")
                try:
                    b = int(pattern[i + 1:i + 3], 16)
                except ValueError:
                    raise RegexError("bad \\x escape")
                i += 3
                return b
            b = _escape_byte(pattern[i])
            if b is None:
                if pattern[i] in "DWS":
                    # char-level complements inside a byte-level class
                    # would be wrong for multi-byte chars — be loud
                    raise RegexError(
                        f"negated class escape \\{pattern[i]} not "
                        "supported inside [...]"
                    )
                m = _class_escape(pattern[i])
                i += 1
                return m
            i += 1
            return b
        bs = c.encode("utf-8")
        if len(bs) != 1:
            raise RegexError("non-ASCII in character class")
        i += 1
        return bs[0]

    def parse_class() -> tuple[np.ndarray, bool]:
        """Returns (ascii mask, negated?).  Negation is resolved by the
        caller at the character level (multi-byte chars count)."""
        nonlocal i
        assert pattern[i] == "["
        i += 1
        mask = np.zeros(256, bool)
        negate = i < n and pattern[i] == "^"
        if negate:
            i += 1
        first = True
        while i < n and (pattern[i] != "]" or first):
            first = False
            lo = class_endpoint()
            if isinstance(lo, np.ndarray):
                mask |= lo
                continue
            if i + 1 < n and pattern[i] == "-" and pattern[i + 1] != "]":
                i += 1
                hi = class_endpoint()
                if isinstance(hi, np.ndarray) or hi < lo:
                    raise RegexError("bad character range in class")
                mask[lo:hi + 1] = True
            else:
                mask[lo] = True
        if i >= n:
            raise RegexError("unterminated character class")
        i += 1  # ']'
        return mask, negate

    def char_fragment(ascii_mask: np.ndarray):
        """One CHARACTER matching ascii_mask for single-byte chars plus
        every multi-byte UTF-8 character — '.' and negated classes are
        char-level (vLLM semantics), and must never emit lone
        continuation bytes (invalid UTF-8 output)."""
        a, b = new_node(), new_node()
        m = ascii_mask.copy()
        m[0x80:] = False
        edges[a].append((m, b))

        def seq(*byte_ranges):
            cur = a
            for j, (lo, hi) in enumerate(byte_ranges):
                nxt = b if j == len(byte_ranges) - 1 else new_node()
                mm = np.zeros(256, bool)
                mm[lo:hi + 1] = True
                edges[cur].append((mm, nxt))
                cur = nxt

        cont = (0x80, 0xBF)
        seq((0xC2, 0xDF), cont)
        seq((0xE0, 0xE0), (0xA0, 0xBF), cont)
        seq((0xE1, 0xEC), cont, cont)
        seq((0xED, 0xED), (0x80, 0x9F), cont)
        seq((0xEE, 0xEF), cont, cont)
        seq((0xF0, 0xF0), (0x90, 0xBF), cont, cont)
        seq((0xF1, 0xF3), cont, cont, cont)
        seq((0xF4, 0xF4), (0x80, 0x8F), cont, cont)
        return a, b

    def atom():
        """Returns (start, end) NFA fragment for one atom."""
        nonlocal i
        if i >= n:
            raise RegexError("unexpected end of pattern")
        c = pattern[i]
        if c == "(":
            i += 1
            frag = alternation()
            if i >= n or pattern[i] != ")":
                raise RegexError("unbalanced group")
            i += 1
            return frag
        if c == "[":
            mask, negate = parse_class()
            if negate:
                inv = ~mask
                inv[:0x09] = False  # raw control noise stays excluded
                return char_fragment(inv)
            a, b = new_node(), new_node()
            edges[a].append((mask, b))
            return a, b
        if c == ".":
            i += 1
            any_ascii = np.ones(256, bool)
            any_ascii[ord("\n")] = False
            return char_fragment(any_ascii)
        if c == "\\":
            i += 1
            if i >= n:
                raise RegexError("trailing backslash")
            esc = pattern[i]
            i += 1
            byte = _escape_byte(esc)
            if byte is None:
                if esc in "DWS":
                    inv = ~_class_escape(esc.lower())
                    inv[:0x09] = False
                    return char_fragment(inv)
                mask = _class_escape(esc)
                a, b = new_node(), new_node()
                edges[a].append((mask, b))
                return a, b
            return _literal_bytes(bytes([byte]))
        if c in ")|*+?{}^$":
            # {m,n} quantifiers and mid-pattern anchors are unsupported —
            # reject rather than silently matching literal chars
            raise RegexError(f"unexpected {c!r}")
        i += 1
        return _literal_bytes(c.encode("utf-8"))

    def _literal_bytes(bs: bytes):
        start = new_node()
        cur = start
        for byte in bs:
            nxt = new_node()
            mask = np.zeros(256, bool)
            mask[byte] = True
            edges[cur].append((mask, nxt))
            cur = nxt
        return start, cur

    def piece():
        nonlocal i
        a, b = atom()
        while i < n and pattern[i] in "*+?":
            q = pattern[i]
            i += 1
            s2, e2 = new_node(), new_node()
            eps[s2].append(a)
            eps[b].append(e2)
            if q in "*?":
                eps[s2].append(e2)
            if q in "*+":
                eps[b].append(a)
            a, b = s2, e2
        return a, b

    def concat():
        nonlocal i
        a, b = piece()
        while i < n and pattern[i] not in ")|":
            a2, b2 = piece()
            eps[b].append(a2)
            b = b2
        return a, b

    def alternation():
        nonlocal i
        frags = [concat()]
        while i < n and pattern[i] == "|":
            i += 1
            frags.append(concat())
        if len(frags) == 1:
            return frags[0]
        a, b = new_node(), new_node()
        for fa, fb in frags:
            eps[a].append(fa)
            eps[fb].append(b)
        return a, b

    start, accept = alternation()
    if i != n:
        raise RegexError(f"unexpected {pattern[i]!r} at {i}")
    return eps, edges, start, accept


def _escape_byte(c: str):
    simple = {"n": 0x0A, "t": 0x09, "r": 0x0D, "\\": 0x5C, ".": 0x2E,
              "(": 0x28, ")": 0x29, "[": 0x5B, "]": 0x5D, "|": 0x7C,
              "*": 0x2A, "+": 0x2B, "?": 0x3F, "^": 0x5E, "$": 0x24,
              "{": 0x7B, "}": 0x7D, "/": 0x2F, '"': 0x22, "'": 0x27,
              "-": 0x2D}
    if c in simple:
        return simple[c]
    if c in "dwsDWS":
        return None  # class escape
    if len(c.encode("utf-8")) == 1 and not c.isalnum():
        return c.encode("utf-8")[0]
    raise RegexError(f"unsupported escape \\{c}")


def _class_escape(c: str) -> np.ndarray:
    mask = np.zeros(256, bool)
    if c == "d":
        mask[ord("0"):ord("9") + 1] = True
    elif c == "w":
        mask[ord("0"):ord("9") + 1] = True
        mask[ord("a"):ord("z") + 1] = True
        mask[ord("A"):ord("Z") + 1] = True
        mask[ord("_")] = True
    elif c == "s":
        for b in b" \t\n\r\f\v":
            mask[b] = True
    else:
        # D/W/S are resolved by the caller at the character level
        raise RegexError(f"unsupported class escape \\{c}")
    return mask


def compile_regex_vocab(
    token_bytes: Sequence[Optional[bytes]],
    pattern: str,
    eos_ids: Sequence[int] = (),
) -> VocabTables:
    """Tables for "the output fullmatches ``pattern``" (bounded regex
    subset; see :func:`_parse_regex`).  NFA -> DFA by subset construction,
    capped at MAX_REGEX_STATES, then composed against the vocab like the
    choice grammars."""
    eps, edges, start, accept = _parse_regex(pattern)
    n_nfa = len(edges)
    if n_nfa > 8192:
        # the closure matrix is O(n_nfa^2): bound it loudly (patterns this
        # large exceed the DFA cap anyway)
        raise RegexError(f"regex NFA too large ({n_nfa} nodes)")

    # precomputed per-node epsilon closures as a bool matrix: subset states
    # become bool VECTORS (bytes-keyed), and closure-of-set is one OR-
    # reduction — Python set/frozenset bookkeeping on large NFAs cost tens
    # of seconds for enum-style alternations
    nclo = np.eye(n_nfa, dtype=bool)
    for node in range(n_nfa):
        stack = [node]
        while stack:
            s0 = stack.pop()
            for t in eps[s0]:
                if not nclo[node, t]:
                    nclo[node, t] = True
                    stack.append(t)

    # per-node outgoing edges, stacked once: masks [E, 256], targets [E],
    # source node per edge [E] (sparse — an [n_nfa, E] ownership matrix
    # costs hundreds of MB at the size cap)
    edge_masks = []
    edge_targets = []
    edge_src = []
    for s0, elist in enumerate(edges):
        for mask, t in elist:
            edge_masks.append(mask)
            edge_targets.append(t)
            edge_src.append(s0)
    edge_masks = (np.stack(edge_masks) if edge_masks
                  else np.zeros((0, 256), bool))
    edge_targets = np.asarray(edge_targets, np.int64)
    edge_src = np.asarray(edge_src, np.int64)

    init_vec = nclo[start].copy()
    dfa_ids: dict[bytes, int] = {init_vec.tobytes(): 1}  # 0 = DEAD
    order = [init_vec]
    accept_flags = {1: bool(init_vec[accept])}
    delta_rows = {1: np.zeros(256, np.int16)}
    qi = 0
    while qi < len(order):
        cur = order[qi]
        qi += 1
        sid = dfa_ids[cur.tobytes()]
        row = delta_rows[sid]
        live = cur[edge_src]  # [E] bool: edges leaving this subset
        if not live.any():
            continue
        # [256, E_live] per-byte edge activation -> unique target classes
        m = edge_masks[live].T  # [256, E_live]
        tgts = edge_targets[live]
        uniq, inv = np.unique(m, axis=0, return_inverse=True)
        for u in range(uniq.shape[0]):
            hit = tgts[uniq[u]]
            if hit.size == 0:
                continue
            vec = nclo[hit].any(axis=0)
            key = vec.tobytes()
            if key not in dfa_ids:
                if len(dfa_ids) >= MAX_REGEX_STATES:
                    raise RegexError(
                        f"regex needs more than {MAX_REGEX_STATES} DFA states"
                    )
                dfa_ids[key] = len(dfa_ids) + 1
                accept_flags[dfa_ids[key]] = bool(vec[accept])
                delta_rows[dfa_ids[key]] = np.zeros(256, np.int16)
                order.append(vec)
            row[inv == u] = dfa_ids[key]
    n_states = len(dfa_ids) + 1
    delta = np.zeros((n_states, 256), np.int16)
    for sid, row in delta_rows.items():
        delta[sid] = row
    eos_ok = np.zeros(n_states, bool)
    terminal_only = np.zeros(n_states, bool)
    for sid, is_accept in accept_flags.items():
        if is_accept:
            eos_ok[sid] = True
            terminal_only[sid] = not delta[sid].any()
    return _compose_dfa_vocab(delta, token_bytes, eos_ok, terminal_only,
                              eos_ids)


def _compose_dfa_vocab(
    delta: np.ndarray,  # [S, 256] int16 byte transitions, DEAD = invalid
    token_bytes: Sequence[Optional[bytes]],
    eos_ok: np.ndarray,
    terminal_only: np.ndarray,
    eos_ids: Sequence[int],
) -> VocabTables:
    """Compose a plain (pushdown-free) byte DFA against the vocab."""
    v = len(token_bytes)
    n_states = delta.shape[0]
    max_len = max((len(t) for t in token_bytes if t), default=1)
    bmat = np.full((v, max_len), 256, np.int16)
    for i, tb in enumerate(token_bytes):
        if tb:
            bmat[i, : len(tb)] = np.frombuffer(tb, np.uint8)
    state = np.broadcast_to(
        np.arange(n_states, dtype=np.int16)[:, None], (n_states, v)
    ).copy()
    alive = np.ones((n_states, v), bool)
    for i, tb in enumerate(token_bytes):
        if not tb:
            alive[:, i] = False
    for col in range(max_len):
        byte = bmat[:, col]
        has = byte != 256
        act = alive & has[None, :]
        if not act.any():
            break
        ns = delta[state, np.where(has, byte, 0).astype(np.int64)[None, :]]
        alive &= ~(act & (ns == DEAD))
        state = np.where(alive & has[None, :], ns, state)
    zeros = np.zeros((n_states, v), np.int8)
    return VocabTables(
        next_state=np.where(alive, state, DEAD).astype(np.int16),
        npops=zeros, popbits=zeros, npush=zeros, pushbits=zeros.copy(),
        eos_ok=np.asarray(eos_ok, bool),
        terminal_only=np.asarray(terminal_only, bool),
        eos_ids=tuple(int(e) for e in eos_ids),
    )


def compose_tables(parts: Sequence[VocabTables]) -> tuple[VocabTables, list[int]]:
    """Stack several grammars into one table set for mixed-grammar batches.

    Returns (composite, offsets): grammar i's state ``s`` lives at
    ``s + offsets[i]`` in the composite (DEAD stays 0 and is shared).
    Rows carry per-request composite state; stack ops are offset-free.
    """
    if not parts:
        raise ValueError("compose_tables needs at least one grammar")
    v = parts[0].vocab_size
    eos = parts[0].eos_ids
    for t in parts:
        if t.vocab_size != v or t.eos_ids != eos:
            raise ValueError("grammars must share vocab and eos ids")
    if len(parts) == 1:
        return parts[0], [0]
    offsets: list[int] = []
    ns_rows, misc = [], {k: [] for k in
                         ("npops", "popbits", "npush", "pushbits")}
    eos_ok, term = [], []
    off = 0
    for i, t in enumerate(parts):
        offsets.append(off)
        if i > 0 and (t.next_state == SENTINEL).any():
            # the sentinel resolves to the JSON grammar's absolute
            # AFTER_VALUE ids, which are only correct at offset 0
            raise ValueError("a pushdown (JSON) grammar must be the first "
                             "part of a composite")
        shifted = t.next_state.astype(np.int32)
        shifted = np.where(shifted > DEAD, shifted + off, shifted)
        ns_rows.append(shifted)
        for k in misc:
            misc[k].append(getattr(t, k))
        eos_ok.append(t.eos_ok)
        term.append(t.terminal_only)
        off += t.n_states
    if off > np.iinfo(np.int16).max:
        raise ValueError(f"composite grammar too large ({off} states)")
    return VocabTables(
        next_state=np.concatenate(ns_rows).astype(np.int16),
        npops=np.concatenate(misc["npops"]),
        popbits=np.concatenate(misc["popbits"]),
        npush=np.concatenate(misc["npush"]),
        pushbits=np.concatenate(misc["pushbits"]),
        eos_ok=np.concatenate(eos_ok),
        terminal_only=np.concatenate(term),
        eos_ids=eos,
    ), offsets


# --------------------------------------------------------------------------
# device side (jax) — used inside the jitted decode scan

from typing import NamedTuple


class GrammarTables(NamedTuple):
    """Device-resident transition tables (a pytree, so it rides jit args)."""

    next_state: object  # [S, V] int16
    npops: object       # [S, V] int8
    popbits: object     # [S, V] int8
    npush: object       # [S, V] int8
    pushbits: object    # [S, V] int8
    eos_ok: object      # [S] bool
    terminal_only: object  # [S] bool
    eos_cols: object    # [V] bool


def device_tables(tables: VocabTables, vocab_size: Optional[int] = None
                  ) -> GrammarTables:
    """Upload compiled tables, padding/truncating the vocab axis to the
    model's logit width (tokenizer vocab can differ from model vocab)."""
    import jax.numpy as jnp

    v = vocab_size or tables.vocab_size

    def fit(a: np.ndarray) -> np.ndarray:
        if a.shape[1] == v:
            return a
        out = np.zeros((a.shape[0], v), a.dtype)
        out[:, : min(v, a.shape[1])] = a[:, :v]
        return out

    eos_cols = np.zeros(v, bool)
    for e in tables.eos_ids:
        if 0 <= e < v:
            eos_cols[e] = True
    return GrammarTables(
        next_state=jnp.asarray(fit(tables.next_state)),
        npops=jnp.asarray(fit(tables.npops)),
        popbits=jnp.asarray(fit(tables.popbits)),
        npush=jnp.asarray(fit(tables.npush)),
        pushbits=jnp.asarray(fit(tables.pushbits)),
        eos_ok=jnp.asarray(tables.eos_ok),
        terminal_only=jnp.asarray(tables.terminal_only),
        eos_cols=jnp.asarray(eos_cols),
    )


def grammar_mask(logits, gt: GrammarTables, jrows, state, depth, stack):
    """Mask invalid-next-token logits for grammar-constrained rows.

    logits [B, V] f32; jrows [B] bool (row uses the grammar); state/depth/
    stack [B] int32.  Pure vectorised gathers + bit math — runs inside the
    decode ``lax.scan`` with no host involvement.
    """
    import jax.numpy as jnp

    ns = gt.next_state[state]                      # [B, V] int8
    np_ = gt.npops[state].astype(jnp.int32)
    nq = gt.npush[state].astype(jnp.int32)
    pb = gt.popbits[state].astype(jnp.int32)
    d = depth[:, None]
    st = stack[:, None]
    rem = jnp.maximum(d - np_, 0)
    ok = (ns != DEAD) & (np_ <= d)
    ok &= ((st >> rem) & ((1 << np_) - 1)) == pb
    ok &= rem + nq <= MAX_DEPTH
    ok &= ~gt.terminal_only[state][:, None]
    ok = jnp.where(gt.eos_cols[None, :], gt.eos_ok[state][:, None], ok)
    return jnp.where(jrows[:, None] & ~ok, -1e30, logits)


def grammar_advance(gt: GrammarTables, jrows, state, depth, stack, sampled):
    """Advance each constrained row's (state, depth, stack) by its sampled
    token (scalar gathers; mirrors VocabTables.advance)."""
    import jax.numpy as jnp

    ns = gt.next_state[state, sampled].astype(jnp.int32)
    np_ = gt.npops[state, sampled].astype(jnp.int32)
    nq = gt.npush[state, sampled].astype(jnp.int32)
    qb = gt.pushbits[state, sampled].astype(jnp.int32)
    d1 = jnp.clip(depth - np_, 0, MAX_DEPTH)
    stack1 = (stack & ((1 << d1) - 1)) | (qb << d1)
    depth1 = jnp.clip(d1 + nq, 0, MAX_DEPTH + MAX_TOKEN_OPS)
    exposed = (stack1 >> jnp.maximum(depth1 - 1, 0)) & 1
    resolved = jnp.where(
        depth1 == 0,
        AFTER_VALUE["T"],
        jnp.where(exposed == SYM_OBJ, AFTER_VALUE["O"], AFTER_VALUE["A"]),
    )
    ns = jnp.where(ns == SENTINEL, resolved, ns)
    upd = jrows & ~gt.eos_cols[sampled]
    return (
        jnp.where(upd, ns, state),
        jnp.where(upd, depth1, depth),
        jnp.where(upd, stack1, stack),
    )


class JsonGrammar:
    """Facade: compile once per tokenizer, share across requests.  Keeps
    the token byte map so per-request choice grammars (guided_choice)
    compile against the same vocab."""

    def __init__(self, tables: VocabTables,
                 token_bytes: Optional[Sequence[Optional[bytes]]] = None):
        self.tables = tables
        self.token_bytes = list(token_bytes) if token_bytes is not None else None

    @classmethod
    def from_tokenizer(cls, tokenizer, eos_ids: Sequence[int] = ()) -> "JsonGrammar":
        tb = token_bytes_map(tokenizer)
        return cls(compile_vocab(tb, eos_ids), tb)

    @classmethod
    def from_token_bytes(
        cls, token_bytes: Sequence[Optional[bytes]], eos_ids: Sequence[int] = ()
    ) -> "JsonGrammar":
        return cls(compile_vocab(token_bytes, eos_ids), token_bytes)

    @staticmethod
    def validate(text: str) -> bool:
        try:
            json.loads(text)
            return True
        except Exception:
            return False
