"""The @service component model.

Reference parity: deploy/dynamo/sdk/src/dynamo/sdk/lib/service.py:71,220
(DynamoService), lib/decorators.py (@dynamo_endpoint), lib/dependency.py
(depends()), lib/bento.py (.link() graph edges + pruning, tested by
tests/test_link.py).

A service is a plain class; the decorator wraps it in a
:class:`DynamoService` carrying its namespace, endpoints, dependencies and
resource asks.  ``depends(Other)`` declares a cross-service client that is
injected at startup as a :class:`ServiceClient` (remote endpoint proxies
over the distributed runtime).  ``A.link(B)`` narrows a dependency edge to
a concrete provider and returns the linked graph entry.
"""

from __future__ import annotations

import inspect
import logging
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Callable, Optional

from dynamo_tpu.runtime.engine import AsyncEngine, Context

log = logging.getLogger("dynamo_tpu.sdk")

__all__ = [
    "service",
    "dynamo_endpoint",
    "async_on_start",
    "depends",
    "Dependency",
    "DynamoService",
    "ServiceClient",
    "EndpointAdapter",
]


# ------------------------------------------------------------- decorators ----


def dynamo_endpoint(fn: Optional[Callable] = None, *, name: Optional[str] = None):
    """Mark an async method as a served endpoint (ref decorators.py:80)."""

    def wrap(f: Callable) -> Callable:
        f._dynamo_endpoint = name or f.__name__
        return f

    return wrap(fn) if fn is not None else wrap


def async_on_start(fn: Callable) -> Callable:
    """Mark an async method to run at worker startup (engine boot etc.)."""
    fn._dynamo_on_start = True
    return fn


class Dependency:
    """Declared with ``depends(Other)`` at class scope; resolved to a
    :class:`ServiceClient` when the worker starts."""

    def __init__(self, target: "DynamoService"):
        if not isinstance(target, DynamoService):
            raise TypeError("depends() takes a @service-decorated class")
        self.target = target
        self.attr: str = ""

    def __set_name__(self, owner, name):
        self.attr = name

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        try:
            return obj.__dict__[f"_dep_{self.attr}"]
        except KeyError:
            raise RuntimeError(
                f"dependency {self.attr!r} not wired — is the service running "
                "under serve_graph()/serve_worker?"
            ) from None


def depends(target: "DynamoService") -> Dependency:
    return Dependency(target)


def service(
    cls=None,
    *,
    dynamo: Optional[dict] = None,
    resources: Optional[dict] = None,
    workers: int = 1,
):
    """Class decorator: ``@service(dynamo={"namespace": ...},
    resources={"tpu": 1}, workers=2)`` (ref service.py:220)."""

    def wrap(c) -> DynamoService:
        return DynamoService(
            c, dynamo=dynamo or {}, resources=resources or {}, workers=workers
        )

    return wrap(cls) if cls is not None else wrap


# ---------------------------------------------------------------- service ----


@dataclass
class _EndpointSpec:
    name: str
    method: str  # attribute name on the inner class


class DynamoService:
    def __init__(self, inner: type, dynamo: dict, resources: dict, workers: int):
        self.inner = inner
        self.name = dynamo.get("name", inner.__name__)
        self.namespace = dynamo.get("namespace", "default")
        self.resources = resources
        self.workers = workers
        self.endpoints: list[_EndpointSpec] = [
            _EndpointSpec(ep, attr)
            for attr, member in vars(inner).items()
            if (ep := getattr(member, "_dynamo_endpoint", None))
        ]
        self.on_start_hooks: list[str] = [
            attr
            for attr, member in vars(inner).items()
            if getattr(member, "_dynamo_on_start", False)
        ]
        self.dependencies: list[Dependency] = [
            m for m in vars(inner).values() if isinstance(m, Dependency)
        ]
        # link edges carry the MODULE that created them: graph modules all
        # mutate these shared class objects at import, so a process that
        # imported several graphs holds their UNION — serving one graph
        # must be able to scope the closure to its own module's edges
        # (production `dynamo serve` imports one graph per process, but
        # in-process serving/tests import many)
        self._links: list[tuple[DynamoService, Optional[str]]] = []

    # component name in the runtime (Namespace→Component→Endpoint)
    @property
    def component(self) -> str:
        return self.name.lower()

    def __call__(self, *args, **kwargs):
        return self.inner(*args, **kwargs)

    def __repr__(self) -> str:  # pragma: no cover
        return f"DynamoService({self.name}, ns={self.namespace})"

    # ------------------------------------------------------------------ graph
    def link(self, other: "DynamoService") -> "DynamoService":
        """Add an edge to the serving graph (ref bento.py .link); chainable:
        ``Frontend.link(Processor).link(Worker)`` returns the tail so the
        conventional one-liner builds a path graph from the entry.  The
        edge remembers the calling module, so a serve can scope to ONE
        graph module's edges (see ``closure``)."""
        import sys

        mod = sys._getframe(1).f_globals.get("__name__")
        self._links.append((other, mod))
        return other

    def closure(self, graph: Optional[str] = None) -> list["DynamoService"]:
        """Every service reachable from this entry via links and
        dependencies — the set `serve` actually deploys (unlinked services
        defined in the module are pruned, ref test_link.py).

        ``graph``: follow only link edges created by that module.  Graph
        modules mutate the SHARED component classes at import, so without
        scoping, a process that imported graphs A and B would deploy
        their union when serving either."""
        seen: dict[int, DynamoService] = {}

        def visit(svc: DynamoService) -> None:
            if id(svc) in seen:
                return
            seen[id(svc)] = svc
            for dep in svc.dependencies:
                visit(dep.target)
            for linked, mod in svc._links:
                if graph is None or mod == graph:
                    visit(linked)

        visit(self)
        return list(seen.values())

    def boot_order(self, graph: Optional[str] = None) -> list["DynamoService"]:
        """Closure in reverse-topological order (postorder DFS): every
        service appears after its dependencies/links, so booting in list
        order guarantees endpoints exist before their dependents start."""
        seen: set[int] = set()
        order: list[DynamoService] = []

        def visit(svc: DynamoService) -> None:
            if id(svc) in seen:
                return
            seen.add(id(svc))
            for dep in svc.dependencies:
                visit(dep.target)
            for linked, mod in svc._links:
                if graph is None or mod == graph:
                    visit(linked)
            order.append(svc)

        visit(self)
        return order


# ------------------------------------------------------- runtime adapters ----


class EndpointAdapter(AsyncEngine):
    """Bound endpoint method → AsyncEngine.  The method receives the
    request payload; async generators stream, plain coroutines yield one
    item."""

    def __init__(self, bound: Callable):
        self.bound = bound

    def generate(self, request: Context) -> AsyncIterator[Any]:
        return self._run(request)

    async def _run(self, request: Context) -> AsyncIterator[Any]:
        result = self.bound(request.data)
        if inspect.isasyncgen(result):
            async for item in result:
                if request.is_killed:
                    return
                yield item
        else:
            yield await result


class RemoteEndpoint:
    """Callable proxy for one endpoint of a dependency: ``dep.generate(x)``
    returns the response stream; ``.direct(x, instance_id)`` pins an
    instance (router modes, ref component/client.rs:52)."""

    def __init__(self, client_factory, endpoint: str):
        self._factory = client_factory
        self.endpoint = endpoint

    def __call__(self, payload: Any) -> AsyncIterator[Any]:
        return self._stream(payload, None)

    def direct(self, payload: Any, instance_id: int) -> AsyncIterator[Any]:
        return self._stream(payload, instance_id)

    async def _stream(self, payload: Any, instance_id: Optional[int]):
        client = await self._factory(self.endpoint)
        # one retry on a connection that breaks BEFORE the first item —
        # idempotent at that point (nothing was streamed), and exactly
        # the window where a stale pooled connection surfaces
        for attempt in (0, 1):
            ctx = Context(payload)
            stream = (
                client.direct(ctx, instance_id)
                if instance_id is not None
                else client.generate(ctx)
            )
            got_any = False
            try:
                async for item in stream:
                    got_any = True
                    yield item
                return
            except OSError:
                # ConnectionError plus gaierror/unreachable-host failures
                if got_any or attempt == 1:
                    raise

    async def instance_ids(self) -> list[int]:
        client = await self._factory(self.endpoint)
        return client.instance_ids()


class ServiceClient:
    """What a ``depends()`` attribute resolves to at runtime: attribute
    access gives a :class:`RemoteEndpoint` for that endpoint name."""

    def __init__(self, runtime, target: DynamoService):
        self._runtime = runtime
        self._target = target
        self._clients: dict[str, Any] = {}

    async def _client(self, endpoint: str):
        if endpoint not in self._clients:
            ep = (
                self._runtime.namespace(self._target.namespace)
                .component(self._target.component)
                .endpoint(endpoint)
            )
            self._clients[endpoint] = await ep.client()
        return self._clients[endpoint]

    def __getattr__(self, name: str) -> RemoteEndpoint:
        if name.startswith("_"):
            raise AttributeError(name)
        return RemoteEndpoint(self._client, name)

    async def close(self) -> None:
        for c in self._clients.values():
            await c.close()
