"""Per-process worker entry (ref cli/serve_dynamo.py:57): connect to the
coordinator, serve ONE service's endpoints, run until terminated.

Usage (spawned by ServeSupervisor): python -m dynamo_tpu.sdk.serve_worker
<module:Entry> <ServiceName>; env: DYNTPU_COORDINATOR, DYNTPU_SERVICE_CONFIG.
"""

from __future__ import annotations

import asyncio
import importlib
import logging
import os
import signal
import sys

from dynamo_tpu.runtime.config import RuntimeConfig
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.sdk.config import ServiceConfig
from dynamo_tpu.sdk.service import DynamoService
from dynamo_tpu.sdk.serving import serve_service

log = logging.getLogger("dynamo_tpu.serve_worker")


async def amain(graph: str, service_name: str) -> None:
    mod_name, _, attr = graph.partition(":")
    sys.path.insert(0, os.getcwd())
    entry = getattr(importlib.import_module(mod_name), attr)
    svc = next(s for s in entry.closure(mod_name) if s.name == service_name)

    cfg = RuntimeConfig(coordinator_url=os.environ["DYNTPU_COORDINATOR"])
    runtime = await DistributedRuntime.connect(cfg)
    await serve_service(svc, runtime, ServiceConfig.from_env(), graph=mod_name)
    log.info("%s serving (pid %s)", service_name, os.getpid())

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    # graceful drain (fault plane): discovery keys go first so routing
    # stops sending work, in-flight streams finish, then the transport
    # stops — a supervisor downscale or planner role flip never
    # amputates live requests.  Grace bounded below the supervisor's
    # SIGKILL escalation window.
    grace = float(os.environ.get("DYNTPU_DRAIN_GRACE_S", "10"))
    try:
        await asyncio.wait_for(runtime.drain_all(timeout=grace), grace + 2)
    except asyncio.TimeoutError:
        log.warning("%s drain timed out after %.1fs", service_name, grace)
    await runtime.shutdown()


def main() -> None:
    logging.basicConfig(level=logging.INFO)
    asyncio.run(amain(sys.argv[1], sys.argv[2]))


if __name__ == "__main__":
    main()
