"""Serve a graph: in-process (tests, `run`) or supervised subprocesses.

Reference parity: deploy/dynamo/sdk/cli/serving.py (circus arbiter spawning
one process per component worker) + cli/serve_dynamo.py:57 (per-worker
entry registering component endpoints in the DistributedRuntime) +
cli/allocator.py (ResourceAllocator pinning GPUs via CUDA_VISIBLE_DEVICES —
here TPU chips via JAX flags).
"""

from __future__ import annotations

import asyncio
import logging
import os
import subprocess
import sys
from dataclasses import dataclass, field
from typing import Optional

from dynamo_tpu.runtime.config import RuntimeConfig
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.sdk.config import ServiceConfig
from dynamo_tpu.sdk.service import (
    Dependency,
    DynamoService,
    EndpointAdapter,
    ServiceClient,
)

log = logging.getLogger("dynamo_tpu.serve")

__all__ = ["serve_graph", "serve_service", "ServeHandle", "ServeSupervisor", "TpuAllocator"]


# -------------------------------------------------------------- in-process ----


@dataclass
class ServeHandle:
    """Running graph: per-service runtimes + instances; stop() tears down."""

    runtimes: list[DistributedRuntime] = field(default_factory=list)
    instances: dict[str, object] = field(default_factory=dict)  # inner objects by name
    clients: list[ServiceClient] = field(default_factory=list)

    async def stop(self) -> None:
        # instance-level shutdown hooks first (HTTP servers, pull loops)
        for inst in self.instances.values():
            hook = getattr(inst, "shutdown", None)
            if hook is not None:
                try:
                    await hook()
                except Exception:
                    log.exception("instance shutdown failed")
        for c in self.clients:
            await c.close()
        for rt in self.runtimes:
            await rt.shutdown()


async def serve_service(
    svc: DynamoService,
    runtime: DistributedRuntime,
    config: Optional[ServiceConfig] = None,
    handle: Optional[ServeHandle] = None,
    graph: Optional[str] = None,
):
    """Instantiate one service and register its endpoints (the
    serve_dynamo.py:57 analogue).  Returns the inner instance."""
    obj = svc.inner.__new__(svc.inner)
    # wire dependencies before __init__ so constructors may touch them
    for dep in svc.dependencies:
        client = ServiceClient(runtime, dep.target)
        obj.__dict__[f"_dep_{dep.attr}"] = client
        if handle is not None:
            handle.clients.append(client)
    # per-service YAML/env args land on the instance before __init__, and
    # the runtime itself so components can build ad-hoc ServiceClients /
    # reach the coordinator (prefill queue, KV events); the service object
    # + graph module let components follow their own link edges
    obj.service_config = (config or ServiceConfig.from_env()).for_service(svc.name)
    obj.dynamo_runtime = runtime
    obj.dynamo_service = svc
    obj.dynamo_graph = graph
    obj.__init__()

    for hook in svc.on_start_hooks:
        await getattr(obj, hook)()

    component = runtime.namespace(svc.namespace).component(svc.component)
    for spec in svc.endpoints:
        adapter = EndpointAdapter(getattr(obj, spec.method))
        await component.endpoint(spec.name).serve(adapter)
    return obj


async def serve_graph(
    entry: DynamoService,
    config: Optional[ServiceConfig] = None,
    runtime_config: Optional[RuntimeConfig] = None,
    graph: Optional[str] = None,
) -> ServeHandle:
    """Serve the entry's whole closure in this process (one runtime + lease
    per service, like separate workers would hold) — the test seam the
    reference gets from its sdk test pipeline (tests/test_e2e.py).

    ``graph``: the graph MODULE name whose link edges define the closure
    — pass it whenever this process may have imported other graph modules
    (they all mutate the shared component classes; see closure())."""
    if graph is not None and entry._links and len(entry.boot_order(graph)) == 1 \
            and len(entry.boot_order()) > 1:
        # a typo'd / mismatched module name would otherwise silently
        # deploy a one-node graph
        raise ValueError(
            f"graph {graph!r} matches no link edges from {entry.name} "
            f"(edges were created by "
            f"{sorted({m for _, m in entry._links if m})}); pass the "
            "module that built this graph's chain"
        )
    handle = ServeHandle()
    # dependencies first so their endpoints exist when dependents boot
    for svc in entry.boot_order(graph):
        rt = await DistributedRuntime.connect(runtime_config)
        handle.runtimes.append(rt)
        obj = await serve_service(svc, rt, config, handle, graph=graph)
        handle.instances[svc.name] = obj
    return handle


# ------------------------------------------------------------- tpu allocator ----


class TpuAllocator:
    """Assign TPU chips to worker processes (ResourceAllocator parity,
    cli/allocator.py:136 — CUDA_VISIBLE_DEVICES becomes TPU chip pinning).

    Pool from DYNTPU_TPU_CHIPS ("0,1,2,3"); a service asking
    resources={"tpu": n} gets n chips exclusively, expressed to the child
    via TPU_VISIBLE_CHIPS (honoured by libtpu) — CPU-only services get
    JAX_PLATFORMS=cpu so they never grab the TPU runtime.
    """

    def __init__(self, chips: Optional[list[int]] = None):
        if chips is None:
            raw = os.environ.get("DYNTPU_TPU_CHIPS", "")
            chips = [int(c) for c in raw.split(",") if c.strip()] if raw else []
        self.free = list(chips)

    def allocate(self, svc: DynamoService) -> dict[str, str]:
        """Allocate one *worker's* chips (call once per worker process)."""
        want = int(svc.resources.get("tpu", 0))
        if want == 0:
            return {"JAX_PLATFORMS": "cpu"}
        if len(self.free) < want:
            raise RuntimeError(
                f"service {svc.name} wants {want} TPU chips, only {len(self.free)} free"
            )
        mine, self.free = self.free[:want], self.free[want:]
        return {"TPU_VISIBLE_CHIPS": ",".join(map(str, mine))}

    def release(self, env_extra: dict[str, str]) -> None:
        """Return a dead worker's chips to the pool."""
        chips = env_extra.get("TPU_VISIBLE_CHIPS", "")
        if chips:
            self.free.extend(int(c) for c in chips.split(","))


# ------------------------------------------------------------- supervisor ----


class ServeSupervisor:
    """Spawn one OS process per service worker and keep them alive
    (the circus-arbiter analogue, serving.py:243)."""

    def __init__(
        self,
        graph: str,  # "package.module:EntryService"
        config: Optional[ServiceConfig] = None,
        coordinator_url: Optional[str] = None,
        restart: bool = True,
        drain_grace_s: float = 12.0,
    ):
        self.graph = graph
        self.config = config or ServiceConfig()
        self.coordinator_url = coordinator_url
        self.restart = restart
        # SIGTERM → serve_worker drains (discovery delete, finish streams)
        # → exits; only after this window does the supervisor SIGKILL
        self.drain_grace_s = drain_grace_s
        self.procs: dict[str, subprocess.Popen] = {}
        self._envs: dict[str, dict[str, str]] = {}  # per-worker env_extra for respawn
        # planner-adjusted worker counts per service (scale()); absent =
        # the graph's declared svc.workers
        self._desired: dict[str, int] = {}
        self._coordinator = None
        self.allocator = TpuAllocator()

    def _load_entry(self) -> DynamoService:
        import importlib

        mod_name, _, attr = self.graph.partition(":")
        sys.path.insert(0, os.getcwd())
        entry = getattr(importlib.import_module(mod_name), attr)
        if not isinstance(entry, DynamoService):
            raise TypeError(f"{self.graph} is not a @service")
        return entry

    async def start(self) -> None:
        if self.coordinator_url is None:
            from dynamo_tpu.runtime.transports.coordinator import CoordinatorServer

            self._coordinator = await CoordinatorServer(port=0).start()
            self.coordinator_url = self._coordinator.url
        entry = self._load_entry()
        for svc in entry.boot_order(self.graph.partition(":")[0]):
            for worker_idx in range(svc.workers):
                # each worker process gets its own exclusive chips
                self._spawn(svc, worker_idx, self.allocator.allocate(svc))

    def _spawn(self, svc: DynamoService, worker_idx: int, env_extra: dict) -> None:
        env = dict(os.environ)
        env.update(env_extra)
        env.update(self.config.to_env())
        env["DYNTPU_COORDINATOR"] = self.coordinator_url
        # worker drains strictly inside our SIGKILL escalation window
        env.setdefault("DYNTPU_DRAIN_GRACE_S",
                       str(max(1.0, self.drain_grace_s - 2.0)))
        key = f"{svc.name}:{worker_idx}"
        self._envs[key] = dict(env_extra)
        self.procs[key] = subprocess.Popen(
            [
                sys.executable,
                "-m",
                "dynamo_tpu.sdk.serve_worker",
                self.graph,
                svc.name,
            ],
            env=env,
        )
        log.info("spawned %s (pid %s)", key, self.procs[key].pid)

    def _stop_worker(self, key: str) -> None:
        """Gracefully stop one worker and return its chips; popped from
        procs FIRST so watch() can never mistake the exit for a crash.
        SIGTERM triggers the worker's drain lifecycle (serve_worker.py:
        deregister from discovery, finish in-flight streams); SIGKILL only
        lands after drain_grace_s — so a planner role flip or downscale
        completes live requests instead of amputating them."""
        proc = self.procs.pop(key, None)
        if proc is None:
            return
        proc.terminate()
        try:
            proc.wait(timeout=self.drain_grace_s)
        except subprocess.TimeoutExpired:
            log.warning("%s did not drain in %.1fs; killing",
                        key, self.drain_grace_s)
            proc.kill()
        self.allocator.release(self._envs.pop(key, {}))
        log.info("stopped %s", key)

    async def scale(self, service_name: str, replicas: int) -> int:
        """Level one service's worker-process count toward ``replicas``
        (the planner's SupervisorActuator calls this; a prefill↔decode
        role flip is one pool scaling down while the other scales up,
        chips flowing through the allocator).  Returns the new count."""
        replicas = max(0, int(replicas))
        entry = self._load_entry()
        by_name = {s.name: s for s in entry.closure(self.graph.partition(":")[0])}
        svc = by_name.get(service_name)
        if svc is None:
            raise KeyError(f"service {service_name!r} not in graph {self.graph}")
        self._desired[service_name] = replicas
        mine = sorted(
            (k for k in self.procs if k.rsplit(":", 1)[0] == service_name),
            key=lambda k: int(k.rsplit(":", 1)[1]),
        )
        # scale down: stop highest worker indices first
        for key in mine[replicas:][::-1]:
            self._stop_worker(key)
        # scale up: fill the missing indices
        have = {int(k.rsplit(":", 1)[1]) for k in self.procs
                if k.rsplit(":", 1)[0] == service_name}
        for idx in range(replicas):
            if idx not in have:
                self._spawn(svc, idx, self.allocator.allocate(svc))
        return sum(1 for k in self.procs
                   if k.rsplit(":", 1)[0] == service_name)

    async def watch(self) -> None:
        """Restart crashed workers until stop() (watcher loop parity)."""
        entry = self._load_entry()
        by_name = {s.name: s for s in entry.closure(self.graph.partition(":")[0])}
        while self.procs:
            await asyncio.sleep(0.5)
            for key, proc in list(self.procs.items()):
                code = proc.poll()
                if code is None:
                    continue
                name, _, idx = key.partition(":")
                if self.restart and code != 0:
                    log.warning("%s exited %s — restarting", key, code)
                    # respawn with the same chip pinning / platform guard
                    self._spawn(by_name[name], int(idx), self._envs[key])
                else:
                    del self.procs[key]
                    self.allocator.release(self._envs.pop(key, {}))

    async def stop(self) -> None:
        for proc in self.procs.values():
            proc.terminate()
        for proc in self.procs.values():
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc.kill()
        self.procs.clear()
        if self._coordinator:
            await self._coordinator.stop()
