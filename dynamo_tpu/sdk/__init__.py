"""Python SDK — declarative serving graphs.

Reference parity: deploy/dynamo/sdk (BentoML-forked @service model,
SURVEY.md §2.7): ``@service`` components with ``@dynamo_endpoint``s,
``depends()`` cross-component clients, ``.link()`` graph edges, YAML
ServiceConfig with Common inheritance, and a process supervisor
(`dynamo-tpu serve`, sdk/serving.py) in place of circus.
"""

from dynamo_tpu.sdk.config import ServiceConfig
from dynamo_tpu.sdk.service import (
    DynamoService,
    async_on_start,
    depends,
    dynamo_endpoint,
    service,
)
from dynamo_tpu.sdk.serving import ServeHandle, serve_graph

__all__ = [
    "ServiceConfig",
    "DynamoService",
    "service",
    "dynamo_endpoint",
    "async_on_start",
    "depends",
    "serve_graph",
    "ServeHandle",
]
