"""ServiceConfig — per-service args from YAML with Common inheritance.

Reference parity: deploy/dynamo/sdk/src/dynamo/sdk/lib/config.py (+ its
test_config.py): top-level keys are service names mapping to arg dicts; a
``Common`` block holds shared values which services opt into via a
``common-configs: [key, ...]`` list; the whole config can be overridden /
injected through the DYNTPU_SERVICE_CONFIG env var (JSON) so spawned
worker processes inherit it without re-reading files.
"""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Any, Optional

__all__ = ["ServiceConfig", "CONFIG_ENV"]

CONFIG_ENV = "DYNTPU_SERVICE_CONFIG"
COMMON_KEY = "Common"
INHERIT_KEY = "common-configs"


class ServiceConfig:
    def __init__(self, data: Optional[dict] = None):
        self.data: dict[str, Any] = data or {}

    # ------------------------------------------------------------------ load
    @classmethod
    def from_yaml(cls, path: str | Path) -> "ServiceConfig":
        import yaml

        with open(path) as f:
            return cls(yaml.safe_load(f) or {})

    @classmethod
    def from_env(cls) -> "ServiceConfig":
        raw = os.environ.get(CONFIG_ENV)
        return cls(json.loads(raw)) if raw else cls()

    def to_env(self) -> dict[str, str]:
        """Env var form for worker subprocesses."""
        return {CONFIG_ENV: json.dumps(self.data)}

    # ----------------------------------------------------------------- query
    def for_service(self, name: str) -> dict[str, Any]:
        """Args for one service: its block, with any ``common-configs`` keys
        filled from the Common block (service-local values win)."""
        block = dict(self.data.get(name, {}))
        common = self.data.get(COMMON_KEY, {})
        for key in block.pop(INHERIT_KEY, []):
            if key not in block and key in common:
                block[key] = common[key]
        return block

    def merged_with(self, overrides: dict) -> "ServiceConfig":
        """New config with service blocks deep-merged (overrides win)."""
        out = {k: dict(v) if isinstance(v, dict) else v for k, v in self.data.items()}
        for svc, block in overrides.items():
            if isinstance(block, dict):
                out.setdefault(svc, {}).update(block)
            else:
                out[svc] = block
        return ServiceConfig(out)
