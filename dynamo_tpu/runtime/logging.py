"""Logging init: DYNTPU_LOG filter, optional JSONL structured output.

Reference parity: lib/runtime/src/logging.rs:62-290 (DYN_LOG env filter,
DYN_LOGGING_JSONL structured mode, custom formatter).
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time

_INITIALIZED = False


class JsonlFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        out = {
            "time": time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(record.created)),
            "level": record.levelname,
            "target": record.name,
            "message": record.getMessage(),
        }
        if record.exc_info:
            out["exception"] = self.formatException(record.exc_info)
        return json.dumps(out)


def init(level: str | None = None) -> None:
    """Idempotent logging setup for workers and CLIs."""
    global _INITIALIZED
    if _INITIALIZED:
        return
    _INITIALIZED = True
    level = level or os.environ.get("DYNTPU_LOG", "INFO")
    handler = logging.StreamHandler(sys.stderr)
    if os.environ.get("DYNTPU_LOGGING_JSONL", "").lower() in ("1", "true"):
        handler.setFormatter(JsonlFormatter())
    else:
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname).1s %(name)s: %(message)s")
        )
    root = logging.getLogger("dynamo_tpu")
    root.setLevel(level.upper())
    root.addHandler(handler)
    root.propagate = False
