"""Wire serde for runtime messages.

Dataclasses used in cross-process requests/responses register here; the
wire form is JSON with a "__type__" tag.  Plain JSON data passes through
untouched.  (The reference uses serde-JSON two-part messages the same way;
pipeline/network.rs.)
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Callable, Type

_REGISTRY: dict[str, Type] = {}


def register(cls: Type) -> Type:
    _REGISTRY[cls.__name__] = cls
    return cls


def _encode_obj(obj: Any) -> Any:
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        d = {f.name: _encode_obj(getattr(obj, f.name)) for f in dataclasses.fields(obj)}
        if type(obj).__name__ in _REGISTRY:
            d["__type__"] = type(obj).__name__
        return d
    if isinstance(obj, dict):
        return {k: _encode_obj(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_encode_obj(v) for v in obj]
    if hasattr(obj, "value") and obj.__class__.__module__ != "builtins":  # enums
        try:
            json.dumps(obj)
            return obj
        except TypeError:
            return obj.value
    return obj


def _decode_obj(data: Any) -> Any:
    if isinstance(data, dict):
        decoded = {k: _decode_obj(v) for k, v in data.items()}
        tname = decoded.pop("__type__", None)
        if tname and tname in _REGISTRY:
            cls = _REGISTRY[tname]
            fields = {f.name for f in dataclasses.fields(cls)}
            return cls(**{k: v for k, v in decoded.items() if k in fields})
        return decoded
    if isinstance(data, list):
        return [_decode_obj(v) for v in data]
    return data


def dumps(obj: Any) -> bytes:
    return json.dumps(_encode_obj(obj), separators=(",", ":")).encode()


def loads(raw: bytes) -> Any:
    if not raw:
        return None
    return _decode_obj(json.loads(raw))


def register_llm_types() -> None:
    """Register the LLM protocol dataclasses (idempotent)."""
    from dynamo_tpu.llm import protocols as p

    for cls in (p.SamplingOptions, p.StopConditions, p.BackendInput, p.LLMEngineOutput):
        register(cls)
