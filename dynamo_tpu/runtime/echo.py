"""Echo engines — the test seam every distributed feature is exercised with.

Reference parity: lib/llm/src/engines.rs:40-100 (EchoEngineCore with
DYN_TOKEN_ECHO_DELAY_MS, EchoEngineFull); used the same way here — pipeline,
router, HTTP and disaggregation tests run against echo engines so no model
weights or TPU are needed.
"""

from __future__ import annotations

import asyncio
import os
from typing import Any, AsyncIterator

from dynamo_tpu.runtime.engine import AsyncEngine, Context

__all__ = ["EchoEngine"]


class EchoEngine(AsyncEngine):
    """Streams each element of the request payload back, one per tick.

    The payload may be a list (token ids) or a string (split into chars).
    Delay between items comes from ``delay_s`` or DYNTPU_TOKEN_ECHO_DELAY_MS.
    """

    def __init__(self, delay_s: float | None = None):
        if delay_s is None:
            delay_s = float(os.environ.get("DYNTPU_TOKEN_ECHO_DELAY_MS", "0")) / 1e3
        self.delay_s = delay_s

    async def _run(self, request: Context) -> AsyncIterator[Any]:
        items = request.data
        for item in items:
            if request.is_stopped:
                break
            if self.delay_s:
                await asyncio.sleep(self.delay_s)
            yield item

    def generate(self, request: Context) -> AsyncIterator[Any]:
        return self._run(request)
