"""Two-part length-prefixed framing: JSON header + binary payload.

Frame layout: [u32 header_len][u32 payload_len][header JSON][payload bytes]
(big-endian).  The header carries control/routing metadata; the payload is
opaque bytes (JSON bodies, or raw tensor data for KV-block transfer, which
must not pay a JSON/base64 tax).  Control frames (stop/kill, and the
fault plane's ping/pong health probes — transports/tcp.py) are
header-only: zero payload, so a probe costs 8 bytes + the header.

Reference parity: lib/runtime/src/pipeline/network/codec/two_part.rs.
"""

from __future__ import annotations

import asyncio
import json
import struct
from typing import Any, Optional

_HDR = struct.Struct(">II")

MAX_FRAME = 1 << 30  # 1 GiB guard

__all__ = ["write_frame", "read_frame", "close_writer", "decode_frames",
           "FrameError"]


class FrameError(Exception):
    pass


async def close_writer(writer: Optional[asyncio.StreamWriter],
                       timeout: float = 2.0) -> None:
    """Close a StreamWriter AND await its transport teardown, bounded.

    ``writer.close()`` alone only schedules the close — nothing awaits
    ``connection_lost``, so shutdown paths that stop at close() leak
    live TCP transports (the sanitizer and DT007 both catch this).  The
    wait is bounded: a transport whose peer never acknowledges the FIN
    must not wedge a drain, and errors are swallowed — the socket may
    already be dead, which is fine on a close path."""
    if writer is None:
        return
    try:
        writer.close()
        await asyncio.wait_for(writer.wait_closed(), timeout)
    except (asyncio.TimeoutError, OSError, RuntimeError):
        pass  # already-dead socket or closing loop: nothing left to tear down


def encode_frame(header: dict[str, Any], payload: bytes = b"") -> bytes:
    hdr = json.dumps(header, separators=(",", ":")).encode()
    return _HDR.pack(len(hdr), len(payload)) + hdr + payload


def write_frame(writer: asyncio.StreamWriter, header: dict[str, Any], payload: bytes = b"") -> None:
    writer.write(encode_frame(header, payload))


def decode_frames(data: bytes) -> list[tuple[dict, bytes]]:
    """Decode a captured byte stream into its complete frames.

    Offline twin of ``read_frame`` for recorded transcripts (the
    protocol plane's channel recorder, wire-fixture tests).  A trailing
    partial frame — a transcript cut mid-frame by a sever or crash — is
    ignored rather than an error; a malformed complete frame still
    raises ``FrameError``.
    """
    frames: list[tuple[dict, bytes]] = []
    off = 0
    while off + _HDR.size <= len(data):
        hlen, plen = _HDR.unpack_from(data, off)
        if hlen > MAX_FRAME or plen > MAX_FRAME:
            raise FrameError(f"oversized frame: header={hlen} payload={plen}")
        end = off + _HDR.size + hlen + plen
        if end > len(data):
            break  # torn tail
        hdr = data[off + _HDR.size:off + _HDR.size + hlen]
        try:
            header = json.loads(hdr)
        except json.JSONDecodeError as e:
            raise FrameError(f"bad frame header: {e}") from e
        frames.append((header, data[end - plen:end] if plen else b""))
        off = end
    return frames


async def read_frame(reader: asyncio.StreamReader) -> Optional[tuple[dict, bytes]]:
    """Read one frame; returns None on clean EOF."""
    try:
        prefix = await reader.readexactly(_HDR.size)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    hlen, plen = _HDR.unpack(prefix)
    if hlen > MAX_FRAME or plen > MAX_FRAME:
        raise FrameError(f"oversized frame: header={hlen} payload={plen}")
    try:
        hdr = await reader.readexactly(hlen)
        payload = await reader.readexactly(plen) if plen else b""
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    try:
        header = json.loads(hdr)
    except json.JSONDecodeError as e:
        raise FrameError(f"bad frame header: {e}") from e
    return header, payload
