"""Transports: the wire layer of the distributed runtime.

Reference parity map (lib/runtime/src/transports/):

  etcd.rs + nats.rs  →  coordinator.py   one lightweight control-plane
                                          service: KV+lease+watch (etcd
                                          semantics), pub/sub subjects and
                                          durable work queues (NATS core +
                                          JetStream semantics)
  pipeline/network/tcp/* + TwoPartCodec
                     →  framing.py, tcp.py  direct duplex worker
                                          connections: request frame out,
                                          response stream back on the same
                                          socket (collapses the reference's
                                          NATS-request + dial-back TCP
                                          response plane into one hop —
                                          lower latency, fewer moving parts)
"""
