"""Direct TCP request plane: serve an AsyncEngine over a duplex socket.

The reference sends requests over NATS and streams responses over a
dial-back TCP connection (pipeline/network/egress/push.rs:88-180,
tcp/server.rs:74).  Here both directions ride ONE connection, multiplexed
by request id — one hop fewer per token, and cancellation (stop/kill
control frames, ref ControlMessage network.rs:58) shares the socket.

Frames (framing.py headers):
  client → server:  {type:"request",  req_id} + payload(serde)
                    {type:"stop"|"kill", req_id}
                    {type:"ping", req_id}
  server → client:  {type:"item", req_id} + payload(serde)
                    {type:"end",  req_id}
                    {type:"error", req_id, error}
                    {type:"pong", req_id}

``ping``/``pong`` is the health-probe plane (fault/health.py): it rides the
same socket as requests, so a pong proves the whole request path — not just
that the port accepts connections.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import os
from typing import Any, AsyncIterator, Callable, Optional

from dynamo_tpu.obs import tracing
from dynamo_tpu.runtime import serde
from dynamo_tpu.runtime.engine import AsyncEngine, Context
from dynamo_tpu.runtime.transports.protocol import FrameType
from dynamo_tpu.runtime.transports.framing import (
    close_writer,
    read_frame,
    write_frame,
)
from dynamo_tpu.runtime.transports.net import DEFAULT_NET

log = logging.getLogger("dynamo_tpu.tcp")

__all__ = [
    "EndpointTcpServer",
    "EndpointTcpClient",
    "TransportError",
    "EndpointDisconnected",
]

_END = object()
_PONG = object()

# Per-stream item-queue bound: under normal operation the consumer (an
# SSE writer, a router hop) drains faster than decode produces, so the
# queue never fills; if a consumer truly wedges, the read loop stops
# buffering at this watermark instead of growing without bound (DT006).
_STREAM_QUEUE_MAX = int(os.environ.get("DYNTPU_STREAM_QUEUE_MAX", "1024"))
# Dial bound: an unroutable peer must not wedge the connect lock (and
# everything queued behind it) for the kernel's full SYN backoff.
_DIAL_TIMEOUT_S = float(os.environ.get("DYNTPU_DIAL_TIMEOUT_S", "30"))


class TransportError(ConnectionError):
    """Typed failure on the endpoint request plane.  Subclasses
    ConnectionError so pre-existing handlers keep working; the fault
    plane (fault/migration.py) keys migration decisions off this type."""


class EndpointDisconnected(TransportError):
    """The peer vanished mid-stream — server death, socket cut, or a
    reset — as opposed to an application error the engine reported."""


class EndpointTcpServer:
    """Serves registered AsyncEngines over TCP; one server per process,
    engines keyed by endpoint name (subject)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, *, net=None):
        self.host = host
        self.port = port
        self._net = net if net is not None else DEFAULT_NET
        self._engines: dict[str, AsyncEngine] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self._conns: set[asyncio.StreamWriter] = set()
        self._handlers: set[asyncio.Task] = set()
        # per-subject in-flight request counts + idle events: the drain
        # lifecycle (Endpoint.drain) waits on these so a deregistered
        # endpoint finishes its live streams before the process stops
        self._inflight: dict[str, int] = {}
        self._idle_events: dict[str, asyncio.Event] = {}
        # deterministic fault-injection seam (fault/injector.py): called
        # with each outbound frame header; may return "drop" (swallow the
        # frame) or "sever" (abort the peer's transport mid-stream)
        self.fault_hook: Optional[Callable[[dict], Optional[str]]] = None

    def register(self, subject: str, engine: AsyncEngine) -> None:
        self._engines[subject] = engine

    def unregister(self, subject: str) -> None:
        self._engines.pop(subject, None)

    # ------------------------------------------------------- drain support
    def inflight(self, subject: str) -> int:
        """Live request count for one registered subject."""
        return self._inflight.get(subject, 0)

    def _track(self, subject: str, delta: int) -> None:
        n = self._inflight.get(subject, 0) + delta
        self._inflight[subject] = n
        ev = self._idle_events.get(subject)
        if n <= 0:
            self._inflight.pop(subject, None)
            if ev:
                ev.set()
        elif ev:
            ev.clear()

    async def wait_idle(self, subject: str, timeout: float = 30.0) -> bool:
        """Block until no request for ``subject`` is in flight (True), or
        the timeout lapses with streams still live (False)."""
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout
        while True:
            ev = self._idle_events.setdefault(subject, asyncio.Event())
            ev.clear()
            # re-check after registering (no await in between): the last
            # stream may have finished before the event existed to be set
            if self._inflight.get(subject, 0) <= 0:
                return True
            remaining = deadline - loop.time()
            if remaining <= 0:
                return False
            try:
                await asyncio.wait_for(ev.wait(), remaining)
            except asyncio.TimeoutError:
                return self._inflight.get(subject, 0) <= 0
            # set() resolved our wait, but a request admitted between the
            # set and this wakeup may have re-cleared the event — loop and
            # re-read the live count instead of trusting the stale wake
            # (drain returning True with a live stream; found by the
            # protocol plane's drain exploration, drain_zero_inflight)

    async def start(self) -> "EndpointTcpServer":
        if self._server is None:
            self._server, self.port = await self._net.start_server(
                self._handle, self.host, self.port)
        return self

    async def stop(self) -> None:
        if self._server:
            self._server.close()
            # sever live connections so wait_closed() (which waits on all
            # handlers in py3.12) returns promptly
            for w in list(self._conns):
                w.close()
            await self._server.wait_closed()
            await self._reap_handlers()
            self._server = None

    async def _reap_handlers(self) -> None:
        """Cancel and await connection handlers still winding down —
        py3.10's wait_closed() doesn't wait on them, and a prompt stop()
        must not leave tasks to be destroyed with the loop."""
        for t in list(self._handlers):
            t.cancel()
        if self._handlers:
            await asyncio.gather(*self._handlers, return_exceptions=True)
        self._handlers.clear()

    async def abort(self) -> None:
        """Hard-kill: drop the listener and RST every live connection
        without flushing — the fault injector's 'worker died mid-stream'.
        Unlike stop(), peers see an abrupt reset, not a clean FIN."""
        if self._server:
            self._server.close()
            for w in list(self._conns):
                try:
                    w.transport.abort()
                except Exception:
                    log.debug("aborting connection transport failed",
                              exc_info=True)
            await self._server.wait_closed()
            await self._reap_handlers()
            self._server = None

    async def _handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        self._conns.add(writer)
        task = asyncio.current_task()
        if task is not None:
            self._handlers.add(task)
            task.add_done_callback(self._handlers.discard)
        contexts: dict[int, Context] = {}
        tasks: dict[int, asyncio.Task] = {}
        wlock = asyncio.Lock()

        async def send(header: dict, payload: bytes = b"") -> None:
            hook = self.fault_hook
            if hook is not None:
                action = hook(header)
                if action == "drop":
                    return
                if action == "sever":
                    try:
                        writer.transport.abort()
                    except Exception:
                        log.debug("fault-hook sever abort failed",
                                  exc_info=True)
                    return
            async with wlock:
                if writer.is_closing():
                    # severed/closed transport: asyncio silently drops
                    # the bytes anyway — don't write into the void
                    # (data-after-sever, the framing guard checks this)
                    return
                try:
                    write_frame(writer, header, payload)
                    await writer.drain()
                except (ConnectionResetError, RuntimeError):
                    pass

        async def run_request(req_id: int, subject: str, data: Any,
                              trace=None) -> None:
            engine = self._engines.get(subject)
            if engine is None:
                await send({"type": FrameType.ERROR, "req_id": req_id,
                            "error": f"no endpoint {subject!r}"})
                return
            ctx = Context(data)
            contexts[req_id] = ctx
            self._track(subject, +1)
            # dtspan: continue the caller's trace across the wire — this
            # task's contextvar carries it into engine.generate
            tracing.attach(trace)
            span = tracing.start_span(
                f"tcp.request.{subject}", attrs={"request_id": ctx.id})
            try:
                async for item in engine.generate(ctx):
                    await send({"type": FrameType.ITEM, "req_id": req_id}, serde.dumps(item))
                await send({"type": FrameType.END, "req_id": req_id})
            except Exception as e:
                log.exception("endpoint %s request failed", subject)
                await send({"type": FrameType.ERROR, "req_id": req_id, "error": str(e)})
            finally:
                span.end()
                self._track(subject, -1)
                contexts.pop(req_id, None)
                tasks.pop(req_id, None)

        try:
            while True:
                frame = await read_frame(reader)
                if frame is None:
                    break
                header, payload = frame
                ftype = header.get("type")
                req_id = header.get("req_id")
                if ftype == FrameType.REQUEST:
                    data = serde.loads(payload)
                    tasks[req_id] = asyncio.ensure_future(
                        run_request(req_id, header.get("subject", ""), data,
                                    trace=tracing.extract(header))
                    )
                elif ftype == FrameType.STOP:
                    ctx = contexts.get(req_id)
                    if ctx:
                        ctx.stop_generating()
                elif ftype == FrameType.KILL:
                    ctx = contexts.get(req_id)
                    if ctx:
                        ctx.kill()
                elif ftype == FrameType.PING:
                    await send({"type": FrameType.PONG, "req_id": req_id})
        finally:
            # peer gone: kill all in-flight requests from this connection
            self._conns.discard(writer)
            try:
                for ctx in contexts.values():
                    ctx.kill()
                pending = [t for t in tasks.values() if not t.done()]
                for t in pending:
                    t.cancel()
                if pending:
                    # await the cancellations so stop()/abort() reaping
                    # this handler leaves no engine task to die with the
                    # loop
                    await asyncio.gather(*pending, return_exceptions=True)
            finally:
                # nested finally: _reap_handlers() cancelling us while we
                # await the gather above must still close the transport
                # (a cancel delivered mid-finally skips trailing lines)
                writer.close()


class EndpointTcpClient(AsyncEngine):
    """Client-side AsyncEngine proxy for one remote endpoint."""

    def __init__(self, host: str, port: int, subject: str, *, net=None):
        self.host = host
        self.port = port
        self.subject = subject
        self._net = net if net is not None else DEFAULT_NET
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._ids = itertools.count(1)
        self._streams: dict[int, asyncio.Queue] = {}
        self._read_task: Optional[asyncio.Task] = None
        self._wlock = asyncio.Lock()
        self._connect_lock = asyncio.Lock()
        self._connected = False
        self._closed = False
        self._idle = asyncio.Event()  # set while no streams are in flight
        self._idle.set()

    async def connect(self) -> "EndpointTcpClient":
        # serialized: concurrent reconnects (several in-flight requests
        # all retrying after a server restart) would otherwise dial twice,
        # overwrite each other's reader/writer, and leave two read loops
        # fighting over one StreamReader
        async with self._connect_lock:
            if self._closed:
                raise ConnectionError("endpoint client is closed")
            if not self._connected:
                # reconnect path: drop the previous socket/read task first
                # so N endpoint restarts don't leak N transports
                if self._read_task is not None:
                    self._read_task.cancel()
                if self._writer is not None:
                    try:
                        await close_writer(self._writer)
                    except Exception:
                        log.debug("closing stale endpoint socket failed",
                                  exc_info=True)
                    # drop the reference NOW: if the dial below fails,
                    # a later close() must not re-close the stale writer
                    self._reader = self._writer = None
                try:
                    self._reader, self._writer = await asyncio.wait_for(
                        self._net.open_connection(self.host, self.port),
                        _DIAL_TIMEOUT_S,
                    )
                except asyncio.TimeoutError:
                    raise TransportError(
                        f"dial {self.host}:{self.port} timed out after "
                        f"{_DIAL_TIMEOUT_S}s"
                    ) from None
                self._read_task = asyncio.ensure_future(
                    self._read_loop(self._reader)
                )
                self._connected = True
        return self

    async def close_when_idle(self, timeout: float = 60.0) -> None:
        """Close once in-flight streams finish (bounded).  Discovery
        deletes can be false positives — a lease that expired behind an
        event-loop stall while the worker is alive and mid-response;
        closing immediately would kill healthy streams.  A genuinely
        dead worker's streams break on their own socket error anyway."""
        try:
            await asyncio.wait_for(self._idle.wait(), timeout)
        except asyncio.TimeoutError:
            pass
        await self.close()

    async def close(self) -> None:
        # under the connect lock + a closed flag: a close() racing a
        # mid-dial connect() must not be overwritten by the dial landing
        # afterwards (leaked socket + live read loop on a closed client)
        self._closed = True
        async with self._connect_lock:
            if self._read_task:
                self._read_task.cancel()
            # close AND await the transport teardown (bounded): stopping
            # at close() leaves a live transport for the sanitizer/GC;
            # null the reference so a second close() is a no-op, not a
            # double-close (the framing guard checks this)
            await close_writer(self._writer)
            self._reader = self._writer = None
            self._connected = False

    @staticmethod
    def _force_put(q: asyncio.Queue, item: Any) -> None:
        """Control markers (end/error/pong/disconnect) must land even on
        a full queue: evict the oldest buffered item to make room — the
        stream is terminating anyway, and a wedged consumer must still
        find its terminal marker when it wakes."""
        while True:
            try:
                q.put_nowait(item)
                return
            except asyncio.QueueFull:
                try:
                    q.get_nowait()
                except asyncio.QueueEmpty:
                    pass  # racing consumer freed space; retry the put

    async def _read_loop(self, reader) -> None:
        try:
            while True:
                frame = await read_frame(reader)
                if frame is None:
                    break
                header, payload = frame
                rid = header.get("req_id")
                q = self._streams.get(rid)
                if q is None:
                    continue
                ftype = header.get("type")
                if ftype == FrameType.ITEM:
                    item = serde.loads(payload)
                    # bounded-queue backpressure (DT006): a wedged
                    # consumer stops the read loop buffering at the
                    # watermark instead of growing without bound.  Poll
                    # rather than block in put(): a consumer cancelled
                    # mid-wait deregisters its stream, and a blocking
                    # put on its dead queue would wedge every stream
                    # multiplexed on this connection.
                    while True:
                        if self._streams.get(rid) is not q:
                            break  # consumer gone: drop the item
                        try:
                            q.put_nowait(item)
                            break
                        except asyncio.QueueFull:
                            await asyncio.sleep(0.01)
                elif ftype == FrameType.END:
                    self._force_put(q, _END)
                elif ftype == FrameType.PONG:
                    self._force_put(q, _PONG)
                elif ftype == FrameType.ERROR:
                    self._force_put(
                        q, RuntimeError(header.get("error", "remote error"))
                    )
        finally:
            # only the CURRENT read loop may do disconnect bookkeeping: a
            # cancelled stale loop (its connection already replaced by a
            # reconnect) must not mark the fresh connection dead or error
            # streams that are healthily served by the new loop
            if reader is self._reader:
                self._connected = False
                for q in self._streams.values():
                    self._force_put(q, EndpointDisconnected(
                        f"endpoint {self.subject!r} connection lost "
                        f"({self.host}:{self.port})"))

    async def _send(self, header: dict, payload: bytes = b"") -> None:
        async with self._wlock:
            try:
                write_frame(self._writer, header, payload)
                await self._writer.drain()
            except Exception:
                # a failed write means THIS socket is dead: mark it so the
                # next generate() (e.g. the service-layer retry) dials
                # fresh instead of deterministically reusing the corpse
                self._connected = False
                raise

    async def ping(self, timeout: float = 1.0) -> float:
        """Round-trip a ping control frame over the live request socket;
        returns the latency in seconds.  Raises TransportError (dead or
        unresponsive peer) — the health prober's suspect signal."""
        try:
            await self.connect()
        except OSError as e:
            if isinstance(e, TransportError):
                raise
            raise TransportError(
                f"dial {self.host}:{self.port} failed: {e}") from e
        req_id = next(self._ids)
        # a probe sees at most pong + disconnect marker; bounded (DT006)
        q: asyncio.Queue = asyncio.Queue(4)
        self._streams[req_id] = q
        self._idle.clear()
        t0 = asyncio.get_running_loop().time()
        try:
            await self._send({"type": FrameType.PING, "req_id": req_id})
            try:
                item = await asyncio.wait_for(q.get(), timeout)
            except asyncio.TimeoutError:
                raise TransportError(
                    f"ping to {self.host}:{self.port} timed out after {timeout}s"
                ) from None
            if item is not _PONG:
                raise TransportError(
                    f"ping to {self.host}:{self.port} failed: {item!r}")
            return asyncio.get_running_loop().time() - t0
        except OSError as e:
            if not isinstance(e, TransportError):
                raise TransportError(
                    f"ping to {self.host}:{self.port} failed: {e}") from e
            raise
        finally:
            self._streams.pop(req_id, None)
            if not self._streams:
                self._idle.set()

    def generate(self, request: Context) -> AsyncIterator[Any]:
        return self._generate(request)

    async def _generate(self, request: Context) -> AsyncIterator[Any]:
        await self.connect()
        req_id = next(self._ids)
        q: asyncio.Queue = asyncio.Queue(_STREAM_QUEUE_MAX)
        # registered BEFORE the send (a reply must not race the
        # registration) — but cleaned up if the send itself fails, or the
        # entry and its queue leak forever
        self._streams[req_id] = q
        self._idle.clear()
        # dtspan: the client-side half of the hop; inject() stamps this
        # span's context on the REQUEST header so the server continues
        # the same trace id
        span = tracing.start_span(
            f"tcp.call.{self.subject}", attrs={"request_id": request.id})
        try:
            await self._send(
                tracing.inject({"type": FrameType.REQUEST, "req_id": req_id,
                                "subject": self.subject}),
                serde.dumps(request.data),
            )
        except BaseException:
            span.end()
            self._streams.pop(req_id, None)
            if not self._streams:
                # mirror the finally-block bookkeeping: without this a
                # failed send on the only in-flight stream leaves _idle
                # cleared and close_when_idle() on a retiring connection
                # waits out its full timeout on an actually-idle client
                self._idle.set()
            raise
        cancel_task = asyncio.ensure_future(request.stopped())
        try:
            while True:
                get_task = asyncio.ensure_future(q.get())
                done, _ = await asyncio.wait(
                    [get_task, cancel_task], return_when=asyncio.FIRST_COMPLETED
                )
                if cancel_task in done and not get_task.done():
                    get_task.cancel()
                    try:
                        await self._send(
                            {"type": FrameType.KILL if request.is_killed else "stop",
                             "req_id": req_id}
                        )
                    except (ConnectionError, RuntimeError, OSError):
                        # peer already gone: cancelling a stream on a dead
                        # socket is a no-op — the read loop surfaces the
                        # disconnect through the queue on its own
                        pass
                    cancel_task = asyncio.ensure_future(asyncio.Event().wait())  # never again
                    continue
                item = get_task.result()
                if item is _END:
                    return
                if isinstance(item, Exception):
                    raise item
                yield item
        finally:
            span.end()
            cancel_task.cancel()
            self._streams.pop(req_id, None)
            if not self._streams:
                self._idle.set()
