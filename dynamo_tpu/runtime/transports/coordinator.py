"""The coordinator — control/event plane for the distributed runtime.

One lightweight asyncio TCP service providing exactly the primitives the
reference gets from etcd + NATS (SURVEY.md §5 "distributed communication
backend", planes 1–3):

  KV + leases + watches   — service discovery, liveness, dynamic config
                            (etcd parity: transports/etcd.rs:40-255)
  pub/sub subjects        — KV events, hit-rate events
                            (NATS core parity: transports/nats.rs)
  durable work queues     — remote prefill queue w/ ack+redelivery
                            (JetStream parity: examples/llm/utils/nats_queue.py)

Failure detection improves on the reference's TTL-only leases: a lease dies
when its owning connection drops (instant) OR when its TTL lapses without
keepalive (backstop) — so a crashed worker vanishes from discovery in
milliseconds, mirroring the etcd lease-expiry → watcher-delete path
(lib/runtime/src/transports/etcd/lease.rs:19-51, component/client.rs:145).

Protocol: two-part frames (framing.py); header {op, id, ...}; replies echo
{id}.  Server pushes carry op "watch_event" / "message" / nothing (queue
deliveries are pull-based replies).
"""

from __future__ import annotations

import asyncio
import base64
import hashlib
import itertools
import json
import logging
import os
import time
from collections import defaultdict, deque
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Callable, Optional

from dynamo_tpu.obs import tracing
from dynamo_tpu.runtime.transports.protocol import CoordOp
from dynamo_tpu.runtime.transports.framing import (
    close_writer,
    read_frame,
    write_frame,
)
from dynamo_tpu.runtime.transports.net import DEFAULT_NET

log = logging.getLogger("dynamo_tpu.coordinator")

# Bound on each coordinator round-trip made while holding the heal lock
# (DT005): a stalled coordinator must surface as a ConnectionError, not
# wedge every lease writer queued behind the heal — the serve_worker
# drain path rides these locks at shutdown.
_HEAL_TIMEOUT_S = float(os.environ.get("DYNTPU_HEAL_TIMEOUT_S", "5"))

# WAL on-disk format version, written as the {"t": "ver"} head record of
# every compacted wal.jsonl (wirecheck WR004)
WAL_VERSION = 1

__all__ = ["CoordinatorServer", "CoordinatorClient"]


def _match(pattern: str, subject: str) -> bool:
    """Exact match, or prefix match when the pattern ends with '>'."""
    if pattern.endswith(">"):
        return subject.startswith(pattern[:-1])
    return pattern == subject


# ============================================================ server ==========


@dataclass
class _Lease:
    lease_id: int
    ttl: float
    expires_at: float
    keys: set[str] = field(default_factory=set)
    conn_id: int = -1


@dataclass
class _QueueItem:
    msg_id: int
    payload: bytes
    header: dict


class CoordinatorServer:
    """``data_dir`` enables durability: unleased KV and queue state are
    appended to a write-ahead log and replayed on restart, so a coordinator
    crash loses no queued remote prefill or registered config (ref: raft-
    backed etcd, transports/etcd.rs:40-255, + JetStream file store,
    examples/llm/utils/nats_queue.py:21-59).  Lease-bound keys are
    deliberately ephemeral — their owners are gone after a restart; they
    re-register through the reconnecting client."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 data_dir: Optional[str] = None, *, net=None):
        self.host = host
        self.port = port
        self._data_dir = Path(data_dir) if data_dir else None
        self._net = net if net is not None else DEFAULT_NET
        # protocol-plane seam: when set, called with a crash-point label
        # ("wal.append.kv", "wal.fsync.qpush", "frame.send.watch_event",
        # ...) at every durability and send boundary.  The checker's hook
        # raises SimulatedCrash at a chosen (label, occurrence) to model a
        # process death there; production leaves it None — one attribute
        # test per boundary, nothing else.
        self.crash_hook: Optional[Callable[[str], None]] = None
        self._wal = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._kv: dict[str, Any] = {}
        self._kv_lease: dict[str, int] = {}
        self._leases: dict[int, _Lease] = {}
        # ids seeded from a ms epoch: a RESTARTED coordinator must never
        # reissue ids (lease/watch/sub ids are client-side handles and
        # instance identities; queue msg ids gate acks — reuse would let a
        # pre-restart consumer ack away someone else's in-flight message)
        self._ids = itertools.count(self._id_epoch())
        # watches: watch_id -> (prefix, writer, conn_id)
        self._watches: dict[int, tuple[str, asyncio.StreamWriter, int]] = {}
        # subs: sub_id -> (pattern, writer, conn_id)
        self._subs: dict[int, tuple[str, asyncio.StreamWriter, int]] = {}
        self._queues: dict[str, deque[_QueueItem]] = defaultdict(deque)
        self._queue_waiters: dict[str, deque[asyncio.Future]] = defaultdict(deque)
        self._pending_acks: dict[tuple[str, int], _QueueItem] = {}
        self._conn_ids = itertools.count(1)
        self._conn_leases: dict[int, set[int]] = defaultdict(set)
        self._expiry_task: Optional[asyncio.Task] = None
        self._write_locks: dict[int, asyncio.Lock] = {}
        self._conn_writers: dict[int, asyncio.StreamWriter] = {}
        # per-connection handler tasks (spawned inside asyncio's Server,
        # where DT008 cannot see them) — reaped in stop()
        self._conn_tasks: dict[int, Optional[asyncio.Task]] = {}
        # blob store (plane 4 — NATS object-store parity, ref
        # lib/llm/src/model_card/model.rs:150-199 publishing model
        # artifacts for remote workers): name -> {size, sha256, meta,
        # file?}.  Payload bytes live on disk under data_dir/blobs
        # (content-addressed by sha256, WAL-indexed) or in memory without
        # a data_dir.  Uploads stream in chunks so multi-GB checkpoints
        # never materialise in one frame or one buffer.
        self._blobs: dict[str, dict] = {}
        self._blob_data: dict[str, bytes] = {}
        self._blob_uploads: dict[int, dict] = {}
        # background tasks (watcher notifies, long queue pulls): retained
        # so their exceptions are logged instead of vanishing at loop
        # teardown, and drained on stop() so no task outlives the server
        self._bg_tasks: set[asyncio.Task] = set()

    def _spawn(self, coro) -> asyncio.Task:
        task = asyncio.ensure_future(coro)
        self._bg_tasks.add(task)
        task.add_done_callback(self._bg_done)
        return task

    def _bg_done(self, task: asyncio.Task) -> None:
        self._bg_tasks.discard(task)
        if not task.cancelled() and task.exception() is not None:
            log.error("coordinator background task failed",
                      exc_info=task.exception())

    @staticmethod
    def _id_epoch() -> int:
        # ~1ms resolution wall-clock, shifted so plenty of ids fit per epoch
        return (int(time.time() * 1e3) & 0x7FFFFFFFFF) << 20

    # ------------------------------------------------------------ durability
    def _log(self, rec: dict) -> None:
        if self._wal is None:
            return
        self._wal.write(json.dumps(rec, separators=(",", ":")) + "\n")
        self._wal.flush()
        if self.crash_hook is not None:
            self.crash_hook(f"wal.append.{rec.get('t')}")

    async def _log_durable(self, rec: dict) -> None:
        """Log + fsync for records whose reply promises durability (queue
        push/ack).  The fsync runs in a worker thread — a synchronous fsync
        on the event loop would stall every connection (keepalives could
        miss their TTL behind a burst of pushes)."""
        if self._wal is None:
            return
        self._log(rec)
        fd = self._wal.fileno()
        await asyncio.get_running_loop().run_in_executor(None, os.fsync, fd)
        if self.crash_hook is not None:
            self.crash_hook(f"wal.fsync.{rec.get('t')}")

    def _recover(self) -> None:
        """Replay the WAL, then rewrite it compacted (current state only)."""
        path = self._data_dir / "wal.jsonl"
        self._data_dir.mkdir(parents=True, exist_ok=True)
        queues: dict[str, dict[int, bytes]] = defaultdict(dict)
        max_id = 0
        if path.exists():
            with path.open() as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        log.warning("truncated WAL record skipped")
                        continue  # torn tail write — ignore
                    t = rec.get("t")
                    if t == "ver":
                        # format marker written at the head of every
                        # compacted WAL; current readers only need to
                        # know it exists (unknown versions still replay
                        # best-effort — the else arm skips unknown "t")
                        pass
                    elif t == "kv":
                        self._kv[rec["key"]] = rec.get("value")
                    elif t == "kvdel":
                        self._kv.pop(rec["key"], None)
                    elif t == "qpush":
                        queues[rec["q"]][rec["mid"]] = base64.b64decode(rec["p"])
                        max_id = max(max_id, rec["mid"])
                    elif t == "qack":
                        queues[rec["q"]].pop(rec["mid"], None)
                    elif t == "blob":
                        # re-index only blobs whose payload file survived
                        if (self._data_dir / "blobs" / rec["file"]).exists():
                            self._blobs[rec["name"]] = {
                                k: rec[k]
                                for k in ("size", "sha256", "meta", "file")
                            }
                    elif t == "blobdel":
                        self._blobs.pop(rec["name"], None)
        for q, items in queues.items():
            for mid, payload in sorted(items.items()):
                self._queues[q].append(_QueueItem(mid, payload, {"queue": q}))
        self._ids = itertools.count(max(max_id + 1, self._id_epoch()))
        # compact: snapshot current state, drop the acked/deleted history
        if self.crash_hook is not None:
            self.crash_hook("wal.compact.write")
        tmp = path.with_suffix(".tmp")
        with tmp.open("w") as f:
            # version tag first (wirecheck WR004): an old server replaying
            # this file skips the unknown "t" harmlessly; a future format
            # bump flips "v" so readers can detect it
            f.write(json.dumps({"t": "ver", "v": WAL_VERSION},
                               separators=(",", ":")) + "\n")
            for key, value in self._kv.items():
                f.write(json.dumps({"t": "kv", "key": key, "value": value},
                                   separators=(",", ":")) + "\n")
            for q, dq in self._queues.items():
                for item in dq:
                    f.write(json.dumps(
                        {"t": "qpush", "q": q, "mid": item.msg_id,
                         "p": base64.b64encode(item.payload).decode()},
                        separators=(",", ":")) + "\n")
            for name, rec in self._blobs.items():
                f.write(json.dumps({"t": "blob", "name": name, **rec},
                                   separators=(",", ":")) + "\n")
            # the rewrite must be as durable as the fsynced records it
            # replaces — flush+fsync file, then fsync the dir after rename
            f.flush()
            os.fsync(f.fileno())
        if self.crash_hook is not None:
            self.crash_hook("wal.compact.rename")
        tmp.replace(path)
        # GC blob-dir litter: temp files from crashed uploads, and payload
        # files no surviving index record references
        bdir = self._data_dir / "blobs"
        if bdir.is_dir():
            referenced = {r["file"] for r in self._blobs.values()
                          if "file" in r}
            for p in bdir.iterdir():
                if p.name.startswith(".up-") or p.name not in referenced:
                    try:
                        p.unlink()
                    except OSError:
                        pass
        dir_fd = os.open(self._data_dir, os.O_RDONLY)
        try:
            os.fsync(dir_fd)
        finally:
            os.close(dir_fd)
        if self.crash_hook is not None:
            self.crash_hook("wal.compact.done")
        self._wal = path.open("a")

    # ------------------------------------------------------------- lifecycle
    async def start(self) -> "CoordinatorServer":
        if self._data_dir is not None:
            self._recover()
        self._server, self.port = await self._net.start_server(
            self._handle, self.host, self.port)
        self._expiry_task = asyncio.ensure_future(self._expiry_loop())
        log.info("coordinator listening on %s:%s", self.host, self.port)
        return self

    async def stop(self) -> None:
        if self._expiry_task:
            self._expiry_task.cancel()
        if self._server:
            self._server.close()
            # sever live client connections so wait_closed() returns (py3.12
            # waits on all connection handlers)
            for w in list(self._conn_writers.values()):
                w.close()
            await self._server.wait_closed()
        # on py<3.12 wait_closed() does NOT wait for connection handlers:
        # cancel-and-reap them, or each _handle task outlives the server
        # (their finally blocks still run the connection-drop cleanup)
        handlers = [t for t in self._conn_tasks.values() if t is not None]
        for t in handlers:
            t.cancel()
        if handlers:
            await asyncio.gather(*handlers, return_exceptions=True)
        # drain retained background tasks (watcher notifies, queue pulls):
        # cancel-then-gather is bounded — nothing here waits on a peer
        for t in list(self._bg_tasks):
            t.cancel()
        if self._bg_tasks:
            await asyncio.gather(*self._bg_tasks, return_exceptions=True)
        if self._wal is not None:
            self._wal.close()
            self._wal = None

    @property
    def url(self) -> str:
        return f"tcp://{self.host}:{self.port}"

    async def _expiry_loop(self) -> None:
        while True:
            await asyncio.sleep(0.5)
            now = time.monotonic()
            for lease in [l for l in self._leases.values() if l.expires_at < now]:
                log.info("lease %s expired", lease.lease_id)
                self._revoke_lease(lease.lease_id)

    # ------------------------------------------------------------ connection
    async def _handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        conn_id = next(self._conn_ids)
        self._write_locks[conn_id] = asyncio.Lock()
        self._conn_writers[conn_id] = writer
        self._conn_tasks[conn_id] = asyncio.current_task()
        try:
            while True:
                frame = await read_frame(reader)
                if frame is None:
                    break
                header, payload = frame
                # dtspan: commands arriving inside a request trace get a
                # server-side span (untraced commands pay one dict lookup)
                trace = tracing.extract(header)
                span = (
                    tracing.start_span(f"coord.{header.get('op')}",
                                       parent=trace)
                    if trace is not None else tracing.NOP_SPAN
                )
                try:
                    await self._dispatch(conn_id, writer, header, payload)
                except Exception as e:  # protocol-level error back to caller
                    log.exception("coordinator op failed: %s", header.get("op"))
                    await self._send(conn_id, writer, {"id": header.get("id"), "error": str(e)})
                finally:
                    span.end()
        finally:
            # connection-drop cleanup: leases, watches, subs, pending queue acks
            for lease_id in list(self._conn_leases.pop(conn_id, ())):
                self._revoke_lease(lease_id)
            for wid in [w for w, (_, _, c) in self._watches.items() if c == conn_id]:
                del self._watches[wid]
            for sid in [s for s, (_, _, c) in self._subs.items() if c == conn_id]:
                del self._subs[sid]
            for (queue, msg_id), item in list(self._pending_acks.items()):
                if item.header.get("conn_id") == conn_id:
                    del self._pending_acks[(queue, msg_id)]
                    self._queue_deliver(queue, item)
            # abandon this connection's in-flight blob uploads
            for up_id in [u for u, st in self._blob_uploads.items()
                          if st.get("conn_id") == conn_id]:
                st = self._blob_uploads.pop(up_id)
                if "file" in st:
                    st["file"].close()
                    try:
                        st["path"].unlink()
                    except OSError:
                        pass
            self._write_locks.pop(conn_id, None)
            self._conn_writers.pop(conn_id, None)
            self._conn_tasks.pop(conn_id, None)
            writer.close()

    async def _send(self, conn_id: int, writer: asyncio.StreamWriter,
                    header: dict, payload: bytes = b"") -> None:
        lock = self._write_locks.get(conn_id)
        if lock is None:
            return
        if self.crash_hook is not None:
            self.crash_hook(
                f"frame.send.{header.get('op') or 'reply'}")
        async with lock:
            try:
                write_frame(writer, header, payload)
                await writer.drain()
            except (ConnectionResetError, RuntimeError):
                pass

    # --------------------------------------------------------------- dispatch
    async def _dispatch(self, conn_id: int, writer: asyncio.StreamWriter,
                        h: dict, payload: bytes) -> None:
        op = h.get("op")
        rid = h.get("id")

        if op == CoordOp.KV_PUT or op == CoordOp.KV_CREATE or op == CoordOp.KV_CREATE_OR_VALIDATE:
            key, value = h["key"], h.get("value")
            exists = key in self._kv
            if op == CoordOp.KV_CREATE and exists:
                await self._send(conn_id, writer, {"id": rid, "ok": False, "exists": True})
                return
            if op == CoordOp.KV_CREATE_OR_VALIDATE and exists:
                ok = self._kv[key] == value
                await self._send(conn_id, writer, {"id": rid, "ok": ok, "exists": True})
                return
            # validate the lease BEFORE any mutation: a failed put must
            # leave the key's previous value, lease binding, WAL record and
            # watchers all untouched
            lease_id = h.get("lease_id")
            lease = self._leases.get(lease_id) if lease_id else None
            if lease_id and lease is None:
                await self._send(conn_id, writer, {"id": rid, "error": "no such lease"})
                return
            # an overwrite changes the key's lease binding: detach from any
            # previous lease so the old owner's expiry can't delete it
            old_lease = self._kv_lease.pop(key, None)
            if old_lease and old_lease in self._leases:
                self._leases[old_lease].keys.discard(key)
            self._kv[key] = value
            if lease is not None:
                lease.keys.add(key)
                self._kv_lease[key] = lease_id
                if not old_lease:
                    # a previously-durable value must not resurrect on
                    # restart now that the key is lease-bound (ephemeral)
                    self._log({"t": "kvdel", "key": key})
            else:
                # only unleased KV is durable; leased state dies with owners
                self._log({"t": "kv", "key": key, "value": value})
            await self._notify_watchers("put", key, value)
            await self._send(conn_id, writer, {"id": rid, "ok": True})

        elif op == CoordOp.KV_GET:
            key = h["key"]
            await self._send(conn_id, writer,
                             {"id": rid, "ok": key in self._kv, "value": self._kv.get(key)})

        elif op == CoordOp.KV_GET_PREFIX:
            prefix = h["prefix"]
            items = {k: v for k, v in self._kv.items() if k.startswith(prefix)}
            await self._send(conn_id, writer, {"id": rid, "ok": True, "items": items})

        elif op == CoordOp.KV_DELETE:
            key = h["key"]
            existed = self._delete_key(key)
            await self._send(conn_id, writer, {"id": rid, "ok": existed})

        elif op == CoordOp.WATCH:
            prefix = h["prefix"]
            watch_id = next(self._ids)
            self._watches[watch_id] = (prefix, writer, conn_id)
            # initial snapshot as put events (etcd get+watch pattern)
            snapshot = {k: v for k, v in self._kv.items() if k.startswith(prefix)}
            await self._send(conn_id, writer,
                             {"id": rid, "ok": True, "watch_id": watch_id, "snapshot": snapshot})

        elif op == CoordOp.UNWATCH:
            self._watches.pop(h["watch_id"], None)
            await self._send(conn_id, writer, {"id": rid, "ok": True})

        elif op == CoordOp.LEASE_CREATE:
            ttl = float(h.get("ttl", 10.0))
            lease_id = next(self._ids)
            self._leases[lease_id] = _Lease(
                lease_id, ttl, time.monotonic() + ttl, conn_id=conn_id
            )
            self._conn_leases[conn_id].add(lease_id)
            await self._send(conn_id, writer, {"id": rid, "ok": True, "lease_id": lease_id})

        elif op == CoordOp.LEASE_KEEPALIVE:
            lease = self._leases.get(h["lease_id"])
            if lease:
                lease.expires_at = time.monotonic() + lease.ttl
            await self._send(conn_id, writer, {"id": rid, "ok": lease is not None})

        elif op == CoordOp.LEASE_REVOKE:
            self._revoke_lease(h["lease_id"])
            await self._send(conn_id, writer, {"id": rid, "ok": True})

        elif op == CoordOp.SUBSCRIBE:
            sub_id = next(self._ids)
            self._subs[sub_id] = (h["subject"], writer, conn_id)
            await self._send(conn_id, writer, {"id": rid, "ok": True, "sub_id": sub_id})

        elif op == CoordOp.UNSUBSCRIBE:
            self._subs.pop(h["sub_id"], None)
            await self._send(conn_id, writer, {"id": rid, "ok": True})

        elif op == CoordOp.PUBLISH:
            subject = h["subject"]
            n = 0
            for sub_id, (pattern, w, cid) in list(self._subs.items()):
                if _match(pattern, subject):
                    await self._send(cid, w, {"op": CoordOp.MESSAGE, "sub_id": sub_id,
                                              "subject": subject}, payload)
                    n += 1
            await self._send(conn_id, writer, {"id": rid, "ok": True, "delivered": n})

        elif op == CoordOp.QUEUE_PUSH:
            item = _QueueItem(next(self._ids), payload, {"queue": h["queue"]})
            await self._log_durable({"t": "qpush", "q": h["queue"], "mid": item.msg_id,
                                     "p": base64.b64encode(payload).decode()})
            self._queue_deliver(h["queue"], item)
            await self._send(conn_id, writer, {"id": rid, "ok": True, "msg_id": item.msg_id})

        elif op == CoordOp.QUEUE_PULL:
            # run as a task: a long pull must not stall this connection's
            # dispatch loop (keepalives and other ops share the socket)
            async def _pull(queue=h["queue"], timeout=h.get("timeout_ms", 0) / 1e3, rid=rid):
                item = await self._queue_take(queue, timeout)
                if item is None:
                    await self._send(conn_id, writer, {"id": rid, "ok": False, "empty": True})
                    return
                if conn_id not in self._write_locks:
                    # the puller's connection died while we waited: its
                    # cleanup sweep (the _handle finally) has already run,
                    # so registering into _pending_acks now would strand
                    # the item forever — no conn-drop pass will ever
                    # redeliver it.  Found by the protocol plane's
                    # queue-sever exploration (no_lost_messages).
                    self._queue_deliver(queue, item)
                    return
                item.header["conn_id"] = conn_id
                self._pending_acks[(queue, item.msg_id)] = item
                await self._send(conn_id, writer,
                                 {"id": rid, "ok": True, "msg_id": item.msg_id}, item.payload)

            self._spawn(_pull())

        elif op == CoordOp.QUEUE_ACK:
            key = (h["queue"], h["msg_id"])
            ok = self._pending_acks.pop(key, None) is not None
            if ok:
                await self._log_durable(
                    {"t": "qack", "q": h["queue"], "mid": h["msg_id"]}
                )
            await self._send(conn_id, writer, {"id": rid, "ok": ok})

        elif op == CoordOp.QUEUE_NACK:
            key = (h["queue"], h["msg_id"])
            item = self._pending_acks.pop(key, None)
            if item is not None:
                self._queue_deliver(h["queue"], item)
            await self._send(conn_id, writer, {"id": rid, "ok": item is not None})

        elif op == CoordOp.QUEUE_LEN:
            n = len(self._queues[h["queue"]]) + sum(
                1 for (q, _) in self._pending_acks if q == h["queue"]
            )
            await self._send(conn_id, writer, {"id": rid, "ok": True, "len": n})

        elif op == CoordOp.BLOB_BEGIN:
            up_id = next(self._ids)
            st: dict = {"conn_id": conn_id, "size": 0,
                        "sha": hashlib.sha256()}
            if self._data_dir is not None:
                bdir = self._data_dir / "blobs"
                bdir.mkdir(parents=True, exist_ok=True)
                st["path"] = bdir / f".up-{up_id}"
                st["file"] = st["path"].open("wb")
            else:
                st["buf"] = bytearray()
            self._blob_uploads[up_id] = st
            await self._send(conn_id, writer,
                             {"id": rid, "ok": True, "upload_id": up_id})

        elif op == CoordOp.BLOB_CHUNK:
            st = self._blob_uploads.get(h["upload_id"])
            if st is None:
                await self._send(conn_id, writer,
                                 {"id": rid, "error": "no such upload"})
                return
            st["sha"].update(payload)
            st["size"] += len(payload)
            if "file" in st:
                # file IO off the event loop: a slow disk must not stall
                # every connection's dispatch
                await asyncio.get_running_loop().run_in_executor(
                    None, st["file"].write, payload
                )
            else:
                st["buf"] += payload
            await self._send(conn_id, writer, {"id": rid, "ok": True})

        elif op == CoordOp.BLOB_COMMIT:
            st = self._blob_uploads.pop(h["upload_id"], None)
            if st is None:
                await self._send(conn_id, writer,
                                 {"id": rid, "error": "no such upload"})
                return
            name = h["name"]
            sha = st["sha"].hexdigest()
            rec = {"size": st["size"], "sha256": sha,
                   "meta": h.get("meta") or {}}
            if "file" in st:
                def _finalize(f=st["file"], src=st["path"],
                              dst=self._data_dir / "blobs" / sha):
                    # flush+fsync of a multi-GB upload off the event loop
                    # (a sync fsync here would stall every connection —
                    # keepalives would miss TTLs behind one big commit);
                    # content-addressed final name: identical re-pushes
                    # and same-bytes-different-name blobs share one file
                    f.flush()
                    os.fsync(f.fileno())
                    f.close()
                    os.replace(src, dst)

                await asyncio.get_running_loop().run_in_executor(
                    None, _finalize
                )
                rec["file"] = sha
                # durable like queue pushes: the ok reply PROMISES the
                # blob survives a crash, so the index record must be
                # fsynced, not merely flushed
                await self._log_durable({"t": "blob", "name": name, **rec})
            else:
                self._blob_data[name] = bytes(st.pop("buf"))
            old = self._blobs.get(name)
            self._blobs[name] = rec
            # GC a superseded payload file nothing references any more
            if old and "file" in old and old["file"] != rec.get("file") \
                    and not any(r.get("file") == old["file"]
                                for r in self._blobs.values()):
                try:
                    (self._data_dir / "blobs" / old["file"]).unlink()
                except OSError:
                    pass
            await self._send(conn_id, writer,
                             {"id": rid, "ok": True, "size": rec["size"],
                              "sha256": sha})

        elif op == CoordOp.BLOB_READ:
            rec = self._blobs.get(h["name"])
            if rec is None:
                await self._send(conn_id, writer,
                                 {"id": rid, "ok": False, "missing": True})
                return
            off = max(0, int(h.get("offset", 0)))
            ln = min(max(1, int(h.get("length", 1 << 20))), 4 << 20)
            if "file" in rec:
                path = self._data_dir / "blobs" / rec["file"]

                def _read(path=path, off=off, ln=ln):
                    with path.open("rb") as f:
                        f.seek(off)
                        return f.read(ln)

                data = await asyncio.get_running_loop().run_in_executor(
                    None, _read
                )
            else:
                data = self._blob_data.get(h["name"], b"")[off:off + ln]
            await self._send(
                conn_id, writer,
                {"id": rid, "ok": True, "size": rec["size"],
                 "sha256": rec["sha256"], "meta": rec["meta"],
                 "eof": off + len(data) >= rec["size"]},
                data,
            )

        elif op == CoordOp.BLOB_STAT:
            rec = self._blobs.get(h["name"])
            await self._send(conn_id, writer,
                             {"id": rid, "ok": rec is not None,
                              **(rec and {k: rec[k] for k in
                                          ("size", "sha256", "meta")} or {})})

        elif op == CoordOp.BLOB_LIST:
            prefix = h.get("prefix", "")
            items = {
                n: {k: r[k] for k in ("size", "sha256", "meta")}
                for n, r in self._blobs.items() if n.startswith(prefix)
            }
            await self._send(conn_id, writer,
                             {"id": rid, "ok": True, "items": items})

        elif op == CoordOp.BLOB_DELETE:
            rec = self._blobs.pop(h["name"], None)
            self._blob_data.pop(h["name"], None)
            if rec is not None and "file" in rec:
                self._log({"t": "blobdel", "name": h["name"]})
                # drop the payload file only when no other name shares it
                if not any(r.get("file") == rec["file"]
                           for r in self._blobs.values()):
                    try:
                        (self._data_dir / "blobs" / rec["file"]).unlink()
                    except OSError:
                        pass
            await self._send(conn_id, writer,
                             {"id": rid, "ok": rec is not None})

        elif op == CoordOp.PING:
            await self._send(conn_id, writer, {"id": rid, "ok": True})

        else:
            await self._send(conn_id, writer, {"id": rid, "error": f"unknown op {op!r}"})

    # ----------------------------------------------------------------- helpers
    def _delete_key(self, key: str) -> bool:
        existed = self._kv.pop(key, None) is not None
        lease_id = self._kv_lease.pop(key, None)
        if lease_id and lease_id in self._leases:
            self._leases[lease_id].keys.discard(key)
        if existed:
            if not lease_id:
                self._log({"t": "kvdel", "key": key})
            self._spawn(self._notify_watchers("delete", key, None))
        return existed

    def _revoke_lease(self, lease_id: int) -> None:
        lease = self._leases.pop(lease_id, None)
        if lease is None:
            return
        self._conn_leases.get(lease.conn_id, set()).discard(lease_id)
        for key in list(lease.keys):
            self._kv.pop(key, None)
            self._kv_lease.pop(key, None)
            # a pre-lease durable value must not resurrect on restart
            self._log({"t": "kvdel", "key": key})
            self._spawn(self._notify_watchers("delete", key, None))

    async def _notify_watchers(self, event: str, key: str, value: Any) -> None:
        for watch_id, (prefix, writer, conn_id) in list(self._watches.items()):
            if key.startswith(prefix):
                await self._send(conn_id, writer, {
                    "op": CoordOp.WATCH_EVENT, "watch_id": watch_id,
                    "event": event, "key": key, "value": value,
                })

    def _queue_deliver(self, queue: str, item: _QueueItem) -> None:
        waiters = self._queue_waiters[queue]
        while waiters:
            fut = waiters.popleft()
            if not fut.done():
                fut.set_result(item)
                return
        self._queues[queue].append(item)

    async def _queue_take(self, queue: str, timeout: float) -> Optional[_QueueItem]:
        q = self._queues[queue]
        if q:
            return q.popleft()
        if timeout <= 0:
            return None
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._queue_waiters[queue].append(fut)
        try:
            return await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            return None


# ============================================================ client ==========


class CoordinatorClient:
    """Async client. Watches and subscriptions deliver via callbacks
    (scheduled on the client's event loop).

    ``reconnect=True`` makes the client survive a coordinator restart: on
    connection loss it redials with backoff and RE-REGISTERS its watches,
    subscriptions, leases, and lease-bound keys (fresh server-side ids,
    stable client-side handles) — so worker discovery heals without any
    caller code.  In-flight calls at the moment of disconnect still raise
    ConnectionError; callers retry (the workers' pull loops already do)."""

    def __init__(self, url: str, reconnect: bool = False, *, net=None):
        # url: tcp://host:port
        hostport = url.split("//", 1)[-1]
        host, port = hostport.rsplit(":", 1)
        self.host, self.port = host, int(port)
        self.reconnect = reconnect
        self._net = net if net is not None else DEFAULT_NET
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._ids = itertools.count(1)
        self._pending: dict[int, asyncio.Future] = {}
        self._read_task: Optional[asyncio.Task] = None
        self._keepalive_tasks: dict[int, asyncio.Task] = {}
        self._write_lock = asyncio.Lock()
        self.closed = asyncio.Event()
        self._closing = False
        # Callbacks are keyed by stable client-side HANDLES (the first
        # server id ever issued); live server ids map back to handles via
        # the *_by_srv tables, rebuilt wholesale on reconnect — so a
        # restarted server reusing id numbers can never misdirect or drop
        # a callback.
        self._watch_cbs: dict[int, Callable[[str, str, Any], None]] = {}
        self._watch_reg: dict[int, str] = {}          # handle -> prefix
        self._watch_by_srv: dict[int, int] = {}       # live watch_id -> handle
        self._watch_keys: dict[int, set] = {}         # handle -> known keys
        self._sub_cbs: dict[int, Callable[[str, bytes], None]] = {}
        self._sub_reg: dict[int, str] = {}            # handle -> subject
        self._sub_by_srv: dict[int, int] = {}         # live sub_id -> handle
        self._lease_srv: dict[int, int] = {}          # handle -> live lease_id
        self._lease_reg: dict[int, float] = {}        # handle -> ttl
        # key -> (value, lease handle, create-exclusive): the flag records
        # kv_create-established keys so heals re-acquire with kv_create
        # (never silently overwriting a new owner's claim)
        self._leased_kv: dict[str, tuple[Any, int, bool]] = {}
        self._reconnect_task: Optional[asyncio.Task] = None
        self._heal_lock = asyncio.Lock()  # serializes expired-lease heals
        self._reconnecting = False
        self._connected = asyncio.Event()  # socket open (internal sends ok)
        self._ready = asyncio.Event()      # re-registration done (user sends ok)
        self._epoch = 0  # bumped on every disconnect; guards stale writes

    async def connect(self) -> "CoordinatorClient":
        self._reader, self._writer = await self._net.open_connection(self.host, self.port)
        self._connected.set()
        self._ready.set()
        self._read_task = asyncio.ensure_future(self._read_loop())
        return self

    async def close(self) -> None:
        self._closing = True
        for t in self._keepalive_tasks.values():
            t.cancel()
        if self._reconnect_task:
            self._reconnect_task.cancel()
        if self._read_task:
            self._read_task.cancel()
        # close AND await the transport teardown (bounded) — stopping at
        # close() leaves a live TCP transport behind at loop shutdown;
        # null the reference so a repeated close() cannot double-close
        await close_writer(self._writer)
        self._writer = None
        self._connected.clear()
        self.closed.set()

    async def _read_loop(self) -> None:
        try:
            while True:
                frame = await read_frame(self._reader)
                if frame is None:
                    break
                header, payload = frame
                op = header.get("op")
                if op == CoordOp.WATCH_EVENT:
                    handle = self._watch_by_srv.get(header["watch_id"])
                    cb = self._watch_cbs.get(handle)
                    if cb:
                        key = header["key"]
                        known = self._watch_keys.setdefault(handle, set())
                        if header["event"] == "put":
                            known.add(key)
                        else:
                            known.discard(key)
                        cb(header["event"], key, header.get("value"))
                elif op == CoordOp.MESSAGE:
                    handle = self._sub_by_srv.get(header["sub_id"])
                    cb = self._sub_cbs.get(handle)
                    if cb:
                        cb(header["subject"], payload)
                else:
                    fut = self._pending.pop(header.get("id"), None)
                    if fut and not fut.done():
                        fut.set_result((header, payload))
        except asyncio.CancelledError:
            pass
        finally:
            # mark disconnected FIRST so no new _call can slip a future in
            # after the sweep below (it would hang forever)
            self._epoch += 1
            self._connected.clear()
            self._ready.clear()
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionError("coordinator connection lost"))
            self._pending.clear()
            if self.reconnect and not self._closing and not self._reconnecting:
                self._reconnect_task = asyncio.ensure_future(self._reconnect_loop())
            elif not self._reconnecting:
                self.closed.set()

    async def _reconnect_loop(self) -> None:
        """Sole owner of redial + re-registration.  A connection that dies
        again mid-re-registration is retried HERE (the dying read loop sees
        _reconnecting and does not spawn a second loop)."""
        self._reconnecting = True
        delay = 0.1
        try:
            while not self._closing:
                # tear down the dead socket first: a server-side sever
                # only half-closes it (EOF), and replacing the reference
                # without closing leaks the old transport at every redial
                if self._writer is not None:
                    try:
                        await close_writer(self._writer)
                    except Exception:
                        log.debug("closing severed writer failed",
                                  exc_info=True)
                    self._writer = None
                try:
                    self._reader, self._writer = await self._net.open_connection(
                        self.host, self.port
                    )
                except OSError:
                    await asyncio.sleep(delay)
                    delay = min(delay * 1.6, 3.0)
                    continue
                self._connected.set()
                self._read_task = asyncio.ensure_future(self._read_loop())
                try:
                    await self._reregister()
                    # only now may USER calls flow: earlier they would hit
                    # stale lease mappings mid-re-registration
                    self._ready.set()
                    log.info("coordinator client reconnected to %s:%s",
                             self.host, self.port)
                    return
                except Exception:
                    log.exception("re-registration failed; redialing")
                    self._connected.clear()
                    try:
                        await close_writer(self._writer)
                    except Exception:
                        log.debug("closing stale writer failed",
                                  exc_info=True)
                    self._writer = None  # the next dial replaces it
                    await asyncio.sleep(delay)
        finally:
            self._reconnecting = False
            if self._closing or not self._connected.is_set():
                self.closed.set()

    async def _reregister(self) -> None:
        """Re-establish server-side state under the fresh connection."""
        self._watch_by_srv.clear()
        self._sub_by_srv.clear()
        for handle, prefix in list(self._watch_reg.items()):
            resp, _ = await self._call({"op": CoordOp.WATCH, "prefix": prefix}, _internal=True)
            self._watch_by_srv[resp["watch_id"]] = handle
            cb = self._watch_cbs.get(handle)
            snapshot = resp.get("snapshot", {})
            if cb:
                # synthesize deletes for keys that vanished while we were
                # down (e.g. a worker that crashed during the outage), then
                # replay the snapshot as puts
                known = self._watch_keys.setdefault(handle, set())
                for k in sorted(known - set(snapshot)):
                    cb("delete", k, None)
                for k, v in snapshot.items():
                    cb("put", k, v)
            self._watch_keys[handle] = set(snapshot)
        for handle, subject in list(self._sub_reg.items()):
            resp, _ = await self._call({"op": CoordOp.SUBSCRIBE, "subject": subject}, _internal=True)
            self._sub_by_srv[resp["sub_id"]] = handle
        for handle, ttl in list(self._lease_reg.items()):
            resp, _ = await self._call({"op": CoordOp.LEASE_CREATE, "ttl": ttl}, _internal=True)
            self._lease_srv[handle] = resp["lease_id"]
        for key, (value, lease_handle, created) in list(self._leased_kv.items()):
            live = self._lease_srv.get(lease_handle)
            if live is None:
                continue  # lease was revoked — never resurrect the key
            if created:
                # same race as the connected-expiry heal: the outage may
                # have outlived the lease TTL, and another process may
                # have legitimately claimed the key since — re-acquire
                # with create-exclusivity.  On conflict, an existing key
                # holding OUR value is the brief-drop case (the server
                # kept our old binding; its old lease will expire) — take
                # it over by rebinding to the fresh lease.  A different
                # value is a new owner: cede.
                resp, _ = await self._call({
                    "op": CoordOp.KV_CREATE, "key": key, "value": value,
                    "lease_id": live,
                }, _internal=True)
                if not resp.get("ok"):
                    cur, _ = await self._call(
                        {"op": CoordOp.KV_GET, "key": key}, _internal=True)
                    if cur.get("ok") and cur.get("value") == value:
                        await self._call({
                            "op": CoordOp.KV_PUT, "key": key, "value": value,
                            "lease_id": live,
                        }, _internal=True)
                    else:
                        log.warning(
                            "reconnect: key %s was claimed by another "
                            "owner during the outage; ceding it", key)
                        del self._leased_kv[key]
            else:
                await self._call({
                    "op": CoordOp.KV_PUT, "key": key, "value": value,
                    "lease_id": live,
                }, _internal=True)

    async def _call(self, header: dict, payload: bytes = b"",
                    _internal: bool = False) -> tuple[dict, bytes]:
        # Never write to a stale half-closed socket (the frame would
        # buffer silently and the future hang forever) — but a
        # reconnecting client WAITS OUT the redial window instead of
        # failing every in-flight caller for a transient drop (an event
        # loop stalled behind an XLA compile is enough to drop the
        # connection under load).  User calls additionally wait out
        # re-registration (lease-handle mappings are stale until it
        # completes); _reregister's own calls ride on _connected alone.
        gate = self._connected if _internal else self._ready
        if not gate.is_set():
            if self._closing or not self.reconnect:
                raise ConnectionError("coordinator disconnected")
            # race the redial against close(): a closing client must not
            # strand callers for the full grace
            g = asyncio.ensure_future(gate.wait())
            c = asyncio.ensure_future(self.closed.wait())
            try:
                await asyncio.wait(
                    {g, c}, return_when=asyncio.FIRST_COMPLETED,
                    timeout=float(os.environ.get("DYNTPU_RECONNECT_GRACE", "10")),
                )
            finally:
                g.cancel()
                c.cancel()
            if not gate.is_set():
                raise ConnectionError("coordinator disconnected")
        epoch = self._epoch
        rid = next(self._ids)
        header["id"] = rid
        tracing.inject(header)  # dtspan: carry the caller's trace context
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        async with self._write_lock:
            if epoch != self._epoch or not self._connected.is_set():
                self._pending.pop(rid, None)
                raise ConnectionError("coordinator disconnected")
            write_frame(self._writer, header, payload)
            await self._writer.drain()
        resp, pl = await fut
        if "error" in resp:
            raise RuntimeError(f"coordinator error: {resp['error']}")
        return resp, pl

    # ----------------------------------------------------------------- KV API
    async def _lease_call(self, header: dict, lease_handle: Optional[int]):
        """``_call`` with the lease handle resolved to its live server id,
        healing an expired-but-keepalive'd lease ONCE on 'no such lease'.

        The keepalive loop heals expiries on its half-TTL tick; a leased
        write landing INSIDE that window (expiry → next tick) would
        otherwise fail hard for a process that is demonstrably alive."""
        try:
            return await self._call(dict(
                header, lease_id=self._lease_srv.get(lease_handle, lease_handle)))
        except RuntimeError as e:
            if "no such lease" not in str(e) or self._closing \
                    or lease_handle not in self._lease_reg \
                    or lease_handle not in self._keepalive_tasks:
                # only keepalive'd leases heal — expiry of an
                # auto_keepalive=False lease is a deliberate signal
                raise
            await self._heal_expired_lease(
                lease_handle, self._lease_reg[lease_handle])
            return await self._call(dict(
                header, lease_id=self._lease_srv.get(lease_handle, lease_handle)))

    async def kv_put(self, key: str, value: Any, lease_id: Optional[int] = None) -> None:
        await self._lease_call(
            {"op": CoordOp.KV_PUT, "key": key, "value": value}, lease_id)
        if lease_id and self.reconnect:
            # a value update must not erase the key's create-exclusive
            # ownership record — heals would revert to blind overwrite
            prev = self._leased_kv.get(key)
            self._leased_kv[key] = (value, lease_id, bool(prev and prev[2]))

    async def kv_create(self, key: str, value: Any, lease_id: Optional[int] = None) -> bool:
        resp, _ = await self._lease_call(
            {"op": CoordOp.KV_CREATE, "key": key, "value": value}, lease_id)
        ok = bool(resp.get("ok"))
        if ok and lease_id and self.reconnect:
            self._leased_kv[key] = (value, lease_id, True)
        return ok

    async def kv_create_or_validate(self, key: str, value: Any) -> bool:
        resp, _ = await self._call({"op": CoordOp.KV_CREATE_OR_VALIDATE, "key": key, "value": value})
        return bool(resp.get("ok"))

    async def kv_get(self, key: str) -> Optional[Any]:
        resp, _ = await self._call({"op": CoordOp.KV_GET, "key": key})
        return resp.get("value") if resp.get("ok") else None

    async def kv_get_prefix(self, prefix: str) -> dict[str, Any]:
        resp, _ = await self._call({"op": CoordOp.KV_GET_PREFIX, "prefix": prefix})
        return resp.get("items", {})

    async def kv_delete(self, key: str) -> bool:
        self._leased_kv.pop(key, None)
        resp, _ = await self._call({"op": CoordOp.KV_DELETE, "key": key})
        return bool(resp.get("ok"))

    async def watch(
        self, prefix: str, callback: Callable[[str, str, Any], None]
    ) -> tuple[int, dict[str, Any]]:
        """Watch a prefix; callback(event, key, value).  Returns
        (watch_id, snapshot-at-watch-start)."""
        resp, _ = await self._call({"op": CoordOp.WATCH, "prefix": prefix})
        handle = resp["watch_id"]  # stable client handle = first server id
        self._watch_cbs[handle] = callback
        self._watch_by_srv[handle] = handle
        self._watch_reg[handle] = prefix
        snapshot = resp.get("snapshot", {})
        self._watch_keys[handle] = set(snapshot)
        return handle, snapshot

    async def unwatch(self, watch_id: int) -> None:
        self._watch_reg.pop(watch_id, None)
        self._watch_cbs.pop(watch_id, None)
        self._watch_keys.pop(watch_id, None)
        live = next(
            (s for s, h in self._watch_by_srv.items() if h == watch_id), watch_id
        )
        self._watch_by_srv.pop(live, None)
        await self._call({"op": CoordOp.UNWATCH, "watch_id": live})

    # -------------------------------------------------------------- lease API
    async def lease_create(self, ttl: float = 10.0, auto_keepalive: bool = True) -> int:
        resp, _ = await self._call({"op": CoordOp.LEASE_CREATE, "ttl": ttl})
        lease_id = resp["lease_id"]
        if self.reconnect:
            self._lease_srv[lease_id] = lease_id
            self._lease_reg[lease_id] = ttl
        if auto_keepalive:
            self._keepalive_tasks[lease_id] = asyncio.ensure_future(
                self._keepalive_loop(lease_id, ttl)
            )
        return lease_id

    async def _keepalive_loop(self, handle: int, ttl: float) -> None:
        # half-TTL ticks (ref transports/etcd/lease.rs:51); resolve the
        # handle each tick — reconnection swaps the server-side lease id
        while True:
            try:
                await asyncio.sleep(ttl / 2)
                resp, _ = await self._call({
                    "op": CoordOp.LEASE_KEEPALIVE,
                    "lease_id": self._lease_srv.get(handle, handle),
                })
                if not resp.get("ok") and handle in self._lease_reg \
                        and not self._closing:
                    # expired while CONNECTED (e.g. the event loop stalled
                    # past the TTL behind a long compile): the server
                    # already dropped the lease and deleted its keys.  The
                    # process is alive, so heal exactly like a reconnect
                    # does — fresh lease, re-put this lease's keys (the
                    # discovery watchers see delete→put and re-add us).
                    await self._heal_expired_lease(handle, ttl)
            except asyncio.CancelledError:
                return
            except (ConnectionError, RuntimeError, OSError):
                if not self.reconnect or self._closing:
                    return  # without reconnect, a lost lease stays lost

    async def _heal_expired_lease(self, handle: int, ttl: float) -> None:
        # serialize heals: the keepalive tick and any number of inline
        # _lease_call heals can race — interleaved lease_create/re-put
        # would strand keys on an orphaned (un-keepalive'd) lease.
        # Every round-trip under the lock is bounded (DT005): a stalled
        # coordinator surfaces as ConnectionError instead of wedging the
        # writers — and the serve_worker drain — queued behind the heal.
        async with self._heal_lock:
            try:
                probe, _ = await asyncio.wait_for(self._call({
                    "op": CoordOp.LEASE_KEEPALIVE,
                    "lease_id": self._lease_srv.get(handle, handle),
                }), _HEAL_TIMEOUT_S)
                if probe.get("ok"):
                    return  # another heal won while we waited on the lock
                resp, _ = await asyncio.wait_for(
                    self._call({"op": CoordOp.LEASE_CREATE, "ttl": ttl}),
                    _HEAL_TIMEOUT_S)
                live = resp["lease_id"]
                log.warning(
                    "lease %x expired while connected; healed as %x and "
                    "re-putting keys", handle, live,
                )
                for key, (value, lh, created) in list(self._leased_kv.items()):
                    if lh != handle:
                        continue
                    if created:
                        # the server-side expiry DELETED the key, so
                        # another process may have legitimately claimed it
                        # since — re-acquire with create-exclusivity and
                        # cede on conflict instead of silently overwriting
                        # the new owner's value and rebinding it to the
                        # healed lease
                        resp, _ = await asyncio.wait_for(self._call({
                            "op": CoordOp.KV_CREATE, "key": key, "value": value,
                            "lease_id": live,
                        }), _HEAL_TIMEOUT_S)
                        if not resp.get("ok"):
                            log.warning(
                                "heal: key %s was claimed by another owner "
                                "during lease expiry; ceding it", key)
                            del self._leased_kv[key]
                    else:
                        await asyncio.wait_for(self._call({
                            "op": CoordOp.KV_PUT, "key": key, "value": value,
                            "lease_id": live,
                        }), _HEAL_TIMEOUT_S)
            except asyncio.TimeoutError:
                raise ConnectionError(
                    "coordinator stalled during lease heal"
                ) from None
            # publish the mapping only AFTER the re-puts: a concurrent
            # writer meanwhile resolves the dead id, fails, and queues
            # behind the heal lock — its retry then lands strictly after
            # these re-puts, so a fresh value can never be reverted by
            # the heal's snapshot
            self._lease_srv[handle] = live

    async def lease_revoke(self, lease_id: int) -> None:
        t = self._keepalive_tasks.pop(lease_id, None)
        if t:
            t.cancel()
        self._lease_reg.pop(lease_id, None)
        # revoked keys must not resurrect through post-reconnect re-puts
        for key in [k for k, v in self._leased_kv.items() if v[1] == lease_id]:
            del self._leased_kv[key]
        live = self._lease_srv.pop(lease_id, lease_id)
        await self._call({"op": CoordOp.LEASE_REVOKE, "lease_id": live})

    # ------------------------------------------------------------- pub/sub API
    async def subscribe(self, subject: str, callback: Callable[[str, bytes], None]) -> int:
        resp, _ = await self._call({"op": CoordOp.SUBSCRIBE, "subject": subject})
        handle = resp["sub_id"]
        self._sub_cbs[handle] = callback
        self._sub_by_srv[handle] = handle
        self._sub_reg[handle] = subject
        return handle

    async def unsubscribe(self, sub_id: int) -> None:
        self._sub_reg.pop(sub_id, None)
        self._sub_cbs.pop(sub_id, None)
        live = next(
            (s for s, h in self._sub_by_srv.items() if h == sub_id), sub_id
        )
        self._sub_by_srv.pop(live, None)
        await self._call({"op": CoordOp.UNSUBSCRIBE, "sub_id": live})

    async def publish(self, subject: str, payload: bytes | dict) -> int:
        if isinstance(payload, dict):
            payload = json.dumps(payload).encode()
        resp, _ = await self._call({"op": CoordOp.PUBLISH, "subject": subject}, payload)
        return resp.get("delivered", 0)

    # --------------------------------------------------------------- queue API
    async def queue_push(self, queue: str, payload: bytes | dict) -> int:
        if isinstance(payload, dict):
            payload = json.dumps(payload).encode()
        resp, _ = await self._call({"op": CoordOp.QUEUE_PUSH, "queue": queue}, payload)
        return resp["msg_id"]

    async def queue_pull(self, queue: str, timeout_s: float = 0.0) -> Optional[tuple[int, bytes]]:
        resp, payload = await self._call(
            {"op": CoordOp.QUEUE_PULL, "queue": queue, "timeout_ms": int(timeout_s * 1e3)}
        )
        if not resp.get("ok"):
            return None
        return resp["msg_id"], payload

    async def queue_len(self, queue: str) -> int:
        """Depth incl. unacked deliveries (disagg router backpressure input)."""
        resp, _ = await self._call({"op": CoordOp.QUEUE_LEN, "queue": queue})
        return int(resp.get("len", 0))

    async def queue_ack(self, queue: str, msg_id: int) -> None:
        await self._call({"op": CoordOp.QUEUE_ACK, "queue": queue, "msg_id": msg_id})

    async def queue_nack(self, queue: str, msg_id: int) -> None:
        await self._call({"op": CoordOp.QUEUE_NACK, "queue": queue, "msg_id": msg_id})

    # ---------------------------------------------------------------- blob API
    async def blob_put(self, name: str, data, meta: Optional[dict] = None,
                       chunk_size: int = 1 << 20) -> dict:
        """Upload a blob: ``data`` is bytes or a filesystem path (streamed
        in chunks — a multi-GB checkpoint never materialises in memory).
        Returns {size, sha256}."""
        resp, _ = await self._call({"op": CoordOp.BLOB_BEGIN})
        up = resp["upload_id"]

        def chunks():
            if isinstance(data, (bytes, bytearray, memoryview)):
                b = bytes(data)
                for i in range(0, max(len(b), 1), chunk_size):
                    yield b[i:i + chunk_size]
            else:
                with open(data, "rb") as f:
                    while True:
                        b = f.read(chunk_size)
                        if not b:
                            return
                        yield b

        for c in chunks():
            await self._call({"op": CoordOp.BLOB_CHUNK, "upload_id": up}, c)
        resp, _ = await self._call(
            {"op": CoordOp.BLOB_COMMIT, "upload_id": up, "name": name,
             "meta": meta or {}}
        )
        return {"size": resp["size"], "sha256": resp["sha256"]}

    async def blob_get(self, name: str, dest=None,
                       chunk_size: int = 1 << 20):
        """Download a blob.  Returns the bytes, or — with ``dest`` (a
        path) — streams to ``dest``.part and renames on completion, so a
        failed or interrupted get never truncates or half-overwrites an
        existing destination.  Returns {size, sha256, meta}."""
        import os as _os

        off = 0
        part = f"{dest}.part" if dest is not None else None
        sink = None  # opened lazily after the first successful read
        buf = bytearray()
        ok = False
        try:
            while True:
                resp, payload = await self._call(
                    {"op": CoordOp.BLOB_READ, "name": name, "offset": off,
                     "length": chunk_size}
                )
                if not resp.get("ok"):
                    raise KeyError(f"no such blob: {name}")
                if dest is not None:
                    if sink is None:
                        sink = open(part, "wb")
                    sink.write(payload)
                else:
                    buf += payload
                off += len(payload)
                if resp.get("eof") or not payload:
                    meta = {"size": resp["size"], "sha256": resp["sha256"],
                            "meta": resp.get("meta", {})}
                    ok = True
                    break
        finally:
            if sink is not None:
                sink.close()
            if dest is not None:
                if ok:
                    if sink is None:  # zero-byte blob: still produce dest
                        open(part, "wb").close()
                    _os.replace(part, dest)
                else:
                    try:
                        _os.unlink(part)
                    except OSError:
                        pass
        return meta if dest is not None else bytes(buf)

    async def blob_stat(self, name: str) -> Optional[dict]:
        resp, _ = await self._call({"op": CoordOp.BLOB_STAT, "name": name})
        if not resp.get("ok"):
            return None
        return {k: resp[k] for k in ("size", "sha256", "meta")}

    async def blob_list(self, prefix: str = "") -> dict[str, dict]:
        resp, _ = await self._call({"op": CoordOp.BLOB_LIST, "prefix": prefix})
        return resp.get("items", {})

    async def blob_delete(self, name: str) -> bool:
        resp, _ = await self._call({"op": CoordOp.BLOB_DELETE, "name": name})
        return bool(resp.get("ok"))

    async def ping(self) -> bool:
        resp, _ = await self._call({"op": CoordOp.PING})
        return bool(resp.get("ok"))
