"""Wire-protocol discriminator constants — the single source of truth.

Every cross-process message in the runtime transports is a framed JSON
header (transports/framing.py) whose dispatch key is a string literal:
the coordinator's ``op``, the TCP endpoint plane's frame ``type``, and
the KV-transfer plane's ``op``.  Scattering those literals across
producer (client) and consumer (server dispatch) modules is exactly the
drift the wire-plane static analysis (analysis/wirecheck.py, rule
WR003) exists to catch — this module removes the drift surface by
giving both sides one name to import.

Plain ``str`` class attributes, not ``enum.Enum``: the values go
straight into ``json.dumps`` headers and ``==`` dispatch comparisons,
and the wire checker resolves ``CoordOp.KV_PUT`` to its literal through
the AST, so a wrapper type would only add indirection on the hot path.
"""

from __future__ import annotations

__all__ = ["CoordOp", "FrameType", "TransferOp", "TRACE_FIELD"]

# Optional trace-context header field (dtspan plane, obs/tracing.py):
# value is a two-element ``[trace_id, span_id]`` list stamped by
# ``obs.tracing.inject`` on TCP REQUEST frames, coordinator commands,
# KV-transfer headers and remote-prefill queue payloads, and read back
# by ``obs.tracing.extract`` on the consuming side.  Absent whenever
# tracing is disabled — every consumer treats it as optional.
TRACE_FIELD = "trace"


class CoordOp:
    """Coordinator request/push header ``op`` values.

    Requests (client -> server, replied to by ``id`` echo) cover the KV,
    watch, lease, pub/sub, queue and blob planes; ``WATCH_EVENT`` and
    ``MESSAGE`` are server-initiated pushes (no ``id``).
    """

    # KV plane
    KV_PUT = "kv_put"
    KV_CREATE = "kv_create"
    KV_CREATE_OR_VALIDATE = "kv_create_or_validate"
    KV_GET = "kv_get"
    KV_GET_PREFIX = "kv_get_prefix"
    KV_DELETE = "kv_delete"
    # watch plane
    WATCH = "watch"
    UNWATCH = "unwatch"
    # lease plane
    LEASE_CREATE = "lease_create"
    LEASE_KEEPALIVE = "lease_keepalive"
    LEASE_REVOKE = "lease_revoke"
    # pub/sub plane
    SUBSCRIBE = "subscribe"
    UNSUBSCRIBE = "unsubscribe"
    PUBLISH = "publish"
    # queue plane
    QUEUE_PUSH = "queue_push"
    QUEUE_PULL = "queue_pull"
    QUEUE_ACK = "queue_ack"
    QUEUE_NACK = "queue_nack"
    QUEUE_LEN = "queue_len"
    # blob plane
    BLOB_BEGIN = "blob_begin"
    BLOB_CHUNK = "blob_chunk"
    BLOB_COMMIT = "blob_commit"
    BLOB_READ = "blob_read"
    BLOB_STAT = "blob_stat"
    BLOB_LIST = "blob_list"
    BLOB_DELETE = "blob_delete"
    # health
    PING = "ping"
    # server -> client pushes
    WATCH_EVENT = "watch_event"
    MESSAGE = "message"


class FrameType:
    """TCP endpoint plane (transports/tcp.py) frame ``type`` values.

    ``REQUEST``/``STOP``/``KILL``/``PING`` flow client -> server;
    ``ITEM``/``END``/``ERROR``/``PONG`` flow server -> client.
    """

    REQUEST = "request"
    STOP = "stop"
    KILL = "kill"
    PING = "ping"
    ITEM = "item"
    END = "end"
    ERROR = "error"
    PONG = "pong"


class TransferOp:
    """KV-block transfer plane (llm/kv/transfer.py) header ``op`` values.

    The ``STREAM_*``/``WRITE_LAYER`` quartet is the layer-wise streamed
    handoff session (llm/kv/stream.py): a versioned ``STREAM_BEGIN``
    opens a per-request session, ``WRITE_LAYER`` frames carry one
    layer's blocks each under a per-session monotonic ``seq``, and
    ``STREAM_END`` closes with a payload sha256 so a torn stream is a
    verifiable miss — never silently-wrong KV.  ``STREAM_ABORT`` is the
    producer-side give-up (fallback to whole-cache ``WRITE_BLOCKS``).
    """

    WRITE_BLOCKS = "write_blocks"
    READ_BLOCKS = "read_blocks"
    NOTIFY = "notify"
    # streamed layer-wise handoff session (llm/kv/stream.py)
    STREAM_BEGIN = "stream_begin"
    WRITE_LAYER = "write_layer"
    STREAM_END = "stream_end"
    STREAM_ABORT = "stream_abort"
