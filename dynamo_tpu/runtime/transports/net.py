"""Pluggable connection factory for the stream transports.

Every place the runtime opens or accepts a TCP stream (coordinator
server/client, endpoint TCP server/client) routes through a ``Net``
instance instead of calling ``asyncio.start_server`` /
``asyncio.open_connection`` directly.  The default ``Net`` is exactly
those calls — zero behavior change, zero hot-path cost (one attribute
lookup at *connection* time, never per frame).

The seam exists for the protocol plane (``analysis/detloop.MemNet``):
an in-memory transport that speaks the same ``framing.py`` bytes over
paired ``StreamReader``s inside a deterministic event loop, so the
model checker can run the real coordinator/drain/replication code with
scheduled severs and crash-point injection, no sockets involved.
"""

from __future__ import annotations

import asyncio
from typing import Awaitable, Callable, Tuple

__all__ = ["Net", "DEFAULT_NET"]

ConnectionCb = Callable[[asyncio.StreamReader, asyncio.StreamWriter],
                        Awaitable[None]]


class Net:
    """Real-socket connection factory (the production default)."""

    async def start_server(self, cb: ConnectionCb, host: str,
                           port: int) -> Tuple[object, int]:
        """Start a stream server; returns ``(server, bound_port)``.

        ``server`` exposes ``close()`` / ``wait_closed()`` like
        ``asyncio.Server`` (MemNet returns its own handle with the same
        surface).
        """
        server = await asyncio.start_server(cb, host, port)
        bound = server.sockets[0].getsockname()[1] if server.sockets else port
        return server, bound

    async def open_connection(
        self, host: str, port: int,
    ) -> Tuple[asyncio.StreamReader, asyncio.StreamWriter]:
        return await asyncio.open_connection(host, port)


DEFAULT_NET = Net()
