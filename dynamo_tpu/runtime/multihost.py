"""Multi-host runtime: one JAX mesh spanning worker processes.

The north-star deployment (BASELINE.md) is a v5e-16 slice = 4 hosts whose
chips form ONE device mesh.  The reference delegates multi-node bootstrap
to its engines (sglang subprocess tp/nnodes/node_rank,
lib/engines/sglang/src/subprocess.rs:59-63; vLLM Ray placement groups,
lib/engines/vllm0_7/src/ray.rs:70-148); this repo owns the engine, so it
owns the bootstrap:

  1. every worker process knows (group, num_processes, process_id),
  2. process 0 publishes its JAX distributed-coordinator address under
     ``mh/{group}/jax_coordinator`` in the control plane (CoordinatorClient
     — the etcd-parity KV store), with a kv_create so restarts can't
     clobber a live rendezvous,
  3. everyone calls ``jax.distributed.initialize(addr, n, pid)``; after
     that ``jax.devices()`` is the GLOBAL device list and a Mesh built
     over it spans all hosts — GSPMD then inserts cross-host collectives
     (ICI within a slice, DCN across slices) exactly like single-host.

Works identically for real TPU pods and the CPU test rig (N processes ×
``--xla_force_host_platform_device_count`` devices, gloo collectives) —
tests/test_multihost.py runs a 2-process × 4-device sharded engine step.
"""

from __future__ import annotations

import asyncio
import os
import socket
import time
from dataclasses import dataclass
from typing import Optional

__all__ = ["MultiHostSpec", "bootstrap", "global_mesh", "spec_from_env"]


@dataclass
class MultiHostSpec:
    num_processes: int = 1
    process_id: int = 0
    group: str = "default"
    # control-plane URL for the rendezvous (coord://host:port); unused when
    # jax_coordinator is given explicitly
    coordinator_url: Optional[str] = None
    # explicit JAX distributed-service address host:port (skips rendezvous)
    jax_coordinator: Optional[str] = None
    # local devices visible to this process (TPU: auto; CPU rig: forced)
    local_device_count: Optional[int] = None

    @property
    def is_multihost(self) -> bool:
        return self.num_processes > 1


def spec_from_env() -> MultiHostSpec:
    """Build a spec from DYN_MH_* env vars (what `dynamo run --nnodes N
    --node-rank R` exports for worker processes)."""
    return MultiHostSpec(
        num_processes=int(os.environ.get("DYN_MH_NPROCS", "1")),
        process_id=int(os.environ.get("DYN_MH_RANK", "0")),
        group=os.environ.get("DYN_MH_GROUP", "default"),
        coordinator_url=os.environ.get("DYN_MH_COORDINATOR"),
        jax_coordinator=os.environ.get("DYN_MH_JAX_COORDINATOR"),
    )


def _free_port() -> int:
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _host_ip() -> str:
    """Best-effort routable address of this host (workers on other hosts
    must reach the JAX coordinator service we start)."""
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(("8.8.8.8", 80))  # no traffic sent — picks the route
        ip = s.getsockname()[0]
        s.close()
        return ip
    except OSError:
        return "127.0.0.1"


async def _rendezvous(spec: MultiHostSpec, timeout: float) -> str:
    """Process 0 publishes its JAX coordinator address; others wait for it."""
    from dynamo_tpu.runtime.transports.coordinator import CoordinatorClient

    key = f"mh/{spec.group}/jax_coordinator"
    client = await CoordinatorClient(spec.coordinator_url).connect()
    try:
        if spec.process_id == 0:
            addr = f"{_host_ip()}:{_free_port()}"
            # kv_create: a stale address from a dead group must not linger —
            # recreate the key if present but unclaimed this epoch
            if not await client.kv_create(key, addr):
                await client.kv_put(key, addr)
            return addr
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            addr = await client.kv_get(key)
            if addr:
                return str(addr)
            await asyncio.sleep(0.1)
        raise TimeoutError(
            f"rendezvous key {key} not published within {timeout}s"
        )
    finally:
        await client.close()


def _run_sync(coro):
    """Run a coroutine to completion whether or not the caller is already
    inside an event loop (the CLI calls bootstrap from async command
    handlers; asyncio.run would raise there)."""
    try:
        asyncio.get_running_loop()
    except RuntimeError:
        return asyncio.run(coro)
    import concurrent.futures

    with concurrent.futures.ThreadPoolExecutor(1) as ex:
        return ex.submit(asyncio.run, coro).result()


def bootstrap(spec: MultiHostSpec, timeout: float = 120.0) -> None:
    """Join this process into the multi-host JAX runtime.  Single-process
    specs are a no-op, so callers can run the same code path everywhere."""
    if not spec.is_multihost:
        return
    addr = spec.jax_coordinator
    if addr is None:
        if spec.coordinator_url is None:
            raise ValueError(
                "multi-host bootstrap needs coordinator_url or jax_coordinator"
            )
        addr = _run_sync(_rendezvous(spec, timeout))
    import jax

    jax.distributed.initialize(
        coordinator_address=addr,
        num_processes=spec.num_processes,
        process_id=spec.process_id,
    )


def global_mesh(shape: tuple[int, ...], axis_names: tuple[str, ...]):
    """Mesh over the GLOBAL device list (all hosts).  Axis order follows
    jax.devices() ordering: devices of one process are contiguous, so the
    LAST mesh axes land within a host (put "model"/TP there — its
    collectives then ride intra-host ICI; "data"/DP spans hosts over DCN,
    the scaling-book layout).  Thin alias over the central constructor
    (utils/mesh.py) so runtime and the sharding lint plane provably
    build the same mesh."""
    from dynamo_tpu.utils.mesh import build_mesh

    return build_mesh(shape, axis_names)
