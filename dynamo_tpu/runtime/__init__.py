"""Runtime core: AsyncEngine abstraction, cancellation contexts, pipelines.

Reference parity: lib/runtime/src/{engine.rs,pipeline.rs,lib.rs}.  The Rust
reference builds on tokio; here the runtime is asyncio-native.  The key
invariant carried over: every request travels with a Context that supports
graceful stop (stop_generating) and hard kill, and cancellation propagates
down a parent→child tree (reference: lib/runtime/src/engine.rs:47-104).
"""

from dynamo_tpu.runtime.engine import (
    AsyncEngine,
    Context,
    EngineStream,
    ResponseStream,
)
from dynamo_tpu.runtime.pipeline import Operator, build_pipeline
from dynamo_tpu.runtime.echo import EchoEngine

__all__ = [
    "AsyncEngine",
    "Context",
    "EngineStream",
    "ResponseStream",
    "Operator",
    "build_pipeline",
    "EchoEngine",
]
