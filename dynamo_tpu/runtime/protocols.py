"""Endpoint addressing — dyn:// URL parsing.

Reference parity: lib/runtime/src/protocols.rs:33-49 (Endpoint
{namespace, component, name} parsed from "dyn://ns.component.endpoint",
with dotted shorthand variants).
"""

from __future__ import annotations

from dataclasses import dataclass

__all__ = ["EndpointAddress", "parse_endpoint_url"]

SCHEME = "dyn://"


@dataclass(frozen=True)
class EndpointAddress:
    namespace: str
    component: str
    name: str

    @property
    def url(self) -> str:
        return f"{SCHEME}{self.namespace}.{self.component}.{self.name}"

    def __iter__(self):
        """Unpack as (namespace, component, name)."""
        return iter((self.namespace, self.component, self.name))


def parse_endpoint_url(url: str, default_namespace: str = "dynamo") -> EndpointAddress:
    """Parse "dyn://ns.component.endpoint"; "component.endpoint" gets the
    default namespace (the reference accepts the same shorthand)."""
    body = url[len(SCHEME):] if url.startswith(SCHEME) else url
    parts = [p for p in body.split(".") if p]
    if len(parts) == 2:
        parts = [default_namespace, *parts]
    if len(parts) != 3:
        raise ValueError(
            f"bad endpoint url {url!r}: want dyn://namespace.component.endpoint"
        )
    return EndpointAddress(*parts)
