"""Layered runtime configuration.

defaults → config file (TOML/YAML/JSON) → DYNTPU_* environment variables,
mirroring the reference's figment stack (lib/runtime/src/config.rs:34-108)
with the env prefix renamed from DYN_RUNTIME_/DYN_WORKER_ to DYNTPU_.
"""

from __future__ import annotations

import dataclasses
import json
import os
try:
    import tomllib
except ModuleNotFoundError:  # Python < 3.11
    import tomli as tomllib
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Optional

ENV_PREFIX = "DYNTPU_"

_TRUTHY = {"1", "true", "yes", "on"}
_FALSY = {"0", "false", "no", "off", ""}


def env_is_truthy(name: str, default: bool = False) -> bool:
    """Reference config.rs:145-176 truthiness helpers."""
    val = os.environ.get(name)
    if val is None:
        return default
    v = val.strip().lower()
    if v in _TRUTHY:
        return True
    if v in _FALSY:
        return False
    raise ValueError(f"env var {name}={val!r} is not a boolean")


@dataclass
class RuntimeConfig:
    """Settings for a worker process."""

    namespace: str = "dynamo"
    component: str = ""
    endpoint: str = ""
    # control-plane coordinator address (the etcd+NATS replacement)
    coordinator_url: str = "tcp://127.0.0.1:6180"
    # static mode: no coordinator, endpoints wired in-process (ref: is_static)
    is_static: bool = False
    # lease TTL for liveness (ref: etcd lease, transports/etcd/lease.rs)
    lease_ttl_s: float = 10.0
    # response-plane TCP server bind
    host: str = "127.0.0.1"
    port: int = 0  # 0 = ephemeral
    num_worker_threads: int = 0  # 0 = asyncio default executor
    extra: dict[str, Any] = field(default_factory=dict)

    @classmethod
    def from_settings(cls, config_path: Optional[str] = None) -> "RuntimeConfig":
        cfg = cls()
        path = config_path or os.environ.get(ENV_PREFIX + "CONFIG")
        if path:
            cfg = cfg._merged(_load_file(Path(path)))
        cfg = cfg._merged(_env_overrides())
        return cfg

    def _merged(self, overrides: dict[str, Any]) -> "RuntimeConfig":
        known = {f.name: f for f in dataclasses.fields(self)}
        out = dataclasses.replace(self)
        for k, v in overrides.items():
            k = k.lower()
            if k in known and k != "extra":
                typ = known[k].type
                if typ == "bool" and isinstance(v, str):
                    v = v.strip().lower() in _TRUTHY
                elif typ == "int" and isinstance(v, str):
                    v = int(v)
                elif typ == "float" and isinstance(v, str):
                    v = float(v)
                setattr(out, k, v)
            else:
                out.extra[k] = v
        return out


def _load_file(path: Path) -> dict[str, Any]:
    text = path.read_text()
    if path.suffix == ".toml":
        return tomllib.loads(text)
    if path.suffix in (".yaml", ".yml"):
        import yaml

        return yaml.safe_load(text) or {}
    return json.loads(text)


def _env_overrides() -> dict[str, Any]:
    out = {}
    for key, val in os.environ.items():
        if key.startswith(ENV_PREFIX) and key != ENV_PREFIX + "CONFIG":
            out[key[len(ENV_PREFIX) :].lower()] = val
    return out
