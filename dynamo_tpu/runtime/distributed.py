"""DistributedRuntime — the cluster handle.

Namespace → Component → Endpoint naming, endpoint serving with lease-backed
discovery, clients with watch-driven instance lists and routing modes.

Reference parity: lib/runtime/src/distributed.rs:32 (DistributedRuntime),
component.rs:107-295 (Component/Endpoint/Namespace, key scheme
"{ns}/components/{comp}/{ep}:{lease}"), component/endpoint.rs:57-141
(serve + discovery registration), component/client.rs:52-267 (Client,
RouterMode random/round_robin/direct, wait_for_endpoints, watch-driven
instance updates on lease expiry).

The transports differ by design: discovery/lease/events ride the
coordinator (transports/coordinator.py) and requests ride direct TCP
(transports/tcp.py) — see transports/__init__.py for the mapping.
"""

from __future__ import annotations

import asyncio
import logging
import random as _random
from dataclasses import dataclass
from typing import Any, AsyncIterator, Callable, Optional

from dynamo_tpu.runtime.config import RuntimeConfig
from dynamo_tpu.runtime.engine import AsyncEngine, Context
from dynamo_tpu.runtime.transports.coordinator import CoordinatorClient
from dynamo_tpu.runtime.transports.tcp import EndpointTcpClient, EndpointTcpServer

log = logging.getLogger("dynamo_tpu.runtime")

__all__ = ["DistributedRuntime", "Namespace", "Component", "Endpoint", "Client", "Instance"]


@dataclass(frozen=True)
class Instance:
    instance_id: int
    host: str
    port: int
    subject: str
    metadata: dict | None = None


class DistributedRuntime:
    def __init__(self, config: Optional[RuntimeConfig] = None, *, net=None):
        self.config = config or RuntimeConfig()
        # connection factory threaded into every transport this runtime
        # opens (transports/net.py); None = real sockets.  The protocol
        # plane injects its in-memory deterministic transport here.
        self._net = net
        self.coordinator: Optional[CoordinatorClient] = None
        self._tcp_server: Optional[EndpointTcpServer] = None
        self.primary_lease: Optional[int] = None
        # every Endpoint.serve() registers here so drain_all() can run the
        # graceful-drain lifecycle over the whole process on shutdown
        self._served: list[tuple["Endpoint", int]] = []
        # services wired onto this runtime (router subscribers, metric
        # aggregators) register their stop() here; shutdown() runs them
        # first so their background tasks drain before the transports
        # they ride on close (otherwise the tasks leak — dtsan/DT008)
        self._on_shutdown: list[Callable[[], Any]] = []

    @classmethod
    async def connect(cls, config: Optional[RuntimeConfig] = None, *,
                      net=None) -> "DistributedRuntime":
        rt = cls(config, net=net)
        # reconnect=True: a coordinator restart re-registers this runtime's
        # leases, discovery keys, watches and subs automatically
        rt.coordinator = await CoordinatorClient(
            rt.config.coordinator_url, reconnect=True, net=net
        ).connect()
        rt.primary_lease = await rt.coordinator.lease_create(rt.config.lease_ttl_s)
        return rt

    def on_shutdown(self, stop: Callable[[], Any]) -> None:
        """Register an async callable to run first at shutdown()."""
        self._on_shutdown.append(stop)

    async def shutdown(self) -> None:
        stops, self._on_shutdown = self._on_shutdown, []
        for stop in reversed(stops):  # LIFO: later services stop first
            try:
                await stop()
            except Exception:
                log.debug("on_shutdown hook failed", exc_info=True)
        if self._tcp_server:
            await self._tcp_server.stop()
        if self.coordinator:
            await self.coordinator.close()

    async def drain_all(self, timeout: float = 30.0) -> None:
        """Gracefully drain every endpoint this runtime serves: discovery
        keys go first (no new routing), live streams finish, then subjects
        deregister.  Callers follow with shutdown().  The serve_worker
        SIGTERM path rides this so a supervisor downscale / planner role
        flip never amputates in-flight requests."""
        served, self._served = self._served, []
        await asyncio.gather(
            *(ep.drain(lease_id=iid, timeout=timeout) for ep, iid in served),
            return_exceptions=True,
        )

    @property
    def instance_id(self) -> int:
        """This process's cluster identity (its primary lease id)."""
        return self.primary_lease or 0

    async def tcp_server(self) -> EndpointTcpServer:
        """Lazily started shared endpoint server (ref: lazy TCP server,
        distributed.rs)."""
        if self._tcp_server is None:
            self._tcp_server = await EndpointTcpServer(
                host=self.config.host, port=self.config.port, net=self._net
            ).start()
        return self._tcp_server

    def namespace(self, name: str) -> "Namespace":
        return Namespace(self, name)


@dataclass
class Namespace:
    runtime: DistributedRuntime
    name: str

    def component(self, name: str) -> "Component":
        return Component(self.runtime, self.name, name)

    # namespace-scoped events (ref traits/events.rs)
    async def publish(self, subject: str, payload: bytes | dict) -> int:
        return await self.runtime.coordinator.publish(f"{self.name}.{subject}", payload)

    async def subscribe(self, subject: str, cb: Callable[[str, bytes], None]) -> int:
        return await self.runtime.coordinator.subscribe(f"{self.name}.{subject}", cb)


@dataclass
class Component:
    runtime: DistributedRuntime
    namespace: str
    name: str

    def endpoint(self, name: str) -> "Endpoint":
        return Endpoint(self.runtime, self.namespace, self.name, name)

    @property
    def event_prefix(self) -> str:
        return f"{self.namespace}.{self.name}"

    async def publish(self, subject: str, payload: bytes | dict) -> int:
        return await self.runtime.coordinator.publish(f"{self.event_prefix}.{subject}", payload)

    async def subscribe(self, subject: str, cb: Callable[[str, bytes], None]) -> int:
        return await self.runtime.coordinator.subscribe(f"{self.event_prefix}.{subject}", cb)


class Endpoint:
    def __init__(self, runtime: DistributedRuntime, namespace: str, component: str, name: str):
        self.runtime = runtime
        self.namespace = namespace
        self.component = component
        self.name = name

    @property
    def discovery_prefix(self) -> str:
        return f"{self.namespace}/components/{self.component}/endpoints/{self.name}/"

    def subject(self, instance_id: int) -> str:
        # "{ns}_{comp}.{ep}-{lease:x}" in the reference (component.rs:262)
        return f"{self.namespace}_{self.component}.{self.name}-{instance_id:x}"

    @property
    def url(self) -> str:
        return f"dyn://{self.namespace}.{self.component}.{self.name}"

    # ------------------------------------------------------------------ serve
    async def serve(
        self, engine: AsyncEngine, metadata: Optional[dict] = None,
        lease_id: Optional[int] = None,
    ) -> Instance:
        """Register this engine as a live instance of the endpoint."""
        rt = self.runtime
        server = await rt.tcp_server()
        instance_id = lease_id or rt.primary_lease
        subject = self.subject(instance_id)
        server.register(subject, engine)
        info = {
            "instance_id": instance_id,
            "host": server.host,
            "port": server.port,
            "subject": subject,
            "metadata": metadata or {},
        }
        key = f"{self.discovery_prefix}{instance_id:x}"
        created = await rt.coordinator.kv_create(key, info, lease_id=instance_id)
        if not created:
            raise RuntimeError(f"endpoint instance already registered at {key}")
        rt._served.append((self, instance_id))
        log.info("serving %s as instance %x on %s:%s", self.url, instance_id, info["host"], info["port"])
        return Instance(instance_id, info["host"], info["port"], subject, metadata)

    async def drain(self, lease_id: Optional[int] = None, timeout: float = 30.0) -> bool:
        """Graceful drain of this endpoint's instance (ref: the reference
        workers deregister-then-drain on shutdown).  Order matters:

          1. delete the discovery key — routing stops sending new work;
          2. wait for in-flight requests on the subject to finish;
          3. deregister the engine from the TCP server.

        Returns True if the subject went idle inside ``timeout``.  Safe to
        call twice (the second delete/unregister is a no-op)."""
        from dynamo_tpu.fault.counters import counters

        rt = self.runtime
        iid = lease_id or rt.primary_lease
        subject = self.subject(iid)
        counters.drains_in_progress += 1
        try:
            try:
                # bounded: a stalled coordinator must not hold up process
                # shutdown — if the delete can't land, the lease expiry
                # deletes the key for us; keep draining local streams
                await asyncio.wait_for(
                    rt.coordinator.kv_delete(f"{self.discovery_prefix}{iid:x}"),
                    min(2.0, timeout))
            except (ConnectionError, RuntimeError, asyncio.TimeoutError):
                log.warning("drain of %s: discovery delete failed", self.url)
            rt._served = [(e, i) for e, i in rt._served
                          if not (i == iid and e.subject(i) == subject)]
            idle = True
            if rt._tcp_server is not None:
                idle = await rt._tcp_server.wait_idle(subject, timeout)
                if not idle:
                    log.warning(
                        "drain of %s instance %x timed out with %d streams live",
                        self.url, iid, rt._tcp_server.inflight(subject))
                rt._tcp_server.unregister(subject)
            log.info("drained %s instance %x (idle=%s)", self.url, iid, idle)
            return idle
        finally:
            counters.drains_in_progress -= 1

    # ----------------------------------------------------------------- client
    async def client(self) -> "Client":
        c = Client(self)
        await c.start()
        # vended clients die with the runtime: callers that never reach
        # their close() (or forget it) must not leak watch subscriptions
        # and endpoint transports past shutdown (close() is idempotent)
        self.runtime.on_shutdown(c.close)
        return c


class Client(AsyncEngine):
    """Watch-driven endpoint client with routing modes (ref client.rs:52)."""

    def __init__(self, endpoint: Endpoint):
        self.endpoint = endpoint
        self._instances: dict[int, Instance] = {}
        self._conns: dict[int, EndpointTcpClient] = {}
        # round-robin cursor: the LAST instance id handed out.  Tracking
        # the id (not a list index) keeps rotation stable when membership
        # churn reshuffles the sorted id list under us.
        self._rr_last: Optional[int] = None
        # optional fault/health.HealthMonitor (anything with
        # is_suspect(instance_id)); picks deprioritize suspect instances
        self.health = None
        self._watch_id: Optional[int] = None
        self._changed = asyncio.Event()
        # seen-then-deleted instance ids, insertion-ordered so the churn
        # bound evicts OLDEST-first (an arbitrary set.pop() could evict a
        # recently-dead id, handing it back the discovery grace window
        # and re-adding the failover latency the no-grace rule avoids)
        self._removed: dict[int, None] = {}
        self._retiring: set[tuple] = set()  # (conn, drain task) pairs

    async def start(self) -> None:
        coord = self.endpoint.runtime.coordinator
        self._watch_id, snapshot = await coord.watch(
            self.endpoint.discovery_prefix, self._on_event
        )
        for key, value in snapshot.items():
            self._add(value)

    async def close(self) -> None:
        if self._watch_id is not None:
            wid, self._watch_id = self._watch_id, None  # idempotent close
            try:
                await self.endpoint.runtime.coordinator.unwatch(wid)
            except (ConnectionError, RuntimeError):
                pass
        for conn in self._conns.values():
            await conn.close()
        for conn, task in list(self._retiring):
            task.cancel()
            await conn.close()
        self._retiring.clear()

    # ------------------------------------------------------------- discovery
    def _on_event(self, event: str, key: str, value: Any) -> None:
        if event == "put":
            self._add(value)
        elif event == "delete":
            iid = int(key.rsplit("/", 1)[-1], 16)
            self._instances.pop(iid, None)
            self._removed.pop(iid, None)  # re-death refreshes recency
            self._removed[iid] = None
            while len(self._removed) > 1024:  # bound long-lived churn
                del self._removed[next(iter(self._removed))]
            conn = self._conns.pop(iid, None)
            if conn:
                # retire, don't kill: the delete may be a false positive
                # (lease expired behind a stall, worker alive mid-stream).
                # Tracked so Client.close() can reap drains still pending.
                task = asyncio.ensure_future(conn.close_when_idle())
                entry = (conn, task)
                self._retiring.add(entry)
                task.add_done_callback(
                    lambda _t, e=entry: self._retiring.discard(e))
        # swap-then-set: waiters hold the OLD event object, so a consumer
        # can never clear() away a notification another waiter needed
        ev, self._changed = self._changed, asyncio.Event()
        ev.set()

    def _add(self, info: dict) -> None:
        inst = Instance(
            instance_id=info["instance_id"],
            host=info["host"],
            port=info["port"],
            subject=info["subject"],
            metadata=info.get("metadata"),
        )
        self._instances[inst.instance_id] = inst
        self._removed.pop(inst.instance_id, None)

    def instance_ids(self) -> list[int]:
        return sorted(self._instances)

    def instances(self) -> list[Instance]:
        return [self._instances[i] for i in self.instance_ids()]

    async def _wait_until(self, pred, timeout: float) -> bool:
        """Await ``pred()`` truth driven by discovery events; False on
        timeout.  Snapshots the CURRENT change event before re-checking
        the predicate — the notifier swaps in a fresh event on every
        change, so a notification between check and wait is never lost."""
        deadline = asyncio.get_running_loop().time() + timeout
        while True:
            ev = self._changed
            if pred():
                return True
            remaining = deadline - asyncio.get_running_loop().time()
            if remaining <= 0:
                return False
            try:
                await asyncio.wait_for(ev.wait(), remaining)
            except asyncio.TimeoutError:
                pass

    async def wait_for_instances(self, n: int = 1, timeout: float = 30.0) -> list[int]:
        """Block until >= n instances are live (ref wait_for_endpoints)."""
        if not await self._wait_until(lambda: len(self._instances) >= n, timeout):
            raise TimeoutError(
                f"only {len(self._instances)}/{n} instances of {self.endpoint.url}"
            )
        return self.instance_ids()

    # --------------------------------------------------------------- routing
    def _conn(self, instance_id: int) -> EndpointTcpClient:
        inst = self._instances.get(instance_id)
        if inst is None:
            raise KeyError(f"instance {instance_id:x} of {self.endpoint.url} not found")
        conn = self._conns.get(instance_id)
        if conn is None:
            conn = EndpointTcpClient(inst.host, inst.port, inst.subject,
                                     net=self.endpoint.runtime._net)
            self._conns[instance_id] = conn
        return conn

    def _candidate_ids(self, exclude: Optional[set] = None) -> list[int]:
        """Live instance ids minus exclusions, with suspect instances
        deprioritized: a suspect id is only eligible when every healthy id
        is also excluded (better a maybe-dead worker than none)."""
        ids = self.instance_ids()
        if exclude:
            ids = [i for i in ids if i not in exclude] or ids
        if self.health is not None:
            healthy = [i for i in ids if not self.health.is_suspect(i)]
            if healthy:
                return healthy
        return ids

    def pick_random(self, exclude: Optional[set] = None) -> int:
        ids = self._candidate_ids(exclude)
        if not ids:
            raise RuntimeError(f"no instances of {self.endpoint.url}")
        return _random.choice(ids)

    def pick_round_robin(self) -> int:
        ids = self._candidate_ids()
        if not ids:
            raise RuntimeError(f"no instances of {self.endpoint.url}")
        # first id strictly after the last pick, wrapping — the first call
        # starts at ids[0] (no pre-increment skip), and a membership change
        # just continues the rotation from the same cursor id
        if self._rr_last is None:
            pick = ids[0]
        else:
            pick = next((i for i in ids if i > self._rr_last), ids[0])
        self._rr_last = pick
        return pick

    def direct(self, request: Context, instance_id: int) -> AsyncIterator[Any]:
        return self._direct_stream(request, instance_id)

    async def _direct_stream(self, request: Context, instance_id: int):
        if instance_id not in self._instances and instance_id not in self._removed:
            # a KV-aware router can learn a worker (via its event plane)
            # a beat before this client's discovery watch does — give
            # discovery a short grace before declaring the id dead.  Ids
            # this client has seen REGISTER AND THEN DELETE get no grace:
            # that worker positively died, and stalling a pinned request
            # 1s per failover would be pure added TTFT.
            await self._wait_until(
                lambda: instance_id in self._instances
                or instance_id in self._removed,
                1.0,
            )
        async for item in self._conn(instance_id).generate(request):
            yield item

    def random(self, request: Context) -> AsyncIterator[Any]:
        return self._routed_stream(request, self.pick_random)

    def round_robin(self, request: Context) -> AsyncIterator[Any]:
        return self._routed_stream(request, self.pick_round_robin)

    async def _routed_stream(self, request: Context, pick):
        if not self._instances:
            # an empty instance map is usually a transient window — a
            # worker still booting, or the coordinator reconnect replaying
            # this watch's delete→put churn — not a dead deployment; give
            # discovery a moment before declaring "no instances"
            await self._wait_until(lambda: self._instances, 3.0)
        async for item in self._conn(pick()).generate(request):
            yield item

    # default AsyncEngine surface = random routing
    def generate(self, request: Context) -> AsyncIterator[Any]:
        return self.random(request)
