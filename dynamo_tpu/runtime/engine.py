"""The AsyncEngine abstraction — THE core trait of the framework.

An engine turns one request into a stream of responses.  Everything is an
engine: the model executor, the preprocessor-wrapped pipeline, a remote
endpoint client.  Composition of engines is how serving graphs are built.

Reference parity:
  * AsyncEngine trait            — lib/runtime/src/engine.rs:104
  * AsyncEngineContext (stop/kill, is_stopped, stopped_or_killed)
                                 — lib/runtime/src/engine.rs:47-101
  * SingleIn<T> = Context<T>, ManyOut<U> = EngineStream<U>
                                 — lib/runtime/src/pipeline.rs:41-68
  * ResponseStream (stream + context handle)
                                 — lib/runtime/src/engine.rs:116
"""

from __future__ import annotations

import asyncio
import uuid
from abc import ABC, abstractmethod
from typing import Any, AsyncIterator, Generic, Optional, TypeVar

T = TypeVar("T")
U = TypeVar("U")

__all__ = ["Context", "AsyncEngine", "ResponseStream", "EngineStream"]


class Context(Generic[T]):
    """A request envelope: payload + id + hierarchical cancellation.

    ``stop_generating()`` asks the engine to finish gracefully (emit what it
    has, mark the stream complete); ``kill()`` demands immediate abort.
    Cancellation propagates to children (created via :meth:`child`), mirroring
    the reference's CancellationToken tree.
    """

    __slots__ = ("data", "id", "_stop", "_kill", "_children", "annotations")

    def __init__(self, data: T = None, id: Optional[str] = None):
        self.data = data
        self.id = id or uuid.uuid4().hex
        self._stop = asyncio.Event()
        self._kill = asyncio.Event()
        self._children: list["Context"] = []
        # free-form per-request annotations (formatted_prompt, token_ids, ...)
        self.annotations: dict[str, Any] = {}

    # -------------------------------------------------------------- transform
    def map(self, data: U) -> "Context[U]":
        """New payload, same identity and cancellation scope."""
        ctx: Context[U] = Context.__new__(Context)
        ctx.data = data
        ctx.id = self.id
        ctx._stop = self._stop
        ctx._kill = self._kill
        ctx._children = self._children
        ctx.annotations = self.annotations
        return ctx

    def child(self, data: U = None) -> "Context[U]":
        """A child scope: killed/stopped when the parent is, but may be
        cancelled independently without affecting the parent."""
        ctx: Context[U] = Context(data, id=self.id)
        self._children.append(ctx)
        if self._stop.is_set():
            ctx._stop.set()
        if self._kill.is_set():
            ctx._kill.set()
        return ctx

    # ------------------------------------------------------------ cancellation
    def stop_generating(self) -> None:
        self._stop.set()
        for c in self._children:
            c.stop_generating()

    def kill(self) -> None:
        self._kill.set()
        self._stop.set()
        for c in self._children:
            c.kill()

    @property
    def is_stopped(self) -> bool:
        return self._stop.is_set()

    @property
    def is_killed(self) -> bool:
        return self._kill.is_set()

    async def stopped(self) -> None:
        """Wait until stop or kill is requested."""
        await self._stop.wait()

    def __repr__(self) -> str:  # pragma: no cover
        state = "killed" if self.is_killed else "stopped" if self.is_stopped else "live"
        return f"Context(id={self.id[:8]}, {state})"


EngineStream = AsyncIterator  # ManyOut<U> in the reference


class AsyncEngine(ABC, Generic[T, U]):
    """generate(Context[T]) -> async stream of U (ref engine.rs:104)."""

    @abstractmethod
    def generate(self, request: Context[T]) -> AsyncIterator[U]:
        """Return an async iterator of responses.  Implementations must
        respect ``request.is_stopped`` / ``request.is_killed``."""

    async def generate_all(self, request: Context[T]) -> list[U]:
        """Convenience: drain the stream (testing / non-streaming callers)."""
        return [item async for item in self.generate(request)]


class ResponseStream(Generic[U]):
    """An async stream bundled with the context that controls it, so callers
    downstream of a pipeline can still cancel (ref engine.rs:116)."""

    def __init__(self, stream: AsyncIterator[U], context: Context):
        self._stream = stream
        self.context = context

    def __aiter__(self) -> AsyncIterator[U]:
        return self._stream.__aiter__()

    def stop_generating(self) -> None:
        self.context.stop_generating()

    def kill(self) -> None:
        self.context.kill()
