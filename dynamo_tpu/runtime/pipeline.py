"""Pipeline composition: operators around an engine.

A serving pipeline is  frontend → op₁ → op₂ → … → engine, where each operator
transforms the request on the way down (``forward``) and wraps the response
stream on the way back up (``backward``).  The preprocessor (OpenAI→tokens)
and the detokenizing backend are both operators.

Reference parity: lib/runtime/src/pipeline/nodes.rs (ServiceFrontend,
ServiceBackend, Operator with forward/backward edges); the reference's
link-time graph building collapses here to simple functional composition —
idiomatic Python rather than trait-object plumbing.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any, AsyncIterator, Generic, Sequence, TypeVar

from dynamo_tpu.runtime.engine import AsyncEngine, Context

ReqIn = TypeVar("ReqIn")
ReqOut = TypeVar("ReqOut")
RespIn = TypeVar("RespIn")
RespOut = TypeVar("RespOut")

__all__ = ["Operator", "build_pipeline"]


class Operator(ABC, Generic[ReqIn, ReqOut, RespIn, RespOut]):
    """A bidirectional pipeline stage (ref pipeline/nodes.rs Operator)."""

    @abstractmethod
    async def forward(self, request: Context[ReqIn]) -> Context[ReqOut]:
        """Transform the request on its way to the engine."""

    def backward(
        self, stream: AsyncIterator[RespIn], request: Context[ReqIn]
    ) -> AsyncIterator[RespOut]:
        """Transform the response stream on its way back.  Default: identity.

        ``request`` is the *incoming* request this operator saw, so backward
        passes can consult what forward computed (via ``request.annotations``).
        """
        return stream  # type: ignore[return-value]


class _PipelineEngine(AsyncEngine):
    def __init__(self, engine: AsyncEngine, operators: Sequence[Operator]):
        self._engine = engine
        self._operators = list(operators)

    async def _run(self, request: Context) -> AsyncIterator[Any]:
        seen: list[tuple[Operator, Context]] = []
        req = request
        for op in self._operators:
            seen.append((op, req))
            req = await op.forward(req)
        stream = self._engine.generate(req)
        for op, op_req in reversed(seen):
            stream = op.backward(stream, op_req)
        async for item in stream:
            yield item

    def generate(self, request: Context) -> AsyncIterator[Any]:
        return self._run(request)


def build_pipeline(engine: AsyncEngine, *operators: Operator) -> AsyncEngine:
    """Compose ``operators`` (outermost first) around ``engine``.

    ``build_pipeline(e, a, b)``: requests flow a.forward → b.forward → e;
    responses flow e → b.backward → a.backward.
    """
    return _PipelineEngine(engine, operators)
