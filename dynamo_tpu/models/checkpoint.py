"""Native checkpoint format: pre-quantized / pre-converted params on disk.

Serving 8B+ from an HF checkpoint pays bf16 load + int8 quantize at every
engine start; saving the converted params once (orbax, the JAX-native
checkpoint library) turns startup into a direct mmap-friendly restore —
the TPU analogue of the reference pointing vLLM at a pre-quantized FP8
repo (docs/architecture.md:57).  `dynamo-tpu quantize` (cli.py) writes
one; `--model-path <dir>` serves one transparently (detected by the
`dynamo_tpu.json` manifest).

Layout: `<dir>/dynamo_tpu.json` (ModelConfig fields + quantized flag) and
`<dir>/params/` (orbax PyTree checkpoint).  QTensor leaves round-trip as
`{"__qtensor__": {"q": int8, "scale": f32}}` subtrees.
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path
from typing import Any, Optional

import jax

from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.models.quant import QTensor

__all__ = ["save_checkpoint", "load_checkpoint", "is_native_checkpoint"]

MANIFEST = "dynamo_tpu.json"
_QKEY = "__qtensor__"


def is_native_checkpoint(path: str | Path) -> bool:
    return (Path(path) / MANIFEST).is_file()


def _encode(tree: Any) -> Any:
    """QTensor leaves -> plain dict subtrees orbax can store."""
    return jax.tree.map(
        lambda x: {_QKEY: {"q": x.q, "scale": x.scale}}
        if isinstance(x, QTensor) else x,
        tree,
        is_leaf=lambda x: isinstance(x, QTensor),
    )


def _decode(tree: Any) -> Any:
    """Inverse of :func:`_encode` over the restored nested dicts."""
    if isinstance(tree, dict):
        if set(tree.keys()) == {_QKEY}:
            return QTensor(tree[_QKEY]["q"], tree[_QKEY]["scale"])
        return {k: _decode(v) for k, v in tree.items()}
    return tree


def save_checkpoint(path: str | Path, cfg: ModelConfig, params: Any,
                    quantized: bool) -> None:
    """Write config manifest + params under ``path`` (created/overwritten)."""
    import orbax.checkpoint as ocp

    path = Path(path).absolute()
    path.mkdir(parents=True, exist_ok=True)
    # the manifest is the commit marker: removed FIRST (re-converting into
    # an existing checkpoint dir must not leave the old manifest validating
    # half-rewritten params) and written LAST, so an interrupted conversion
    # never leaves a dir that passes is_native_checkpoint
    (path / MANIFEST).unlink(missing_ok=True)
    ckpt = ocp.PyTreeCheckpointer()
    ckpt.save(path / "params", _encode(params), force=True)
    manifest = {
        "format": 1,
        "quantized": quantized,
        "config": dataclasses.asdict(cfg),
    }
    (path / MANIFEST).write_text(json.dumps(manifest, indent=2))


def load_checkpoint(path: str | Path, dtype: Optional[str] = None
                    ) -> tuple[ModelConfig, Any, bool]:
    """Returns (ModelConfig, params, quantized).  ``dtype`` overrides the
    saved activation dtype (weights keep their stored dtype)."""
    import orbax.checkpoint as ocp

    path = Path(path).absolute()
    manifest = json.loads((path / MANIFEST).read_text())
    if manifest.get("format") != 1:
        raise ValueError(f"unknown checkpoint format {manifest.get('format')}")
    cfg_kw = manifest["config"]
    if dtype:
        cfg_kw = {**cfg_kw, "dtype": dtype}
    cfg = ModelConfig(**cfg_kw)
    params = _decode(ocp.PyTreeCheckpointer().restore(path / "params"))
    return cfg, params, bool(manifest.get("quantized"))
