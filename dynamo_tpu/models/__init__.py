"""JAX model implementations — the in-process engine's compute path.

The reference delegates forward passes to external engines (vLLM/SGLang,
SURVEY.md §2.4); here the models are first-class: pure-JAX functions over a
params pytree, written for XLA — lax.scan over homogeneous layers, static
shapes, bfloat16 matmuls on the MXU, shardable over a device mesh via
NamedSharding partition specs supplied alongside the params.
"""

from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.models.llama import LlamaModel

__all__ = ["ModelConfig", "LlamaModel"]
