"""Model architecture configuration (Llama family + MoE extensions).

Loadable from a HuggingFace ``config.json`` so checkpoints drop in directly
(reference analogue: ModelDeploymentCard builds from HF repo contents,
lib/llm/src/model_card/create.rs).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Optional

import jax.numpy as jnp

# Llama-family architectures the unified decoder serves (reference parity:
# vLLM's model zoo; these cover the reference's example deployments —
# Llama/R1-Distill, Mistral, Mixtral MoE, Qwen2/3, Phi3, Gemma 1/2).
SUPPORTED_ARCHITECTURES = {
    "LlamaForCausalLM",
    "MistralForCausalLM",
    "MixtralForCausalLM",
    "Qwen2ForCausalLM",
    "Qwen3ForCausalLM",
    "Qwen3MoeForCausalLM",
    "Phi3ForCausalLM",
    "GemmaForCausalLM",
    "Gemma2ForCausalLM",
}


@dataclass
class ModelConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32
    head_dim: Optional[int] = None  # default hidden_size // num_heads
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-5
    max_position_embeddings: int = 4096
    tie_word_embeddings: bool = False
    # Qwen2-style QKV projection bias (o_proj stays bias-free)
    attention_bias: bool = False
    # Qwen3-style per-head RMSNorm on q and k (over head_dim, before RoPE)
    qk_norm: bool = False
    # Uniform sliding-window size (Mistral/Phi3): attention masks keys
    # older than `window` positions — EXACT HF semantics.  The attention
    # dispatch applies it only when the static context bound can exceed
    # the window (ops/paged_attention.py); deployments whose max_model_len
    # fits inside the window keep the flash kernels (full == windowed
    # there).  Gemma2's interleaved local/global windows are NOT this
    # field — from_hf_config nulls it for Gemma2 with a warning.
    sliding_window: Optional[int] = None
    # MoE (Mixtral-style); num_experts == 0 → dense MLP
    num_experts: int = 0
    num_experts_per_tok: int = 2
    # renormalize top-k router probs (Mixtral always; Qwen3-MoE flag)
    norm_topk_prob: bool = True
    # --- Gemma-family deltas (all default to the Llama behavior) ---
    # MLP activation on the gate branch: "silu" (Llama) or "gelu_tanh"
    # (Gemma GeGLU)
    hidden_activation: str = "silu"
    # RMSNorm multiplies by (1 + weight): Gemma stores zero-centred scales
    rmsnorm_unit_offset: bool = False
    # multiply embeddings by sqrt(hidden_size) after lookup
    scale_embeddings: bool = False
    # Gemma2 sandwich norms: extra post-attention / post-MLP RMSNorms
    post_norms: bool = False
    # rope_scaling (HF config.json): {"rope_type": "llama3"|"linear", ...}
    # — Llama-3.1+ checkpoints REQUIRE llama3 frequency scaling; ignoring
    # it would silently corrupt long-context behavior
    rope_scaling: Optional[dict] = None
    # attention sm_scale = query_pre_attn_scalar**-0.5 (None = head_dim)
    query_pre_attn_scalar: Optional[float] = None
    # tanh softcaps: scores (Gemma2 attn_logit_softcapping) and final logits
    attn_logit_softcap: Optional[float] = None
    final_logit_softcap: Optional[float] = None
    # runtime
    dtype: str = "bfloat16"

    def __post_init__(self):
        if self.head_dim is None:
            self.head_dim = self.hidden_size // self.num_heads

    @property
    def jax_dtype(self):
        return jnp.dtype(self.dtype)

    @property
    def is_moe(self) -> bool:
        return self.num_experts > 0

    @classmethod
    def tiny(cls, **kw) -> "ModelConfig":
        """A toy config for tests (fast CPU compile, exercises GQA)."""
        defaults = dict(
            vocab_size=256,
            hidden_size=64,
            intermediate_size=128,
            num_layers=2,
            num_heads=4,
            num_kv_heads=2,
            max_position_embeddings=512,
            dtype="float32",
        )
        defaults.update(kw)
        return cls(**defaults)

    @classmethod
    def from_hf_config(cls, path_or_dict, dtype: str = "bfloat16") -> "ModelConfig":
        """Build from a HuggingFace config.json (file, dir, or dict)."""
        if isinstance(path_or_dict, (str, Path)):
            p = Path(path_or_dict)
            if p.is_dir():
                p = p / "config.json"
            cfg = json.loads(p.read_text())
        else:
            cfg = dict(path_or_dict)
        archs = cfg.get("architectures") or []
        arch = archs[0] if archs else "LlamaForCausalLM"
        if arch not in SUPPORTED_ARCHITECTURES:
            raise ValueError(
                f"unsupported architecture {arch!r}; supported: "
                f"{sorted(SUPPORTED_ARCHITECTURES)}"
            )
        gemma = arch in ("GemmaForCausalLM", "Gemma2ForCausalLM")
        qwen3_moe = arch == "Qwen3MoeForCausalLM"
        if qwen3_moe and (
            cfg.get("decoder_sparse_step", 1) != 1 or cfg.get("mlp_only_layers")
        ):
            # partially-sparse stacks interleave dense and MoE layers; the
            # scan-over-layers decoder assumes a uniform layer type
            raise ValueError(
                "Qwen3-MoE with decoder_sparse_step != 1 or mlp_only_layers "
                "is not supported (non-uniform layer stack)"
            )
        rs = cfg.get("rope_scaling")
        if rs:
            kind = rs.get("rope_type") or rs.get("type")
            if kind not in ("llama3", "linear", "default", None):
                # longrope/yarn/dynamic are not implemented — be loud, a
                # silently-unscaled rope corrupts every long prompt
                raise ValueError(
                    f"rope_scaling type {kind!r} not supported "
                    "(supported: llama3, linear)"
                )
        act = cfg.get("hidden_activation") or cfg.get("hidden_act") or "silu"
        # original Gemma-1 configs say "gelu" but the canonical weights were
        # trained with tanh-approx GELU (transformers maps it the same way);
        # unknown activations must fail loudly, not silently run SiLU
        act_map = {
            "silu": "silu",
            "gelu": "gelu_tanh",
            "gelu_pytorch_tanh": "gelu_tanh",
            "gelu_tanh": "gelu_tanh",
        }
        if act not in act_map:
            raise ValueError(
                f"unsupported hidden activation {act!r} for {arch}; "
                f"supported: {sorted(act_map)}"
            )
        sliding = cfg.get("sliding_window")
        if sliding and arch in ("Qwen2ForCausalLM", "Qwen3ForCausalLM",
                                "Qwen3MoeForCausalLM"):
            if not cfg.get("use_sliding_window"):
                # HF Qwen configs carry sliding_window but gate it behind
                # use_sliding_window (default False) — honoring the number
                # without the gate would wrongly window full-attention models
                sliding = None
            elif cfg.get("max_window_layers", None) != 0:
                import logging

                # HF windows only layers >= max_window_layers; a uniform
                # window over the scan-over-layers decoder would corrupt
                # the full-attention lower layers — same treatment as
                # Gemma2's interleave: full attention + a loud warning.
                # An ABSENT key means the HF default, which is nonzero
                # (e.g. 28 for Qwen2) — also non-uniform, NOT a uniform
                # window over all layers (ADVICE r5)
                logging.getLogger("dynamo_tpu.models").warning(
                    "%s use_sliding_window with max_window_layers=%s "
                    "(non-uniform layer windows): served with full "
                    "attention — outputs match HF only for contexts "
                    "within the window", arch,
                    cfg.get("max_window_layers", "absent (HF default)"),
                )
                sliding = None
        if sliding and arch == "Gemma2ForCausalLM":
            import logging

            # Gemma2 interleaves LOCAL and GLOBAL layers; a uniform window
            # over the scan-over-layers decoder would corrupt the global
            # layers, so Gemma2 keeps full attention — exact for contexts
            # within the window, divergent beyond it
            logging.getLogger("dynamo_tpu.models").warning(
                "%s sliding_window=%d: interleaved local/global layers are "
                "served with full attention — outputs match HF only for "
                "contexts within the window", arch, sliding,
            )
            sliding = None
        return cls(
            vocab_size=cfg["vocab_size"],
            hidden_size=cfg["hidden_size"],
            # MoE experts use their own width (Qwen3-MoE moe_intermediate_size)
            intermediate_size=(
                cfg["moe_intermediate_size"] if qwen3_moe
                else cfg["intermediate_size"]
            ),
            num_layers=cfg["num_hidden_layers"],
            num_heads=cfg["num_attention_heads"],
            num_kv_heads=cfg.get("num_key_value_heads", cfg["num_attention_heads"]),
            head_dim=cfg.get("head_dim"),
            rope_theta=cfg.get("rope_theta", 10000.0),
            rms_norm_eps=cfg.get("rms_norm_eps", 1e-5),
            max_position_embeddings=cfg.get("max_position_embeddings", 4096),
            # HF Gemma checkpoints tie embeddings and omit the flag
            tie_word_embeddings=cfg.get("tie_word_embeddings", gemma),
            # HF Qwen2 attention always carries QKV bias; Llama exposes an
            # explicit attention_bias flag (default False)
            attention_bias=cfg.get("attention_bias", arch == "Qwen2ForCausalLM"),
            qk_norm=arch in ("Qwen3ForCausalLM", "Qwen3MoeForCausalLM"),
            sliding_window=sliding,
            num_experts=cfg.get("num_local_experts",
                                cfg.get("num_experts", 0) if qwen3_moe else 0),
            num_experts_per_tok=cfg.get("num_experts_per_tok", 2),
            # HF default differs by family: Mixtral always renormalizes,
            # Qwen3MoeConfig defaults the flag to False
            norm_topk_prob=bool(cfg.get("norm_topk_prob", not qwen3_moe)),
            rope_scaling=dict(rs) if rs else None,
            hidden_activation=act_map[act],
            rmsnorm_unit_offset=gemma,
            scale_embeddings=gemma,
            post_norms=arch == "Gemma2ForCausalLM",
            query_pre_attn_scalar=cfg.get("query_pre_attn_scalar"),
            attn_logit_softcap=cfg.get("attn_logit_softcapping"),
            final_logit_softcap=cfg.get("final_logit_softcapping"),
            dtype=dtype,
        )
