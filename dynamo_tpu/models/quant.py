"""Int8 weight-only quantization for TPU serving.

Decode is HBM-bandwidth-bound: each step streams every weight byte once,
so int8 weights (per-output-channel symmetric scales) halve the per-step
weight traffic vs bf16 AND halve the HBM footprint — Llama-3-8B drops
from ~16GB to ~8GB and fits a single v5e chip.  This is the TPU-native
analogue of the reference's published FP8 serving configuration
(/root/reference/docs/architecture.md:57-63: all headline numbers are on
an FP8 70B model); TPU v5e has no fp8 MXU mode, int8 is its native
narrow matmul type.

Design:
  * :class:`QTensor` — a pytree node ``(q: int8, scale: f32)`` that rides
    the existing params dict unchanged, so ``lax.scan`` over stacked
    layers, sharding via ``jax.device_put``, and checkpointing all work
    untouched.  ``scale`` keeps the weight's rank with size-1 reduced
    axes, so scan slicing and sharding specs line up axis-for-axis.
  * Matmuls run ``x @ q.astype(bf16)`` — XLA fuses the int8→bf16 convert
    into the dot's operand load, so HBM reads stay int8 — and apply the
    per-output-channel scale to the (much smaller) output.  The MXU
    accumulates in f32 as usual.
  * Per-channel symmetric scales (amax/127) keep worst-case quantization
    error ~0.4%; the logit-error bound is asserted by
    tests/test_quant.py.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

__all__ = [
    "QTensor", "quantize", "dequantize", "quantize_params", "matmul",
    "take_rows", "align_specs", "prune_specs", "random_qtensor",
    "stacked_channel_axes",
]


def stacked_channel_axes(ndim: int, channel_axes=(-1,)):
    """Channel axes for a possibly layer/expert-stacked matmul weight:
    every leading axis before the final [in, out] pair gets independent
    scales (per-layer, per-expert).  Single source of truth for both
    quantize_params and the direct random-int8 init."""
    if ndim >= 3:
        return tuple(range(ndim - 2)) + tuple(channel_axes)
    return tuple(channel_axes)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class QTensor:
    """Symmetric int8 weight + broadcastable f32 per-channel scale."""

    q: jax.Array      # int8, original weight shape
    scale: jax.Array  # f32, same rank, reduced axes are size 1

    def tree_flatten(self):
        return (self.q, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim


def quantize(w: jax.Array, channel_axes=(-1,)) -> QTensor:
    """Quantize ``w`` to int8 with one scale per channel along
    ``channel_axes`` (amax over all other axes)."""
    axes = tuple(a % w.ndim for a in channel_axes)
    reduce_axes = tuple(a for a in range(w.ndim) if a not in axes)
    wf = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=reduce_axes, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return QTensor(q, scale)


def dequantize(w, dtype=jnp.bfloat16):
    if isinstance(w, QTensor):
        return (w.q.astype(jnp.float32) * w.scale).astype(dtype)
    return w


def matmul(x: jax.Array, w, preferred_element_type=None) -> jax.Array:
    """``x @ w`` for a dense array or QTensor.

    QTensor path: int8 operand streams from HBM, convert fuses into the
    dot, scale applies to the output (valid because the scale is constant
    along every contracted axis — it is per-*output*-channel)."""
    if isinstance(w, QTensor):
        if _pallas_int8_matmul_enabled() and w.q.ndim == 2 and x.ndim >= 2:
            # opt-in dequant-in-kernel path (perf hypothesis #2): falls
            # back when shapes don't tile the kernel's blocks
            y = _pallas_int8_matmul(x, w, preferred_element_type)
            if y is not None:
                return y
        y = jnp.matmul(x, w.q.astype(x.dtype),
                       preferred_element_type=preferred_element_type)
        s = w.scale
        # drop the contracted (penultimate) axis — it is size 1 by
        # construction for matmul weights
        s = jnp.squeeze(s, axis=-2)
        return y * s.astype(y.dtype)
    if preferred_element_type is not None:
        return jnp.matmul(x, w, preferred_element_type=preferred_element_type)
    return x @ w


def _pallas_int8_matmul_enabled() -> bool:
    import os

    flag = os.environ.get("DYNAMO_PALLAS_INT8_MATMUL", "").lower()
    return flag in ("1", "true", "yes") and jax.default_backend() == "tpu"


def _pallas_int8_matmul(x: jax.Array, w: "QTensor", pet):
    """Route a 2-D QTensor matmul through the dequant-in-kernel Pallas
    path; returns None when shapes don't tile (caller falls back)."""
    from dynamo_tpu.ops.pallas.int8_matmul import BK, BM, BN, int8_matmul

    k, n = w.q.shape
    lead = x.shape[:-1]
    m = 1
    for d in lead:
        m *= d
    # route full-BM prefill tiles AND decode-shaped row counts (m a bf16
    # sublane multiple below BM: batch-64 decode runs one [64, bk] block —
    # underfilled MXU rows, but the decode step is weight-bandwidth-bound,
    # and in-kernel dequant is exactly the decode bandwidth hypothesis to
    # A/B).  Row counts that tile neither way fall back to XLA.
    m_ok = m % BM == 0 or (16 <= m < BM and m % 16 == 0)
    if m == 0 or not m_ok or n % min(BN, n) or k % min(BK, k):
        return None
    out = int8_matmul(
        x.reshape(m, k), w.q, jnp.squeeze(w.scale, axis=-2),
        out_dtype=pet or x.dtype,
    )
    return out.reshape(*lead, n)


def take_rows(w, idx: jax.Array, dtype) -> jax.Array:
    """Row lookup (embedding): ``w[idx]`` dequantized to ``dtype``.
    Requires the QTensor scale to be per-row (axis 0)."""
    if isinstance(w, QTensor):
        rows = jnp.take(w.q, idx, axis=0).astype(dtype)
        s = jnp.take(w.scale[..., 0], idx, axis=0)[..., None]
        return rows * s.astype(dtype)
    return jnp.take(w, idx, axis=0)


# params-dict keys quantized by default, with their channel axes.
# Norms, biases and the (tiny, accuracy-critical) MoE router stay dense.
_CHANNEL_AXES = {
    "wq": (-1,), "wk": (-1,), "wv": (-1,), "wo": (-1,),
    "w_gate": (-1,), "w_up": (-1,), "w_down": (-1,),
    "lm_head": (-1,),
    # per-row so the same tensor serves lookup (take) and tied lm_head
    "embed": (0,),
}


def quantize_params(params: dict) -> dict:
    """Quantize a Llama-family params pytree in place-shape: every matmul
    weight becomes a QTensor, everything else passes through unchanged.
    MoE expert stacks keep the expert axis as an extra channel axis so
    each expert is scaled independently."""

    def walk(d):
        out = {}
        for k, v in d.items():
            if isinstance(v, dict):
                out[k] = walk(v)
            elif k in _CHANNEL_AXES:
                axes = _CHANNEL_AXES[k]
                if k != "embed":
                    axes = stacked_channel_axes(v.ndim, axes)
                out[k] = quantize(v, axes)
            else:
                out[k] = v
        return out

    return walk(params)


def random_qtensor(key, shape, fan_in: int, channel_axes=(-1,)) -> QTensor:
    """Directly synthesize a random quantized weight (bench/test init):
    avoids materializing the bf16 tensor first, which for 8B would not
    fit the chip the int8 path exists to fit."""
    q = jax.random.randint(key, shape, -127, 128, dtype=jnp.int8)
    # match dense init's N(0, 1/fan_in) std: int8 uniform has std ~73.3
    sshape = tuple(
        shape[i] if i in tuple(a % len(shape) for a in channel_axes) else 1
        for i in range(len(shape))
    )
    scale = jnp.full(sshape, 1.0 / (73.3 * fan_in ** 0.5), jnp.float32)
    return QTensor(q, scale)


def _scale_spec(spec: P, qt: QTensor) -> P:
    """Sharding spec for the scale: inherit the weight's spec on axes the
    scale actually has (size > 1), replicate the reduced axes."""
    entries = list(spec) + [None] * (qt.q.ndim - len(spec))
    return P(*[
        e if qt.scale.shape[i] != 1 else None
        for i, e in enumerate(entries[: qt.q.ndim])
    ])


def prune_specs(params, specs, mesh):
    """Drop mesh axes that don't divide the annotated array dimension.

    ``device_put`` refuses an explicit sharding whose axis doesn't divide
    the dim (e.g. an MoE expert FFN dim of 128 over tp=3); replicating
    that axis is always CORRECT — each device just keeps the full dim —
    so any model runs on any mesh, merely without that one split.  Run
    BEFORE :func:`align_specs` (operates on the plain spec tree against
    array/QTensor shapes)."""

    def one(p, s):
        entries = list(s) + [None] * (p.ndim - len(tuple(s)))
        out = []
        for i, e in enumerate(entries[: p.ndim]):
            if e is None:
                out.append(None)
                continue
            names = e if isinstance(e, tuple) else (e,)
            size = 1
            for nm in names:
                size *= mesh.shape[nm]
            out.append(e if p.shape[i] % size == 0 else None)
        return P(*out)

    return jax.tree_util.tree_map(
        one, params, specs, is_leaf=lambda x: isinstance(x, QTensor)
    )


def align_specs(params, specs):
    """Mirror a PartitionSpec pytree onto a (possibly quantized) params
    pytree: wherever params holds a QTensor, the flat spec fans out into a
    QTensor-of-specs so ``jax.device_put(params, tree-of-shardings)``
    sees matching structures."""
    return jax.tree_util.tree_map(
        lambda p, s: QTensor(s, _scale_spec(s, p)) if isinstance(p, QTensor) else s,
        params, specs,
        is_leaf=lambda x: isinstance(x, QTensor),
    )
