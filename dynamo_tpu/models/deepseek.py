"""DeepSeek-V2 family (MLA + DeepSeekMoE) for paged serving.

Multi-head Latent Attention projects hidden states through low-rank
latents (``kv_a`` → norm → ``kv_b``) and splits queries/keys into a
no-position part and a small rotary part shared across heads; the MoE
layers combine softmax-routed experts (optionally group-limited routing)
scaled by ``routed_scaling_factor`` with always-on shared experts, and
the first ``first_k_dense_replace`` layers use a plain dense MLP.

TPU mapping:
  * Default ``attn_impl="absorbed"`` — the MLA deployment shape: the
    paged cache stores ONE shared latent row per token (c_hat ‖ roped
    k_pe, width kv_lora_rank+rope), queries absorb kv_b's K-half into
    latent space, attention runs as GQA with a single KV head, and the
    attended latent expands per head through kv_b's V-half.  This is the
    MLA memory win — the generic pool's K/V axis still holds the row
    twice, so the per-token cost is 2·(kv_lora+rope) (1,152 for
    DeepSeek-V2 vs 49,152 expanded at 128 heads; collapsing the
    duplicate plane is a follow-up) — and is logit-exact vs
    transformers.
  * ``attn_impl="expanded"`` keeps the per-head K/V oracle (V padded to
    qk_head_dim) — parity baseline and debugging aid.
  * Two ``lax.scan`` stacks — dense-MLP layers then MoE layers — because
    the two layer kinds carry different parameter pytrees; attention
    parameters are stacked per group.
  * Routed experts run the same sort-by-expert + ``lax.ragged_dot``
    grouped dispatch as the Llama-family MoE (models/llama.py), sharded
    TP-within-experts.
  * RoPE is DeepSeek's INTERLEAVED complex-pair form (adjacent element
    pairs rotate together), unlike the Llama rotate-half layout.
  * The Pallas attention kernels currently assume lane-friendly head
    dims; serve this family with DYNAMO_DISABLE_PALLAS=1 until an MLA
    kernel lands (the pure-JAX paged path is used in tests).

Reference parity: the reference serves DeepSeek through vLLM (its patch
carries a DeepSeek MoE tweak, container/deps/vllm patch:4074); here the
family is native.  HF oracle: transformers DeepseekV2ForCausalLM.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from dynamo_tpu.models.llama import (
    grouped_expert_dispatch,
    rms_norm,
    rope_inv_freq,
)
# canonical axis names (utils/mesh.py) — same alias convention as llama.py
from dynamo_tpu.utils.mesh import AXIS_MODEL as _TP
from dynamo_tpu.utils.mesh import AXIS_SP
from dynamo_tpu.ops.paged_attention import (
    paged_attention_layer,
    write_kv_cache_layer,
)

Params = Any

__all__ = ["DeepseekConfig", "DeepseekModel", "convert_hf_state_dict"]


@dataclass
class DeepseekConfig:
    vocab_size: int
    hidden_size: int
    num_layers: int
    num_heads: int
    qk_nope_head_dim: int
    qk_rope_head_dim: int
    v_head_dim: int
    kv_lora_rank: int
    q_lora_rank: Optional[int] = None      # None = direct q_proj (V2-Lite)
    intermediate_size: int = 0             # dense-MLP layers
    moe_intermediate_size: int = 0
    n_routed_experts: int = 0
    num_experts_per_tok: int = 0
    n_shared_experts: int = 0
    routed_scaling_factor: float = 1.0
    topk_method: str = "greedy"            # or "group_limited_greedy"
    n_group: int = 1
    topk_group: int = 1
    first_k_dense_replace: int = 0
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    max_position_embeddings: int = 4096
    dtype: str = "bfloat16"
    attention_bias: bool = False
    # "absorbed" (default, the MLA deployment shape: latent cache, one
    # shared KV head) or "expanded" (per-head K/V oracle)
    attn_impl: str = "absorbed"

    @property
    def qk_head_dim(self) -> int:
        return self.qk_nope_head_dim + self.qk_rope_head_dim

    # ---- engine-facing surface (duck-typed like ModelConfig) ----
    @property
    def num_kv_heads(self) -> int:
        return 1 if self.attn_impl == "absorbed" else self.num_heads

    @property
    def head_dim(self) -> int:
        if self.attn_impl == "absorbed":
            return self.kv_lora_rank + self.qk_rope_head_dim
        return self.qk_head_dim  # cache row width (V padded up to it)

    @property
    def jax_dtype(self):
        return {"bfloat16": jnp.bfloat16, "float32": jnp.float32}[self.dtype]

    @classmethod
    def from_hf(cls, cfg) -> "DeepseekConfig":
        """transformers DeepseekV2Config (object or dict) → DeepseekConfig."""
        g = (lambda k, d=None: cfg.get(k, d)) if isinstance(cfg, dict) \
            else (lambda k, d=None: getattr(cfg, k, d))
        # loud rejection of anything this port would get silently WRONG —
        # same policy as ModelConfig's rope_scaling handling
        if int(g("moe_layer_freq", 1)) != 1:
            raise NotImplementedError("moe_layer_freq != 1")
        if g("rope_scaling") not in (None, {}):
            raise NotImplementedError(
                "DeepSeek rope_scaling (yarn + mscale softmax correction) "
                "is not implemented yet — loading this checkpoint would "
                "produce silently wrong logits at every position"
            )
        if g("topk_method", "greedy") not in ("greedy",
                                              "group_limited_greedy"):
            raise NotImplementedError(
                f"topk_method {g('topk_method')!r} (e.g. V3's noaux_tc) "
                "is not implemented"
            )
        if bool(g("norm_topk_prob", False)):
            raise NotImplementedError("norm_topk_prob=True routing")
        if g("scoring_func", "softmax") != "softmax":
            raise NotImplementedError(
                f"scoring_func {g('scoring_func')!r}"
            )
        if bool(g("attention_bias", False)):
            raise NotImplementedError(
                "attention_bias=True (biases would be silently dropped)"
            )
        return cls(
            vocab_size=g("vocab_size"),
            hidden_size=g("hidden_size"),
            num_layers=g("num_hidden_layers"),
            num_heads=g("num_attention_heads"),
            qk_nope_head_dim=g("qk_nope_head_dim"),
            qk_rope_head_dim=g("qk_rope_head_dim"),
            v_head_dim=g("v_head_dim"),
            kv_lora_rank=g("kv_lora_rank"),
            q_lora_rank=g("q_lora_rank"),
            intermediate_size=g("intermediate_size"),
            moe_intermediate_size=g("moe_intermediate_size", 0) or 0,
            n_routed_experts=g("n_routed_experts", 0) or 0,
            num_experts_per_tok=g("num_experts_per_tok", 0) or 0,
            n_shared_experts=g("n_shared_experts", 0) or 0,
            routed_scaling_factor=float(g("routed_scaling_factor", 1.0)),
            topk_method=g("topk_method", "greedy"),
            n_group=g("n_group", 1) or 1,
            topk_group=g("topk_group", 1) or 1,
            first_k_dense_replace=g("first_k_dense_replace", 0) or 0,
            rms_norm_eps=float(g("rms_norm_eps", 1e-6)),
            rope_theta=float(g("rope_theta", 10000.0)),
            max_position_embeddings=g("max_position_embeddings", 4096),
            attention_bias=bool(g("attention_bias", False)),
        )


def apply_rope_interleaved(x: jax.Array, positions: jax.Array,
                           inv_freq: jax.Array) -> jax.Array:
    """DeepSeek rotary: adjacent element PAIRS (2i, 2i+1) rotate by
    pos·inv_freq[i] (the complex ``freqs_cis`` form in transformers),
    unlike Llama's rotate-half layout.  x: [B,S,H,Dr]."""
    b, s, h, d = x.shape
    angles = positions.astype(jnp.float32)[:, :, None] * inv_freq[None, None, :]
    cos = jnp.cos(angles)[:, :, None, :]  # [B,S,1,d/2]
    sin = jnp.sin(angles)[:, :, None, :]
    xr = x.astype(jnp.float32).reshape(b, s, h, d // 2, 2)
    x0, x1 = xr[..., 0], xr[..., 1]
    out = jnp.stack([x0 * cos - x1 * sin, x0 * sin + x1 * cos], axis=-1)
    return out.reshape(b, s, h, d).astype(x.dtype)


class DeepseekModel:
    """Engine-facing functional model (same protocol as LlamaModel)."""

    def __init__(self, config: DeepseekConfig):
        self.config = config
        self.sm_scale = float(config.qk_head_dim ** -0.5)
        self.inv_freq = rope_inv_freq(config.qk_rope_head_dim,
                                      config.rope_theta)

    # ------------------------------------------------------------------ init
    def _attn_params(self, keys, n_layers: int) -> dict:
        cfg = self.config
        dt = cfg.jax_dtype
        dm, h = cfg.hidden_size, cfg.num_heads
        qk, rope, v = cfg.qk_head_dim, cfg.qk_rope_head_dim, cfg.v_head_dim

        def dense(key, shape, fan_in):
            return (jax.random.normal(key, shape, jnp.float32)
                    / math.sqrt(fan_in)).astype(dt)

        p = {
            "attn_norm": jnp.ones((n_layers, dm), dt),
            "mlp_norm": jnp.ones((n_layers, dm), dt),
            "kv_a": dense(next(keys), (n_layers, dm, cfg.kv_lora_rank + rope), dm),
            "kv_a_norm": jnp.ones((n_layers, cfg.kv_lora_rank), dt),
            "kv_b": dense(next(keys),
                          (n_layers, cfg.kv_lora_rank,
                           h * (cfg.qk_nope_head_dim + v)), cfg.kv_lora_rank),
            "wo": dense(next(keys), (n_layers, h * v, dm), h * v),
        }
        if cfg.q_lora_rank is None:
            p["wq"] = dense(next(keys), (n_layers, dm, h * qk), dm)
        else:
            p["q_a"] = dense(next(keys), (n_layers, dm, cfg.q_lora_rank), dm)
            p["q_a_norm"] = jnp.ones((n_layers, cfg.q_lora_rank), dt)
            p["q_b"] = dense(next(keys), (n_layers, cfg.q_lora_rank, h * qk),
                             cfg.q_lora_rank)
        return p

    def init_params(self, rng: jax.Array) -> Params:
        cfg = self.config
        dt = cfg.jax_dtype
        dm = cfg.hidden_size
        keys = iter(jax.random.split(rng, 32))

        def dense(key, shape, fan_in):
            return (jax.random.normal(key, shape, jnp.float32)
                    / math.sqrt(fan_in)).astype(dt)

        ld = cfg.first_k_dense_replace
        lm = cfg.num_layers - ld
        dense_layers = self._attn_params(keys, ld)
        dense_layers.update(
            w_gate=dense(next(keys), (ld, dm, cfg.intermediate_size), dm),
            w_up=dense(next(keys), (ld, dm, cfg.intermediate_size), dm),
            w_down=dense(next(keys), (ld, cfg.intermediate_size, dm),
                         cfg.intermediate_size),
        )
        fm = cfg.moe_intermediate_size
        fs = fm * cfg.n_shared_experts
        e = cfg.n_routed_experts
        moe_layers = self._attn_params(keys, lm)
        moe_layers.update(
            router=(jax.random.normal(next(keys), (lm, dm, e), jnp.float32)
                    / math.sqrt(dm)).astype(dt),
            w_gate=dense(next(keys), (lm, e, dm, fm), dm),
            w_up=dense(next(keys), (lm, e, dm, fm), dm),
            w_down=dense(next(keys), (lm, e, fm, dm), fm),
            shared_gate=dense(next(keys), (lm, dm, fs), dm),
            shared_up=dense(next(keys), (lm, dm, fs), dm),
            shared_down=dense(next(keys), (lm, fs, dm), fs),
        )
        return {
            "embed": dense(next(keys), (cfg.vocab_size, dm), dm),
            "dense_layers": dense_layers,
            "moe_layers": moe_layers,
            "final_norm": jnp.ones((dm,), dt),
            "lm_head": dense(next(keys), (dm, cfg.vocab_size), dm),
        }

    # -------------------------------------------------------------- sharding
    def partition_specs(self) -> Params:
        """TP over "model": attention heads column-split, wo row-split,
        MoE experts TP-within-experts (FFN dim), shared experts like a
        dense MLP.  (Single-host tested; mesh execution follows the same
        GSPMD path as the Llama family.)"""
        cfg = self.config

        def attn(n):
            p = {
                "attn_norm": P(None, None), "mlp_norm": P(None, None),
                "kv_a": P(None, None, None),
                "kv_a_norm": P(None, None),
                "kv_b": P(None, None, _TP),
                "wo": P(None, _TP, None),
            }
            if cfg.q_lora_rank is None:
                p["wq"] = P(None, None, _TP)
            else:
                p.update(q_a=P(None, None, None), q_a_norm=P(None, None),
                         q_b=P(None, None, _TP))
            return p

        dense_layers = attn(cfg.first_k_dense_replace)
        dense_layers.update(
            w_gate=P(None, None, _TP), w_up=P(None, None, _TP),
            w_down=P(None, _TP, None),
        )
        moe_layers = attn(cfg.num_layers - cfg.first_k_dense_replace)
        moe_layers.update(
            router=P(None, None, None),
            w_gate=P(None, None, None, _TP),
            w_up=P(None, None, None, _TP),
            w_down=P(None, None, _TP, None),
            shared_gate=P(None, None, _TP),
            shared_up=P(None, None, _TP),
            shared_down=P(None, _TP, None),
        )
        return {
            "embed": P(None, None),
            "dense_layers": dense_layers,
            "moe_layers": moe_layers,
            "final_norm": P(None),
            "lm_head": P(None, _TP),
        }

    def cache_spec(self, quant: bool = False):
        if self.config.attn_impl == "absorbed":
            # ONE shared latent row per token (num_kv_heads == 1):
            # nothing head-sharded to split — the latent replicates (it
            # is tiny: kv_lora+rope), and so does its one-scale-per-token
            # pool
            data = P(None, None, None, None, None)
            scale_head = None
        else:
            data = P(None, None, None, None, _TP)
            # scale-pool head axis shards only when tile-exact (see
            # LlamaModel.cache_spec for the padded-axis rationale)
            scale_head = (_TP if self.config.num_kv_heads % 8 == 0
                          else None)
        if not quant:
            return data
        from dynamo_tpu.ops.kv_quant import QuantKvCache

        return QuantKvCache(data, P(None, None, None, scale_head, None))

    # --------------------------------------------------------------- kv cache
    def init_kv_cache(self, num_blocks: int, block_size: int, dtype=None):
        cfg = self.config
        # the engine-facing num_kv_heads/head_dim properties encode the
        # two cache forms: absorbed = ONE latent row of kv_lora+rope per
        # token (still ~43x smaller than expanded at V2's 128 heads),
        # expanded = per-head rows of qk_head_dim (V padded up to it)
        hk = cfg.num_kv_heads
        width = hk * cfg.head_dim
        shape = (cfg.num_layers, num_blocks, 2, block_size, width)
        dt = dtype or cfg.jax_dtype
        if str(dt) in ("int8", "<dtype: int8>") or dt == jnp.int8:
            # int8 on top of the latent cache is what fits real DeepSeek
            # shapes on 16GiB chips: same QuantKvCache layout as the GQA
            # models (per-token-per-head scales; ONE scale/token for the
            # absorbed latent), transparently handled by the write and
            # attention paths (ops/kv_quant.py)
            from dynamo_tpu.ops.kv_quant import QuantKvCache, scale_tile

            hp, sp = scale_tile(hk, block_size)
            return QuantKvCache(
                jnp.zeros(shape, jnp.int8),
                jnp.ones((cfg.num_layers, num_blocks, 2, hp, sp),
                         jnp.float32),
            )
        if str(dt) not in (str(cfg.jax_dtype), cfg.dtype):
            raise NotImplementedError(f"MLA cache dtype {dt!r}")
        return jnp.zeros(shape, cfg.jax_dtype)

    # ---------------------------------------------------------------- forward
    def _qkv_latent(self, lp, x, positions):
        """Shared front half of both attention forms: per-head queries
        (nope ‖ roped pe) and the per-token latent pieces."""
        cfg = self.config
        b, s, _ = x.shape
        nh, nope = cfg.num_heads, cfg.qk_nope_head_dim
        if cfg.q_lora_rank is None:
            q = x @ lp["wq"]
        else:
            q = rms_norm(x @ lp["q_a"], lp["q_a_norm"], cfg.rms_norm_eps) \
                @ lp["q_b"]
        q = q.reshape(b, s, nh, cfg.qk_head_dim)
        q_nope, q_pe = q[..., :nope], q[..., nope:]
        q_pe = apply_rope_interleaved(q_pe, positions, self.inv_freq)

        ckv = x @ lp["kv_a"]  # [B,S, kv_lora + rope]
        c_kv, k_pe = ckv[..., :cfg.kv_lora_rank], ckv[..., cfg.kv_lora_rank:]
        c_hat = rms_norm(c_kv, lp["kv_a_norm"], cfg.rms_norm_eps)
        k_pe = apply_rope_interleaved(
            k_pe[:, :, None, :], positions, self.inv_freq
        )  # [B,S,1,rope] — shared across heads
        return q_nope, q_pe, c_hat, k_pe

    def _attention(self, lp, li, h_in, positions, cache, block_tables,
                   seq_lens, slot_idx):
        if self.config.attn_impl == "absorbed":
            return self._attention_absorbed(
                lp, li, h_in, positions, cache, block_tables, seq_lens,
                slot_idx,
            )
        return self._attention_expanded(
            lp, li, h_in, positions, cache, block_tables, seq_lens, slot_idx,
        )

    def _attention_expanded(self, lp, li, h_in, positions, cache,
                            block_tables, seq_lens, slot_idx):
        """Oracle form: materialise per-head K/V like a GQA model (cache
        row H·qk_head_dim, V padded).  Logit-exact, memory-hungry."""
        cfg = self.config
        b, s = positions.shape
        nh = cfg.num_heads
        nope, rope, vd = (cfg.qk_nope_head_dim, cfg.qk_rope_head_dim,
                          cfg.v_head_dim)
        x = rms_norm(h_in, lp["attn_norm"], cfg.rms_norm_eps)
        q_nope, q_pe, c_hat, k_pe = self._qkv_latent(lp, x, positions)
        kv = (c_hat @ lp["kv_b"]).reshape(b, s, nh, nope + vd)
        k_nope, v = kv[..., :nope], kv[..., nope:]

        q = jnp.concatenate([q_nope, q_pe], axis=-1)  # [B,S,H,qk_head]
        k = jnp.concatenate(
            [k_nope, jnp.broadcast_to(k_pe, (*k_nope.shape[:-1], rope))],
            axis=-1,
        )
        # V padded to the cache row width; sliced back after attention
        v_pad = jnp.pad(v, ((0, 0), (0, 0), (0, 0),
                            (0, cfg.qk_head_dim - vd)))
        cache = write_kv_cache_layer(cache, li, k, v_pad, slot_idx)
        attn = paged_attention_layer(
            q, cache, li, block_tables, seq_lens, positions,
            sm_scale=self.sm_scale,
        )  # [B,S,H,qk_head]
        attn = attn[..., :vd].reshape(b, s, nh * vd)
        return h_in + attn @ lp["wo"], cache

    def _absorbed_qkv(self, lp, h_in, positions):
        """Shared absorption front-end (paged `_attention_absorbed` AND
        the ring `forward_seq_parallel`): queries projected INTO the
        latent space through kv_b's K-half, and the one shared KV row.
        Returns (q_lat [B,S,H,r+rope], row [B,S,1,r+rope], w_v).  The
        absorption identity:
          q_nope[h]·k_nope[h] = q_nope[h]·(Wk[h]ᵀ c_hat)
                              = (Wk[h] q_nope[h]) · c_hat."""
        cfg = self.config
        nh = cfg.num_heads
        nope, vd, r = (cfg.qk_nope_head_dim, cfg.v_head_dim,
                       cfg.kv_lora_rank)
        x = rms_norm(h_in, lp["attn_norm"], cfg.rms_norm_eps)
        q_nope, q_pe, c_hat, k_pe = self._qkv_latent(lp, x, positions)
        kv_b = lp["kv_b"].reshape(r, nh, nope + vd)
        w_k = kv_b[..., :nope]            # [r, H, nope]
        w_v = kv_b[..., nope:]            # [r, H, vd]
        q_eff = jnp.einsum("bshn,rhn->bshr", q_nope, w_k)
        q_lat = jnp.concatenate([q_eff, q_pe], axis=-1)
        row = jnp.concatenate(
            [c_hat[:, :, None, :], k_pe], axis=-1
        )  # the ONE shared KV row; K == V == latent
        return q_lat, row, w_v

    def _absorbed_out(self, lp, h_in, attn, w_v):
        """Shared absorption back-end: expand attended latents per head
        through kv_b's V-half and project out."""
        cfg = self.config
        b, s = h_in.shape[:2]
        out = jnp.einsum("bshr,rhv->bshv",
                         attn[..., :cfg.kv_lora_rank], w_v)
        return h_in + out.reshape(b, s, cfg.num_heads * cfg.v_head_dim) \
            @ lp["wo"]

    def _attention_absorbed(self, lp, li, h_in, positions, cache,
                            block_tables, seq_lens, slot_idx):
        """Absorbed form (the MLA deployment shape): attention runs as
        GQA with ONE shared KV head whose row is the cached latent
        (c_hat ‖ k_pe) — see `_absorbed_qkv` for the identity.  Cache
        cost per token: the latent row (stored twice — the pool's K/V
        planes) vs 2·H·qk_head_dim expanded."""
        q_lat, row, w_v = self._absorbed_qkv(lp, h_in, positions)
        cache = write_kv_cache_layer(cache, li, row, row, slot_idx)
        attn = paged_attention_layer(
            q_lat, cache, li, block_tables, seq_lens, positions,
            sm_scale=self.sm_scale,
        )  # [B,S,H,r+rope] — attended latents per head
        return self._absorbed_out(lp, h_in, attn, w_v), cache

    def _moe_mlp(self, lp, x):
        """DeepSeekMoE: softmax routing (optionally group-limited) ×
        routed_scaling_factor through the grouped ragged_dot dispatch,
        plus the always-on shared experts."""
        cfg = self.config
        b, s, d = x.shape
        t = b * s
        e, k = cfg.n_routed_experts, cfg.num_experts_per_tok
        xf = x.reshape(t, d)
        # HF gates fully in f32 (inputs AND weights cast before the
        # matmul): near-tie logits must resolve to the same experts
        scores = jax.nn.softmax(
            xf.astype(jnp.float32) @ lp["router"].astype(jnp.float32),
            axis=-1,
        )  # [T,E]
        if cfg.topk_method == "group_limited_greedy":
            gs = scores.reshape(t, cfg.n_group, -1).max(axis=-1)  # [T,G]
            _, gidx = jax.lax.top_k(gs, cfg.topk_group)
            gmask = jnp.zeros_like(gs).at[
                jnp.arange(t)[:, None], gidx
            ].set(1.0)
            scores = scores * jnp.repeat(gmask, e // cfg.n_group, axis=-1)
        weights, topi = jax.lax.top_k(scores, k)  # [T,k]
        weights = weights * cfg.routed_scaling_factor

        routed = grouped_expert_dispatch(
            xf, weights, topi, e,
            lp["w_gate"], lp["w_up"], lp["w_down"], jax.nn.silu,
        )

        shared = (jax.nn.silu(xf @ lp["shared_gate"]) * (xf @ lp["shared_up"])
                  ) @ lp["shared_down"]
        return (routed + shared).reshape(b, s, d)

    def forward(self, params, tokens, positions, cache, block_tables,
                seq_lens, slot_idx, prefix_blocks=None):
        """(hidden [B,S,Dm], cache).  ``prefix_blocks`` is accepted for
        engine compatibility; MLA always takes the generic paged path."""
        cfg = self.config
        hidden = params["embed"][tokens].astype(cfg.jax_dtype)

        def dense_step(carry, layer_in):
            h, cache = carry
            lp, li = layer_in
            h, cache = self._attention(lp, li, h, positions, cache,
                                       block_tables, seq_lens, slot_idx)
            x = rms_norm(h, lp["mlp_norm"], cfg.rms_norm_eps)
            h = h + (jax.nn.silu(x @ lp["w_gate"]) * (x @ lp["w_up"])) \
                @ lp["w_down"]
            return (h, cache), None

        def moe_step(carry, layer_in):
            h, cache = carry
            lp, li = layer_in
            h, cache = self._attention(lp, li, h, positions, cache,
                                       block_tables, seq_lens, slot_idx)
            x = rms_norm(h, lp["mlp_norm"], cfg.rms_norm_eps)
            h = h + self._moe_mlp(lp, x)
            return (h, cache), None

        ld = cfg.first_k_dense_replace
        carry = (hidden, cache)
        if ld:
            carry, _ = jax.lax.scan(
                dense_step, carry,
                (params["dense_layers"], jnp.arange(ld, dtype=jnp.int32)),
            )
        carry, _ = jax.lax.scan(
            moe_step, carry,
            (params["moe_layers"],
             jnp.arange(ld, cfg.num_layers, dtype=jnp.int32)),
        )
        hidden, cache = carry
        hidden = rms_norm(hidden, params["final_norm"], cfg.rms_norm_eps)
        return hidden, cache

    @property
    def supports_seq_parallel(self) -> bool:
        """Ring-attention prefill exists only for the absorbed cache form
        (the expanded oracle is not a deployment shape) — the engine's
        construction-time guard reads this so an unsupported config fails
        at startup, not on the first long prompt."""
        return self.config.attn_impl == "absorbed"

    def forward_seq_parallel(self, params, tokens, positions, mesh,
                             sp_axis: str = AXIS_SP):
        """Long-context MLA prefill with ring attention (context
        parallelism), the engine's SP path for prompts beyond one chip's
        comfort (EngineConfig.sp_prefill_threshold).

        The absorbed form is ring-friendly: each device's sequence chunk
        computes its latent rows (c_hat ‖ k_pe) and latent-space queries;
        attention runs as GQA with ONE shared KV head whose rows rotate
        over ICI (ops/ring_attention.py — hq/hk=H broadcast fuses into
        the matmuls), and the attended latent expands per head through
        kv_b's V-half — the same absorption identity as the paged form
        (`_attention_absorbed`), so results match it exactly.

        Returns (hidden [B,S,Dm], kv [L,2,B,S,width]) with the sequence
        sharding kept; the kv output is the latent row duplicated into
        the generic pool's K/V planes, exactly what the engine scatters
        into paged-cache blocks after a long prefill.
        """
        from dynamo_tpu.ops.ring_attention import ring_attention

        cfg = self.config
        if cfg.attn_impl != "absorbed":
            raise NotImplementedError(
                "seq-parallel MLA prefill needs attn_impl='absorbed' "
                "(the expanded oracle is not a deployment shape)")
        hidden = params["embed"][tokens].astype(cfg.jax_dtype)

        def attn_sp(lp, h_in):
            q_lat, row, w_v = self._absorbed_qkv(lp, h_in, positions)
            attn = ring_attention(
                q_lat, row, row, positions, positions, mesh=mesh,
                axis=sp_axis, sm_scale=self.sm_scale,
            )  # [B,S,H,r+rope] attended latents per head
            return self._absorbed_out(lp, h_in, attn, w_v), row[:, :, 0]

        def dense_step(h, lp):
            h, row = attn_sp(lp, h)
            x = rms_norm(h, lp["mlp_norm"], cfg.rms_norm_eps)
            h = h + (jax.nn.silu(x @ lp["w_gate"]) * (x @ lp["w_up"])) \
                @ lp["w_down"]
            return h, jnp.stack([row, row], axis=0)  # K == V == latent

        def moe_step(h, lp):
            h, row = attn_sp(lp, h)
            x = rms_norm(h, lp["mlp_norm"], cfg.rms_norm_eps)
            h = h + self._moe_mlp(lp, x)
            return h, jnp.stack([row, row], axis=0)

        h = hidden
        kvs = []
        if cfg.first_k_dense_replace:
            h, kv_d = jax.lax.scan(dense_step, h, params["dense_layers"])
            kvs.append(kv_d)
        h, kv_m = jax.lax.scan(moe_step, h, params["moe_layers"])
        kvs.append(kv_m)
        kv = jnp.concatenate(kvs, axis=0) if len(kvs) > 1 else kvs[0]
        hidden = rms_norm(h, params["final_norm"], cfg.rms_norm_eps)
        return hidden, kv  # kv: [L, 2, B, S, kv_lora+rope]

    def compute_logits(self, params, hidden):
        w = params["lm_head"]
        return jnp.matmul(hidden.astype(w.dtype), w,
                          preferred_element_type=jnp.float32)


# ------------------------------------------------------------- HF weights ----
def convert_hf_state_dict(sd: dict, cfg: DeepseekConfig) -> Params:
    """transformers DeepseekV2ForCausalLM state dict → DeepseekModel
    params (numpy in, jnp out).  Linear weights transpose to [in, out]."""
    import numpy as _np

    dt = cfg.jax_dtype

    def w(name):
        return _np.asarray(sd[name], dtype=_np.float32)

    def lin(name):
        return w(name).T  # torch [out, in] -> [in, out]

    def stack(fmt, layers, f):
        return jnp.asarray(_np.stack([f(fmt.format(i)) for i in layers]), dt)

    ld = cfg.first_k_dense_replace
    dense_idx = list(range(ld))
    moe_idx = list(range(ld, cfg.num_layers))

    def attn_group(idx):
        pre = "model.layers.{}."
        g = {
            "attn_norm": stack(pre + "input_layernorm.weight", idx, w),
            "mlp_norm": stack(pre + "post_attention_layernorm.weight", idx, w),
            "kv_a": stack(pre + "self_attn.kv_a_proj_with_mqa.weight", idx, lin),
            "kv_a_norm": stack(pre + "self_attn.kv_a_layernorm.weight", idx, w),
            "kv_b": stack(pre + "self_attn.kv_b_proj.weight", idx, lin),
            "wo": stack(pre + "self_attn.o_proj.weight", idx, lin),
        }
        if cfg.q_lora_rank is None:
            g["wq"] = stack(pre + "self_attn.q_proj.weight", idx, lin)
        else:
            g["q_a"] = stack(pre + "self_attn.q_a_proj.weight", idx, lin)
            g["q_a_norm"] = stack(pre + "self_attn.q_a_layernorm.weight", idx, w)
            g["q_b"] = stack(pre + "self_attn.q_b_proj.weight", idx, lin)
        return g

    dense_layers = attn_group(dense_idx)
    dense_layers.update(
        w_gate=stack("model.layers.{}.mlp.gate_proj.weight", dense_idx, lin),
        w_up=stack("model.layers.{}.mlp.up_proj.weight", dense_idx, lin),
        w_down=stack("model.layers.{}.mlp.down_proj.weight", dense_idx, lin),
    )

    def experts(kind):
        e = cfg.n_routed_experts

        def per_layer(i):
            return _np.stack([
                lin(f"model.layers.{i}.mlp.experts.{j}.{kind}.weight")
                for j in range(e)
            ])

        return jnp.asarray(_np.stack([per_layer(i) for i in moe_idx]), dt)

    moe_layers = attn_group(moe_idx)
    moe_layers.update(
        router=stack("model.layers.{}.mlp.gate.weight", moe_idx, lin),
        w_gate=experts("gate_proj"),
        w_up=experts("up_proj"),
        w_down=experts("down_proj"),
        shared_gate=stack(
            "model.layers.{}.mlp.shared_experts.gate_proj.weight", moe_idx, lin),
        shared_up=stack(
            "model.layers.{}.mlp.shared_experts.up_proj.weight", moe_idx, lin),
        shared_down=stack(
            "model.layers.{}.mlp.shared_experts.down_proj.weight", moe_idx, lin),
    )
    return {
        "embed": jnp.asarray(w("model.embed_tokens.weight"), dt),
        "dense_layers": dense_layers,
        "moe_layers": moe_layers,
        "final_norm": jnp.asarray(w("model.norm.weight"), dt),
        "lm_head": jnp.asarray(lin("lm_head.weight"), dt),
    }
