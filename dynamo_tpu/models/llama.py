"""Llama-family decoder in pure JAX, built for paged serving on TPU.

Design (TPU-first, not a port):
  * One unified forward pass serves prefill, chunked prefill and decode —
    the S new tokens of each sequence scatter K/V into the paged cache then
    run paged attention over their full context (ops/paged_attention.py).
  * ``lax.scan`` over layers: per-layer weights are stacked on a leading L
    axis so the whole stack compiles once — fast XLA compiles even at 80
    layers.  The KV cache is scan CARRY updated in place by scatter (never
    sliced per layer), so decode traffic is O(tokens), not O(cache).
  * Static shapes everywhere; bf16 weights/activations on the MXU, f32
    norms/softmax/logits.
  * Tensor parallelism is declarative: :meth:`partition_specs` returns a
    PartitionSpec pytree over mesh axes ("data", "model") and GSPMD inserts
    the collectives (all-gather/psum over ICI) — no NCCL-style plumbing.
  * MoE (Mixtral-style) uses grouped dispatch: token→expert assignments
    sort by expert and each projection runs as ONE ``lax.ragged_dot``
    (XLA's grouped matmul) — exactly k experts of FLOPs per token and
    [T·k, F] intermediates.  Experts shard their FFN dim over "model"
    (TP-within-experts), so compute/memory balance is routing-independent.
    A dense one-hot oracle path remains for parity tests (DYNAMO_MOE_DENSE).

The reference has no model code at all (engines are external, SURVEY.md
§2.4); this module plus engine/ is the "native JAX/XLA engine" the rebuild
adds (BASELINE.json north star).
"""

from __future__ import annotations

import math
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

# canonical axis names (utils/mesh.py): _TP is the tensor-parallel mesh
# axis every spec below shards over — shardcheck audits specs under the
# same constants, so a renamed axis breaks loudly instead of replicating
from dynamo_tpu.utils.mesh import AXIS_MODEL as _TP
from dynamo_tpu.utils.mesh import AXIS_SP

from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.models.quant import (
    QTensor,
    dequantize,
    matmul,
    quantize_params,
    random_qtensor,
    stacked_channel_axes,
    take_rows,
)
from dynamo_tpu.ops.paged_attention import (
    paged_attention_layer,
    prefill_attention,
    ragged_prefill_attention,
    softcap,
    write_kv_cache_layer,
)

Params = Any  # pytree of jax.Array


def rms_norm(x: jax.Array, weight: jax.Array, eps: float,
             unit_offset: bool = False) -> jax.Array:
    xf = x.astype(jnp.float32)
    norm = xf * jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    w = weight.astype(jnp.float32)
    if unit_offset:  # Gemma stores zero-centred scales: multiply by (1 + w)
        w = w + 1.0
    return (norm * w).astype(x.dtype)


def rope_inv_freq(head_dim: int, theta: float,
                  rope_scaling: Optional[dict] = None) -> jax.Array:
    """Rotary inverse frequencies [D/2], with HF rope_scaling applied.

    llama3 scaling (Llama-3.1+): low-frequency components divide by
    ``factor``, high-frequency ones stay, the band between interpolates —
    matching transformers' _compute_llama3_parameters.  "linear" divides
    every frequency by ``factor``.
    """
    half = head_dim // 2
    inv = 1.0 / (theta ** (np.arange(0, half, dtype=np.float64) * 2.0
                           / head_dim))
    if rope_scaling:
        kind = rope_scaling.get("rope_type") or rope_scaling.get("type")
        if kind == "linear":
            inv = inv / float(rope_scaling["factor"])
        elif kind == "llama3":
            factor = float(rope_scaling["factor"])
            low = float(rope_scaling.get("low_freq_factor", 1.0))
            high = float(rope_scaling.get("high_freq_factor", 4.0))
            old_ctx = float(
                rope_scaling.get("original_max_position_embeddings", 8192)
            )
            wavelen = 2.0 * np.pi / inv
            # long wavelengths (low freq): fully scaled; short: untouched;
            # medium: smooth interpolation — transformers parity
            scaled = inv / factor
            smooth = (old_ctx / wavelen - low) / (high - low)
            smooth = np.clip(smooth, 0.0, 1.0)
            interp = (1.0 - smooth) * scaled + smooth * inv
            inv = np.where(wavelen > old_ctx / low, scaled,
                           np.where(wavelen < old_ctx / high, inv, interp))
    return jnp.asarray(inv, jnp.float32)


def apply_rope(x: jax.Array, positions: jax.Array, theta: float,
               inv_freq: Optional[jax.Array] = None) -> jax.Array:
    """HF-Llama rotate-half RoPE.  x: [B,S,H,D], positions: [B,S]."""
    d = x.shape[-1]
    half = d // 2
    if inv_freq is None:
        inv_freq = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) * 2.0 / d))
    angles = positions.astype(jnp.float32)[:, :, None] * inv_freq[None, None, :]
    cos = jnp.cos(angles)[:, :, None, :]  # [B,S,1,half]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., :half].astype(jnp.float32), x[..., half:].astype(jnp.float32)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


class LlamaModel:
    """Functional model: params pytree + pure forward functions."""

    # forward() accepts the token-budget ragged prefill layout (the engine
    # gates the batched scheduler on this; models without the ragged
    # attention path — expanded-MLA DeepSeek — fall back to per-request)
    supports_ragged_prefill = True
    # forward() additionally accepts the unified mixed layout (decode
    # rows leading the flat axis via ``ragged_row_tokens``) — the engine
    # gates the unified token-budget scheduler on this
    supports_unified_dispatch = True

    def __init__(self, config: ModelConfig):
        self.config = config
        # Gemma2 scales scores by query_pre_attn_scalar**-0.5, not head_dim
        self.sm_scale = float(
            (config.query_pre_attn_scalar or config.head_dim) ** -0.5
        )
        # rotary frequencies with rope_scaling applied (llama3/linear)
        self.inv_freq = rope_inv_freq(
            config.head_dim, config.rope_theta, config.rope_scaling
        )

    # ------------------------------------------------------------------ init
    def init_params(self, rng: jax.Array, quantized: bool = False) -> Params:
        """Random init as ONE compiled program.

        The eager body dispatches ~5 ops per tensor; on a remote-compile
        backend (the axon tunnel) every eager op pays a ~25s AOT compile
        — 8B init took >40 min eager vs one ~1 min jitted compile.
        """
        fn = getattr(self, "_init_params_jit", None)
        if fn is None:
            fn = self._init_params_jit = jax.jit(
                self._init_params_impl, static_argnames=("quantized",))
        return fn(rng, quantized=quantized)

    def _init_params_impl(self, rng: jax.Array, quantized: bool = False) -> Params:
        """``quantized=True`` synthesizes int8 QTensor matmul
        weights directly (never materializing the bf16 tensor — 8B bf16
        would not fit the single chip the int8 path exists to fit)."""
        cfg = self.config
        dt = cfg.jax_dtype
        dm, hq, hk, dh, f = (
            cfg.hidden_size,
            cfg.num_heads,
            cfg.num_kv_heads,
            cfg.head_dim,
            cfg.intermediate_size,
        )
        L = cfg.num_layers
        keys = iter(jax.random.split(rng, 16))

        def dense(key, shape, fan_in, channel_axes=None):
            if quantized:
                axes = channel_axes or stacked_channel_axes(len(shape))
                return random_qtensor(key, shape, fan_in, axes)
            return (jax.random.normal(key, shape, jnp.float32) / math.sqrt(fan_in)).astype(dt)

        # Gemma's (1 + w) RMSNorm wants zero-init scales; Llama wants ones
        norm_init = jnp.zeros if cfg.rmsnorm_unit_offset else jnp.ones
        layers: dict[str, jax.Array] = {
            "attn_norm": norm_init((L, dm), dt),
            "wq": dense(next(keys), (L, dm, hq * dh), dm),
            "wk": dense(next(keys), (L, dm, hk * dh), dm),
            "wv": dense(next(keys), (L, dm, hk * dh), dm),
            "wo": dense(next(keys), (L, hq * dh, dm), hq * dh),
            "mlp_norm": norm_init((L, dm), dt),
        }
        if cfg.post_norms:  # Gemma2 sandwich norms
            layers.update(
                post_attn_norm=norm_init((L, dm), dt),
                post_mlp_norm=norm_init((L, dm), dt),
            )
        if cfg.attention_bias:  # Qwen2-style QKV bias
            layers.update(
                bq=jnp.zeros((L, hq * dh), dt),
                bk=jnp.zeros((L, hk * dh), dt),
                bv=jnp.zeros((L, hk * dh), dt),
            )
        if cfg.qk_norm:  # Qwen3 per-head q/k RMSNorm
            layers.update(
                q_norm=jnp.ones((L, dh), dt),
                k_norm=jnp.ones((L, dh), dt),
            )
        if cfg.is_moe:
            e = cfg.num_experts
            # router stays dense even under quantization: it is tiny and
            # its logits pick experts (accuracy-critical, no bandwidth win)
            router_w = (
                jax.random.normal(next(keys), (L, dm, e), jnp.float32)
                / math.sqrt(dm)
            ).astype(dt)
            layers.update(
                router=router_w,
                w_gate=dense(next(keys), (L, e, dm, f), dm),
                w_up=dense(next(keys), (L, e, dm, f), dm),
                w_down=dense(next(keys), (L, e, f, dm), f),
            )
        else:
            layers.update(
                w_gate=dense(next(keys), (L, dm, f), dm),
                w_up=dense(next(keys), (L, dm, f), dm),
                w_down=dense(next(keys), (L, f, dm), f),
            )
        params = {
            # per-row scales so the same tensor serves lookup + tied lm_head
            "embed": dense(next(keys), (cfg.vocab_size, dm), dm, channel_axes=(0,)),
            "layers": layers,
            "final_norm": norm_init((dm,), dt),
        }
        if not cfg.tie_word_embeddings:
            params["lm_head"] = dense(next(keys), (dm, cfg.vocab_size), dm)
        return params

    def quantize_params(self, params: Params) -> Params:
        """bf16 params → int8 weight-only QTensor params (models/quant.py)."""
        return quantize_params(params)

    # -------------------------------------------------------------- sharding
    def partition_specs(self) -> Params:
        """PartitionSpec pytree matching init_params — TP over axis "model".

        GSPMD turns these annotations into ICI collectives; this is the whole
        tensor-parallel implementation (cf. reference delegating TP to
        vLLM/Ray, SURVEY.md §2.4 parallelism summary).
        """
        cfg = self.config
        layers = {
            "attn_norm": P(None, None),
            "wq": P(None, None, _TP),
            "wk": P(None, None, _TP),
            "wv": P(None, None, _TP),
            "wo": P(None, _TP, None),
            "mlp_norm": P(None, None),
        }
        if cfg.attention_bias:
            layers.update(
                bq=P(None, _TP), bk=P(None, _TP), bv=P(None, _TP)
            )
        if cfg.qk_norm:
            layers.update(q_norm=P(None, None), k_norm=P(None, None))
        if cfg.post_norms:
            layers.update(
                post_attn_norm=P(None, None), post_mlp_norm=P(None, None)
            )
        if cfg.is_moe:
            # TP-within-experts: shard every expert's FFN intermediate dim
            # F over "model" (same layout as the dense MLP).  Weight memory
            # AND compute split evenly across devices regardless of routing
            # skew, and GSPMD partitions the grouped ragged_dot directly on
            # F.  (Device-EP — sharding the E axis — load-balances only
            # when routing is uniform; at serving batch sizes it idles
            # devices whose experts draw no tokens.)
            layers.update(
                router=P(None, None, None),
                w_gate=P(None, None, None, _TP),
                w_up=P(None, None, None, _TP),
                w_down=P(None, None, _TP, None),
            )
        else:
            layers.update(
                w_gate=P(None, None, _TP),
                w_up=P(None, None, _TP),
                w_down=P(None, _TP, None),
            )
        specs = {
            "embed": P(None, None),
            "layers": layers,
            "final_norm": P(None),
        }
        if not cfg.tie_word_embeddings:
            specs["lm_head"] = P(None, _TP)
        return specs

    def cache_spec(self, quant: bool = False):
        """KV cache [L,N,2,Bs,Hk*D]: the trailing axis is kv-head-major, so
        sharding it over "model" splits whole kv heads across the mesh.
        For a quantized cache, the scale pool [L,N,2,Hp,Sp] shards its
        head axis the same way — but only when Hk is tile-exact (Hk % 8 ==
        0, so Hp == Hk and shard boundaries land on real head rows); a
        padded head axis replicates instead, since an even split of the
        padded axis would put different heads on a shard than the data's
        head-major lane split does."""
        data = P(None, None, None, None, _TP)
        if not quant:
            return data
        from dynamo_tpu.ops.kv_quant import QuantKvCache

        head_axis = _TP if self.config.num_kv_heads % 8 == 0 else None
        return QuantKvCache(data, P(None, None, None, head_axis, None))

    # --------------------------------------------------------------- kv cache
    def init_kv_cache(self, num_blocks: int, block_size: int, dtype=None) -> jax.Array:
        """One array for the whole model: [L, N, 2, Bs, Hk*D].

        A single multi-layer array (rather than per-layer leaves) is what
        lets (a) the decode kernel index layers with a scalar instead of
        slicing, (b) block transfer move a block id across all layers at
        once (ops/block_copy.py), and (c) the engine donate one buffer.
        K and V of a block are adjacent (k/v axis inside the block axis) so
        the decode kernel's per-block fetch is ONE contiguous DMA.  The
        flat Hk*D minor axis is lane-aligned (512+ for real models).

        ``dtype="int8"`` returns a :class:`QuantKvCache` (int8 payload +
        per-token-per-head scale pool, ops/kv_quant.py) — same layout, half
        the HBM, transparently handled by every write/attention path.
        """
        cfg = self.config
        shape = (
            cfg.num_layers,
            num_blocks,
            2,
            block_size,
            cfg.num_kv_heads * cfg.head_dim,
        )
        dt = dtype or cfg.jax_dtype
        if str(dt) in ("int8", "<dtype: int8>") or dt == jnp.int8:
            from dynamo_tpu.ops.kv_quant import QuantKvCache, scale_tile

            hp, sp = scale_tile(cfg.num_kv_heads, block_size)
            return QuantKvCache(
                jnp.zeros(shape, jnp.int8),
                jnp.ones(
                    (cfg.num_layers, num_blocks, 2, hp, sp), jnp.float32,
                ),
            )
        return jnp.zeros(shape, dt)

    # ---------------------------------------------------------------- forward
    def forward(
        self,
        params: Params,
        tokens: jax.Array,        # [B, S] int32
        positions: jax.Array,     # [B, S] int32 (absolute; padding rows may be 0)
        kv_cache: jax.Array,      # [L, N, 2, Bs, Hk*D]
        block_tables: jax.Array,  # [B, M] int32
        seq_lens: jax.Array,      # [B] int32 — context length incl. new tokens
        slot_idx: jax.Array,      # [B, S] int32 — cache slot per new token, -1 pad
        prefix_blocks: int | None = None,  # STATIC — prefill fast path (see below)
        ragged: tuple | None = None,       # (seq_ids, starts, row_offsets)
        ragged_row_tokens: int = 0,        # STATIC — unified mixed layout
    ) -> tuple[jax.Array, jax.Array]:
        """Returns (hidden [B,S,Dm], updated kv_cache).

        ``prefix_blocks`` (static int) activates the prefill fast path for
        S>1: attention runs against this chunk's in-register K/V plus at
        most ``prefix_blocks`` cached prefix blocks, instead of gathering
        the whole padded block table.  Requires the S tokens of each row to
        be contiguous from block-aligned position ``positions[:, 0]``
        (exactly how the engine lays out prefill).  None = generic path.

        ``ragged`` switches the prefill fast path to token-budget ragged
        form: B is 1 and the S axis packs several sequences' chunks, each a
        contiguous block-aligned span.  ``seq_ids`` [1, S] names each
        token's owning row (-1 = padding), ``starts``/``row_offsets`` [R]
        give each row's absolute chunk start and flat offset, and
        ``block_tables``/``seq_lens`` are per-ROW ([R, M] / [R]) rather
        than per-batch-row.  Requires ``prefix_blocks`` to be set.

        ``ragged_row_tokens`` (static) marks the unified mixed layout:
        the first that-many flat tokens are DECODE rows — one fresh token
        each, at an arbitrary (non-block-aligned) in-block cache slot —
        so the KV write scatters them per row and only the block-aligned
        prefill spans after them take the block-granular write.  The
        ragged attention itself needs no change: its prefix mask is
        positionally exact for any ``starts``.
        """
        cfg = self.config
        b, s = tokens.shape
        dh, hq, hk = cfg.head_dim, cfg.num_heads, cfg.num_kv_heads
        ragged_prefill = (
            ragged is not None and prefix_blocks is not None and s > 1
        )
        fast_prefill = (
            prefix_blocks is not None and s > 1 and not ragged_prefill
        )

        hidden = take_rows(params["embed"], tokens, cfg.jax_dtype)
        if cfg.scale_embeddings:  # Gemma multiplies by sqrt(hidden_size)
            hidden = hidden * jnp.asarray(
                math.sqrt(cfg.hidden_size), cfg.jax_dtype
            )

        # The cache rides the scan as CARRY, updated by scatter: XLA keeps
        # one buffer and updates it in place.  (Passing it as xs/ys instead
        # copies the whole multi-GB cache through the loop every step —
        # that copy, not attention, dominated decode ITL.)
        uo = cfg.rmsnorm_unit_offset

        def layer_step(carry, layer_in):
            h, cache = carry
            lp, li = layer_in
            x = rms_norm(h, lp["attn_norm"], cfg.rms_norm_eps, uo)
            q, k, v = _qkv_proj(cfg, lp, x, b, s)
            q = apply_rope(q, positions, cfg.rope_theta, self.inv_freq)
            k = apply_rope(k, positions, cfg.rope_theta, self.inv_freq)
            # fast_prefill/ragged imply the engine's block-aligned
            # contiguous span layout — unlocks the block-granular write
            cache = write_kv_cache_layer(
                cache, li, k, v, slot_idx,
                block_aligned=fast_prefill or ragged_prefill,
                row_tokens=ragged_row_tokens if ragged_prefill else 0,
            )
            if ragged_prefill:
                seq_ids, seq_starts, row_offsets = ragged
                attn = ragged_prefill_attention(
                    q, k, v, cache, li, block_tables, seq_lens,
                    seq_starts, row_offsets, seq_ids, prefix_blocks,
                    sm_scale=self.sm_scale, logit_cap=cfg.attn_logit_softcap,
                    window=cfg.sliding_window,
                )
            elif fast_prefill:
                attn = prefill_attention(
                    q, k, v, cache, li, block_tables, seq_lens,
                    positions[:, 0], prefix_blocks,
                    sm_scale=self.sm_scale, logit_cap=cfg.attn_logit_softcap,
                    window=cfg.sliding_window,
                )
            else:
                attn = paged_attention_layer(
                    q, cache, li, block_tables, seq_lens, positions,
                    sm_scale=self.sm_scale, logit_cap=cfg.attn_logit_softcap,
                    window=cfg.sliding_window,
                )
            attn_out = matmul(attn.reshape(b, s, hq * dh), lp["wo"])
            if cfg.post_norms:  # Gemma2 sandwich: norm the residual branch
                attn_out = rms_norm(attn_out, lp["post_attn_norm"],
                                    cfg.rms_norm_eps, uo)
            h = h + attn_out

            x = rms_norm(h, lp["mlp_norm"], cfg.rms_norm_eps, uo)
            mlp_out = _moe_mlp(cfg, lp, x) if cfg.is_moe else _dense_mlp(cfg, lp, x)
            if cfg.post_norms:
                mlp_out = rms_norm(mlp_out, lp["post_mlp_norm"],
                                   cfg.rms_norm_eps, uo)
            h = h + mlp_out
            return (h, cache), None

        (hidden, new_cache), _ = jax.lax.scan(
            layer_step,
            (hidden, kv_cache),
            (params["layers"], jnp.arange(cfg.num_layers, dtype=jnp.int32)),
        )
        hidden = rms_norm(hidden, params["final_norm"], cfg.rms_norm_eps,
                          cfg.rmsnorm_unit_offset)
        return hidden, new_cache

    def forward_seq_parallel(
        self,
        params: Params,
        tokens: jax.Array,      # [B, S] int32, S sharded over mesh[sp_axis]
        positions: jax.Array,   # [B, S] int32 global positions
        mesh: jax.sharding.Mesh,
        sp_axis: str = AXIS_SP,
    ) -> tuple[jax.Array, jax.Array]:
        """Long-context prefill with ring attention (context parallelism).

        The sequence axis is sharded over ``mesh[sp_axis]``; each device
        computes its chunk's Q/K/V and attention runs blockwise while KV
        chunks rotate over ICI (ops/ring_attention.py) — prompts far beyond
        one chip's HBM prefill exactly, a capability absent from the
        reference (SURVEY.md §5 long-context).

        Returns (hidden [B,S,Dm], kv [L,2,B,S,Hk*D]); the kv output is what
        the engine scatters into paged-cache blocks after a long prefill,
        and both keep the sequence sharding.
        """
        from dynamo_tpu.ops.ring_attention import ring_attention

        cfg = self.config
        b, s = tokens.shape
        dh, hq, hk = cfg.head_dim, cfg.num_heads, cfg.num_kv_heads

        hidden = take_rows(params["embed"], tokens, cfg.jax_dtype)
        if cfg.scale_embeddings:
            hidden = hidden * jnp.asarray(
                math.sqrt(cfg.hidden_size), cfg.jax_dtype
            )
        uo = cfg.rmsnorm_unit_offset

        def layer_step(h, lp):
            x = rms_norm(h, lp["attn_norm"], cfg.rms_norm_eps, uo)
            q, k, v = _qkv_proj(cfg, lp, x, b, s)
            q = apply_rope(q, positions, cfg.rope_theta, self.inv_freq)
            k = apply_rope(k, positions, cfg.rope_theta, self.inv_freq)
            attn = ring_attention(
                q, k, v, positions, positions, mesh=mesh, axis=sp_axis,
                sm_scale=self.sm_scale, logit_cap=cfg.attn_logit_softcap,
                window=cfg.sliding_window,
            )
            attn_out = matmul(attn.reshape(b, s, hq * dh), lp["wo"])
            if cfg.post_norms:
                attn_out = rms_norm(attn_out, lp["post_attn_norm"],
                                    cfg.rms_norm_eps, uo)
            h = h + attn_out

            x = rms_norm(h, lp["mlp_norm"], cfg.rms_norm_eps, uo)
            mlp_out = _moe_mlp(cfg, lp, x) if cfg.is_moe else _dense_mlp(cfg, lp, x)
            if cfg.post_norms:
                mlp_out = rms_norm(mlp_out, lp["post_mlp_norm"],
                                   cfg.rms_norm_eps, uo)
            h = h + mlp_out
            kv = jnp.stack(
                [k.reshape(b, s, hk * dh), v.reshape(b, s, hk * dh)], axis=0
            )
            return h, kv

        hidden, kv = jax.lax.scan(layer_step, hidden, params["layers"])
        hidden = rms_norm(hidden, params["final_norm"], cfg.rms_norm_eps,
                          cfg.rmsnorm_unit_offset)
        return hidden, kv  # kv: [L, 2, B, S, Hk*D]

    def compute_logits(self, params: Params, hidden: jax.Array) -> jax.Array:
        """hidden [..., Dm] -> logits [..., V] in f32.

        The matmul runs in the weights' dtype with f32 accumulation — an
        explicit f32 cast of the vocab matrix would materialise a copy of
        the largest tensor in the model every step."""
        if self.config.tie_word_embeddings:
            w = params["embed"]
            # embed's per-row scale transposes into lm_head's per-column
            w = QTensor(w.q.T, w.scale.T) if isinstance(w, QTensor) else w.T
        else:
            w = params["lm_head"]
        if isinstance(w, QTensor):
            logits = matmul(hidden, w, preferred_element_type=jnp.float32)
        else:
            logits = jnp.matmul(
                hidden.astype(w.dtype), w, preferred_element_type=jnp.float32
            )
        cap = self.config.final_logit_softcap
        if cap:  # Gemma2 final logit softcap
            logits = softcap(logits, float(cap))
        return logits


def _qkv_proj(
    cfg: ModelConfig, lp: dict, x: jax.Array, b: int, s: int
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """QKV projections (+ Qwen2 bias / Qwen3 per-head q-k norms)."""
    dh, hq, hk = cfg.head_dim, cfg.num_heads, cfg.num_kv_heads
    q, k, v = matmul(x, lp["wq"]), matmul(x, lp["wk"]), matmul(x, lp["wv"])
    if cfg.attention_bias:
        q, k, v = q + lp["bq"], k + lp["bk"], v + lp["bv"]
    q = q.reshape(b, s, hq, dh)
    k = k.reshape(b, s, hk, dh)
    if cfg.qk_norm:  # Qwen3: RMSNorm over head_dim, pre-RoPE
        q = rms_norm(q, lp["q_norm"], cfg.rms_norm_eps)
        k = rms_norm(k, lp["k_norm"], cfg.rms_norm_eps)
    return q, k, v.reshape(b, s, hk, dh)


def _act(cfg: ModelConfig, gate: jax.Array) -> jax.Array:
    """Gate activation shared by every MLP path: SiLU (Llama) or
    tanh-GELU (Gemma GeGLU)."""
    return (jax.nn.gelu(gate, approximate=True)
            if cfg.hidden_activation == "gelu_tanh" else jax.nn.silu(gate))


def _dense_mlp(cfg: ModelConfig, lp: dict, x: jax.Array) -> jax.Array:
    """Gated MLP: act(x·Wg) * (x·Wu) · Wd."""
    return matmul(
        _act(cfg, matmul(x, lp["w_gate"])) * matmul(x, lp["w_up"]),
        lp["w_down"],
    )


def _moe_router(cfg: ModelConfig, lp: dict, xf: jax.Array):
    """Shared routing for both dispatch paths: top-k expert ids + weights.
    xf: [T, Dm] → (weights [T,k] f32, topi [T,k] int32)."""
    router_logits = (xf @ lp["router"]).astype(jnp.float32)  # [T,E]
    topv, topi = jax.lax.top_k(router_logits, cfg.num_experts_per_tok)
    if cfg.norm_topk_prob:
        # renormalized top-k == softmax over the top-k logits
        weights = jax.nn.softmax(topv, axis=-1)
    else:
        # Qwen3-MoE norm_topk_prob=False: full-softmax probs of the top-k
        probs_all = jax.nn.softmax(router_logits, axis=-1)
        weights = jnp.take_along_axis(probs_all, topi, axis=-1)
    return weights, topi


def _moe_mlp(cfg: ModelConfig, lp: dict, x: jax.Array) -> jax.Array:
    import os

    if os.environ.get("DYNAMO_MOE_DENSE"):
        return _moe_mlp_dense(cfg, lp, x)
    return _moe_mlp_grouped(cfg, lp, x)


def grouped_expert_dispatch(xf, weights, topi, num_experts,
                            w_gate, w_up, w_down, act):
    """The grouped-MoE core, shared across model families (Llama-family
    MoE here, DeepSeekMoE in models/deepseek.py): sort token→expert
    assignments by expert, run each projection as ONE ``lax.ragged_dot``
    (XLA's grouped matmul), then weighted unsort-sum back per token.
    ``xf`` [T,Dm]; ``weights``/``topi`` [T,k]; ``w_*`` dense [E,Dm,F] /
    [E,F,Dm]; ``act`` maps the gate activation."""
    t, d = xf.shape
    k = topi.shape[1]
    flat_e = topi.reshape(t * k)
    order = jnp.argsort(flat_e)          # stable: ties keep token order
    token_idx = order // k               # source token of each sorted row
    xs = xf[token_idx]                   # [T*k, Dm] gather
    group_sizes = jnp.bincount(flat_e, length=num_experts).astype(jnp.int32)
    gate = jax.lax.ragged_dot(xs, w_gate, group_sizes)
    up = jax.lax.ragged_dot(xs, w_up, group_sizes)
    out = jax.lax.ragged_dot(act(gate) * up, w_down, group_sizes)  # [T*k, Dm]
    out = out * weights.reshape(t * k)[order, None].astype(out.dtype)
    # unsort (inverse permutation) then reduce the k slots of each token;
    # gather+reshape-sum keeps the combine deterministic (no scatter-add)
    return out[jnp.argsort(order)].reshape(t, k, d).sum(axis=1)


def _moe_mlp_grouped(cfg: ModelConfig, lp: dict, x: jax.Array) -> jax.Array:
    """Grouped MoE dispatch: sort token→expert assignments by expert, run
    ONE ragged (grouped) matmul per projection, unsort, weighted-sum per
    token.  Intermediates are [T·k, F] — E/k× smaller than the dense
    path's [T, E, F] — and FLOPs are exactly the k experts each token
    routed to (the dense path computes all E).

    TPU mapping: ``lax.ragged_dot`` is XLA's grouped matmul and tiles onto
    the MXU; under the mesh the expert FFN dim F is sharded over "model"
    (partition_specs), which GSPMD partitions directly — compute and
    weight memory split evenly across devices REGARDLESS of routing skew
    (device-EP would idle devices whose experts receive no tokens).
    Replaces the reference's inherited vLLM fused-MoE CUDA kernels
    (container/deps/vllm patch, grouped_topk region) with the XLA-native
    equivalent."""
    b, s, d = x.shape
    xf = x.reshape(b * s, d)
    weights, topi = _moe_router(cfg, lp, xf)
    out = grouped_expert_dispatch(
        xf, weights, topi, cfg.num_experts,
        # quantized experts dequant at the operand: convert fuses into
        # the grouped dot's operand load, HBM reads stay int8
        dequantize(lp["w_gate"], x.dtype),
        dequantize(lp["w_up"], x.dtype),
        dequantize(lp["w_down"], x.dtype),
        lambda g: _act(cfg, g),
    )
    return out.reshape(b, s, d)


def _moe_mlp_dense(cfg: ModelConfig, lp: dict, x: jax.Array) -> jax.Array:
    """Dense-dispatch MoE oracle: each expert computes all tokens, weighted
    by its (top-k-normalised) router probability.  O(E/k) wasted FLOPs and
    [B,S,E,F] intermediates — kept as the parity oracle for the grouped
    path (DYNAMO_MOE_DENSE=1) because it contains no permutation logic."""
    b, s, d = x.shape
    weights, topi = _moe_router(cfg, lp, x.reshape(b * s, d))
    weights = weights.reshape(b, s, -1)
    topi = topi.reshape(b, s, -1)
    onehot = jax.nn.one_hot(topi, cfg.num_experts, dtype=jnp.float32)  # [B,S,k,E]
    gate_probs = jnp.einsum("bske,bsk->bse", onehot, weights)  # [B,S,E]
    w_up = dequantize(lp["w_up"], x.dtype)
    w_gate = dequantize(lp["w_gate"], x.dtype)
    w_down = dequantize(lp["w_down"], x.dtype)
    up = jnp.einsum("bsd,edf->bsef", x, w_up)
    gate = jnp.einsum("bsd,edf->bsef", x, w_gate)
    act = _act(cfg, gate) * up
    out = jnp.einsum("bsef,efd->bsed", act, w_down)
    return jnp.einsum("bsed,bse->bsd", out, gate_probs.astype(out.dtype))
