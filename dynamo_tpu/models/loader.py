"""HuggingFace checkpoint → params pytree loader.

Maps the HF Llama/Mixtral weight naming onto the stacked-layer layout used
by LlamaModel (weights transposed to [in, out] and stacked on a leading L
axis for lax.scan).  Loads from a local HF model directory (safetensors) or
from an in-memory state_dict (tests use a tiny random transformers model).

Reference analogue: the reference never loads weights itself (vLLM does);
its closest piece is ModelDeploymentCard creation from an HF repo
(lib/llm/src/model_card/create.rs).  Here loading is first-class.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Callable, Mapping

import jax.numpy as jnp
import numpy as np

from dynamo_tpu.models.config import ModelConfig

__all__ = ["load_params_from_state_dict", "load_params_from_dir", "load_model_dir"]


def _np(x) -> np.ndarray:
    if hasattr(x, "detach"):  # torch tensor
        x = x.detach().to("cpu").float().numpy()
    return np.asarray(x)


def load_params_from_state_dict(
    cfg: ModelConfig, state: Mapping[str, Any], dtype=None
) -> dict:
    """Convert an HF-style state dict (torch tensors or ndarrays) to params."""
    dt = dtype or cfg.jax_dtype
    L = cfg.num_layers

    def get(name: str) -> np.ndarray:
        return _np(state[name])

    def stack(fmt: str, transpose: bool = True) -> jnp.ndarray:
        ws = []
        for i in range(L):
            w = get(fmt.format(i=i))
            ws.append(w.T if transpose else w)
        return jnp.asarray(np.stack(ws), dtype=dt)

    # Phi3 fuses qkv_proj and gate_up_proj into single matrices
    fused_qkv = "model.layers.0.self_attn.qkv_proj.weight" in state
    fused_gate_up = "model.layers.0.mlp.gate_up_proj.weight" in state

    def stack_fused(fmt: str, sizes: list[int]) -> list[jnp.ndarray]:
        """One read of each layer's fused [sum(sizes), in] matrix, split
        into len(sizes) stacked parts (the lazy safetensors mapping
        re-reads the whole tensor per get(), so per-part reads would cost
        len(sizes)x the host I/O at load)."""
        parts: list[list[np.ndarray]] = [[] for _ in sizes]
        for i in range(L):
            w = get(fmt.format(i=i))
            off = 0
            for j, sz in enumerate(sizes):
                parts[j].append(w[off:off + sz].T)
                off += sz
        return [jnp.asarray(np.stack(p), dtype=dt) for p in parts]

    dh = cfg.head_dim
    if fused_qkv:
        wq, wk, wv = stack_fused(
            "model.layers.{i}.self_attn.qkv_proj.weight",
            [cfg.num_heads * dh, cfg.num_kv_heads * dh, cfg.num_kv_heads * dh],
        )
    else:
        wq = stack("model.layers.{i}.self_attn.q_proj.weight")
        wk = stack("model.layers.{i}.self_attn.k_proj.weight")
        wv = stack("model.layers.{i}.self_attn.v_proj.weight")
    layers = {
        "attn_norm": stack("model.layers.{i}.input_layernorm.weight", transpose=False),
        "wq": wq,
        "wk": wk,
        "wv": wv,
        "wo": stack("model.layers.{i}.self_attn.o_proj.weight"),
        # Gemma2 renames the pre-MLP norm and adds sandwich norms; in the
        # Llama family post_attention_layernorm IS the pre-MLP norm
        "mlp_norm": stack(
            "model.layers.{i}.pre_feedforward_layernorm.weight"
            if cfg.post_norms
            else "model.layers.{i}.post_attention_layernorm.weight",
            transpose=False,
        ),
    }
    if cfg.post_norms:
        layers.update(
            post_attn_norm=stack(
                "model.layers.{i}.post_attention_layernorm.weight",
                transpose=False,
            ),
            post_mlp_norm=stack(
                "model.layers.{i}.post_feedforward_layernorm.weight",
                transpose=False,
            ),
        )
    if cfg.attention_bias:
        layers.update(
            bq=stack("model.layers.{i}.self_attn.q_proj.bias", transpose=False),
            bk=stack("model.layers.{i}.self_attn.k_proj.bias", transpose=False),
            bv=stack("model.layers.{i}.self_attn.v_proj.bias", transpose=False),
        )
    if cfg.qk_norm:  # Qwen3 per-head norms
        layers.update(
            q_norm=stack("model.layers.{i}.self_attn.q_norm.weight",
                         transpose=False),
            k_norm=stack("model.layers.{i}.self_attn.k_norm.weight",
                         transpose=False),
        )
    if cfg.is_moe:
        e = cfg.num_experts

        def stack_experts(fmt: str) -> jnp.ndarray:
            return jnp.asarray(
                np.stack(
                    [
                        np.stack([get(fmt.format(i=i, e=j)).T for j in range(e)])
                        for i in range(L)
                    ]
                ),
                dtype=dt,
            )

        if "model.layers.0.mlp.gate.weight" in state:  # Qwen3-MoE naming
            layers.update(
                router=stack("model.layers.{i}.mlp.gate.weight"),
                w_gate=stack_experts("model.layers.{i}.mlp.experts.{e}.gate_proj.weight"),
                w_down=stack_experts("model.layers.{i}.mlp.experts.{e}.down_proj.weight"),
                w_up=stack_experts("model.layers.{i}.mlp.experts.{e}.up_proj.weight"),
            )
        else:  # Mixtral naming
            layers.update(
                router=stack("model.layers.{i}.block_sparse_moe.gate.weight"),
                w_gate=stack_experts("model.layers.{i}.block_sparse_moe.experts.{e}.w1.weight"),
                w_down=stack_experts("model.layers.{i}.block_sparse_moe.experts.{e}.w2.weight"),
                w_up=stack_experts("model.layers.{i}.block_sparse_moe.experts.{e}.w3.weight"),
            )
    else:
        if fused_gate_up:
            w_gate, w_up = stack_fused(
                "model.layers.{i}.mlp.gate_up_proj.weight",
                [cfg.intermediate_size, cfg.intermediate_size],
            )
        else:
            w_gate = stack("model.layers.{i}.mlp.gate_proj.weight")
            w_up = stack("model.layers.{i}.mlp.up_proj.weight")
        layers.update(
            w_gate=w_gate,
            w_up=w_up,
            w_down=stack("model.layers.{i}.mlp.down_proj.weight"),
        )

    params = {
        "embed": jnp.asarray(get("model.embed_tokens.weight"), dtype=dt),
        "layers": layers,
        "final_norm": jnp.asarray(get("model.norm.weight"), dtype=dt),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = jnp.asarray(get("lm_head.weight").T, dtype=dt)
    return params


class _LazySafetensors(Mapping):
    """Mapping over all *.safetensors files in a dir, loading tensors on
    demand so 70B checkpoints never fully materialise in host RAM at once."""

    def __init__(self, model_dir: Path):
        from safetensors import safe_open

        self._open: Callable = safe_open
        self._index: dict[str, Path] = {}
        files = sorted(model_dir.glob("*.safetensors"))
        if not files:
            raise FileNotFoundError(f"no safetensors files in {model_dir}")
        index_file = model_dir / "model.safetensors.index.json"
        if index_file.exists():
            weight_map = json.loads(index_file.read_text())["weight_map"]
            for name, fname in weight_map.items():
                self._index[name] = model_dir / fname
        else:
            for f in files:
                with safe_open(f, framework="np") as sf:
                    for name in sf.keys():
                        self._index[name] = f

    def __getitem__(self, name: str) -> np.ndarray:
        with self._open(self._index[name], framework="np") as sf:
            return sf.get_tensor(name)

    def __iter__(self):
        return iter(self._index)

    def __len__(self):
        return len(self._index)


def load_params_from_dir(cfg: ModelConfig, model_dir: str | Path, dtype=None) -> dict:
    return load_params_from_state_dict(cfg, _LazySafetensors(Path(model_dir)), dtype)


def load_model_dir(model_dir: str | Path, dtype: str = "bfloat16"):
    """Convenience: (ModelConfig, params) from a local HF model directory."""
    cfg = ModelConfig.from_hf_config(model_dir, dtype=dtype)
    return cfg, load_params_from_dir(cfg, model_dir)


def is_deepseek_dir(model_dir: str | Path) -> bool:
    """True when config.json declares a DeepSeek architecture (the MLA
    family loads through models/deepseek.py, not the unified decoder)."""
    import json as _json

    p = Path(model_dir) / "config.json"
    if not p.exists():
        return False
    try:
        archs = _json.loads(p.read_text()).get("architectures") or []
    except Exception:
        return False
    return any(str(a).startswith("Deepseek") for a in archs)


def load_deepseek_dir(model_dir: str | Path, dtype: str = "bfloat16"):
    """(DeepseekConfig, params) from a DeepSeek-V2 HF directory —
    safetensors stream lazily through the same shard mapping."""
    import json as _json

    from dynamo_tpu.models.deepseek import DeepseekConfig, convert_hf_state_dict

    cfg = DeepseekConfig.from_hf(
        _json.loads((Path(model_dir) / "config.json").read_text())
    )
    cfg.dtype = dtype
    return cfg, convert_hf_state_dict(_LazySafetensors(Path(model_dir)), cfg)
