"""Seeded production-shaped traffic for the scale-simulation plane.

The generator is pure and deterministic: ``generate(spec, seed=, rps=,
duration_s=)`` returns the same request list byte-for-byte on every
machine (sub-streams are seeded by string tags, never by wall clock or
global RNG state).  Shapes modeled, per the FlowKV / Prefill-as-a-Service
observation that cache economies only pay off under production traffic:

  * **multi-turn agent sessions** — turn k's prompt is exactly turn
    k-1's prompt + the assistant's reply + the new user turn, so the
    previous turn's prefill blocks are a true prefix of the next turn
    (the router's chained sequence hashes match without any special
    casing here);
  * **tenant skew** — tenants drawn Zipf(a); each tenant has a fixed
    system-prompt prefix shared by all its sessions (cross-session
    overlap, not just intra-session);
  * **diurnal ramp** — sinusoidal rate modulation over the trace;
  * **burst storms** — windows where the arrival rate multiplies;
  * **failure storms** — a schedule of kill/restore marks the harness
    applies to simulated workers mid-trace.

Arrivals are an open-loop non-homogeneous Poisson process (thinning),
so an overloaded system sheds or queues — offered load never back-offs
to fit capacity, which is what makes the capacity knee observable.
"""

from __future__ import annotations

import bisect
import math
import random
from dataclasses import dataclass
from typing import Sequence

from dynamo_tpu.tokens import sequence_hashes

__all__ = [
    "Request",
    "ScenarioSpec",
    "FAMILIES",
    "generate",
    "tenant_mass",
    "prefix_share",
    "arrival_histogram",
]

_VOCAB = 32000


@dataclass(frozen=True)
class Request:
    """One generated request, ready for the harness to dispatch."""

    rid: int
    arrival_s: float
    tenant: str
    session: str
    turn: int                 # 0-based turn index within the session
    token_ids: tuple          # full prompt (history included)
    osl: int                  # output tokens to decode
    priority: str = "normal"

    @property
    def isl(self) -> int:
        return len(self.token_ids)


@dataclass(frozen=True)
class ScenarioSpec:
    """One scenario family's shape.  Rates and durations are supplied by
    the harness (derived from the topology's capacity), so the same spec
    scales from a smoke run to a nightly million-request trace."""

    name: str
    family: str
    turns_max: int = 1
    think_s: float = 2.0            # virtual pause between a session's turns
    shared_prefix_blocks: int = 0   # tenant system-prompt depth (blocks)
    isl_blocks_mean: int = 8        # mean first-turn prompt length (blocks)
    osl_mean: int = 48              # mean output tokens
    num_tenants: int = 32
    zipf_a: float = 1.1
    diurnal_amplitude: float = 0.0  # rate *= 1 + A*sin(2*pi*t/period)
    diurnal_period_s: float = 60.0
    # burst storms: (start_frac, duration_frac, rate_multiplier)
    bursts: tuple = ()
    # failure storms: (at_frac, "kill"|"restore", worker_ordinal)
    failures: tuple = ()
    # TTFT SLA = factor * unloaded TTFT (router hop + one prefill)
    sla_ttft_factor: float = 20.0
    block_size: int = 16


FAMILIES: dict[str, ScenarioSpec] = {
    s.name: s for s in [
        # single-turn, no shared prefix: the pure routing/admission floor
        ScenarioSpec(name="steady", family="steady", turns_max=1,
                     shared_prefix_blocks=0, zipf_a=0.0),
        # agentic sessions with deep shared prefixes — the regime where
        # overlap-aware placement has to beat load balancing
        ScenarioSpec(name="agentic", family="agentic", turns_max=4,
                     think_s=1.5, shared_prefix_blocks=6,
                     isl_blocks_mean=8, osl_mean=64, num_tenants=16,
                     zipf_a=1.2),
        # diurnal ramp + a mid-trace burst storm
        ScenarioSpec(name="burst", family="burst", turns_max=2,
                     shared_prefix_blocks=3, diurnal_amplitude=0.5,
                     bursts=((0.45, 0.15, 3.0),), zipf_a=1.1),
        # a worker dies mid-trace and returns cold later
        ScenarioSpec(name="failure", family="failure", turns_max=2,
                     shared_prefix_blocks=3,
                     failures=((0.35, "kill", 0), (0.7, "restore", 0))),
    ]
}


def _rng(seed: int, tag: str) -> random.Random:
    """Independent deterministic sub-stream (str seeding is stable)."""
    return random.Random(f"dtload:{seed}:{tag}")


def _zipf_cum(n: int, a: float) -> list[float]:
    if a <= 0:
        w = [1.0] * n
    else:
        w = [1.0 / (r ** a) for r in range(1, n + 1)]
    total = sum(w)
    cum, acc = [], 0.0
    for x in w:
        acc += x / total
        cum.append(acc)
    return cum


def _rate_mult(spec: ScenarioSpec, t: float, duration_s: float) -> float:
    m = 1.0 + spec.diurnal_amplitude * math.sin(
        2.0 * math.pi * t / max(spec.diurnal_period_s, 1e-9))
    for start_frac, dur_frac, mult in spec.bursts:
        start = start_frac * duration_s
        if start <= t < start + dur_frac * duration_s:
            m *= mult
    return max(m, 0.0)


def _peak_mult(spec: ScenarioSpec) -> float:
    peak = 1.0 + spec.diurnal_amplitude
    for _s, _d, mult in spec.bursts:
        peak = max(peak, (1.0 + spec.diurnal_amplitude) * mult)
    return peak


def _tokens(rng: random.Random, n: int) -> list[int]:
    return [rng.randrange(_VOCAB) for _ in range(n)]


def _draw_len(rng: random.Random, mean: int, lo: int, hi: int) -> int:
    return max(lo, min(hi, int(rng.expovariate(1.0 / max(mean, 1)))))


def generate(spec: ScenarioSpec, *, seed: int, rps: float,
             duration_s: float) -> list[Request]:
    """Open-loop trace: session starts arrive Poisson at
    ``rps / mean_turns`` modulated by the diurnal/burst envelope; each
    start expands into 1..turns_max turns spaced ``think_s`` apart."""
    bs = spec.block_size
    mean_turns = (1 + spec.turns_max) / 2.0
    session_rate = max(rps, 1e-9) / mean_turns
    lam_max = session_rate * _peak_mult(spec)

    arr = _rng(seed, "arrivals")
    zipf = _zipf_cum(spec.num_tenants, spec.zipf_a)
    prefix_cache: dict[str, list[int]] = {}

    requests: list[Request] = []
    rid = 0
    t = 0.0
    sess_no = 0
    while True:
        t += arr.expovariate(lam_max)
        if t >= duration_s:
            break
        # thinning: keep the candidate with prob rate(t)/rate_max
        if arr.random() >= _rate_mult(spec, t, duration_s) / _peak_mult(spec):
            continue
        tenant = f"t{bisect.bisect_left(zipf, arr.random())}"
        sess_no += 1
        session = f"s{sess_no}"
        srng = _rng(seed, f"session:{session}")
        n_turns = srng.randint(1, spec.turns_max)

        prefix = prefix_cache.get(tenant)
        if prefix is None:
            prefix = _tokens(_rng(seed, f"prefix:{tenant}"),
                             spec.shared_prefix_blocks * bs)
            prefix_cache[tenant] = prefix

        history = list(prefix)
        arrival = t
        for turn in range(n_turns):
            if arrival >= duration_s:
                break
            user_mean = max(bs, spec.isl_blocks_mean * bs - len(prefix)
                            if turn == 0 else 2 * bs)
            user = _tokens(srng, _draw_len(srng, user_mean, 4,
                                           8 * spec.isl_blocks_mean * bs))
            osl = _draw_len(srng, spec.osl_mean, 4, 4 * spec.osl_mean)
            p = srng.random()
            priority = "high" if p < 0.1 else ("low" if p > 0.9 else "normal")
            token_ids = tuple(history + user)
            requests.append(Request(
                rid=rid, arrival_s=round(arrival, 6), tenant=tenant,
                session=session, turn=turn, token_ids=token_ids, osl=osl,
                priority=priority))
            rid += 1
            # the served prompt + the assistant reply becomes the next
            # turn's history — an exact prefix, so prefill blocks reuse
            history = list(token_ids) + _tokens(srng, osl)
            arrival += spec.think_s + srng.expovariate(2.0 / spec.think_s)
    requests.sort(key=lambda r: (r.arrival_s, r.rid))
    return requests


# ------------------------------------------------------------------ oracles
# Distribution checks the tests pin the generator's shape with.


def tenant_mass(requests: Sequence[Request], top: int = 1) -> float:
    """Fraction of requests belonging to the ``top`` busiest tenants."""
    counts: dict[str, int] = {}
    for r in requests:
        counts[r.tenant] = counts.get(r.tenant, 0) + 1
    if not counts:
        return 0.0
    busiest = sorted(counts.values(), reverse=True)[:top]
    return sum(busiest) / len(requests)


def prefix_share(requests: Sequence[Request], block_size: int = 16) -> float:
    """Fraction of prompt blocks (over the whole trace, arrival order)
    whose chained sequence hash was already produced by an earlier
    request — the trace's intrinsic cache-reuse ceiling."""
    seen: set[int] = set()
    total = dup = 0
    for r in requests:
        for h in sequence_hashes(r.token_ids, block_size):
            total += 1
            if h in seen:
                dup += 1
            else:
                seen.add(h)
    return dup / total if total else 0.0


def arrival_histogram(requests: Sequence[Request], duration_s: float,
                      bins: int = 12) -> list[int]:
    out = [0] * bins
    for r in requests:
        i = min(bins - 1, int(r.arrival_s / max(duration_s, 1e-9) * bins))
        out[i] += 1
    return out
