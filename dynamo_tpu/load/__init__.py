"""dtload — scale-simulation plane (macro-simulation at virtual time).

Extends the protocol plane's DetLoop from correctness checking to
capacity measurement: the REAL control-plane components (KvIndexer,
KvScheduler, AdmissionController, planner policy) run against simulated
workers whose dispatch durations come from dtperf's committed
predicted-latency manifest, under production-shaped traffic from a
seeded generator.  A ten-minute, many-thousand-request trace runs in
seconds of wall clock, byte-identically per seed.

    load/traffic.py   seeded scenario generator (sessions, Zipf tenants,
                      diurnal ramps, bursts, failure storms)
    load/workers.py   LatencyModel (from analysis/perf_manifest.json)
                      + SimWorker (slot-gated, time-sliced, KV-evicting)
    load/sim.py       the harness: run_cell / sweep over topologies and
                      offered-load levels

The capacity gate lives in analysis/loadcheck.py (`dynamo-tpu lint
--load`, rules LD001-LD004 against analysis/load_manifest.json).
"""

from dynamo_tpu.load.traffic import (
    FAMILIES,
    Request,
    ScenarioSpec,
    arrival_histogram,
    generate,
    prefix_share,
    tenant_mass,
)
from dynamo_tpu.load.workers import LatencyModel, SimWorker, SimWorkerDied
from dynamo_tpu.load.sim import (
    LOAD_LEVELS,
    TOPOLOGIES,
    Topology,
    canonical_bytes,
    run_cell,
    sweep,
)

__all__ = [
    "FAMILIES",
    "Request",
    "ScenarioSpec",
    "arrival_histogram",
    "generate",
    "prefix_share",
    "tenant_mass",
    "LatencyModel",
    "SimWorker",
    "SimWorkerDied",
    "LOAD_LEVELS",
    "TOPOLOGIES",
    "Topology",
    "canonical_bytes",
    "run_cell",
    "sweep",
]
