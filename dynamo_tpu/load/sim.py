"""The macro-simulation harness: real control plane, simulated scale.

One *cell* = (scenario family, topology) at one offered-load level.
``run_cell`` builds the real router stack — ``KvIndexer`` fed through
the real KV-event wire codec, ``KvScheduler`` with the real selector
cost model, the real ``AdmissionController`` — on a seeded ``DetLoop``,
then replays a generated trace against SimWorkers that consume virtual
time per dtperf's predicted latencies.  Routing, admission, planner
role-flip and persist/transfer scoring all execute their actual code
paths; only chips and sockets are simulated.

Offered load is derived from the modeled capacity (min of worker-pool
throughput and the serialized router's decision rate) so ``level=1.0``
means "at the knee's doorstep" on every topology, and ``level=2.0`` is
a genuine overload.  Duration is level-independent: a level-2 cell
carries twice the requests of level-1.

Determinism contract: same (family, topology, seed, level, target,
latency model) → byte-identical ``canonical_bytes``.  The gate's LD003
rule holds this line; everything here avoids wall clock, global RNG,
and unordered iteration.
"""

from __future__ import annotations

import asyncio
import math
import os
import random
from dataclasses import dataclass, replace
from typing import Optional, Union

from dynamo_tpu.analysis.detloop import DetLoop, RandomScheduler, run_deterministic
from dynamo_tpu.llm.kv.events import event_from_wire
from dynamo_tpu.llm.kv_router.indexer import KvIndexer
from dynamo_tpu.llm.kv_router.scheduler import (
    AllWorkersBusy,
    DefaultWorkerSelector,
    KvScheduler,
)
from dynamo_tpu.llm.kv_router.shards.indexer import ShardedKvIndexer
from dynamo_tpu.utils.chash import HashRing
from dynamo_tpu.load.traffic import FAMILIES, generate
from dynamo_tpu.load.workers import LatencyModel, SimWorker, SimWorkerDied
from dynamo_tpu.obs.costs import TransferCostTable
from dynamo_tpu.planner.admission import (
    AdmissionConfig,
    AdmissionController,
    AdmissionRejected,
    PriorityClass,
)
from dynamo_tpu.planner.policy import (
    MetricsSnapshot,
    PlannerPolicy,
    PoolSnapshot,
    WorkerSample,
)
from dynamo_tpu.tokens import sequence_hashes

__all__ = [
    "Topology",
    "TOPOLOGIES",
    "CELLS",
    "LOAD_LEVELS",
    "default_target",
    "run_cell",
    "sweep",
    "canonical_bytes",
    "knee_level",
]


@dataclass(frozen=True)
class Topology:
    name: str
    n_workers: int
    disagg: bool = False
    n_prefill: int = 0          # of n_workers, when disagg
    slots: int = 8
    kv_blocks: int = 4096
    # sharded control plane (llm/kv_router/shards/): number of router
    # replicas, each owning a hash partition of the prefix index and
    # serializing only its own decisions.  1 = the singleton router.
    router_shards: int = 1
    # per-topology router decision cost override (ms).  The default
    # LatencyModel prices a decision at its micro-benchmarked Python
    # cost, where the pool is the wall at any modeled scale; the
    # router-stress topologies below price it at the production-index
    # per-decision cost instead (full radix walk + scoring over a large
    # fleet) — the regime ROADMAP item 1 targets — so the singleton
    # router IS the binding constraint and sharding is measurable.
    router_ms: Optional[float] = None
    # per-topology offered-load grid override (None = LOAD_LEVELS);
    # the r-cells need headroom levels to locate each shard count's knee
    levels: Optional[tuple[float, ...]] = None

    @property
    def n_decode(self) -> int:
        return self.n_workers - (self.n_prefill if self.disagg else 0)


# offered-load grid for the router-stress cells: levels are priced off
# the SINGLETON's capacity for every shard count (see _derive), so the
# same level means the same absolute offered rps across r1/r2/r4 and
# knee levels are directly comparable.  120ms/decision keeps the
# singleton router wall ~10x below the pool wall, so every grid level
# up to 8x stays in the router-bound regime and the knee movement is
# attributable to sharding alone.
ROUTER_STRESS_MS = 120.0
SHARD_LEVELS: tuple[float, ...] = (0.5, 1.0, 2.0, 4.0, 8.0)

TOPOLOGIES: dict[str, Topology] = {
    t.name: t for t in [
        Topology(name="w1", n_workers=1),
        Topology(name="w4", n_workers=4),
        Topology(name="w16", n_workers=16, disagg=True, n_prefill=4),
        # router-stress trio: identical pool, identical SLA, identical
        # offered-load pricing — only the shard count varies
        Topology(name="w16r1", n_workers=16, disagg=True, n_prefill=4,
                 router_shards=1, router_ms=ROUTER_STRESS_MS,
                 levels=SHARD_LEVELS),
        Topology(name="w16r2", n_workers=16, disagg=True, n_prefill=4,
                 router_shards=2, router_ms=ROUTER_STRESS_MS,
                 levels=SHARD_LEVELS),
        Topology(name="w16r4", n_workers=16, disagg=True, n_prefill=4,
                 router_shards=4, router_ms=ROUTER_STRESS_MS,
                 levels=SHARD_LEVELS),
    ]
}

# the committed capacity grid: every family on every topology except the
# steady floor twice over — 10 cells spanning 4 families x 3 topologies,
# plus the sharded-router trio on the session-heavy agentic family
CELLS: tuple[tuple[str, str], ...] = (
    ("steady", "w1"), ("steady", "w4"), ("steady", "w16"),
    ("agentic", "w1"), ("agentic", "w4"), ("agentic", "w16"),
    ("burst", "w4"), ("burst", "w16"),
    ("failure", "w4"), ("failure", "w16"),
    ("agentic", "w16r1"), ("agentic", "w16r2"), ("agentic", "w16r4"),
)

LOAD_LEVELS: tuple[float, ...] = (0.5, 1.0, 2.0)

# offered = level * this fraction of modeled capacity: level 1.0 runs
# warm but under the knee, level 2.0 is structurally past it
_UTILIZATION = 0.7
_SCRAPE_EVERY_S = 0.1
_PLANNER_TICK_S = 2.0


def default_target() -> int:
    """Requests per cell at level 1.0 (DTLOAD_TARGET overrides; a
    non-default value marks the run non-pinned for the drift rules)."""
    return int(os.environ.get("DTLOAD_TARGET", "") or 160)


def _lvl_key(level: float) -> str:
    return f"{level:g}"


@dataclass(frozen=True)
class _Derived:
    offered_rps: float
    duration_s: float
    sla_ttft_s: float
    service_s: float


def _router_s(topo: Topology, lat: LatencyModel) -> float:
    """Per-decision router cost, honoring the topology override."""
    return topo.router_ms / 1e3 if topo.router_ms is not None \
        else lat.router_s()


def _derive(spec, topo: Topology, lat: LatencyModel, level: float,
            target: int) -> _Derived:
    isl_tokens = spec.isl_blocks_mean * spec.block_size
    # mean engine occupancy of one request: a local prefill plus a
    # decode time-sliced across a full complement of co-resident slots
    # (the saturation regime — SimWorker scales step time by co-residency)
    service_s = (lat.prefill_s(isl_tokens)
                 + spec.osl_mean * lat.decode_step_s() * topo.slots)
    pool_cap = topo.n_decode * topo.slots / service_s
    r_s = _router_s(topo, lat)
    # deliberately SINGLETON-priced: router_cap ignores router_shards so
    # one level is the same absolute offered rps on every shard count —
    # the r-cells' knee comparison needs a common x-axis
    router_cap = 1.0 / r_s
    sys_cap = min(pool_cap, 0.9 * router_cap)
    base = _UTILIZATION * sys_cap
    duration = target / base
    sla = spec.sla_ttft_factor * (r_s
                                  + lat.prefill_s(isl_tokens)
                                  + lat.decode_step_s())
    return _Derived(offered_rps=level * base, duration_s=duration,
                    sla_ttft_s=sla, service_s=service_s)


def _pct(sorted_vals: list, q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, max(0, math.ceil(q * len(sorted_vals)) - 1))
    return sorted_vals[i]


def _admission_config(topo: Topology, d: _Derived) -> AdmissionConfig:
    # deadlines scale with the cell's SLA so shedding engages near the
    # knee instead of at the defaults' wall-clock-sized waits
    def pc(name: str, level: int, depth_mult: int, wait_mult: float):
        return PriorityClass(name, level,
                             max_queue_depth=depth_mult * topo.n_decode
                             * topo.slots,
                             max_wait_s=round(wait_mult * d.sla_ttft_s, 9))
    return AdmissionConfig(
        max_concurrent=topo.n_decode * topo.slots,
        priorities={
            "high": pc("high", 0, 8, 16.0),
            "normal": pc("normal", 1, 4, 8.0),
            "low": pc("low", 2, 2, 2.0),
        },
        default_service_s=round(d.service_s, 9),
    )


def run_cell(family: str, topology: Union[str, Topology], *, seed: int,
             level: float = 1.0, target_requests: Optional[int] = None,
             lat: Optional[LatencyModel] = None,
             collect_decisions: bool = False) -> dict:
    """One deterministic simulated cell.  Returns ``{"metrics", "census",
    "decisions"?}`` — everything the gate snapshots, rounded for stable
    canonical bytes."""
    spec = FAMILIES[family]
    topo = TOPOLOGIES[topology] if isinstance(topology, str) else topology
    lat = lat or LatencyModel.from_perf_manifest()
    target = target_requests if target_requests is not None \
        else default_target()
    d = _derive(spec, topo, lat, level, target)
    # keep multi-turn sessions inside the trace window on fast topologies
    if spec.turns_max > 1:
        spec = replace(spec, think_s=min(
            spec.think_s, d.duration_s / (4.0 * spec.turns_max)))
    reqs = generate(spec, seed=seed, rps=d.offered_rps,
                    duration_s=d.duration_s)
    bs = spec.block_size

    loop = DetLoop(RandomScheduler(seed),
                   horizon_s=max(600.0, 40.0 * d.duration_s),
                   max_steps=max(300_000, 600 * max(1, len(reqs))))

    state = {
        "ttfts": [], "itls": [], "completed": 0, "shed": 0, "failed": 0,
        "tokens_out": 0, "router_busy": 0.0, "decisions": 0, "top1": 0,
        "overlap_blocks": 0, "isl_blocks": 0, "load_std_sum": 0.0,
        "load_std_n": 0, "t_end": 0.0,
    }
    census: dict[str, int] = {}
    decisions: list[dict] = []

    def bump(key: str, n: int = 1) -> None:
        census[key] = census.get(key, 0) + n

    async def _main() -> None:
        clock = loop.time
        n_shards = topo.router_shards
        if n_shards > 1:
            # the REAL sharded index: events split by hash ownership,
            # lookups run the scatter-gather merge (shards/scatter.py)
            indexer = ShardedKvIndexer(n_shards)
        else:
            indexer = KvIndexer(use_native=False)   # env-independent facts

        def publish(wire: dict) -> None:
            eid, wid, ev = event_from_wire(wire)
            indexer.apply_event(wid, ev, eid)
            bump("kv_events")

        decode_workers = {
            i: SimWorker(i, lat, publish=publish, clock=clock,
                         slots=topo.slots, kv_blocks=topo.kv_blocks,
                         block_size=bs)
            for i in range(topo.n_decode)
        }
        prefill_workers = [
            SimWorker(100 + i, lat, publish=publish, clock=clock,
                      slots=topo.slots, kv_blocks=topo.kv_blocks,
                      block_size=bs)
            for i in range(topo.n_prefill if topo.disagg else 0)
        ]
        selector = DefaultWorkerSelector(
            random.Random(f"dtload:{seed}:selector"))
        sched = KvScheduler(selector, block_size=bs,
                            transfer_weight=1.0 if topo.disagg else 0.0)
        costs = TransferCostTable(clock=clock)
        admission = AdmissionController(_admission_config(topo, d),
                                        clock=clock)
        for w in decode_workers.values():
            sched.update_worker(w.metrics())
        r_s = _router_s(topo, lat)
        # one lock per router replica: each replica serializes its own
        # decisions; sessions stick to a replica via the same consistent-
        # hash ring the frontends use (utils/chash.py), so a multi-turn
        # session's decisions stay ordered on one replica
        router_locks = [asyncio.Lock() for _ in range(n_shards)]
        if n_shards > 1:
            ring = HashRing(f"replica-{i}" for i in range(n_shards))
            replica_ix = {f"replica-{i}": i for i in range(n_shards)}

            def replica_of(session) -> int:
                return replica_ix[ring.lookup(f"session:{session}")]
        else:
            def replica_of(session) -> int:
                return 0
        t0 = clock()

        async def route(req):
            """The serialized router: one decision at a time PER REPLICA,
            each consuming its modeled cost — the singleton wall ROADMAP
            item 1 predicts (measurable as router_busy_frac), and the
            knob the sharded cells turn."""
            async with router_locks[replica_of(req.session)]:
                await asyncio.sleep(r_s)
                state["router_busy"] += r_s
                hashes = sequence_hashes(req.token_ids, bs)
                match = indexer.find_matches(hashes)
                tcosts = None
                pw = None
                if topo.disagg:
                    pw = min((w for w in prefill_workers if w.alive),
                             key=lambda w: (w._active + w._waiting, w.wid),
                             default=None)
                    if pw is not None:
                        nbytes = lat.transfer_bytes(len(hashes))
                        tcosts = {
                            wid: costs.cost_s(f"w{pw.wid}", f"w{wid}",
                                              "ici", nbytes)
                            for wid, w in decode_workers.items() if w.alive
                        }
                scored = sched.score_candidates(
                    match.scores, len(req.token_ids),
                    persist_overlaps=match.persist_scores,
                    transfer_costs_s=tcosts)
                wid = sched.schedule(
                    match.scores, len(req.token_ids),
                    persist_overlaps=match.persist_scores,
                    transfer_costs_s=tcosts)
                return hashes, match, wid, scored, pw

        async def handle(req) -> None:
            try:
                ticket = await admission.acquire(req.tenant, req.priority)
            except AdmissionRejected:
                state["shed"] += 1
                bump("shed")
                return
            t_arrive = clock()
            try:
                for attempt in (0, 1):
                    try:
                        hashes, match, wid, scored, pw = await route(req)
                    except AllWorkersBusy:
                        state["shed"] += 1
                        bump("shed_busy")
                        return
                    w = decode_workers[wid]
                    overlap = match.scores.get(wid, 0)
                    state["decisions"] += 1
                    if scored and wid == scored[0][0]:
                        state["top1"] += 1
                    state["overlap_blocks"] += overlap
                    state["isl_blocks"] += len(hashes)
                    if collect_decisions:
                        decisions.append({
                            "rid": req.rid, "session": req.session,
                            "turn": req.turn, "worker": wid,
                            "overlap_blocks": overlap,
                            "isl_blocks": len(hashes),
                        })
                    try:
                        if topo.disagg and pw is not None:
                            await pw.prefill(hashes, len(req.token_ids))
                            move = max(0, len(hashes) - overlap)
                            nbytes = lat.transfer_bytes(move)
                            src, dst = f"w{pw.wid}", f"w{wid}"
                            tr_s = costs.cost_s(src, dst, "ici", nbytes)
                            if move:
                                await asyncio.sleep(tr_s)
                                costs.record(src, dst, "ici", nbytes, tr_s)
                                bump("kv_transfers")
                            t_first, t_done, _ = await w.decode(
                                hashes, req.osl)
                        else:
                            t_first, t_done, _ = await w.decode(
                                hashes, req.osl,
                                prefill_tokens=len(req.token_ids))
                        ttft = t_first - t_arrive
                        itl = (t_done - t_first) / max(1, req.osl - 1)
                        state["ttfts"].append(ttft)
                        state["itls"].append(itl)
                        state["completed"] += 1
                        state["tokens_out"] += req.osl
                        admission.observe_ttft(ttft)
                        admission.observe_itl(itl)
                        return
                    except SimWorkerDied:
                        bump("worker_died")
                        if attempt == 0:
                            bump("retried")
                            continue
                        state["failed"] += 1
                        return
            finally:
                ticket.release()

        async def scrape() -> None:
            while True:
                await asyncio.sleep(_SCRAPE_EVERY_S)
                for w in decode_workers.values():
                    if w.alive:
                        sched.update_worker(w.metrics())
                ls = sched.load_summary()
                state["load_std_sum"] += ls["load_std"]
                state["load_std_n"] += 1

        async def planner_ticks() -> None:
            policy = PlannerPolicy()
            tick = 0
            osl_mean = float(spec.osl_mean)
            isl_mean = float(spec.isl_blocks_mean * bs)
            while True:
                await asyncio.sleep(_PLANNER_TICK_S)
                tick += 1

                def samples(ws):
                    return tuple(
                        WorkerSample(
                            worker_id=w.wid,
                            request_active_slots=m.request_active_slots,
                            request_total_slots=m.request_total_slots,
                            kv_active_blocks=m.kv_active_blocks,
                            kv_total_blocks=m.kv_total_blocks,
                            num_requests_waiting=m.num_requests_waiting,
                        )
                        for w in ws if w.alive
                        for m in (w.metrics(),))
                live_pf = [w for w in prefill_workers if w.alive]
                live_dc = [w for w in decode_workers.values() if w.alive]
                snap = MetricsSnapshot(
                    tick=tick,
                    prefill=PoolSnapshot(
                        replicas=len(prefill_workers),
                        registered=len(live_pf),
                        samples=samples(prefill_workers),
                        queue_depth=sum(w._waiting for w in live_pf)),
                    decode=PoolSnapshot(
                        replicas=len(decode_workers),
                        registered=len(live_dc),
                        samples=samples(decode_workers.values())),
                    isl_mean=isl_mean, osl_mean=osl_mean)
                p = policy.plan(snap)
                bump("planner_ticks")
                if p.flip:
                    bump("planner_flips")

        async def failure_storm() -> None:
            for at_frac, action, ordinal in spec.failures:
                when = t0 + at_frac * d.duration_s
                delay = when - clock()
                if delay > 0:
                    await asyncio.sleep(delay)
                w = decode_workers[ordinal % len(decode_workers)]
                if action == "kill":
                    w.kill()
                    sched.mark_suspect(w.wid)
                    bump("kills")
                    # the health plane's lease expiry follows shortly
                    await asyncio.sleep(2 * _SCRAPE_EVERY_S)
                    indexer.remove_worker(w.wid)
                    sched.remove_worker(w.wid)
                else:
                    w.restore()
                    sched.clear_suspect(w.wid)
                    sched.update_worker(w.metrics())
                    bump("restores")

        scrape_task = asyncio.ensure_future(scrape())
        plan_task = (asyncio.ensure_future(planner_ticks())
                     if topo.disagg else None)
        fail_task = (asyncio.ensure_future(failure_storm())
                     if spec.failures else None)

        req_tasks = []
        for req in reqs:
            delay = req.arrival_s - (clock() - t0)
            if delay > 0:
                await asyncio.sleep(delay)
            req_tasks.append(asyncio.ensure_future(handle(req)))
        await asyncio.gather(*req_tasks)
        if fail_task is not None:
            await fail_task
        for t in (scrape_task, plan_task):
            if t is None:
                continue
            t.cancel()
            try:
                await t
            except asyncio.CancelledError:
                pass
        state["t_end"] = clock() - t0

    run_deterministic(loop, _main())
    loop.close()

    span = max(state["t_end"], 1e-9)
    ttfts = sorted(state["ttfts"])
    itls = sorted(state["itls"])
    n = len(reqs)
    metrics = {
        "offered_rps": round(d.offered_rps, 3),
        "duration_s": round(d.duration_s, 3),
        "sla_ttft_ms": round(d.sla_ttft_s * 1e3, 3),
        "requests": n,
        "completed": state["completed"],
        "shed_rate": round((state["shed"] + state["failed"]) / max(1, n), 4),
        "ttft_p50_ms": round(_pct(ttfts, 0.50) * 1e3, 3),
        "ttft_p95_ms": round(_pct(ttfts, 0.95) * 1e3, 3),
        "ttft_p99_ms": round(_pct(ttfts, 0.99) * 1e3, 3),
        "itl_p50_ms": round(_pct(itls, 0.50) * 1e3, 3),
        "itl_p99_ms": round(_pct(itls, 0.99) * 1e3, 3),
        "itl_mean_ms": round(
            sum(itls) / len(itls) * 1e3 if itls else 0.0, 3),
        "output_tok_s": round(state["tokens_out"] / span, 3),
        "overlap_ratio": round(
            state["overlap_blocks"] / max(1, state["isl_blocks"]), 4),
        "decision_top1_frac": round(
            state["top1"] / max(1, state["decisions"]), 4),
        "load_std": round(
            state["load_std_sum"] / max(1, state["load_std_n"]), 4),
        # busy fraction of the AGGREGATE replica budget: span seconds of
        # wall per replica — for shards=1 this is the singleton's
        # serialized busy fraction, unchanged
        "router_busy_frac": round(
            state["router_busy"] / (span * topo.router_shards), 4),
    }
    if topo.router_shards > 1:
        metrics["router_shards"] = topo.router_shards
    out = {"metrics": metrics, "census": dict(sorted(census.items()))}
    if collect_decisions:
        out["decisions"] = decisions
    return out


def canonical_bytes(result: dict) -> bytes:
    """Stable byte serialization of a cell result — the LD003 twin-run
    comparison surface."""
    import json

    return json.dumps(
        {"metrics": result["metrics"], "census": result["census"]},
        sort_keys=True, separators=(",", ":")).encode()


def knee_level(levels: dict, sla_ttft_ms: float) -> Optional[float]:
    """Lowest offered-load level whose p99 TTFT breaches the SLA or
    whose shed rate exceeds 1% — None when capacity holds everywhere."""
    for lvl in sorted(levels, key=float):
        m = levels[lvl]
        if m["ttft_p99_ms"] > sla_ttft_ms or m["shed_rate"] > 0.01:
            return float(lvl)
    return None


def sweep(*, budget: int = 1, seed_base: int = 0,
          target_requests: Optional[int] = None,
          lat: Optional[LatencyModel] = None,
          cells: Optional[tuple] = None) -> dict:
    """The full capacity grid.  ``budget`` adds extra seeds per cell
    (each with its own twin-determinism check) on top of the pinned
    level sweep; facts' level metrics always come from ``seed_base``
    so the committed manifest is budget-independent."""
    lat = lat or LatencyModel.from_perf_manifest()
    target = target_requests if target_requests is not None \
        else default_target()
    out_cells: dict[str, dict] = {}
    for family, topology in (cells or CELLS):
        name = f"{family}/{topology}"
        grid = TOPOLOGIES[topology].levels or LOAD_LEVELS
        levels: dict[str, dict] = {}
        census: dict[str, int] = {}
        base_level1 = None
        for level in grid:
            res = run_cell(family, topology, seed=seed_base, level=level,
                           target_requests=target, lat=lat)
            levels[_lvl_key(level)] = res["metrics"]
            for k, v in res["census"].items():
                census[k] = census.get(k, 0) + v
            if level == 1.0:
                base_level1 = res
        twin_match = True
        for i in range(max(1, budget)):
            seed = seed_base + i
            first = base_level1 if i == 0 else run_cell(
                family, topology, seed=seed, level=1.0,
                target_requests=target, lat=lat)
            twin = run_cell(family, topology, seed=seed, level=1.0,
                            target_requests=target, lat=lat)
            if canonical_bytes(first) != canonical_bytes(twin):
                twin_match = False
        sla = levels[_lvl_key(1.0)]["sla_ttft_ms"]
        knee = knee_level(levels, sla)
        out_cells[name] = {
            "levels": levels,
            "census": census,
            "twin_match": twin_match,
            "knee_level": knee,
        }
    return {
        "cells": out_cells,
        "params": {
            "target_requests": target,
            "levels": [float(x) for x in LOAD_LEVELS],
            "scale": lat.scale,
            "prefill_ms_per_token": round(lat.prefill_ms_per_token, 9),
            "decode_ms_per_step": round(lat.decode_ms_per_step, 9),
            "router_ms_per_decision": lat.router_ms_per_decision,
        },
    }
