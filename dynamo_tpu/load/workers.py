"""Simulated workers whose dispatch durations come from dtperf.

``LatencyModel`` reads the committed ``analysis/perf_manifest.json``
(the perf plane's predicted per-signature latencies) and turns it into
per-token prefill and per-step decode costs.  That coupling is the
point: a PR that regresses the predicted engine latencies moves every
simulated capacity number, and the load gate (LD001) catches it — the
macro-simulation inherits dtperf's sensitivity without re-measuring
anything.

The committed predictions price the tiny audit-rig model, so an
explicit ``scale`` knob maps them to a production-class checkpoint:
the *shape* (prefill:decode ratio, growth with tokens) comes from the
manifest, the magnitude from scale.  Control-plane costs (the router's
per-decision Python time) are NOT scaled — they are real wall costs
independent of model size, which is exactly why the singleton router
becomes the wall at high worker counts (ROADMAP item 1).

``SimWorker`` consumes virtual time only: slot-gated admission,
time-sliced decode (ITL grows with concurrent decodes on the chip),
LRU KV eviction publishing REAL KvRemovedEvents, and a kill/restore
surface for failure storms.  All cache traffic goes through the real
``event_to_wire``/``event_from_wire`` codec so the router's indexer
sees production-shaped event streams.
"""

from __future__ import annotations

import asyncio
import json
import os
import statistics
import time
from pathlib import Path
from typing import Callable, Optional, Sequence

from dynamo_tpu.llm.kv.events import (
    KvRemovedEvent,
    KvStoredEvent,
    event_to_wire,
)
from dynamo_tpu.llm.kv_router.scheduler import WorkerMetrics

__all__ = ["LatencyModel", "SimWorker", "SimWorkerDied"]

DEFAULT_PERF_MANIFEST = (
    Path(__file__).resolve().parents[1] / "analysis" / "perf_manifest.json")

# committed tiny-llama predictions (perf_manifest.json), used verbatim
# when the manifest is missing or its keys moved — the sim must never
# crash on a trimmed checkout
_FALLBACK_PREFILL_MS_PER_TOKEN = 0.003022 / 64
_FALLBACK_DECODE_MS_PER_STEP = 0.016498 / 16
_DEFAULT_SCALE = 2000.0


def _sig_params(sig: str) -> dict[str, float]:
    out: dict[str, float] = {}
    for part in sig.split(","):
        if "=" in part:
            k, _, v = part.partition("=")
            try:
                out[k.strip()] = float(v)
            except ValueError:
                pass
    return out


def _per_unit_ms(entry: Optional[dict], param: str) -> Optional[float]:
    """Median predicted total_ms per unit of ``param`` over an
    entrypoint's signatures (robust to which shapes are committed)."""
    if not entry:
        return None
    vals = []
    for sig, rec in entry.get("signatures", {}).items():
        n = _sig_params(sig).get(param)
        total = (rec.get("predicted") or {}).get("total_ms")
        if n and total:
            vals.append(total / n)
    return statistics.median(vals) if vals else None


class LatencyModel:
    """Virtual-time costs for one simulated deployment."""

    def __init__(self, *, prefill_ms_per_token: float,
                 decode_ms_per_step: float,
                 router_ms_per_decision: float = 0.15,
                 kv_bytes_per_block: int = 2 * 1024 * 1024,
                 scale: float = _DEFAULT_SCALE):
        self.prefill_ms_per_token = prefill_ms_per_token
        self.decode_ms_per_step = decode_ms_per_step
        self.router_ms_per_decision = router_ms_per_decision
        self.kv_bytes_per_block = kv_bytes_per_block
        self.scale = scale

    @classmethod
    def from_perf_manifest(cls, path: Optional[Path] = None,
                           config: str = "tiny-llama",
                           scale: Optional[float] = None,
                           router_ms_per_decision: float = 0.15,
                           ) -> "LatencyModel":
        if scale is None:
            scale = float(os.environ.get("DTLOAD_SCALE", "") or _DEFAULT_SCALE)
        p = Path(path) if path is not None else DEFAULT_PERF_MANIFEST
        prefill = decode = None
        if p.is_file():
            try:
                entries = json.loads(p.read_text()).get("entrypoints", {})
            except (json.JSONDecodeError, OSError):
                entries = {}
            prefill = _per_unit_ms(
                entries.get(f"engine.prefill_ragged[{config}]"), "t")
            decode = _per_unit_ms(
                entries.get(f"engine.decode_multi[{config}]"), "k")
        return cls(
            prefill_ms_per_token=prefill or _FALLBACK_PREFILL_MS_PER_TOKEN,
            decode_ms_per_step=decode or _FALLBACK_DECODE_MS_PER_STEP,
            router_ms_per_decision=router_ms_per_decision,
            scale=scale,
        )

    # ------------------------------------------------------------- durations
    def prefill_s(self, new_tokens: int) -> float:
        return max(0, new_tokens) * self.prefill_ms_per_token * self.scale / 1e3

    def decode_step_s(self) -> float:
        return self.decode_ms_per_step * self.scale / 1e3

    def router_s(self) -> float:
        return self.router_ms_per_decision / 1e3

    def transfer_bytes(self, blocks: int) -> int:
        return blocks * self.kv_bytes_per_block


class SimWorkerDied(Exception):
    """The worker was killed while serving (failure storm)."""


class SimWorker:
    """One simulated engine: ``slots`` concurrent requests, a
    ``kv_blocks``-deep LRU device cache, decode time-sliced across the
    requests actively decoding on the chip."""

    def __init__(self, wid: int, lat: LatencyModel, *,
                 publish: Callable[[dict], None],
                 clock: Callable[[], float] = time.monotonic,
                 slots: int = 8, kv_blocks: int = 4096,
                 block_size: int = 16):
        self.wid = wid
        self.lat = lat
        self.publish = publish
        self.clock = clock
        self.slots = slots
        self.kv_blocks = kv_blocks
        self.block_size = block_size
        self.alive = True
        self.completed = 0
        self.tokens_out = 0
        self.evicted_blocks = 0
        self._sem = asyncio.Semaphore(slots)
        self._active = 0
        self._waiting = 0
        self._decoding = 0
        self._resident: dict[int, None] = {}   # insertion order = LRU order
        self._event_id = 0

    # -------------------------------------------------------------- KV cache
    def _resident_prefix(self, hashes: Sequence[int]) -> int:
        k = 0
        for h in hashes:
            if h not in self._resident:
                break
            k += 1
            self._resident[h] = self._resident.pop(h)   # LRU touch
        return k

    def _emit(self, ev) -> None:
        self._event_id += 1
        self.publish(event_to_wire(self._event_id, self.wid, ev))

    def _store(self, hashes: Sequence[int], known: int) -> None:
        new = [h for h in hashes[known:] if h not in self._resident]
        if new:
            parent = hashes[known - 1] if known > 0 else None
            for h in new:
                self._resident[h] = None
            self._emit(KvStoredEvent(block_hashes=new, parent_hash=parent))
        if len(self._resident) > self.kv_blocks:
            n_evict = len(self._resident) - self.kv_blocks
            victims = list(self._resident)[:n_evict]
            for h in victims:
                del self._resident[h]
            self.evicted_blocks += len(victims)
            self._emit(KvRemovedEvent(block_hashes=victims))

    # --------------------------------------------------------------- serving
    def _check_alive(self) -> None:
        if not self.alive:
            raise SimWorkerDied(f"worker {self.wid} died mid-serve")

    async def prefill(self, hashes: Sequence[int], isl_tokens: int,
                      pre_delay_s: float = 0.0) -> int:
        """Prefill only (disagg prefill role).  Returns the warm-prefix
        block count it reused; publishes the new blocks as stored."""
        self._waiting += 1
        await self._sem.acquire()
        self._waiting -= 1
        self._active += 1
        try:
            self._check_alive()
            known = self._resident_prefix(hashes)
            new_tokens = isl_tokens - known * self.block_size
            if pre_delay_s > 0:
                await asyncio.sleep(pre_delay_s)
            await asyncio.sleep(self.lat.prefill_s(new_tokens))
            self._check_alive()
            self._store(hashes, known)
            return known
        finally:
            self._active -= 1
            self._sem.release()

    async def decode(self, hashes: Sequence[int], osl: int,
                     pre_delay_s: float = 0.0,
                     prefill_tokens: int = 0) -> tuple[float, float, int]:
        """Hold a slot and decode ``osl`` tokens, time-sliced across the
        chip's active decodes.  ``prefill_tokens`` > 0 folds a local
        prefill in first (aggregated serving); 0 means the KV arrived by
        transfer (disagg decode role).  Returns (t_first_token, t_done,
        warm_prefix_blocks)."""
        self._waiting += 1
        await self._sem.acquire()
        self._waiting -= 1
        self._active += 1
        try:
            self._check_alive()
            known = self._resident_prefix(hashes)
            if pre_delay_s > 0:
                await asyncio.sleep(pre_delay_s)
            if prefill_tokens > 0:
                new_tokens = max(0, prefill_tokens - known * self.block_size)
                await asyncio.sleep(self.lat.prefill_s(new_tokens))
                self._check_alive()
            self._store(hashes, known)
            step = self.lat.decode_step_s()
            self._decoding += 1
            try:
                await asyncio.sleep(step * max(1, self._decoding))
                t_first = self.clock()
                # two chunks, concurrency resampled between them:
                # scheduling-point economy over per-token fidelity
                left = max(0, osl - 1)
                for n in (left // 2, left - left // 2):
                    if n:
                        await asyncio.sleep(
                            n * step * max(1, self._decoding))
                    self._check_alive()
            finally:
                self._decoding -= 1
            t_done = self.clock()
            self.completed += 1
            self.tokens_out += osl
            return t_first, t_done, known
        finally:
            self._active -= 1
            self._sem.release()

    # --------------------------------------------------------------- control
    def kill(self) -> None:
        self.alive = False

    def restore(self) -> None:
        """Back from the dead, cache cold (the harness already tore the
        worker out of the router index)."""
        self._resident.clear()
        self.alive = True

    def metrics(self) -> WorkerMetrics:
        return WorkerMetrics(
            worker_id=self.wid,
            request_active_slots=self._active,
            request_total_slots=self.slots,
            kv_active_blocks=len(self._resident),
            kv_total_blocks=self.kv_blocks,
            num_requests_waiting=self._waiting,
            updated_at=self.clock(),
        )
