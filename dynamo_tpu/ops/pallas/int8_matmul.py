"""Dequant-in-kernel int8 matmul — the standby fix for perf hypothesis #2.

docs/perf_analysis_r3.md: if the profiler shows XLA materializing
bf16-converted weight tiles to HBM (instead of fusing the convert into
the matmul operand load), int8 weight-only serving loses its entire
bandwidth win. This kernel guarantees the int8->bf16 convert happens in
VMEM: weight tiles stream from HBM as int8, convert on-chip, hit the MXU,
and the per-output-channel scale applies in the epilogue.

Gated OFF by default (DYNAMO_PALLAS_INT8_MATMUL=1 enables it in
models/quant.py's matmul) so it can be A/B-measured against the XLA path
the moment hardware answers; oracle parity is pinned in
tests/test_pallas_kernels.py either way.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dynamo_tpu.ops.pallas.registry import (
    INT8_MATMUL_BK,
    INT8_MATMUL_BM,
    INT8_MATMUL_BN,
)

__all__ = ["int8_matmul", "BM", "BN", "BK"]

# default block sizes — owned by the kernel registry (the audit prices
# against the same table); re-exported so the routing precheck in
# models/quant.py and the kernel's tiling asserts can never disagree
BM, BN, BK = INT8_MATMUL_BM, INT8_MATMUL_BN, INT8_MATMUL_BK


def _kernel(x_ref, w_ref, s_ref, o_ref, acc_ref, *, nk: int):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # int8 tile -> bf16 in VMEM -> MXU; HBM only ever saw int8 bytes
    acc_ref[:] += jax.lax.dot(
        x_ref[:].astype(jnp.bfloat16),
        w_ref[:].astype(jnp.bfloat16),
        preferred_element_type=jnp.float32,
    )

    @pl.when(k == nk - 1)
    def _epilogue():
        o_ref[:] = (
            acc_ref[:] * s_ref[0, :].astype(jnp.float32)[None, :]
        ).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("out_dtype", "bm", "bn", "bk", "interpret"),
)
def int8_matmul(
    x: jax.Array,       # [M, K] bf16/f32
    wq: jax.Array,      # [K, N] int8
    scale: jax.Array,   # [N] f32 — per-output-channel
    out_dtype=None,
    bm: int = BM,
    bn: int = BN,
    bk: int = BK,
    interpret: bool = False,
) -> jax.Array:
    """``x @ dequant(wq, scale)`` with the convert inside the kernel.

    Grid (M/bm, N/bn, K/bk); the K axis is the sequential reduction (TPU
    grids execute in order), accumulating into VMEM scratch and applying
    the scale at the last K step.  Dims must tile exactly — model dims
    are 128-multiples, and callers fall back to the XLA path otherwise.
    """
    m, k = x.shape
    k2, n = wq.shape
    assert k == k2, (x.shape, wq.shape)
    out_dtype = out_dtype or x.dtype
    bm = min(bm, m)
    bn = min(bn, n)
    bk = min(bk, k)
    assert m % bm == 0 and n % bn == 0 and k % bk == 0, (m, n, k, bm, bn, bk)
    nk = k // bk

    return pl.pallas_call(
        functools.partial(_kernel, nk=nk),
        grid=(m // bm, n // bn, nk),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
            # scale rides as [1, N] on the standard f32 (8,128) layout —
            # a 1-D f32 operand's XLA layout is T(1024)-tiled, which
            # Mosaic rejects for 512-wide blocks on real TPUs
            pl.BlockSpec((1, bn), lambda i, j, kk: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), out_dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        interpret=interpret,
    )(x, wq, scale.reshape(1, n))
