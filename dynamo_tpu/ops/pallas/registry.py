"""Kernel registry — the single source of truth for Pallas kernel geometry.

Every `pallas_call` site in ops/pallas/ is registered here together with
the geometry matrix it is audited under (analysis/kerncheck.py, the
dtkern plane) and probed under (benchmarks/probe_kernels.py, bench.py).
The registry owns three things the kernels themselves must not:

- **tile constants**: blocks-per-chunk / rows-per-chunk / matmul block
  sizes.  The kernels import their defaults from here, so a tuning-knob
  change is one edit that the audit, the probes and the serving path all
  see (DT105 flags integer tile literals that bypass this table).
- **the audit matrix**: per-kernel geometry cases, including the
  adversarial ragged shapes (empty rows, 1-token decode rows,
  non-block-divisible lengths, max-block rows, non-block-aligned decode
  starts) that the NaN-canary padding oracles run against, plus
  serving-scale spec-only cases that are shape-traced (jax.eval_shape)
  for VMEM/pricing without executing.
- **capture + pricing**: a `pallas_call` spy that records grid, specs,
  scratch and operands at call time, and the analytic cost model (HBM
  DMA bytes / FLOPs / transcendentals) shared between the kern-manifest
  pricing facts and the `cost_estimate=` each attention kernel hands
  XLA's scheduler.

kerncheck turns the captures into KN001-KN006 facts; this module stays
importable from ops/ (no analysis imports) and imports the kernels only
lazily inside builders so the kernels can import the constants above.
"""

from __future__ import annotations

import contextlib
import functools
import math

__all__ = [
    "DECODE_BLOCKS_PER_CHUNK",
    "DECODE_SEQS_PER_GROUP",
    "PREFILL_ROWS_PER_CHUNK",
    "PREFILL_BLOCKS_PER_CHUNK",
    "INT8_MATMUL_BM",
    "INT8_MATMUL_BN",
    "INT8_MATMUL_BK",
    "V5E_VMEM_BYTES",
    "VMEM_BUDGET_BYTES",
    "KERNELS",
    "audit_cases",
    "fuzz_case",
    "capture_pallas_calls",
    "decode_kernel_cost",
    "prefill_kernel_cost",
    "ragged_kernel_cost",
    "int8_matmul_cost",
    "decode_cost_estimate",
    "prefill_cost_estimate",
    "ragged_cost_estimate",
    "fallback_census",
    "probe_coverage",
    "quantize_audit_cache",
]

# ------------------------------------------------------- tile constants ----
# The serving tile sizes.  decode: 4 blocks per DMA chunk x 8 sequences
# per grid step fits the 8B bf16 KV working set; prefill: 128 query rows
# per grid step keeps acc/m/l scratch + the VMEM-resident fresh K/V well
# inside VMEM at S=2048.  int8_matmul: MXU-shaped (128, 512, 512).
DECODE_BLOCKS_PER_CHUNK = 4
DECODE_SEQS_PER_GROUP = 8
PREFILL_ROWS_PER_CHUNK = 128
PREFILL_BLOCKS_PER_CHUNK = 8
INT8_MATMUL_BM = 128
INT8_MATMUL_BN = 512
INT8_MATMUL_BK = 512

# v5e VMEM is 128 MiB per core (accelerator guide); budget 75% of it —
# the compiler needs headroom for spills and the double-buffer pipeline.
V5E_VMEM_BYTES = 128 * 1024 * 1024
VMEM_BUDGET_BYTES = int(V5E_VMEM_BYTES * 0.75)

# Pallas allocates two buffers per blocked operand (pipeline double
# buffering); manual kvbuf scratch already carries its own factor 2.
DOUBLE_BUFFER = 2

# ------------------------------------------------------- kernel census ----
# Every pallas_call site, plus the unified-kernel placeholder: ROADMAP
# item 2 (Ragged Paged Attention, arxiv 2604.15464) replaces the
# decode/ragged-prefill split with ONE kernel.  While `unified` has no
# module, kerncheck's census reports the two-kernel split (KN006) — the
# accepted manifest entry that landing item 2 re-trips.
KERNELS = {
    "paged_decode_attention_mq": {
        "module": "dynamo_tpu.ops.pallas.decode_attention",
        "placeholder": False,
    },
    "paged_prefill_attention": {
        "module": "dynamo_tpu.ops.pallas.prefill_attention",
        "placeholder": False,
    },
    "ragged_paged_prefill_attention": {
        "module": "dynamo_tpu.ops.pallas.prefill_attention",
        "placeholder": False,
    },
    "int8_matmul": {
        "module": "dynamo_tpu.ops.pallas.int8_matmul",
        "placeholder": False,
    },
    "unified_ragged_attention": {
        "module": None,  # ROADMAP item 2 — not yet written
        "placeholder": True,
    },
}


# ------------------------------------------------------------- capture ----


@contextlib.contextmanager
def capture_pallas_calls(records: list):
    """Monkeypatch `pl.pallas_call` on the shared pallas module with a
    spy that records (kernel name, grid, specs, scratch, operand avals)
    at call time and delegates to the real pallas_call.  The kernel
    modules all hold the module object (`from jax.experimental import
    pallas as pl`), so the attribute patch is visible to every site."""
    import jax.experimental.pallas as plmod

    real = plmod.pallas_call

    def spy(kernel, **kw):
        inner = real(kernel, **kw)

        def wrapped(*operands):
            records.append(_record_call(kernel, kw, operands))
            return inner(*operands)

        return wrapped

    plmod.pallas_call = spy
    try:
        yield records
    finally:
        plmod.pallas_call = real


def _kernel_name(kernel) -> str:
    fn = getattr(kernel, "func", kernel)  # unwrap functools.partial
    return getattr(fn, "__name__", repr(fn))


def _record_call(kernel, kw: dict, operands) -> dict:
    """Normalize one pallas_call into a plain capture record.  Works for
    both concrete operands (eager interpret runs) and tracers (spec-only
    jax.eval_shape runs) — only shape/dtype are read off the operands."""
    gs = kw.get("grid_spec")
    if gs is not None:
        grid = tuple(gs.grid)
        in_specs = list(gs.in_specs)
        out_specs = gs.out_specs
        scratch = list(getattr(gs, "scratch_shapes", ()) or ())
        nsp = int(getattr(gs, "num_scalar_prefetch", 0) or 0)
    else:
        grid = kw.get("grid", ())
        grid = (grid,) if isinstance(grid, int) else tuple(grid or ())
        in_specs = list(kw.get("in_specs", ()) or ())
        out_specs = kw.get("out_specs")
        scratch = list(kw.get("scratch_shapes", ()) or ())
        nsp = 0
    out_specs = (
        list(out_specs) if isinstance(out_specs, (list, tuple))
        else [out_specs]
    )
    out_shape = kw.get("out_shape")
    out_shapes = (
        list(out_shape) if isinstance(out_shape, (list, tuple))
        else [out_shape]
    )
    return {
        "name": _kernel_name(kernel),
        "grid": grid,
        "num_scalar_prefetch": nsp,
        "in_specs": in_specs,
        "out_specs": out_specs,
        "scratch": scratch,
        "operands": [(tuple(o.shape), str(o.dtype)) for o in operands],
        "out_shapes": [(tuple(o.shape), str(o.dtype)) for o in out_shapes],
        "interpret": bool(kw.get("interpret", False)),
    }


# ------------------------------------------------------- pricing model ----


def _cdiv(a: int, b: int) -> int:
    return -(-a // b)


def decode_kernel_cost(
    b: int, s_q: int, h: int, hk: int, d: int, bs: int, m: int,
    lens, cache_bytes: int = 2, quant: bool = False, q_bytes: int = 4,
    blocks_per_chunk: int = DECODE_BLOCKS_PER_CHUNK,
    seqs_per_group: int = DECODE_SEQS_PER_GROUP,
) -> dict:
    """Analytic cost of one flash-decode dispatch: per-group chunk DMA
    (work proportional to the group max context, the kernel's actual
    loop bound), blocked q/out traffic, QK+PV FLOPs and softmax exps.
    ``lens`` is the per-row context; pass ``[m * bs] * b`` for the
    worst-case static bound (cost_estimate=)."""
    hkd = hk * d
    rows = s_q * h
    c = min(blocks_per_chunk, m)
    g = max(1, seqs_per_group // s_q)
    while b % g:
        g -= 1
    t = c * bs
    block_bytes = 2 * bs * hkd * cache_bytes
    if quant:
        from dynamo_tpu.ops.kv_quant import scale_tile

        hp, sp = scale_tile(hk, bs)
        block_bytes += 2 * hp * sp * 4
    lens = [int(x) for x in lens]
    dma = flops = trans = 0
    for gi in range(b // g):
        grp_max = max(lens[gi * g:(gi + 1) * g])
        chunks = _cdiv(grp_max, t) if grp_max > 0 else 0
        dma += chunks * g * c * block_bytes
        flops += chunks * g * 4 * rows * t * hkd  # QK + PV matmuls
        trans += chunks * g * rows * t            # softmax exp
    steps = b // g
    dma += steps * g * rows * hkd * (4 + q_bytes)  # q (f32) in + out
    return _cost_dict(dma, flops, trans)


def prefill_kernel_cost(
    b: int, s: int, h: int, hk: int, d: int, bs: int, m: int,
    starts, cache_bytes: int = 2, quant: bool = False, q_bytes: int = 2,
    rows_per_chunk: int = PREFILL_ROWS_PER_CHUNK,
    blocks_per_chunk: int = PREFILL_BLOCKS_PER_CHUNK,
) -> dict:
    """Analytic cost of one flash-prefill dispatch.  Each of the S/TQ
    row-chunks of a row re-streams that row's cached prefix (the kernel
    restarts the prefix walk per grid step); the fresh phase is the
    causal triangle.  ``starts`` is the per-row prefix length (pass
    ``[m * bs] * b`` for the worst-case static bound)."""
    g = h // hk
    hkd = hk * d
    tq = min(rows_per_chunk, s)
    while s % tq:
        tq //= 2
    c = min(blocks_per_chunk, m)
    t = c * bs
    n_steps = s // tq
    rows = tq * g
    block_bytes = 2 * bs * hkd * cache_bytes
    if quant:
        from dynamo_tpu.ops.kv_quant import scale_tile

        hp, sp = scale_tile(hk, bs)
        block_bytes += 2 * hp * sp * 4
    dma = flops = trans = 0
    for start in [int(x) for x in starts]:
        p = _cdiv(start, t)
        dma += n_steps * p * c * block_bytes
        flops += n_steps * p * hk * 4 * rows * t * d
        trans += n_steps * p * hk * rows * t
    # fresh phase: step ri visits ri+1 TQ-sized K/V chunks (causal)
    tri = n_steps * (n_steps + 1) // 2
    flops += b * tri * hk * 4 * rows * tq * d
    trans += b * tri * hk * rows * tq
    # blocked traffic: q/out per step; fresh K/V re-fetched per batch row
    dma += b * n_steps * tq * hkd * g // hk * 0  # (kept explicit below)
    dma += b * n_steps * (tq * g * d * hk // hk) * 0
    dma += b * n_steps * tq * g * d * hk * 0
    dma += b * n_steps * hk * tq * g * d * (q_bytes + q_bytes)  # q + out
    dma += b * 2 * s * hkd * cache_bytes  # fresh K and V, once per row
    return _cost_dict(dma, flops, trans)


def ragged_kernel_cost(
    t_tokens: int, h: int, hk: int, d: int, bs: int, m: int,
    starts, cache_bytes: int = 2, quant: bool = False, q_bytes: int = 2,
    rows_per_chunk: int = PREFILL_ROWS_PER_CHUNK,
    blocks_per_chunk: int = PREFILL_BLOCKS_PER_CHUNK,
) -> dict:
    """Analytic cost of one ragged (mixed-chunk) dispatch: grid T/TQ;
    EVERY grid step walks every overlapping row's prefix — the audit
    prices the conservative bound where each step streams each row's
    full prefix (the kernel skips non-overlapping rows, so the true
    cost is lower for well-packed batches)."""
    g = h // hk
    hkd = hk * d
    tq = min(rows_per_chunk, t_tokens)
    while t_tokens % tq:
        tq //= 2
    c = min(blocks_per_chunk, m)
    t = c * bs
    n_steps = t_tokens // tq
    rows = tq * g
    block_bytes = 2 * bs * hkd * cache_bytes
    if quant:
        from dynamo_tpu.ops.kv_quant import scale_tile

        hp, sp = scale_tile(hk, bs)
        block_bytes += 2 * hp * sp * 4
    dma = flops = trans = 0
    for start in [int(x) for x in starts]:
        p = _cdiv(start, t)
        dma += n_steps * p * c * block_bytes
        flops += n_steps * p * hk * 4 * rows * t * d
        trans += n_steps * p * hk * rows * t
    tri = n_steps * (n_steps + 1) // 2
    flops += tri * hk * 4 * rows * tq * d
    trans += tri * hk * rows * tq
    dma += n_steps * hk * tq * g * d * (q_bytes + q_bytes)  # q + out
    dma += 2 * t_tokens * hkd * cache_bytes  # packed fresh K and V
    return _cost_dict(dma, flops, trans)


def int8_matmul_cost(
    m: int, k: int, n: int, x_bytes: int = 2, out_bytes: int = 2,
    bm: int = INT8_MATMUL_BM, bn: int = INT8_MATMUL_BN,
    bk: int = INT8_MATMUL_BK,
) -> dict:
    """Analytic cost of one dequant-in-kernel int8 matmul: the weight
    tile streams as int8 (the whole point), x tiles re-stream per N
    block, outputs write once."""
    bm, bn, bk = min(bm, m), min(bn, n), min(bk, k)
    gm, gn, gk = m // bm, n // bn, k // bk
    dma = (
        gm * gn * gk * (bm * bk * x_bytes + bk * bn * 1)  # x bf16 + w int8
        + gm * gn * (bm * bn * out_bytes + bn * 4)        # out + scale
    )
    return _cost_dict(dma, 2 * m * n * k, 0)


def _cost_dict(dma: int, flops: int, trans: int) -> dict:
    return {
        "hbm_bytes": int(dma),
        "flops": int(flops),
        "transcendentals": int(trans),
        "intensity": round(flops / dma, 4) if dma else 0.0,
    }


def _cost_estimate(cost: dict):
    """dict -> pl.CostEstimate (None when this jax predates it)."""
    from jax.experimental import pallas as pl

    ce = getattr(pl, "CostEstimate", None)
    if ce is None:  # pragma: no cover - older jax
        return None
    return ce(
        flops=cost["flops"],
        transcendentals=cost["transcendentals"],
        bytes_accessed=cost["hbm_bytes"],
    )


def decode_cost_estimate(b, s_q, h, hk, d, bs, m, cache_bytes, quant,
                         blocks_per_chunk, seqs_per_group):
    """Worst-case (full-table context) CostEstimate for the decode
    pallas_call — seq_lens are dynamic at trace time, so the static
    bound is every row at M*Bs context."""
    return _cost_estimate(decode_kernel_cost(
        b, s_q, h, hk, d, bs, m, [m * bs] * b, cache_bytes=cache_bytes,
        quant=quant, blocks_per_chunk=blocks_per_chunk,
        seqs_per_group=seqs_per_group,
    ))


def prefill_cost_estimate(b, s, h, hk, d, bs, m, cache_bytes, quant,
                          rows_per_chunk, blocks_per_chunk):
    return _cost_estimate(prefill_kernel_cost(
        b, s, h, hk, d, bs, m, [m * bs] * b, cache_bytes=cache_bytes,
        quant=quant, rows_per_chunk=rows_per_chunk,
        blocks_per_chunk=blocks_per_chunk,
    ))


def ragged_cost_estimate(t_tokens, r_rows, h, hk, d, bs, m, cache_bytes,
                         quant, rows_per_chunk, blocks_per_chunk):
    return _cost_estimate(ragged_kernel_cost(
        t_tokens, h, hk, d, bs, m, [m * bs] * r_rows,
        cache_bytes=cache_bytes, quant=quant,
        rows_per_chunk=rows_per_chunk, blocks_per_chunk=blocks_per_chunk,
    ))


# -------------------------------------------------- cross-plane census ----


def fallback_census() -> dict:
    """The XLA-fallback collective census the shard plane accepted: the
    CPU decode probes gather the paged cache because the Pallas kernels
    (which keep it on-chip) don't lower there.  kerncheck asserts these
    stay in sync with shard_manifest.json's accepted SH002 entries
    (KN006) — retiring a kernel, or landing the unified kernel, must
    update BOTH planes deliberately."""
    return {
        "probe.llama.decode[tiny-llama]": {"all-gather": 6},
        "probe.deepseek.decode[tiny-mla]": {"all-gather": 7},
    }


def probe_coverage() -> dict:
    """kernel -> probed?  True when benchmarks/probe_kernels.py builds a
    variant from this registry's probe builders (satellite: a registered
    kernel without a probe is a KN006 finding).  Placeholders carry no
    probe by definition."""
    return {
        name: (name in _PROBE_BUILDERS or meta["placeholder"])
        for name, meta in KERNELS.items()
    }


# ------------------------------------------------------ input builders ----


def quantize_audit_cache(cache, hk: int):
    """f32 cache [L, N, 2, Bs, Hk*D] -> QuantKvCache with the canonical
    token-minor tile-padded scale layout."""
    import jax.numpy as jnp

    from dynamo_tpu.ops.kv_quant import (
        QuantKvCache,
        pad_scales,
        quantize_kv_rows,
    )

    L, n, _, bs, hkd = cache.shape
    d = hkd // hk
    q8, sc = quantize_kv_rows(cache.reshape(L, n, 2, bs, hk, d))
    data = q8.reshape(L, n, 2, bs, hkd)
    sc = jnp.swapaxes(sc, -1, -2)  # [..., Hk, Bs] token-minor
    return QuantKvCache(data, pad_scales(sc))


def _np():
    import numpy as np

    return np


def _poison_cache(cache, bt, valid, bs):
    """NaN-poison a f32/bf16 cache: every slot of every unreferenced
    block, and every slot at/past ``valid[r]`` inside row r's blocks.
    (valid = seq_len for decode, prefix start for prefill/ragged.)"""
    np = _np()
    c = np.asarray(cache, np.float32)
    poisoned = np.full_like(c, np.nan)
    for r in range(bt.shape[0]):
        for ti in range(bt.shape[1]):
            bid = int(bt[r, ti])
            keep = max(0, min(bs, int(valid[r]) - ti * bs))
            if keep:
                poisoned[:, bid, :, :keep] = c[:, bid, :, :keep]
    return poisoned


def _poison_scales(scale, bt, valid, hk, bs):
    """Same poison for the quant scale pool [L, N, 2, Hp, Sp]: the pad
    lanes go NaN too — the kernels slice [:hk, :bs] value-level, and
    that slice is what keeps the poison out."""
    np = _np()
    s = np.asarray(scale, np.float32)
    poisoned = np.full_like(s, np.nan)
    for r in range(bt.shape[0]):
        for ti in range(bt.shape[1]):
            bid = int(bt[r, ti])
            keep = max(0, min(bs, int(valid[r]) - ti * bs))
            if keep:
                poisoned[:, bid, :, :hk, :keep] = s[:, bid, :, :hk, :keep]
    return poisoned


def _disjoint_tables(rows: int, m: int, n: int):
    """One disjoint block-id table per row, skipping block 0 so the
    clamp-path reads of padding table slots (which the engine leaves 0)
    hit an unreferenced — poisoned — block if they ever load."""
    np = _np()
    assert rows * m + 1 <= n, (rows, m, n)
    return (1 + np.arange(rows * m, dtype=np.int32)).reshape(rows, m)


# Audit dims shared by the small attention cases: tiny enough for
# interpret mode on CPU inside the tier-1 budget, shaped enough (GQA,
# multi-block tables, two layers) to exercise every index path.
_L, _BS, _HK, _D, _H, _M = 2, 8, 2, 16, 4, 4
_HKD = _HK * _D


def _decode_case(name: str, quant: bool, s_q: int = 1) -> dict:
    import jax.numpy as jnp

    np = _np()
    b = 8 if s_q == 1 else 4
    n = b * _M + 1
    layer = 1
    if s_q == 1:
        # empty row, 1-token row, block-exact, non-divisible, max-table
        lens = np.asarray([1, _M * _BS, 11, 0, _BS, 5, 17, 29], np.int32)
    else:
        # multi-query rows with non-block-aligned first-query positions
        lens = np.asarray([7, _M * _BS, 2, 19], np.int32)

    def build():
        rng = np.random.default_rng(101 if quant else 100)
        cache = jnp.asarray(
            rng.normal(size=(_L, n, 2, _BS, _HKD)), jnp.float32)
        bt = _disjoint_tables(b, _M, n)
        q = jnp.asarray(rng.normal(size=(b, s_q, _H, _D)), jnp.float32)
        kcache = quantize_audit_cache(cache, _HK) if quant else cache
        return {
            "q": q, "cache": kcache, "clean": cache,
            "bt": jnp.asarray(bt), "bt_np": bt, "lens": jnp.asarray(lens),
            "layer": jnp.int32(layer), "q0": jnp.asarray(lens - s_q),
        }

    def run(inp, poisoned: bool):
        from dynamo_tpu.ops.kv_quant import QuantKvCache
        from dynamo_tpu.ops.pallas.decode_attention import (
            paged_decode_attention_mq,
        )

        cache = inp["cache"]
        if poisoned:
            if quant:
                cache = QuantKvCache(cache.data, _np().asarray(
                    _poison_scales(cache.scale, inp["bt_np"], lens,
                                   _HK, _BS)))
            else:
                cache = _np().asarray(
                    _poison_cache(cache, inp["bt_np"], lens, _BS),
                    dtype=_np().float32)
        return paged_decode_attention_mq.__wrapped__(
            inp["q"], cache, inp["layer"], inp["bt"], inp["lens"],
            inp["q0"], blocks_per_chunk=2, seqs_per_group=4,
            interpret=True,
        )

    def oracle(inp):
        import jax

        from dynamo_tpu.ops.kv_quant import dequant_layer_slice
        from dynamo_tpu.ops.paged_attention import paged_attention

        np = _np()
        cache = inp["cache"]
        if quant:
            data = jax.lax.dynamic_index_in_dim(
                cache.data, inp["layer"], axis=0, keepdims=False)
            sc = jax.lax.dynamic_index_in_dim(
                cache.scale, inp["layer"], axis=0, keepdims=False)
            layer_kv = dequant_layer_slice(data, sc, _HK)
        else:
            layer_kv = cache[layer]
        kc = layer_kv[:, 0].reshape(n, _BS, _HK, _D)
        vc = layer_kv[:, 1].reshape(n, _BS, _HK, _D)
        positions = (lens - s_q)[:, None] + np.arange(s_q)[None, :]
        ref = paged_attention(
            inp["q"], kc, vc, inp["bt"],
            inp["lens"], positions.astype(np.int32))
        live = np.broadcast_to(
            (lens >= s_q)[:, None, None, None], ref.shape).copy()
        zero = np.broadcast_to(
            (lens == 0)[:, None, None, None], ref.shape).copy()
        return np.asarray(ref), live, zero

    def pricing():
        return decode_kernel_cost(
            b, s_q, _H, _HK, _D, _BS, _M, lens, cache_bytes=1 if quant
            else 4, quant=quant, blocks_per_chunk=2, seqs_per_group=4)

    return {
        "name": name, "kernel": "paged_decode_attention_mq",
        "mode": "interpret", "atol": 2e-3 if quant else 2e-4,
        "build": build, "run": run, "oracle": oracle, "pricing": pricing,
    }


def _prefill_case(name: str = "prefill-bf16") -> dict:
    import jax.numpy as jnp

    np = _np()
    b, s, layer = 2, 16, 0
    n = b * _M + 1
    starts = np.asarray([8, 0], np.int32)   # 1-block prefix / no prefix
    lens = np.asarray([24, 13], np.int32)   # row 1: 3 padding tail rows

    def build():
        rng = np.random.default_rng(200)
        cache = jnp.asarray(
            rng.normal(size=(_L, n, 2, _BS, _HKD)), jnp.float32)
        bt = _disjoint_tables(b, _M, n)
        q = jnp.asarray(rng.normal(size=(b, s, _H, _D)), jnp.float32)
        k_new = jnp.asarray(rng.normal(size=(b, s, _HK, _D)), jnp.float32)
        v_new = jnp.asarray(rng.normal(size=(b, s, _HK, _D)), jnp.float32)
        return {
            "q": q, "k": k_new, "v": v_new, "cache": cache,
            "bt": jnp.asarray(bt), "bt_np": bt,
            "lens": jnp.asarray(lens), "starts": jnp.asarray(starts),
            "layer": jnp.int32(layer),
        }

    def run(inp, poisoned: bool):
        from dynamo_tpu.ops.pallas.prefill_attention import (
            paged_prefill_attention,
        )

        np = _np()
        q, k, v, cache = inp["q"], inp["k"], inp["v"], inp["cache"]
        if poisoned:
            cache = np.asarray(
                _poison_cache(cache, inp["bt_np"], starts, _BS),
                np.float32)
            fresh = (lens - starts)
            qp, kp, vp = (np.asarray(x, np.float32).copy()
                          for x in (q, k, v))
            for r in range(b):
                qp[r, fresh[r]:] = np.nan
                kp[r, fresh[r]:] = np.nan
                vp[r, fresh[r]:] = np.nan
            q, k, v = qp, kp, vp
        return paged_prefill_attention.__wrapped__(
            q, k, v, cache, inp["layer"], inp["bt"], inp["lens"],
            inp["starts"], rows_per_chunk=8, blocks_per_chunk=2,
            interpret=True,
        )

    def oracle(inp):
        import os

        from dynamo_tpu.ops.paged_attention import prefill_attention

        np = _np()
        os.environ["DYNAMO_DISABLE_PALLAS_PREFILL"] = "1"
        try:
            ref = prefill_attention(
                inp["q"], inp["k"], inp["v"], inp["cache"], inp["layer"],
                inp["bt"], inp["lens"], inp["starts"], prefix_blocks=1)
        finally:
            os.environ.pop("DYNAMO_DISABLE_PALLAS_PREFILL", None)
        fresh = lens - starts
        idx = np.arange(s)
        live = np.broadcast_to(
            (idx[None, :] < fresh[:, None])[:, :, None, None],
            ref.shape).copy()
        # padding rows are finite garbage the caller discards (they
        # still see the causal columns) — no zero claim
        return np.asarray(ref), live, np.zeros_like(live)

    def pricing():
        return prefill_kernel_cost(
            b, s, _H, _HK, _D, _BS, _M, starts, cache_bytes=4,
            q_bytes=4, rows_per_chunk=8, blocks_per_chunk=2)

    return {
        "name": name, "kernel": "paged_prefill_attention",
        "mode": "interpret", "atol": 2e-4,
        "build": build, "run": run, "oracle": oracle, "pricing": pricing,
    }


# The adversarial ragged row set (ISSUE matrix): empty row, 1-token
# decode row with a non-block-aligned start, non-block-divisible chunk,
# max-block row at the full table context.
_RAGGED_ROWS = (
    # (start, fresh)
    (8, 0),    # empty row: zero fresh tokens, span [x, x)
    (11, 1),   # decode row: 1 token, start NOT block-aligned
    (8, 13),   # non-block-divisible chunk length
    (24, 8),   # max-block row: full M*Bs context
)


def _ragged_geometry(rows, tq: int = 8):
    np = _np()
    starts = np.asarray([r[0] for r in rows], np.int32)
    fresh = np.asarray([r[1] for r in rows], np.int32)
    lens = starts + fresh
    roffs = np.concatenate([[0], np.cumsum(fresh)[:-1]]).astype(np.int32)
    total = int(fresh.sum())
    t_tokens = max(tq, _cdiv(total, tq) * tq)
    sid = np.full(t_tokens, -1, np.int32)
    for r in range(len(rows)):
        sid[roffs[r]:roffs[r] + fresh[r]] = r
    return starts, fresh, lens, roffs, sid, t_tokens


def _ragged_case(name: str, quant: bool, rows=_RAGGED_ROWS,
                 seed: int = 300, tq: int = 8) -> dict:
    import jax.numpy as jnp

    np = _np()
    r_rows = len(rows)
    starts, fresh, lens, roffs, sid, t_tokens = _ragged_geometry(rows, tq)
    n = r_rows * _M + 1
    layer = 1
    prefix_blocks = int(_cdiv(int(starts.max()), _BS)) if len(rows) else 0

    def build():
        rng = np.random.default_rng(seed + (1 if quant else 0))
        cache = jnp.asarray(
            rng.normal(size=(_L, n, 2, _BS, _HKD)), jnp.float32)
        bt = _disjoint_tables(r_rows, _M, n)
        q = jnp.asarray(
            rng.normal(size=(1, t_tokens, _H, _D)), jnp.float32)
        k_new = jnp.asarray(
            rng.normal(size=(1, t_tokens, _HK, _D)), jnp.float32)
        v_new = jnp.asarray(
            rng.normal(size=(1, t_tokens, _HK, _D)), jnp.float32)
        kcache = quantize_audit_cache(cache, _HK) if quant else cache
        return {
            "q": q, "k": k_new, "v": v_new, "cache": kcache,
            "clean": cache, "bt": jnp.asarray(bt), "bt_np": bt,
            "lens": jnp.asarray(lens), "starts": jnp.asarray(starts),
            "roffs": jnp.asarray(roffs),
            "sid": jnp.asarray(sid[None, :]), "layer": jnp.int32(layer),
        }

    def run(inp, poisoned: bool):
        from dynamo_tpu.ops.kv_quant import QuantKvCache
        from dynamo_tpu.ops.pallas.prefill_attention import (
            ragged_paged_prefill_attention,
        )

        np = _np()
        q, k, v, cache = inp["q"], inp["k"], inp["v"], inp["cache"]
        if poisoned:
            if quant:
                cache = QuantKvCache(cache.data, np.asarray(
                    _poison_scales(cache.scale, inp["bt_np"], starts,
                                   _HK, _BS)))
            else:
                cache = np.asarray(
                    _poison_cache(cache, inp["bt_np"], starts, _BS),
                    np.float32)
            qp, kp, vp = (np.asarray(x, np.float32).copy()
                          for x in (q, k, v))
            pad = sid < 0
            qp[0, pad] = np.nan
            kp[0, pad] = np.nan
            vp[0, pad] = np.nan
            q, k, v = qp, kp, vp
        return ragged_paged_prefill_attention.__wrapped__(
            q, k, v, cache, inp["layer"], inp["bt"], inp["lens"],
            inp["starts"], inp["roffs"], rows_per_chunk=tq,
            blocks_per_chunk=2, interpret=True,
        )

    def oracle(inp):
        import os

        from dynamo_tpu.ops.paged_attention import ragged_prefill_attention

        np = _np()
        os.environ["DYNAMO_DISABLE_PALLAS_PREFILL"] = "1"
        try:
            ref = ragged_prefill_attention(
                inp["q"], inp["k"], inp["v"], inp["cache"], inp["layer"],
                inp["bt"], inp["lens"], inp["starts"], inp["roffs"],
                inp["sid"], prefix_blocks)
        finally:
            os.environ.pop("DYNAMO_DISABLE_PALLAS_PREFILL", None)
        live = np.broadcast_to(
            (sid >= 0)[None, :, None, None], ref.shape).copy()
        return np.asarray(ref), live, np.zeros_like(live)

    def pricing():
        return ragged_kernel_cost(
            t_tokens, _H, _HK, _D, _BS, _M, starts,
            cache_bytes=1 if quant else 4, quant=quant, q_bytes=4,
            rows_per_chunk=tq, blocks_per_chunk=2)

    return {
        "name": name, "kernel": "ragged_paged_prefill_attention",
        "mode": "interpret", "atol": 2e-3 if quant else 2e-4,
        "build": build, "run": run, "oracle": oracle, "pricing": pricing,
    }


def _int8_matmul_case() -> dict:
    import jax.numpy as jnp

    np = _np()
    m, k, n = 256, 1024, 1024  # grid (2, 2, 2): revisits the K axis

    def build():
        rng = np.random.default_rng(400)
        x = jnp.asarray(rng.normal(size=(m, k)), jnp.bfloat16)
        wq = jnp.asarray(
            rng.integers(-127, 128, size=(k, n)), jnp.int8)
        scale = jnp.asarray(
            rng.uniform(0.01, 0.1, size=(n,)), jnp.float32)
        return {"x": x, "wq": wq, "scale": scale}

    def run(inp, poisoned: bool):
        from dynamo_tpu.ops.pallas.int8_matmul import int8_matmul

        return int8_matmul.__wrapped__(
            inp["x"], inp["wq"], inp["scale"], interpret=True)

    def oracle(inp):
        np = _np()
        x = np.asarray(inp["x"], np.float32)
        w = np.asarray(inp["wq"], np.float32)
        sc = np.asarray(inp["scale"], np.float32)
        ref = (x @ w) * sc[None, :]
        live = np.ones(ref.shape, bool)
        return ref, live, np.zeros_like(live)

    def pricing():
        return int8_matmul_cost(m, k, n)

    return {
        "name": "int8-matmul", "kernel": "int8_matmul",
        # bf16 x + K=1024 reduction: ~1.5% relative on O(100) outputs
        "mode": "interpret", "atol": 8.0,
        "build": build, "run": run, "oracle": oracle, "pricing": pricing,
    }


# ---------------------------------------------- serving-scale (spec) ----


def _spec_decode_8b() -> dict:
    """8B-serving decode shape, shape-traced only: VMEM budget and
    pricing at the geometry that matters, without executing."""
    b, h, hk, d, bs, n, m, L = 64, 32, 8, 128, 16, 4096, 128, 32

    def build():
        import jax

        import jax.numpy as jnp

        f = jax.ShapeDtypeStruct
        return {
            "q": f((b, 1, h, d), jnp.bfloat16),
            "cache": f((L, n, 2, bs, hk * d), jnp.bfloat16),
            "layer": f((), jnp.int32),
            "bt": f((b, m), jnp.int32),
            "lens": f((b,), jnp.int32),
            "q0": f((b,), jnp.int32),
        }

    def run(inp, poisoned: bool):
        import jax

        from dynamo_tpu.ops.pallas.decode_attention import (
            paged_decode_attention_mq,
        )

        fn = functools.partial(
            paged_decode_attention_mq.__wrapped__, interpret=False)
        return jax.eval_shape(
            fn, inp["q"], inp["cache"], inp["layer"], inp["bt"],
            inp["lens"], inp["q0"])

    def pricing():
        return decode_kernel_cost(
            b, 1, h, hk, d, bs, m, [m * bs] * b, cache_bytes=2,
            q_bytes=2)

    return {
        "name": "decode-8b", "kernel": "paged_decode_attention_mq",
        "mode": "spec", "build": build, "run": run, "oracle": None,
        "pricing": pricing,
    }


def _spec_prefill_8b() -> dict:
    """S=2048 prefill at the documented serving tile (Hk*D=512), shape
    traced: this is the case the rows_per_chunk=128 VMEM claim is
    machine-checked against."""
    b, s, h, hk, d, bs, n, m, L = 1, 2048, 32, 4, 128, 16, 4096, 128, 32

    def build():
        import jax

        import jax.numpy as jnp

        f = jax.ShapeDtypeStruct
        return {
            "q": f((b, s, h, d), jnp.bfloat16),
            "k": f((b, s, hk, d), jnp.bfloat16),
            "v": f((b, s, hk, d), jnp.bfloat16),
            "cache": f((L, n, 2, bs, hk * d), jnp.bfloat16),
            "layer": f((), jnp.int32),
            "bt": f((b, m), jnp.int32),
            "lens": f((b,), jnp.int32),
            "starts": f((b,), jnp.int32),
        }

    def run(inp, poisoned: bool):
        import jax

        from dynamo_tpu.ops.pallas.prefill_attention import (
            paged_prefill_attention,
        )

        fn = functools.partial(
            paged_prefill_attention.__wrapped__, interpret=False)
        return jax.eval_shape(
            fn, inp["q"], inp["k"], inp["v"], inp["cache"], inp["layer"],
            inp["bt"], inp["lens"], inp["starts"])

    def pricing():
        return prefill_kernel_cost(
            b, s, h, hk, d, bs, m, [m * bs] * b, cache_bytes=2,
            q_bytes=2)

    return {
        "name": "prefill-8b", "kernel": "paged_prefill_attention",
        "mode": "spec", "build": build, "run": run, "oracle": None,
        "pricing": pricing,
    }


def audit_cases() -> list[dict]:
    """The committed audit matrix: every non-placeholder kernel x its
    geometry cases.  Interpret cases run the NaN-canary differential on
    CPU; spec cases shape-trace only (VMEM + pricing)."""
    return [
        _decode_case("decode-bf16", quant=False),
        _decode_case("decode-int8", quant=True),
        _decode_case("decode-mq-unaligned", quant=False, s_q=2),
        _prefill_case(),
        _ragged_case("ragged-bf16", quant=False),
        _ragged_case("ragged-int8", quant=True),
        _int8_matmul_case(),
        _spec_decode_8b(),
        _spec_prefill_8b(),
    ]


def fuzz_case(seed: int) -> dict:
    """One seeded random ragged geometry for the nightly kern-fuzz
    sweep: rows drawn from the adversarial families (empty / 1-token
    decode / odd-length chunk / max-block), canary-checked against the
    oracle.  Deterministic per seed — the replay token is just the
    seed."""
    np = _np()
    rng = np.random.default_rng(seed)
    r_rows = int(rng.integers(2, 6))
    rows = []
    for _ in range(r_rows):
        kind = int(rng.integers(0, 4))
        if kind == 0:    # empty row
            rows.append((int(rng.integers(0, _M * _BS)), 0))
        elif kind == 1:  # decode row, any (non-aligned) start
            rows.append((int(rng.integers(0, _M * _BS - 1)), 1))
        elif kind == 2:  # odd-length chunk from a block-aligned start
            start = int(rng.integers(0, _M - 1)) * _BS
            fresh = int(rng.integers(1, _M * _BS - start + 1))
            rows.append((start, fresh))
        else:            # max-block row
            start = int(rng.integers(0, _M)) * _BS
            rows.append((start, _M * _BS - start))
    if all(f == 0 for _, f in rows):
        rows[0] = (0, 1)  # at least one real token so T > 0
    return _ragged_case(
        f"fuzz[ragged-{seed}]", quant=bool(rng.integers(0, 2)),
        rows=tuple(rows), seed=seed, tq=8)


# ------------------------------------------------------ probe builders ----
# bench.py and benchmarks/probe_kernels.py build their kernel probes
# from these, so probe coverage is registry coverage by construction.


def _probe_cache(rng, n, bs, hk, hd, dtype, quant):
    import jax.numpy as jnp

    cache = jnp.asarray(
        rng.normal(size=(1, n, 2, bs, hk * hd)), dtype)
    return quantize_audit_cache(cache, hk) if quant else cache


def probe_decode_inputs(batch, h, hk, hd, bs, n, bt_width, lens,
                        dtype=None, quant=False, s_q=0):
    """Concrete decode-probe inputs at serving dims (bench.py's on-TPU
    lowering probe and probe_kernels.py's sweep share this).  With
    ``s_q > 0`` the multi-query shape is built instead: q gains a
    per-row query axis and a sixth element — the context lengths
    (``seq_lens - s_q``) the mq kernel takes — joins the tuple."""
    import jax.numpy as jnp

    np = _np()
    dtype = dtype or jnp.bfloat16
    rng = np.random.default_rng(0)
    qshape = (batch, s_q, h, hd) if s_q else (batch, h, hd)
    q = jnp.asarray(rng.normal(size=qshape), dtype)
    cache = _probe_cache(rng, n, bs, hk, hd, dtype, quant)
    bt = _probe_bt(batch, bt_width, n)
    lens = jnp.asarray(lens, jnp.int32)
    if s_q:
        return q, cache, jnp.int32(0), bt, lens, \
            jnp.maximum(lens - s_q, 0)
    return q, cache, jnp.int32(0), bt, lens


def probe_prefill_inputs(batch, s, h, hk, hd, bs, n, bt_width,
                         dtype=None, quant=False):
    import jax.numpy as jnp

    np = _np()
    dtype = dtype or jnp.bfloat16
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(batch, s, h, hd)), dtype)
    k = jnp.asarray(rng.normal(size=(batch, s, hk, hd)), dtype)
    v = jnp.asarray(rng.normal(size=(batch, s, hk, hd)), dtype)
    cache = _probe_cache(rng, n, bs, hk, hd, dtype, quant)
    # one cached block of prefix, clamped so prefix+fresh still fits
    # the block table (s == bt_width * bs means no prefix room)
    total = min(bs + s, bt_width * bs)
    lens = jnp.full((batch,), total, jnp.int32)
    starts = jnp.full((batch,), total - s, jnp.int32)
    return q, k, v, cache, jnp.int32(0), _probe_bt(batch, bt_width, n), \
        lens, starts


def _probe_bt(rows, bt_width, n):
    import jax.numpy as jnp

    np = _np()
    return jnp.asarray(
        np.arange(rows * bt_width).reshape(rows, bt_width) % n,
        jnp.int32)


def probe_ragged_inputs(t_tokens, r_rows, h, hk, hd, bs, n, bt_width,
                        dtype=None, quant=False):
    import jax.numpy as jnp

    np = _np()
    dtype = dtype or jnp.bfloat16
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(1, t_tokens, h, hd)), dtype)
    k = jnp.asarray(rng.normal(size=(1, t_tokens, hk, hd)), dtype)
    v = jnp.asarray(rng.normal(size=(1, t_tokens, hk, hd)), dtype)
    cache = _probe_cache(rng, n, bs, hk, hd, dtype, quant)
    bt = _probe_bt(r_rows, bt_width, n)
    per = t_tokens // r_rows
    roffs = jnp.asarray(
        np.arange(r_rows, dtype=np.int32) * per, jnp.int32)
    # one cached block of prefix per row, clamped into the block table
    start = max(0, min(bs, bt_width * bs - per))
    starts = jnp.full((r_rows,), start, jnp.int32)
    lens = starts + per
    return q, k, v, cache, jnp.int32(0), bt, lens, starts, roffs


def probe_int8_matmul_inputs(m, k, n):
    import jax.numpy as jnp

    np = _np()
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(m, k)), jnp.bfloat16)
    wq = jnp.asarray(rng.integers(-127, 128, size=(k, n)), jnp.int8)
    scale = jnp.asarray(rng.uniform(0.01, 0.1, size=(n,)), jnp.float32)
    return x, wq, scale


_PROBE_BUILDERS = {
    "paged_decode_attention_mq": probe_decode_inputs,
    "paged_prefill_attention": probe_prefill_inputs,
    "ragged_paged_prefill_attention": probe_ragged_inputs,
    "int8_matmul": probe_int8_matmul_inputs,
}
