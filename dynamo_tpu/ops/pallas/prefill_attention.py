"""Flash prefill over the paged cache — the TTFT hot kernel.

The pure-JAX prefill path materialises the full [Hk, G, S, S+P] f32 score
tensor per layer (537MB at S=2048 on a 1B model) and round-trips it
through HBM for the softmax.  This kernel runs the classic flash pattern
instead: the query rows stream in TQ-sized chunks, keys/values arrive as
(a) the chunk's own fresh K/V resident in VMEM and (b) the cached-prefix
blocks double-buffer-DMA'd straight from the paged cache in HBM (same
machinery as the decode kernel), with online-softmax accumulation — scores
never touch HBM.

Semantics match ops.paged_attention.prefill_attention:
  * queries are S contiguous tokens starting at block-aligned ``start[b]``,
  * fresh-fresh attention is causal by chunk index,
  * fresh-prefix attention is full over slots [0, start),
  * query padding rows (index >= seq_len - start) yield 0.

Grid: (B, S/TQ).  GQA is handled per kv-head: q rows fold the G query
heads into the row axis ([TQ, G*D] -> [TQ*G, D]), so scores and PV are
plain MXU matmuls.  SURVEY.md §7 hard part 3; VERDICT r2 ask #4.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dynamo_tpu.ops.paged_attention import softcap
from dynamo_tpu.ops.pallas.registry import (
    PREFILL_BLOCKS_PER_CHUNK,
    PREFILL_ROWS_PER_CHUNK,
    prefill_cost_estimate,
    ragged_cost_estimate,
)

__all__ = ["paged_prefill_attention", "ragged_paged_prefill_attention"]

NEG_INF = -1e30


def _kernel(
    seq_ref, start_ref, bt_ref, layer_ref, q_ref, k_ref, v_ref, cache_ref,
    out_ref, acc_ref, m_ref, l_ref, kvbuf, sems,
    *, c: int, tq: int, hk: int, g: int, d: int, sm_scale: float,
    logit_cap=None,
):
    return _kernel_impl(seq_ref, start_ref, bt_ref, layer_ref, q_ref, k_ref,
                        v_ref, cache_ref, None, out_ref, acc_ref, m_ref,
                        l_ref, kvbuf, sems, None, None, c=c, tq=tq, hk=hk,
                        g=g, d=d, sm_scale=sm_scale, logit_cap=logit_cap)


def _kernel_quant(
    seq_ref, start_ref, bt_ref, layer_ref, q_ref, k_ref, v_ref, cache_ref,
    scale_ref, out_ref, acc_ref, m_ref, l_ref, kvbuf, sems, scbuf, scsems,
    *, c: int, tq: int, hk: int, g: int, d: int, sm_scale: float,
    logit_cap=None,
):
    return _kernel_impl(seq_ref, start_ref, bt_ref, layer_ref, q_ref, k_ref,
                        v_ref, cache_ref, scale_ref, out_ref, acc_ref, m_ref,
                        l_ref, kvbuf, sems, scbuf, scsems, c=c, tq=tq, hk=hk,
                        g=g, d=d, sm_scale=sm_scale, logit_cap=logit_cap)


def _kernel_impl(
    # scalar prefetch (SMEM)
    seq_ref,     # [B] int32 — context length incl. fresh tokens
    start_ref,   # [B] int32 — absolute position of q[:, 0]
    bt_ref,      # [B, M] int32
    layer_ref,   # [1] int32
    # inputs
    q_ref,       # [1, Hk, TQ, G*D] VMEM — this grid step's query rows.
    #              The kv-head axis LEADS (outside the tiled minor-2 dims):
    #              per-head reads are then plain leading-index loads —
    #              `[1, TQ, Hk, G*D]` with h in the sublane slot made
    #              Mosaic reject the kernel (sublane slices of extent 1
    #              aren't tile-aligned).
    k_ref,       # [1, S, Hk*D] VMEM — whole fresh K (chunk-resident)
    v_ref,       # [1, S, Hk*D] VMEM
    cache_ref,   # [L, N, 2, Bs, Hk*D] HBM (manual DMA)
    scale_ref,   # [L, N, 2, Hp, Sp] HBM f32 (tile-padded), or None (bf16)
    # outputs
    out_ref,     # [1, Hk, TQ, G*D] VMEM (head-leading, as q_ref)
    # scratch
    acc_ref,     # [Hk, TQ*G, D] f32
    m_ref,       # [Hk, TQ*G, 128] f32
    l_ref,       # [Hk, TQ*G, 128] f32
    kvbuf,       # [2, C, 2, Bs, Hk*D] cache-dtype (double buffer)
    sems,        # [2, C] DMA semaphores
    scbuf,       # [2, C, 2, Hp, Sp] f32, or None
    scsems,      # [2, C] DMA semaphores, or None
    *,
    c: int,
    tq: int,
    hk: int,
    g: int,
    d: int,
    sm_scale: float,
    logit_cap=None,
):
    quant = scale_ref is not None
    bi = pl.program_id(0)
    ri = pl.program_id(1)
    bs = kvbuf.shape[3]
    t = c * bs
    lyr = layer_ref[0]
    prefix = start_ref[bi]                  # cached-prefix token count
    fresh = seq_ref[bi] - prefix            # valid fresh tokens
    n_pref = pl.cdiv(prefix, t)             # data-dependent chunk bound

    acc_ref[:] = jnp.zeros_like(acc_ref)
    m_ref[:] = jnp.full_like(m_ref, NEG_INF)
    l_ref[:] = jnp.zeros_like(l_ref)

    rows = jax.lax.broadcasted_iota(jnp.int32, (tq * g, 1), 0) // g  # query row

    def flash_update(h, s_scores, v_cols, p_scale=None):
        """Online-softmax fold of one [TQ*G, TKV] score tile (masked).
        ``p_scale`` [1, TKV] rescales P before the PV product (int8 V
        dequant folded per column; softmax stats use the true probs)."""
        m_prev = m_ref[h, :, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s_scores, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s_scores - m_new)
        l_ref[h] = l_ref[h] * alpha + jnp.sum(p, axis=1, keepdims=True)
        m_ref[h] = jnp.broadcast_to(m_new, m_ref.shape[1:])
        pv = jnp.dot(p if p_scale is None else p * p_scale, v_cols,
                     preferred_element_type=jnp.float32)
        acc_ref[h] = acc_ref[h] * alpha + pv

    def q_head(h):
        # [TQ, G*D] -> [TQ*G, D], pre-scaled f32
        return q_ref[0, h].reshape(tq * g, d).astype(jnp.float32) * sm_scale

    # ---------------------------------------------------- prefix phase (DMA)
    def block_dmas(ci, slot):
        m_table = bt_ref.shape[1]
        out = []
        for i in range(c):  # static unroll: C block copies per chunk
            bid = bt_ref[bi, jnp.minimum(ci * c + i, m_table - 1)]
            out.append(pltpu.make_async_copy(
                cache_ref.at[lyr, bid], kvbuf.at[slot, i], sems.at[slot, i]
            ))
            if quant:  # the block's scale tile rides a second small DMA
                out.append(pltpu.make_async_copy(
                    scale_ref.at[lyr, bid], scbuf.at[slot, i],
                    scsems.at[slot, i]
                ))
        return out

    @pl.when(n_pref > 0)
    def _prologue():
        for dma in block_dmas(0, 0):
            dma.start()

    def pref_body(ci, _):
        slot = jax.lax.rem(ci, 2)

        @pl.when(ci + 1 < n_pref)
        def _prefetch():
            for dma in block_dmas(ci + 1, jax.lax.rem(ci + 1, 2)):
                dma.start()

        for dma in block_dmas(ci, slot):
            dma.wait()

        kc = kvbuf[slot, :, 0].reshape(t, hk * d).astype(jnp.float32)
        vc = kvbuf[slot, :, 1].reshape(t, hk * d).astype(jnp.float32)
        if quant:
            # padded [Hp, Sp] tiles -> valid [Hk, Bs] -> [Hk, T] by lane
            # concat (token-minor scale layout exists exactly for this —
            # no transpose; the slice is value-level in VMEM)
            sck = jnp.concatenate(
                [scbuf[slot, i, 0][:hk, :bs] for i in range(c)], axis=-1)
            scv = jnp.concatenate(
                [scbuf[slot, i, 1][:hk, :bs] for i in range(c)], axis=-1)
        col = ci * t + jax.lax.broadcasted_iota(jnp.int32, (1, t), 1)
        allow = col < prefix                              # [1, T]
        # dead prefix slots (past `prefix` in the tail block) may hold
        # non-finite pool garbage; the score mask zeroes their P columns
        # but 0 * NaN-V survives the PV product — zero V rows (and the V
        # scales) for them outright
        vmask = ci * t + jax.lax.broadcasted_iota(
            jnp.int32, (t, 1), 0) < prefix
        vc = jnp.where(vmask, vc, 0.0)
        if quant:
            scv = jnp.where(allow, scv, 0.0)
        for h in range(hk):  # static unroll over kv heads
            s_ = jax.lax.dot_general(
                q_head(h), kc[:, h * d:(h + 1) * d],
                (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
            )  # [TQ*G, T]
            if quant:
                # K's per-token scale multiplies score columns; V's folds
                # into P inside flash_update's PV product via p_scale
                s_ = s_ * sck[h:h + 1, :]
            if logit_cap is not None:  # Gemma2 attention softcap
                s_ = softcap(s_, logit_cap)
            s_ = jnp.where(allow, s_, NEG_INF)
            flash_update(h, s_, vc[:, h * d:(h + 1) * d],
                         p_scale=scv[h:h + 1, :] if quant else None)
        return 0

    jax.lax.fori_loop(0, n_pref, pref_body, 0)

    # ------------------------------------------------- fresh phase (causal)
    def fresh_body(cj, _):
        col0 = cj * tq
        kc = k_ref[0, pl.ds(col0, tq)].astype(jnp.float32)   # [TQ, Hk*D]
        vc = v_ref[0, pl.ds(col0, tq)].astype(jnp.float32)
        col = col0 + jax.lax.broadcasted_iota(jnp.int32, (1, tq), 1)
        # causal by fresh index + clip padding columns
        allow = (col <= ri * tq + rows) & (col < fresh)      # [TQ*G, TQ]
        # fresh padding tokens may be non-finite — zero their V rows
        vc = jnp.where(col0 + jax.lax.broadcasted_iota(
            jnp.int32, (tq, 1), 0) < fresh, vc, 0.0)
        for h in range(hk):
            s_ = jax.lax.dot_general(
                q_head(h), kc[:, h * d:(h + 1) * d],
                (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
            )
            if logit_cap is not None:
                s_ = softcap(s_, logit_cap)
            s_ = jnp.where(allow, s_, NEG_INF)
            flash_update(h, s_, vc[:, h * d:(h + 1) * d])
        return 0

    jax.lax.fori_loop(0, ri + 1, fresh_body, 0)

    for h in range(hk):
        denom = jnp.maximum(l_ref[h, :, :1], 1e-9)  # padding rows → 0
        out_ref[0, h] = (
            (acc_ref[h] / denom).reshape(tq, g * d).astype(out_ref.dtype)
        )


@functools.partial(
    jax.jit,
    static_argnames=("sm_scale", "logit_cap", "rows_per_chunk",
                     "blocks_per_chunk", "interpret"),
)
def paged_prefill_attention(
    q: jax.Array,             # [B, S, H, D]
    k_new: jax.Array,         # [B, S, Hk, D] — fresh keys (pre-RoPE'd)
    v_new: jax.Array,         # [B, S, Hk, D]
    cache: jax.Array,         # [L, N, 2, Bs, Hk*D]
    layer: jax.Array,         # scalar int32
    block_tables: jax.Array,  # [B, M] int32 (prefix blocks lead the table)
    seq_lens: jax.Array,      # [B] int32
    start: jax.Array,         # [B] int32 — block-aligned chunk start
    sm_scale: float | None = None,
    logit_cap: float | None = None,
    # 128 rows/chunk keeps scratch (acc + m/l at 128-lane padding) + the
    # VMEM-resident fresh K/V well inside the per-core VMEM budget at
    # S=2048, Hk*D=512 — machine-checked by kerncheck's `prefill-8b`
    # geometry (KN001) against registry.VMEM_BUDGET_BYTES
    rows_per_chunk: int = PREFILL_ROWS_PER_CHUNK,
    blocks_per_chunk: int = PREFILL_BLOCKS_PER_CHUNK,
    interpret: bool = False,
) -> jax.Array:
    """Flash prefill for S fresh tokens against fresh K/V + cached prefix.
    Returns [B, S, H, D]."""
    from dynamo_tpu.ops.kv_quant import is_quant

    quant = is_quant(cache)
    data, scale = (cache.data, cache.scale) if quant else (cache, None)
    b, s, h, d = q.shape
    l, n, _, bs, hkd = data.shape
    hk = hkd // d
    g = h // hk
    m = block_tables.shape[1]
    if sm_scale is None:
        sm_scale = 1.0 / (d**0.5)
    tq = min(rows_per_chunk, s)
    while s % tq:
        tq //= 2
    c = min(blocks_per_chunk, m)

    # head-leading query layout (see kernel docstring): [B, Hk, S, G*D]
    q_in = q.reshape(b, s, hk, g * d).transpose(0, 2, 1, 3)
    k_in = k_new.reshape(b, s, hkd)
    v_in = v_new.reshape(b, s, hkd)

    in_specs = [
        pl.BlockSpec((1, hk, tq, g * d), lambda bi, ri, *_: (bi, 0, ri, 0)),
        pl.BlockSpec((1, s, hkd), lambda bi, ri, *_: (bi, 0, 0)),
        pl.BlockSpec((1, s, hkd), lambda bi, ri, *_: (bi, 0, 0)),
        pl.BlockSpec(memory_space=pl.ANY),  # cache stays in HBM
    ]
    scratch = [
        pltpu.VMEM((hk, tq * g, d), jnp.float32),
        pltpu.VMEM((hk, tq * g, 128), jnp.float32),
        pltpu.VMEM((hk, tq * g, 128), jnp.float32),
        pltpu.VMEM((2, c, 2, bs, hkd), data.dtype),
        pltpu.SemaphoreType.DMA((2, c)),
    ]
    operands = [
        seq_lens.astype(jnp.int32),
        start.astype(jnp.int32),
        block_tables.astype(jnp.int32),
        jnp.asarray(layer, jnp.int32).reshape(1),
        q_in,
        k_in,
        v_in,
        data,
    ]
    if quant:
        hp, sp = scale.shape[-2:]  # tile-padded (scale_tile(hk, bs))
        in_specs.append(pl.BlockSpec(memory_space=pl.ANY))
        scratch += [
            pltpu.VMEM((2, c, 2, hp, sp), jnp.float32),
            pltpu.SemaphoreType.DMA((2, c)),
        ]
        operands.append(scale)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(b, s // tq),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, hk, tq, g * d), lambda bi, ri, *_: (bi, 0, ri, 0)
        ),
        scratch_shapes=scratch,
    )

    # Honest scheduling hint at the static worst case (full-table
    # prefixes) — seq_lens/start are dynamic.  None on older jax.
    cost = prefill_cost_estimate(
        b, s, h, hk, d, bs, m, cache_bytes=data.dtype.itemsize,
        quant=quant, rows_per_chunk=rows_per_chunk,
        blocks_per_chunk=blocks_per_chunk)
    cost_kw = {} if cost is None else {"cost_estimate": cost}

    out = pl.pallas_call(
        functools.partial(
            _kernel_quant if quant else _kernel,
            c=c, tq=tq, hk=hk, g=g, d=d, sm_scale=float(sm_scale),
            logit_cap=logit_cap,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hk, s, g * d), q.dtype),
        interpret=interpret,
        **cost_kw,
    )(*operands)
    # [B, Hk, S, G*D] -> [B, S, H, D]
    return out.transpose(0, 2, 1, 3).reshape(b, s, h, d)


# --------------------------------------------------------- ragged prefill
# Token-budget batched attention over ONE flat token axis holding several
# sequences' chunks.  A row may be a prefill chunk (a contiguous
# block-aligned span) or — in the engine's unified mixed dispatch — a
# DECODE row: one fresh token whose `start` (= context − 1) is NOT
# block-aligned; the per-row prefix DMA streams ceil(start / (C·Bs))
# chunks and the `col < prefix` mask is positionally exact, so the
# partially-filled tail block contributes exactly its resident slots.
# The grid
# walks flat query tiles; a tile may straddle sequences, so row membership
# is derived in-kernel from the span table (row_offsets/row_ends in SMEM)
# instead of a seq_ids vector — 1-D vector gathers are hostile on TPU,
# span comparisons against a 2-D iota are free.  Fresh-fresh attention is
# causal by flat index within a span (flat order == position order); the
# cached prefix streams per ROW: the row loop DMAs each overlapping row's
# own prefix blocks, masked to that row's queries.


def _ragged_kernel(
    start_ref, roff_ref, rend_ref, bt_ref, layer_ref, q_ref, k_ref, v_ref,
    cache_ref, out_ref, acc_ref, m_ref, l_ref, kvbuf, sems,
    *, c: int, tq: int, hk: int, g: int, d: int, r_rows: int,
    sm_scale: float, logit_cap=None,
):
    return _ragged_kernel_impl(
        start_ref, roff_ref, rend_ref, bt_ref, layer_ref, q_ref, k_ref,
        v_ref, cache_ref, None, out_ref, acc_ref, m_ref, l_ref, kvbuf,
        sems, None, None, c=c, tq=tq, hk=hk, g=g, d=d, r_rows=r_rows,
        sm_scale=sm_scale, logit_cap=logit_cap)


def _ragged_kernel_quant(
    start_ref, roff_ref, rend_ref, bt_ref, layer_ref, q_ref, k_ref, v_ref,
    cache_ref, scale_ref, out_ref, acc_ref, m_ref, l_ref, kvbuf, sems,
    scbuf, scsems,
    *, c: int, tq: int, hk: int, g: int, d: int, r_rows: int,
    sm_scale: float, logit_cap=None,
):
    return _ragged_kernel_impl(
        start_ref, roff_ref, rend_ref, bt_ref, layer_ref, q_ref, k_ref,
        v_ref, cache_ref, scale_ref, out_ref, acc_ref, m_ref, l_ref,
        kvbuf, sems, scbuf, scsems, c=c, tq=tq, hk=hk, g=g, d=d,
        r_rows=r_rows, sm_scale=sm_scale, logit_cap=logit_cap)


def _ragged_kernel_impl(
    # scalar prefetch (SMEM)
    start_ref,   # [R] int32 — absolute chunk start per row (prefix length)
    roff_ref,    # [R] int32 — flat index of the row's first token
    rend_ref,    # [R] int32 — flat index one past the row's last REAL token
    bt_ref,      # [R, M] int32
    layer_ref,   # [1] int32
    # inputs
    q_ref,       # [1, Hk, TQ, G*D] VMEM — this grid step's query rows
    k_ref,       # [1, T, Hk*D] VMEM — whole packed fresh K
    v_ref,       # [1, T, Hk*D] VMEM
    cache_ref,   # [L, N, 2, Bs, Hk*D] HBM (manual DMA)
    scale_ref,   # [L, N, 2, Hp, Sp] HBM f32, or None (bf16 cache)
    # outputs
    out_ref,     # [1, Hk, TQ, G*D] VMEM
    # scratch
    acc_ref,     # [Hk, TQ*G, D] f32
    m_ref,       # [Hk, TQ*G, 128] f32
    l_ref,       # [Hk, TQ*G, 128] f32
    kvbuf,       # [2, C, 2, Bs, Hk*D] cache-dtype (double buffer)
    sems,        # [2, C] DMA semaphores
    scbuf,       # [2, C, 2, Hp, Sp] f32, or None
    scsems,      # [2, C] DMA semaphores, or None
    *,
    c: int,
    tq: int,
    hk: int,
    g: int,
    d: int,
    r_rows: int,
    sm_scale: float,
    logit_cap=None,
):
    quant = scale_ref is not None
    ri = pl.program_id(0)
    bs = kvbuf.shape[3]
    t_chunk = c * bs
    lyr = layer_ref[0]
    q0 = ri * tq

    acc_ref[:] = jnp.zeros_like(acc_ref)
    m_ref[:] = jnp.full_like(m_ref, NEG_INF)
    l_ref[:] = jnp.zeros_like(l_ref)

    rows = jax.lax.broadcasted_iota(jnp.int32, (tq * g, 1), 0) // g
    qflat = q0 + rows                      # [TQ*G, 1] flat query index

    def sid_at(x):
        """Row id per flat index in ``x`` (-1 = padding), from the span
        table — spans are disjoint, so the last matching row wins."""
        def body(r, acc):
            hit = (x >= roff_ref[r]) & (x < rend_ref[r])
            return jnp.where(hit, r, acc)
        return jax.lax.fori_loop(
            0, r_rows, body, jnp.full(x.shape, -1, jnp.int32))

    sid_q = sid_at(qflat)                  # [TQ*G, 1]

    def flash_update(h, s_scores, v_cols, p_scale=None):
        m_prev = m_ref[h, :, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s_scores, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s_scores - m_new)
        l_ref[h] = l_ref[h] * alpha + jnp.sum(p, axis=1, keepdims=True)
        m_ref[h] = jnp.broadcast_to(m_new, m_ref.shape[1:])
        pv = jnp.dot(p if p_scale is None else p * p_scale, v_cols,
                     preferred_element_type=jnp.float32)
        acc_ref[h] = acc_ref[h] * alpha + pv

    def q_head(h):
        return q_ref[0, h].reshape(tq * g, d).astype(jnp.float32) * sm_scale

    # ------------------------------------------------ prefix phase (per row)
    def block_dmas(r, ci, slot):
        m_table = bt_ref.shape[1]
        out = []
        for i in range(c):  # static unroll: C block copies per chunk
            bid = bt_ref[r, jnp.minimum(ci * c + i, m_table - 1)]
            out.append(pltpu.make_async_copy(
                cache_ref.at[lyr, bid], kvbuf.at[slot, i], sems.at[slot, i]
            ))
            if quant:
                out.append(pltpu.make_async_copy(
                    scale_ref.at[lyr, bid], scbuf.at[slot, i],
                    scsems.at[slot, i]
                ))
        return out

    def row_body(r, _):
        prefix = start_ref[r]
        overlap = (q0 < rend_ref[r]) & (q0 + tq > roff_ref[r])

        @pl.when(overlap & (prefix > 0))
        def _row():
            n_pref = pl.cdiv(prefix, t_chunk)
            for dma in block_dmas(r, 0, 0):
                dma.start()

            def pref_body(ci, _):
                slot = jax.lax.rem(ci, 2)

                @pl.when(ci + 1 < n_pref)
                def _prefetch():
                    for dma in block_dmas(r, ci + 1, jax.lax.rem(ci + 1, 2)):
                        dma.start()

                for dma in block_dmas(r, ci, slot):
                    dma.wait()

                kc = kvbuf[slot, :, 0].reshape(t_chunk, hk * d).astype(
                    jnp.float32)
                vc = kvbuf[slot, :, 1].reshape(t_chunk, hk * d).astype(
                    jnp.float32)
                if quant:
                    sck = jnp.concatenate(
                        [scbuf[slot, i, 0][:hk, :bs] for i in range(c)],
                        axis=-1)
                    scv = jnp.concatenate(
                        [scbuf[slot, i, 1][:hk, :bs] for i in range(c)],
                        axis=-1)
                col = ci * t_chunk + jax.lax.broadcasted_iota(
                    jnp.int32, (1, t_chunk), 1)
                # only this row's queries see this row's prefix slots
                allow = (col < prefix) & (sid_q == r)
                # dead tail-block slots may be non-finite pool garbage —
                # zero their V rows (and V scales); the score mask alone
                # leaves 0 * NaN in the PV product
                vmask = ci * t_chunk + jax.lax.broadcasted_iota(
                    jnp.int32, (t_chunk, 1), 0) < prefix
                vc = jnp.where(vmask, vc, 0.0)
                if quant:
                    scv = jnp.where(col < prefix, scv, 0.0)
                for h in range(hk):
                    s_ = jax.lax.dot_general(
                        q_head(h), kc[:, h * d:(h + 1) * d],
                        (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    )
                    if quant:
                        s_ = s_ * sck[h:h + 1, :]
                    if logit_cap is not None:
                        s_ = softcap(s_, logit_cap)
                    s_ = jnp.where(allow, s_, NEG_INF)
                    flash_update(h, s_, vc[:, h * d:(h + 1) * d],
                                 p_scale=scv[h:h + 1, :] if quant else None)
                return 0

            jax.lax.fori_loop(0, n_pref, pref_body, 0)

        return 0

    jax.lax.fori_loop(0, r_rows, row_body, 0)

    # ------------------------------------------------- fresh phase (causal)
    def fresh_body(cj, _):
        col0 = cj * tq
        kc = k_ref[0, pl.ds(col0, tq)].astype(jnp.float32)   # [TQ, Hk*D]
        vc = v_ref[0, pl.ds(col0, tq)].astype(jnp.float32)
        col = col0 + jax.lax.broadcasted_iota(jnp.int32, (1, tq), 1)
        sid_c = sid_at(col)                                  # [1, TQ]
        # packed-padding tokens (sid -1) may be non-finite — zero their
        # V rows before the PV product
        sid_v = sid_at(col0 + jax.lax.broadcasted_iota(
            jnp.int32, (tq, 1), 0))
        vc = jnp.where(sid_v >= 0, vc, 0.0)
        # same sequence + causal by flat index; padding queries (sid -1)
        # match nothing — fully-masked rows degenerate to a finite
        # uniform-weight PV mean (exp(NEG_INF - NEG_INF) = 1), which the
        # caller discards, matching the base kernel's padding contract
        allow = (sid_c == sid_q) & (col <= qflat) & (sid_q >= 0)
        for h in range(hk):
            s_ = jax.lax.dot_general(
                q_head(h), kc[:, h * d:(h + 1) * d],
                (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
            )
            if logit_cap is not None:
                s_ = softcap(s_, logit_cap)
            s_ = jnp.where(allow, s_, NEG_INF)
            flash_update(h, s_, vc[:, h * d:(h + 1) * d])
        return 0

    jax.lax.fori_loop(0, ri + 1, fresh_body, 0)

    for h in range(hk):
        denom = jnp.maximum(l_ref[h, :, :1], 1e-9)  # keep padding finite
        out_ref[0, h] = (
            (acc_ref[h] / denom).reshape(tq, g * d).astype(out_ref.dtype)
        )


@functools.partial(
    jax.jit,
    static_argnames=("sm_scale", "logit_cap", "rows_per_chunk",
                     "blocks_per_chunk", "interpret"),
)
def ragged_paged_prefill_attention(
    q: jax.Array,             # [1, T, H, D] — packed fresh queries
    k_new: jax.Array,         # [1, T, Hk, D]
    v_new: jax.Array,         # [1, T, Hk, D]
    cache: jax.Array,         # [L, N, 2, Bs, Hk*D]
    layer: jax.Array,         # scalar int32
    block_tables: jax.Array,  # [R, M] int32 — per packed sequence
    seq_lens: jax.Array,      # [R] int32 — context length incl. this chunk
    starts: jax.Array,        # [R] int32 — absolute chunk start per row
    row_offsets: jax.Array,   # [R] int32 — flat index of row's first token
    sm_scale: float | None = None,
    logit_cap: float | None = None,
    rows_per_chunk: int = PREFILL_ROWS_PER_CHUNK,
    blocks_per_chunk: int = PREFILL_BLOCKS_PER_CHUNK,
    interpret: bool = False,
) -> jax.Array:
    """Flash ragged (mixed-chunk) attention: T packed fresh tokens of up
    to R sequences against fresh K/V + each row's own cached prefix.
    Rows may be prefill chunks or 1-token decode rows (``starts`` need
    not be block-aligned — see the module comment).  Returns
    [1, T, H, D]."""
    from dynamo_tpu.ops.kv_quant import is_quant

    quant = is_quant(cache)
    data, scale = (cache.data, cache.scale) if quant else (cache, None)
    _, t, h, d = q.shape
    l, n, _, bs, hkd = data.shape
    hk = hkd // d
    g = h // hk
    m = block_tables.shape[1]
    r_rows = block_tables.shape[0]
    if sm_scale is None:
        sm_scale = 1.0 / (d**0.5)
    tq = min(rows_per_chunk, t)
    while t % tq:
        tq //= 2
    c = min(blocks_per_chunk, m)

    q_in = q.reshape(1, t, hk, g * d).transpose(0, 2, 1, 3)
    k_in = k_new.reshape(1, t, hkd)
    v_in = v_new.reshape(1, t, hkd)
    row_ends = row_offsets + (seq_lens - starts)  # one past last real token

    in_specs = [
        pl.BlockSpec((1, hk, tq, g * d), lambda ri, *_: (0, 0, ri, 0)),
        pl.BlockSpec((1, t, hkd), lambda ri, *_: (0, 0, 0)),
        pl.BlockSpec((1, t, hkd), lambda ri, *_: (0, 0, 0)),
        pl.BlockSpec(memory_space=pl.ANY),  # cache stays in HBM
    ]
    scratch = [
        pltpu.VMEM((hk, tq * g, d), jnp.float32),
        pltpu.VMEM((hk, tq * g, 128), jnp.float32),
        pltpu.VMEM((hk, tq * g, 128), jnp.float32),
        pltpu.VMEM((2, c, 2, bs, hkd), data.dtype),
        pltpu.SemaphoreType.DMA((2, c)),
    ]
    operands = [
        starts.astype(jnp.int32),
        row_offsets.astype(jnp.int32),
        row_ends.astype(jnp.int32),
        block_tables.astype(jnp.int32),
        jnp.asarray(layer, jnp.int32).reshape(1),
        q_in,
        k_in,
        v_in,
        data,
    ]
    if quant:
        hp, sp = scale.shape[-2:]
        in_specs.append(pl.BlockSpec(memory_space=pl.ANY))
        scratch += [
            pltpu.VMEM((2, c, 2, hp, sp), jnp.float32),
            pltpu.SemaphoreType.DMA((2, c)),
        ]
        operands.append(scale)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(t // tq,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, hk, tq, g * d), lambda ri, *_: (0, 0, ri, 0)
        ),
        scratch_shapes=scratch,
    )

    cost = ragged_cost_estimate(
        t, r_rows, h, hk, d, bs, m, cache_bytes=data.dtype.itemsize,
        quant=quant, rows_per_chunk=rows_per_chunk,
        blocks_per_chunk=blocks_per_chunk)
    cost_kw = {} if cost is None else {"cost_estimate": cost}

    out = pl.pallas_call(
        functools.partial(
            _ragged_kernel_quant if quant else _ragged_kernel,
            c=c, tq=tq, hk=hk, g=g, d=d, r_rows=r_rows,
            sm_scale=float(sm_scale), logit_cap=logit_cap,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((1, hk, t, g * d), q.dtype),
        interpret=interpret,
        **cost_kw,
    )(*operands)
    # [1, Hk, T, G*D] -> [1, T, H, D]
    return out.transpose(0, 2, 1, 3).reshape(1, t, h, d)
