"""Pallas TPU kernels — the hand-tuned hot ops.

Each kernel has a pure-JAX oracle in dynamo_tpu/ops/ that defines its
semantics; tests compare against the oracle in interpret mode on CPU.
"""

from dynamo_tpu.ops.pallas.decode_attention import paged_decode_attention

__all__ = ["paged_decode_attention"]
