"""Flash-decoding over paged KV blocks — the decode-step hot kernel.

Why this exists: a naive paged-attention gathers each sequence's whole
padded context out of the block pool before attending — at batch 64 / 2k
context that is GBs of HBM traffic per step and dominates ITL.  This kernel
instead streams ONLY the blocks each sequence actually owns, directly from
the full multi-layer cache in HBM.

Design (one grid step per sequence, work ∝ actual context length):

  * Grid is (B,).  Inside the kernel a `fori_loop` with a *data-dependent*
    bound (ceil(seq_len / chunk)) walks the sequence's chunks — padding
    chunks are never visited, never DMA'd: a 100-token sequence in a
    2048-token table costs 7 block fetches, not 128.  This also keeps the
    Mosaic grid overhead at B steps instead of B × M/C tiny steps.
  * K/V blocks are fetched with manual double-buffered `make_async_copy`
    from the cache in HBM (`pltpu.ANY`), chunk i+1 in flight while chunk i
    computes.  Block ids come from the scalar-prefetched block table in
    SMEM; the layer is a scalar operand, so the per-layer K/V is never
    sliced out (a slice would copy ~100s of MB per layer per step).
  * GQA is handled by expanding q to a block-diagonal [H, Hk*D] layout
    outside the kernel: scores and the PV product are then two plain MXU
    matmuls per chunk with no per-head lane slicing.  The extra zeros cost
    FLOPs the decode step has to spare (it is bandwidth-bound).
  * Online softmax (flash) accumulation in VMEM scratch across chunks.

Semantics match `paged_attention` with S=1: each query row attends over
slots [0, seq_len) of its own block table.  Rows with seq_len == 0 yield 0.

Reference parity: the reference's engines delegate decode attention to
vLLM/TRT-LLM paged-attention CUDA kernels; this is the TPU-native
equivalent the rebuild owns (SURVEY.md §7 stage 4, hard part #3).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["paged_decode_attention"]

NEG_INF = -1e30


def _kernel(
    # scalar prefetch (SMEM)
    seq_ref,     # [B] int32
    bt_ref,      # [B, M] int32
    layer_ref,   # [1] int32
    # inputs
    q_ref,       # [1, H, HkD] VMEM — block-diagonal expanded, pre-scaled f32
    cache_ref,   # [L, 2, N, Bs, HkD] HBM (manual DMA)
    # outputs
    out_ref,     # [1, H, HkD] VMEM
    # scratch
    acc_ref,     # [H, HkD] f32
    m_ref,       # [H, 128] f32
    l_ref,       # [H, 128] f32
    kbuf,        # [2, C, Bs, HkD] cache-dtype (double buffer)
    vbuf,        # [2, C, Bs, HkD]
    sems,        # [2, 2C] DMA semaphores
    *,
    c: int,
):
    b = pl.program_id(0)
    bs, hkd = kbuf.shape[2], kbuf.shape[3]
    h = q_ref.shape[1]
    t = c * bs
    seq_len = seq_ref[b]
    lyr = layer_ref[0]
    last_block = jnp.maximum(seq_len - 1, 0) // bs
    num_chunks = pl.cdiv(seq_len, t)  # data-dependent loop bound

    def block_dmas(ci, slot):
        out = []
        for i in range(c):  # static unroll: C copies per chunk
            bid = bt_ref[b, jnp.minimum(ci * c + i, last_block)]
            out.append(pltpu.make_async_copy(
                cache_ref.at[lyr, 0, bid], kbuf.at[slot, i], sems.at[slot, i]
            ))
            out.append(pltpu.make_async_copy(
                cache_ref.at[lyr, 1, bid], vbuf.at[slot, i], sems.at[slot, c + i]
            ))
        return out

    acc_ref[:] = jnp.zeros_like(acc_ref)
    m_ref[:] = jnp.full_like(m_ref, NEG_INF)
    l_ref[:] = jnp.zeros_like(l_ref)

    @pl.when(num_chunks > 0)
    def _prologue():
        for dma in block_dmas(0, 0):
            dma.start()

    def body(ci, _):
        slot = jax.lax.rem(ci, 2)

        @pl.when(ci + 1 < num_chunks)
        def _prefetch():
            for dma in block_dmas(ci + 1, jax.lax.rem(ci + 1, 2)):
                dma.start()

        for dma in block_dmas(ci, slot):
            dma.wait()

        q = q_ref[0]  # [H, HkD]
        k = kbuf[slot].reshape(t, hkd).astype(jnp.float32)
        v = vbuf[slot].reshape(t, hkd).astype(jnp.float32)

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [H, T]
        pos = ci * t + jax.lax.broadcasted_iota(jnp.int32, (h, t), 1)
        s = jnp.where(pos < seq_len, s, NEG_INF)

        m_prev = m_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        pv = jnp.dot(p, v, preferred_element_type=jnp.float32)
        acc_ref[:] = acc_ref[:] * alpha + pv
        return 0

    jax.lax.fori_loop(0, num_chunks, body, 0)

    denom = jnp.maximum(l_ref[:, :1], 1e-9)
    out_ref[0] = (acc_ref[:] / denom).astype(out_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("sm_scale", "blocks_per_chunk", "interpret"),
)
def paged_decode_attention(
    q: jax.Array,             # [B, H, D]
    cache: jax.Array,         # [L, 2, N, Bs, Hk*D] — full multi-layer cache
    layer: jax.Array,         # scalar int32
    block_tables: jax.Array,  # [B, M] int32
    seq_lens: jax.Array,      # [B] int32
    sm_scale: float | None = None,
    blocks_per_chunk: int = 8,
    interpret: bool = False,
) -> jax.Array:
    """One decode step of attention for B sequences.  Returns [B, H, D]."""
    b, h, d = q.shape
    l, _, n, bs, hkd = cache.shape
    hk = hkd // d
    m = block_tables.shape[1]
    g = h // hk
    if sm_scale is None:
        sm_scale = 1.0 / (d**0.5)
    c = min(blocks_per_chunk, m)

    # Block-diagonal q expansion: row for head (k, g) lives in kv-head k's
    # D-wide column slot; zeros elsewhere.  [B, H, D] -> [B, H, Hk*D] f32,
    # columns ordered (kv_head, d) to match the cache's trailing axis.
    qf = q.astype(jnp.float32) * sm_scale
    eye = jnp.eye(hk, dtype=jnp.float32)
    q_exp = jnp.einsum("bkgd,ke->bkged", qf.reshape(b, hk, g, d), eye)
    q_exp = q_exp.reshape(b, h, hkd)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, h, hkd), lambda b_idx, *_: (b_idx, 0, 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),  # cache stays in HBM
        ],
        out_specs=pl.BlockSpec((1, h, hkd), lambda b_idx, *_: (b_idx, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, hkd), jnp.float32),
            pltpu.VMEM((h, 128), jnp.float32),
            pltpu.VMEM((h, 128), jnp.float32),
            pltpu.VMEM((2, c, bs, hkd), cache.dtype),
            pltpu.VMEM((2, c, bs, hkd), cache.dtype),
            pltpu.SemaphoreType.DMA((2, 2 * c)),
        ],
    )

    out = pl.pallas_call(
        functools.partial(_kernel, c=c),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, hkd), q.dtype),
        interpret=interpret,
    )(
        seq_lens.astype(jnp.int32),
        block_tables.astype(jnp.int32),
        jnp.asarray(layer, jnp.int32).reshape(1),
        q_exp,
        cache,
    )

    # Collapse the block-diagonal layout back to [B, H, D].
    out = out.reshape(b, hk, g, hk, d)
    out = jnp.einsum("bkged,ke->bkgd", out, jnp.eye(hk, dtype=out.dtype))
    return out.reshape(b, h, d)
