"""Flash-decoding over paged KV blocks — the decode-step hot kernel.

Why this exists: a naive paged-attention gathers each sequence's whole
padded context out of the block pool before attending — at batch 64 / 2k
context that is GBs of HBM traffic per step and dominates ITL.  This kernel
instead streams ONLY the blocks each sequence actually owns, directly from
the full multi-layer cache in HBM.

Design (one grid step per GROUP of G sequences, work ∝ actual context):

  * Grid is (B/G,).  TPU grid steps run sequentially on the core, so the
    per-step fixed cost (DMA issue, loop control, semaphore waits) is paid
    B times if the grid is (B,).  Grouping G sequences per step issues all
    their block DMAs together — G×C copies in flight per chunk — and
    amortises the fixed cost G-fold.  At batch 64 this took the 1B-model
    decode step from ~B sequential latency-bound walks to B/G.
  * Inside the kernel a `fori_loop` with a *data-dependent* bound
    (ceil(max(seq_len in group) / chunk)) walks the group's chunks —
    chunks past a sequence's end fetch its last block again (clamped id,
    masked compute), chunks past the GROUP's max are never visited.
  * K/V blocks are fetched with manual double-buffered `make_async_copy`
    from the cache in HBM (`pl.ANY`), chunk i+1 in flight while chunk i
    computes.  K and V of a block are adjacent in the cache layout
    [L, N, 2, Bs, HkD], so each block is ONE contiguous DMA.  Block ids
    come from the scalar-prefetched block table in SMEM; the layer is a
    scalar operand, so per-layer K/V is never sliced out.
  * GQA is handled by expanding q to a block-diagonal [H, Hk*D] layout
    outside the kernel: scores and the PV product are then plain MXU
    matmuls with no per-head lane slicing.  The extra zeros cost FLOPs the
    decode step has to spare (it is bandwidth/latency-bound).
  * Online softmax (flash) accumulation in VMEM scratch across chunks.

Semantics match `paged_attention` with S=1: each query row attends over
slots [0, seq_len) of its own block table.  Rows with seq_len == 0 yield 0.

Reference parity: the reference's engines delegate decode attention to
vLLM/TRT-LLM paged-attention CUDA kernels; this is the TPU-native
equivalent the rebuild owns (SURVEY.md §7 stage 4, hard part #3).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dynamo_tpu.ops.paged_attention import softcap
from dynamo_tpu.ops.pallas.registry import (
    DECODE_BLOCKS_PER_CHUNK,
    DECODE_SEQS_PER_GROUP,
    decode_cost_estimate,
)

__all__ = ["paged_decode_attention", "paged_decode_attention_mq"]

NEG_INF = -1e30


def _kernel(
    # scalar prefetch (SMEM)
    seq_ref,     # [B] int32
    q0_ref,      # [B] int32 — absolute position of each row's FIRST query
    bt_ref,      # [B, M] int32
    layer_ref,   # [1] int32
    # inputs
    q_ref,       # [G, S*H, HkD] VMEM — block-diagonal expanded, pre-scaled f32
    cache_ref,   # [L, N, 2, Bs, HkD] HBM (manual DMA)
    # (scale_ref [L, N, 2, Hp, Sp] HBM when quant — spliced via *rest)
    # outputs
    out_ref,     # [G, S*H, HkD] VMEM
    # scratch
    acc_ref,     # [G, S*H, HkD] f32
    m_ref,       # [G, S*H, 128] f32
    l_ref,       # [G, S*H, 128] f32
    kvbuf,       # [2, G, C, 2, Bs, HkD] cache-dtype (double buffer)
    sems,        # [2, G, C] DMA semaphores
    # (scbuf [2, G, C, 2, Hp, Sp] f32 + scsems when quant)
    *,
    c: int,
    g: int,
    s_q: int,
    hk: int,
    logit_cap=None,
):
    return _kernel_impl(seq_ref, q0_ref, bt_ref, layer_ref, q_ref, cache_ref,
                        None, out_ref, acc_ref, m_ref, l_ref, kvbuf, sems,
                        None, None, c=c, g=g, s_q=s_q, hk=hk,
                        logit_cap=logit_cap)


def _kernel_quant(seq_ref, q0_ref, bt_ref, layer_ref, q_ref, cache_ref,
                  scale_ref, out_ref, acc_ref, m_ref, l_ref, kvbuf, sems,
                  scbuf, scsems, *, c: int, g: int, s_q: int, hk: int,
                  logit_cap=None):
    return _kernel_impl(seq_ref, q0_ref, bt_ref, layer_ref, q_ref, cache_ref,
                        scale_ref, out_ref, acc_ref, m_ref, l_ref, kvbuf,
                        sems, scbuf, scsems, c=c, g=g, s_q=s_q, hk=hk,
                        logit_cap=logit_cap)


def _kernel_impl(
    seq_ref, q0_ref, bt_ref, layer_ref, q_ref, cache_ref, scale_ref,
    out_ref, acc_ref, m_ref, l_ref, kvbuf, sems, scbuf, scsems,
    *,
    c: int,
    g: int,
    s_q: int,
    hk: int,
    logit_cap=None,
):
    gi = pl.program_id(0)
    bs, hkd = kvbuf.shape[4], kvbuf.shape[5]
    h = q_ref.shape[1] // s_q  # rows are (query, head)-major
    t = c * bs
    lyr = layer_ref[0]
    quant = scale_ref is not None

    # group-wide chunk bound: max seq_len among the G sequences
    max_len = seq_ref[gi * g]
    for j in range(1, g):
        max_len = jnp.maximum(max_len, seq_ref[gi * g + j])
    num_chunks = pl.cdiv(max_len, t)  # data-dependent loop bound

    def block_dmas(ci, slot):
        out = []
        m = bt_ref.shape[1]
        for j in range(g):          # static unroll over group
            b = gi * g + j
            # clamp to the table width: a caller-side seq_len beyond the
            # table must not index SMEM out of bounds
            last_block = jnp.minimum(jnp.maximum(seq_ref[b] - 1, 0) // bs, m - 1)
            for i in range(c):      # static unroll: C copies per seq per chunk
                bid = bt_ref[b, jnp.minimum(ci * c + i, last_block)]
                # K and V are adjacent in the [.., 2, Bs, HkD] block: ONE DMA
                out.append(pltpu.make_async_copy(
                    cache_ref.at[lyr, bid], kvbuf.at[slot, j, i], sems.at[slot, j, i]
                ))
                if quant:  # the block's scale tile rides a second small DMA
                    out.append(pltpu.make_async_copy(
                        scale_ref.at[lyr, bid], scbuf.at[slot, j, i],
                        scsems.at[slot, j, i]
                    ))
        return out

    acc_ref[:] = jnp.zeros_like(acc_ref)
    m_ref[:] = jnp.full_like(m_ref, NEG_INF)
    l_ref[:] = jnp.zeros_like(l_ref)

    @pl.when(num_chunks > 0)
    def _prologue():
        for dma in block_dmas(0, 0):
            dma.start()

    def body(ci, _):
        slot = jax.lax.rem(ci, 2)

        @pl.when(ci + 1 < num_chunks)
        def _prefetch():
            for dma in block_dmas(ci + 1, jax.lax.rem(ci + 1, 2)):
                dma.start()

        for dma in block_dmas(ci, slot):
            dma.wait()

        for j in range(g):  # static unroll: one flash update per sequence
            seq_len = seq_ref[gi * g + j]

            # skip chunks past THIS sequence's end (and zero-length rows:
            # their acc/l stay 0 → output 0)
            @pl.when(ci * t < seq_len)
            def _update(j=j, seq_len=seq_len):
                q = q_ref[j]  # [S*H, HkD]
                k = kvbuf[slot, j, :, 0].reshape(t, hkd).astype(jnp.float32)
                v = kvbuf[slot, j, :, 1].reshape(t, hkd).astype(jnp.float32)

                # Slots at/past seq_len hold whatever the pool holds (pad
                # lanes of a live block, or a clamped re-fetch).  The score
                # mask zeroes their P columns, but 0 * garbage-V is still
                # garbage when the pool holds non-finite values — zero V
                # rows (and the V scales below) for dead slots outright.
                slot_pos = ci * t + jax.lax.broadcasted_iota(
                    jnp.int32, (t, 1), 0)
                v = jnp.where(slot_pos < seq_len, v, 0.0)

                s = jax.lax.dot_general(
                    q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
                )  # [H, T]
                if quant:
                    # int8 KV: k rows carry a per-(token, kv-head) scale.
                    # Column t of s uses k row t whose scale depends on the
                    # query's kv head — slice each block's padded [Hp, Sp]
                    # tile down to its valid [Hk, Bs] region (value-level
                    # slice in VMEM; the DMA moved the whole aligned tile),
                    # build [H, T] tiles by lane-concat, then repeat each
                    # kv head's row for its G query heads (q rows are
                    # kv-head-major).  V's scale folds into P before the PV
                    # matmul (not into l: softmax stats use true probs).
                    gq = h // hk
                    sck = jnp.concatenate(
                        [scbuf[slot, j, i, 0][:hk, :bs] for i in range(c)],
                        axis=-1
                    )  # [Hk, T]
                    scv = jnp.concatenate(
                        [scbuf[slot, j, i, 1][:hk, :bs] for i in range(c)],
                        axis=-1
                    )
                    sck = jnp.repeat(sck, gq, axis=0)  # [H, T]
                    scv = jnp.repeat(scv, gq, axis=0)
                    if s_q > 1:  # row layout is (query, head)-major
                        sck = jnp.concatenate([sck] * s_q, axis=0)
                        scv = jnp.concatenate([scv] * s_q, axis=0)
                    s = s * sck
                if logit_cap is not None:  # Gemma2 attention softcap
                    s = softcap(s, logit_cap)
                rows = s_q * h
                pos = ci * t + jax.lax.broadcasted_iota(jnp.int32, (rows, t), 1)
                # causal per query: query sq (row sq*H + h) sits at absolute
                # position q0 + sq and sees cache slots <= that position
                q_pos = q0_ref[gi * g + j] + (
                    jax.lax.broadcasted_iota(jnp.int32, (rows, t), 0) // h
                )
                s = jnp.where((pos <= q_pos) & (pos < seq_len), s, NEG_INF)
                if quant:
                    # dead-slot V scales may be non-finite (pad lanes of
                    # the scale tile) — see the V zeroing above
                    scv = jnp.where(pos < seq_len, scv, 0.0)

                m_prev = m_ref[j, :, :1]
                m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
                alpha = jnp.exp(m_prev - m_new)
                p = jnp.exp(s - m_new)
                l_ref[j] = l_ref[j] * alpha + jnp.sum(p, axis=1, keepdims=True)
                m_ref[j] = jnp.broadcast_to(m_new, m_ref.shape[1:])
                pv = jnp.dot(p * scv if quant else p, v,
                             preferred_element_type=jnp.float32)
                acc_ref[j] = acc_ref[j] * alpha + pv
        return 0

    jax.lax.fori_loop(0, num_chunks, body, 0)

    for j in range(g):
        denom = jnp.maximum(l_ref[j, :, :1], 1e-9)
        out_ref[j] = (acc_ref[j] / denom).astype(out_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("sm_scale", "logit_cap", "blocks_per_chunk",
                     "seqs_per_group", "interpret"),
)
def paged_decode_attention(
    q: jax.Array,             # [B, H, D]
    cache,                    # [L, N, 2, Bs, Hk*D] cache — or QuantKvCache
    layer: jax.Array,         # scalar int32
    block_tables: jax.Array,  # [B, M] int32
    seq_lens: jax.Array,      # [B] int32
    sm_scale: float | None = None,
    logit_cap: float | None = None,
    blocks_per_chunk: int = DECODE_BLOCKS_PER_CHUNK,
    seqs_per_group: int = DECODE_SEQS_PER_GROUP,
    interpret: bool = False,
) -> jax.Array:
    """One decode step of attention for B sequences.  Returns [B, H, D]."""
    return paged_decode_attention_mq(
        q[:, None], cache, layer, block_tables, seq_lens,
        seq_lens - 1,  # the single query is the sequence tail
        sm_scale=sm_scale, logit_cap=logit_cap,
        blocks_per_chunk=blocks_per_chunk, seqs_per_group=seqs_per_group,
        interpret=interpret,
    )[:, 0]


@functools.partial(
    jax.jit,
    static_argnames=("sm_scale", "logit_cap", "blocks_per_chunk",
                     "seqs_per_group", "interpret"),
)
def paged_decode_attention_mq(
    q: jax.Array,             # [B, S, H, D] — S contiguous trailing queries
    cache,                    # [L, N, 2, Bs, Hk*D] cache — or QuantKvCache
    layer: jax.Array,         # scalar int32
    block_tables: jax.Array,  # [B, M] int32
    seq_lens: jax.Array,      # [B] int32 — context incl. the new queries
    q0_pos: jax.Array,        # [B] int32 — absolute position of q[:, 0]
    sm_scale: float | None = None,
    logit_cap: float | None = None,
    blocks_per_chunk: int = DECODE_BLOCKS_PER_CHUNK,
    seqs_per_group: int = DECODE_SEQS_PER_GROUP,
    interpret: bool = False,
) -> jax.Array:
    """Multi-query flash decode: S queries per row (query j at position
    q0_pos+j, causal) against the row's owned blocks — the speculative
    verify pass and other short non-block-aligned S>1 steps stream only
    live KV instead of gathering the padded table.  Returns [B, S, H, D].
    Rows whose real query count is < S put padding at the tail; their
    outputs are finite garbage the caller discards."""
    from dynamo_tpu.ops.kv_quant import is_quant

    quant = is_quant(cache)
    data, scale = (cache.data, cache.scale) if quant else (cache, None)
    b, s_q, h, d = q.shape
    l, n, _, bs, hkd = data.shape
    hk = hkd // d
    m = block_tables.shape[1]
    g_heads = h // hk
    rows = s_q * h
    if sm_scale is None:
        sm_scale = 1.0 / (d**0.5)
    c = min(blocks_per_chunk, m)
    # VMEM scratch scales with S*H rows: shrink the group accordingly
    g = max(1, seqs_per_group // s_q)
    while b % g:  # group size must divide the batch (terminates at g=1)
        g -= 1

    # Block-diagonal q expansion: row for (query sq, head (k, gh)) lives in
    # kv-head k's D-wide column slot; zeros elsewhere.  [B, S, H, D] ->
    # [B, S*H, Hk*D] f32, columns ordered (kv_head, d) to match the cache.
    qf = q.astype(jnp.float32) * sm_scale
    eye = jnp.eye(hk, dtype=jnp.float32)
    q_exp = jnp.einsum("bskgd,ke->bskged",
                       qf.reshape(b, s_q, hk, g_heads, d), eye)
    q_exp = q_exp.reshape(b, rows, hkd)

    in_specs = [
        pl.BlockSpec((g, rows, hkd), lambda i, *_: (i, 0, 0)),
        pl.BlockSpec(memory_space=pl.ANY),  # cache stays in HBM
    ]
    scratch = [
        pltpu.VMEM((g, rows, hkd), jnp.float32),
        pltpu.VMEM((g, rows, 128), jnp.float32),
        pltpu.VMEM((g, rows, 128), jnp.float32),
        pltpu.VMEM((2, g, c, 2, bs, hkd), data.dtype),
        pltpu.SemaphoreType.DMA((2, g, c)),
    ]
    operands = [
        seq_lens.astype(jnp.int32),
        q0_pos.astype(jnp.int32),
        block_tables.astype(jnp.int32),
        jnp.asarray(layer, jnp.int32).reshape(1),
        q_exp,
        data,
    ]
    if quant:
        hp, sp = scale.shape[-2:]  # tile-padded (scale_tile(hk, bs))
        in_specs.append(pl.BlockSpec(memory_space=pl.ANY))  # scales in HBM
        scratch += [
            pltpu.VMEM((2, g, c, 2, hp, sp), jnp.float32),
            pltpu.SemaphoreType.DMA((2, g, c)),
        ]
        operands.append(scale)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(b // g,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((g, rows, hkd), lambda i, *_: (i, 0, 0)),
        scratch_shapes=scratch,
    )

    # Honest scheduling hint: seq_lens are dynamic, so price the static
    # worst case (every row at full-table context).  None on older jax.
    cost = decode_cost_estimate(
        b, s_q, h, hk, d, bs, m, cache_bytes=data.dtype.itemsize,
        quant=quant, blocks_per_chunk=blocks_per_chunk,
        seqs_per_group=seqs_per_group)
    cost_kw = {} if cost is None else {"cost_estimate": cost}

    out = pl.pallas_call(
        functools.partial(_kernel_quant if quant else _kernel, c=c, g=g,
                          s_q=s_q, hk=hk, logit_cap=logit_cap),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, rows, hkd), q.dtype),
        interpret=interpret,
        **cost_kw,
    )(*operands)

    # Collapse the block-diagonal layout back to [B, S, H, D].
    out = out.reshape(b, s_q, hk, g_heads, hk, d)
    out = jnp.einsum("bskged,ke->bskgd", out, jnp.eye(hk, dtype=out.dtype))
    return out.reshape(b, s_q, h, d)
