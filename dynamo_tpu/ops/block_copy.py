"""Batched KV-block gather/scatter — the block_copy.cu equivalent.

The reference ships a CUDA kernel (lib/llm/src/kernels/block_copy.cu:41,
entry points :167,246,309) that moves whole KV blocks between device and
host pools for offload and disaggregated transfer.  On TPU the same job is
a gather/scatter over the leading block axis of the cache — XLA compiles
these to efficient HBM DMAs; the cross-host path stages through host RAM
(``jax.device_get``/``device_put``) and the wire (see
dynamo_tpu/llm/kv/transfer.py).

Cache layout: [L, N, 2, Bs, Hk*D] (layers, blocks, k/v, block_size,
flat kv_heads*head_dim) — one array for the whole model so a block id selects
the block across every layer at once, exactly what transfer needs.  K and V
of a block are adjacent (k/v axis INSIDE the block axis) so the decode
kernel fetches both with a single DMA per block.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = [
    "gather_blocks",
    "gather_blocks_padded",
    "scatter_blocks",
    "scatter_blocks_inplace",
]


@jax.jit
def gather_blocks(cache, block_ids: jax.Array):
    """Pull blocks out of a cache: [L,N,2,Bs,HkD] × [n] -> [L,n,2,Bs,HkD].

    Used to extract a sequence's KV for offload / cross-worker transfer.
    Works on any cache pytree whose leaves index blocks on axis 1 (the
    plain bf16 array, or QuantKvCache's data+scale pair).
    """
    return jax.tree.map(lambda a: jnp.take(a, block_ids, axis=1), cache)


@jax.jit
def scatter_blocks(cache, block_ids: jax.Array, blocks):
    """Write transferred blocks into a cache at ``block_ids``.

    cache: [L,N,2,Bs,HkD]; blocks: [L,n,2,Bs,HkD]; block_ids: [n].
    """
    return jax.tree.map(
        lambda c, b: c.at[:, block_ids].set(b.astype(c.dtype)), cache, blocks
    )


def gather_blocks_padded(cache, block_ids):
    """gather_blocks with the id count padded to a power of two (duplicating
    the last id, sliced off after) so arbitrary eviction/transfer batch
    sizes reuse O(log n) compiled executables instead of one per size."""
    import numpy as np

    n = len(block_ids)
    ids = np.asarray(block_ids, np.int32)
    padded = 1 << max(0, (n - 1).bit_length())
    if padded != n:
        ids = np.concatenate([ids, np.full(padded - n, ids[-1], np.int32)])
    out = gather_blocks(cache, jnp.asarray(ids))
    if padded != n:
        out = jax.tree.map(lambda a: a[:, :n], out)
    return out


_scatter_donated = jax.jit(
    lambda cache, block_ids, blocks: jax.tree.map(
        lambda c, b: c.at[:, block_ids].set(b.astype(c.dtype)), cache, blocks
    ),
    donate_argnums=(0,),
)


def scatter_blocks_inplace(cache, block_ids, blocks):
    """Donating scatter for the serving path: the input cache buffer is
    donated so XLA updates it in place instead of copying the whole pool.

    The block count is padded to a power of two (duplicating the last id,
    which rewrites identical data — idempotent) so XLA compiles O(log n)
    executables rather than one per transfer size.
    """
    import numpy as np

    n = len(block_ids)
    if n == 0:
        return cache
    padded = 1 << max(0, (n - 1).bit_length())
    block_ids = np.asarray(block_ids, np.int32)
    if padded != n:
        block_ids = np.concatenate(
            [block_ids, np.full(padded - n, block_ids[-1], np.int32)]
        )
        blocks = jax.tree.map(
            lambda b: jnp.concatenate(
                [b, jnp.repeat(b[:, -1:], padded - n, axis=1)], axis=1
            ),
            blocks,
        )
    return _scatter_donated(cache, jnp.asarray(block_ids), blocks)
