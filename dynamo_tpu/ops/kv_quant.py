"""Int8 KV-cache quantization (per-token, per-kv-head dynamic scales).

Decode reads the whole live context every step, so KV bytes are decode
bandwidth: int8 halves both the cache's HBM footprint (Llama-3-8B:
128KB/token bf16 -> 65KB) and the per-step KV traffic.  This is the
cache-side complement of int8 weight-only serving (models/quant.py); the
reference gets the equivalent from vLLM's fp8 KV-cache mode
(/root/reference/docs/architecture.md:57 runs FP8 end to end).

Design:
  * :class:`QuantKvCache` — pytree of ``data`` int8 `[L, N, 2, Bs, Hk*D]`
    (identical layout to the bf16 cache, so block ids, the decode kernel's
    one-DMA-per-block property, and donation all carry over) and ``scale``
    f32 `[L, N, 2, Hp, Sp]` where `(Hp, Sp) = scale_tile(Hk, Bs)` pads the
    per-block scale tile to the f32 TPU tiling (sublane 8, lane 128); the
    valid region is `[..., :Hk, :Bs]`.  Scales are stored TOKEN-MINOR
    (head row, token lane): the Pallas kernels DMA a block's `[Hp, Sp]`
    tile whole (Mosaic rejects partial-tile memref slices — an unpadded
    `[Hk, Bs]` tile with Bs < 128 cannot be DMA'd from HBM at all, which
    is why the padding is part of the LAYOUT, not a kernel detail), then
    build a per-chunk `[Hk, T]` tile by slicing + lane-concat in VMEM and
    fold it into the score/PV products as row/column rescales.  Padding
    costs (8·128)/(Hk·Bs)·4B per block — ~12.5% of the int8 payload at
    Hk=8, Bs=32 — and buys the kernels' DMA path; the pure-JAX paths just
    ignore the pad lanes.
  * Quantization happens at cache-write time (`write_kv_cache_layer`):
    amax over the head dim of each new K/V row.  Fresh chunk K/V stay
    unquantized in prefill attention (they never round-trip the cache).
  * Dequantization happens at read time: the pure-JAX paths multiply the
    gathered layer slice by its scales; the Pallas kernels DMA the block's
    scale row alongside its data row and rescale in VMEM.

Accuracy: per-row-per-head symmetric int8 keeps worst-case relative error
~0.4%; tests/test_kv_quant.py bounds the logit error against the bf16
cache oracle.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

__all__ = ["QuantKvCache", "is_quant", "quantize_kv_rows", "dequant_layer_slice",
           "scale_tile", "pad_scales"]


class QuantKvCache(NamedTuple):
    """Paged KV cache with int8 payload + per-row-per-head scales."""

    data: jax.Array   # [L, N, 2, Bs, Hk*D] int8
    scale: jax.Array  # [L, N, 2, Hp, Sp]  f32 (token-minor, tile-padded;
    #                   valid region [..., :Hk, :Bs] — see module doc)


def scale_tile(hk: int, bs: int) -> tuple[int, int]:
    """Physical (sublane, lane) dims of a block's scale tile: (Hk, Bs)
    rounded up to the f32 TPU tiling (8, 128) so the Pallas kernels can
    DMA the tile whole (partial-tile memref slices don't lower)."""
    return (-(-hk // 8) * 8, -(-bs // 128) * 128)


def pad_scales(sc: jax.Array) -> jax.Array:
    """Pad a token-minor scale array [..., Hk, Bs] to the canonical
    tile-padded layout [..., Hp, Sp] (pad value 1.0 — a neutral scale, so
    accidentally-read pad lanes dequantize zeros to zeros)."""
    hk, bs = sc.shape[-2:]
    hp, sp = scale_tile(hk, bs)
    if (hp, sp) == (hk, bs):
        return sc
    cfg = [(0, 0)] * (sc.ndim - 2) + [(0, hp - hk), (0, sp - bs)]
    return jnp.pad(sc, cfg, constant_values=1.0)


def is_quant(cache) -> bool:
    # exact type check: every quant-aware caller dereferences .data/.scale,
    # so a plain (data, scale) tuple must be wrapped first (the engine's
    # scatter_external does this for wire-format tuples)
    return isinstance(cache, QuantKvCache)


def quantize_kv_rows(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """[..., Hk, D] -> (int8 [..., Hk, D], scale f32 [..., Hk])."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(xf / scale[..., None]), -127, 127).astype(jnp.int8)
    return q, scale


def dequant_layer_slice(
    data: jax.Array,   # [..., Bs, Hk*D] int8 (any leading block dims)
    scale: jax.Array,  # [..., Hp, Sp]  f32 (token-minor, tile-padded)
    hk: int,
    dtype=jnp.float32,
) -> jax.Array:
    """Rescale an int8 cache slice back to real values (read path)."""
    *lead, bs, hkd = data.shape
    d = hkd // hk
    sc = scale[..., :hk, :bs]  # drop tile padding
    x = data.astype(jnp.float32).reshape(*lead, bs, hk, d)
    x = x * jnp.swapaxes(sc, -1, -2)[..., None]
    return x.reshape(*lead, bs, hkd).astype(dtype)
