"""Int8 KV-cache quantization (per-token, per-kv-head dynamic scales).

Decode reads the whole live context every step, so KV bytes are decode
bandwidth: int8 halves both the cache's HBM footprint (Llama-3-8B:
128KB/token bf16 -> 65KB) and the per-step KV traffic.  This is the
cache-side complement of int8 weight-only serving (models/quant.py); the
reference gets the equivalent from vLLM's fp8 KV-cache mode
(/root/reference/docs/architecture.md:57 runs FP8 end to end).

Design:
  * :class:`QuantKvCache` — pytree of ``data`` int8 `[L, N, 2, Bs, Hk*D]`
    (identical layout to the bf16 cache, so block ids, the decode kernel's
    one-DMA-per-block property, and donation all carry over) and ``scale``
    f32 `[L, N, 2, Hk, Bs]` (one scale per written K/V row per kv head —
    ~3% extra bytes at D=128).  Scales are stored TOKEN-MINOR (Hk, Bs):
    the Pallas kernels then build a per-chunk `[Hk, T]` scale tile by
    concatenating block tiles along lanes — no in-kernel transpose — and
    fold it into the score/PV products as row/column rescales.
  * Quantization happens at cache-write time (`write_kv_cache_layer`):
    amax over the head dim of each new K/V row.  Fresh chunk K/V stay
    unquantized in prefill attention (they never round-trip the cache).
  * Dequantization happens at read time: the pure-JAX paths multiply the
    gathered layer slice by its scales; the Pallas kernels DMA the block's
    scale row alongside its data row and rescale in VMEM.

Accuracy: per-row-per-head symmetric int8 keeps worst-case relative error
~0.4%; tests/test_kv_quant.py bounds the logit error against the bf16
cache oracle.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

__all__ = ["QuantKvCache", "is_quant", "quantize_kv_rows", "dequant_layer_slice"]


class QuantKvCache(NamedTuple):
    """Paged KV cache with int8 payload + per-row-per-head scales."""

    data: jax.Array   # [L, N, 2, Bs, Hk*D] int8
    scale: jax.Array  # [L, N, 2, Hk, Bs]  f32 (token-minor; see module doc)


def is_quant(cache) -> bool:
    # exact type check: every quant-aware caller dereferences .data/.scale,
    # so a plain (data, scale) tuple must be wrapped first (the engine's
    # scatter_external does this for wire-format tuples)
    return isinstance(cache, QuantKvCache)


def quantize_kv_rows(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """[..., Hk, D] -> (int8 [..., Hk, D], scale f32 [..., Hk])."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(xf / scale[..., None]), -127, 127).astype(jnp.int8)
    return q, scale


def dequant_layer_slice(
    data: jax.Array,   # [..., Bs, Hk*D] int8 (any leading block dims)
    scale: jax.Array,  # [..., Hk, Bs]  f32 (token-minor)
    hk: int,
    dtype=jnp.float32,
) -> jax.Array:
    """Rescale an int8 cache slice back to real values (read path)."""
    *lead, bs, hkd = data.shape
    d = hkd // hk
    x = data.astype(jnp.float32).reshape(*lead, bs, hk, d)
    x = x * jnp.swapaxes(scale, -1, -2)[..., None]
    return x.reshape(*lead, bs, hkd).astype(dtype)
