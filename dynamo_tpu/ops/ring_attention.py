"""Ring attention — context/sequence parallelism over an ICI mesh axis.

Long-context capability the reference lacks entirely (SURVEY.md §5
"long-context / sequence parallelism: absent from the reference") but a
TPU-native engine needs: a prompt too long for one chip's HBM is sharded
along the sequence axis of the mesh, and attention runs blockwise while
K/V chunks rotate around the ring (jax.lax.ppermute over ICI), overlapping
the collective with compute.  Online-softmax accumulation (the
flash-attention recurrence) makes the result exact, not approximate.

    device i holds Q_i forever; at ring step t it multiplies against
    KV_{(i-t) mod n}, merging partial results with the running (m, l, o)
    log-sum-exp state.  n steps visit every KV chunk once.

Designed for use under ``jax.shard_map`` (wrapper below) so GSPMD sees the
per-device program explicitly — no accidental all-gather of the sequence.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from dynamo_tpu.ops.paged_attention import softcap
from dynamo_tpu.utils.mesh import AXIS_SP

__all__ = ["ring_attention", "ring_attention_inner"]

_NEG_INF = -1e30


def ring_attention_inner(
    q: jax.Array,       # [B, Sq, Hq, D]  local query shard
    k: jax.Array,       # [B, Sk, Hk, D]  local key shard
    v: jax.Array,       # [B, Sk, Hk, D]  local value shard
    q_pos: jax.Array,   # [B, Sq] int32   global positions of local queries
    kv_pos: jax.Array,  # [B, Sk] int32   global positions of local keys
    axis_name: str,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    logit_cap: Optional[float] = None,
    window: Optional[int] = None,
) -> jax.Array:
    """Per-device ring attention body (call under shard_map).

    Returns [B, Sq, Hq, D] in q.dtype.  GQA handled by repeating kv heads.
    Masking is position-based (q_pos >= kv_pos), so ragged/padded chunks
    work: give padding keys a position larger than any query.  ``window``
    adds sliding-window masking (q_pos − kv_pos < window).
    """
    if window is not None and not causal:
        # the window mask lives inside the causal branch; silently
        # ignoring it for bidirectional callers would be a wrong answer
        raise ValueError("window requires causal=True")
    n = jax.lax.psum(1, axis_name)
    b, sq, hq, d = q.shape
    hk = k.shape[2]
    rep = hq // hk
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    perm = [(j, (j + 1) % n) for j in range(n)]

    # grouped layout [B, Sq, Hk, rep, D]: the kv-head broadcast of GQA fuses
    # into the matmuls instead of materialising rep× copies of each K/V chunk
    qf = q.astype(jnp.float32).reshape(b, sq, hk, rep, d)

    def step(carry, _):
        o, m, l, k_c, v_c, kv_pos_c = carry
        kf = k_c.astype(jnp.float32)
        vf = v_c.astype(jnp.float32)
        # [B, Hk, rep, Sq, Sk]
        s = jnp.einsum("bqgrd,bkgd->bgrqk", qf, kf) * scale
        if logit_cap is not None:  # Gemma2 attention score softcap
            s = softcap(s, logit_cap)
        if causal:
            mask = q_pos[:, None, None, :, None] >= kv_pos_c[:, None, None, None, :]
            if window is not None:
                mask &= (q_pos[:, None, None, :, None]
                         - kv_pos_c[:, None, None, None, :]) < window
            s = jnp.where(mask, s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[..., None])
        # fully-masked rows: m_new is still _NEG_INF, so s - m_new == 0 and
        # p would be 1 for every masked key — zero it (flash-attention guard)
        p = jnp.where((m_new == _NEG_INF)[..., None], 0.0, p)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=-1)
        o_new = o * corr[..., None] + jnp.einsum("bgrqk,bkgd->bgrqd", p, vf)
        # rotate the KV chunk to the next device; XLA overlaps this ICI
        # ppermute with the next step's matmuls
        k_c = jax.lax.ppermute(k_c, axis_name, perm)
        v_c = jax.lax.ppermute(v_c, axis_name, perm)
        kv_pos_c = jax.lax.ppermute(kv_pos_c, axis_name, perm)
        return (o_new, m_new, l_new, k_c, v_c, kv_pos_c), None

    o0 = jnp.zeros((b, hk, rep, sq, d), jnp.float32)
    m0 = jnp.full((b, hk, rep, sq), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, hk, rep, sq), jnp.float32)
    (o, _, l, _, _, _), _ = jax.lax.scan(
        step, (o0, m0, l0, k, v, kv_pos), None, length=n
    )
    out = o / jnp.where(l == 0.0, 1.0, l)[..., None]  # fully-masked rows -> 0
    # [B, Hk, rep, Sq, D] -> [B, Sq, Hk*rep = Hq, D]
    return out.transpose(0, 3, 1, 2, 4).reshape(b, sq, hq, d).astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    q_pos: jax.Array,
    kv_pos: jax.Array,
    *,
    mesh: jax.sharding.Mesh,
    axis: str = AXIS_SP,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    logit_cap: Optional[float] = None,
    window: Optional[int] = None,
) -> jax.Array:
    """Sequence-parallel attention: inputs sharded on their seq axis over
    ``mesh[axis]``; output keeps that sharding.  q/k/v: [B, S, H, D] global;
    q_pos/kv_pos: [B, S] global positions."""
    if axis not in mesh.axis_names:
        # a renamed/missing axis must fail HERE: a PartitionSpec naming an
        # axis the mesh doesn't have would otherwise silently replicate the
        # sequence on every chip and psum(1) over a size-1 axis would make
        # the ring degenerate to a single (wrong) step
        raise ValueError(
            f"ring_attention axis {axis!r} not in mesh axes "
            f"{tuple(mesh.axis_names)}"
        )
    inner = functools.partial(
        ring_attention_inner, axis_name=axis, causal=causal,
        sm_scale=sm_scale, logit_cap=logit_cap, window=window,
    )
    seq = P(None, axis, None, None)
    pos = P(None, axis)
    if hasattr(jax, "shard_map"):
        wrapped = jax.shard_map(
            inner,
            mesh=mesh,
            in_specs=(seq, seq, seq, pos, pos),
            out_specs=seq,
            check_vma=False,
        )
    else:
        # jax < 0.6: shard_map lives in jax.experimental and the
        # replication-check kwarg is check_rep
        from jax.experimental.shard_map import shard_map as _shard_map

        wrapped = _shard_map(
            inner,
            mesh=mesh,
            in_specs=(seq, seq, seq, pos, pos),
            out_specs=seq,
            check_rep=False,
        )
    return wrapped(q, k, v, q_pos, kv_pos)
