"""TPU kernels and their portable JAX reference implementations.

Everything the reference implements in CUDA/Triton lives here as a Pallas
kernel plus a pure-JAX fallback (used on CPU in tests, and as the
correctness oracle for the kernels):

  paged_attention — the vLLM-engine equivalent attention over block tables
                    (reference delegates this to vLLM; TPU version is ours)
  block_copy      — batched gather/scatter of KV blocks between caches
                    (reference: lib/llm/src/kernels/block_copy.cu)
"""

from dynamo_tpu.ops.paged_attention import paged_attention, write_kv_cache
from dynamo_tpu.ops.block_copy import gather_blocks, scatter_blocks

__all__ = ["paged_attention", "write_kv_cache", "gather_blocks", "scatter_blocks"]
