"""Paged attention over block tables — the engine's core op.

The KV cache is a pool of fixed-size blocks; each sequence owns an ordered
list of block ids (its *block table*).  A single unified op serves prefill,
chunked prefill and decode: the S new tokens of each sequence first scatter
their K/V into the cache, then attend over the sequence's whole context
(cached prefix + themselves) with causal masking by absolute position.

This file holds the pure-JAX implementation: correct on any backend, used
directly on CPU in tests, and as the oracle for the Pallas TPU kernel in
``dynamo_tpu/ops/pallas/``.  On TPU the gather-based fallback is still a
reasonable baseline: XLA fuses the block-table gather with the attention
einsums, and all shapes are static (B, S, M buckets) so everything tiles
onto the MXU.

Reference parity: the reference has no such op in-repo (attention lives in
vLLM); its CUDA surface is block_copy.cu.  This op is the heart of what the
TPU rebuild owns natively (SURVEY.md §7 stage 4).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

from dynamo_tpu.ops.kv_quant import (
    QuantKvCache, dequant_layer_slice, is_quant, quantize_kv_rows,
)

__all__ = [
    "softcap",
    "write_kv_cache",
    "write_kv_cache_layer",
    "paged_attention",
    "paged_attention_layer",
    "prefill_attention",
    "ragged_prefill_attention",
]


def softcap(x: jax.Array, cap: float) -> jax.Array:
    """Gemma2-style tanh logit softcap (shared by every attention path)."""
    return jnp.tanh(x / cap) * cap


def _pallas_decode_enabled() -> bool:
    """Use the Pallas flash-decoding kernel for S=1 steps on TPU."""
    if os.environ.get("DYNAMO_DISABLE_PALLAS"):
        return False
    if os.environ.get("DYNAMO_DISABLE_PALLAS_DECODE"):
        return False
    return jax.default_backend() == "tpu"


def _pallas_prefill_enabled() -> bool:
    """Use the Pallas flash-prefill kernel for S>1 steps on TPU."""
    if os.environ.get("DYNAMO_DISABLE_PALLAS"):
        return False
    if os.environ.get("DYNAMO_DISABLE_PALLAS_PREFILL"):
        return False
    return jax.default_backend() == "tpu"


MQ_MAX_S = 8  # multi-query decode kernel: trailing-query count it serves


def _pallas_mq_enabled() -> bool:
    """Use the multi-query flash-decode kernel for small S>1 steps on TPU
    (the speculative-verify shape; positions must be contiguous per row,
    which every in-repo caller guarantees)."""
    if os.environ.get("DYNAMO_DISABLE_PALLAS"):
        return False
    if os.environ.get("DYNAMO_DISABLE_PALLAS_MQ"):
        return False
    return jax.default_backend() == "tpu"


def paged_attention_layer(
    q: jax.Array,             # [B, S, H, D]
    cache: jax.Array,         # [L, N, 2, Bs, Hk*D] — full multi-layer cache
    layer: jax.Array,         # scalar int32
    block_tables: jax.Array,  # [B, M] int32
    seq_lens: jax.Array,      # [B] int32
    positions: jax.Array,     # [B, S] int32
    sm_scale: float | None = None,
    logit_cap: float | None = None,
    window: int | None = None,
) -> jax.Array:
    """Attention for layer ``layer`` against the full paged cache.

    Dispatch on TPU: S=1 takes the Pallas flash-decode kernel; 1 < S <=
    MQ_MAX_S takes the multi-query variant (the speculative-verify shape).
    BOTH kernel paths require each row's positions to be CONTIGUOUS
    (positions[:, j] == positions[:, 0] + j) — true for every engine
    caller (decode tails, spec verify, prefill chunks); a caller with
    gapped/repeated positions must disable them (DYNAMO_DISABLE_PALLAS /
    DYNAMO_DISABLE_PALLAS_MQ) to get the position-exact oracle, which also
    serves S > MQ_MAX_S and non-TPU backends by materialising the layer
    slice.

    ``window`` (Mistral/Phi3 sliding window) routes to the position-exact
    oracle ONLY when the STATIC context bound (M·Bs) can actually exceed
    the window — a deployment whose max_model_len fits inside the window
    is mathematically full attention and keeps the flash kernels.
    """
    b, s, h, d = q.shape
    quant = is_quant(cache)
    data = cache.data if quant else cache
    _, n, _, bs, hkd = data.shape
    hk = hkd // d
    windowed = window is not None and block_tables.shape[1] * bs > window
    if not windowed:
        window = None  # static no-op: full attention is exact here
    # int8 payload tiles are (32, 128): a quant cache with Bs % 32 != 0
    # pads the block's sublane dim, and the kernels' manual per-block DMA
    # cannot slice a partial tile — take the XLA dequant path instead
    kernel_ok = (not quant or bs % 32 == 0) and not windowed
    if s == 1 and kernel_ok and _pallas_decode_enabled():
        from dynamo_tpu.ops.pallas.decode_attention import paged_decode_attention

        # tuning knobs for on-chip sweeps (benchmarks/profile_decode.py):
        # group size trades per-grid-step fixed cost against VMEM; the
        # defaults fit 8B bf16 KV, int8 KV has headroom for larger groups
        spg = int(os.environ.get("DYNAMO_DECODE_SEQS_PER_GROUP", "8"))
        bpc = int(os.environ.get("DYNAMO_DECODE_BLOCKS_PER_CHUNK", "4"))
        out = paged_decode_attention(
            q[:, 0], cache, layer, block_tables, seq_lens, sm_scale=sm_scale,
            logit_cap=logit_cap, seqs_per_group=spg, blocks_per_chunk=bpc,
        )
        return out[:, None]
    if 1 < s <= MQ_MAX_S and kernel_ok and _pallas_mq_enabled():
        # speculative-verify shape: a few trailing queries per row — stream
        # only the owned blocks instead of gathering the padded table
        from dynamo_tpu.ops.pallas.decode_attention import (
            paged_decode_attention_mq,
        )

        return paged_decode_attention_mq(
            q, cache, layer, block_tables, seq_lens, positions[:, 0],
            sm_scale=sm_scale, logit_cap=logit_cap,
        )

    layer_kv = jax.lax.dynamic_index_in_dim(data, layer, axis=0, keepdims=False)
    if quant:
        layer_sc = jax.lax.dynamic_index_in_dim(
            cache.scale, layer, axis=0, keepdims=False
        )
        layer_kv = dequant_layer_slice(layer_kv, layer_sc, hk)
    k_cache = layer_kv[:, 0].reshape(n, bs, hk, d)
    v_cache = layer_kv[:, 1].reshape(n, bs, hk, d)
    return paged_attention(
        q, k_cache, v_cache, block_tables, seq_lens, positions, sm_scale,
        logit_cap, window=window,
    )


def prefill_attention(
    q: jax.Array,             # [B, S, H, D] — fresh queries (contiguous from `start`)
    k_new: jax.Array,         # [B, S, Hk, D] — this chunk's keys (pre-cache-write values)
    v_new: jax.Array,         # [B, S, Hk, D]
    cache: jax.Array,         # [L, N, 2, Bs, Hk*D]
    layer: jax.Array,         # scalar int32
    block_tables: jax.Array,  # [B, M] int32
    seq_lens: jax.Array,      # [B] int32 — context length incl. new tokens
    start: jax.Array,         # [B] int32 — absolute position of q[:, 0] (block-aligned)
    prefix_blocks: int,       # STATIC: cache blocks holding the cached prefix (bucketed)
    sm_scale: float | None = None,
    logit_cap: float | None = None,
    window: int | None = None,
) -> jax.Array:
    """Prefill attention without gathering the sequence's whole block table.

    The chunk's own K/V are right here in registers — only the *cached
    prefix* (prefix-cache hits / earlier chunks) lives in the cache, and it
    spans just ``prefix_blocks`` blocks (a compile-time bucket, usually 0 or
    small).  The padded-table gather this replaces read M×Bs tokens per
    layer regardless of context and dominated TTFT.

    Fresh-fresh attention is causal by chunk index; fresh-prefix is full.
    Padding tail rows (index ≥ seq_len−start) are masked out of everyone's
    context.  Returns [B, S, H, D].
    """
    b, s, h, d = q.shape
    hk = k_new.shape[2]
    g = h // hk
    quant = is_quant(cache)
    if sm_scale is None:
        sm_scale = 1.0 / (d**0.5)
    data_ = cache.data if quant else cache
    bs_ = data_.shape[3]
    # sliding window matters only when the STATIC attended span (visible
    # prefix + this chunk) can exceed it; otherwise full attention is
    # exact and the flash kernel stays in play
    windowed = window is not None and prefix_blocks * bs_ + s > window
    if not windowed:
        window = None
    # same (32, 128) int8 tile constraint as the decode dispatch
    kernel_ok = (not quant or bs_ % 32 == 0) and not windowed
    if s > 1 and kernel_ok and _pallas_prefill_enabled():
        # flash path: online softmax, scores never leave VMEM; the cached
        # prefix streams from HBM by its TRUE length (start), so the
        # static prefix_blocks bucket doesn't even force recompiles here
        from dynamo_tpu.ops.pallas.prefill_attention import (
            paged_prefill_attention,
        )

        return paged_prefill_attention(
            q, k_new, v_new, cache, layer, block_tables, seq_lens, start,
            sm_scale=sm_scale, logit_cap=logit_cap,
        )
    qg = q.reshape(b, s, hk, g, d).astype(jnp.float32)
    fresh = (seq_lens - start)[:, None, None]  # valid fresh tokens per row

    sf = jnp.einsum("bskgd,btkd->bkgst", qg, k_new.astype(jnp.float32)) * sm_scale
    if logit_cap is not None:  # Gemma2 attention score softcap
        sf = softcap(sf, logit_cap)
    i = jnp.arange(s, dtype=jnp.int32)
    allow_f = (i[None, :, None] >= i[None, None, :]) & (i[None, None, :] < fresh)
    if window is not None:
        # fresh-fresh distance is the chunk-index gap (both offsets from
        # the same block-aligned start)
        allow_f &= (i[None, :, None] - i[None, None, :]) < window
    sf = jnp.where(allow_f[:, None, None], sf, -jnp.inf)

    if prefix_blocks == 0:
        probs = jax.nn.softmax(sf, axis=-1)
        out = jnp.einsum("bkgst,btkd->bskgd", probs, v_new.astype(jnp.float32))
        return out.reshape(b, s, h, d).astype(q.dtype)

    data = cache.data if quant else cache
    _, n, _, bs, hkd = data.shape
    layer_kv = jax.lax.dynamic_index_in_dim(data, layer, axis=0, keepdims=False)
    ctx = layer_kv[block_tables[:, :prefix_blocks]]  # [B, P, 2, Bs, HkD]
    if quant:
        layer_sc = jax.lax.dynamic_index_in_dim(
            cache.scale, layer, axis=0, keepdims=False
        )
        ctx = dequant_layer_slice(ctx, layer_sc[block_tables[:, :prefix_blocks]], hk)
    t = prefix_blocks * bs
    kp = ctx[:, :, 0].reshape(b, t, hk, d)
    vp = ctx[:, :, 1].reshape(b, t, hk, d)
    sp = jnp.einsum("bskgd,btkd->bkgst", qg, kp.astype(jnp.float32)) * sm_scale
    if logit_cap is not None:
        sp = softcap(sp, logit_cap)
    slot = jnp.arange(t, dtype=jnp.int32)
    allow_p = slot[None, None, :] < start[:, None, None]
    if window is not None:
        # prefix slot t IS absolute position t (the fast path's identity
        # block layout); query i sits at absolute start + i
        q_pos = start[:, None, None] + i[None, :, None]
        allow_p &= (q_pos - slot[None, None, :]) < window
    sp = jnp.where(allow_p[:, None, None], sp, -jnp.inf)

    scores = jnp.concatenate([sp, sf], axis=-1)  # [B, Hk, G, S, T+S]
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "bkgst,btkd->bskgd", probs[..., :t], vp.astype(jnp.float32)
    ) + jnp.einsum(
        "bkgst,btkd->bskgd", probs[..., t:], v_new.astype(jnp.float32)
    )
    return out.reshape(b, s, h, d).astype(q.dtype)


def ragged_prefill_attention(
    q: jax.Array,             # [1, T, H, D] — packed fresh queries (flat token axis)
    k_new: jax.Array,         # [1, T, Hk, D] — packed fresh keys (pre-cache-write)
    v_new: jax.Array,         # [1, T, Hk, D]
    cache: jax.Array,         # [L, N, 2, Bs, Hk*D]
    layer: jax.Array,         # scalar int32
    block_tables: jax.Array,  # [R, M] int32 — one table per packed sequence
    seq_lens: jax.Array,      # [R] int32 — context length incl. this chunk
    starts: jax.Array,        # [R] int32 — absolute chunk start (block-aligned)
    row_offsets: jax.Array,   # [R] int32 — flat index of each row's first token
    seq_ids: jax.Array,       # [1, T] int32 — owning row per flat token; -1 = pad
    prefix_blocks: int,       # STATIC: max cached-prefix blocks over rows (bucketed)
    sm_scale: float | None = None,
    logit_cap: float | None = None,
    window: int | None = None,
) -> jax.Array:
    """Mixed-chunk ragged attention over one flat token axis — the
    unified prefill+decode kernel oracle.

    The token-budget scheduler packs several sequences' chunks onto a
    single [T] axis; ``seq_ids`` names each token's owner.  A row may be
    a *prefill chunk* (L contiguous tokens, ``start`` block-aligned) or a
    *decode row* (1 fresh token at ``start = context − 1``, which need
    NOT be block-aligned: the prefix mask is positionally exact, so the
    partially-filled tail block simply contributes ``start % Bs`` visible
    slots).  Fresh-fresh attention is causal *within* a sequence — flat
    order equals position order inside a span, so the mask is
    seq-equality plus flat-index causality — and tokens never see
    another sequence.  Fresh-prefix attention gathers each ROW's own
    cached-prefix blocks and masks slots at/past that row's ``start``
    (for a decode row that is its full cached context, so
    ``prefix_blocks`` must cover ``ceil(start / Bs)`` blocks).

    This is the pure-JAX oracle (CPU tests, XLA fallback); the per-token
    prefix gather materialises [T, P*Bs] keys, which the Pallas kernel
    (ops/pallas/prefill_attention.py) avoids by streaming each row's
    blocks from HBM.  Padding tokens attend only padding (finite rows,
    discarded by the caller).  Returns [1, T, H, D].
    """
    _, t, h, d = q.shape
    hk = k_new.shape[2]
    g = h // hk
    quant = is_quant(cache)
    if sm_scale is None:
        sm_scale = 1.0 / (d**0.5)
    data = cache.data if quant else cache
    _, n, _, bs, hkd = data.shape
    # same window routing as prefill_attention: only when the static
    # attended span can actually exceed the window
    windowed = window is not None and prefix_blocks * bs + t > window
    if not windowed:
        window = None
    kernel_ok = (not quant or bs % 32 == 0) and not windowed
    if t > 1 and kernel_ok and _pallas_prefill_enabled():
        from dynamo_tpu.ops.pallas.prefill_attention import (
            ragged_paged_prefill_attention,
        )

        return ragged_paged_prefill_attention(
            q, k_new, v_new, cache, layer, block_tables, seq_lens, starts,
            row_offsets, sm_scale=sm_scale, logit_cap=logit_cap,
        )

    qg = q[0].reshape(t, hk, g, d).astype(jnp.float32)
    sid = seq_ids[0]                              # [T]
    idx = jnp.arange(t, dtype=jnp.int32)
    same = sid[:, None] == sid[None, :]           # padding pairs with padding
    allow_f = same & (idx[None, :] <= idx[:, None])
    if window is not None:
        # flat gap IS the position gap inside a contiguous span
        allow_f &= (idx[:, None] - idx[None, :]) < window
    sf = jnp.einsum(
        "skgd,tkd->kgst", qg, k_new[0].astype(jnp.float32)
    ) * sm_scale
    if logit_cap is not None:
        sf = softcap(sf, logit_cap)
    sf = jnp.where(allow_f[None, None], sf, -jnp.inf)

    if prefix_blocks == 0:
        probs = jax.nn.softmax(sf, axis=-1)
        out = jnp.einsum(
            "kgst,tkd->skgd", probs, v_new[0].astype(jnp.float32)
        )
        return out.reshape(1, t, h, d).astype(q.dtype)

    r_rows = block_tables.shape[0]
    layer_kv = jax.lax.dynamic_index_in_dim(data, layer, axis=0, keepdims=False)
    ctx = layer_kv[block_tables[:, :prefix_blocks]]  # [R, P, 2, Bs, HkD]
    if quant:
        layer_sc = jax.lax.dynamic_index_in_dim(
            cache.scale, layer, axis=0, keepdims=False
        )
        ctx = dequant_layer_slice(
            ctx, layer_sc[block_tables[:, :prefix_blocks]], hk
        )
    u = prefix_blocks * bs
    kp = ctx[:, :, 0].reshape(r_rows, u, hk, d)
    vp = ctx[:, :, 1].reshape(r_rows, u, hk, d)
    rid = jnp.clip(sid, 0, r_rows - 1)
    kp_t = kp[rid]                                # [T, U, Hk, D] own-row prefix
    vp_t = vp[rid]
    sp = jnp.einsum(
        "skgd,sukd->kgsu", qg, kp_t.astype(jnp.float32)
    ) * sm_scale
    if logit_cap is not None:
        sp = softcap(sp, logit_cap)
    slot = jnp.arange(u, dtype=jnp.int32)
    allow_p = (sid[:, None] >= 0) & (slot[None, :] < starts[rid][:, None])
    if window is not None:
        # prefix slot u IS absolute position u; the query's absolute
        # position is its row start plus its offset within the span
        q_pos = starts[rid] + idx - row_offsets[rid]
        allow_p &= (q_pos[:, None] - slot[None, :]) < window
    sp = jnp.where(allow_p[None, None], sp, -jnp.inf)

    scores = jnp.concatenate([sp, sf], axis=-1)   # [Hk, G, T, U+T]
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "kgsu,sukd->skgd", probs[..., :u], vp_t.astype(jnp.float32)
    ) + jnp.einsum(
        "kgst,tkd->skgd", probs[..., u:], v_new[0].astype(jnp.float32)
    )
    return out.reshape(1, t, h, d).astype(q.dtype)


def write_kv_cache_layer(
    cache: jax.Array,    # [L, N, 2, Bs, Hk*D] — the WHOLE paged cache
    layer: jax.Array,    # scalar int32 layer index
    k_new: jax.Array,    # [B, S, Hk, D]
    v_new: jax.Array,    # [B, S, Hk, D]
    slot_idx: jax.Array, # [B, S] int32  flat slot = block_id * Bs + offset; -1 = drop
    block_aligned: bool = False,  # STATIC: rows are Bs-groups, each group
                                  # contiguous from a block-leading slot
    row_tokens: int = 0,  # STATIC: leading tokens written per-row (see below)
) -> jax.Array:
    """Scatter new K/V rows straight into the full multi-layer cache.

    ``row_tokens`` (static) splits the S axis of a ``block_aligned``
    write: the first ``row_tokens`` tokens take the per-row scatter path
    (their slots may sit anywhere in a block) and only the remainder
    takes the block-granular path.  This is the unified mixed-dispatch
    layout: decode rows — one fresh token each at an arbitrary in-block
    offset — lead the flat axis, block-aligned prefill spans follow, and
    the big spans keep the fast write.  ``row_tokens`` must be a block
    multiple so the aligned remainder starts on a span boundary.

    The cache is a scan carry: scattering into it (rather than slicing a
    per-layer view) lets XLA update the buffer in place — the whole-cache
    copy-through-the-loop this replaces dominated decode ITL on TPU.

    With ``block_aligned=True`` (the engine's prefill layout guarantees
    it: chunks start block-aligned and rows are contiguous) the scatter
    collapses to block-granular read-modify-writes: S/Bs big rows instead
    of S small ones (a 2048-token prefill writes 64 block rows per layer,
    not 2048 row scatters — XLA lowers many-small-row scatter to a slow
    sequential loop, which dominated TTFT).  Rows with slot -1 inside a
    partially-valid group keep the EXISTING cache content (the gather+
    select below), honoring the '-1 = drop' contract bit-for-bit.
    Alignment is a caller contract, not data-inspected — callers that
    cannot guarantee it use the default row path.

    For a :class:`QuantKvCache`, the fresh rows are quantized here (one
    scale per row per kv head) and data + scale scatter with the same base
    indices — write-time quantization is what keeps every read path
    (decode kernel, prefill prefix, transfer) a plain rescale.
    """
    if block_aligned and 0 < row_tokens < k_new.shape[1]:
        cache = write_kv_cache_layer(
            cache, layer, k_new[:, :row_tokens], v_new[:, :row_tokens],
            slot_idx[:, :row_tokens], block_aligned=False,
        )
        return write_kv_cache_layer(
            cache, layer, k_new[:, row_tokens:], v_new[:, row_tokens:],
            slot_idx[:, row_tokens:], block_aligned=True,
        )
    if block_aligned and row_tokens >= k_new.shape[1]:
        block_aligned = False  # everything is row-path tokens
    if is_quant(cache):
        b, s, hk, d = k_new.shape
        kq, ks = quantize_kv_rows(k_new)
        vq, vs = quantize_kv_rows(v_new)
        return QuantKvCache(
            _write_layer_rows(cache.data, layer,
                              kq.reshape(b, s, hk * d),
                              vq.reshape(b, s, hk * d),
                              slot_idx, block_aligned),
            _write_layer_scales(cache.scale, layer, ks, vs,
                                slot_idx, block_aligned,
                                bs=cache.data.shape[3]),
        )
    b, s, hk, d = k_new.shape
    return _write_layer_rows(
        cache, layer,
        k_new.astype(cache.dtype).reshape(b, s, hk * d),
        v_new.astype(cache.dtype).reshape(b, s, hk * d),
        slot_idx, block_aligned,
    )


def _write_layer_rows(
    cache: jax.Array,    # [L, N, 2, Bs, R] — R = Hk*D (data) or Hk (scales)
    layer: jax.Array,
    rows_k: jax.Array,   # [B, S, R]
    rows_v: jax.Array,   # [B, S, R]
    slot_idx: jax.Array,
    block_aligned: bool,
) -> jax.Array:
    l, n, two, bs, r = cache.shape
    b, s, _ = rows_k.shape
    rows_k = rows_k.astype(cache.dtype)
    rows_v = rows_v.astype(cache.dtype)
    if block_aligned and s > 1 and s % bs == 0:
        nb = s // bs
        size = l * n * 2  # one-past-the-end: truly dropped by mode="drop"
        first = slot_idx[:, ::bs]                     # [B, nb] block-leading slot
        bid = jnp.where(first >= 0, first // bs, -1)  # [B, nb]
        flat = cache.reshape(size, bs, r)
        base = layer * (n * 2) + bid * 2              # K row of (layer, bid)
        # NOTE: the drop sentinel must be OUT OF BOUNDS (size), never -1 —
        # scatter wraps negative indices like numpy, so -1 would silently
        # corrupt the LAST cache row with padding K/V
        base = jnp.where(bid >= 0, base, size).reshape(-1)
        valid = (slot_idx >= 0).reshape(b * nb, bs, 1)
        gk = rows_k.reshape(b * nb, bs, r)
        gv = rows_v.reshape(b * nb, bs, r)
        # read-modify-write: padding rows inside a partial block preserve
        # the existing cache bytes instead of clobbering them with K/V of
        # padding tokens
        cur_k = flat[jnp.minimum(base, size - 1)]
        cur_v = flat[jnp.minimum(base + 1, size - 1)]
        flat = flat.at[base].set(jnp.where(valid, gk, cur_k), mode="drop")
        flat = flat.at[jnp.where(base < size, base + 1, size)].set(
            jnp.where(valid, gv, cur_v), mode="drop"
        )
        return flat.reshape(cache.shape)
    size = l * n * 2 * bs
    flat = cache.reshape(size, r)
    idx = slot_idx.reshape(-1)
    valid = idx >= 0
    # row for (layer, block=idx//bs, kv, offset=idx%bs) in the flat view
    base = layer * (n * 2 * bs) + (idx // bs) * (2 * bs) + idx % bs
    # OOB sentinel, NOT -1: scatter wraps negative indices (see above)
    k_idx = jnp.where(valid, base, size)
    v_idx = jnp.where(valid, base + bs, size)
    flat = flat.at[k_idx].set(rows_k.reshape(-1, r), mode="drop")
    flat = flat.at[v_idx].set(rows_v.reshape(-1, r), mode="drop")
    return flat.reshape(cache.shape)


def _write_layer_scales(
    scale: jax.Array,     # [L, N, 2, Hp, Sp] f32 (token-minor, tile-padded)
    layer: jax.Array,
    ks: jax.Array,        # [B, S, Hk] per-token K scales
    vs: jax.Array,        # [B, S, Hk]
    slot_idx: jax.Array,  # [B, S]
    block_aligned: bool,
    bs: int,              # block size (tokens) — Sp is padded, so not derivable
) -> jax.Array:
    """Scatter per-token scales into the token-minor scale pool (mirrors
    the data writes in :func:`_write_layer_rows`, index-for-index).  Only
    the valid [:Hk, :Bs] region of each block's padded tile is written."""
    l, n, two, hp, sp = scale.shape
    b, s, hk = ks.shape
    ks = ks.astype(scale.dtype)
    vs = vs.astype(scale.dtype)
    if block_aligned and s > 1 and s % bs == 0:
        nb = s // bs
        size = l * n * 2
        first = slot_idx[:, ::bs]
        bid = jnp.where(first >= 0, first // bs, -1)
        flat = scale.reshape(size, hp, sp)
        base = layer * (n * 2) + bid * 2
        base = jnp.where(bid >= 0, base, size).reshape(-1)
        valid = (slot_idx >= 0).reshape(b * nb, 1, bs)
        # [B, nb, Bs, Hk] -> [B*nb, Hk, Bs] (token-minor tiles)
        gk = jnp.swapaxes(ks.reshape(b * nb, bs, hk), 1, 2)
        gv = jnp.swapaxes(vs.reshape(b * nb, bs, hk), 1, 2)
        cur_k = flat[jnp.minimum(base, size - 1)]
        cur_v = flat[jnp.minimum(base + 1, size - 1)]
        # fold the new tile into the current padded tile: pad lanes/rows
        # keep their existing bytes, padding tokens keep cur
        new_k = cur_k.at[:, :hk, :bs].set(
            jnp.where(valid, gk, cur_k[:, :hk, :bs]))
        new_v = cur_v.at[:, :hk, :bs].set(
            jnp.where(valid, gv, cur_v[:, :hk, :bs]))
        flat = flat.at[base].set(new_k, mode="drop")
        flat = flat.at[jnp.where(base < size, base + 1, size)].set(
            new_v, mode="drop"
        )
        return flat.reshape(scale.shape)
    size = l * n * 2
    flat = scale.reshape(size, hp, sp)
    idx = slot_idx.reshape(-1)
    valid = idx >= 0
    row = layer * (n * 2) + (idx // bs) * 2
    lane = idx % bs
    row_k = jnp.where(valid, row, size)
    row_v = jnp.where(valid, row + 1, size)
    flat = flat.at[row_k, :hk, lane].set(ks.reshape(-1, hk), mode="drop")
    flat = flat.at[row_v, :hk, lane].set(vs.reshape(-1, hk), mode="drop")
    return flat.reshape(scale.shape)


def write_kv_cache(
    k_cache: jax.Array,  # [N, Bs, Hk, D]  block pool
    v_cache: jax.Array,  # [N, Bs, Hk, D]
    k_new: jax.Array,    # [B, S, Hk, D]   fresh keys for the new tokens
    v_new: jax.Array,    # [B, S, Hk, D]
    slot_idx: jax.Array, # [B, S] int32    flat slot = block_id * Bs + offset; -1 = drop (padding)
) -> tuple[jax.Array, jax.Array]:
    """Scatter new K/V rows into the paged cache.  Negative slots (padding
    tokens) are remapped to an out-of-bounds sentinel and dropped —
    scatter WRAPS negative indices like numpy, so -1 itself would write
    the pool's last slot."""
    n, bs, hk, d = k_cache.shape
    flat_idx = slot_idx.reshape(-1)
    flat_idx = jnp.where(flat_idx >= 0, flat_idx, n * bs)
    k_flat = k_cache.reshape(n * bs, hk, d).at[flat_idx].set(
        k_new.astype(k_cache.dtype).reshape(-1, hk, d), mode="drop"
    )
    v_flat = v_cache.reshape(n * bs, hk, d).at[flat_idx].set(
        v_new.astype(v_cache.dtype).reshape(-1, hk, d), mode="drop"
    )
    return k_flat.reshape(n, bs, hk, d), v_flat.reshape(n, bs, hk, d)


def paged_attention(
    q: jax.Array,            # [B, S, H, D]
    k_cache: jax.Array,      # [N, Bs, Hk, D]
    v_cache: jax.Array,      # [N, Bs, Hk, D]
    block_tables: jax.Array, # [B, M] int32 (entries past the sequence end may be any valid id)
    seq_lens: jax.Array,     # [B] int32 — context length including the new tokens
    positions: jax.Array,    # [B, S] int32 — absolute position of each query token
    sm_scale: float | None = None,
    logit_cap: float | None = None,
    window: int | None = None,
) -> jax.Array:
    """Attention of S new tokens against their sequence's paged context.

    Causal by absolute position: query at position p sees cache slots
    0..p (the new tokens' K/V must already be in the cache — call
    :func:`write_kv_cache` first).  ``window`` adds sliding-window
    masking (Mistral/Phi3): slot j additionally needs p − j < window.
    Returns [B, S, H, D].
    """
    b, s, h, d = q.shape
    _, bs, hk, _ = k_cache.shape
    m = block_tables.shape[1]
    t = m * bs
    g = h // hk
    if sm_scale is None:
        sm_scale = 1.0 / (d**0.5)

    # Gather each sequence's context: [B, M, Bs, Hk, D] -> [B, T, Hk, D]
    k_ctx = k_cache[block_tables].reshape(b, t, hk, d)
    v_ctx = v_cache[block_tables].reshape(b, t, hk, d)

    qg = q.reshape(b, s, hk, g, d).astype(jnp.float32)
    scores = jnp.einsum("bskgd,btkd->bkgst", qg, k_ctx.astype(jnp.float32)) * sm_scale
    if logit_cap is not None:
        scores = softcap(scores, logit_cap)

    # mask: slot j visible iff j <= position(query) and j < seq_len
    slot = jnp.arange(t, dtype=jnp.int32)
    lens = jnp.maximum(seq_lens, 1)  # keep padded rows numerically sane
    visible = (slot[None, None, :] <= positions[:, :, None]) & (
        slot[None, None, :] < lens[:, None, None]
    )  # [B, S, T]
    if window is not None:
        # sliding window: the last `window` positions only (HF semantics:
        # attend iff q_pos − k_pos < window)
        visible &= (positions[:, :, None] - slot[None, None, :]) < window
    scores = jnp.where(visible[:, None, None, :, :], scores, -jnp.inf)

    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgst,btkd->bskgd", probs, v_ctx.astype(jnp.float32))
    return out.reshape(b, s, h, d).astype(q.dtype)
