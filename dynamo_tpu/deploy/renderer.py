"""DynamoTpuDeployment spec → Kubernetes manifests.

Reference parity: deploy/dynamo/operator/api/v1alpha1/dynamodeployment_types.go:31
(DynamoDeployment CRD → per-service DynamoNimDeployment → Deployments,
Services, ingress, autoscaling) and the helm charts under deploy/.

The TPU translation: instead of `nvidia.com/gpu` resources and the GPU
operator, workers request `google.com/tpu` chips on GKE TPU node pools
(nodeSelector `cloud.google.com/gke-tpu-accelerator` + `-topology`), the
coordinator replaces etcd+NATS as one lightweight Deployment, and
multi-host slices map to one worker Deployment per slice with
`hostNetwork` ICI reachability.

Spec shape (YAML):

    name: llama-disagg
    namespace: default
    image: dynamo-tpu:latest
    coordinator: {}                      # optional overrides
    frontend: {replicas: 1, port: 8080}
    services:
      decode:
        command: ["dynamo-tpu", "run", "in=dyn://dynamo.decode.generate", "out=tpu"]
        replicas: 2
        tpu: {type: v5e, topology: "2x2", chips: 4}
      prefill:
        command: [...]
        replicas: 4
        tpu: {type: v5e, topology: "1x1", chips: 1}
"""

from __future__ import annotations

from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Optional

import yaml

__all__ = ["DeploymentSpec", "render_manifests", "render_to_dir"]

_TPU_ACCEL_LABEL = "cloud.google.com/gke-tpu-accelerator"
_TPU_TOPO_LABEL = "cloud.google.com/gke-tpu-topology"
_TPU_RESOURCE = "google.com/tpu"

_ACCELERATOR_NAMES = {
    "v4": "tpu-v4-podslice",
    "v5e": "tpu-v5-lite-podslice",
    "v5p": "tpu-v5p-slice",
    "v6e": "tpu-v6e-slice",
}


@dataclass
class ServiceSpec:
    name: str
    command: list[str]
    replicas: int = 1
    tpu_type: Optional[str] = None      # v4 | v5e | v5p | v6e
    tpu_topology: Optional[str] = None  # e.g. "2x2"
    tpu_chips: int = 0
    env: dict[str, str] = field(default_factory=dict)
    port: Optional[int] = None
    # queue-depth autoscale (planner-lite; the reference only documents
    # its Planner, docs/architecture.md:47): {min, max, target_per_replica,
    # queue?}.  The operator levels replicas toward
    # ceil(depth / target_per_replica) within [min, max]; ``queue``
    # defaults to the service's dyn:// namespace prefill queue.
    autoscale: Optional[dict] = None


@dataclass
class DeploymentSpec:
    name: str
    image: str
    namespace: str = "default"
    services: list[ServiceSpec] = field(default_factory=list)
    frontend_port: int = 8080
    frontend_replicas: int = 1
    coordinator_port: int = 6180
    metrics_port: int = 9091

    @classmethod
    def from_yaml(cls, path_or_text: str | Path) -> "DeploymentSpec":
        p = Path(path_or_text)
        text = p.read_text() if p.exists() else str(path_or_text)
        return cls.from_dict(yaml.safe_load(text))

    @classmethod
    def from_dict(cls, d: dict) -> "DeploymentSpec":
        services = []
        for name, s in (d.get("services") or {}).items():
            tpu = s.get("tpu") or {}
            services.append(
                ServiceSpec(
                    name=name,
                    command=list(s["command"]),
                    replicas=int(s.get("replicas", 1)),
                    tpu_type=tpu.get("type"),
                    tpu_topology=tpu.get("topology"),
                    tpu_chips=int(tpu.get("chips", 0)),
                    env={k: str(v) for k, v in (s.get("env") or {}).items()},
                    port=s.get("port"),
                    autoscale=s.get("autoscale"),
                )
            )
        fe = d.get("frontend") or {}
        return cls(
            name=d["name"],
            image=d["image"],
            namespace=d.get("namespace", "default"),
            services=services,
            frontend_port=int(fe.get("port", 8080)),
            frontend_replicas=int(fe.get("replicas", 1)),
            coordinator_port=int((d.get("coordinator") or {}).get("port", 6180)),
            metrics_port=int((d.get("metrics") or {}).get("port", 9091)),
        )


def _labels(spec: DeploymentSpec, component: str) -> dict:
    return {
        "app.kubernetes.io/name": "dynamo-tpu",
        "app.kubernetes.io/instance": spec.name,
        "app.kubernetes.io/component": component,
    }


def _deployment(
    spec: DeploymentSpec,
    component: str,
    command: list[str],
    replicas: int,
    env: dict[str, str],
    port: Optional[int] = None,
    svc: Optional[ServiceSpec] = None,
) -> dict:
    labels = _labels(spec, component)
    container: dict[str, Any] = {
        "name": component,
        "image": spec.image,
        "command": command,
        "env": [{"name": k, "value": v} for k, v in env.items()],
    }
    if port:
        container["ports"] = [{"containerPort": port}]
    pod_spec: dict[str, Any] = {"containers": [container]}
    if svc is not None and svc.tpu_chips > 0:
        container["resources"] = {
            "requests": {_TPU_RESOURCE: str(svc.tpu_chips)},
            "limits": {_TPU_RESOURCE: str(svc.tpu_chips)},
        }
        selector: dict[str, str] = {}
        if svc.tpu_type:
            selector[_TPU_ACCEL_LABEL] = _ACCELERATOR_NAMES.get(
                svc.tpu_type, svc.tpu_type
            )
        if svc.tpu_topology:
            selector[_TPU_TOPO_LABEL] = svc.tpu_topology
        if selector:
            pod_spec["nodeSelector"] = selector
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {
            "name": f"{spec.name}-{component}",
            "namespace": spec.namespace,
            "labels": labels,
        },
        "spec": {
            "replicas": replicas,
            "selector": {"matchLabels": labels},
            "template": {
                "metadata": {"labels": labels},
                "spec": pod_spec,
            },
        },
    }


def _service(spec: DeploymentSpec, component: str, port: int) -> dict:
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {
            "name": f"{spec.name}-{component}",
            "namespace": spec.namespace,
            "labels": _labels(spec, component),
        },
        "spec": {
            "selector": _labels(spec, component),
            "ports": [{"port": port, "targetPort": port}],
        },
    }


def render_manifests(spec: DeploymentSpec) -> list[dict]:
    """All k8s objects for a deployment: coordinator, frontend, metrics,
    and one Deployment per worker service."""
    coord_url = f"tcp://{spec.name}-coordinator.{spec.namespace}.svc:{spec.coordinator_port}"
    base_env = {"DYNTPU_COORDINATOR": coord_url}

    out = [
        _deployment(
            spec, "coordinator",
            ["dynamo-tpu", "coordinator", "--port", str(spec.coordinator_port)],
            1, {}, port=spec.coordinator_port,
        ),
        _service(spec, "coordinator", spec.coordinator_port),
        _deployment(
            spec, "frontend",
            ["dynamo-tpu", "http", "--host", "0.0.0.0",
             "--http-port", str(spec.frontend_port),
             "--coordinator", coord_url],
            spec.frontend_replicas, base_env, port=spec.frontend_port,
        ),
        _service(spec, "frontend", spec.frontend_port),
        _deployment(
            spec, "metrics",
            ["dynamo-tpu", "metrics", "--host", "0.0.0.0",
             "--port", str(spec.metrics_port), "--coordinator", coord_url],
            1, base_env, port=spec.metrics_port,
        ),
        _service(spec, "metrics", spec.metrics_port),
    ]
    for svc in spec.services:
        env = dict(base_env)
        env.update(svc.env)
        cmd = list(svc.command)
        if "--coordinator" not in cmd:
            cmd += ["--coordinator", coord_url]
        out.append(
            _deployment(spec, svc.name, cmd, svc.replicas, env, port=svc.port, svc=svc)
        )
        if svc.port:
            out.append(_service(spec, svc.name, svc.port))
    return out


def render_to_dir(spec: DeploymentSpec, out_dir: str | Path) -> list[Path]:
    """Write one YAML file per object; returns the paths."""
    out = Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)
    paths = []
    for obj in render_manifests(spec):
        kind = obj["kind"].lower()
        name = obj["metadata"]["name"]
        p = out / f"{name}-{kind}.yaml"
        p.write_text(yaml.safe_dump(obj, sort_keys=False))
        paths.append(p)
    return paths
