"""Packaged serving graphs: build / inspect / unpack (the "bento" flow).

Reference parity: the reference's api-store + CLI package a serving graph
(code + config + manifest) into a versioned archive that the operator and
``dynamo serve`` deploy from (deploy/dynamo/api-store, ~3k LoC Postgres +
S3).  TPU-native lean shape: a deterministic tar.gz of the graph's Python
sources plus a JSON manifest, stored versioned in the api-store's sqlite
(components/api_store.py) — weights do NOT ride in the package (they live
in the model store / dyn://models, which workers already pull from).

A package contains:

  manifest.json       {"format": 1, "name", "entry": "module:Service",
                       "files": {relpath: sha256}}
  src/<relpath...>    the graph's source tree (python + yaml configs)

``unpack_package`` verifies every hash and refuses path traversal, then
returns the src root — add it to sys.path / PYTHONPATH and hand
``manifest["entry"]`` to ServeSupervisor (cli: ``dynamo-tpu serve
--package name[:version]``).
"""

from __future__ import annotations

import gzip
import hashlib
import io
import json
import shutil
import tarfile
from pathlib import Path
from typing import Optional

__all__ = ["build_package", "read_manifest", "unpack_package",
           "cached_unpack", "PackageError"]

FORMAT = 1
# what rides in a package: graph code + configs, nothing else (weights
# go through the model store; caches/VCS noise never ship)
_INCLUDE_SUFFIXES = {".py", ".yaml", ".yml", ".json", ".txt", ".md"}
_SKIP_PARTS = {"__pycache__", ".git", ".locks"}


class PackageError(ValueError):
    """Malformed, unverifiable, or unsafe package archive."""


def _iter_files(root: Path):
    for p in sorted(root.rglob("*")):
        if not p.is_file():
            continue
        rel = p.relative_to(root)
        if _SKIP_PARTS.intersection(rel.parts):
            continue
        if p.suffix.lower() in _INCLUDE_SUFFIXES:
            yield rel.as_posix(), p


def build_package(src_dir: str | Path, entry: str, name: str,
                  out_path: str | Path) -> dict:
    """Archive ``src_dir``'s graph sources into ``out_path`` (tar.gz).

    ``entry`` is the serve target relative to the package root, e.g.
    ``graphs.agg:Frontend`` — validated for shape here and resolved at
    deploy time (the build host may lack the runtime deps).  Returns the
    manifest.  The archive is deterministic (sorted members, zeroed
    mtimes) so re-building unchanged sources yields identical bytes —
    version bumps in the store then reflect real changes.
    """
    src = Path(src_dir)
    if not src.is_dir():
        raise PackageError(f"source dir {src} does not exist")
    if ":" not in entry:
        raise PackageError(
            f"entry {entry!r} must be 'module:Service' (relative to the "
            "package root)")
    files: dict[str, str] = {}
    members: list[tuple[str, Path]] = []
    for rel, p in _iter_files(src):
        files[rel] = hashlib.sha256(p.read_bytes()).hexdigest()
        members.append((rel, p))
    if not files:
        raise PackageError(f"no packageable sources under {src}")
    mod = entry.partition(":")[0]
    cand = mod.replace(".", "/")
    if f"{cand}.py" not in files and not any(
            r.startswith(f"{cand}/") for r in files):
        raise PackageError(
            f"entry module {mod!r} not found in the package sources")
    # no timestamp in the archive: the api-store stamps created_at on
    # push, and a build-time stamp would break byte-determinism
    manifest = {
        "format": FORMAT, "name": name, "entry": entry, "files": files,
    }
    out = Path(out_path)
    out.parent.mkdir(parents=True, exist_ok=True)
    # mtime=0 + filename="" in the gzip header: tarfile's "w:gz" stamps
    # build time there, and GzipFile embeds the OUTPUT filename from the
    # fileobj — both would break the byte-determinism promised above
    with open(out, "wb") as fh, \
            gzip.GzipFile(fileobj=fh, mode="wb", mtime=0,
                          filename="") as gz, \
            tarfile.open(fileobj=gz, mode="w") as tf:
        mdata = json.dumps(manifest, sort_keys=True).encode()
        info = tarfile.TarInfo("manifest.json")
        info.size = len(mdata)
        tf.addfile(info, io.BytesIO(mdata))
        for rel, p in members:
            info = tarfile.TarInfo(f"src/{rel}")
            data = p.read_bytes()
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))
    return manifest


def _open_archive(pkg) -> tarfile.TarFile:
    """Archive from a path OR raw bytes (the api-store keeps archives as
    sqlite blobs and never touches disk)."""
    try:
        if isinstance(pkg, (bytes, bytearray)):
            return tarfile.open(fileobj=io.BytesIO(pkg), mode="r:gz")
        return tarfile.open(pkg, "r:gz")
    except (tarfile.TarError, OSError) as e:
        raise PackageError(f"not a package archive: {e}") from None


def _load_manifest(tf: tarfile.TarFile) -> dict:
    try:
        f = tf.extractfile("manifest.json")
        manifest = json.loads(f.read())
    except KeyError:
        raise PackageError("archive has no manifest.json") from None
    except (ValueError, AttributeError) as e:
        # invalid JSON, or a directory member (extractfile -> None) —
        # both must surface as a 422-able PackageError, not a 500
        raise PackageError(f"bad manifest.json: {e}") from None
    _check_manifest(manifest)
    return manifest


def read_manifest(pkg) -> dict:
    """The manifest of a package archive (path or bytes), validated."""
    with _open_archive(pkg) as tf:
        return _load_manifest(tf)


def _check_manifest(manifest: dict) -> None:
    if not isinstance(manifest, dict) or manifest.get("format") != FORMAT:
        raise PackageError(f"unsupported package format: "
                           f"{manifest.get('format')!r}")
    for k in ("name", "entry", "files"):
        if not manifest.get(k):
            raise PackageError(f"manifest missing {k!r}")
    for rel in manifest["files"]:
        parts = Path(rel).parts
        if Path(rel).is_absolute() or ".." in parts:
            raise PackageError(f"manifest path escapes the package: {rel!r}")


def unpack_package(pkg_path: str | Path, dest: str | Path) -> tuple[dict, Path]:
    """Extract a package into ``dest`` (hash-verified, traversal-safe).

    Returns ``(manifest, src_root)``; put ``src_root`` on sys.path /
    PYTHONPATH and serve ``manifest['entry']``.
    """
    dest = Path(dest)
    # extract into a sibling temp dir and swap: extracting OVER an
    # existing dest would leave stale files from a prior unpack on the
    # importable src root — code outside the verified package
    tmp = dest.with_name(dest.name + ".extract-tmp")
    if tmp.exists():
        shutil.rmtree(tmp)
    with _open_archive(pkg_path) as tf:
        manifest = _load_manifest(tf)
        src_root = tmp / "src"
        for rel, want_sha in manifest["files"].items():
            member = f"src/{rel}"
            try:
                data = tf.extractfile(member).read()
            except (KeyError, AttributeError):
                raise PackageError(f"archive missing {member!r}") from None
            got = hashlib.sha256(data).hexdigest()
            if got != want_sha:
                raise PackageError(
                    f"hash mismatch for {rel!r}: manifest {want_sha[:12]} "
                    f"vs archive {got[:12]}")
            target = src_root / rel
            # rel was validated non-escaping, but belt-and-braces against
            # symlinked intermediates
            if not str(target.resolve()).startswith(str(tmp.resolve())):
                raise PackageError(f"unsafe extraction path {rel!r}")
            target.parent.mkdir(parents=True, exist_ok=True)
            target.write_bytes(data)
    (tmp / "manifest.json").write_text(json.dumps(manifest, sort_keys=True))
    if dest.exists():
        shutil.rmtree(dest)
    dest.parent.mkdir(parents=True, exist_ok=True)
    tmp.rename(dest)
    return manifest, dest / "src"


def cache_lookup(cache_root: str | Path, name: str,
                 version: int) -> Optional[tuple[dict, Path]]:
    """An existing verified unpack of (name, version), or None.  Lets
    callers skip the archive transfer entirely on a cache hit."""
    dest = Path(cache_root) / f"{name}-{version}"
    mf = dest / "manifest.json"
    if not mf.exists():
        return None
    try:
        manifest = json.loads(mf.read_text())
        _check_manifest(manifest)
        return manifest, dest / "src"
    except (ValueError, PackageError):
        return None  # damaged cache: caller re-extracts


def cached_unpack(pkg_path: str | Path, cache_root: str | Path,
                  name: str, version: int) -> tuple[dict, Path]:
    """Unpack into the per-(name, version) cache dir, reusing an existing
    verified unpack (the model-store cache idiom).  ``version`` is
    required: an unversioned "latest" cache dir would pin the first pull
    forever across newer pushes."""
    hit = cache_lookup(cache_root, name, version)
    if hit is not None:
        return hit
    return unpack_package(pkg_path, Path(cache_root) / f"{name}-{version}")
