"""Deployment tooling: k8s manifest rendering for TPU serving graphs.

Reference parity (lite): deploy/dynamo/operator (Go CRD controller turning
DynamoDeployment specs into per-service Deployments/Services) — here a
renderer that turns the same shape of spec into manifests directly, built
for GKE TPU node pools instead of GPU operators.
"""

from dynamo_tpu.deploy.renderer import DeploymentSpec, render_manifests

__all__ = ["DeploymentSpec", "render_manifests"]
