"""Operator-lite: a watch/reconcile loop over DynamoTpuDeployment specs.

Reference parity: deploy/dynamo/operator (DynamoDeployment CRD +
controller reconcilers, api/v1alpha1/dynamodeployment_types.go:31).  The
full reference operator is ~10k lines of kubebuilder Go; this is the same
control loop in its TPU-native shape:

  desired  = render_manifests(spec)   for every registered spec
  actual   = cluster.list(owner=operator)
  apply    = creates + updates (spec hash changed) ; prune = deletes

The cluster side is pluggable: :class:`KubectlCluster` shells out to
``kubectl`` (real clusters), :class:`MemoryCluster` applies to an
in-memory object store (tests, dry runs).  Specs arrive via
:meth:`Operator.set_spec` / :meth:`delete_spec`, or from a watched
directory of YAML files (the CRD-watch stand-in), and the loop levels
actual state toward desired on every tick — create, scale, and delete all
fall out of the same diff.

With a coordinator connection the operator goes beyond the reference's
controller: per-deployment ``status`` phases are derived from LIVE worker
registrations (the dyn:// endpoint each service's command names —
Pending/Degraded/Ready, Unknown when unobservable), and services with an
``autoscale`` block scale on one of two signals, BOTH delegated to the
shared planner policy (dynamo_tpu/planner/policy.py — the reference
Planner's decision kernel, docs/architecture.md:47; the sdk supervisor
actuates the same functions locally):

  * ``signal: queue`` (default) — remote-prefill queue depth: replicas
    level toward ceil(depth / target_per_replica)
    (planner.policy.prefill_replica_target).
  * ``signal: decode`` — decode-side saturation from the live metrics
    plane ({ns}.kv_metrics.*, the same ForwardPassMetrics the KV router
    schedules on): per-worker max(slot usage, KV-block usage) averaged
    over the REPORTING workers, levelled toward ``target_usage``
    (default 0.7) with the HPA formula ceil(reporting × usage / target);
    reporting < registered holds current replicas
    (planner.policy.decode_replica_target).

Both clamp to [min, max]; levelling is planner.policy.step_replicas —
scale up immediately, down one step per tick.
"""

from __future__ import annotations

import asyncio
import copy
import hashlib
import json
import logging
import re
import subprocess
import time
from pathlib import Path
from typing import Optional, Protocol

import yaml

from dynamo_tpu.deploy.renderer import DeploymentSpec, ServiceSpec, render_manifests
from dynamo_tpu.planner import policy as planner_policy

log = logging.getLogger("dynamo_tpu.operator")

__all__ = ["Operator", "MemoryCluster", "KubectlCluster", "obj_key"]

_DYN_RX = re.compile(r"dyn://([\w-]+)\.([\w-]+)\.([\w-]+)")


def _dyn_target(svc: ServiceSpec) -> Optional[tuple[str, str, str]]:
    """(namespace, component, endpoint) a worker service registers under,
    parsed from the dyn:// URL in its command — the link between the k8s
    object and the live coordinator registration."""
    for arg in svc.command:
        m = _DYN_RX.search(arg)
        if m:
            return m.group(1), m.group(2), m.group(3)
    return None

OWNER_ANNOTATION = "dynamo-tpu.dev/owned-by"
HASH_ANNOTATION = "dynamo-tpu.dev/spec-hash"


def obj_key(obj: dict) -> tuple[str, str, str]:
    """(kind, namespace, name) identity of a manifest."""
    md = obj.get("metadata", {})
    return (obj.get("kind", ""), md.get("namespace", "default"), md.get("name", ""))


def _hash(obj: dict) -> str:
    return hashlib.sha256(
        json.dumps(obj, sort_keys=True, separators=(",", ":")).encode()
    ).hexdigest()[:16]


class Cluster(Protocol):
    def apply(self, obj: dict) -> None: ...
    def delete(self, kind: str, namespace: str, name: str) -> None: ...
    def list_owned(self, owner: str) -> list[dict]: ...


class MemoryCluster:
    """In-memory object store with kubectl-apply semantics — the test
    double for reconcile logic (and a dry-run target)."""

    def __init__(self):
        self.objects: dict[tuple[str, str, str], dict] = {}
        self.ops: list[tuple[str, tuple[str, str, str]]] = []  # audit trail

    def apply(self, obj: dict) -> None:
        key = obj_key(obj)
        self.ops.append(("apply", key))
        self.objects[key] = obj

    def delete(self, kind: str, namespace: str, name: str) -> None:
        self.ops.append(("delete", (kind, namespace, name)))
        self.objects.pop((kind, namespace, name), None)

    def list_owned(self, owner: str) -> list[dict]:
        return [
            o for o in self.objects.values()
            if o.get("metadata", {}).get("annotations", {}).get(OWNER_ANNOTATION)
            == owner
        ]


def _run_kubectl(base: list[str], args: list[str],
                 stdin: Optional[str] = None) -> str:
    """Shared kubectl subprocess wrapper (cluster + CR source)."""
    proc = subprocess.run(
        base + args, input=stdin, capture_output=True, text=True
    )
    if proc.returncode != 0:
        raise RuntimeError(f"kubectl {' '.join(args)}: {proc.stderr.strip()}")
    return proc.stdout


class KubectlCluster:
    """Real-cluster backend via kubectl (no k8s client dependency)."""

    def __init__(self, kubectl: str = "kubectl", context: Optional[str] = None):
        self.base = [kubectl] + (["--context", context] if context else [])

    def _run(self, args: list[str], stdin: Optional[str] = None) -> str:
        return _run_kubectl(self.base, args, stdin)

    def apply(self, obj: dict) -> None:
        self._run(["apply", "-f", "-"], stdin=yaml.safe_dump(obj))

    def delete(self, kind: str, namespace: str, name: str) -> None:
        self._run(["delete", kind, name, "-n", namespace, "--ignore-not-found"])

    def list_owned(self, owner: str) -> list[dict]:
        out = self._run([
            "get", "deployments,services,configmaps", "--all-namespaces",
            "-o", "json",
        ])
        items = json.loads(out).get("items", [])
        return [
            o for o in items
            if o.get("metadata", {}).get("annotations", {}).get(OWNER_ANNOTATION)
            == owner
        ]


CRD_GROUP = "dynamo-tpu.dev"
CRD_PLURAL = "dynamotpudeployments"


def spec_from_cr(obj: dict) -> DeploymentSpec:
    """A DynamoTpuDeployment custom resource → DeploymentSpec (name and
    namespace come from metadata, like the reference CRD)."""
    md = obj.get("metadata", {})
    d = dict(obj.get("spec") or {})
    d.setdefault("name", md.get("name"))
    d.setdefault("namespace", md.get("namespace", "default"))
    return DeploymentSpec.from_dict(d)


class KubectlCrSource:
    """Custom-resource spec source over kubectl (no k8s client dep):
    lists DynamoTpuDeployment objects each tick and writes ``.status``
    back through the status subresource — the reference operator's
    CRD-watch + status-conditions surface (dynamodeployment_types.go:31)
    in poll form."""

    def __init__(self, kubectl: str = "kubectl", context: Optional[str] = None,
                 read_only: bool = False):
        self.base = [kubectl] + (["--context", context] if context else [])
        # dry runs must never write to live CRs
        self.read_only = read_only

    def _run(self, args: list[str], stdin: Optional[str] = None) -> str:
        return _run_kubectl(self.base, args, stdin)

    def list(self) -> list[dict]:
        out = self._run(["get", f"{CRD_PLURAL}.{CRD_GROUP}",
                         "--all-namespaces", "-o", "json"])
        return json.loads(out).get("items", [])

    def patch_status(self, namespace: str, name: str, status: dict) -> None:
        if self.read_only:
            log.info("dry-run: would patch %s/%s status to %s",
                     namespace, name, status)
            return
        self._run([
            "patch", f"{CRD_PLURAL}.{CRD_GROUP}", name, "-n", namespace,
            "--subresource=status", "--type=merge", "-p",
            json.dumps({"status": status}),
        ])


class Operator:
    """The reconcile loop.  One operator instance owns every object it
    created (tracked via an owner annotation), so pruning is safe even
    across restarts — actual state is re-listed from the cluster, never
    remembered."""

    def __init__(self, cluster: Cluster, owner: str = "dynamo-tpu-operator",
                 interval_s: float = 2.0, watch_dir: Optional[str] = None,
                 coordinator=None, cr_source=None):
        self.cluster = cluster
        self.owner = owner
        self.interval_s = interval_s
        self.watch_dir = watch_dir  # rescanned every tick when set
        # optional CoordinatorClient (duck-typed: kv_get_prefix +
        # queue_len): with it the operator reports TRUTHFUL per-deployment
        # phases from live worker registrations and runs queue-depth
        # autoscaling; without it phases are "Unknown" for worker-bearing
        # deployments (the honest answer — it cannot see them)
        self.coordinator = coordinator
        # optional custom-resource source (duck-typed: list() +
        # patch_status()): specs come from DynamoTpuDeployment CRs and
        # the computed status writes back through the status subresource
        self.cr_source = cr_source
        # (deployment name) -> (namespace, cr name) for status patches
        self._cr_ident: dict[str, tuple[str, str]] = {}
        self._pushed_status: dict[str, dict] = {}  # last status per CR
        self.specs: dict[str, DeploymentSpec] = {}
        self.status: dict[str, dict] = {}
        # (deployment, service) -> live registered instance count, filled
        # by observe(); None until the first successful observation
        self.live: Optional[dict[tuple[str, str], int]] = None
        self.queue_depth: dict[tuple[str, str], int] = {}
        # decode-saturation signal: last ForwardPassMetrics per namespace
        # per worker id (fed by a lazy {ns}.kv_metrics.* subscription) and
        # the usage number each decode-autoscaled service last levelled on
        self._metrics: dict[str, dict[int, dict]] = {}
        self._metric_subs: dict[str, int] = {}
        self.decode_usage: dict[tuple[str, str], float] = {}
        # autoscale bookkeeping: the operator's current replica decision
        # and the SPEC FILE's declared replicas per autoscaled service.
        # load_dir re-parses files every tick — without re-applying the
        # decision, each reparse would clobber the scaled value back to
        # the file's (and the resulting perpetual "spec changed" would
        # hot-spin the loop).
        self._scale: dict[tuple[str, str], int] = {}
        self._declared: dict[tuple[str, str], int] = {}
        # last successfully parsed spec name per watched file: a torn read
        # must keep its previous spec, not delete it (see load_dir)
        self._file_spec: dict[str, str] = {}
        self._task: Optional[asyncio.Task] = None
        self._wake = asyncio.Event()
        self._stop = False

    # ------------------------------------------------------------ spec admin
    def _adopt_spec(self, spec: DeploymentSpec) -> None:
        """Install a freshly parsed spec, re-applying any standing
        autoscale decision over the file's declared replicas (clamped to
        the file's current [min, max])."""
        for svc in spec.services:
            if not svc.autoscale:
                continue
            key = (spec.name, svc.name)
            self._declared[key] = svc.replicas
            if key in self._scale:
                lo = int(svc.autoscale.get("min", 1))
                hi = int(svc.autoscale.get("max", max(svc.replicas, lo)))
                svc.replicas = min(hi, max(lo, self._scale[key]))
        self.specs[spec.name] = spec

    def set_spec(self, spec: DeploymentSpec) -> None:
        """Create or update a deployment (CRD upsert analogue)."""
        self._adopt_spec(spec)
        self._wake.set()

    def delete_spec(self, name: str) -> None:
        self.specs.pop(name, None)
        self._wake.set()

    def load_dir(self, path: str | Path) -> None:
        """Sync specs from a directory of YAML files (CRD-watch stand-in):
        files present become specs; specs whose file vanished are deleted.

        A file that fails to PARSE keeps its previous spec: non-atomic
        writers (editors, CI) produce transient torn reads, and treating
        those as deletions would tear down a healthy deployment's objects
        for one reconcile tick and recreate them the next (full pod churn).
        """
        files = sorted(Path(path).glob("*.yaml"))
        before = dict(self.specs)
        seen = set()
        for f in files:
            key = str(f)
            try:
                spec = DeploymentSpec.from_yaml(f)
            except Exception:
                log.exception("bad spec file %s skipped (keeping previous "
                              "spec if any)", f)
                # the file is still present: whatever it last parsed to
                # stays live until it parses again
                prev = self._file_spec.get(key)
                if prev is not None:
                    seen.add(prev)
                continue
            seen.add(spec.name)
            self._file_spec[key] = spec.name
            self._adopt_spec(spec)
        self._file_spec = {
            k: v for k, v in self._file_spec.items() if Path(k).exists()
        }
        for name in [n for n in self.specs if n not in seen]:
            del self.specs[name]
        # wake only on actual change: run() calls load_dir every tick when
        # watch_dir is set, and an unconditional set() would make the
        # interval wait return instantly — a 100%-CPU reconcile hot-spin
        if self.specs != before:
            self._wake.set()

    def load_crs(self) -> None:
        """Sync specs from the custom-resource source (CRD watch in poll
        form): present CRs become specs (autoscale decisions re-applied,
        like load_dir), vanished ones are deleted.  Torn-read rules match
        load_dir at BOTH granularities: an unlistable source keeps every
        current spec, and a CR that transiently fails to PARSE keeps its
        previous spec (tearing down a live deployment's objects over one
        bad read would churn every pod).  Only CR-owned specs are pruned
        — directory-loaded / set_spec specs are never touched."""
        try:
            items = self.cr_source.list()
        except Exception:
            log.exception("CR list failed; keeping current specs")
            return
        before = dict(self.specs)
        seen = set()
        idents: dict[str, tuple[str, str, str]] = {}
        by_ident = {v[:2]: k for k, v in self._cr_ident.items()}
        claimed_ns: dict[str, str] = {}
        for obj in items:
            md = obj.get("metadata", {})
            # uid in the ident: a deleted-and-recreated CR (same ns/name,
            # fresh uid) must invalidate the pushed-status cache — the new
            # object's .status starts empty and needs a write even when
            # the computed status is unchanged
            ident = (md.get("namespace", "default"), md.get("name", ""),
                     str(md.get("uid", "")))
            try:
                spec = spec_from_cr(obj)
            except Exception:
                log.exception("bad DynamoTpuDeployment %s/%s skipped "
                              "(keeping previous spec if any)", *ident[:2])
                prev = by_ident.get(ident[:2])
                if prev is not None:
                    seen.add(prev)
                    idents[prev] = ident
                continue
            if spec.name in claimed_ns and claimed_ns[spec.name] != ident[0]:
                # deployment names must be unique across namespaces (the
                # rendered objects are named from spec.name); a silent
                # last-writer-wins would deploy one and starve the other
                log.error(
                    "DynamoTpuDeployment name collision: %r exists in both "
                    "namespace %s and %s; skipping %s/%s",
                    spec.name, claimed_ns[spec.name], ident[0], *ident[:2],
                )
                continue
            if spec.name in self.specs and spec.name not in self._cr_ident:
                # the name belongs to a dir/set_spec deployment: adopting
                # the CR would hijack it now and tear it down on CR delete
                log.error(
                    "DynamoTpuDeployment %s/%s collides with a non-CR "
                    "deployment spec %r; skipping the CR", *ident[:2],
                    spec.name,
                )
                continue
            claimed_ns[spec.name] = ident[0]
            seen.add(spec.name)
            idents[spec.name] = ident
            self._adopt_spec(spec)
        # prune only specs the CR source OWNS (previously mapped to a CR)
        for name in [n for n in self._cr_ident
                     if n not in seen and n in self.specs]:
            del self.specs[name]
        # pushed-status cache follows CR identity: vanished or recreated
        # (uid change) CRs must be re-pushed from scratch
        for name in list(self._pushed_status):
            if idents.get(name) != self._cr_ident.get(name):
                self._pushed_status.pop(name, None)
        self._cr_ident = idents
        if self.specs != before:
            self._wake.set()

    def push_status(self) -> None:
        """Write each CR's computed status through the status subresource
        (reference parity: status conditions on the CRD).  No-op patches
        are skipped — a steady cluster costs zero apiserver writes per
        tick; a failed patch clears the cache entry so it retries.  The
        merge patch explicitly nulls keys the previous push set that the
        new status dropped (JSON merge-patch otherwise leaves them stale
        on the CR forever); a ``live: None`` (coordinator unobservable)
        likewise merge-deletes the field on the CR."""
        if self.cr_source is None:
            return

        def with_deletes(new, old):
            out = dict(new)
            for k, ov in (old or {}).items():
                if k not in out:
                    out[k] = None  # merge-patch delete of a dropped key
                elif isinstance(ov, dict) and isinstance(out[k], dict):
                    out[k] = with_deletes(out[k], ov)
            return out

        for name, ident in self._cr_ident.items():
            ns, cr_name = ident[0], ident[1]
            st = self.status.get(name)
            if st is None or self._pushed_status.get(name) == st:
                continue
            try:
                self.cr_source.patch_status(
                    ns, cr_name, with_deletes(st, self._pushed_status.get(name))
                )
                self._pushed_status[name] = copy.deepcopy(st)
            except Exception:
                self._pushed_status.pop(name, None)
                log.exception("status patch for %s/%s failed", ns, cr_name)

    # ------------------------------------------------------------ observation
    async def _ensure_metrics_sub(self, ns: str) -> None:
        """Lazily subscribe to a namespace's ForwardPassMetrics subject
        the first time a decode-autoscaled service names it.  The
        coordinator duck needs ``subscribe`` for this signal (the real
        client has it); without it the signal degrades to hold."""
        if ns in self._metric_subs or not hasattr(self.coordinator, "subscribe"):
            return
        from dynamo_tpu.llm.kv_router.publisher import metrics_subject

        store = self._metrics.setdefault(ns, {})

        def on_metrics(subject: str, payload: bytes) -> None:
            try:
                d = json.loads(payload)
                d["_rx"] = time.monotonic()
                store[int(d["worker_id"])] = d
            except Exception:
                log.exception("bad kv_metrics payload on %s", subject)

        self._metric_subs[ns] = await self.coordinator.subscribe(
            metrics_subject(ns), on_metrics
        )

    def _decode_want(self, ns: str, insts: dict, svc: ServiceSpec,
                     auto: dict, lo: int, hi: int):
        """(want, usage) from decode-side saturation, delegated to the
        SHARED planner policy (planner/policy.py decode_replica_target —
        the same formula the planner loop and supervisor actuate on).
        Per registered worker, max(active-slot usage, KV-block usage)
        from its latest fresh ForwardPassMetrics feeds the HPA formula
        ceil(reporting × usage / target).  The policy holds current
        replicas whenever the reporting count falls short of the
        REGISTERED count — no metrics at all, or some workers silent
        (stale publisher, startup lag): scaling on a fresh-only subset
        would shrink the product and act on absence of evidence
        (ADVICE r5).  [min, max] edits still apply on hold."""
        target = float(auto.get("target_usage", 0.7))
        stale = float(auto.get("stale_after_s", 15.0))
        now = time.monotonic()
        ids = []
        for k in insts:
            try:
                ids.append(int(k.rsplit("/", 1)[-1], 16))
            except ValueError:
                continue
        store = self._metrics.get(ns, {})
        usages = []
        for wid in ids:
            m = store.get(wid)
            if not m or now - m.get("_rx", 0.0) > stale:
                continue
            usages.append(planner_policy.WorkerSample(
                worker_id=wid,
                request_active_slots=m.get("request_active_slots", 0),
                request_total_slots=m.get("request_total_slots", 1),
                kv_active_blocks=m.get("kv_active_blocks", 0),
                kv_total_blocks=m.get("kv_total_blocks", 1),
            ).usage)
        return planner_policy.decode_replica_target(
            svc.replicas, len(ids), usages, target, lo, hi)

    async def observe(self) -> None:
        """Refresh live worker counts and autoscale signals from the
        coordinator, and level autoscaled services' replicas toward the
        signal's target within [min, max] — queue depth for prefill
        (``signal: queue``, the default), slot/KV saturation for decode
        (``signal: decode``).

        Scale-up jumps straight to the target (queued work is latency);
        scale-down steps one replica per tick (cheap hysteresis — a
        transiently empty queue must not flap the pool).  Changing
        ``svc.replicas`` changes the rendered Deployment's hash, so the
        next reconcile applies the scale exactly like any spec edit."""
        if self.coordinator is None:
            return
        live: dict[tuple[str, str], int] = {}
        depths: dict[tuple[str, str], int] = {}
        usages: dict[tuple[str, str], float] = {}
        scale: dict[tuple[str, str], int] = {}
        decode_ns: set[str] = set()
        for dep, spec in list(self.specs.items()):
            for svc in spec.services:
                target = _dyn_target(svc)
                if target is None:
                    continue
                ns, comp, ep = target
                prefix = f"{ns}/components/{comp}/endpoints/{ep}/"
                insts = await self.coordinator.kv_get_prefix(prefix)
                live[(dep, svc.name)] = len(insts)
                auto = svc.autoscale
                if not auto:
                    continue
                key = (dep, svc.name)
                lo = int(auto.get("min", 1))
                # default cap = the spec FILE's declared replicas — never
                # the live (possibly scaled-down) value, which would
                # ratchet the ceiling downward and pin scale-up
                hi = int(auto.get(
                    "max", max(self._declared.get(key, svc.replicas), lo)
                ))
                if str(auto.get("signal", "queue")) == "decode":
                    decode_ns.add(ns)
                    await self._ensure_metrics_sub(ns)
                    want, usage = self._decode_want(ns, insts, svc, auto,
                                                    lo, hi)
                    if usage is not None:
                        usages[key] = round(usage, 3)
                    detail = f"usage={usage and round(usage, 3)}"
                else:
                    queue = auto.get("queue") or f"{ns}_prefill_queue"
                    depth = await self.coordinator.queue_len(queue)
                    depths[key] = depth
                    want = planner_policy.prefill_replica_target(
                        depth, svc.replicas,
                        int(auto.get("target_per_replica", 4)), lo, hi)
                    detail = f"queue={depth}"
                new = planner_policy.step_replicas(svc.replicas, want)
                if new != svc.replicas:
                    log.info("autoscale %s/%s: %s -> replicas %d -> %d",
                             dep, svc.name, detail, svc.replicas, new)
                    svc.replicas = new
                scale[key] = svc.replicas
        # fresh maps each pass: deleted deployments / removed autoscale
        # blocks must not leave stale depths or decisions behind.  The
        # metrics plumbing follows the same rule: subscriptions for
        # namespaces no decode-autoscaled service names any more are
        # dropped, and departed workers' stored metrics are evicted once
        # well past any plausible staleness window.
        for ns in [n for n in self._metric_subs if n not in decode_ns]:
            sub = self._metric_subs.pop(ns)
            self._metrics.pop(ns, None)
            if hasattr(self.coordinator, "unsubscribe"):
                try:
                    await self.coordinator.unsubscribe(sub)
                except Exception:
                    log.warning("unsubscribe %s failed", ns, exc_info=True)
        now = time.monotonic()
        for store in self._metrics.values():
            for wid in [w for w, m in store.items()
                        if now - m.get("_rx", 0.0) > 120.0]:
                del store[wid]
        self.live = live
        self.queue_depth = depths
        self.decode_usage = usages
        self._scale = scale
        self._declared = {
            k: v for k, v in self._declared.items() if k in scale
        }

    def _phase(self, spec: DeploymentSpec) -> str:
        """Truthful per-deployment phase from live registrations:
        Ready (every worker service fully registered), Degraded (some),
        Pending (none yet), Unknown (no coordinator to ask).  A
        deployment with no dyn:// worker services has nothing to verify
        beyond object application — Ready."""
        workers = [s for s in spec.services if _dyn_target(s) is not None]
        if not workers:
            return "Ready"
        if self.live is None:
            return "Unknown"
        counts = [self.live.get((spec.name, s.name), 0) for s in workers]
        if all(c >= s.replicas for c, s in zip(counts, workers)):
            return "Ready"
        return "Pending" if sum(counts) == 0 else "Degraded"

    # ------------------------------------------------------------- reconcile
    def desired_objects(self) -> dict[tuple[str, str, str], dict]:
        out: dict[tuple[str, str, str], dict] = {}
        for spec in self.specs.values():
            for obj in render_manifests(spec):
                ann = obj.setdefault("metadata", {}).setdefault("annotations", {})
                ann[OWNER_ANNOTATION] = self.owner
                ann[HASH_ANNOTATION] = _hash(obj)
                out[obj_key(obj)] = obj
        return out

    def reconcile_once(self) -> dict:
        """One level pass: apply creates/changes, prune orphans.  Returns a
        summary {created, updated, deleted, unchanged} and updates
        per-deployment status."""
        desired = self.desired_objects()
        actual = {obj_key(o): o for o in self.cluster.list_owned(self.owner)}
        created = updated = unchanged = 0
        for key, obj in desired.items():
            cur = actual.get(key)
            if cur is None:
                self.cluster.apply(obj)
                created += 1
            elif (
                cur.get("metadata", {}).get("annotations", {}).get(HASH_ANNOTATION)
                != obj["metadata"]["annotations"][HASH_ANNOTATION]
            ):
                self.cluster.apply(obj)
                updated += 1
            else:
                unchanged += 1
        deleted = 0
        for key in [k for k in actual if k not in desired]:
            kind, ns, name = key
            self.cluster.delete(kind, ns, name)
            deleted += 1
        summary = {
            "created": created, "updated": updated,
            "deleted": deleted, "unchanged": unchanged,
        }
        # status per deployment by the rendered instance label (exact —
        # substring matching would double-count "llm" vs "llm-router")
        counts: dict[str, int] = {}
        for o in desired.values():
            inst = o["metadata"].get("labels", {}).get("app.kubernetes.io/instance")
            if inst:
                counts[inst] = counts.get(inst, 0) + 1
        for name, spec in self.specs.items():
            st: dict = {
                "objects": counts.get(name, 0), "phase": self._phase(spec),
            }
            workers = {
                s.name: {
                    "want": s.replicas,
                    "live": (self.live or {}).get((name, s.name)),
                }
                for s in spec.services if _dyn_target(s) is not None
            }
            if workers:
                st["workers"] = workers
            qd = {s: d for (n, s), d in self.queue_depth.items() if n == name}
            if qd:
                st["queue_depth"] = qd
            du = {s: u for (n, s), u in self.decode_usage.items() if n == name}
            if du:
                st["decode_usage"] = du
            self.status[name] = st
        return summary

    # ------------------------------------------------------------------ loop
    async def run(self) -> None:
        """Leveling loop: reconcile on spec changes and every interval
        (drift repair), until stop()."""
        while not self._stop:
            try:
                if self.watch_dir is not None:
                    self.load_dir(self.watch_dir)
                if self.cr_source is not None:
                    self.load_crs()
                try:
                    await self.observe()
                except Exception:
                    # a coordinator outage must NOT halt k8s reconcile:
                    # degrade to Unknown phases and keep levelling objects
                    log.warning("observe failed (coordinator unreachable?); "
                                "phases Unknown this tick", exc_info=True)
                    self.live = None
                self.reconcile_once()
                self.push_status()
            except Exception:
                log.exception("reconcile failed; retrying next tick")
            try:
                await asyncio.wait_for(self._wake.wait(), timeout=self.interval_s)
            except asyncio.TimeoutError:
                pass
            self._wake.clear()

    def start(self) -> "Operator":
        self._task = asyncio.ensure_future(self.run())
        return self

    async def stop(self) -> None:
        self._stop = True
        self._wake.set()
        if self._task is not None:
            await self._task
