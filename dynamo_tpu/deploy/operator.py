"""Operator-lite: a watch/reconcile loop over DynamoTpuDeployment specs.

Reference parity: deploy/dynamo/operator (DynamoDeployment CRD +
controller reconcilers, api/v1alpha1/dynamodeployment_types.go:31).  The
full reference operator is ~10k lines of kubebuilder Go; this is the same
control loop in its TPU-native shape:

  desired  = render_manifests(spec)   for every registered spec
  actual   = cluster.list(owner=operator)
  apply    = creates + updates (spec hash changed) ; prune = deletes

The cluster side is pluggable: :class:`KubectlCluster` shells out to
``kubectl`` (real clusters), :class:`MemoryCluster` applies to an
in-memory object store (tests, dry runs).  Specs arrive via
:meth:`Operator.set_spec` / :meth:`delete_spec`, or from a watched
directory of YAML files (the CRD-watch stand-in), and the loop levels
actual state toward desired on every tick — create, scale, and delete all
fall out of the same diff.
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import logging
import subprocess
from pathlib import Path
from typing import Optional, Protocol

import yaml

from dynamo_tpu.deploy.renderer import DeploymentSpec, render_manifests

log = logging.getLogger("dynamo_tpu.operator")

__all__ = ["Operator", "MemoryCluster", "KubectlCluster", "obj_key"]

OWNER_ANNOTATION = "dynamo-tpu.dev/owned-by"
HASH_ANNOTATION = "dynamo-tpu.dev/spec-hash"


def obj_key(obj: dict) -> tuple[str, str, str]:
    """(kind, namespace, name) identity of a manifest."""
    md = obj.get("metadata", {})
    return (obj.get("kind", ""), md.get("namespace", "default"), md.get("name", ""))


def _hash(obj: dict) -> str:
    return hashlib.sha256(
        json.dumps(obj, sort_keys=True, separators=(",", ":")).encode()
    ).hexdigest()[:16]


class Cluster(Protocol):
    def apply(self, obj: dict) -> None: ...
    def delete(self, kind: str, namespace: str, name: str) -> None: ...
    def list_owned(self, owner: str) -> list[dict]: ...


class MemoryCluster:
    """In-memory object store with kubectl-apply semantics — the test
    double for reconcile logic (and a dry-run target)."""

    def __init__(self):
        self.objects: dict[tuple[str, str, str], dict] = {}
        self.ops: list[tuple[str, tuple[str, str, str]]] = []  # audit trail

    def apply(self, obj: dict) -> None:
        key = obj_key(obj)
        self.ops.append(("apply", key))
        self.objects[key] = obj

    def delete(self, kind: str, namespace: str, name: str) -> None:
        self.ops.append(("delete", (kind, namespace, name)))
        self.objects.pop((kind, namespace, name), None)

    def list_owned(self, owner: str) -> list[dict]:
        return [
            o for o in self.objects.values()
            if o.get("metadata", {}).get("annotations", {}).get(OWNER_ANNOTATION)
            == owner
        ]


class KubectlCluster:
    """Real-cluster backend via kubectl (no k8s client dependency)."""

    def __init__(self, kubectl: str = "kubectl", context: Optional[str] = None):
        self.base = [kubectl] + (["--context", context] if context else [])

    def _run(self, args: list[str], stdin: Optional[str] = None) -> str:
        proc = subprocess.run(
            self.base + args, input=stdin, capture_output=True, text=True
        )
        if proc.returncode != 0:
            raise RuntimeError(f"kubectl {' '.join(args)}: {proc.stderr.strip()}")
        return proc.stdout

    def apply(self, obj: dict) -> None:
        self._run(["apply", "-f", "-"], stdin=yaml.safe_dump(obj))

    def delete(self, kind: str, namespace: str, name: str) -> None:
        self._run(["delete", kind, name, "-n", namespace, "--ignore-not-found"])

    def list_owned(self, owner: str) -> list[dict]:
        out = self._run([
            "get", "deployments,services,configmaps", "--all-namespaces",
            "-o", "json",
        ])
        items = json.loads(out).get("items", [])
        return [
            o for o in items
            if o.get("metadata", {}).get("annotations", {}).get(OWNER_ANNOTATION)
            == owner
        ]


class Operator:
    """The reconcile loop.  One operator instance owns every object it
    created (tracked via an owner annotation), so pruning is safe even
    across restarts — actual state is re-listed from the cluster, never
    remembered."""

    def __init__(self, cluster: Cluster, owner: str = "dynamo-tpu-operator",
                 interval_s: float = 2.0, watch_dir: Optional[str] = None):
        self.cluster = cluster
        self.owner = owner
        self.interval_s = interval_s
        self.watch_dir = watch_dir  # rescanned every tick when set
        self.specs: dict[str, DeploymentSpec] = {}
        self.status: dict[str, dict] = {}
        # last successfully parsed spec name per watched file: a torn read
        # must keep its previous spec, not delete it (see load_dir)
        self._file_spec: dict[str, str] = {}
        self._task: Optional[asyncio.Task] = None
        self._wake = asyncio.Event()
        self._stop = False

    # ------------------------------------------------------------ spec admin
    def set_spec(self, spec: DeploymentSpec) -> None:
        """Create or update a deployment (CRD upsert analogue)."""
        self.specs[spec.name] = spec
        self._wake.set()

    def delete_spec(self, name: str) -> None:
        self.specs.pop(name, None)
        self._wake.set()

    def load_dir(self, path: str | Path) -> None:
        """Sync specs from a directory of YAML files (CRD-watch stand-in):
        files present become specs; specs whose file vanished are deleted.

        A file that fails to PARSE keeps its previous spec: non-atomic
        writers (editors, CI) produce transient torn reads, and treating
        those as deletions would tear down a healthy deployment's objects
        for one reconcile tick and recreate them the next (full pod churn).
        """
        files = sorted(Path(path).glob("*.yaml"))
        before = dict(self.specs)
        seen = set()
        for f in files:
            key = str(f)
            try:
                spec = DeploymentSpec.from_yaml(f)
            except Exception:
                log.exception("bad spec file %s skipped (keeping previous "
                              "spec if any)", f)
                # the file is still present: whatever it last parsed to
                # stays live until it parses again
                prev = self._file_spec.get(key)
                if prev is not None:
                    seen.add(prev)
                continue
            seen.add(spec.name)
            self._file_spec[key] = spec.name
            self.specs[spec.name] = spec
        self._file_spec = {
            k: v for k, v in self._file_spec.items() if Path(k).exists()
        }
        for name in [n for n in self.specs if n not in seen]:
            del self.specs[name]
        # wake only on actual change: run() calls load_dir every tick when
        # watch_dir is set, and an unconditional set() would make the
        # interval wait return instantly — a 100%-CPU reconcile hot-spin
        if self.specs != before:
            self._wake.set()

    # ------------------------------------------------------------- reconcile
    def desired_objects(self) -> dict[tuple[str, str, str], dict]:
        out: dict[tuple[str, str, str], dict] = {}
        for spec in self.specs.values():
            for obj in render_manifests(spec):
                ann = obj.setdefault("metadata", {}).setdefault("annotations", {})
                ann[OWNER_ANNOTATION] = self.owner
                ann[HASH_ANNOTATION] = _hash(obj)
                out[obj_key(obj)] = obj
        return out

    def reconcile_once(self) -> dict:
        """One level pass: apply creates/changes, prune orphans.  Returns a
        summary {created, updated, deleted, unchanged} and updates
        per-deployment status."""
        desired = self.desired_objects()
        actual = {obj_key(o): o for o in self.cluster.list_owned(self.owner)}
        created = updated = unchanged = 0
        for key, obj in desired.items():
            cur = actual.get(key)
            if cur is None:
                self.cluster.apply(obj)
                created += 1
            elif (
                cur.get("metadata", {}).get("annotations", {}).get(HASH_ANNOTATION)
                != obj["metadata"]["annotations"][HASH_ANNOTATION]
            ):
                self.cluster.apply(obj)
                updated += 1
            else:
                unchanged += 1
        deleted = 0
        for key in [k for k in actual if k not in desired]:
            kind, ns, name = key
            self.cluster.delete(kind, ns, name)
            deleted += 1
        summary = {
            "created": created, "updated": updated,
            "deleted": deleted, "unchanged": unchanged,
        }
        # status per deployment by the rendered instance label (exact —
        # substring matching would double-count "llm" vs "llm-router")
        counts: dict[str, int] = {}
        for o in desired.values():
            inst = o["metadata"].get("labels", {}).get("app.kubernetes.io/instance")
            if inst:
                counts[inst] = counts.get(inst, 0) + 1
        for name in self.specs:
            self.status[name] = {
                "objects": counts.get(name, 0), "phase": "Ready",
            }
        return summary

    # ------------------------------------------------------------------ loop
    async def run(self) -> None:
        """Leveling loop: reconcile on spec changes and every interval
        (drift repair), until stop()."""
        while not self._stop:
            try:
                if self.watch_dir is not None:
                    self.load_dir(self.watch_dir)
                self.reconcile_once()
            except Exception:
                log.exception("reconcile failed; retrying next tick")
            try:
                await asyncio.wait_for(self._wake.wait(), timeout=self.interval_s)
            except asyncio.TimeoutError:
                pass
            self._wake.clear()

    def start(self) -> "Operator":
        self._task = asyncio.ensure_future(self.run())
        return self

    async def stop(self) -> None:
        self._stop = True
        self._wake.set()
        if self._task is not None:
            await self._task
