"""Metrics plane end-to-end: workers publish ForwardPassMetrics + KV events
over a real coordinator; the router's subscriber feeds its indexer and
scheduler; the metrics service renders Prometheus text with hit rates.

Mirrors the reference seam (SURVEY §4): mock worker + real local broker →
the whole router/metrics stack tested with no TPU.
"""

from __future__ import annotations

import asyncio

from aiohttp import ClientSession

from dynamo_tpu.components.metrics import MetricsService, PrometheusMetricsCollector
from dynamo_tpu.obs.metric_names import RouterMetric as RM
from dynamo_tpu.components.mock_worker import MockWorker
from dynamo_tpu.llm.kv.events import KvStoredEvent
from dynamo_tpu.llm.kv_router.metrics_aggregator import KvRouterSubscriber
from dynamo_tpu.llm.kv_router.publisher import KvEventPublisher, KvMetricsPublisher
from dynamo_tpu.llm.kv_router.router import KvRouter
from dynamo_tpu.llm.kv_router.scheduler import WorkerMetrics
from dynamo_tpu.runtime.transports.coordinator import CoordinatorClient, CoordinatorServer
from dynamo_tpu.tokens import sequence_hashes

async def _wait_for(cond, timeout=5.0, interval=0.02):
    async def _poll():
        while not cond():
            await asyncio.sleep(interval)

    await asyncio.wait_for(_poll(), timeout)


def test_publisher_to_router_subscriber():
    asyncio.new_event_loop().run_until_complete(_publisher_to_router_subscriber())


async def _publisher_to_router_subscriber():
    server = await CoordinatorServer(port=0).start()
    try:
        wcoord = await CoordinatorClient(server.url).connect()
        rcoord = await CoordinatorClient(server.url).connect()

        router = KvRouter(block_size=16)
        sub = await KvRouterSubscriber(router, rcoord, namespace="t").start()

        # worker 7 publishes stored events + metrics
        pub = KvEventPublisher(wcoord, worker_id=7, namespace="t")
        prompt = list(range(64))
        hashes = sequence_hashes(prompt, 16)
        pub.sink(KvStoredEvent(block_hashes=hashes))
        await pub.flush()

        metrics_pub = KvMetricsPublisher(
            wcoord,
            worker_id=7,
            source=lambda: {
                "request_active_slots": 1,
                "request_total_slots": 8,
                "kv_active_blocks": 4,
                "kv_total_blocks": 64,
            },
            namespace="t",
        )
        await metrics_pub.publish_once()

        await _wait_for(lambda: router.indexer.num_blocks == 4)
        await _wait_for(lambda: 7 in router.scheduler.workers())

        decision = router.schedule(prompt + [9999] * 16)
        assert decision.worker_id == 7
        assert decision.overlap_blocks == 4

        await sub.stop()
        await wcoord.close()
        await rcoord.close()
    finally:
        await server.stop()


def test_mock_workers_feed_metrics_service_prometheus():
    asyncio.new_event_loop().run_until_complete(_mock_workers_feed_metrics())


async def _mock_workers_feed_metrics():
    server = await CoordinatorServer(port=0).start()
    try:
        mcoord = await CoordinatorClient(server.url).connect()
        wcoord = await CoordinatorClient(server.url).connect()
        rcoord = await CoordinatorClient(server.url).connect()

        svc = await MetricsService(mcoord, namespace="t", port=0).start()
        router = KvRouter(block_size=16)
        sub = await KvRouterSubscriber(
            router, rcoord, namespace="t", hit_rate_flush_s=0.05
        ).start()

        w1 = await MockWorker(wcoord, worker_id=1, namespace="t", interval_s=0.05).start()
        w2 = await MockWorker(wcoord, worker_id=2, namespace="t", interval_s=0.05).start()

        # wait until both workers visible to the scheduler and indexer fed
        await _wait_for(lambda: {1, 2} <= set(router.scheduler.workers()))
        await _wait_for(lambda: router.indexer.num_blocks > 0)

        # route a few requests -> hit-rate events flow to the metrics service
        for _ in range(5):
            router.schedule([1] * 32)
        await _wait_for(lambda: svc.collector.hits, timeout=5.0)

        async with ClientSession() as s:
            r = await s.get(f"http://127.0.0.1:{svc.port}/metrics")
            assert r.status == 200
            text = await r.text()
        assert f'{RM.KV_BLOCKS_ACTIVE}{{worker="1"}}' in text
        assert RM.ROUTING_DECISIONS_TOTAL in text
        assert RM.KV_HIT_RATE_PERCENT in text

        await w1.stop()
        await w2.stop()
        await sub.stop()
        await svc.stop()
        for c in (mcoord, wcoord, rcoord):
            await c.close()
    finally:
        await server.stop()


def test_prometheus_collector_render():
    c = PrometheusMetricsCollector()
    c.on_worker_metrics(WorkerMetrics(worker_id=3, kv_active_blocks=10, kv_total_blocks=40))
    c.on_hit_rate_event(3, isl_blocks=8, overlap_blocks=6)
    c.on_hit_rate_event(3, isl_blocks=8, overlap_blocks=2)
    out = c.render()
    assert f'{RM.KV_CACHE_USAGE}{{worker="3"}} 0.250000' in out
    assert f'{RM.ROUTING_DECISIONS_TOTAL}{{worker="3"}} 2' in out
    assert f'{RM.KV_HIT_RATE_PERCENT}{{worker="3"}} 50.000' in out
    c.remove_worker(3)
    assert 'kv_cache_usage{worker="3"}' not in c.render()
