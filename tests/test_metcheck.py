"""Metrics-plane static analysis (dtmet) tests: THE tenth tier-1 gate
(zero non-accepted findings over the extracted producer→renderer→
scraper census against the committed metrics manifest), the census/
registry/docs drift contract, the renamed-counter injection proof, and
each MT001–MT005 rule on bad/good fixtures under tests/lint_fixtures/.
"""

import argparse
import copy
import io
import json
import shutil
import time
from pathlib import Path

import pytest

from dynamo_tpu.analysis.metcheck import (
    DEFAULT_METRICS_MANIFEST_PATH,
    DOCS_BEGIN,
    DOCS_END,
    MET_RULES,
    census_snapshot,
    check_metric_facts,
    collect_metric_facts,
    render_docs_table,
    run_metrics,
)
from dynamo_tpu.analysis.tracecheck import Manifest, TraceFinding
from dynamo_tpu.obs.metric_names import SCHEMA, EngineMetric as EM

ROOT = Path(__file__).resolve().parents[1]
FIXTURES = Path(__file__).parent / "lint_fixtures"

# the fixtures' widget surface, for run_metrics tests where the real
# SCHEMA would drown everything in registry drift
_WIDGET_SCHEMA = {
    "dynamo_tpu_widget_dispatches_total": ("counter", ()),
    "dynamo_tpu_widget_orphaned": ("gauge", ()),
}


def _registry():
    return {name: (typ, list(labels))
            for name, (typ, labels) in SCHEMA.items()}


def _rules(findings):
    return {f.rule for f in findings}


def _fixture_findings(path):
    """Findings for one fixture file with MT005 self-suppressed via a
    census self-snapshot (fixtures test the site rules, not drift)."""
    facts, intrinsic = collect_metric_facts([path], root=FIXTURES)
    manifest = Manifest(entrypoints=census_snapshot(facts))
    return facts, check_metric_facts(facts, manifest, intrinsic)


# ------------------------------------------------------------- the gate ----


@pytest.fixture(scope="module")
def real():
    t0 = time.perf_counter()
    facts, intrinsic = collect_metric_facts()
    elapsed = time.perf_counter() - t0
    docs_text = (ROOT / "docs" / "observability.md").read_text()
    return facts, intrinsic, docs_text, elapsed


def _real_findings(real, manifest):
    facts, intrinsic, docs_text, _ = real
    return check_metric_facts(facts, manifest, intrinsic,
                              registry=_registry(), docs_text=docs_text)


def test_metrics_gate_zero_nonaccepted_findings(real):
    """THE tier-1 metrics-plane gate: every rendered metric, scrape
    site and engine-dict read is clean against the committed metrics
    manifest, the metric_names registry and the generated docs table.
    If this fails you either fix the drift (a renamed series, a stale
    scrape literal, dead telemetry — preferred) or, for a justified
    by-design deviation, re-snapshot with `dynamo-tpu lint --metrics
    --update-baseline` and justify the new accepted entry."""
    manifest = Manifest.load(DEFAULT_METRICS_MANIFEST_PATH)
    assert manifest.entrypoints, "metrics manifest missing or empty"
    fresh = manifest.filter(_real_findings(real, manifest))
    assert not fresh, (
        "non-accepted metrics-plane findings:\n  "
        + "\n  ".join(f.render() for f in fresh)
        + "\nFix the drift, or re-snapshot via `dynamo-tpu lint "
        "--metrics --update-baseline` and add a justification "
        "(docs/static_analysis.md#metrics-plane)."
    )


def test_metrics_gate_is_fast(real):
    """Acceptance bound from the issue: the tenth gate's fact
    collection stays well under 15s (it shares core.parse_module's
    cache with the other nine passes)."""
    *_, elapsed = real
    assert elapsed <= 15.0, f"metrics fact collection took {elapsed:.1f}s"


def test_manifest_accepted_entries_justified_and_live(real):
    """Every accepted entry carries a real justification and still
    matches a current finding — shared contract in
    tests/manifest_hygiene.py (metcheck keys entries on the metric
    name, carried in the entrypoint field)."""
    from manifest_hygiene import assert_manifest_hygiene

    manifest = Manifest.load(DEFAULT_METRICS_MANIFEST_PATH)
    assert_manifest_hygiene(
        manifest, _real_findings(real, manifest),
        entity_field="entrypoint")


def test_census_matches_registry_exactly(real):
    """The extracted census IS the registry: every SCHEMA name is
    rendered and every rendered name is declared.  (The gate enforces
    this via MT005 registry findings; this pins it directly so a
    future accepted entry can't quietly grandfather a gap.)"""
    facts, *_ = real
    assert set(facts["metrics"]) == set(SCHEMA)


def test_consumers_resolve_through_the_registry(real):
    """The typed scrape layer shows up as consumers by NAME (registry
    references resolve through the const table), and the bench summary
    keys it feeds all sit on rendered metrics."""
    facts, *_ = real
    sites = facts["consumers"].get(EM.PREFILL_DISPATCHES_TOTAL)
    assert sites and any("benchmarks/scrape.py" in s for s in sites), sites
    assert set(facts["consumers"]) <= set(facts["metrics"])
    engine = facts["engine"]
    assert engine["keys"], "EngineCore.metrics() keys not extracted"
    assert set(engine["consumers"]) <= set(engine["keys"])


def test_renamed_counter_is_caught_at_the_scrape_site(real):
    """THE scenario this plane exists for: rename a rendered counter
    (drop it from the census) and MT002 must fire naming the exact
    stale scrape site in benchmarks/scrape.py — the bench column would
    otherwise silently zero."""
    facts, *_ = real
    broken = copy.deepcopy(facts)
    del broken["metrics"][EM.PREFILL_DISPATCHES_TOTAL]
    findings = check_metric_facts(broken, Manifest(), [], drift=False)
    hits = [f for f in findings
            if f.rule == "MT002"
            and f.entrypoint == EM.PREFILL_DISPATCHES_TOTAL]
    assert hits, [f.render() for f in findings]
    assert any("benchmarks/scrape.py" in f.key for f in hits), (
        [f.key for f in hits])


# ------------------------------------------------------- rule fixtures ----


@pytest.mark.parametrize("rule", ["MT001", "MT002", "MT003", "MT004"])
def test_rule_fixtures(rule):
    n = int(rule[-3:])
    bad = FIXTURES / f"mt{n:03d}_bad.py"
    good = FIXTURES / f"mt{n:03d}_good.py"
    _, bad_findings = _fixture_findings(bad)
    _, good_findings = _fixture_findings(good)
    assert rule in _rules(bad_findings), (
        f"{bad.name} should trip {rule}, got "
        + str([f.render() for f in bad_findings]))
    assert rule not in _rules(good_findings), (
        f"{good.name} should be clean of {rule}, got "
        + str([f.render() for f in good_findings]))


def test_mt004_flags_all_three_misuses():
    """The bad fixture packs a non-_total counter, a millisecond
    histogram and a decremented counter — all three keys fire."""
    _, findings = _fixture_findings(FIXTURES / "mt004_bad.py")
    keys = {f.key for f in findings if f.rule == "MT004"}
    assert {"counter-name", "histogram-units", "decremented-counter"} <= keys


def test_mt005_census_drift_fixture_pair():
    """A manifest snapshotted from the base side flags exactly the
    four drifts on the drift side: added, removed, retyped, relabeled."""
    base_facts, base_intr = collect_metric_facts(
        [FIXTURES / "mt005_base.py"], root=FIXTURES)
    drift_facts, _ = collect_metric_facts(
        [FIXTURES / "mt005_drift.py"], root=FIXTURES)
    manifest = Manifest(entrypoints=census_snapshot(base_facts))
    assert not check_metric_facts(base_facts, manifest, base_intr)
    findings = check_metric_facts(drift_facts, manifest, [])
    assert [(f.entrypoint, f.rule, f.key) for f in findings] == [
        ("dynamo_tpu_widget_new_total", "MT005", "added"),
        ("dynamo_tpu_widget_old_total", "MT005", "removed"),
        ("dynamo_tpu_widget_ops_total", "MT005", "labels"),
        ("dynamo_tpu_widget_ops_total", "MT005", "type"),
    ]


def test_mt005_first_snapshot_is_free():
    """An empty manifest (no committed census yet) raises no drift."""
    facts, _ = collect_metric_facts(
        [FIXTURES / "mt005_base.py"], root=FIXTURES)
    assert "MT005" not in _rules(check_metric_facts(facts, Manifest(), []))


def test_mt005_registry_cross_check():
    """census vs obs/metric_names SCHEMA: missing, unrendered, retyped
    and relabeled declarations each get their own MT005 key."""
    facts, _ = collect_metric_facts(
        [FIXTURES / "mt005_base.py"], root=FIXTURES)
    manifest = Manifest(entrypoints=census_snapshot(facts))

    exact = {"dynamo_tpu_widget_ops_total": ("counter", ["phase"]),
             "dynamo_tpu_widget_old_total": ("counter", [])}
    assert not check_metric_facts(facts, manifest, [], registry=exact)

    drifted = {"dynamo_tpu_widget_ops_total": ("gauge", ["kind"]),
               "dynamo_tpu_widget_ghost_total": ("counter", [])}
    keys = {(f.entrypoint, f.key) for f in check_metric_facts(
        facts, manifest, [], registry=drifted) if f.rule == "MT005"}
    assert keys == {
        ("dynamo_tpu_widget_old_total", "registry-missing"),
        ("dynamo_tpu_widget_ghost_total", "registry-unrendered"),
        ("dynamo_tpu_widget_ops_total", "registry-type"),
        ("dynamo_tpu_widget_ops_total", "registry-labels"),
    }


def test_mt005_docs_table_cross_check():
    """docs/observability.md: absent markers and a stale generated
    table are both census drift; the regenerated table is clean."""
    facts, _ = collect_metric_facts(
        [FIXTURES / "mt005_base.py"], root=FIXTURES)
    manifest = Manifest(entrypoints=census_snapshot(facts))

    def docs_keys(text):
        return {f.key for f in check_metric_facts(
            facts, manifest, [], docs_text=text) if f.rule == "MT005"}

    good = f"prose\n{DOCS_BEGIN}\n{render_docs_table(facts['metrics'])}{DOCS_END}\n"
    assert docs_keys(good) == set()
    assert docs_keys("prose with no markers") == {"docs-markers"}
    stale = f"{DOCS_BEGIN}\n| metric | type | labels |\n{DOCS_END}"
    assert docs_keys(stale) == {"docs-table"}


def test_rule_table_complete():
    assert sorted(MET_RULES) == [f"MT00{i}" for i in range(1, 6)]


# --------------------------------------------------- update + CLI contract ----


def _args(**kw):
    base = dict(paths=None, fmt="text", select=None, baseline=None,
                no_baseline=False, update_baseline=False, root=None,
                project=False, trace=False, wire=False, perf=False,
                shard=False, proto=False, load=False, kern=False,
                metrics=True, manifest=None, changed=False)
    base.update(kw)
    return argparse.Namespace(**base)


@pytest.fixture()
def widget_root(tmp_path, monkeypatch):
    """A scan root holding only the MT001 fixture pair's bad side
    (under dynamo_tpu/ — run_metrics scans the package dirs, and
    producer scope excludes tests/benchmarks), with SCHEMA pinned to
    the widget surface so run_metrics sees no registry noise from the
    real 66-metric registry."""
    (tmp_path / "dynamo_tpu").mkdir()
    shutil.copy(FIXTURES / "mt001_bad.py",
                tmp_path / "dynamo_tpu" / "mt001_bad.py")
    # only the rendered name: the bad side never renders orphaned, and a
    # registry-unrendered MT005 would (correctly) keep the root red
    monkeypatch.setattr(
        "dynamo_tpu.obs.metric_names.SCHEMA",
        {"dynamo_tpu_widget_dispatches_total": ("counter", ())})
    return tmp_path


def test_update_roundtrip_carries_justifications(widget_root):
    """finding -> exit 1 -> --update accepts it (TODO) -> justify ->
    second --update carries the justification by key -> gate green."""
    mpath = widget_root / "manifest.json"
    args = lambda **kw: _args(root=str(widget_root),
                              manifest=str(mpath), **kw)
    assert run_metrics(args(), out=io.StringIO()) == 1       # MT001

    assert run_metrics(args(update_baseline=True),
                       out=io.StringIO()) == 0
    doc = json.loads(mpath.read_text())
    assert "dynamo_tpu_widget_dispatches_total" in doc["entrypoints"]
    assert [e["justification"] for e in doc["accepted"]] == [
        "TODO: justify"]
    assert [e["rule"] for e in doc["accepted"]] == ["MT001"]

    doc["accepted"][0]["justification"] = "kept: debug-only family"
    mpath.write_text(json.dumps(doc))
    assert run_metrics(args(), out=io.StringIO()) == 0  # accepted

    assert run_metrics(args(update_baseline=True),
                       out=io.StringIO()) == 0
    doc = json.loads(mpath.read_text())
    assert [e["justification"] for e in doc["accepted"]] == [
        "kept: debug-only family"]


def test_json_output_stable_sorted(widget_root):
    outs = []
    for _ in range(2):
        out = io.StringIO()
        run_metrics(_args(root=str(widget_root), fmt="json",
                          manifest=str(widget_root / "m.json")), out=out)
        outs.append(out.getvalue())
    assert outs[0] == outs[1]
    doc = json.loads(outs[0])
    assert {"findings", "accepted", "total", "metrics"} <= set(doc)
    assert doc["findings"] == sorted(
        doc["findings"],
        key=lambda f: (f["entrypoint"], f["rule"], f["key"]))


def test_cli_routes_metrics_flag(tmp_path, monkeypatch):
    """`dynamo-tpu lint --metrics` reaches run_metrics (not the file
    pass), and a clean widget surface exits 0."""
    from dynamo_tpu.analysis.cli import run_lint

    (tmp_path / "dynamo_tpu").mkdir()
    shutil.copy(FIXTURES / "mt001_good.py",
                tmp_path / "dynamo_tpu" / "mt001_good.py")
    monkeypatch.setattr("dynamo_tpu.obs.metric_names.SCHEMA",
                        dict(_WIDGET_SCHEMA))
    out = io.StringIO()
    rc = run_lint(_args(root=str(tmp_path),
                        manifest=str(tmp_path / "m.json")), out=out)
    assert rc == 0
    assert "metrics finding" in out.getvalue()


def test_changed_skip_when_plane_untouched(widget_root, monkeypatch):
    """`lint --changed`: the metrics pass skips when no metrics-plane
    input changed (and the skip is explicit in the output)."""
    import dynamo_tpu.analysis.metcheck as mc

    monkeypatch.setattr(mc, "_metrics_affected", lambda root: False)
    out = io.StringIO()
    rc = run_metrics(_args(root=str(widget_root), changed=True,
                           manifest=str(widget_root / "m.json")), out=out)
    assert rc == 0
    assert "unaffected" in out.getvalue()


def test_manifest_filter_is_a_multiset():
    f = TraceFinding("dynamo_tpu_widget_ops_total", "MT001", "k", "d")
    m = Manifest(accepted=[{"entrypoint": "dynamo_tpu_widget_ops_total",
                            "rule": "MT001", "key": "k"}])
    assert m.filter([f]) == []
    assert m.filter([f, f]) == [f]  # budget of one covers one
