"""Shared manifest-hygiene assertion for the gated analysis planes.

The trace, wire, perf and shard planes all commit a manifest whose
``accepted`` entries follow one contract: every entry carries a real
justification (no blank, no ``TODO: justify`` left by
``--update-baseline``) and still matches a finding the checker produces
TODAY — an entry whose finding disappeared is stale grandfathering and
must be pruned by re-snapshotting.  Each plane's gate test had grown
its own copy of that assertion (drifting on the entity field name:
trace/perf/shard key entries on ``entrypoint``, wire on ``message``);
this helper is the single parameterized implementation they all call.
"""

from __future__ import annotations


def assert_manifest_hygiene(manifest, findings, *,
                            entity_field: str = "entrypoint") -> None:
    """Assert every ``manifest.accepted`` entry is justified and live.

    ``manifest`` needs an ``accepted`` list of dicts keyed on
    (``entity_field``, ``rule``, ``key``); ``findings`` is the CURRENT
    full finding list (pre-filter) whose elements expose
    ``accept_key`` tuples in the same shape.
    """
    for e in manifest.accepted:
        assert e.get("justification", "").strip() not in (
            "", "TODO: justify"), (
            f"accepted entry {e[entity_field]}:{e['rule']}[{e['key']}] "
            "needs a one-line justification"
        )
    keys = {f.accept_key for f in findings}
    stale = [e for e in manifest.accepted
             if (e[entity_field], e["rule"], e["key"]) not in keys]
    assert not stale, (
        "accepted entries no longer match any finding (re-snapshot with "
        "--update-baseline): "
        + str([(e[entity_field], e["rule"], e["key"]) for e in stale])
    )
